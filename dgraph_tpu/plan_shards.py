"""Crash-safe sharded plan artifacts: per-rank shard IO + integrity manifest.

The r5 papers100M campaign died at the plan, not the partition: the
monolithic EdgePlan pickle is ~40+ GB and the in-RAM ``[W, E_pad]`` stack
OOM-killed the build at ~130 GB (``logs/p100m_r5_stages.log``, ROADMAP
item 3).  Cache format v8 replaces that single all-or-nothing artifact
with **per-rank plan shards** plus a checksummed JSON **manifest**:

- one ``shard_XXXX.pkl`` per rank (a plain dict of that rank's plan
  arrays — see ``dgraph_tpu.plan._assemble_shard_payload`` for the
  schema), each written with
  :func:`~dgraph_tpu.train.checkpoint.atomic_pickle_dump`;
- ``manifest.json`` recording, per shard, its SHA-256 and byte size, plus
  the build fingerprint, :data:`~dgraph_tpu.train.checkpoint.
  PLAN_FORMAT_VERSION`, the plan statics, and build progress — rewritten
  atomically after every shard, so a SIGKILL mid-build **resumes** from
  the last durable shard instead of restarting;
- an optional ``layout.pkl`` sidecar (the
  :class:`~dgraph_tpu.plan.EdgePlanLayout` arrays), checksummed the same
  way.

Loaders (:func:`~dgraph_tpu.train.checkpoint.cached_edge_plan`,
``DistributedGraph.from_global``, serve, bench,
``comm.multihost.process_local_plan_shards``) read only the shards they
need, verify checksums on read, and on a corrupt / truncated / missing
shard rebuild **just that shard** — mirroring ``restore_checkpoint``'s
fall-back-past-corrupt-steps contract — degrading to a full rebuild only
when the manifest itself is unreadable.

Peak build memory beyond the O(E) numpy skeleton (the per-edge
intermediates every plan build computes) is bounded by ONE shard, and
the bound is enforced: the writer (and the streaming builder's upfront
estimate) raise a structured :class:`PlanBuildMemoryExceeded` instead of
getting OOM-killed.  What the budget does NOT cover is the skeleton
itself — at billion-edge scale keep the edge list memmap'd
(``data.memmap.renumber_edges_chunked``) and skip the O(E) layout
sidecar (``build_plan_shards(write_layout=False)``).

Chaos points (:mod:`dgraph_tpu.chaos`): ``plan.write`` fires before each
shard write, ``plan.load`` before each shard read, and the builder fires
``plan.build_shard`` before assembling each rank — so kill / poison /
torn-write scenarios are deterministic and pinned in tests
(``DGRAPH_CHAOS="plan.write=sigterm@2"`` kills the build after two
durable shards; the rerun resumes bit-identically).

This module is **jax-free by contract** (``analysis.lint``'s
``jax-free-module`` rule): pure stdlib + numpy IO, so integrity checks
and the ``--selftest`` CLI run without a backend.  Assembly into an
:class:`~dgraph_tpu.plan.EdgePlan` lives in :mod:`dgraph_tpu.plan`.

``python -m dgraph_tpu.plan_shards --selftest true`` is the compile-free
smoke (run by ``scripts/check.py``): manifest round-trip + tamper
detection, shard checksum / missing-file detection, writer resume,
memory-budget enforcement, and the chaos points.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
from typing import Any, Iterable, Optional

import numpy as np

from dgraph_tpu.train.checkpoint import atomic_pickle_dump

_logger = logging.getLogger("dgraph_tpu.plan_shards")

MANIFEST_NAME = "manifest.json"
LAYOUT_NAME = "layout.pkl"

# env knob: default per-shard memory budget in MiB for streaming plan
# builds (0 / unset = unlimited). build_edge_plan_sharded's explicit
# memory_budget_bytes argument wins.
MEMORY_BUDGET_ENV = "DGRAPH_PLAN_MEMORY_BUDGET_MB"


# ---------------------------------------------------------------------------
# structured errors
# ---------------------------------------------------------------------------


class PlanManifestError(RuntimeError):
    """The manifest is missing, unparseable, or fails its own checksum —
    the one condition that degrades a shard-level repair to a full
    rebuild."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"plan manifest {path!r} unreadable: {reason}")
        self.path = path
        self.reason = reason


class PlanShardError(RuntimeError):
    """One shard is missing / truncated / checksum-mismatched — the caller
    rebuilds THAT shard, not the world."""

    def __init__(self, rank: int, path: str, reason: str):
        super().__init__(
            f"plan shard {rank} ({path!r}) unreadable: {reason}"
        )
        self.rank = rank
        self.path = path
        self.reason = reason

    def record(self) -> dict:
        return {
            "kind": "plan_shard_error",
            "rank": self.rank,
            "path": self.path,
            "reason": self.reason,
        }


class PlanBuildMemoryExceeded(RuntimeError):
    """The streaming build would exceed its memory budget — raised
    structured and early instead of letting the kernel OOM-kill a
    multi-hour pipeline (the r5 failure mode)."""

    def __init__(self, needed_bytes: int, budget_bytes: int,
                 rank: Optional[int] = None):
        where = "upfront estimate" if rank is None else f"shard {rank}"
        super().__init__(
            f"plan build {where} needs ~{needed_bytes / 2**20:.1f} MiB per "
            f"shard, over the {budget_bytes / 2**20:.1f} MiB budget "
            f"(raise it via memory_budget_bytes or ${MEMORY_BUDGET_ENV})"
        )
        self.needed_bytes = int(needed_bytes)
        self.budget_bytes = int(budget_bytes)
        self.rank = rank

    def record(self) -> dict:
        return {
            "kind": "plan_build_memory_exceeded",
            "needed_bytes": self.needed_bytes,
            "budget_bytes": self.budget_bytes,
            "rank": self.rank,
        }


def resolve_memory_budget(memory_budget_bytes: Optional[int]) -> Optional[int]:
    """The explicit argument, else the env knob, else None (unlimited)."""
    if memory_budget_bytes is not None:
        return int(memory_budget_bytes) or None
    mb = os.environ.get(MEMORY_BUDGET_ENV, "").strip()
    return int(float(mb) * 2**20) if mb else None


# ---------------------------------------------------------------------------
# checksums + manifest IO
# ---------------------------------------------------------------------------


def _sha256_file(path: str, chunk: int = 1 << 22) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _manifest_body_sha(manifest: dict) -> str:
    body = {k: v for k, v in manifest.items() if k != "manifest_sha256"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()
    ).hexdigest()


def manifest_path(plan_dir: str) -> str:
    return os.path.join(plan_dir, MANIFEST_NAME)


def shard_filename(rank: int) -> str:
    return f"shard_{rank:04d}.pkl"


def atomic_write_json(path: str, obj: dict) -> None:
    """Durable atomic JSON write (tmp + flush + fsync + rename — the same
    torn-write discipline as ``atomic_pickle_dump``): readers never see a
    truncated document, and a host crash cannot leave a durable-looking
    empty file behind the rename.  Used for the plan manifest and for the
    elastic-world adoption pointer (:mod:`dgraph_tpu.train.shrink`) —
    anywhere "the last atomic rename wins" is the adoption semantics."""
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, sort_keys=True, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_savez(path: str, **arrays) -> None:
    """Durable atomic ``.npz`` write (savez to tmp + flush + fsync +
    rename) for generation artifacts like ``graph_g<N>.npz`` — the numpy
    sibling of :func:`atomic_write_json`.  A direct ``np.savez(path)``
    can tear two ways on a host crash: a truncated archive under the
    final name, or (with a hand-rolled tmp + rename that skips the
    fsync) a rename committed before the bytes.  Shared by
    ``train/shrink.py`` and ``serve/deltas.py`` so the two generation
    machineries cannot drift (``analysis.host``'s ``host-durable-write``
    rule enforces the routing)."""
    tmp = path + f".tmp.{os.getpid()}.npz"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_manifest(plan_dir: str, manifest: dict) -> None:
    """Atomically write the manifest with a self-checksum (see
    :func:`atomic_write_json`)."""
    manifest = dict(manifest)
    manifest["manifest_sha256"] = _manifest_body_sha(manifest)
    atomic_write_json(manifest_path(plan_dir), manifest)


def read_manifest(plan_dir: str) -> dict:
    """Read + checksum-verify the manifest; raises :class:`PlanManifestError`
    on any failure (missing file, bad JSON, wrong kind, tampered body)."""
    path = manifest_path(plan_dir)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except OSError as e:
        raise PlanManifestError(path, f"{type(e).__name__}: {e}")
    except ValueError as e:
        raise PlanManifestError(path, f"bad JSON: {e}")
    if not isinstance(manifest, dict) or manifest.get("kind") != "plan_manifest":
        raise PlanManifestError(path, "not a plan manifest")
    want = manifest.get("manifest_sha256")
    if want != _manifest_body_sha(manifest):
        raise PlanManifestError(path, "manifest checksum mismatch")
    return manifest


# ---------------------------------------------------------------------------
# shard IO
# ---------------------------------------------------------------------------


def payload_nbytes(payload: Any) -> int:
    """Total numpy bytes of one shard payload (dict/list/tuple tree) — the
    number the memory budget is enforced against."""
    if isinstance(payload, dict):
        return sum(payload_nbytes(v) for v in payload.values())
    if isinstance(payload, (list, tuple)):
        return sum(payload_nbytes(v) for v in payload)
    if isinstance(payload, np.ndarray):
        return int(payload.nbytes)
    return 0


def write_shard(plan_dir: str, rank: int, payload: dict) -> dict:
    """Write one rank's payload; returns its manifest entry
    ``{"file", "sha256", "bytes"}``.  The ``plan.write`` chaos point fires
    first — a ``sigterm`` clause here is the deterministic stand-in for a
    SIGKILL mid-build."""
    from dgraph_tpu import chaos

    chaos.fire("plan.write")
    fname = shard_filename(rank)
    path = os.path.join(plan_dir, fname)
    atomic_pickle_dump(path, payload)
    return {
        "file": fname,
        "sha256": _sha256_file(path),
        "bytes": os.path.getsize(path),
    }


def read_shard(plan_dir: str, rank: int, entry: dict, *,
               verify: bool = True) -> dict:
    """Read + verify one shard; raises :class:`PlanShardError` with a
    ``reason`` of ``missing`` / ``checksum`` / ``unreadable``.  The
    ``plan.load`` chaos point fires first."""
    from dgraph_tpu import chaos

    chaos.fire("plan.load")
    path = os.path.join(plan_dir, entry["file"])
    if not os.path.exists(path):
        raise PlanShardError(rank, path, "missing")
    if verify:
        if os.path.getsize(path) != entry["bytes"]:
            raise PlanShardError(
                rank, path,
                f"checksum (size {os.path.getsize(path)} != "
                f"{entry['bytes']})",
            )
        got = _sha256_file(path)
        if got != entry["sha256"]:
            raise PlanShardError(rank, path, "checksum")
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except Exception as e:  # noqa: BLE001 — truncated/corrupt pickle
        raise PlanShardError(rank, path, f"unreadable ({type(e).__name__}: {e})")


def write_layout(plan_dir: str, payload: dict) -> dict:
    """Write the (whole-graph) layout sidecar; returns its manifest entry."""
    path = os.path.join(plan_dir, LAYOUT_NAME)
    atomic_pickle_dump(path, payload)
    return {
        "file": LAYOUT_NAME,
        "sha256": _sha256_file(path),
        "bytes": os.path.getsize(path),
    }


def read_layout(plan_dir: str, manifest: dict, *, verify: bool = True) -> dict:
    entry = manifest.get("layout")
    if not entry:
        raise PlanShardError(-1, os.path.join(plan_dir, LAYOUT_NAME), "missing")
    path = os.path.join(plan_dir, entry["file"])
    if not os.path.exists(path):
        raise PlanShardError(-1, path, "missing")
    if verify and _sha256_file(path) != entry["sha256"]:
        raise PlanShardError(-1, path, "checksum")
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except Exception as e:  # noqa: BLE001
        raise PlanShardError(-1, path, f"unreadable ({type(e).__name__}: {e})")


def bad_shards(plan_dir: str, manifest: dict,
               ranks: Optional[Iterable[int]] = None) -> dict:
    """rank -> reason for every requested shard that fails its integrity
    check (missing / size / checksum), WITHOUT unpickling."""
    shards = manifest.get("shards", {})
    out: dict = {}
    want = [int(r) for r in (ranks if ranks is not None else shards)]
    for rank in want:
        entry = shards.get(str(rank))
        if entry is None:
            out[rank] = "not in manifest"
            continue
        path = os.path.join(plan_dir, entry["file"])
        if not os.path.exists(path):
            out[rank] = "missing"
        elif os.path.getsize(path) != entry["bytes"]:
            out[rank] = "truncated"
        elif _sha256_file(path) != entry["sha256"]:
            out[rank] = "checksum"
    return out


# ---------------------------------------------------------------------------
# streaming writer (resume + memory budget)
# ---------------------------------------------------------------------------


class PlanShardWriter:
    """Streams per-rank shards into ``plan_dir`` with durable progress.

    The manifest is rewritten (atomically) after every shard, so a killed
    build resumes: a fresh writer with the same ``fingerprint`` picks up
    the durable shard set (each re-verified by checksum) and
    :meth:`done` reports which ranks can be skipped.  A fingerprint or
    format-version mismatch discards the stale progress — a manifest can
    never splice shards from two different builds.
    """

    def __init__(
        self,
        plan_dir: str,
        *,
        fingerprint: str,
        world_size: int,
        statics: dict,
        build_kwargs: Optional[dict] = None,
        memory_budget_bytes: Optional[int] = None,
        resume: bool = True,
        rebuild_ranks: Iterable[int] = (),
    ):
        from dgraph_tpu.train.checkpoint import PLAN_FORMAT_VERSION

        self.plan_dir = plan_dir
        self.budget = resolve_memory_budget(memory_budget_bytes)
        os.makedirs(plan_dir, exist_ok=True)
        self.manifest = {
            "kind": "plan_manifest",
            "format_version": PLAN_FORMAT_VERSION,
            "fingerprint": fingerprint,
            "world_size": int(world_size),
            "statics": dict(statics),
            "build_kwargs": dict(build_kwargs or {}),
            "shards": {},
            "layout": None,
            "complete": False,
        }
        if resume:
            self._adopt_progress(set(int(r) for r in rebuild_ranks))

    def _adopt_progress(self, rebuild: set) -> None:
        try:
            old = read_manifest(self.plan_dir)
        except PlanManifestError:
            return
        old_statics = old.get("statics", {})
        same = all(
            old.get(k) == self.manifest[k]
            for k in ("format_version", "fingerprint", "world_size")
        ) and all(
            # finalize() folds maxed per-shard hints into the durable
            # statics; a fresh writer only knows the build-time keys, so
            # compare on those (extra finalized keys are not drift)
            old_statics.get(k) == v
            for k, v in self.manifest["statics"].items()
        )
        if not same:
            # reclaim the stale artifact NOW: tens of GB of orphaned
            # shards in a fixed out_dir is the disk-exhaustion mode that
            # SIGBUS'd the r5 campaign (an orphaned tmp pickle filled the
            # disk) — and delete the stale manifest too, so a kill before
            # the first new shard cannot leave it referencing nothing
            stale = [e["file"] for e in old.get("shards", {}).values()]
            if old.get("layout"):
                stale.append(old["layout"]["file"])
            freed = 0
            for fname in stale:
                path = os.path.join(self.plan_dir, fname)
                try:
                    freed += os.path.getsize(path)
                    os.unlink(path)
                except OSError:
                    pass
            try:
                os.unlink(manifest_path(self.plan_dir))
            except OSError:
                pass
            _logger.info(
                "plan shard progress in %s is from a different build "
                "(fingerprint/format/statics changed); starting fresh "
                "(%d stale file(s) deleted, %.1f MiB reclaimed)",
                self.plan_dir, len(stale), freed / 2**20,
            )
            return
        kept = {
            rank: entry
            for rank, entry in old.get("shards", {}).items()
            if int(rank) not in rebuild
        }
        bad = bad_shards(self.plan_dir, {"shards": kept})
        self.manifest["shards"] = {
            rank: entry for rank, entry in kept.items()
            if int(rank) not in bad
        }
        if self.manifest["shards"]:
            _logger.info(
                "resuming plan shard build in %s: %d/%d shards already "
                "durable", self.plan_dir, len(self.manifest["shards"]),
                self.manifest["world_size"],
            )

    def done(self, rank: int) -> bool:
        """True when ``rank``'s shard is already durable (resume skip)."""
        return str(rank) in self.manifest["shards"]

    def check_budget(self, needed_bytes: int, rank: Optional[int] = None) -> None:
        if self.budget is not None and needed_bytes > self.budget:
            raise PlanBuildMemoryExceeded(needed_bytes, self.budget, rank)

    def write(self, rank: int, payload: dict,
              hints: Optional[dict] = None) -> None:
        """Budget-check, write, and durably record one shard."""
        self.check_budget(payload_nbytes(payload), rank)
        entry = write_shard(self.plan_dir, rank, payload)
        if hints:
            entry["hints"] = {k: int(v) for k, v in hints.items()}
        self.manifest["shards"][str(rank)] = entry
        write_manifest(self.plan_dir, self.manifest)

    def finalize(self, layout_payload: Optional[dict] = None,
                 statics_update: Optional[dict] = None) -> dict:
        """Mark the build complete (all ranks present) and return the
        final manifest."""
        missing = [
            r for r in range(self.manifest["world_size"])
            if str(r) not in self.manifest["shards"]
        ]
        if missing:
            raise PlanShardError(
                missing[0], self.plan_dir, "cannot finalize: shard not built"
            )
        if statics_update:
            self.manifest["statics"].update(statics_update)
        if layout_payload is not None:
            self.manifest["layout"] = write_layout(self.plan_dir, layout_payload)
        self.manifest["complete"] = True
        write_manifest(self.plan_dir, self.manifest)
        return dict(self.manifest)


# ---------------------------------------------------------------------------
# selftest CLI (compile-free; run by scripts/check.py)
# ---------------------------------------------------------------------------


def _selftest() -> dict:
    import tempfile

    from dgraph_tpu import chaos

    failures: list = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    chaos.disarm()
    try:
        with tempfile.TemporaryDirectory(prefix="dgraph_plan_shards_") as tmp:
            statics = {"e_pad": 8, "s_pad": 2}
            w = PlanShardWriter(
                tmp, fingerprint="fp0", world_size=3, statics=statics,
            )
            pay = {
                "src_index": np.arange(8, dtype=np.int32),
                "edge_mask": np.ones(8, np.float32),
            }
            for r in range(2):
                w.write(r, pay, hints={"scatter_mc": r + 1})
            # durable progress: a fresh writer resumes past ranks 0-1
            w2 = PlanShardWriter(
                tmp, fingerprint="fp0", world_size=3, statics=statics,
            )
            check(w2.done(0) and w2.done(1) and not w2.done(2),
                  "writer resume did not adopt durable shards")
            # finalize requires every shard
            try:
                w2.finalize()
                failures.append("finalize accepted a missing shard")
            except PlanShardError:
                pass
            w2.write(2, pay)
            man = w2.finalize(layout_payload={"edge_rank": np.zeros(4, np.int8)})
            check(man["complete"], "finalize did not mark complete")
            man = read_manifest(tmp)
            check(man["complete"] and len(man["shards"]) == 3,
                  "manifest round-trip lost state")
            got = read_shard(tmp, 1, man["shards"]["1"])
            check(np.array_equal(got["src_index"], pay["src_index"]),
                  "shard round-trip corrupted payload")
            check(read_layout(tmp, man)["edge_rank"].dtype == np.int8,
                  "layout round-trip corrupted payload")
            # corruption detection: flip one byte -> checksum error, and
            # bad_shards names exactly that rank
            spath = os.path.join(tmp, man["shards"]["1"]["file"])
            blob = bytearray(open(spath, "rb").read())
            blob[len(blob) // 2] ^= 0xFF
            open(spath, "wb").write(bytes(blob))
            try:
                read_shard(tmp, 1, man["shards"]["1"])
                failures.append("checksum mismatch not detected")
            except PlanShardError as e:
                check(e.reason == "checksum" and e.record()["rank"] == 1,
                      f"wrong shard error: {e.reason}")
            check(bad_shards(tmp, man) == {1: "checksum"},
                  f"bad_shards wrong: {bad_shards(tmp, man)}")
            # missing-file detection
            os.unlink(os.path.join(tmp, man["shards"]["0"]["file"]))
            check(bad_shards(tmp, man, ranks=[0]) == {0: "missing"},
                  "missing shard not detected")
            # manifest tamper detection
            mpath = manifest_path(tmp)
            txt = open(mpath).read().replace('"complete": true',
                                             '"complete": false')
            # deliberate non-atomic tamper: the selftest is TESTING the
            # checksum's torn-write detection  # lint: allow(host-durable-write)
            open(mpath, "w").write(txt)
            try:
                read_manifest(tmp)
                failures.append("manifest tamper not detected")
            except PlanManifestError:
                pass

        # a different fingerprint discards the stale progress AND deletes
        # the orphaned shard/manifest files (tens of GB in a fixed
        # out_dir is the r5 disk-exhaustion mode)
        with tempfile.TemporaryDirectory(prefix="dgraph_plan_shards_") as tmp:
            w = PlanShardWriter(tmp, fingerprint="fp0", world_size=2,
                                statics={})
            w.write(0, {"a": np.zeros(4)})
            w3 = PlanShardWriter(tmp, fingerprint="OTHER", world_size=2,
                                 statics={})
            check(not w3.done(0), "stale progress adopted across fingerprints")
            check(not os.path.exists(os.path.join(tmp, shard_filename(0))),
                  "stale shard file not deleted on fresh start")
            check(not os.path.exists(manifest_path(tmp)),
                  "stale manifest not deleted on fresh start")

        # memory budget: structured raise, not an OOM kill
        with tempfile.TemporaryDirectory(prefix="dgraph_plan_shards_") as tmp:
            w = PlanShardWriter(
                tmp, fingerprint="fp", world_size=1, statics={},
                memory_budget_bytes=16,
            )
            try:
                w.write(0, {"big": np.zeros(64, np.float32)})
                failures.append("memory budget not enforced")
            except PlanBuildMemoryExceeded as e:
                rec = e.record()
                check(rec["budget_bytes"] == 16 and rec["rank"] == 0
                      and rec["needed_bytes"] >= 256,
                      f"budget record malformed: {rec}")

        # chaos points: plan.write / plan.load consult the registry
        with tempfile.TemporaryDirectory(prefix="dgraph_plan_shards_") as tmp:
            for pt in ("plan.build_shard", "plan.write", "plan.load"):
                check(pt in chaos.KNOWN_POINTS,
                      f"chaos point {pt!r} not registered")
            w = PlanShardWriter(tmp, fingerprint="fp", world_size=2, statics={})
            chaos.arm("plan.write=raise@1")
            w.write(0, {"a": np.zeros(2)})
            try:
                w.write(1, {"a": np.zeros(2)})
                failures.append("plan.write chaos clause did not fire")
            except chaos.ChaosFault:
                pass
            chaos.arm("plan.load=raise@0")
            man = read_manifest(tmp)
            try:
                read_shard(tmp, 0, man["shards"]["0"])
                failures.append("plan.load chaos clause did not fire")
            except chaos.ChaosFault:
                pass
            chaos.disarm()
    finally:
        chaos.reset()
    return {"kind": "plan_shards_selftest", "failures": failures}


def _main() -> None:
    import dataclasses

    from dgraph_tpu.obs.health import RunHealth
    from dgraph_tpu.utils.cli import parse_config

    @dataclasses.dataclass
    class Config:
        """Sharded plan artifact IO (``--selftest`` for the compile-free
        tier-1/check.py smoke; default prints a manifest summary of
        ``--plan_dir``)."""

        selftest: bool = False
        plan_dir: str = ""
        indent: int = 0

    cfg = parse_config(Config)
    health = RunHealth.begin("plan_shards.cli")
    if not cfg.selftest:
        out: dict = {"kind": "plan_manifest_summary", "plan_dir": cfg.plan_dir}
        if cfg.plan_dir:
            try:
                man = read_manifest(cfg.plan_dir)
                out.update(
                    complete=man["complete"],
                    world_size=man["world_size"],
                    fingerprint=man["fingerprint"],
                    shards=len(man["shards"]),
                    bad=bad_shards(cfg.plan_dir, man),
                )
            except PlanManifestError as e:
                out["error"] = str(e)
        out["run_health"] = health.finish(out.get("error"))
        print(json.dumps(out, indent=cfg.indent or None))
        return
    try:
        out = _selftest()
    except BaseException as e:
        print(json.dumps({
            "kind": "plan_shards_selftest",
            "failures": [f"crashed: {type(e).__name__}: {e}"],
            "run_health": health.finish(
                f"plan_shards selftest crashed: {type(e).__name__}: {e}",
                wedge="stage_failure",
            ),
        }))
        raise
    failures = out["failures"]
    out["run_health"] = health.finish(
        "; ".join(failures) if failures else None,
        wedge="stage_failure" if failures else None,
    )
    print(json.dumps(out, indent=cfg.indent or None))
    if failures:
        raise SystemExit("plan_shards selftest FAILED: " + "; ".join(failures))


if __name__ == "__main__":
    _main()
