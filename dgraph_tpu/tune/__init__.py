"""Plan autotuner: cost-model-guided configuration search.

The reference picks its communication strategy per graph by hand (three
hand-chosen backends, ``DGraph/Communicator.py:21``); this subsystem
replaces that class of decision with a search over the configuration space
the framework already exposes:

- partition method (:func:`dgraph_tpu.partition.partition_graph`),
- edge-plan layout / ``pad_multiple`` (:func:`dgraph_tpu.plan.build_edge_plan`),
- halo lowering (:func:`dgraph_tpu.plan.pick_halo_impl` candidates),
- Pallas-vs-XLA scatter (from the on-chip sweep log, when present),
- serve :class:`~dgraph_tpu.serve.bucketing.BucketLadder` geometry.

Two phases: a cheap **analytic** phase ranks every candidate by
:func:`dgraph_tpu.obs.footprint.plan_footprint`'s byte/imbalance/roofline
model (never touches a device), then an optional **measured** phase times
only the top-K survivors with the compile-inside-scan protocol ``bench.py``
uses. The winner persists as a versioned :class:`~dgraph_tpu.tune.record.
TuningRecord` (JSON, keyed by a renumbering-invariant graph signature) in
the plan-cache directory, and is auto-adopted by
``DistributedGraph.from_global``, ``ServeEngine``, and ``bench.py`` when
the signature matches (env ``DGRAPH_TUNE_RECORD`` pins or disables).

CLI::

    python -m dgraph_tpu.tune --budget 0        # analytic-only, arxiv shape
    python -m dgraph_tpu.tune --selftest true   # tier-1 smoke
"""

from dgraph_tpu.tune.record import (
    TuningRecord,
    adopt_record,
    default_record_dir,
    lookup_record,
)
from dgraph_tpu.tune.search import search
from dgraph_tpu.tune.signature import graph_signature, signature_key

__all__ = [
    "TuningRecord",
    "adopt_record",
    "default_record_dir",
    "lookup_record",
    "search",
    "graph_signature",
    "signature_key",
]
