"""The tunable configuration space.

Every knob here is one the framework already exposes — the tuner invents
no new mechanisms, it only automates choices that were hand-picked
constants: the partition method fed to :func:`dgraph_tpu.partition.
partition_graph`, the ``pad_multiple`` fed to :func:`dgraph_tpu.plan.
build_edge_plan`, and the serve :class:`~dgraph_tpu.serve.bucketing.
BucketLadder` geometry. Halo lowering and Pallas-vs-XLA scatter are
*derived* per winner (from the footprint cost model and the kernel-sweep
log respectively), not enumerated here.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the plan-build space."""

    partition_method: str
    pad_multiple: int

    @property
    def key(self) -> str:
        return f"{self.partition_method}/pad{self.pad_multiple}"


# pad_multiple candidates: the lane-tile ladder the codebase already uses
# (8 = from_global default, 128 = bench/footprint convention, 256 = one
# step of extra alignment headroom)
PAD_MULTIPLES = (8, 128, 256)

# partition methods cheap enough to enumerate host-side at tuning time;
# 'multilevel' joins only when the native core is built (its python
# fallback is greedy_bfs, which is already in the list)
_METHODS = ("block", "random", "rcm", "greedy_bfs")


def default_candidate(world_size: int) -> Candidate:
    """The hard-coded defaults the tuner must beat (or tie): ``rcm`` +
    ``pad_multiple=8`` (``DistributedGraph.from_global``). At world size 1
    every partition degenerates to one block, so 'block' stands in — the
    plan is identical and the partitioner is O(V) instead of a sparse
    factorization."""
    return Candidate("block" if world_size == 1 else "rcm", 8)


def plan_candidates(
    world_size: int,
    methods: Optional[Sequence[str]] = None,
    pad_multiples: Optional[Sequence[int]] = None,
) -> list:
    """Cartesian candidate list, default-candidate first (stable trace
    order; ties in the analytic ranking resolve toward the default)."""
    if methods is None:
        if world_size == 1:
            methods = ("block",)
        else:
            from dgraph_tpu import native

            methods = _METHODS + (("multilevel",) if native.available() else ())
    pads = tuple(pad_multiples) if pad_multiples is not None else PAD_MULTIPLES
    cands = [Candidate(m, p) for m in methods for p in pads]
    d = default_candidate(world_size)
    if d in cands:
        cands.remove(d)
    cands.insert(0, d)
    return cands


# serve-ladder geometry space: (min_bucket, growth)
LADDER_MIN_BUCKETS = (8, 16)
LADDER_GROWTHS = (1.4, 2.0, 3.0)


def ladder_candidates() -> list:
    return [(m, g) for m in LADDER_MIN_BUCKETS for g in LADDER_GROWTHS]
