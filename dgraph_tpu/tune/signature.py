"""Graph signatures: the key a :class:`~dgraph_tpu.tune.record.TuningRecord`
is filed under.

A tuning decision transfers between runs only when the *workload* matches,
not the literal arrays: the same graph re-loaded with a different vertex
numbering (or rebuilt from an edge list in a different order) must map to
the same record, while a graph with a different size, skew, topology width,
or activation dtype must not. The signature therefore hashes
renumbering-invariant aggregates only:

- vertex / edge counts,
- a log2-bucketed total-degree histogram digest (captures the power-law
  skew that decides ``s_pad`` inflation and shard imbalance — the quantity
  :func:`~dgraph_tpu.plan.plan_efficiency` measures after the fact),
- world size (the plan's padding geometry is per-topology),
- activation dtype and feature width (the roofline's byte axis).

Everything is pure host numpy; hashing a papers100M-scale edge list is two
bincounts, not a sort.
"""

from __future__ import annotations

import hashlib
import json

import numpy as np

SIGNATURE_SCHEMA_VERSION = 1

# log2 degree buckets: bucket 0 = degree 0, bucket b>=1 = degree in
# [2^(b-1), 2^b). 40 buckets cover degrees past 5e11 — every real graph.
DEGREE_BUCKETS = 40


def canonical_dtype(dtype) -> str:
    """'bfloat16' / 'float32' / ... for numpy dtypes, jax dtypes, and
    plain strings (the same family :func:`dgraph_tpu.obs.footprint.
    dtype_bytes` accepts)."""
    name = getattr(dtype, "__name__", None) or str(dtype)
    return {"bf16": "bfloat16", "f32": "float32", "f16": "float16"}.get(
        name, name
    )


def degree_histogram(edge_index: np.ndarray, num_nodes: int) -> np.ndarray:
    """[DEGREE_BUCKETS] int64 counts of vertices per log2 total-degree
    bucket. Invariant under vertex renumbering and edge reordering."""
    edge_index = np.asarray(edge_index)
    deg = np.bincount(edge_index[0], minlength=num_nodes).astype(np.int64)
    deg += np.bincount(edge_index[1], minlength=num_nodes)
    hist = np.zeros(DEGREE_BUCKETS, dtype=np.int64)
    nz = deg > 0
    hist[0] = int(num_nodes - nz.sum())
    if nz.any():
        b = np.floor(np.log2(deg[nz])).astype(np.int64) + 1
        np.add.at(hist, np.minimum(b, DEGREE_BUCKETS - 1), 1)
    return hist


def graph_signature(
    edge_index: np.ndarray,
    num_nodes: int,
    world_size: int,
    *,
    dtype="float32",
    feat_dim: int = 0,
) -> dict:
    """JSON-able signature dict for one (graph, topology, dtype) workload."""
    edge_index = np.asarray(edge_index)
    hist = degree_histogram(edge_index, num_nodes)
    digest = hashlib.sha256(hist.tobytes()).hexdigest()[:16]
    return {
        "schema": SIGNATURE_SCHEMA_VERSION,
        "num_nodes": int(num_nodes),
        "num_edges": int(edge_index.shape[1]),
        "world_size": int(world_size),
        "dtype": canonical_dtype(dtype),
        "feat_dim": int(feat_dim),
        "degree_digest": digest,
    }


def signature_key(sig: dict) -> str:
    """Stable 16-hex-char key of a signature dict (the record filename
    stem). Key order is canonicalized so dict construction order can
    never split the cache."""
    payload = json.dumps(sig, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def signatures_match(a: dict, b: dict) -> bool:
    """Field-by-field match (not just key equality — a record file renamed
    or hand-edited must not adopt onto the wrong workload)."""
    fields = (
        "schema", "num_nodes", "num_edges", "world_size", "dtype",
        "feat_dim", "degree_digest",
    )
    return all(a.get(f) == b.get(f) for f in fields)
