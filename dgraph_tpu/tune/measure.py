"""Measured phase: time one candidate plan with bench.py's protocol.

The tunneled-chip timing rules bench.py established apply verbatim:
``block_until_ready`` is not a reliable completion barrier and repeated
same-input dispatches can be memoized, so n training steps run INSIDE one
jit (``lax.scan``), completion is forced with a scalar fetch, and the
reported number is the delta between two scan lengths — per-call RPC
latency cancels out. A round that never yields a positive delta returns
NaN, which the search's NaN guard drops (never crowned winner).

Scope: single-shard plans (``world_size == 1`` — the bench workload).
Multi-chip candidates return NaN with a warning; their ranking stays
analytic. This is deliberate: a rank-0-only proxy measurement would time
the compute and skip the exchange — exactly the term multi-chip tuning
exists to rank.
"""

from __future__ import annotations

import logging
import time

import numpy as np

_logger = logging.getLogger("dgraph_tpu.tune")


def _timed_scan_ms(run, state, n_long: int, reps: int = 2, max_rounds: int = 4):
    """Median positive (long-short)/(n_long-1) delta in ms (bench.py's
    protocol, compacted); NaN when the tunnel never yields one."""
    deltas = []
    rounds = 0
    while len(deltas) < reps and rounds < max_rounds:
        rounds += 1
        t0 = time.perf_counter()
        state = run(state, 1)
        t_short = time.perf_counter() - t0
        t0 = time.perf_counter()
        state = run(state, n_long)
        t_long = time.perf_counter() - t0
        d = (t_long - t_short) / (n_long - 1) * 1000.0
        if d > 0:
            deltas.append(d)
    if not deltas:
        return float("nan"), state
    ds = sorted(deltas)
    mid = len(ds) // 2
    return (ds[mid] if len(ds) % 2 else (ds[mid - 1] + ds[mid]) / 2), state


def measure_plan_ms(
    plan,
    *,
    feat_dim: int,
    dtype="bfloat16",
    seed: int = 0,
    hidden: int = 64,
    num_classes: int = 32,
    n_long: int = 4,
) -> float:
    """Steps/ms of a 2-layer GCN train step over ``plan`` on one device.

    Returns NaN for multi-shard plans (see module docstring) and on
    timing-protocol failure — callers must apply the NaN guard.
    """
    if plan.world_size != 1:
        _logger.warning(
            "measured phase supports world_size == 1 only (got %d); "
            "candidate keeps its analytic rank", plan.world_size,
        )
        return float("nan")

    import functools

    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.models import GCN

    dname = getattr(dtype, "__name__", None) or str(dtype)
    jdtype = jnp.bfloat16 if dname in ("bfloat16", "bf16") else jnp.float32
    sq_plan = jax.tree.map(lambda leaf: jnp.asarray(np.asarray(leaf)[0]), plan)
    comm = Communicator.init_process_group("single")
    model = GCN(
        hidden_features=hidden, out_features=num_classes, comm=comm,
        num_layers=2, dtype=jdtype,
    )

    n_pad = plan.n_src_pad
    x = jax.random.normal(jax.random.key(seed), (n_pad, feat_dim), jnp.float32)
    y = jax.random.randint(jax.random.key(seed + 1), (n_pad,), 0, num_classes)
    mask = jnp.ones((n_pad,), jnp.float32)
    params = model.init(jax.random.key(seed + 2), x, sq_plan)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    @functools.partial(jax.jit, static_argnames="n", donate_argnums=(0, 1))
    def steps(params, opt_state, salt, n):
        def lf(p):
            logits = model.apply(p, x, sq_plan)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
            return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def body(carry, _):
            p, o, s = carry
            loss, grads = jax.value_and_grad(lf)(p)
            updates, o = optimizer.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, o, s + loss * 1e-20), None

        (p, o, s), _ = jax.lax.scan(
            body, (params, opt_state, salt), None, length=n
        )
        return p, o, s

    def run(state, n):
        p, o, s = steps(*state, n)
        float(s)  # the only trustworthy completion barrier on the tunnel
        return (p, o, s)

    state = (params, opt_state, jnp.float32(0.0))
    state = run(state, 1)
    state = run(state, n_long)  # both lengths compiled before timing
    ms, _ = _timed_scan_ms(run, state, n_long)
    return ms
