"""Two-phase configuration search.

Phase 1 (**analytic**, always): every candidate's plan is built host-side
and priced with :func:`dgraph_tpu.obs.footprint.plan_footprint`'s
byte/imbalance/roofline model — per-layer wire and HBM-stream time at the
workload's feature width and dtype. The padded-static-shape design makes
this honest: every shard executes ``e_pad`` edge slots whether they are
real or padding, so a skewed partition's cost shows up directly as a
bigger ``e_pad``, and hub-driven ``s_pad`` inflation as a bigger exchange
operand. No device is touched.

Phase 2 (**measured**, when ``budget_s > 0``): only the top-K analytic
survivors are timed, with the compile-inside-scan protocol ``bench.py``
uses (run n steps inside one jit, delta two scan lengths — per-call RPC
latency cancels). Non-finite timings are dropped before ranking — the
same NaN guard :mod:`dgraph_tpu.tune.adopt` applies to sweep rows (a
crashed compile must not be crowned winner because ``x < nan`` is always
False).

The result is a :class:`~dgraph_tpu.tune.record.TuningRecord`; every
candidate evaluation emits one ``kind="tune_trace"`` JSONL row through the
caller's :class:`~dgraph_tpu.utils.logging.ExperimentLog` and ticks the
:mod:`dgraph_tpu.obs.metrics` registry.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Optional, Sequence

import numpy as np

from dgraph_tpu.obs.footprint import (
    V5E_ICI_GBPS,
    V5E_PEAK_HBM_GBPS,
    dtype_bytes,
    plan_footprint,
)
from dgraph_tpu.tune.record import TuningRecord
from dgraph_tpu.tune.signature import graph_signature
from dgraph_tpu.tune.space import (
    default_candidate,
    ladder_candidates,
    plan_candidates,
)

_logger = logging.getLogger("dgraph_tpu.tune")

# per-collective launch overhead (us) charged when choosing the halo
# lowering: a2a pays it once, ppermute pays it per live delta — this is
# what keeps "W-1 rounds of ppermute" from beating one all_to_all on
# dense peer sets purely on wire bytes
LAUNCH_US = 2.0

# serve-ladder proxy constants: one bucket == one AOT warmup compile
# (~seconds), amortized over a nominal request volume; padding waste costs
# a fraction of a nominal infer. Proxies, not measurements — the ladder
# choice only needs the *ordering* to be sane (few huge buckets vs many
# tiny ones), and both endpoints are dominated by these terms.
LADDER_COMPILE_US_PER_BUCKET = 300.0  # 3 s compile / 10k requests
LADDER_INFER_US = 1000.0


@dataclasses.dataclass
class SearchResult:
    record: TuningRecord
    trace: list
    ranked: list  # [(candidate_key, analytic_total_us)] best-first
    measured: dict  # candidate_key -> ms (finite only)


def candidate_cost(
    plan,
    *,
    feat_dim: int,
    dtype,
    ici_gbps: float = V5E_ICI_GBPS,
    hbm_gbps: float = V5E_PEAK_HBM_GBPS,
) -> dict:
    """Analytic per-layer cost (us) of one plan at one feature width,
    derived from the footprint report: the better of the two halo
    lowerings (wire + launch + exchange HBM streams, x2 for the gather
    and scatter legs) plus the padded local edge/vertex streams (the
    6-stream-per-layer accounting bench.py's roofline uses)."""
    fp = plan_footprint(plan, dtype, feat_dim, ici_gbps=ici_gbps, hbm_gbps=hbm_gbps)
    W, S = plan.world_size, plan.halo.s_pad
    row = feat_dim * dtype_bytes(dtype)
    n_d = fp["num_halo_deltas"]
    wire = fp["halo"]["wire_bytes_per_shard"]
    split = fp["edge_split"]

    def exch_bound(impl: str) -> float:
        sent_blocks = {"all_to_all": W, "ppermute": n_d}.get(impl, 0)
        launches = {"all_to_all": 1, "ppermute": n_d}.get(impl, 0)
        wire_us = wire.get(impl, 0) / (ici_gbps * 1e3) + launches * LAUNCH_US
        hbm_us = (2 * sent_blocks + W) * S * row / (hbm_gbps * 1e3)
        return max(wire_us, hbm_us)

    # the overlap lowering moves ppermute's boundary-only rounds but hides
    # them behind the interior-edge aggregation (3 HBM streams of interior
    # rows per exchange leg — the per-leg half of the 6-stream local
    # model), so its EXPOSED exchange cost is what serial rounds cost
    # minus what the interior work can absorb
    int_rows_max = max(split["interior_per_shard"] or [0])
    interior_leg_us = 3 * int_rows_max * row / (hbm_gbps * 1e3)
    overlap_exposed = 0.0
    p2p_exposed = 0.0
    if n_d:
        pp_us = exch_bound("ppermute")
        overlap_exposed = max(pp_us - interior_leg_us, 0.0)
        # pallas_p2p: the same boundary-only tiles as one-sided puts
        # issued from inside the Pallas kernel — ONE launch instead of
        # n_d collective rounds; the split routing hides the puts behind
        # the interior aggregation like overlap does. HBM streams are
        # billed at ppermute's (2*n_d + W) blocks: only the forward
        # leg's in-VMEM mask fusion can skip a stream, and only when the
        # stack fits the budget — the ranking must not credit a saving
        # the reverse leg never delivers.
        p2p_wire_us = wire.get("pallas_p2p", 0) / (ici_gbps * 1e3) + LAUNCH_US
        p2p_hbm_us = (2 * n_d + W) * S * row / (hbm_gbps * 1e3)
        p2p_exposed = max(max(p2p_wire_us, p2p_hbm_us) - interior_leg_us, 0.0)

    # the compiled schedule enters the ranking only when the plan carries
    # one (plan.halo_schedule attached at build) — ranked from the SAME
    # launch-aware bound family as the fixed lowerings: per-round compiled
    # operand bytes on the wire + one launch per round, the staged blocks'
    # HBM streams, minus the same interior absorption the overlap rounds
    # get (the sched executor has the identical issue-all-then-place shape)
    sched_fp = fp["collectives"]["halo_exchange"].get("sched")
    sched_rankable = bool(n_d) and sched_fp is not None
    sched_exposed = 0.0
    if sched_rankable:
        n_r = sched_fp["rounds"]
        sched_wire_us = (
            wire.get("sched", 0) / (ici_gbps * 1e3) + n_r * LAUNCH_US
        )
        sched_hbm_us = (2 * n_r + W) * S * row / (hbm_gbps * 1e3)
        sched_exposed = max(
            max(sched_wire_us, sched_hbm_us) - interior_leg_us, 0.0
        )

    # the pallas_p2p knob only enters the ranking where it can actually
    # lower (TPU backend, or the explicit interpret opt-in) — a record
    # should not persist a winner the run would degrade away from
    from dgraph_tpu import config as _cfg

    p2p_rankable = bool(n_d) and _cfg.pallas_p2p_available()

    if n_d == 0:
        impl, exch_us = "none", 0.0
    else:
        bounds = {
            "all_to_all": exch_bound("all_to_all"),
            "ppermute": exch_bound("ppermute"),
            "overlap": overlap_exposed,
        }
        if p2p_rankable:
            bounds["pallas_p2p"] = p2p_exposed
        if sched_rankable:
            bounds["sched"] = sched_exposed
        # stable tie-break preserving the pre-overlap semantics: ppermute
        # beats all_to_all on equal cost (as before), overlap — equal to
        # ppermute exactly when there is no interior work to hide behind
        # — only wins when it actually hides something, and pallas_p2p /
        # sched (last) only when they strictly beat the fixed lowerings:
        # an un-A/B'd transport or compiled schedule never wins a tie
        order = ("ppermute", "all_to_all", "overlap", "pallas_p2p", "sched")
        impl = min(
            (k for k in order if k in bounds),
            key=lambda k: (bounds[k], order.index(k)),
        )
        exch_us = bounds[impl]

    # wire-format ranking (dgraph_tpu.wire): the codec changes only the
    # WIRE leg of the chosen lowering — decode accumulates at the
    # activation dtype, so HBM streams, launches and local work are
    # format-invariant. Re-price the winner's exchange bound with each
    # registered format's row width and keep the min; the ordering
    # tie-break prefers the less lossy format (fp32 first), so a lossy
    # codec never engages without STRICTLY beating the lossless wire —
    # e.g. an HBM-bound exchange ties every format and fp32 stands.
    from dgraph_tpu.wire.spec import (
        WIRE_FORMAT_NAMES,
        fp8_available,
        get_format,
    )

    exch_rep = fp["collectives"]["halo_exchange"]
    res_row = exch_rep["wire_row_bytes"]
    wire_rank: dict = {}
    wf_winner = "fp32"
    wire_operand_bytes = 0
    if n_d and res_row:
        launches_by = {
            "all_to_all": 1, "ppermute": n_d, "overlap": n_d,
            "pallas_p2p": 1,
            "sched": sched_fp["rounds"] if sched_fp else 0,
        }
        sent_by = {
            "all_to_all": W, "ppermute": n_d, "overlap": n_d,
            "pallas_p2p": n_d,
            "sched": sched_fp["rounds"] if sched_fp else 0,
        }

        def _bound_at_wire_scale(scale: float) -> float:
            wire_us = (
                wire.get(impl, 0) * scale / (ici_gbps * 1e3)
                + launches_by[impl] * LAUNCH_US
            )
            hbm_us = (2 * sent_by[impl] + W) * S * row / (hbm_gbps * 1e3)
            bound = max(wire_us, hbm_us)
            if impl in ("overlap", "pallas_p2p", "sched"):
                bound = max(bound - interior_leg_us, 0.0)
            return bound

        names = [
            n for n in WIRE_FORMAT_NAMES
            if n != "fp8" or fp8_available()
        ]
        b_act = dtype_bytes(dtype)
        for name in names:
            row_f = get_format(name).wire_row_bytes(feat_dim, b_act)
            wire_rank[name] = round(_bound_at_wire_scale(row_f / res_row), 3)
        wf_winner = min(
            names, key=lambda n: (wire_rank[n], names.index(n))
        )
        # byte-exact operand figure at the winner's width: the resolved
        # operand is rows * res_row, so recover rows first (exact) and
        # re-multiply — the wire_compile ledger gate is zero-tolerance
        rows = exch_rep["operand_bytes_per_shard"] // res_row
        wire_operand_bytes = rows * get_format(wf_winner).wire_row_bytes(
            feat_dim, b_act
        )

    local_us = 6 * (plan.e_pad + plan.n_dst_pad) * row / (hbm_gbps * 1e3)
    return {
        "total_us": round(2 * exch_us + local_us, 3),
        "exchange_us": round(exch_us, 3),
        "local_stream_us": round(local_us, 3),
        "halo_impl": impl,
        "e_pad": int(plan.e_pad),
        "s_pad": int(S),
        "num_halo_deltas": n_d,
        # overlap-knob pricing: both alternatives land in the trace so the
        # record's choice is auditable (overlap in {off, on} first-class)
        "overlap_exposed_us": round(overlap_exposed, 3),
        # pallas_p2p-knob pricing: always priced (auditable even where it
        # cannot lower); ranked only when pallas_p2p_rankable
        "pallas_p2p_exposed_us": round(p2p_exposed, 3),
        "pallas_p2p_rankable": p2p_rankable,
        # compiled-schedule pricing: always reported when a schedule is
        # attached (auditable), ranked only via sched_rankable
        "sched_exposed_us": round(sched_exposed, 3),
        "sched_rankable": sched_rankable,
        "sched_rounds": int(sched_fp["rounds"]) if sched_fp else 0,
        "sched_schedule_id": sched_fp["schedule_id"] if sched_fp else None,
        "sched_operand_bytes": (
            int(sched_fp["operand_bytes_per_shard"]) if sched_fp else 0
        ),
        # wire-format ranking: every priced alternative lands in the
        # trace (auditable); the winner is what the record adopts
        "wire_format": wf_winner,
        "wire_formats_us": wire_rank,
        "wire_operand_bytes": int(wire_operand_bytes),
        "wire_compression_ratio": round(
            get_format(wf_winner).compression_ratio(
                feat_dim, dtype_bytes(dtype)
            ), 4,
        ),
        "interior_frac": split["interior_frac"],
        "boundary_frac": split["boundary_frac"],
        "wire_efficiency": fp["collectives"]["halo_exchange"]["wire_efficiency"],
        "edge_imbalance": fp["imbalance"]["edges"]["max_over_mean"],
    }


def ladder_cost(sizes: Sequence[int], max_request: int) -> float:
    """Proxy cost (us/request) of one bucket ladder under a uniform
    request-size distribution on [1, max_request]: amortized warmup
    compiles + relative padding waste."""
    import bisect

    sizes = sorted(sizes)
    n = np.arange(1, max_request + 1, dtype=np.float64)
    buckets = np.asarray(
        [sizes[bisect.bisect_left(sizes, int(v))] for v in n], np.float64
    )
    waste = float((buckets - n).sum() / n.sum())
    return len(sizes) * LADDER_COMPILE_US_PER_BUCKET + waste * LADDER_INFER_US


def choose_ladder(max_request: int) -> dict:
    """Best (min_bucket, growth) geometry for the workload's request
    ceiling; returns the BucketLadder.geometric kwargs plus its cost."""
    from dgraph_tpu.serve.bucketing import BucketLadder

    best = None
    for min_bucket, growth in ladder_candidates():
        mb = min(min_bucket, max_request)
        sizes = BucketLadder.geometric(mb, max(max_request, mb), growth).sizes
        cost = ladder_cost(sizes, max_request)
        if best is None or cost < best["cost_us"]:
            best = {
                "min_bucket": int(mb),
                "max_bucket": int(max(max_request, mb)),
                "growth": float(growth),
                "num_buckets": len(sizes),
                "cost_us": round(cost, 3),
            }
    return best


def _pallas_config(dtype, feat_dim: int, sweep_log: str) -> dict:
    """Scatter/tile choices from the on-chip sweep log, when one exists.
    The analytic model cannot rank Pallas against XLA (same bytes, different
    schedulers), so this dimension only ever comes from measurement. When
    the log holds verdicts at several feature widths, the one measured
    closest to this workload's ``feat_dim`` decides — a verdict from a
    4x-wider sweep can invert at narrow rows."""
    from dgraph_tpu.tune import adopt
    from dgraph_tpu.tune.signature import canonical_dtype

    report = adopt.sweep_report(sweep_log) if sweep_log else None
    if report is None:
        return {}
    out = {}
    short = {"bfloat16": "bf16", "float32": "f32"}.get(
        canonical_dtype(dtype), canonical_dtype(dtype)
    )
    scatter = [
        v for v in report["verdicts"]
        if v["flag"] == "use_pallas_scatter"
        and v["dtype"] in (short, canonical_dtype(dtype))
    ]
    if scatter:
        best = min(scatter, key=lambda v: abs((v["F"] or 0) - feat_dim))
        out["use_pallas_scatter"] = best["verdict"] == "PALLAS"
    if report["consensus"] is not None:
        be, bn = report["consensus"]
        out["scatter_block_e"] = int(be)
        out["scatter_block_n"] = int(bn)
    return out


def search(
    edge_index: np.ndarray,
    num_nodes: int,
    world_size: int,
    *,
    feat_dim: int = 128,
    dtype="float32",
    budget_s: float = 0.0,
    top_k: int = 3,
    methods: Optional[Sequence[str]] = None,
    pad_multiples: Optional[Sequence[int]] = None,
    measure_fn: Optional[Callable] = None,
    max_request: int = 1024,
    seed: int = 0,
    sweep_log: str = "logs/kernel_benchmarks.jsonl",
    log=None,
    registry=None,
) -> SearchResult:
    """Run the two-phase search and return the winning record.

    Args:
      edge_index: [2, E] global edges (any numbering — partitioning
        renumbers internally per candidate).
      budget_s: measured-phase wall budget in seconds; 0 = analytic only.
      measure_fn: ``(plan, feat_dim=..., dtype=..., seed=...) -> ms``;
        defaults to :func:`dgraph_tpu.tune.measure.measure_plan_ms` (only
        consulted when ``budget_s > 0``). Non-finite returns are dropped.
      log: an :class:`~dgraph_tpu.utils.logging.ExperimentLog` for the
        JSONL search trace (optional).
      registry: an :class:`~dgraph_tpu.obs.metrics.Metrics`; defaults to
        the obs default registry.
    """
    from dgraph_tpu import partition as pt
    from dgraph_tpu.plan import build_edge_plan
    from dgraph_tpu.obs.metrics import default_registry

    t_start = time.perf_counter()
    reg = registry if registry is not None else default_registry
    edge_index = np.asarray(edge_index)
    sig = graph_signature(
        edge_index, num_nodes, world_size, dtype=dtype, feat_dim=feat_dim
    )
    trace: list = []

    def emit(**row):
        rec = {"kind": "tune_trace", **row}
        trace.append(rec)
        if log is not None:
            log.write(rec)

    cands = plan_candidates(world_size, methods, pad_multiples)
    default = default_candidate(world_size)
    if default not in cands:
        # a restricted space must still price the baseline the record's
        # cost claim is made against
        cands.append(default)

    partitions: dict = {}  # method -> (new_edges, ren)
    evaluated: list = []  # (Candidate, cost dict, plan)

    for cand in cands:
        t0 = time.perf_counter()
        try:
            if cand.partition_method not in partitions:
                partitions[cand.partition_method] = pt.partition_graph(
                    edge_index, num_nodes, world_size,
                    method=cand.partition_method, seed=seed,
                )
            new_edges, ren = partitions[cand.partition_method]
            plan, _layout = build_edge_plan(
                new_edges, ren.partition, world_size=world_size,
                pad_multiple=cand.pad_multiple,
            )
        except (ValueError, ImportError) as e:
            # an un-lowerable knob combination (build_edge_plan's early
            # rejection) or a missing optional dep is a pruned branch of
            # the space, not a search failure
            emit(phase="analytic", candidate=cand.key, error=str(e))
            reg.counter("tune.candidates_rejected")
            continue
        cost = candidate_cost(plan, feat_dim=feat_dim, dtype=dtype)
        build_s = round(time.perf_counter() - t0, 3)
        emit(
            phase="analytic", candidate=cand.key,
            partition_method=cand.partition_method,
            pad_multiple=cand.pad_multiple, build_s=build_s, **cost,
        )
        reg.counter("tune.candidates_analytic")
        reg.histogram("tune.candidate_build_s", build_s)
        evaluated.append((cand, cost, plan))

    if not evaluated:
        raise ValueError(
            "tuning search evaluated zero candidates; every combination was "
            "rejected — check the methods/pad_multiples restrictions"
        )

    # default-first tie-break: equal-cost exotic candidates must not
    # displace the known-good baseline
    evaluated.sort(
        key=lambda r: (r[1]["total_us"], r[0] != default, r[0].key)
    )
    default_cost = next((c for cd, c, _ in evaluated if cd == default), None)
    if default_cost is None:
        # the default itself was rejected (e.g. rcm without scipy): the
        # winner stands in as the baseline so the record's cost claim
        # stays well-formed, and the trace says why
        default_cost = evaluated[0][1]
        emit(phase="analytic", candidate=default.key,
             note="default candidate rejected; winner used as baseline")

    # plans are dead weight after pricing except for the measured top-K:
    # at arxiv scale each one holds multi-MB index arrays, so drop the rest
    # before the measured phase instead of holding the whole space live
    keep_plans = top_k if budget_s > 0 else 0
    evaluated = [
        (cd, c, p if i < keep_plans else None)
        for i, (cd, c, p) in enumerate(evaluated)
    ]

    measured: dict = {}
    phase = "analytic"
    winner_cand, winner_cost, _winner_plan = evaluated[0]
    if budget_s > 0:
        if measure_fn is None:
            from dgraph_tpu.tune.measure import measure_plan_ms

            measure_fn = measure_plan_ms
        # the budget buys MEASUREMENT time: the clock starts here, not at
        # the top of the search — an expensive analytic phase must not
        # silently starve the phase the caller explicitly paid for
        deadline = time.perf_counter() + budget_s
        for cand, cost, plan in evaluated[:top_k]:
            if time.perf_counter() >= deadline:
                emit(phase="measured", candidate=cand.key,
                     skipped="budget_exhausted")
                break
            t0 = time.perf_counter()
            try:
                ms = float(
                    measure_fn(plan, feat_dim=feat_dim, dtype=dtype, seed=seed)
                )
            except Exception as e:  # noqa: BLE001 — one broken candidate
                # must not abort the phase
                emit(phase="measured", candidate=cand.key,
                     error=f"{type(e).__name__}: {e}")
                continue
            emit(
                phase="measured", candidate=cand.key, ms=ms,
                measure_s=round(time.perf_counter() - t0, 3),
            )
            reg.histogram("tune.measure_ms", ms)
            if ms == ms:  # NaN guard (see tune.adopt)
                measured[cand.key] = ms
        if measured:
            phase = "measured"
            winner_key = min(measured, key=measured.get)
            winner_cand, winner_cost, _winner_plan = next(
                r for r in evaluated if r[0].key == winner_key
            )

    config = {
        "partition_method": winner_cand.partition_method,
        "pad_multiple": int(winner_cand.pad_multiple),
        "edge_owner": "dst",
        "halo_impl": winner_cost["halo_impl"],
        "wire_format": winner_cost.get("wire_format", "fp32"),
        "serve": choose_ladder(min(max_request, num_nodes)),
    }
    config.update(_pallas_config(dtype, feat_dim, sweep_log))

    cost = {
        "winner_us": winner_cost["total_us"],
        "default_us": default_cost["total_us"],
        "unit": "analytic_us_per_layer",
        "candidates_evaluated": len(evaluated),
        "search_wall_s": round(time.perf_counter() - t_start, 3),
    }
    if winner_cand.key in measured:
        cost["measured_ms"] = round(measured[winner_cand.key], 4)
    record = TuningRecord.create(sig, config, cost, phase)
    emit(
        phase="result", record_id=record.record_id, winner=winner_cand.key,
        **cost,
    )
    if winner_cost.get("sched_schedule_id"):
        # the winner's compiled halo schedule joins the perf ledger: its
        # _bytes/_count metrics land in regress's byte-exact class, so a
        # compiler change that alters what this workload's schedule looks
        # like goes RED across commits (off unless DGRAPH_LEDGER_DIR set;
        # maybe_ingest swallows every failure)
        from dgraph_tpu.obs.ledger import maybe_ingest

        maybe_ingest(
            {
                "kind": "sched_compile",
                "workload": {
                    "world_size": world_size, "nodes": num_nodes,
                    "edges": int(edge_index.shape[1]),
                    "feat_dim": feat_dim,
                },
                "schedule_id": winner_cost["sched_schedule_id"],
                "rounds": winner_cost["sched_rounds"],
                "operand_bytes_per_shard": winner_cost["sched_operand_bytes"],
                "exposed_us": winner_cost["sched_exposed_us"],
            },
            source="tune.search", default_on=False,
        )
    if winner_cost.get("wire_operand_bytes"):
        # the winner's wire format joins the perf ledger the same way:
        # operand_bytes lands in regress's byte-exact class, so a codec
        # or pricing change that alters what this workload ships on the
        # wire goes RED across commits
        from dgraph_tpu.obs.ledger import maybe_ingest

        maybe_ingest(
            {
                "kind": "wire_compile",
                "workload": {
                    "world_size": world_size, "nodes": num_nodes,
                    "edges": int(edge_index.shape[1]),
                    "feat_dim": feat_dim,
                },
                "wire_format": winner_cost["wire_format"],
                "wire_format_source": "tune",
                "operand_bytes": winner_cost["wire_operand_bytes"],
                "compression_ratio": winner_cost["wire_compression_ratio"],
            },
            source="tune.search", default_on=False,
        )
    _logger.info(
        "tuning search done: winner=%s (%s us/layer vs default %s), phase=%s",
        winner_cand.key, winner_cost["total_us"], default_cost["total_us"],
        phase,
    )
    return SearchResult(
        record=record,
        trace=trace,
        ranked=[(cd.key, c["total_us"]) for cd, c, _ in evaluated],
        measured=measured,
    )
