"""``python -m dgraph_tpu.tune`` — the plan autotuner CLI.

Default mode searches the config space for the arxiv-shaped synthetic
workload (the bench graph: same construction, same signature), persists
the winning :class:`~dgraph_tpu.tune.record.TuningRecord` into the record
directory, and prints it as one JSON line. ``--budget 0`` (the default) is
analytic-only — pure host numpy, no device ever dialed; ``--budget N``
spends up to N seconds timing the top-K survivors on the local backend.

``--selftest`` is the compile-free tier-1 smoke: a tiny two-shard graph
goes through the full pipeline — search, record save, signature lookup,
mismatch fallback, adoption — with hard assertions, exit 0 only if all
hold.

Every exit path (success, selftest failure, crash) writes a RunHealth
record to the JSONL log, and the search trace streams there too
(``kind="tune_trace"``, one row per candidate).
"""

from __future__ import annotations

import dataclasses
import json
import tempfile


@dataclasses.dataclass
class Config:
    """Plan autotuner (``--budget 0`` = analytic-only; ``--selftest`` for
    the compile-free tier-1 smoke)."""

    selftest: bool = False
    # workload: the bench's arxiv-shaped synthetic graph unless overridden
    arxiv: bool = True
    nodes: int = 4096
    edges: int = 16384  # directed edges before symmetrization
    symmetrize: bool = True
    world: int = 1  # the bench protocol's world size
    feat_dim: int = 128
    dtype: str = "bfloat16"  # bench's default activation dtype
    # search
    budget: float = 0.0  # measured-phase seconds; 0 = analytic only
    top_k: int = 3
    methods: str = ""  # comma list; "" = full space for this world size
    pads: str = ""  # comma list; "" = default pad_multiple ladder
    max_request: int = 1024  # serve-ladder request ceiling
    seed: int = 0
    sweep_log: str = "logs/kernel_benchmarks.jsonl"
    # outputs
    out_dir: str = ""  # "" = tune.record.default_record_dir()
    log_path: str = "logs/tune.jsonl"
    indent: int = 0  # >0 pretty-prints the record


def _build_workload(cfg: Config):
    from dgraph_tpu.data.synthetic import arxiv_shaped_edges, random_edges

    if cfg.arxiv:
        return arxiv_shaped_edges(cfg.seed)
    return (
        random_edges(cfg.nodes, cfg.edges, cfg.seed, cfg.symmetrize),
        cfg.nodes,
    )


def _run_search(cfg: Config, log):
    from dgraph_tpu.tune.record import default_record_dir
    from dgraph_tpu.tune.search import search

    edge_index, num_nodes = _build_workload(cfg)
    methods = [m for m in cfg.methods.split(",") if m] or None
    pads = [int(p) for p in cfg.pads.split(",") if p] or None
    result = search(
        edge_index,
        num_nodes,
        cfg.world,
        feat_dim=cfg.feat_dim,
        dtype=cfg.dtype,
        budget_s=cfg.budget,
        top_k=cfg.top_k,
        methods=methods,
        pad_multiples=pads,
        max_request=cfg.max_request,
        seed=cfg.seed,
        sweep_log=cfg.sweep_log,
        log=log,
    )
    out_dir = cfg.out_dir or default_record_dir()
    path = result.record.save(out_dir)
    return result, path


def _selftest(cfg: Config, log) -> dict:
    """Compile-free end-to-end check of the whole subsystem."""
    from dgraph_tpu import config as _dcfg
    from dgraph_tpu.tune.record import TuningRecord, adopt_record, lookup_record
    from dgraph_tpu.tune.signature import graph_signature

    failures = []
    with tempfile.TemporaryDirectory(prefix="dgraph_tune_selftest_") as tmp:
        cfg = dataclasses.replace(
            cfg, arxiv=False, nodes=400, edges=1600, world=2, feat_dim=16,
            budget=0.0, max_request=64, out_dir=tmp, sweep_log="",
        )
        result, path = _run_search(cfg, log)
        rec = result.record

        if rec.cost["winner_us"] > rec.cost["default_us"]:
            failures.append(
                f"winner cost {rec.cost['winner_us']} exceeds default "
                f"{rec.cost['default_us']} (the default is in the space; "
                f"the minimum cannot be above it)"
            )
        if not any(t.get("phase") == "analytic" for t in result.trace):
            failures.append("no analytic trace rows emitted")

        # overlap knob coverage (all analytic — no XLA compile): every
        # priced candidate must carry the overlap-vs-serial numbers, and
        # on a 2-shard graph with interior edges the exposed overlap cost
        # strictly beats serial rounds, so the winner adopts it
        priced = [
            t for t in result.trace
            if t.get("phase") == "analytic" and "overlap_exposed_us" in t
        ]
        if not priced:
            failures.append("analytic trace rows carry no overlap pricing")
        elif not all(
            t["overlap_exposed_us"] <= t["exchange_us"] or
            t["halo_impl"] != "overlap" for t in priced
        ):
            failures.append("an overlap winner priced above its exchange")
        if rec.config.get("halo_impl") != "overlap":
            failures.append(
                f"2-shard workload with interior edges should adopt the "
                f"overlap lowering, got {rec.config.get('halo_impl')!r}"
            )

        # the adopted record must round-trip tuned_halo_impl='overlap'
        # through save -> load -> adopt (the knob is useless if the
        # persisted winner cannot re-apply it next process)
        reloaded_ov = TuningRecord.load(path)
        saved_impl = _dcfg.tuned_halo_impl
        try:
            adopt_record(reloaded_ov)
            if _dcfg.tuned_halo_impl != "overlap":
                failures.append(
                    f"adopt_record set tuned_halo_impl="
                    f"{_dcfg.tuned_halo_impl!r}, expected 'overlap'"
                )
            from dgraph_tpu.plan import resolve_halo_impl

            impl, source = resolve_halo_impl(2, (1,), overlap_available=True)
            if (impl, source) != ("overlap", "record"):
                failures.append(
                    f"resolve_halo_impl under the adopted record returned "
                    f"({impl!r}, {source!r}), expected ('overlap', 'record')"
                )
            # a plan WITHOUT the split must degrade, never half-lower
            impl_no_spec, _ = resolve_halo_impl(2, (1,), overlap_available=False)
            if impl_no_spec == "overlap":
                failures.append(
                    "resolve_halo_impl lowered 'overlap' on a plan without "
                    "the interior/boundary split"
                )
        finally:
            _dcfg.set_flags(tuned_halo_impl=saved_impl)

        # pallas_p2p knob coverage (mirror of the overlap clause): every
        # analytic row prices the one-sided lowering next to the others,
        # and a record persisting halo_impl='pallas_p2p' round-trips
        # save -> load -> adopt -> resolve — with both degrade paths
        # (no split / no backend support) staying un-lowerable
        if priced and not all("pallas_p2p_exposed_us" in t for t in priced):
            failures.append("analytic trace rows carry no pallas_p2p pricing")
        p2p_rec = TuningRecord.create(
            rec.signature,
            {**rec.config, "halo_impl": "pallas_p2p"},
            rec.cost, rec.phase,
        )
        with tempfile.TemporaryDirectory(
            prefix="dgraph_tune_selftest_p2p_"
        ) as p2p_dir:
            p2p_path = p2p_rec.save(p2p_dir)
            reloaded_p2p = TuningRecord.load(p2p_path)
            saved_impl = _dcfg.tuned_halo_impl
            saved_p2p = _dcfg.use_pallas_p2p
            try:
                adopt_record(reloaded_p2p)
                if _dcfg.tuned_halo_impl != "pallas_p2p":
                    failures.append(
                        f"adopt_record set tuned_halo_impl="
                        f"{_dcfg.tuned_halo_impl!r}, expected 'pallas_p2p'"
                    )
                from dgraph_tpu.plan import resolve_halo_impl

                _dcfg.set_flags(use_pallas_p2p=True)
                impl, source = resolve_halo_impl(
                    2, (1,), overlap_available=True)
                if (impl, source) != ("pallas_p2p", "record"):
                    failures.append(
                        f"resolve_halo_impl under the adopted pallas_p2p "
                        f"record returned ({impl!r}, {source!r}), expected "
                        f"('pallas_p2p', 'record')"
                    )
                # a plan WITHOUT the split must degrade, never half-lower
                impl_no_spec, _ = resolve_halo_impl(
                    2, (1,), overlap_available=False)
                if impl_no_spec == "pallas_p2p":
                    failures.append(
                        "resolve_halo_impl lowered 'pallas_p2p' on a plan "
                        "without the interior/boundary split"
                    )
                # ... and so must a backend that cannot lower the kernels
                _dcfg.set_flags(use_pallas_p2p=False)
                impl_no_backend, _ = resolve_halo_impl(
                    2, (1,), overlap_available=True)
                if impl_no_backend == "pallas_p2p":
                    failures.append(
                        "resolve_halo_impl lowered 'pallas_p2p' with "
                        "pallas_p2p_available() False"
                    )
            finally:
                _dcfg.set_flags(
                    tuned_halo_impl=saved_impl, use_pallas_p2p=saved_p2p)

        # round trip: the persisted JSON reloads, validates, and is found
        # by a signature lookup
        reloaded = TuningRecord.load(path)
        if reloaded.record_id != rec.record_id:
            failures.append("record round-trip changed record_id")
        edge_index, num_nodes = _build_workload(cfg)
        sig = graph_signature(
            edge_index, num_nodes, cfg.world, dtype=cfg.dtype,
            feat_dim=cfg.feat_dim,
        )
        found = lookup_record(sig, cache_dir=tmp)
        if found is None or found.record_id != rec.record_id:
            failures.append("signature lookup missed the saved record")

        # a different workload must fall back to None, not half-adopt
        other = graph_signature(
            edge_index, num_nodes, cfg.world + 1, dtype=cfg.dtype,
            feat_dim=cfg.feat_dim,
        )
        if lookup_record(other, cache_dir=tmp) is not None:
            failures.append("mismatched signature adopted a record")

        kw = adopt_record(rec)
        if "partition_method" not in kw or "pad_multiple" not in kw:
            failures.append(f"adopt_record returned {kw}, expected build kwargs")

    return {
        "kind": "tune_selftest",
        "failures": failures,
        "record_id": rec.record_id,
        "phase": rec.phase,
        "cost": rec.cost,
    }


def main(cfg: Config) -> dict:
    from dgraph_tpu.obs.health import RunHealth
    from dgraph_tpu.utils import ExperimentLog

    health = RunHealth.begin("tune.cli")
    log = ExperimentLog(cfg.log_path, echo=False)
    try:
        if cfg.selftest:
            out = _selftest(cfg, log)
            failures = out["failures"]
            out["run_health"] = health.finish(
                "; ".join(failures) if failures else None,
                wedge="stage_failure" if failures else None,
            )
            log.write(out)
            print(json.dumps(out, indent=cfg.indent or None))
            if failures:
                raise SystemExit("tune selftest FAILED: " + "; ".join(failures))
            return out
        if cfg.budget > 0:
            # the measured phase is about to touch the backend; record the
            # topology the numbers will come from
            health.snapshot_backend()
        result, path = _run_search(cfg, log)
        out = {
            "kind": "tuning_record",
            **result.record.to_dict(),
            "path": path,
            "ranked": result.ranked,
            "measured": result.measured,
            "run_health": health.finish(),
        }
        log.write(out)
        print(json.dumps(out, indent=cfg.indent or None))
        return out
    except SystemExit:
        raise
    except BaseException as e:  # every exit path carries a RunHealth record
        log.write(
            {
                "kind": "run_health",
                **health.finish(
                    f"tune failed: {type(e).__name__}: {e}",
                    wedge="interrupted"
                    if isinstance(e, KeyboardInterrupt)
                    else "stage_failure",
                ),
            }
        )
        raise


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
