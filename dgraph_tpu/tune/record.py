"""Versioned, persisted tuning records and their adoption rules.

A :class:`TuningRecord` is the durable output of one autotuner run: the
winning configuration for one workload signature, plus enough cost context
to audit *why* it won. Records are single JSON files named
``tune_<signature_key>.json`` inside the plan-cache directory (the same
directory :func:`~dgraph_tpu.train.checkpoint.cached_edge_plan` uses), so
the artifacts that must travel together — the cached plan and the config
that built it — live together.

Adoption rules (implemented by :func:`lookup_record`):

- env ``DGRAPH_TUNE_RECORD=<path>`` pins one record file unconditionally
  (a signature mismatch is warned about, not rejected — pinning exists for
  exactly the "I know better" case);
- env ``DGRAPH_TUNE_RECORD=off`` (or ``0`` / ``none``) disables adoption;
- otherwise the caller's plan-cache dir, then :func:`default_record_dir`
  (env ``DGRAPH_TUNE_DIR``, default ``cache/plans``), are probed for a
  record whose stored signature matches field-by-field. No match -> the
  hard-coded defaults, exactly as before the tuner existed.

:func:`adopt_record` applies the runtime-scoped knobs (the tuned halo
lowering, via :mod:`dgraph_tpu.config` so ``comm.collectives`` and
``obs.footprint`` both see it) and returns the build-scoped kwargs
(partition method, pad_multiple) for the caller to pass explicitly —
adoption never mutates plan-builder module state behind the caller's back.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Optional

from dgraph_tpu.tune.signature import signature_key, signatures_match

_logger = logging.getLogger("dgraph_tpu.tune")

RECORD_SCHEMA_VERSION = 1

# config keys a record may carry; "serve" is a nested dict (ladder geometry)
_BUILD_KEYS = ("partition_method", "pad_multiple")
_KNOWN_CONFIG_KEYS = _BUILD_KEYS + (
    "edge_owner",
    "halo_impl",
    "wire_format",
    "use_pallas_scatter",
    "scatter_block_e",
    "scatter_block_n",
    "serve",
)

ENV_RECORD = "DGRAPH_TUNE_RECORD"
ENV_DIR = "DGRAPH_TUNE_DIR"
_DISABLE_VALUES = ("", "0", "off", "none", "disabled", "false")


def default_record_dir() -> str:
    """Where records land when no plan-cache dir is in play: env
    ``DGRAPH_TUNE_DIR``, else the repo-conventional ``cache/plans``."""
    return os.environ.get(ENV_DIR) or os.path.join("cache", "plans")


def record_path(directory: str, sig: dict) -> str:
    return os.path.join(directory, f"tune_{signature_key(sig)}.json")


@dataclasses.dataclass
class TuningRecord:
    """One workload's winning configuration, JSON round-trippable."""

    record_id: str
    signature: dict
    config: dict
    cost: dict
    phase: str  # 'analytic' | 'measured'
    created_at: str = ""
    schema: int = RECORD_SCHEMA_VERSION

    @classmethod
    def create(
        cls, signature: dict, config: dict, cost: dict, phase: str
    ) -> "TuningRecord":
        rid = f"tune-{signature_key(signature)}-v{RECORD_SCHEMA_VERSION}"
        rec = cls(
            record_id=rid,
            signature=dict(signature),
            config=dict(config),
            cost=dict(cost),
            phase=phase,
            created_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        )
        rec.validate()
        return rec

    def validate(self) -> None:
        """Structural validation; raises ValueError naming the defect (a
        hand-edited or truncated record must fail adoption loudly, not
        half-apply)."""
        errors = []
        if self.schema != RECORD_SCHEMA_VERSION:
            errors.append(
                f"schema {self.schema} != supported {RECORD_SCHEMA_VERSION}"
            )
        if not isinstance(self.signature, dict) or "degree_digest" not in self.signature:
            errors.append("signature missing or lacks degree_digest")
        if self.phase not in ("analytic", "measured"):
            errors.append(f"phase {self.phase!r} not analytic|measured")
        if not isinstance(self.config, dict) or not self.config:
            errors.append("config empty")
        else:
            unknown = set(self.config) - set(_KNOWN_CONFIG_KEYS)
            if unknown:
                errors.append(f"unknown config keys {sorted(unknown)}")
            pm = self.config.get("pad_multiple")
            if pm is not None and (not isinstance(pm, int) or pm < 1):
                errors.append(f"pad_multiple {pm!r} not a positive int")
            impl = self.config.get("halo_impl")
            if impl is not None and impl not in (
                "none", "ppermute", "all_to_all", "overlap", "pallas_p2p",
                "sched",
            ):
                errors.append(f"halo_impl {impl!r} unknown")
            wf = self.config.get("wire_format")
            if wf is not None:
                from dgraph_tpu.wire.spec import WIRE_FORMAT_NAMES

                if wf not in WIRE_FORMAT_NAMES:
                    errors.append(
                        f"wire_format {wf!r} unknown "
                        f"(known: {WIRE_FORMAT_NAMES})"
                    )
            serve = self.config.get("serve")
            if serve is not None:
                # the serve CLI indexes these directly; a partial dict must
                # fail HERE (load/validate time), not as a KeyError deep in
                # serving startup
                if not isinstance(serve, dict) or not (
                    all(
                        isinstance(serve.get(k), int)
                        and not isinstance(serve.get(k), bool)
                        for k in ("min_bucket", "max_bucket")
                    )
                    and isinstance(serve.get("growth"), (int, float))
                ):
                    errors.append(
                        "serve config must carry int min_bucket/max_bucket "
                        f"and numeric growth, got {serve!r}"
                    )
        if not isinstance(self.cost, dict) or "winner_us" not in self.cost:
            errors.append("cost missing winner_us")
        if errors:
            raise ValueError("invalid TuningRecord: " + "; ".join(errors))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TuningRecord":
        known = {f.name for f in dataclasses.fields(cls)}
        rec = cls(**{k: v for k, v in d.items() if k in known})
        rec.validate()
        return rec

    def save(self, directory: str) -> str:
        """Atomic durable write to ``directory``; returns the path.

        Routed through :func:`~dgraph_tpu.plan_shards.atomic_write_json`
        (fsync before the rename): a tuning record silently truncated by
        a host crash would otherwise be *adopted* as a corrupt-but-named
        config on the next run (``analysis.host``'s
        ``host-durable-write`` rule pins the routing)."""
        from dgraph_tpu.plan_shards import atomic_write_json

        os.makedirs(directory, exist_ok=True)
        path = record_path(directory, self.signature)
        atomic_write_json(path, self.to_dict())
        return path

    @classmethod
    def load(cls, path: str) -> "TuningRecord":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def lookup_record(sig: dict, cache_dir: str = "") -> Optional[TuningRecord]:
    """Resolve the record to adopt for ``sig`` under the adoption rules
    above. Returns None when adoption is disabled, nothing matches, or a
    candidate file is unreadable/mismatched (logged, never raised — a
    corrupt record degrades to the defaults, not a crash)."""
    pin = os.environ.get(ENV_RECORD)
    if pin is not None:
        if pin.strip().lower() in _DISABLE_VALUES:
            return None
        try:
            rec = TuningRecord.load(pin)
        except (OSError, ValueError, KeyError, TypeError) as e:
            _logger.warning(
                "%s=%s unreadable (%s: %s); tuning disabled for this run",
                ENV_RECORD, pin, type(e).__name__, e,
            )
            return None
        if not signatures_match(rec.signature, sig):
            _logger.warning(
                "pinned tuning record %s was tuned for a different workload "
                "(signature mismatch); adopting anyway because %s pins it",
                rec.record_id, ENV_RECORD,
            )
        return rec
    for d in dict.fromkeys((cache_dir or "", default_record_dir())):
        if not d:
            continue
        path = record_path(d, sig)
        if not os.path.exists(path):
            continue
        try:
            rec = TuningRecord.load(path)
        except (OSError, ValueError, KeyError, TypeError) as e:
            _logger.warning(
                "tuning record %s unreadable (%s: %s); ignoring",
                path, type(e).__name__, e,
            )
            continue
        if not signatures_match(rec.signature, sig):
            # filename collision or a hand-edit: the stored signature is
            # authoritative, and it says this record is for another graph
            _logger.warning(
                "tuning record %s signature does not match this workload; "
                "ignoring", path,
            )
            continue
        return rec
    return None


def clear_adoption() -> None:
    """Reset the process-global tuned flags to the no-record state.

    Adoption state is process-global (``config.tuned_halo_impl`` /
    ``config.tuned_wire_format`` / ``config.tuning_record_id``); a
    consumer that looked up a record and found NONE must call this so a
    previously adopted graph's halo lowering (or wire codec) cannot
    silently leak onto an untuned one built later in the same
    process."""
    from dgraph_tpu import config as _cfg

    _cfg.set_flags(
        tuned_halo_impl=None, tuned_wire_format=None, tuning_record_id=None
    )


def adopt_record(rec: TuningRecord) -> dict:
    """Apply runtime-scoped knobs and return build-scoped kwargs.

    Sets ``dgraph_tpu.config.tuned_halo_impl`` (consulted by the halo
    lowering resolver between the env pin and the heuristic) and
    ``config.tuning_record_id`` (process-level attribution for consumers
    without a graph handle), then returns ``{partition_method,
    pad_multiple}`` (the keys present in the record) for the caller to
    feed into the plan build. The flags describe the MOST RECENT adoption
    decision; lookup misses must go through :func:`clear_adoption`.
    """
    from dgraph_tpu import config as _cfg

    impl = rec.config.get("halo_impl")
    _cfg.set_flags(
        tuned_halo_impl=impl
        if impl in ("ppermute", "all_to_all", "overlap", "pallas_p2p", "sched")
        else None
    )
    # the tuned wire format rides the 'record' tier of wire.spec.
    # resolve_wire_format; an fp32 winner clears the flag (identity is
    # the default, not an adoption)
    wf = rec.config.get("wire_format")
    _cfg.set_flags(
        tuned_wire_format=wf if wf not in (None, "fp32") else None
    )
    _cfg.set_flags(tuning_record_id=rec.record_id)
    _logger.info(
        "adopted tuning record %s (phase=%s): %s",
        rec.record_id, rec.phase,
        {k: v for k, v in rec.config.items() if k != "serve"},
    )
    # longitudinal trajectory: each adoption joins the perf ledger when
    # DGRAPH_LEDGER_DIR is set (off by default; maybe_ingest swallows
    # every failure — adoption must never break on observability)
    from dgraph_tpu.obs.ledger import maybe_ingest

    maybe_ingest(
        {"kind": "tune_record", **rec.to_dict()},
        source="tune.adopt", default_on=False,
    )
    return {k: rec.config[k] for k in _BUILD_KEYS if k in rec.config}
