"""Kernel-sweep winner picking (the ``scripts/adopt_sweep.py`` logic,
promoted into the tuner so the search can consume measured tile data).

Reads ``logs/kernel_benchmarks.jsonl`` (the ``kernel_benchmarks.py
--sweep true`` output) and derives: the fastest (block_e, block_n) per
(kernel, dtype, F), the XLA-vs-Pallas verdicts the config defaults hang
on, and the consensus tile pair a plan should carry. The NaN-row guard
lives here: NaN ``ms`` rows mark per-op failures (a crashed compile, a
noisy tunnel), and ``min()`` over a dict containing NaN can crown the
crashed tile as winner (every ``x < nan`` is False), so non-finite rows
are dropped before any ranking. :func:`dgraph_tpu.tune.search.search`
applies the same guard to its measured phase.

Pure stdlib by design: ``scripts/adopt_sweep.py`` stays a thin wrapper
that loads this file directly (no package import, hence no jax import),
so the script keeps working with the TPU lease in any state.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Optional


def load_rows(path: str) -> list:
    """JSONL rows from an append-only sweep log (non-JSON lines skipped)."""
    rows = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line.startswith("{"):
                rows.append(json.loads(line))
    return rows


def deployed_scatter_op(dtype: str) -> str:
    """The scatter variant the framework actually DEPLOYS per dtype
    (ops/local.py: prec='highest' whenever dtype != bfloat16 — comparing
    the bf16-MXU 'default' variant for f32 would judge a kernel that
    never runs in f32 training)."""
    is_bf16 = dtype in ("bf16", "bfloat16")
    return (
        "segment_sum_pallas_default" if is_bf16 else "segment_sum_pallas_highest"
    )


def pick_winners(rows: list) -> dict:
    """Structured winner report from sweep rows.

    Returns ``{"winners": {(op, dtype, F): (block_e, block_n)}, "tiles":
    {key: {(be, bn): ms}}, "verdicts": [{flag, dtype, F, xla_ms,
    pallas_ms, verdict, speedup}], "consensus": (be, bn) | None,
    "consensus_votes": (n, total)}``. Latest record wins for identical
    keys (the log is append-only); non-finite ``ms`` rows are dropped
    (the NaN guard).
    """

    def key(r, *names):
        return tuple(r.get(n) for n in names)

    sweep = defaultdict(dict)  # (op, dtype, F) -> {(be, bn): ms}
    flat = {}  # (op, dtype, F) -> ms (non-sweep rows)
    for r in rows:
        ms = r.get("ms")
        if ms is None or ms != ms:  # NaN guard
            continue
        k = key(r, "op", "dtype", "F")
        if "block_e" in r:
            sweep[k][(r["block_e"], r["block_n"])] = r["ms"]
        else:
            flat[k] = r["ms"]

    winners = {k: min(tiles, key=tiles.get) for k, tiles in sweep.items()}

    verdicts = []
    for k, ms_x in sorted(flat.items()):
        op, dtype, F = k
        if op == "segment_sum_xla":
            pl_ops, flag = [deployed_scatter_op(dtype)], "use_pallas_scatter"
        elif op == "gather_sorted_xla":
            pl_ops = ["gather_sorted_pallas", "gather_sorted_pallas_sweep"]
            flag = "use_pallas_gather"
        else:
            continue
        best_p = None
        for pl_op in pl_ops:
            k_pl = (pl_op, dtype, F)
            cands = [flat[k_pl]] if k_pl in flat else []
            if k_pl in sweep:
                cands.append(min(sweep[k_pl].values()))
            for ms in cands:
                best_p = ms if best_p is None else min(best_p, ms)
        if best_p is None:
            continue
        verdicts.append(
            {
                "flag": flag,
                "dtype": dtype,
                "F": F,
                "xla_ms": ms_x,
                "pallas_ms": best_p,
                "verdict": "PALLAS" if best_p < ms_x else "XLA",
                "speedup": ms_x / best_p,
            }
        )

    # consensus tile across kernels/dtypes: the plan carries ONE
    # (scatter_block_e, scatter_block_n) pair serving BOTH kernels, so
    # each (kernel FAMILY, dtype, F) gets exactly one vote — counting
    # both precision variants of the scatter would double-weight it
    # against the gather
    def family(op, dtype):
        if op.startswith("segment_sum_pallas"):
            return ("scatter", dtype) if op == deployed_scatter_op(dtype) else None
        if op.startswith("gather_sorted_pallas"):
            return ("gather", dtype)
        return None

    votes = defaultdict(int)
    for (op, dtype, F), best in winners.items():
        if family(op, dtype) is None:
            continue
        votes[best] += 1
    consensus, n_votes = None, (0, 0)
    if votes:
        consensus, n = max(votes.items(), key=lambda kv: kv[1])
        n_votes = (n, sum(votes.values()))

    return {
        "winners": winners,
        "tiles": dict(sweep),
        "verdicts": verdicts,
        "consensus": consensus,
        "consensus_votes": n_votes,
    }


def sweep_report(path: str = "logs/kernel_benchmarks.jsonl") -> Optional[dict]:
    """pick_winners over a log file; None when the log is missing or empty
    (the search treats that as 'no measured kernel data')."""
    try:
        rows = load_rows(path)
    except OSError:
        return None
    if not rows:
        return None
    return pick_winners(rows)


def main(path: str = "logs/kernel_benchmarks.jsonl") -> None:
    """Print the human report (byte-compatible with the historical
    ``scripts/adopt_sweep.py`` workflow)."""
    rows = load_rows(path)
    if not rows:
        raise SystemExit(f"no records in {path}")
    report = pick_winners(rows)

    print("== tile winners (lowest ms) ==")
    for k in sorted(report["winners"]):
        best = report["winners"][k]
        tiles = report["tiles"][k]
        ranked = sorted(tiles.items(), key=lambda kv: kv[1])
        line = ", ".join(f"{be}x{bn}={ms:.3f}" for (be, bn), ms in ranked[:4])
        print(
            f"{k[0]} [{k[1]} F={k[2]}]: WINNER block_e={best[0]} "
            f"block_n={best[1]}  ({line})"
        )

    print("\n== XLA vs Pallas verdicts (deployed precision per dtype) ==")
    for v in report["verdicts"]:
        print(
            f"{v['flag']} [{v['dtype']} F={v['F']}]: xla={v['xla_ms']:.3f} "
            f"pallas={v['pallas_ms']:.3f} -> {v['verdict']} "
            f"({v['speedup']:.2f}x)"
        )

    if report["consensus"] is not None:
        be, bn = report["consensus"]
        n, total = report["consensus_votes"]
        print(
            f"\n== consensus: block_e={be} block_n={bn} "
            f"({n}/{total} family votes) =="
        )
        print(
            "adopt in: dgraph_tpu/plan.py (scatter_block_e/_n defaults) + "
            "PLAN_FORMAT_VERSION bump if changed"
        )
