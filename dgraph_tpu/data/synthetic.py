"""Synthetic graph datasets for tests and benchmarks.

The reference keeps synthetic generators beside every real dataset so the
full stack is exercisable without downloads: synthetic MAG-like hetero graphs
(``experiments/OGB-LSC/lsc_datasets/synthetic_dataset.py:37-76``) and a
synthetic ERA5 weather dataset (``experiments/GraphCast/dataset.py:24-232``).
Same policy here (this environment has no ogb package and zero egress; the
OGB wrapper in ``dgraph_tpu.data.ogb`` gates on ogb availability).
"""

from __future__ import annotations

import numpy as np

# ogbn-arxiv shape (V, directed E before symmetrization) — the bench
# workload's dimensions
ARXIV_NODES = 169_343
ARXIV_EDGES = 1_166_243


def random_edges(
    num_nodes: int, num_edges: int, seed: int = 0, symmetrize: bool = True
) -> np.ndarray:
    """Uniform random [2, E] edge list — THE shared construction
    ``bench.py``, ``obs.footprint``'s CLI, and ``dgraph_tpu.tune`` use for
    the arxiv-shaped synthetic workload. One definition, because the tune
    subsystem keys records on a graph signature: three hand-rolled copies
    that drift by an rng call would silently stop matching each other."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, num_nodes, num_edges)
    dst = rng.integers(0, num_nodes, num_edges)
    if symmetrize:
        return np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
    return np.stack([src, dst]).astype(np.int64)


def arxiv_shaped_edges(seed: int = 0) -> tuple:
    """(edge_index [2, 2*ARXIV_EDGES], num_nodes) for the bench workload."""
    return random_edges(ARXIV_NODES, ARXIV_EDGES, seed), ARXIV_NODES


def sbm_classification_graph(
    num_nodes: int = 1000,
    num_classes: int = 4,
    feat_dim: int = 16,
    avg_degree: float = 8.0,
    homophily: float = 0.8,
    train_frac: float = 0.6,
    val_frac: float = 0.2,
    seed: int = 0,
):
    """Stochastic-block-model node-classification task (Cora-like shape).

    Features = class centroid + noise; edges mostly intra-class, so graph
    aggregation is genuinely informative (a GCN beats an MLP).

    Returns dict(edge_index [2,E], features [V,F], labels [V],
    masks {train,val,test}).
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, num_nodes)
    centroids = rng.normal(0, 1.0, (num_classes, feat_dim))
    feats = centroids[labels] + rng.normal(0, 2.0, (num_nodes, feat_dim))

    E = int(num_nodes * avg_degree // 2)
    # rejection sampling with the ANALYTIC acceptance rate: p(keep) =
    # homophily/num_classes + (1-homophily)(1-1/num_classes); a fixed 3x
    # oversample silently underfills the quota at high class counts
    # (num_classes=40, homophily=0.8 -> ~0.215 keep rate, ~35% short)
    p_keep = homophily / num_classes + (1 - homophily) * (1 - 1 / num_classes)
    src_parts, dst_parts, have = [], [], 0
    while have < E:
        n_draw = int((E - have) / max(p_keep, 1e-6) * 1.2) + 1024
        s = rng.integers(0, num_nodes, n_draw)
        d = rng.integers(0, num_nodes, n_draw)
        same = labels[s] == labels[d]
        keep = np.where(same, rng.random(n_draw) < homophily, rng.random(n_draw) < (1 - homophily))
        keep &= s != d
        src_parts.append(s[keep])
        dst_parts.append(d[keep])
        have += int(keep.sum())
    src = np.concatenate(src_parts)[:E]
    dst = np.concatenate(dst_parts)[:E]
    # symmetrize (the reference's OGB preprocessing does the same for arxiv)
    edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)

    order = rng.permutation(num_nodes)
    n_tr = int(train_frac * num_nodes)
    n_va = int(val_frac * num_nodes)
    masks = {
        "train": np.zeros(num_nodes, bool),
        "val": np.zeros(num_nodes, bool),
        "test": np.zeros(num_nodes, bool),
    }
    masks["train"][order[:n_tr]] = True
    masks["val"][order[n_tr : n_tr + n_va]] = True
    masks["test"][order[n_tr + n_va :]] = True
    return {
        "edge_index": edge_index,
        "features": feats.astype(np.float32),
        "labels": labels.astype(np.int32),
        "masks": masks,
        "num_classes": num_classes,
    }


def power_law_graph(num_nodes: int, avg_degree: float, seed: int = 0) -> np.ndarray:
    """Degree-skewed random digraph (papers100M-like degree profile) —
    endpoint sampling proportional to a Zipf-ish weight."""
    rng = np.random.default_rng(seed)
    E = int(num_nodes * avg_degree)
    w = 1.0 / np.arange(1, num_nodes + 1) ** 0.75
    w /= w.sum()
    src = rng.choice(num_nodes, E, p=w)
    dst = rng.integers(0, num_nodes, E)
    return np.stack([src, dst]).astype(np.int64)
