from dgraph_tpu.data.graph import DistributedGraph
from dgraph_tpu.data import memmap, ogbn, synthetic

__all__ = ["DistributedGraph", "memmap", "ogbn", "synthetic"]
