from dgraph_tpu.data.graph import DistributedGraph
from dgraph_tpu.data import memmap, synthetic

__all__ = ["DistributedGraph", "memmap", "synthetic"]
