from dgraph_tpu.data.graph import DistributedGraph
from dgraph_tpu.data import synthetic

__all__ = ["DistributedGraph", "synthetic"]
