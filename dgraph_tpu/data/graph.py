"""DistributedGraph: one vertex-partitioned graph, plan + sharded tensors.

Reference parity: ``DGraph/data/graph.py:24-268`` (DistributedGraph holding
features/edge_index/labels + rank maps with per-rank slicing accessors) and
``DGraph/data/preprocess.py`` (renumbering/edge sort). TPU-first: instead of
per-rank slicing accessors, everything is stored stacked ``[W, n_pad, ...]``
ready to place on the mesh with ``PartitionSpec('graph')``; masks replace the
reference's node-range arithmetic (``graph.py:224-259``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dgraph_tpu import partition as pt
from dgraph_tpu.plan import (
    EdgePlan,
    EdgePlanLayout,
    shard_edge_data,
    shard_vertex_data,
)


@dataclasses.dataclass
class DistributedGraph:
    num_nodes: int
    num_edges: int
    world_size: int
    edge_index: np.ndarray  # [2, E] renumbered (contiguous per-rank blocks)
    ren: pt.Renumbering
    plan: EdgePlan
    layout: EdgePlanLayout
    features: np.ndarray  # [W, n_pad, F]
    # [W, n_pad] int32 class ids, or [W, n_pad, C] float32 multi-label
    # targets (ogbn-proteins); float inputs keep their dtype through
    # from_global for BCE losses
    labels: Optional[np.ndarray]
    masks: dict  # split name -> [W, n_pad] f32
    vertex_mask: np.ndarray  # [W, n_pad] f32: 1.0 for real vertices
    edge_weight: Optional[np.ndarray] = None  # [W, e_pad] f32
    # the adopted TuningRecord (dgraph_tpu.tune), or None when the
    # hard-coded defaults are in effect; serving/health artifacts read
    # tuning_record_id off this so perf numbers stay attributable
    tuning_record: Optional[object] = None

    @property
    def tuning_record_id(self) -> Optional[str]:
        return self.tuning_record.record_id if self.tuning_record else None

    @classmethod
    def from_global(
        cls,
        edge_index: np.ndarray,
        features: np.ndarray,
        labels: Optional[np.ndarray],
        masks: Optional[dict],
        world_size: int,
        *,
        partition_method: Optional[str] = None,
        edge_owner: str = "dst",
        add_symmetric_norm: bool = False,
        pad_multiple: Optional[int] = None,
        seed: int = 0,
        sample_frac: Optional[float] = None,
        edge_balance: Optional[float] = None,
        partition_kwargs: Optional[dict] = None,
        plan_cache_dir: str = "",
        tune: str = "auto",
    ) -> "DistributedGraph":
        """Partition + plan + shard one global graph.

        ``partition_method`` / ``pad_multiple`` left at None resolve
        through the tuning layer: with ``tune="auto"`` (default) a
        persisted :class:`~dgraph_tpu.tune.record.TuningRecord` matching
        this graph's signature (in ``plan_cache_dir`` or the default
        record dir; env ``DGRAPH_TUNE_RECORD`` pins/disables) supplies
        them, else the hard-coded defaults (``"rcm"`` / ``8``) apply.
        Explicit values always win — adoption never overrides a caller's
        stated choice. ``tune="off"`` skips the lookup entirely.

        ``sample_frac`` / ``edge_balance`` are the
        ``method="multilevel_sampled"`` quality knobs (ADVICE r5: the
        measured-good p100m blend — 0.35 sample fraction + edge-balance
        vertex weights — was previously reachable only from
        ``scripts/p100m_r5_stages.py``), forwarded to
        :func:`~dgraph_tpu.partition.partition_graph` (which rejects
        them for other methods) and folded into the plan-cache key so a
        re-blended partition can never warm-hit a plan built under
        different knobs.
        """
        if tune not in ("auto", "off"):
            raise ValueError(f"tune must be 'auto' or 'off', got {tune!r}")
        from dgraph_tpu import chaos

        chaos.fire("data.load")  # the partition/plan/shard host boundary
        num_nodes = features.shape[0]
        edge_index = np.asarray(edge_index)
        from dgraph_tpu.tune.record import (
            adopt_record,
            clear_adoption,
            lookup_record,
        )

        record = None
        if tune == "auto" and (partition_method is None or pad_multiple is None):
            from dgraph_tpu import config as _cfg
            from dgraph_tpu.tune.signature import graph_signature

            # dtype axis of the signature = the COMPUTE dtype the run will
            # use (a bfloat16-tuned record is a different workload from a
            # float32 one), not the storage dtype of the features array —
            # from_global casts those to f32 regardless
            sig = graph_signature(
                edge_index, num_nodes, world_size,
                dtype=_cfg.default_compute_dtype,
                feat_dim=features.shape[1] if features.ndim > 1 else 0,
            )
            record = lookup_record(sig, cache_dir=plan_cache_dir)
            if record is not None:
                tuned = adopt_record(record)
                if partition_method is None:
                    partition_method = tuned.get("partition_method")
                if pad_multiple is None:
                    pad_multiple = tuned.get("pad_multiple")
        if record is None:
            # no record adopted for THIS graph — whether the lookup missed,
            # tune="off", or explicit knobs skipped it entirely: reset the
            # process-global tuned flags so an earlier graph's adopted halo
            # lowering cannot leak onto this one (most-recent-wins)
            clear_adoption()
        if partition_method is None:
            partition_method = "rcm"
        if pad_multiple is None:
            pad_multiple = 8
        part_kwargs = dict(partition_kwargs or {})
        # explicit first-class knobs win over a duplicate in
        # partition_kwargs (the pre-plumbing spelling)
        if sample_frac is not None:
            part_kwargs["sample_frac"] = sample_frac
        if edge_balance is not None:
            part_kwargs["edge_balance"] = edge_balance
        new_edges, ren = pt.partition_graph(
            edge_index, num_nodes, world_size, method=partition_method,
            seed=seed, **part_kwargs,
        )
        # the on-disk plan cache (train/checkpoint.cached_edge_plan) resolves
        # a falsy dir to a plain build, so this is the one call site either way
        from dgraph_tpu.train.checkpoint import cached_edge_plan

        # an adopted record whose halo lowering is 'overlap' needs the plan
        # to CARRY the interior/boundary split — pass the intent explicitly
        # so the plan-cache fingerprint distinguishes spec-ful plans (None
        # keeps the builder's env/record auto-resolution for everyone else)
        overlap = True if (
            record is not None and record.config.get("halo_impl") == "overlap"
        ) else None
        # partition knobs ride the cache key (key_extra folds into the
        # fingerprint without reaching the plan builder): the partition
        # CONTENT is hashed too, so this is belt-and-braces against two
        # blends that happen to collide — and it makes the artifact name
        # self-describing for cache forensics
        key_extra = {"partition_method": partition_method}
        for k, v in part_kwargs.items():
            key_extra[f"part_{k}"] = v
        plan, layout = cached_edge_plan(
            plan_cache_dir,
            new_edges,
            ren.partition,
            world_size=world_size,
            edge_owner=edge_owner,
            pad_multiple=pad_multiple,
            overlap=overlap,
            key_extra=key_extra,
        )
        n_pad = plan.n_src_pad
        feats = shard_vertex_data(
            np.asarray(features)[ren.inv], ren.counts, n_pad
        ).astype(np.float32)
        if labels is not None:
            lab_arr = np.asarray(labels)
            # integer class ids -> int32; float arrays (e.g. ogbn-proteins'
            # [V, 112] multi-label targets) keep float32 for BCE losses
            lab_dtype = (
                np.float32 if np.issubdtype(lab_arr.dtype, np.floating) else np.int32
            )
            lab = shard_vertex_data(
                lab_arr[ren.inv].astype(lab_dtype), ren.counts, n_pad
            )
        else:
            lab = None
        m = {}
        if masks:
            for k, v in masks.items():
                m[k] = shard_vertex_data(
                    np.asarray(v).astype(np.float32)[ren.inv], ren.counts, n_pad
                )
        vmask = shard_vertex_data(
            np.ones(num_nodes, np.float32), ren.counts, n_pad
        )
        ew = None
        if add_symmetric_norm:
            ew = shard_edge_data(
                symmetric_norm_weights(new_edges, num_nodes), layout, plan.e_pad
            )
        return cls(
            num_nodes=num_nodes,
            num_edges=edge_index.shape[1],
            world_size=world_size,
            edge_index=new_edges,
            ren=ren,
            plan=plan,
            layout=layout,
            features=feats,
            labels=lab,
            masks=m,
            vertex_mask=vmask,
            edge_weight=ew,
            tuning_record=record,
        )

    def batch(self, split: str) -> dict:
        """Pytree for the train/eval step: leaves have leading [W] axis."""
        out = {
            "x": self.features,
            "mask": self.masks[split] if split in self.masks else self.vertex_mask,
        }
        if self.labels is not None:
            out["y"] = self.labels
        if self.edge_weight is not None:
            out["edge_weight"] = self.edge_weight
        return out


def symmetric_norm_weights(edge_index: np.ndarray, num_nodes: int) -> np.ndarray:
    """Kipf-Welling GCN normalization 1/sqrt(d_src * d_dst) per edge."""
    src, dst = edge_index
    deg = np.zeros(num_nodes, np.float64)
    np.add.at(deg, src, 1.0)
    np.add.at(deg, dst, 1.0)
    deg = np.maximum(deg, 1.0)
    return (1.0 / np.sqrt(deg[src] * deg[dst])).astype(np.float32)
