"""DistributedGraph: one vertex-partitioned graph, plan + sharded tensors.

Reference parity: ``DGraph/data/graph.py:24-268`` (DistributedGraph holding
features/edge_index/labels + rank maps with per-rank slicing accessors) and
``DGraph/data/preprocess.py`` (renumbering/edge sort). TPU-first: instead of
per-rank slicing accessors, everything is stored stacked ``[W, n_pad, ...]``
ready to place on the mesh with ``PartitionSpec('graph')``; masks replace the
reference's node-range arithmetic (``graph.py:224-259``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dgraph_tpu import partition as pt
from dgraph_tpu.plan import (
    EdgePlan,
    EdgePlanLayout,
    shard_edge_data,
    shard_vertex_data,
)


@dataclasses.dataclass
class DistributedGraph:
    num_nodes: int
    num_edges: int
    world_size: int
    edge_index: np.ndarray  # [2, E] renumbered (contiguous per-rank blocks)
    ren: pt.Renumbering
    plan: EdgePlan
    layout: EdgePlanLayout
    features: np.ndarray  # [W, n_pad, F]
    # [W, n_pad] int32 class ids, or [W, n_pad, C] float32 multi-label
    # targets (ogbn-proteins); float inputs keep their dtype through
    # from_global for BCE losses
    labels: Optional[np.ndarray]
    masks: dict  # split name -> [W, n_pad] f32
    vertex_mask: np.ndarray  # [W, n_pad] f32: 1.0 for real vertices
    edge_weight: Optional[np.ndarray] = None  # [W, e_pad] f32

    @classmethod
    def from_global(
        cls,
        edge_index: np.ndarray,
        features: np.ndarray,
        labels: Optional[np.ndarray],
        masks: Optional[dict],
        world_size: int,
        *,
        partition_method: str = "rcm",
        edge_owner: str = "dst",
        add_symmetric_norm: bool = False,
        pad_multiple: int = 8,
        seed: int = 0,
        partition_kwargs: Optional[dict] = None,
        plan_cache_dir: str = "",
    ) -> "DistributedGraph":
        num_nodes = features.shape[0]
        edge_index = np.asarray(edge_index)
        new_edges, ren = pt.partition_graph(
            edge_index, num_nodes, world_size, method=partition_method,
            seed=seed, **(partition_kwargs or {}),
        )
        # the on-disk plan cache (train/checkpoint.cached_edge_plan) resolves
        # a falsy dir to a plain build, so this is the one call site either way
        from dgraph_tpu.train.checkpoint import cached_edge_plan

        plan, layout = cached_edge_plan(
            plan_cache_dir,
            new_edges,
            ren.partition,
            world_size=world_size,
            edge_owner=edge_owner,
            pad_multiple=pad_multiple,
        )
        n_pad = plan.n_src_pad
        feats = shard_vertex_data(
            np.asarray(features)[ren.inv], ren.counts, n_pad
        ).astype(np.float32)
        if labels is not None:
            lab_arr = np.asarray(labels)
            # integer class ids -> int32; float arrays (e.g. ogbn-proteins'
            # [V, 112] multi-label targets) keep float32 for BCE losses
            lab_dtype = (
                np.float32 if np.issubdtype(lab_arr.dtype, np.floating) else np.int32
            )
            lab = shard_vertex_data(
                lab_arr[ren.inv].astype(lab_dtype), ren.counts, n_pad
            )
        else:
            lab = None
        m = {}
        if masks:
            for k, v in masks.items():
                m[k] = shard_vertex_data(
                    np.asarray(v).astype(np.float32)[ren.inv], ren.counts, n_pad
                )
        vmask = shard_vertex_data(
            np.ones(num_nodes, np.float32), ren.counts, n_pad
        )
        ew = None
        if add_symmetric_norm:
            ew = shard_edge_data(
                symmetric_norm_weights(new_edges, num_nodes), layout, plan.e_pad
            )
        return cls(
            num_nodes=num_nodes,
            num_edges=edge_index.shape[1],
            world_size=world_size,
            edge_index=new_edges,
            ren=ren,
            plan=plan,
            layout=layout,
            features=feats,
            labels=lab,
            masks=m,
            vertex_mask=vmask,
            edge_weight=ew,
        )

    def batch(self, split: str) -> dict:
        """Pytree for the train/eval step: leaves have leading [W] axis."""
        out = {
            "x": self.features,
            "mask": self.masks[split] if split in self.masks else self.vertex_mask,
        }
        if self.labels is not None:
            out["y"] = self.labels
        if self.edge_weight is not None:
            out["edge_weight"] = self.edge_weight
        return out


def symmetric_norm_weights(edge_index: np.ndarray, num_nodes: int) -> np.ndarray:
    """Kipf-Welling GCN normalization 1/sqrt(d_src * d_dst) per edge."""
    src, dst = edge_index
    deg = np.zeros(num_nodes, np.float64)
    np.add.at(deg, src, 1.0)
    np.add.at(deg, dst, 1.0)
    deg = np.maximum(deg, 1.0)
    return (1.0 / np.sqrt(deg[src] * deg[dst])).astype(np.float32)
