"""On-disk memmap datasets for graphs whose features exceed host RAM.

Reference parity: the MAG240M pipeline (``experiments/OGB-LSC/lsc_datasets/
MAG240M_dataset.py:116-320``) generates a node-feature memmap from ogb.lsc
once, then every rank opens it read-only and slices out only its own rows.
The TPU-native version keeps the same shape:

- :func:`open_memmap_dataset` / :func:`create_memmap_dataset`: a directory of
  ``.npy`` files opened with ``np.load(mmap_mode="r")`` — nothing resident
  until rows are touched.
- :func:`shard_rows`: materialize ONLY the requested ranks' row blocks
  (fancy-indexing a memmap reads just those pages). Combined with
  ``comm.multihost.process_local_shards`` this is the per-host loading story
  for multi-controller pods (reference per-rank slicing,
  ``data/ogbn_datasets.py:135-148``).
- :func:`generate_chunked`: stream-write a dataset in row chunks so the
  111M x 128 papers100M feature matrix is never in RAM during generation
  (reference memmap-generation loop, ``MAG240M_dataset.py:150-220``).
- :func:`renumber_edges_chunked`: stream a renumbered ``[2, E]`` edge-list
  copy to disk — the memmap'd input the streaming sharded plan build
  (``plan.build_plan_shards``, cache format v8) assembles per-rank shards
  from without ever holding the edge list resident.

Everything here is host-side numpy except :func:`shard_rows_to_device`
(lazy jax import), which streams shard blocks straight onto a device mesh so
the full ``[W, n_pad, ...]`` stack never exists host-side.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterable, Optional

import numpy as np

_META = "dgraph_meta.json"


def create_memmap_dataset(
    path: str, schema: dict[str, tuple[tuple[int, ...], str]]
) -> dict[str, np.memmap]:
    """Create a directory of writable ``.npy`` memmaps.

    Args:
      schema: name -> (shape, dtype-string), e.g.
        ``{"features": ((V, 128), "float32"), "labels": ((V,), "int32")}``.
    Returns name -> writable memmap (flush with ``.flush()`` or just let the
    process exit; the data lives in the page cache/disk).
    """
    os.makedirs(path, exist_ok=True)
    arrays = {}
    for name, (shape, dtype) in schema.items():
        arrays[name] = np.lib.format.open_memmap(
            os.path.join(path, name + ".npy"), mode="w+", dtype=np.dtype(dtype), shape=tuple(shape)
        )
    with open(os.path.join(path, _META), "w") as f:
        json.dump(
            {n: {"shape": list(s), "dtype": d} for n, (s, d) in schema.items()}, f
        )
    return arrays


def open_memmap_dataset(path: str, names: Optional[Iterable[str]] = None) -> dict:
    """Open a directory of ``.npy`` files read-only as memmaps.

    When the :data:`_META` sidecar written by :func:`create_memmap_dataset`
    is present it is the source of truth: it names the arrays (when
    ``names`` is None) and each opened array is validated against its
    recorded shape/dtype — catching a half-written or overwritten dataset
    at open time instead of as silent garbage mid-training.
    """
    meta = {}
    meta_path = os.path.join(path, _META)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    if names is None:
        names = sorted(meta) if meta else [
            f[: -len(".npy")] for f in sorted(os.listdir(path)) if f.endswith(".npy")
        ]
    arrays = {
        n: np.load(os.path.join(path, n + ".npy"), mmap_mode="r") for n in names
    }
    for n, arr in arrays.items():
        if n in meta:
            want = (tuple(meta[n]["shape"]), np.dtype(meta[n]["dtype"]))
            got = (arr.shape, arr.dtype)
            if want != got:
                raise ValueError(
                    f"memmap dataset {path!r}: array {n!r} is {got}, "
                    f"but {_META} records {want}"
                )
    return arrays


def generate_chunked(
    out: np.memmap,
    make_chunk: Callable[[int, int], np.ndarray],
    chunk_rows: int = 1 << 20,
) -> np.memmap:
    """Fill ``out`` row-block by row-block: ``out[lo:hi] = make_chunk(lo, hi)``.

    Keeps peak RAM at one chunk regardless of total size — the reference's
    memmap feature-generation loop shape (``MAG240M_dataset.py:150-220``).
    """
    n = out.shape[0]
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        out[lo:hi] = make_chunk(lo, hi)
    out.flush()
    return out


def renumber_edges_chunked(
    edges,
    perm: np.ndarray,
    out_path: str,
    chunk_cols: int = 1 << 26,
) -> np.ndarray:
    """Apply a vertex renumbering to a ``[2, E]`` (memmap) edge list,
    streaming the result TO DISK column-block by column-block.

    Peak RAM is one ``[2, chunk_cols]`` block regardless of E — the
    r5 papers100M plan stage's in-RAM renumbered copy (25.8 GB anon on
    top of the plan transients) was part of what OOM-killed it at
    ~130 GB.  Returns the result re-opened read-only with
    ``mmap_mode="r"``: the plan core (``plan.build_plan_shards``) reads
    src/dst in sequential passes, so file-backed pages reclaim under
    memory pressure instead of counting against the OOM killer.
    """
    E = edges.shape[1]
    out = np.lib.format.open_memmap(
        out_path, mode="w+", dtype=np.int64, shape=(2, E)
    )
    for lo in range(0, E, chunk_cols):
        blk = np.asarray(edges[:, lo : lo + chunk_cols])
        out[:, lo : lo + blk.shape[1]] = perm[blk]
    out.flush()
    del out
    return np.load(out_path, mmap_mode="r")


def shard_rows(
    data,
    inv: np.ndarray,
    offsets: np.ndarray,
    n_pad: int,
    shard_ids: Iterable[int],
    dtype=None,
) -> np.ndarray:
    """Materialize selected ranks' padded row blocks from a (memmap) array.

    Args:
      data: [V, ...] array or memmap in ORIGINAL vertex numbering.
      inv: renumbering's inverse permutation (new id -> original id,
        ``partition.Renumbering.inv``) — rank r owns new ids
        ``offsets[r]:offsets[r+1]``.
      offsets: [W+1] rank block offsets in the new numbering.
      n_pad: padded per-shard row count.
      shard_ids: which ranks to materialize (e.g.
        ``comm.multihost.process_local_shards(W)``); only these rows are
        ever read from disk.
    Returns [len(shard_ids), n_pad, ...] with zero padding.
    """
    shard_ids = list(shard_ids)
    tail = data.shape[1:]
    dtype = np.dtype(dtype) if dtype is not None else data.dtype
    out = np.zeros((len(shard_ids), n_pad) + tuple(tail), dtype)
    for i, r in enumerate(shard_ids):
        rows = inv[offsets[r] : offsets[r + 1]]
        # memmap fancy-indexing reads only the touched pages; sort the row
        # ids for sequential disk access then restore plan order
        order = np.argsort(rows, kind="stable")
        got = np.asarray(data[rows[order]], dtype)
        undo = np.empty_like(order)
        undo[order] = np.arange(len(order))
        out[i, : len(rows)] = got[undo]
    return out


def shard_rows_to_device(
    data,
    inv: np.ndarray,
    offsets: np.ndarray,
    n_pad: int,
    mesh,
    *,
    axis: Optional[str] = None,
    dtype=None,
):
    """Stream per-rank padded row blocks directly onto a device mesh.

    Equivalent to ``jnp.asarray(shard_rows(data, inv, offsets, n_pad,
    range(W)))`` sharded ``P(axis)``, but host-residency is ONE device's
    block at a time instead of the whole ``[W, n_pad, ...]`` stack — at
    real papers100M scale that stack is ~57 GB (VERDICT r4 weak #6), while
    a single shard block is ~57/W GB. Only addressable devices' blocks are
    materialized, so multi-controller hosts each read 1/num_hosts of the
    rows (subsuming the explicit ``process_local_shards`` recipe).

    Returns a global :class:`jax.Array` of shape ``[W, n_pad, ...]``
    sharded over the mesh's ``axis`` (default the graph axis).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from dgraph_tpu.comm.mesh import GRAPH_AXIS

    axis = axis or GRAPH_AXIS
    W = len(offsets) - 1
    shape = (W, n_pad) + tuple(data.shape[1:])
    sharding = NamedSharding(mesh, PartitionSpec(axis))
    # group devices by their global-array slice: replicas of the same rows
    # (replica/trailing mesh axes) share ONE disk read + host block
    groups: dict = {}
    for dev, idx in sharding.addressable_devices_indices_map(shape).items():
        lead = idx[0]
        r0 = lead.start if lead.start is not None else 0
        r1 = lead.stop if lead.stop is not None else W
        groups.setdefault((r0, r1), []).append(dev)
    arrs: list = []
    in_flight: list = []
    for (r0, r1), devs in groups.items():
        # disk read of this block overlaps the previous block's transfer
        block = shard_rows(data, inv, offsets, n_pad, range(r0, r1), dtype)
        # device_put is async and pins its numpy source until the copy
        # lands; without this barrier several blocks stay resident and the
        # documented bound quietly becomes the full stack
        jax.block_until_ready(in_flight)
        in_flight = [jax.device_put(block, d) for d in devs]
        arrs.extend(in_flight)
        del block  # ≤2 blocks resident: this one + the one transferring
    return jax.make_array_from_single_device_arrays(shape, sharding, arrs)


def synthetic_papers_like(
    path: str,
    num_nodes: int,
    feat_dim: int = 128,
    num_classes: int = 172,
    avg_degree: float = 14.5,
    train_frac: float = 0.01,
    seed: int = 0,
    chunk_rows: int = 1 << 20,
) -> str:
    """Write a papers100M-shaped dataset to disk without holding it in RAM.

    Edge list from the same power-law generator as
    ``data.synthetic.power_law_graph``; features streamed chunk-wise.
    Returns ``path`` (loadable by ``experiments/papers100m_gcn.py
    --data_npz <path>`` and :func:`open_memmap_dataset`).
    """
    from dgraph_tpu.data.synthetic import power_law_graph

    edges = power_law_graph(num_nodes, avg_degree, seed=seed)
    arrays = create_memmap_dataset(
        path,
        {
            "edge_index": (tuple(edges.shape), "int64"),
            "features": ((num_nodes, feat_dim), "float32"),
            "labels": ((num_nodes,), "int32"),
            "train_mask": ((num_nodes,), "bool"),
        },
    )
    arrays["edge_index"][:] = edges

    def feat_chunk(lo, hi):
        r = np.random.default_rng(seed + 1 + lo)
        return r.normal(size=(hi - lo, feat_dim)).astype(np.float32)

    generate_chunked(arrays["features"], feat_chunk, chunk_rows)
    r = np.random.default_rng(seed + 2)
    arrays["labels"][:] = r.integers(0, num_classes, num_nodes).astype(np.int32)
    arrays["train_mask"][:] = r.random(num_nodes) < train_frac
    for a in arrays.values():
        a.flush()
    return path
