"""Direct reader/writer of OGB's node-prediction on-disk download layout.

Reference parity: the reference ingests OGB through the ``ogb`` package
(``DGraph/data/ogbn_datasets.py:67-95`` — ``NodePropPredDataset`` download +
indexing). This environment can never ``pip install ogb``, so the day egress
appears the raw download zip is all we get — this module parses that layout
with numpy+pandas only, producing the same ``(graph, labels, split_idx)``
triple the package returns. ``dgraph_tpu.data.ogbn.load_ogb_arrays`` prefers
the package when importable and falls back to this reader when a raw
directory exists.

Layout parsed (ogb >= 1.3 ``ogb/io/read_graph_raw.py`` conventions):

``{root}/{name with - -> _}/``
  ``raw/edge.csv.gz``            "src,dst" int lines, no header
  ``raw/num-node-list.csv.gz``   one int (single-graph datasets)
  ``raw/num-edge-list.csv.gz``   one int
  ``raw/node-feat.csv.gz``       comma floats, one row per node (if any)
  ``raw/edge-feat.csv.gz``       comma floats, one row per edge (if any)
  ``raw/node_species.csv.gz``    extra node file (ogbn-proteins)
  ``raw/node-label.csv.gz``      one label row per node
  ``split/{split_type}/{train,valid,test}.csv.gz``  node index per line

Binary datasets (ogbn-papers100M) instead ship
``raw/data.npz`` (keys ``edge_index``, ``node_feat``, ``num_nodes_list``,
``num_edges_list``) and ``raw/node-label.npz`` (key ``node_label``); splits
stay csv.gz. A ``split/{split_type}/split_dict.pt`` short-circuit (newer ogb
releases) is honored when present.

Per-dataset metadata that ogb keeps in its package-internal ``master.csv``
(split type, add_inverse_edge, which side files exist) is inlined in
``NODE_DATASET_META`` — the raw download does not carry it.

One deliberate divergence: ``add_inverse_edge`` APPENDS the reversed edges
after the originals, where ogb's ``read_csv_graph_raw`` interleaves them
per edge. Same edge set, different element order — see the note at the
doubling site in :func:`read_node_pred_raw`.

The writer (:func:`write_node_pred_raw`) emits the same bytes ogb's
pipeline does (pandas ``to_csv(header=False, index=False)`` + gzip), so
fixture tests exercise the identical parse the real download will get.
"""

from __future__ import annotations

import gzip
import os
from typing import Optional

import numpy as np

# split type + graph-shaping flags from ogb's master.csv (package-internal;
# restated here because the download itself doesn't include them)
NODE_DATASET_META = {
    "ogbn-arxiv": dict(
        split="time", add_inverse_edge=False, binary=False,
        has_node_feat=True, has_edge_feat=False, extra_node_files=(),
    ),
    "ogbn-products": dict(
        split="sales_ranking", add_inverse_edge=True, binary=False,
        has_node_feat=True, has_edge_feat=False, extra_node_files=(),
    ),
    "ogbn-proteins": dict(
        split="species", add_inverse_edge=True, binary=False,
        has_node_feat=False, has_edge_feat=True,
        extra_node_files=("node_species",),
    ),
    "ogbn-papers100M": dict(
        split="time", add_inverse_edge=False, binary=True,
        has_node_feat=True, has_edge_feat=False, extra_node_files=(),
    ),
}


def dataset_dir(root: str, name: str) -> str:
    """ogb's directory naming: dashes become underscores."""
    return os.path.join(root, "_".join(name.split("-")))


def has_raw_download(root: str, name: str) -> bool:
    """True when the official download layout is present under ``root``."""
    if name not in NODE_DATASET_META:
        return False
    raw = os.path.join(dataset_dir(root, name), "raw")
    probe = "data.npz" if NODE_DATASET_META[name]["binary"] else "edge.csv.gz"
    return os.path.exists(os.path.join(raw, probe))


def _read_csv_gz(path: str, dtype) -> np.ndarray:
    import pandas as pd

    return pd.read_csv(
        path, compression="gzip", header=None
    ).values.astype(dtype)


def _read_split_component(split_dir: str, key: str) -> np.ndarray:
    """One split file: csv.gz (canonical) or npz (some mirrors)."""
    csv = os.path.join(split_dir, key + ".csv.gz")
    if os.path.exists(csv):
        return _read_csv_gz(csv, np.int64).reshape(-1)
    npz = os.path.join(split_dir, key + ".npz")
    if os.path.exists(npz):
        return np.asarray(np.load(npz)["data"], dtype=np.int64).reshape(-1)
    raise FileNotFoundError(f"no {key}.csv.gz / {key}.npz under {split_dir}")


def read_split(root: str, name: str) -> dict:
    """``split_idx`` dict with train/valid/test int64 index arrays."""
    split_dir = os.path.join(
        dataset_dir(root, name), "split", NODE_DATASET_META[name]["split"]
    )
    pt = os.path.join(split_dir, "split_dict.pt")
    if os.path.exists(pt):
        import torch

        d = torch.load(pt, map_location="cpu", weights_only=False)
        return {
            k: np.asarray(
                v.numpy() if hasattr(v, "numpy") else v, dtype=np.int64
            )
            for k, v in d.items()
        }
    return {
        k: _read_split_component(split_dir, k)
        for k in ("train", "valid", "test")
    }


def read_node_pred_raw(root: str, name: str) -> tuple[dict, np.ndarray, dict]:
    """Parse a raw download into ``(graph, labels, split_idx)`` — the same
    triple ``NodePropPredDataset`` yields (``ds[0]`` + ``get_idx_split()``),
    including ``add_inverse_edge`` doubling where master.csv mandates it."""
    if name not in NODE_DATASET_META:
        raise ValueError(
            f"unknown dataset {name!r}; known: {tuple(NODE_DATASET_META)}"
        )
    meta = NODE_DATASET_META[name]
    raw = os.path.join(dataset_dir(root, name), "raw")

    if meta["binary"]:
        data = np.load(os.path.join(raw, "data.npz"))
        num_nodes_list = np.asarray(data["num_nodes_list"]).reshape(-1)
        num_edges_list = np.asarray(data["num_edges_list"]).reshape(-1)
        if len(num_nodes_list) != 1:
            raise ValueError(
                f"{name}: expected a single graph, got {len(num_nodes_list)}"
            )
        graph = {
            "num_nodes": int(num_nodes_list[0]),
            "edge_index": np.asarray(data["edge_index"], dtype=np.int64),
        }
        if graph["edge_index"].shape != (2, int(num_edges_list[0])):
            raise ValueError(
                f"{name}: data.npz edge_index shape "
                f"{graph['edge_index'].shape} != (2, {int(num_edges_list[0])})"
                " from num_edges_list (truncated or drifted download?)"
            )
        if "node_feat" in data:
            graph["node_feat"] = np.asarray(data["node_feat"])
            if graph["node_feat"].shape[0] != graph["num_nodes"]:
                raise ValueError(
                    f"{name}: data.npz node_feat rows "
                    f"{graph['node_feat'].shape[0]} != num_nodes_list "
                    f"{graph['num_nodes']}"
                )
        labels = np.asarray(
            np.load(os.path.join(raw, "node-label.npz"))["node_label"]
        )
    else:
        num_nodes = int(
            _read_csv_gz(os.path.join(raw, "num-node-list.csv.gz"), np.int64)
            .reshape(-1)[0]
        )
        num_edges = int(
            _read_csv_gz(os.path.join(raw, "num-edge-list.csv.gz"), np.int64)
            .reshape(-1)[0]
        )
        edge_index = _read_csv_gz(os.path.join(raw, "edge.csv.gz"), np.int64).T
        if edge_index.shape != (2, num_edges):
            raise ValueError(
                f"{name}: edge.csv.gz rows {edge_index.shape[1]} != "
                f"num-edge-list {num_edges}"
            )
        graph = {"num_nodes": num_nodes, "edge_index": edge_index}
        if meta["has_node_feat"]:
            graph["node_feat"] = _read_csv_gz(
                os.path.join(raw, "node-feat.csv.gz"), np.float32
            )
            if graph["node_feat"].shape[0] != num_nodes:
                raise ValueError(
                    f"{name}: node-feat rows {graph['node_feat'].shape[0]} "
                    f"!= num-node-list {num_nodes}"
                )
        if meta["has_edge_feat"]:
            graph["edge_feat"] = _read_csv_gz(
                os.path.join(raw, "edge-feat.csv.gz"), np.float32
            )
        for extra in meta["extra_node_files"]:
            graph[extra] = _read_csv_gz(
                os.path.join(raw, extra + ".csv.gz"), np.int64
            )
        labels = _read_csv_gz(
            os.path.join(raw, "node-label.csv.gz"), np.float32
        )

    if meta["add_inverse_edge"]:
        # Reversed edges are APPENDED as one block — the result is
        # ``[e_0..e_{E-1}, rev(e_0)..rev(e_{E-1})]``. ogb's own
        # ``read_csv_graph_raw`` INTERLEAVES instead (``np.repeat(...,2)``
        # + odd-column swap -> ``[e_0, rev(e_0), e_1, rev(e_1), ...]``).
        # The edge SET (and edge_feat pairing) is identical; element
        # ORDER is not — never rely on column-order parity between this
        # reader and a package-produced npz artifact. Pinned by
        # tests/test_ogb_raw.py::test_add_inverse_edge_appends_not_
        # interleaves. Everything downstream (plan build) treats the edge
        # list as a set, so the cheaper append layout wins.
        graph["edge_index"] = np.concatenate(
            [graph["edge_index"], graph["edge_index"][::-1]], axis=1
        )
        if "edge_feat" in graph:
            graph["edge_feat"] = np.concatenate(
                [graph["edge_feat"], graph["edge_feat"]], axis=0
            )

    return graph, labels, read_split(root, name)


def _write_csv_gz(path: str, arr: np.ndarray) -> None:
    """Byte-parity with ogb's pipeline: pandas ``to_csv(header=False,
    index=False)`` into gzip."""
    import pandas as pd

    arr = np.asarray(arr)
    if arr.ndim == 1:
        arr = arr[:, None]
    pd.DataFrame(arr).to_csv(
        path, header=False, index=False, compression="gzip"
    )


def write_node_pred_raw(
    root: str,
    name: str,
    *,
    edge_index: np.ndarray,  # [2, E] PRE-inverse (as the download ships it)
    labels: np.ndarray,
    split_idx: dict,
    node_feat: Optional[np.ndarray] = None,
    edge_feat: Optional[np.ndarray] = None,
    node_species: Optional[np.ndarray] = None,
    num_nodes: Optional[int] = None,
) -> str:
    """Emit the official download layout (fixture generator; also the
    recipe an egress-day download must match — a drift fails the tests)."""
    meta = NODE_DATASET_META[name]
    base = dataset_dir(root, name)
    raw = os.path.join(base, "raw")
    split_dir = os.path.join(base, "split", meta["split"])
    os.makedirs(raw, exist_ok=True)
    os.makedirs(split_dir, exist_ok=True)
    num_nodes = int(
        num_nodes
        if num_nodes is not None
        else (len(node_feat) if node_feat is not None else len(labels))
    )

    if meta["binary"]:
        arrays = {
            "edge_index": np.asarray(edge_index, np.int64),
            "num_nodes_list": np.asarray([num_nodes], np.int64),
            "num_edges_list": np.asarray([edge_index.shape[1]], np.int64),
        }
        if node_feat is not None:
            arrays["node_feat"] = np.asarray(node_feat)
        np.savez(os.path.join(raw, "data.npz"), **arrays)
        np.savez(
            os.path.join(raw, "node-label.npz"),
            node_label=np.asarray(labels),
        )
    else:
        _write_csv_gz(
            os.path.join(raw, "edge.csv.gz"), np.asarray(edge_index).T
        )
        _write_csv_gz(
            os.path.join(raw, "num-node-list.csv.gz"),
            np.asarray([num_nodes]),
        )
        _write_csv_gz(
            os.path.join(raw, "num-edge-list.csv.gz"),
            np.asarray([edge_index.shape[1]]),
        )
        if node_feat is not None:
            _write_csv_gz(os.path.join(raw, "node-feat.csv.gz"), node_feat)
        if edge_feat is not None:
            _write_csv_gz(os.path.join(raw, "edge-feat.csv.gz"), edge_feat)
        if node_species is not None:
            _write_csv_gz(
                os.path.join(raw, "node_species.csv.gz"), node_species
            )
        _write_csv_gz(os.path.join(raw, "node-label.csv.gz"), labels)

    for key in ("train", "valid", "test"):
        _write_csv_gz(
            os.path.join(split_dir, key + ".csv.gz"),
            np.asarray(split_idx[key], np.int64),
        )
    # the download ships a release marker at the dataset root
    with open(os.path.join(base, "RELEASE_v1.txt"), "w") as f:
        f.write(f"{name} fixture in the official raw layout\n")
    return base
