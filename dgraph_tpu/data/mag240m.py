"""MAG240M memmap dataset binding.

Reference parity: ``experiments/OGB-LSC/lsc_datasets/MAG240M_dataset.py``
(``DGraph_MAG240M_Dataset``): ogb.lsc arrays + derived author/institution
features generated ONCE into float16 ``.npy`` memmaps
(``generate_feature_data`` + ``_generate_features_from_paper_features``,
``:65-107,262-320``) — author features are the mean of the author's papers'
features, institution features the mean of its authors', computed in
column chunks so the 768-dim x 121M-paper matrix never materializes.

This environment has neither the ogb package nor the 1.4TB download, so the
module is split the same way the reference splits real vs synthetic
(``synthetic_dataset.py``):

- :func:`prepare_mag240m_memmap` — the real pipeline, import-gated on
  ``ogb.lsc`` (runs unchanged wherever ogb + data exist);
- :func:`synthetic_mag240m_memmap` — writes the IDENTICAL on-disk layout at
  a chosen scale from the synthetic generator;
- :func:`load_mag240m_memmap` — opens either layout lazily (np.memmap) and
  returns the dict shapes :class:`DistributedHeteroGraph.from_global`
  consumes. Consumers cannot tell which generator produced the directory.

Derived-feature aggregation (:func:`aggregate_mean_features`) is pure
numpy + memmap: row-chunked over destinations, column-chunked over features
(the reference's ``dim_chunk_size=64`` pattern), no torch_sparse.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

_META = "mag240m_meta.json"


def aggregate_mean_features(
    out: np.ndarray,  # [N_dst, F] writable (memmap ok)
    src_feat: np.ndarray,  # [N_src, F] (memmap ok)
    edge_index: np.ndarray,  # [2, E] (dst_entity, src_entity) pairs
    row_chunk: int = 1 << 20,
    col_chunk: int = 64,
    edge_chunk: int = 1 << 22,
) -> None:
    """out[d] = mean over edges (d, s) of src_feat[s]; rows with no edges
    stay zero. The reference computes exactly this with torch_sparse
    ``adj.matmul(reduce="mean")`` in 64-wide column slices
    (``MAG240M_dataset.py:65-107``).

    Memory is bounded by BOTH chunk knobs: ``row_chunk`` caps the fp32
    accumulator, ``edge_chunk`` caps the gathered source rows (one
    destination chunk can own arbitrarily many edges — all 44.6M
    affiliation edges land on MAG240M's 26k institutions). The segment
    reduction uses ``np.add.reduceat`` over the sorted run starts, not the
    elementwise ``np.ufunc.at``."""
    dst = np.asarray(edge_index[0])
    src = np.asarray(edge_index[1])
    order = np.argsort(dst, kind="stable")
    dst, src = dst[order], src[order]
    N, F = out.shape
    counts = np.bincount(dst, minlength=N).astype(np.float32)
    starts = np.searchsorted(dst, np.arange(0, N, row_chunk))
    ends = np.searchsorted(dst, np.minimum(np.arange(0, N, row_chunk) + row_chunk, N))
    for ci, lo in enumerate(range(0, N, row_chunk)):
        hi = min(lo + row_chunk, N)
        e0, e1 = int(starts[ci]), int(ends[ci])
        denom = np.maximum(counts[lo:hi], 1.0)[:, None]
        acc = np.zeros((hi - lo, F), np.float32)
        for s0 in range(e0, e1, edge_chunk):
            s1 = min(s0 + edge_chunk, e1)
            seg = dst[s0:s1] - lo
            # gather each source row from the (possibly on-disk) matrix
            # ONCE per edge chunk, in its storage dtype
            gathered = np.asarray(src_feat[src[s0:s1]])
            run_starts = np.nonzero(
                np.concatenate([[True], seg[1:] != seg[:-1]])
            )[0]
            uniq = seg[run_starts]
            for j in range(0, F, col_chunk):
                k = min(j + col_chunk, F)
                part = np.add.reduceat(
                    gathered[:, j:k].astype(np.float32), run_starts, axis=0
                )
                acc[uniq, j:k] += part
        out[lo:hi] = (acc / denom).astype(out.dtype)


def _write(out_dir: str, name: str, arr: np.ndarray) -> None:
    np.save(os.path.join(out_dir, name + ".npy"), arr)


_RAW_SUBDIR = "mag240m_kddcup2021"
# relation-name inference table from ogb.lsc.MAG240MDataset.edge_index
_RAW_RELS = {
    ("author", "paper"): "writes",
    ("author", "institution"): "affiliated_with",
    ("paper", "paper"): "cites",
}


class RawMAG240M:
    """Pure numpy+pickle accessor for the official MAG240M download layout
    (``{root}/mag240m_kddcup2021/``: ``meta.pt``, ``split_dict.pt``,
    ``processed/paper/node_feat.npy`` float16 memmap,
    ``processed/{src}___{rel}___{dst}/edge_index.npy``). Exposes the exact
    attribute surface :func:`prepare_mag240m_memmap` uses from
    ``ogb.lsc.MAG240MDataset``, so the pipeline runs identically from the
    raw download with no ogb package (this environment can never pip
    install — VERDICT r4 #7)."""

    def __init__(self, root: str):
        import torch

        self.dir = os.path.join(root, _RAW_SUBDIR)
        if not os.path.exists(os.path.join(self.dir, "meta.pt")):
            raise FileNotFoundError(
                f"no MAG240M download at {self.dir} (missing meta.pt)"
            )
        # ogb writes these with torch.save; plain dicts of ints / numpy
        # arrays, so weights_only=False is just pickle
        self.__meta__ = torch.load(
            os.path.join(self.dir, "meta.pt"),
            map_location="cpu", weights_only=False,
        )
        self.__split__ = torch.load(
            os.path.join(self.dir, "split_dict.pt"),
            map_location="cpu", weights_only=False,
        )

    num_paper_features = 768  # hardcoded in ogb.lsc, not in meta.pt

    @property
    def num_papers(self):
        return int(self.__meta__["paper"])

    @property
    def num_authors(self):
        return int(self.__meta__["author"])

    @property
    def num_institutions(self):
        return int(self.__meta__["institution"])

    @property
    def num_classes(self):
        return int(self.__meta__["num_classes"])

    @property
    def paper_feat(self):
        return np.load(
            os.path.join(self.dir, "processed", "paper", "node_feat.npy"),
            mmap_mode="r",
        )

    @property
    def paper_label(self):
        return np.load(
            os.path.join(self.dir, "processed", "paper", "node_label.npy"),
            mmap_mode="r",
        )

    def edge_index(self, id1: str, id2: str, id3: Optional[str] = None):
        src, rel, dst = (
            (id1, id2, id3) if id3 is not None
            else (id1, _RAW_RELS[(id1, id2)], id2)
        )
        return np.load(
            os.path.join(
                self.dir, "processed", f"{src}___{rel}___{dst}",
                "edge_index.npy",
            ),
            mmap_mode="r",
        )

    def get_idx_split(self, key: str):
        return np.asarray(self.__split__[key])


def write_mag240m_raw_fixture(
    root: str,
    *,
    paper_feat: np.ndarray,  # [P, F] (float16 in the real download)
    paper_label: np.ndarray,  # [P] float with NaN on unlabeled
    cites: np.ndarray,  # [2, E] (paper, paper)
    writes: np.ndarray,  # [2, E] (author, paper)
    affiliated: np.ndarray,  # [2, E] (author, institution)
    num_authors: int,
    num_institutions: int,
    num_classes: int = 153,
    split_idx: Optional[dict] = None,  # train/valid/test-dev paper indices
) -> str:
    """Emit the official download layout (fixture generator for tests; also
    documents the byte format an egress-day download must match)."""
    import torch

    base = os.path.join(root, _RAW_SUBDIR)
    paper_dir = os.path.join(base, "processed", "paper")
    os.makedirs(paper_dir, exist_ok=True)
    P = len(paper_feat)
    np.save(
        os.path.join(paper_dir, "node_feat.npy"),
        np.asarray(paper_feat, np.float16),
    )
    np.save(
        os.path.join(paper_dir, "node_label.npy"),
        np.asarray(paper_label, np.float32),
    )
    np.save(
        os.path.join(paper_dir, "node_year.npy"),
        np.full(P, 2015, np.int32),
    )
    for (src, rel, dst), arr in (
        (("paper", "cites", "paper"), cites),
        (("author", "writes", "paper"), writes),
        (("author", "affiliated_with", "institution"), affiliated),
    ):
        d = os.path.join(base, "processed", f"{src}___{rel}___{dst}")
        os.makedirs(d, exist_ok=True)
        np.save(os.path.join(d, "edge_index.npy"), np.asarray(arr, np.int64))
    if split_idx is None:
        labeled = np.nonzero(~np.isnan(np.asarray(paper_label)))[0]
        thirds = np.array_split(labeled, 3)
        split_idx = {
            "train": thirds[0], "valid": thirds[1], "test-dev": thirds[2],
        }
    torch.save(
        {
            "paper": P, "author": int(num_authors),
            "institution": int(num_institutions),
            "num_classes": int(num_classes),
        },
        os.path.join(base, "meta.pt"),
    )
    torch.save(
        {k: np.asarray(v, np.int64) for k, v in split_idx.items()},
        os.path.join(base, "split_dict.pt"),
    )
    return base


def prepare_mag240m_memmap(
    data_dir: str, out_dir: str, num_features: Optional[int] = None
) -> str:
    """Real-data pipeline: export edges/labels/splits and generate
    author/institution features into the shared memmap layout. Uses
    ``ogb.lsc.MAG240MDataset`` when importable; otherwise reads the
    official download layout directly via :class:`RawMAG240M` (same
    accessor surface), so egress-day ingestion needs no pip install."""
    try:
        from ogb.lsc import MAG240MDataset  # type: ignore
    except ImportError:
        MAG240MDataset = None  # noqa: N806

    ds = (
        MAG240MDataset(root=data_dir)
        if MAG240MDataset is not None
        else RawMAG240M(data_dir)
    )
    os.makedirs(out_dir, exist_ok=True)
    F = num_features or ds.num_paper_features
    paper_feat = ds.paper_feat  # [P, 768] float16 memmap
    P, A, I = ds.num_papers, ds.num_authors, ds.num_institutions

    pf = np.lib.format.open_memmap(
        os.path.join(out_dir, "paper_feat.npy"), mode="w+", dtype=np.float16,
        shape=(P, F),
    )
    for lo in range(0, P, 1 << 20):
        hi = min(lo + (1 << 20), P)
        pf[lo:hi] = paper_feat[lo:hi, :F]
    ap = ds.edge_index("author", "writes", "paper")  # [2, E] author, paper
    af = np.lib.format.open_memmap(
        os.path.join(out_dir, "author_feat.npy"), mode="w+", dtype=np.float16,
        shape=(A, F),
    )
    aggregate_mean_features(af, pf, ap)
    ai = ds.edge_index("author", "institution")
    inf = np.lib.format.open_memmap(
        os.path.join(out_dir, "institution_feat.npy"), mode="w+",
        dtype=np.float16, shape=(I, F),
    )
    aggregate_mean_features(inf, af, ai[::-1])  # institution <- its authors

    _write(out_dir, "paper_cites_paper", ds.edge_index("paper", "cites", "paper"))
    _write(out_dir, "author_writes_paper", ap)
    _write(out_dir, "author_affiliated_institution", ai)
    # NaN = unlabeled (non-arxiv papers, hidden test-dev labels): keep the
    # ogb convention of -1 so accidental use fails loudly instead of
    # silently scoring against a fake class 0
    raw_label = ds.paper_label
    _write(
        out_dir, "paper_label",
        np.where(np.isnan(raw_label), -1, raw_label).astype(np.int32),
    )
    for split, key in (("train", "train"), ("valid", "valid"), ("test", "test-dev")):
        _write(out_dir, f"{split}_idx", ds.get_idx_split(key))
    with open(os.path.join(out_dir, _META), "w") as f:
        json.dump(
            {"num_papers": P, "num_authors": A, "num_institutions": I,
             "num_features": F, "num_classes": int(ds.num_classes),
             "source": (
                 "ogb.lsc" if MAG240MDataset is not None else "raw-download"
             )},
            f,
        )
    return out_dir


def synthetic_mag240m_memmap(
    out_dir: str, scale: float = 0.01, num_features: int = 64, seed: int = 0
) -> str:
    """Write the real pipeline's EXACT on-disk layout from the synthetic
    MAG generator (MAG240M proportions: 121.7M papers / 122.4M authors /
    26k institutions, scaled). Author/institution features go through the
    same :func:`aggregate_mean_features` memmap path as the real data."""
    from dgraph_tpu.data.hetero import synthetic_mag

    P = max(int(121_751_666 * scale), 1_000)
    A = max(int(122_383_112 * scale), 600)
    I = max(int(25_721 * scale), 16)
    C = 153  # MAG240M classes
    nf, rels, labels, masks = synthetic_mag(P, A, I, num_features, C, seed=seed)
    os.makedirs(out_dir, exist_ok=True)

    pf = np.lib.format.open_memmap(
        os.path.join(out_dir, "paper_feat.npy"), mode="w+", dtype=np.float16,
        shape=(P, num_features),
    )
    pf[:] = nf["paper"].astype(np.float16)
    ap = rels[("author", "writes", "paper")]
    af = np.lib.format.open_memmap(
        os.path.join(out_dir, "author_feat.npy"), mode="w+", dtype=np.float16,
        shape=(A, num_features),
    )
    aggregate_mean_features(af, pf, ap)
    ai = rels[("author", "affiliated", "institution")]
    inf = np.lib.format.open_memmap(
        os.path.join(out_dir, "institution_feat.npy"), mode="w+",
        dtype=np.float16, shape=(I, num_features),
    )
    aggregate_mean_features(inf, af, ai[::-1])

    _write(out_dir, "paper_cites_paper", rels[("paper", "cites", "paper")])
    _write(out_dir, "author_writes_paper", ap)
    _write(out_dir, "author_affiliated_institution", ai)
    _write(out_dir, "paper_label", labels["paper"].astype(np.int32))
    tr = np.nonzero(masks["paper"]["train"])[0]
    held = np.nonzero(masks["paper"]["val"])[0]
    # disjoint val/test (the real layout's splits are disjoint; a synthetic
    # directory must be indistinguishable to consumers)
    _write(out_dir, "train_idx", tr)
    _write(out_dir, "valid_idx", held[: len(held) // 2])
    _write(out_dir, "test_idx", held[len(held) // 2 :])
    with open(os.path.join(out_dir, _META), "w") as f:
        json.dump(
            {"num_papers": P, "num_authors": A, "num_institutions": I,
             "num_features": num_features, "num_classes": C,
             "source": "synthetic"},
            f,
        )
    return out_dir


def load_mag240m_memmap(path: str) -> tuple[dict, dict, dict, dict, dict]:
    """Open a prepared directory (real or synthetic — identical layout).

    Returns (node_features, relations, labels, masks, meta) in the shapes
    :meth:`DistributedHeteroGraph.from_global` takes; feature arrays are
    lazy np.memmap views (nothing large loads eagerly)."""
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)

    def mm(name):
        return np.load(os.path.join(path, name + ".npy"), mmap_mode="r")

    node_features = {
        "paper": mm("paper_feat"),
        "author": mm("author_feat"),
        "institution": mm("institution_feat"),
    }
    ap = np.asarray(mm("author_writes_paper"))
    ai = np.asarray(mm("author_affiliated_institution"))
    relations = {
        ("paper", "cites", "paper"): np.asarray(mm("paper_cites_paper")),
        ("author", "writes", "paper"): ap,
        ("paper", "written_by", "author"): ap[::-1],
        ("author", "affiliated", "institution"): ai,
        ("institution", "hosts", "author"): ai[::-1],
    }
    labels = {"paper": np.asarray(mm("paper_label"))}
    P = meta["num_papers"]
    masks = {"paper": {}}
    for split, name in (("train", "train_idx"), ("val", "valid_idx"), ("test", "test_idx")):
        m = np.zeros(P, bool)
        m[np.asarray(mm(name))] = True
        masks["paper"][split] = m
    return node_features, relations, labels, masks, meta
