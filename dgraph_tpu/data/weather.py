"""Synthetic ERA5-like weather dataset for GraphCast training.

Reference parity: ``experiments/GraphCast/dataset.py:24-232``
(SyntheticWeatherDataset: random 721x1440x73-channel fields served as
(input, target) steps, partitioned per rank) — with the §2.6-noted missing
``mesh_vertex_placement`` constructor bug designed out (this dataset only
needs the grid renumbering, taken directly from the built graphs).

Fields are smooth (low-frequency Fourier mixtures) so one-step prediction is
learnable; the target is a fixed deterministic advection/decay of the input,
giving a non-trivial but stationary mapping.
"""

from __future__ import annotations

import numpy as np

from dgraph_tpu.plan import shard_vertex_data


class SyntheticWeatherDataset:
    def __init__(
        self,
        graphs,  # GraphCastGraphs
        num_lat: int,
        num_lon: int,
        num_channels: int = 73,
        num_samples: int = 8,
        seed: int = 0,
    ):
        self.num_lat, self.num_lon = num_lat, num_lon
        self.num_channels = num_channels
        self.graphs = graphs
        rng = np.random.default_rng(seed)
        n_grid = num_lat * num_lon

        # smooth random fields: sum of a few random spatial harmonics / channel
        lat = np.linspace(0, np.pi, num_lat)[:, None]
        lon = np.linspace(0, 2 * np.pi, num_lon, endpoint=False)[None, :]
        self._samples = []
        for _ in range(num_samples):
            fields = np.zeros((num_lat, num_lon, num_channels), np.float32)
            for c in range(num_channels):
                for _ in range(3):
                    kl, kk = rng.integers(1, 4), rng.integers(1, 5)
                    ph = rng.uniform(0, 2 * np.pi)
                    amp = rng.normal(0, 1.0)
                    fields[:, :, c] += amp * np.sin(kl * lat + ph) * np.cos(kk * lon)
            x = fields.reshape(n_grid, num_channels)
            y = self._advance(x)
            self._samples.append((x.astype(np.float32), y.astype(np.float32)))

    def _advance(self, x: np.ndarray) -> np.ndarray:
        """The dataset's deterministic dynamics T: eastward roll + mild
        decay + channel mix. Iterating T gives true multi-step
        trajectories for rollout evaluation."""
        fields = x.reshape(self.num_lat, self.num_lon, self.num_channels)
        rolled = np.roll(fields, shift=3, axis=1).reshape(x.shape)
        return (0.9 * rolled + 0.1 * x.mean(axis=1, keepdims=True)).astype(
            np.float32)

    def __len__(self):
        return len(self._samples)

    def _shard(self, a: np.ndarray):
        g = self.graphs
        return shard_vertex_data(a[g.grid_ren.inv], g.grid_ren.counts, g.n_grid_pad)

    def get_sharded(self, i: int):
        """(input, target) as [W, n_grid_pad, C] plan-layout arrays."""
        x, y = self._samples[i % len(self._samples)]
        return self._shard(x), self._shard(y)

    def trajectory_sharded(self, i: int, num_steps: int):
        """(x0, [T, W, n_grid_pad, C]) — the true num_steps-long forward
        trajectory T(x0), T^2(x0), ... for rollout evaluation
        (models.graphcast.rollout)."""
        x, _ = self._samples[i % len(self._samples)]
        steps = []
        cur = x
        for _ in range(num_steps):
            cur = self._advance(cur)
            steps.append(self._shard(cur))
        return self._shard(x), np.stack(steps)
