"""OGB node-property-prediction ingestion.

Reference parity: ``DGraph/data/ogbn_datasets.py`` (``DistributedOGBWrapper``,
``:40-148``) — rank-0-first download with a barrier (``:67-85``), a processed
partitioned-graph cache keyed by dataset+world_size (``:96-99``), and the
supported-dataset table (arxiv / proteins / papers100M / products, ``:25-37``).

TPU-native differences:

- One ingestion path produces a :class:`~dgraph_tpu.data.graph.DistributedGraph`
  (stacked ``[W, n_pad, ...]`` shards + static plan) instead of the
  reference's per-backend collation split (global edges for NCCL vs local
  for one-sided, ``:135-148``) — under SPMD there is only one layout.
- The ``ogb`` package is import-gated: this environment has no egress, so
  :func:`load_ogb_arrays` falls back to an ``.npz``/memmap-dir export made
  elsewhere with :func:`export_npz` (same array names either way).
- Lead-first loading uses a filesystem sentinel rather than a process-group
  barrier: multi-controller launches share a filesystem, and the processed
  cache makes followers read-only consumers.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Optional

import numpy as np

SUPPORTED = (
    "ogbn-arxiv",
    "ogbn-products",
    "ogbn-proteins",
    "ogbn-papers100M",
)

_ARRAYS = ("edge_index", "features", "labels", "train_mask", "valid_mask", "test_mask")


def masks_from_split(split_idx: dict, num_nodes: int) -> dict:
    """OGB's {train,valid,test} index arrays -> float masks (the framework's
    loss/metric masking convention)."""
    masks = {}
    for name, key in (("train", "train"), ("valid", "valid"), ("test", "test")):
        m = np.zeros(num_nodes, np.float32)
        if key in split_idx:
            m[np.asarray(split_idx[key], dtype=np.int64)] = 1.0
        masks[name] = m
    return masks


def load_ogb_arrays(name: str, root: str = "dataset") -> dict:
    """Load one OGB node-prediction dataset as plain numpy arrays.

    Resolution order:

    1. ``ogb.nodeproppred.NodePropPredDataset`` when the package is
       importable (it downloads on first use — the reference's rank-0
       download, ``ogbn_datasets.py:67-85``);
    2. a raw download in the official on-disk layout under ``root``,
       parsed directly by :mod:`dgraph_tpu.data.ogb_raw` (this environment
       cannot pip-install ogb, so egress-day ingestion takes this branch);
    3. ImportError with the export recipe (run :func:`export_npz` where
       ogb exists, ship the ``.npz``).

    Both loading branches share :func:`_arrays_from_graph`, so the fixture
    tests of branch 2 exercise the exact post-processing branch 1 gets.
    """
    if name not in SUPPORTED:
        raise ValueError(f"unsupported dataset {name!r}; supported: {SUPPORTED}")
    try:
        from ogb.nodeproppred import NodePropPredDataset  # type: ignore
    except ImportError as e:
        from dgraph_tpu.data.ogb_raw import has_raw_download, read_node_pred_raw

        if has_raw_download(root, name):
            return _arrays_from_graph(name, *read_node_pred_raw(root, name))
        raise ImportError(
            f"the 'ogb' package is not installed and no raw download layout "
            f"for {name} exists under {root!r}; either place the official "
            "download there (dgraph_tpu.data.ogb_raw parses it directly) or "
            "export elsewhere with dgraph_tpu.data.ogbn.export_npz(name, "
            "out_path) and pass the .npz (or memmap dir) to from_npz()/the "
            "experiment CLIs"
        ) from e

    ds = NodePropPredDataset(name=name, root=root)
    graph, labels = ds[0]
    return _arrays_from_graph(name, graph, labels, ds.get_idx_split())


def _arrays_from_graph(name: str, graph: dict, labels, split_idx: dict) -> dict:
    """(graph, labels, split_idx) -> the flat array dict every consumer
    takes; shared by the ogb-package and raw-download loaders."""
    num_nodes = int(graph["num_nodes"])
    edge_index = np.asarray(graph["edge_index"], dtype=np.int64)
    if name == "ogbn-proteins":
        # proteins ships no node features (edge features only) and [V, 112]
        # multi-label float targets; the reference carries a per-dataset
        # num_classes table for it (ogbn_datasets.py:25-37). Node features:
        # species one-hot + log-degree (the standard featureless recipe).
        species = np.asarray(graph["node_species"]).squeeze()
        uniq, inv = np.unique(species, return_inverse=True)
        onehot = np.zeros((num_nodes, len(uniq)), np.float32)
        onehot[np.arange(num_nodes), inv] = 1.0
        deg = np.bincount(edge_index[0], minlength=num_nodes).astype(np.float32)
        features = np.concatenate([onehot, np.log1p(deg)[:, None]], axis=1)
        labels = np.asarray(labels, dtype=np.float32)  # [V, 112] multi-label
    else:
        features = np.asarray(graph["node_feat"], dtype=np.float32)
        labels = np.asarray(labels).squeeze()
        # papers100M labels are float with NaN on unlabeled nodes (reference
        # handles the same in its loaders); class 0 + loss mask is equivalent
        if np.issubdtype(labels.dtype, np.floating):
            labels = np.where(np.isnan(labels), 0, labels)
        labels = labels.astype(np.int32)
    out = {
        "edge_index": edge_index,
        "features": features,
        "labels": labels,
        "num_nodes": num_nodes,
    }
    out.update(
        {k + "_mask": v for k, v in masks_from_split(split_idx, num_nodes).items()}
    )
    return out


def export_npz(name: str, out_path: str, root: str = "dataset") -> str:
    """One-time export (run where ogb + network exist): write the dataset to
    a single ``.npz`` consumable by :func:`from_npz` and the experiment CLIs
    in this (egress-less) environment."""
    arrs = load_ogb_arrays(name, root=root)
    np.savez(
        out_path,
        **{k: v for k, v in arrs.items() if isinstance(v, np.ndarray)},
    )
    return out_path


def export_arxiv_shaped_npz(
    out_path: str, scale: float = 1.0, seed: int = 0
) -> str:
    """Write an ogbn-arxiv-SHAPED learnable stand-in export (this
    environment has neither the ogb package nor egress — VERDICT r1 #5).

    Same shapes, dtypes, array names, and split proportions as a real
    :func:`export_npz` of ogbn-arxiv (169 343 nodes, 1 166 243 directed
    edges, 128-dim features, 40 classes, 90 941/29 799/48 603
    train/valid/test — ``ogbn_datasets.py:25-37`` scale), with SBM
    community structure + feature signal so reported accuracy measures
    real learning. The moment the real arrays are available,
    :func:`export_npz` produces the identical format and every consumer
    (from_npz, ogb_gcn.py, DistributedOGBDataset) runs unchanged.
    """
    from dgraph_tpu.data.synthetic import sbm_classification_graph

    V = max(int(169_343 * scale), 1_000)
    avg_directed_degree = 2 * 1_166_243 / 169_343  # symmetrized, like the CLI
    data = sbm_classification_graph(
        num_nodes=V,
        num_classes=40,
        feat_dim=128,
        avg_degree=avg_directed_degree,
        homophily=0.8,
        train_frac=90_941 / 169_343,
        val_frac=29_799 / 169_343,
        seed=seed,
    )
    np.savez(
        out_path,
        edge_index=data["edge_index"],
        features=data["features"].astype(np.float32),
        labels=data["labels"].astype(np.int32),
        train_mask=data["masks"]["train"],
        valid_mask=data["masks"]["val"],
        test_mask=data["masks"]["test"],
    )
    return out_path


def from_npz(path: str) -> dict:
    """Load the :func:`export_npz` format (or a memmap dir with the same
    array names) into the dict shape :func:`load_ogb_arrays` returns."""
    if os.path.isdir(path):
        from dgraph_tpu.data.memmap import open_memmap_dataset

        present = [
            n for n in _ARRAYS
            if os.path.exists(os.path.join(path, n + ".npy"))
        ]
        z = open_memmap_dataset(path, names=present)
    else:
        z = dict(np.load(path).items())
    z["num_nodes"] = int(z["features"].shape[0])
    return z


def lead_first(path: str, build, is_lead: bool, poll_s: float = 5.0,
               timeout_s: float = 24 * 3600.0):
    """Run ``build(path)`` on the lead process only; followers wait for the
    sentinel. The reference's rank-0-first download + barrier
    (``ogbn_datasets.py:67-85``) restated for shared-filesystem SPMD:
    the artifact itself (plus a ``.done`` sentinel) is the barrier.
    """
    done = path + ".done"
    # the sentinel vouches for the artifact only if the artifact is there too
    # (a deleted/partial cache with a leftover sentinel must rebuild)
    if os.path.exists(done) and os.path.exists(path):
        return path
    if is_lead:
        if os.path.exists(done):
            os.remove(done)  # stale sentinel without artifact
        build(path)
        with open(done, "w") as f:
            json.dump({"ts": time.time()}, f)
        return path
    waited = 0.0
    while not (os.path.exists(done) and os.path.exists(path)):
        time.sleep(poll_s)
        waited += poll_s
        if waited > timeout_s:
            raise TimeoutError(f"lead process never produced {done}")
    return path


class DistributedOGBDataset:
    """Partitioned OGB dataset with an on-disk processed cache.

    Parity: ``DistributedOGBWrapper`` (``ogbn_datasets.py:40-148``) — its
    ``{dname}_graph_data_{world}.pt`` processed cache (``:96-99``) becomes a
    pickle of the fully built :class:`DistributedGraph` keyed by
    (dataset, world_size, partition_method) plus a hash of every other
    graph-shaping option (pad_multiple, symmetrize, norm, data_path).
    """

    def __init__(
        self,
        name: str,
        world_size: int,
        *,
        data_path: Optional[str] = None,  # npz/memmap export (no-ogb path)
        root: str = "dataset",
        cache_dir: str = "cache/ogb",
        partition_method: str = "rcm",
        symmetrize: bool = True,
        add_symmetric_norm: bool = True,
        pad_multiple: int = 128,
        is_lead: Optional[bool] = None,
    ):
        from dgraph_tpu.data.graph import DistributedGraph

        if is_lead is None:
            # multi-controller default: exactly one builder (the reference
            # serializes via rank 0 + barrier, ogbn_datasets.py:67-85)
            from dgraph_tpu.utils.logging import is_lead_process

            is_lead = is_lead_process()
        self.name = name
        self.world_size = world_size
        os.makedirs(cache_dir, exist_ok=True)
        # every knob that changes the built graph participates in the cache
        # key — a partial key would silently reuse a graph built with
        # different normalization/padding/source. The pickle embeds a
        # built EdgePlan, so the plan FORMAT version (and the block size
        # that now shapes e_pad) must key it too: a warm v5 cache would
        # otherwise keep serving unaligned plans forever.
        import hashlib

        from dgraph_tpu.plan import SCATTER_BLOCK_E
        from dgraph_tpu.train.checkpoint import PLAN_FORMAT_VERSION

        # root participates because the raw-download fallback makes content
        # root-dependent (two roots can hold different fixtures/downloads;
        # a warm cache must not serve one as the other)
        opts = hashlib.sha256(
            repr((pad_multiple, symmetrize, add_symmetric_norm, data_path,
                  root, PLAN_FORMAT_VERSION, SCATTER_BLOCK_E)).encode()
        ).hexdigest()[:10]
        cache = os.path.join(
            cache_dir, f"{name}_w{world_size}_{partition_method}_{opts}.pkl"
        )

        def build(path):
            arrs = (
                from_npz(data_path) if data_path else load_ogb_arrays(name, root)
            )
            edge_index = np.asarray(arrs["edge_index"])
            if symmetrize:
                edge_index = np.concatenate(
                    [edge_index, edge_index[::-1]], axis=1
                )
            g = DistributedGraph.from_global(
                edge_index,
                np.asarray(arrs["features"]),
                np.asarray(arrs["labels"]),
                {
                    k[: -len("_mask")]: np.asarray(v)
                    for k, v in arrs.items()
                    if k.endswith("_mask")
                },
                world_size=world_size,
                partition_method=partition_method,
                add_symmetric_norm=add_symmetric_norm,
                pad_multiple=pad_multiple,
            )
            from dgraph_tpu.train.checkpoint import atomic_pickle_dump

            atomic_pickle_dump(path, g)

        lead_first(cache, build, is_lead)
        with open(cache, "rb") as f:
            self.graph: DistributedGraph = pickle.load(f)

    @property
    def plan(self):
        return self.graph.plan

    def batch(self, split: str) -> dict:
        return self.graph.batch(split)
