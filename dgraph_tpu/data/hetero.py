"""Distributed heterogeneous graphs: multiple node types, multiple relations.

Reference parity: ``experiments/OGB-LSC/lsc_datasets/distributed_graph_dataset.py``
(DistributedHeteroGraphDataset: per-relation edge-conditioned comm plans over
MAG240M's 3 node types / 5 relations) and
``DGraph/distributed/nccl/_NCCLCommPlan.py:103-137``
(NCCLEdgeConditionedGraphCommPlan: src-plan + dst-plan pairs). Here a
relation is simply a bipartite :class:`~dgraph_tpu.plan.EdgePlan` between two
independently partitioned node sets; all relations sharing a node type share
that type's padded size so one feature buffer serves every relation.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dgraph_tpu import partition as pt
from dgraph_tpu.plan import (
    EdgePlan,
    EdgePlanLayout,
    build_edge_plan,
    shard_vertex_data,
    _pad_to,
)

RelKey = tuple[str, str, str]  # (src_type, relation_name, dst_type)


@dataclasses.dataclass
class DistributedHeteroGraph:
    world_size: int
    node_types: list
    renumberings: dict  # type -> Renumbering
    n_pads: dict  # type -> padded per-shard vertex count
    features: dict  # type -> [W, n_pad, F] float32
    plans: dict  # RelKey -> EdgePlan
    layouts: dict  # RelKey -> EdgePlanLayout
    labels: Optional[dict] = None  # type -> [W, n_pad] int32 (sparse types omitted)
    masks: Optional[dict] = None  # (type, split) -> [W, n_pad] f32
    vertex_masks: Optional[dict] = None  # type -> [W, n_pad] f32

    @classmethod
    def from_global(
        cls,
        node_features: dict,
        relations: dict,
        world_size: int,
        *,
        labels: Optional[dict] = None,
        masks: Optional[dict] = None,
        partition_method: str = "random",
        pad_multiple: int = 8,
        seed: int = 0,
    ) -> "DistributedHeteroGraph":
        """Args:
        node_features: type -> [V_t, F_t] float array.
        relations: (src_type, name, dst_type) -> [2, E] global edges.
        labels: type -> [V_t] int labels (optional, per type).
        masks: type -> {split: [V_t] bool} (optional).
        """
        node_types = list(node_features)
        rens, n_pads, feats = {}, {}, {}
        for t in node_types:
            V = node_features[t].shape[0]
            if partition_method == "round_robin":
                part = pt.round_robin_partition(V, world_size)
            elif partition_method == "block":
                part = pt.block_partition(V, world_size)
            else:
                part = pt.random_partition(V, world_size, seed)
            rens[t] = pt.renumber_contiguous(part, world_size)
            n_pads[t] = _pad_to(int(rens[t].counts.max(initial=1)), pad_multiple)
            feats[t] = shard_vertex_data(
                np.asarray(node_features[t], np.float32)[rens[t].inv],
                rens[t].counts,
                n_pads[t],
            )

        plans, layouts = {}, {}
        for key, edges in relations.items():
            st, _, dt = key
            e = np.stack([rens[st].perm[np.asarray(edges[0])], rens[dt].perm[np.asarray(edges[1])]])
            plan, layout = build_edge_plan(
                e,
                rens[st].partition,
                rens[dt].partition if dt != st else None,
                world_size=world_size,
                edge_owner="dst",
                n_src_pad=n_pads[st],
                n_dst_pad=n_pads[dt],
                pad_multiple=pad_multiple,
            )
            plans[key], layouts[key] = plan, layout

        lab = None
        if labels:
            lab = {
                t: shard_vertex_data(
                    np.asarray(v, np.int32)[rens[t].inv], rens[t].counts, n_pads[t]
                )
                for t, v in labels.items()
            }
        msk = None
        if masks:
            msk = {}
            for t, splits in masks.items():
                for s, v in splits.items():
                    msk[(t, s)] = shard_vertex_data(
                        np.asarray(v, np.float32)[rens[t].inv], rens[t].counts, n_pads[t]
                    )
        vmasks = {
            t: shard_vertex_data(
                np.ones(len(rens[t].perm), np.float32), rens[t].counts, n_pads[t]
            )
            for t in node_types
        }
        return cls(
            world_size=world_size,
            node_types=node_types,
            renumberings=rens,
            n_pads=n_pads,
            features=feats,
            plans=plans,
            layouts=layouts,
            labels=lab,
            masks=msk,
            vertex_masks=vmasks,
        )


def synthetic_mag(
    num_papers: int = 300,
    num_authors: int = 200,
    num_institutions: int = 30,
    feat_dim: int = 16,
    num_classes: int = 4,
    seed: int = 0,
):
    """Synthetic MAG240M-like heterogeneous graph.

    Degree calibration follows the reference's synthetic generator
    (``lsc_datasets/synthetic_dataset.py:37-76``): paper-paper citations with
    avg degree ~11, ~3.5 authors per paper, ~0.35 institutions per author.
    Returns (node_features, relations, labels, masks) ready for
    :meth:`DistributedHeteroGraph.from_global`. The 5 relations mirror
    ``distributed_graph_dataset.py:276,475-489``: p->p cites, a->p writes,
    p->a writed_by, a->i affiliated, i->a hosts.
    """
    rng = np.random.default_rng(seed)
    labels_p = rng.integers(0, num_classes, num_papers)
    centroids = rng.normal(0, 1.0, (num_classes, feat_dim))
    feat_p = centroids[labels_p] + rng.normal(0, 1.5, (num_papers, feat_dim))
    feat_a = rng.normal(0, 1.0, (num_authors, feat_dim))
    feat_i = rng.normal(0, 1.0, (num_institutions, feat_dim))

    def rand_rel(n_src, n_dst, n_edges, homophily_labels=None):
        src = rng.integers(0, n_src, n_edges)
        dst = rng.integers(0, n_dst, n_edges)
        return np.stack([src, dst]).astype(np.int64)

    E_pp = int(num_papers * 11 / 2)
    # citations biased intra-class so the task is learnable
    s = rng.integers(0, num_papers, E_pp * 3)
    d = rng.integers(0, num_papers, E_pp * 3)
    keep = np.where(labels_p[s] == labels_p[d], rng.random(E_pp * 3) < 0.8, rng.random(E_pp * 3) < 0.2)
    s, d = s[keep][:E_pp], d[keep][:E_pp]
    pp = np.stack([np.concatenate([s, d]), np.concatenate([d, s])]).astype(np.int64)

    ap = rand_rel(num_authors, num_papers, int(num_papers * 3.5))
    ai = rand_rel(num_authors, num_institutions, int(num_authors * 0.35) + 1)

    relations = {
        ("paper", "cites", "paper"): pp,
        ("author", "writes", "paper"): ap,
        ("paper", "written_by", "author"): pp_rev(ap),
        ("author", "affiliated", "institution"): ai,
        ("institution", "hosts", "author"): pp_rev(ai),
    }
    node_features = {"paper": feat_p, "author": feat_a, "institution": feat_i}

    order = rng.permutation(num_papers)
    n_tr = int(0.6 * num_papers)
    masks = {
        "paper": {
            "train": np.isin(np.arange(num_papers), order[:n_tr]),
            "val": np.isin(np.arange(num_papers), order[n_tr:]),
        }
    }
    return node_features, relations, {"paper": labels_p.astype(np.int32)}, masks


def pp_rev(edges: np.ndarray) -> np.ndarray:
    return np.stack([edges[1], edges[0]])
