"""Distributed heterogeneous graphs: multiple node types, multiple relations.

Reference parity: ``experiments/OGB-LSC/lsc_datasets/distributed_graph_dataset.py``
(DistributedHeteroGraphDataset: per-relation edge-conditioned comm plans over
MAG240M's 3 node types / 5 relations) and
``DGraph/distributed/nccl/_NCCLCommPlan.py:103-137``
(NCCLEdgeConditionedGraphCommPlan: src-plan + dst-plan pairs). Here a
relation is simply a bipartite :class:`~dgraph_tpu.plan.EdgePlan` between two
independently partitioned node sets; all relations sharing a node type share
that type's padded size so one feature buffer serves every relation.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dgraph_tpu import partition as pt
from dgraph_tpu.plan import (
    EdgePlan,
    EdgePlanLayout,
    build_edge_plan,
    shard_vertex_data,
    _pad_to,
)

RelKey = tuple[str, str, str]  # (src_type, relation_name, dst_type)


def locality_partitions(
    node_counts: dict,
    relations: dict,
    world_size: int,
    method: str = "multilevel",
    seed: int = 0,
    balance_slack: float = 1.05,
) -> dict:
    """Locality-aware partitions for every node type at once, via the TYPED
    UNION GRAPH: all types share one vertex id space (per-type offsets), all
    relations become edges of one graph, and a single multilevel/BFS
    partition keeps cited papers, their authors, and their institutions on
    the same shard — the hetero analogue of the reference's METIS
    partitioning (VERDICT r1 #6/#7: hetero graphs previously only had
    random/round-robin/block, making RGAT halo volume worst-case by
    construction).

    Per-type balance is then enforced separately (padded shard sizes are
    per-type maxima, so one type imbalanced by the union partition would
    blow up every rank's padding): vertices of overfull ranks move to the
    least-loaded ranks until every rank holds <= ceil(V_t/W)*balance_slack.

    Args:
      node_counts: type -> V_t.
      relations: (src_type, name, dst_type) -> [2, E] global edges.
    Returns: type -> [V_t] int32 rank assignment.
    """
    types = list(node_counts)
    offsets = {}
    total = 0
    for t in types:
        offsets[t] = total
        total += int(node_counts[t])
    union_edges = np.concatenate(
        [
            np.stack(
                [
                    np.asarray(e[0], np.int64) + offsets[st],
                    np.asarray(e[1], np.int64) + offsets[dt],
                ]
            )
            for (st, _, dt), e in relations.items()
        ],
        axis=1,
    )
    if method in ("multilevel", "metis"):
        part_union = pt.multilevel_partition(union_edges, total, world_size, seed)
    else:
        part_union = pt.greedy_bfs_partition(union_edges, total, world_size, seed)

    out = {}
    for t in types:
        part = np.asarray(
            part_union[offsets[t] : offsets[t] + node_counts[t]], np.int32
        ).copy()
        cap = int(np.ceil(node_counts[t] / world_size * balance_slack))
        counts = np.bincount(part, minlength=world_size)
        for r in np.argsort(-counts):
            excess = counts[r] - cap
            if excess <= 0:
                continue
            movable = np.nonzero(part == r)[0][-excess:]
            targets = np.argsort(counts)
            for dst_r in targets:
                if excess <= 0:
                    break
                room = cap - counts[dst_r]
                if room <= 0:
                    continue
                take = min(room, excess)
                part[movable[excess - take : excess]] = dst_r
                counts[dst_r] += take
                counts[r] -= take
                excess -= take
        out[t] = part
    return out


@dataclasses.dataclass
class DistributedHeteroGraph:
    world_size: int
    node_types: list
    renumberings: dict  # type -> Renumbering
    n_pads: dict  # type -> padded per-shard vertex count
    features: dict  # type -> [W, n_pad, F] float32
    plans: dict  # RelKey -> EdgePlan
    layouts: dict  # RelKey -> EdgePlanLayout
    labels: Optional[dict] = None  # type -> [W, n_pad] int32 (sparse types omitted)
    masks: Optional[dict] = None  # (type, split) -> [W, n_pad] f32
    vertex_masks: Optional[dict] = None  # type -> [W, n_pad] f32

    @classmethod
    def from_global(
        cls,
        node_features: dict,
        relations: dict,
        world_size: int,
        *,
        labels: Optional[dict] = None,
        masks: Optional[dict] = None,
        partition_method: str = "random",
        pad_multiple: int = 8,
        seed: int = 0,
        plan_cache: Optional[str] = None,
    ) -> "DistributedHeteroGraph":
        """Args:
        node_features: type -> [V_t, F_t] float array.
        relations: (src_type, name, dst_type) -> [2, E] global edges.
        labels: type -> [V_t] int labels (optional, per type).
        masks: type -> {split: [V_t] bool} (optional).
        """
        node_types = list(node_features)
        loc_parts = None
        if partition_method in ("multilevel", "metis", "greedy_bfs", "locality"):
            loc_parts = locality_partitions(
                {t: node_features[t].shape[0] for t in node_types},
                relations,
                world_size,
                method="greedy_bfs" if partition_method == "greedy_bfs" else "multilevel",
                seed=seed,
            )
        rens, n_pads, feats = {}, {}, {}
        for t in node_types:
            V = node_features[t].shape[0]
            if loc_parts is not None:
                part = loc_parts[t]
            elif partition_method == "round_robin":
                part = pt.round_robin_partition(V, world_size)
            elif partition_method == "block":
                part = pt.block_partition(V, world_size)
            else:
                part = pt.random_partition(V, world_size, seed)
            rens[t] = pt.renumber_contiguous(part, world_size)
            n_pads[t] = _pad_to(int(rens[t].counts.max(initial=1)), pad_multiple)
            # shard_rows reads each shard's rows page-sequentially — a
            # memmap source (MAG240M fp16 features, 187 GB at full scale)
            # is never materialized whole, unlike
            # np.asarray(...)[inv] which would copy it twice
            from dgraph_tpu.data.memmap import shard_rows

            feats[t] = shard_rows(
                node_features[t], rens[t].inv, rens[t].offsets,
                n_pads[t], range(world_size), np.float32,
            )

        plans, layouts = {}, {}
        for key, edges in relations.items():
            st, _, dt = key
            e = np.stack([rens[st].perm[np.asarray(edges[0])], rens[dt].perm[np.asarray(edges[1])]])
            kw = dict(
                world_size=world_size,
                edge_owner="dst",
                n_src_pad=n_pads[st],
                n_dst_pad=n_pads[dt],
                pad_multiple=pad_multiple,
            )
            if plan_cache:
                # per-relation on-disk cache — the reference's offline
                # per-relation plan precompute (_save_comm_plans,
                # distributed_graph_dataset.py:399-422)
                from dgraph_tpu.train.checkpoint import cached_edge_plan

                plan, layout = cached_edge_plan(
                    plan_cache, e, rens[st].partition,
                    rens[dt].partition if dt != st else None, **kw,
                )
            else:
                plan, layout = build_edge_plan(
                    e, rens[st].partition,
                    rens[dt].partition if dt != st else None, **kw,
                )
            plans[key], layouts[key] = plan, layout

        lab = None
        if labels:
            lab = {
                t: shard_vertex_data(
                    np.asarray(v, np.int32)[rens[t].inv], rens[t].counts, n_pads[t]
                )
                for t, v in labels.items()
            }
        msk = None
        if masks:
            msk = {}
            for t, splits in masks.items():
                for s, v in splits.items():
                    msk[(t, s)] = shard_vertex_data(
                        np.asarray(v, np.float32)[rens[t].inv], rens[t].counts, n_pads[t]
                    )
        vmasks = {
            t: shard_vertex_data(
                np.ones(len(rens[t].perm), np.float32), rens[t].counts, n_pads[t]
            )
            for t in node_types
        }
        return cls(
            world_size=world_size,
            node_types=node_types,
            renumberings=rens,
            n_pads=n_pads,
            features=feats,
            plans=plans,
            layouts=layouts,
            labels=lab,
            masks=msk,
            vertex_masks=vmasks,
        )


def synthetic_mag(
    num_papers: int = 300,
    num_authors: int = 200,
    num_institutions: int = 30,
    feat_dim: int = 16,
    num_classes: int = 4,
    seed: int = 0,
):
    """Synthetic MAG240M-like heterogeneous graph.

    Degree calibration follows the reference's synthetic generator
    (``lsc_datasets/synthetic_dataset.py:37-76``): paper-paper citations with
    avg degree ~11, ~3.5 authors per paper, ~0.35 institutions per author.
    Returns (node_features, relations, labels, masks) ready for
    :meth:`DistributedHeteroGraph.from_global`. The 5 relations mirror
    ``distributed_graph_dataset.py:276,475-489``: p->p cites, a->p writes,
    p->a writed_by, a->i affiliated, i->a hosts.
    """
    rng = np.random.default_rng(seed)
    labels_p = rng.integers(0, num_classes, num_papers)
    centroids = rng.normal(0, 1.0, (num_classes, feat_dim))
    feat_p = centroids[labels_p] + rng.normal(0, 1.5, (num_papers, feat_dim))
    feat_a = rng.normal(0, 1.0, (num_authors, feat_dim))
    feat_i = rng.normal(0, 1.0, (num_institutions, feat_dim))

    # every entity gets a "field" (class): papers carry it as the label,
    # authors/institutions work predominantly within one field — the
    # community structure real MAG has and a locality partitioner exploits
    # (the reference's generator is uniform-random on these relations,
    # synthetic_dataset.py:37-76; degree calibration is kept identical)
    labels_a = rng.integers(0, num_classes, num_authors)
    labels_i = rng.integers(0, num_classes, num_institutions)

    def clustered_rel(src_labels, dst_labels, n_edges, in_field=0.8):
        n_src, n_dst = len(src_labels), len(dst_labels)
        by_class = [np.nonzero(dst_labels == c)[0] for c in range(num_classes)]
        src = rng.integers(0, n_src, n_edges)
        same = rng.random(n_edges) < in_field
        dst = rng.integers(0, n_dst, n_edges)
        for c in range(num_classes):
            rows = np.nonzero(same & (src_labels[src] == c))[0]
            pool = by_class[c]
            if len(pool) and len(rows):
                dst[rows] = pool[rng.integers(0, len(pool), len(rows))]
        return np.stack([src, dst]).astype(np.int64)

    E_pp = int(num_papers * 11 / 2)
    # citations biased intra-class so the task is learnable
    s = rng.integers(0, num_papers, E_pp * 3)
    d = rng.integers(0, num_papers, E_pp * 3)
    keep = np.where(labels_p[s] == labels_p[d], rng.random(E_pp * 3) < 0.8, rng.random(E_pp * 3) < 0.2)
    s, d = s[keep][:E_pp], d[keep][:E_pp]
    pp = np.stack([np.concatenate([s, d]), np.concatenate([d, s])]).astype(np.int64)

    ap = clustered_rel(labels_a, labels_p, int(num_papers * 3.5))
    ai = clustered_rel(labels_a, labels_i, int(num_authors * 0.35) + 1)

    relations = {
        ("paper", "cites", "paper"): pp,
        ("author", "writes", "paper"): ap,
        ("paper", "written_by", "author"): pp_rev(ap),
        ("author", "affiliated", "institution"): ai,
        ("institution", "hosts", "author"): pp_rev(ai),
    }
    node_features = {"paper": feat_p, "author": feat_a, "institution": feat_i}

    order = rng.permutation(num_papers)
    n_tr = int(0.6 * num_papers)
    masks = {
        "paper": {
            "train": np.isin(np.arange(num_papers), order[:n_tr]),
            "val": np.isin(np.arange(num_papers), order[n_tr:]),
        }
    }
    return node_features, relations, {"paper": labels_p.astype(np.int32)}, masks


def pp_rev(edges: np.ndarray) -> np.ndarray:
    return np.stack([edges[1], edges[0]])
