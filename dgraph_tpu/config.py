"""Framework flags — one place, env-overridable.

Replaces the reference's scattered env-var flags
(``DGRAPH_CLEAR_BUFFER_CACHE``, ``RGAT_DDP_FIND_UNUSED``,
``DISABLE_DGRAPH_NVSHMEM``, … — SURVEY.md §5 config) with a single module.
"""

from __future__ import annotations

import os


def _env_flag(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


# Use the Pallas sorted-segment-sum kernel for owner-side scatter on TPU
# (requires plan.owner_sorted; falls back to jnp segment_sum elsewhere).
use_pallas_scatter: bool = _env_flag("DGRAPH_TPU_PALLAS_SCATTER", False)

# Compute dtype for model matmuls (bfloat16 keeps the MXU fed; params stay
# float32). Models read this at construction time.
default_compute_dtype: str = os.environ.get("DGRAPH_TPU_COMPUTE_DTYPE", "float32")

# Column-chunk width for row gathers (ops.local.row_take). XLA's TPU
# row-gather fast path covers one 128-lane tile; wider rows are gathered
# in <=this many columns per pass. 0 disables splitting.
gather_col_block: int = int(os.environ.get("DGRAPH_TPU_GATHER_COL_BLOCK", "128"))

# Halo exchange lowering: 'auto' (ppermute neighbor rounds when the plan's
# active peer-delta set is sparse, else one padded all_to_all),
# 'all_to_all', or 'ppermute'.
halo_impl: str = os.environ.get("DGRAPH_TPU_HALO_IMPL", "auto")


def set_flags(**kw) -> None:
    g = globals()
    for k, v in kw.items():
        if k not in g:
            raise KeyError(f"unknown dgraph_tpu.config flag: {k}")
        g[k] = v
