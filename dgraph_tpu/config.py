"""Framework flags — one place, env-overridable.

Replaces the reference's scattered env-var flags
(``DGRAPH_CLEAR_BUFFER_CACHE``, ``RGAT_DDP_FIND_UNUSED``,
``DISABLE_DGRAPH_NVSHMEM``, … — SURVEY.md §5 config) with a single module.
"""

from __future__ import annotations

import os


def _env_flag(name: str, default: bool | None = False) -> bool | None:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


# Use the Pallas sorted-segment-sum kernel for owner-side scatter on TPU
# (requires plan.owner_sorted; falls back to jnp segment_sum elsewhere).
# Tri-state: None = auto (ON when the default backend is TPU — e2e A/B'd on
# v5e, logs/pallas_ab_r2.jsonl); env DGRAPH_TPU_PALLAS_SCATTER=0/1 pins it.
use_pallas_scatter: bool | None = _env_flag("DGRAPH_TPU_PALLAS_SCATTER", None)


def pallas_scatter_enabled() -> bool:
    """Resolve the tri-state ``use_pallas_scatter`` (None = TPU backend)."""
    if use_pallas_scatter is not None:
        return use_pallas_scatter
    import jax

    return jax.default_backend() == "tpu"


# The Pallas sorted ROW-GATHER kernel (transpose of the scatter;
# ops.pallas_segment.sorted_row_gather). Tri-state, but unlike the
# scatter its AUTO state is OFF: it has never been A/B'd on a real chip
# (r2's XLA-gather numbers were invalidated by the timing-harness fix),
# so it engages only on an explicit DGRAPH_TPU_PALLAS_GATHER=1 (or
# set_flags) until on-chip data says otherwise.
use_pallas_gather: bool | None = _env_flag("DGRAPH_TPU_PALLAS_GATHER", None)


def pallas_gather_enabled() -> bool:
    return use_pallas_gather is True


# The FUSED bias+relu scatter kernel gets its own kill switch (tri-state;
# None = follow the plain-scatter decision): a Mosaic regression in one
# kernel must be disablable without losing the other (bench's self-check
# sets these independently).
use_pallas_fused: bool | None = _env_flag("DGRAPH_TPU_PALLAS_FUSED", None)


def pallas_fused_enabled() -> bool:
    if use_pallas_fused is not None:
        return use_pallas_fused
    return pallas_scatter_enabled()


# The fused-BACKWARD kernel pair (chunk-major gd + epilogue="act" d_bias)
# inside the fused op's VJP. Tri-state; None = engage whenever the fused
# op itself runs. A Mosaic regression hitting only the bwd kernels can be
# disabled here without vetoing the whole fused op (ADVICE r4): the
# composed bwd fallback stays available as the A/B control.
use_pallas_fused_bwd: bool | None = _env_flag("DGRAPH_TPU_PALLAS_FUSED_BWD", None)


def pallas_fused_bwd_enabled() -> bool:
    if use_pallas_fused_bwd is not None:
        return use_pallas_fused_bwd
    return True

# The device-initiated one-sided halo transport (halo_impl="pallas_p2p":
# pltpu.make_async_remote_copy puts issued from inside the Pallas kernel,
# ops.pallas_p2p). Tri-state like the scatter kernels: None = auto (the
# lowering is AVAILABLE on a TPU backend — actual adoption still requires
# an env pin or tuned record; resolve_halo_impl never heuristically picks
# an un-A/B'd kernel), True forces availability on ANY backend (off-TPU
# the kernels run in Pallas interpret mode — how the tier-1 parity pins
# run without a chip), False vetoes it everywhere.
use_pallas_p2p: bool | None = _env_flag("DGRAPH_TPU_PALLAS_P2P", None)


def pallas_p2p_available() -> bool:
    """Can halo_impl='pallas_p2p' lower on this backend? (One of the two
    gates resolve_halo_impl applies; the other is the plan carrying the
    interior/boundary split.)"""
    if use_pallas_p2p is not None:
        return use_pallas_p2p
    import jax

    return jax.default_backend() == "tpu"


# Mosaic flash-attention kernel for the Ulysses full-sequence per-head
# attention (parallel/sequence.py). Tri-state like the scatter kernels:
# None = auto (ON on TPU when shapes qualify), env DGRAPH_TPU_FLASH_ATTN
# pins it; consumers should run flash_attention_selfcheck() on chip first
# (same Mosaic-divergence rationale as the scatter self-checks).
use_flash_attention: bool | None = _env_flag("DGRAPH_TPU_FLASH_ATTN", None)


def flash_attention_enabled() -> bool:
    if use_flash_attention is not None:
        return use_flash_attention
    import jax

    return jax.default_backend() == "tpu"


# Compute dtype for model matmuls (bfloat16 keeps the MXU fed; params stay
# float32). Models resolve dtype=None through resolve_compute_dtype(), so
# DGRAPH_TPU_COMPUTE_DTYPE=bfloat16 flips every model at once.
default_compute_dtype: str = os.environ.get("DGRAPH_TPU_COMPUTE_DTYPE", "float32")


def resolve_compute_dtype(dtype):
    """None -> the configured default ('float32' stays None: flax Dense's
    native f32 path); an explicit dtype wins. Unknown config strings raise
    (a typo like 'bf16' silently training in f32 would misattribute every
    benchmark)."""
    if dtype is not None:
        return dtype
    name = default_compute_dtype
    if name in ("float32", "f32"):
        return None
    import jax.numpy as jnp

    table = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16, "float16": jnp.float16}
    if name not in table:
        raise ValueError(
            f"DGRAPH_TPU_COMPUTE_DTYPE={name!r} not understood; expected "
            "float32, bfloat16, or float16"
        )
    return table[name]

# Column-chunk width for row gathers (ops.local.row_take). XLA's TPU
# row-gather fast path covers one 128-lane tile; wider rows are gathered
# in <=this many columns per pass. 0 disables splitting.
gather_col_block: int = int(os.environ.get("DGRAPH_TPU_GATHER_COL_BLOCK", "128"))

# Halo exchange lowering: 'auto' (ppermute neighbor rounds when the plan's
# active peer-delta set is sparse, else one padded all_to_all; 'overlap'
# — interior/boundary split with the boundary rounds hidden behind
# interior aggregation — whenever the plan carries its OverlapSpec),
# 'all_to_all', 'ppermute', 'overlap', 'pallas_p2p' (device-initiated
# one-sided puts fused into the Pallas kernel; needs the overlap split
# AND pallas_p2p_available()), or 'sched' (a compiled multi-round
# schedule — dgraph_tpu.sched — replayed as data; needs the plan's
# attached halo_schedule). Resolution precedence lives in
# plan.resolve_halo_impl: this env pin > the adopted tuning record
# (tuned_halo_impl below) > the cost-model heuristic (which never picks
# pallas_p2p or sched on its own).
halo_impl: str = os.environ.get("DGRAPH_TPU_HALO_IMPL", "auto")

# Edge-axis chunk count for the overlap lowering's interior aggregation
# (comm.collectives._interior_chunks): 1 = one sorted segment-sum (the
# default — XLA already overlaps a single independent op with in-flight
# rounds, and chunk partial sums regroup float adds, costing bit-parity
# with the serial path); >1 splits the interior sum so pieces interleave
# with individual ppermute rounds (capped at the live-delta count).
overlap_interior_chunks: int = int(
    os.environ.get("DGRAPH_TPU_OVERLAP_CHUNKS", "1")
)

# Wire codec for halo payloads (dgraph_tpu.wire): 'auto' (defer to the
# adopted tuning record, then the plan-attached format, then the fp32
# identity — a lossy codec never engages on its own), or an explicit
# 'fp32' / 'bf16' / 'fp8' pin. Resolution precedence lives in
# wire.spec.resolve_wire_format: this env pin > tuned_wire_format
# (below) > EdgePlan.wire_format > 'fp32'; a pinned format whose
# preconditions fail (fp8 without the e4m3 dtype) degrades with one
# warning to the next tier.
wire_format: str = os.environ.get("DGRAPH_TPU_WIRE_FORMAT", "auto")

# Wire format chosen by an adopted TuningRecord: set by
# tune.record.adopt_record, consulted by wire.spec.resolve_wire_format
# AFTER the env pin. None = no record adopted.
tuned_wire_format: str | None = None

# Halo lowering chosen by an adopted TuningRecord (dgraph_tpu.tune):
# set by tune.record.adopt_record, consulted by plan.resolve_halo_impl
# AFTER the env pin — an operator's explicit DGRAPH_TPU_HALO_IMPL always
# beats a persisted search result. None = no record adopted.
tuned_halo_impl: str | None = None

# record_id of the MOST RECENTLY adopted TuningRecord (None = defaults in
# effect). Set by tune.record.adopt_record, reset by clear_adoption on a
# lookup miss; process-level attribution for consumers without a graph
# handle (artifact writers read the id off their graph/engine directly).
tuning_record_id: str | None = None


def set_flags(**kw) -> None:
    g = globals()
    for k, v in kw.items():
        if k not in g:
            raise KeyError(f"unknown dgraph_tpu.config flag: {k}")
        g[k] = v
