"""Parallelism strategies — one namespace over the mesh/collective layer.

Maps the reference's parallelism inventory (SURVEY.md §2.3) onto mesh axes:

- Graph/spatial partition parallelism (the reference's core; activations
  sharded by vertex, halo exchange per layer — the graph analogue of
  context/sequence parallelism): the ``graph`` mesh axis +
  :mod:`dgraph_tpu.comm.collectives`.
- Data parallelism (DDP gradient all-reduce): the ``replica`` mesh axis +
  :meth:`~dgraph_tpu.comm.communicator._BaseComm.grad_sync`.
- Hybrid partition-groups x replicas (``ranks_per_graph``,
  ``NCCLBackendEngine.py:56-64``): the 2-D ``('replica','graph')`` mesh from
  :func:`~dgraph_tpu.comm.mesh.make_graph_mesh`.
- Activation-stat parallelism (distributed BatchNorm,
  ``distributed_layers.py:22-207``):
  :class:`~dgraph_tpu.models.norm.DistributedBatchNorm`.

- Sequence/context parallelism (absent in the reference; first-class here):
  ring attention (K/V blocks streaming over ``lax.ppermute``) and the
  Ulysses all-to-all layout swap — :mod:`dgraph_tpu.parallel.sequence`.
- Pipeline parallelism: GPipe microbatch streaming over a ``pipe`` axis —
  :mod:`dgraph_tpu.parallel.pipeline`.
- Tensor parallelism: Megatron column/row-parallel linear pairs —
  :mod:`dgraph_tpu.parallel.tensor`.
- Expert parallelism: top-1 token-dispatch MoE over an ``expert`` axis —
  :mod:`dgraph_tpu.parallel.expert`.

Every strategy in SURVEY §2.3 (plus four the reference lacks) is therefore
implemented and tested on the virtual 8-device mesh.
"""

from dgraph_tpu.parallel.expert import (
    load_balance_loss,
    moe_apply,
    top1_dispatch,
    topk_dispatch,
)
from dgraph_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from dgraph_tpu.parallel.tensor import (
    column_parallel_dense,
    row_parallel_dense,
    shard_columns,
    shard_rows,
    tensor_parallel_mlp,
)
from dgraph_tpu.parallel.sequence import (
    dense_attention,
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
)
from dgraph_tpu.comm import collectives
from dgraph_tpu.comm.collectives import (
    gather,
    gather_concat,
    halo_exchange,
    halo_scatter_sum,
    psum_mean,
    scatter_sum,
)
from dgraph_tpu.comm.mesh import (
    GRAPH_AXIS,
    REPLICA_AXIS,
    make_graph_mesh,
    plan_in_specs,
    replicated_specs,
    squeeze_plan,
)

__all__ = [
    "column_parallel_dense",
    "row_parallel_dense",
    "tensor_parallel_mlp",
    "shard_columns",
    "shard_rows",
    "moe_apply",
    "top1_dispatch",
    "topk_dispatch",
    "load_balance_loss",
    "pipeline_apply",
    "stack_stage_params",
    "dense_attention",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "collectives",
    "gather",
    "gather_concat",
    "halo_exchange",
    "halo_scatter_sum",
    "psum_mean",
    "scatter_sum",
    "GRAPH_AXIS",
    "REPLICA_AXIS",
    "make_graph_mesh",
    "plan_in_specs",
    "replicated_specs",
    "squeeze_plan",
]
