"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh axis.

Beyond-reference (SURVEY.md §2.3 lists pipeline parallelism as absent in
the reference). Stages live on consecutive ranks of a ``pipe`` mesh axis;
activations hop stage-to-stage with ``lax.ppermute`` while microbatches
stream through, so at steady state every stage computes a different
microbatch concurrently — the classic bubble of (S-1) slots at the ramp
ends, amortized by the microbatch count M (efficiency M / (M + S - 1)).

TPU-first shape: the whole schedule is ONE ``lax.scan`` inside
``shard_map`` — no host round trips, no per-step dispatch; XLA sees a
static loop of compute + neighbor ``CollectivePermute`` and overlaps them.
Differentiable end to end: the scan/ppermute transpose runs the reverse
schedule (backward pipeline) automatically — no hand-written schedule.

Scope: homogeneous stages (same params pytree structure per stage — e.g.
N identical transformer blocks split across ranks). Heterogeneous
first/last stages (embed/head) stay outside the pipelined region, which is
how the classic GPipe deployments slice models anyway.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    stage_fn: Callable,  # (stage_params, x_mb) -> y_mb, same shape
    stage_params,  # THIS rank's stage parameters (pytree)
    x_micro: jax.Array,  # [M, mb, ...] microbatches (valid on stage 0)
    axis_name: str,
) -> jax.Array:
    """Run ``x_micro`` through S pipelined stages (S = axis size).

    Stage s applies ``stage_fn(stage_params, ·)`` on rank s; the result of
    the LAST stage is returned on every rank (broadcast via the final
    collective) with shape [M, mb, ...].

    Call inside ``shard_map``; shard ``stage_params`` over ``axis_name``
    (one stage's params per rank) and replicate ``x_micro`` or feed it on
    stage 0 (other ranks' copies are ignored).
    """
    S = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    total = M + S - 1  # ramp-up + steady + ramp-down

    vary = lambda t: lax.pcast(t, axis_name, to="varying")
    state = vary(jnp.zeros(mb_shape, x_micro.dtype))  # current activation
    out = vary(jnp.zeros((M,) + mb_shape, x_micro.dtype))

    def step(carry, t):
        state, out = carry
        # stage 0 ingests microbatch t (zeros once the stream is done);
        # other ranks use what arrived from the previous stage
        mb_idx = jnp.clip(t, 0, M - 1)
        injected = jnp.where(
            (rank == 0) & (t < M),
            lax.dynamic_index_in_dim(x_micro, mb_idx, keepdims=False),
            state,
        )
        y = stage_fn(stage_params, injected)
        # the LAST stage's output at step t is microbatch (t - (S-1));
        # store it (every rank stores — only the last stage's rows are
        # meaningful, selected by the psum-broadcast below)
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        is_valid = (rank == S - 1) & (t >= S - 1)
        cur = lax.dynamic_index_in_dim(out, out_idx, keepdims=False)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(is_valid, y, cur), out_idx, axis=0
        )
        # hop the activation to the next stage
        perm = [(i, (i + 1) % S) for i in range(S)]
        state = lax.ppermute(y, axis_name, perm)
        return (state, out), None

    (state, out), _ = lax.scan(step, (state, out), jnp.arange(total))
    # broadcast the last stage's collected outputs to every rank (psum of
    # one-hot contributions: only rank S-1 holds nonzero rows)
    contrib = jnp.where(rank == S - 1, out, jnp.zeros_like(out))
    return lax.psum(contrib, axis_name)


def stack_stage_params(params_list):
    """Host helper: stack S per-stage pytrees into one pytree with a
    leading [S] axis, ready to shard with ``P('pipe')``."""
    import numpy as np

    return jax.tree.map(lambda *xs: np.stack(xs), *params_list)
