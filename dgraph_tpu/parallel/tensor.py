"""Tensor (operator) parallelism: Megatron-style column/row-parallel
linear pairs over a mesh axis.

Beyond-reference (SURVEY.md §2.3 lists tensor parallelism as absent in the
reference). The classic pairing for an MLP/attention block:

- **column-parallel** first linear: weight [F, H/W] per rank, no
  communication on the forward (input is replicated over the axis);
- elementwise nonlinearity on the [.., H/W] shard;
- **row-parallel** second linear: weight [H/W, F] per rank, one ``psum``
  on the forward to reduce the partial products.

Exactly one collective per pair in each direction — AD transposes the
forward ``psum`` into the backward identity and vice versa, so the
backward also has one collective (the input-gradient reduction of the
column layer). Composes freely with the other axes of a mesh
(graph/replica/pipe): these helpers only touch ``axis_name``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def column_parallel_dense(x, w_shard, b_shard=None):
    """y_shard = x @ w_shard (+ b_shard): input replicated over the tensor
    axis, output feature-sharded. No communication."""
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel_dense(x_shard, w_shard, axis_name: str, b=None):
    """y = psum_over_axis(x_shard @ w_shard) (+ b): input feature-sharded,
    output replicated. ONE psum; add the (replicated) bias AFTER the
    reduction so it isn't summed W times."""
    y = lax.psum(x_shard @ w_shard, axis_name)
    if b is not None:
        y = y + b
    return y


def tensor_parallel_mlp(
    x: jax.Array,  # [.., F] replicated over the tensor axis
    w1_shard: jax.Array,  # [F, H/W] this rank's column shard
    b1_shard: Optional[jax.Array],  # [H/W] or None
    w2_shard: jax.Array,  # [H/W, F] this rank's row shard
    b2: Optional[jax.Array],  # [F] replicated or None
    axis_name: str,
    activation: Callable = jax.nn.silu,
) -> jax.Array:
    """The canonical column->act->row pair: one forward psum total."""
    h = activation(column_parallel_dense(x, w1_shard, b1_shard))
    return row_parallel_dense(h, w2_shard, axis_name, b2)


def shard_columns(w, num_shards: int, rank_axis: int = -1):
    """Host helper: split a dense weight into per-rank column shards with a
    leading [W] axis (shard with ``P('tensor')``)."""
    import numpy as np

    return np.stack(np.split(np.asarray(w), num_shards, axis=rank_axis))


def shard_rows(w, num_shards: int):
    """Host helper: per-rank row shards, leading [W] axis."""
    import numpy as np

    return np.stack(np.split(np.asarray(w), num_shards, axis=0))
