"""Sequence/context parallelism: ring attention over a mesh axis.

Long-context support is first-class in this framework even though the
reference has none (SURVEY.md §2.3 lists sequence parallelism as absent;
its graph-partition parallelism — vertex-sharded activations + per-layer
halo exchange — is the structural analogue and lives in
:mod:`dgraph_tpu.comm.collectives`). This module supplies the sequence
side of that story for transformer-style attention over sequences too
long for one device:

- **Ring attention** (blockwise attention + online softmax): Q stays
  resident; K/V blocks stream around the ring via ``lax.ppermute``, one
  neighbor hop per step, so each device holds O(T/W) of the sequence and
  the ICI traffic per step is exactly one K/V block. The online-softmax
  recurrence makes the result numerically identical to dense attention
  (it is the flash-attention accumulation, distributed).
- The all-to-all (DeepSpeed-Ulysses-style) head-scatter variant trades
  one big collective for per-step neighbor hops; on TPU the ring maps
  straight onto ICI neighbor links, so the ring is the default here.

Differentiable end to end: the backward of ``ppermute`` is the reverse
``ppermute`` and the scan transposes into the standard two-pass
flash-attention backward schedule, so ``jax.grad`` through
:func:`ring_attention` emits ring communication in the backward too —
no hand-written transpose needed (pinned in tests/test_sequence.py).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)  # finite -inf stand-in:
# keeps the online-softmax recurrence NaN-free for fully-masked blocks
# (exp(NEG_BIG - NEG_BIG) would be exp(0); masked probabilities are
# re-zeroed explicitly, see below)


def _block_attend(q, k, v, m, l, o, allowed, scale):
    """One online-softmax accumulation step against a K/V block.

    q: [T, H, D]; k/v: [S, H, D]; m/l: [T, H] running max / normalizer;
    o: [T, H, D] running (unnormalized) output; allowed: [T, S] bool.
    Returns updated (m, l, o). All math in f32 for stability.
    """
    logits = jnp.einsum(
        "thd,shd->ths", q, k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale  # [T, H, S]
    ok = allowed[:, None, :]  # [T, 1, S]
    logits = jnp.where(ok, logits, NEG_BIG)
    m_new = jnp.maximum(m, logits.max(axis=-1))  # [T, H]
    # alpha rescales the running state; exp() of (NEG_BIG - NEG_BIG) = 1 is
    # fine for alpha (state is all zeros then)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None]) * ok  # masked entries -> exactly 0
    l = l * alpha + p.sum(axis=-1)
    o = o * alpha[..., None] + jnp.einsum(
        "ths,shd->thd", p, v.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    return m_new, l, o


def ring_attention(
    q: jax.Array,  # [T_loc, H, D] this shard's queries
    k: jax.Array,  # [T_loc, H, D] this shard's keys
    v: jax.Array,  # [T_loc, H, D] this shard's values
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,  # [T_loc] 1.0 = real position
) -> jax.Array:
    """Exact attention over the full (sharded) sequence, computed blockwise
    with K/V rotating around the ring. Call inside ``shard_map`` with the
    sequence dimension sharded over ``axis_name``.

    Global position of local row i on rank r is ``r * T_loc + i`` (the
    natural contiguous-block sharding); ``causal=True`` masks with those
    global positions, so the result equals dense causal attention on the
    gathered sequence. Padded tail positions (ragged sequences) are
    excluded via ``kv_mask``.

    Returns [T_loc, H, D] in q's dtype.
    """
    T, H, D = q.shape
    W = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / math.sqrt(D)

    qf = q.astype(jnp.float32)
    # constants must be marked device-varying over the ring axis or the
    # scan carry types mismatch (shard_map varying-axis tracking)
    vary = lambda t: lax.pcast(t, axis_name, to="varying")
    m0 = vary(jnp.full((T, H), NEG_BIG, jnp.float32))
    l0 = vary(jnp.zeros((T, H), jnp.float32))
    o0 = vary(jnp.zeros((T, H, D), jnp.float32))
    had_mask = kv_mask is not None
    if kv_mask is None:
        kv_mask = vary(jnp.ones((T,), jnp.float32))

    q_pos = me * T + jnp.arange(T)  # [T] global query positions

    # jax.checkpoint: AD through the scan would otherwise SAVE every
    # step's [T, H, S] probability block (O(W * T^2 * H / W) — the exact
    # memory wall ring attention exists to avoid); rematerializing the
    # block math in the backward keeps saved state at the O(T/W) carries.
    @jax.checkpoint
    def attend(m, l, o, k_blk, v_blk, mask_blk, s):
        # the block we hold at step s originated on rank (me - s) mod W
        src = (me - s) % W
        k_pos = src * T + jnp.arange(T)  # [S] global key positions
        allowed = mask_blk[None, :] > 0
        if causal:
            allowed = allowed & (k_pos[None, :] <= q_pos[:, None])
        return _block_attend(qf, k_blk, v_blk, m, l, o, allowed, scale)

    def step(carry, s):
        m, l, o, k_blk, v_blk, mask_blk = carry
        if causal:
            # a block strictly in the query shard's future contributes
            # nothing; skip its FLOPs entirely (on W ranks, (W-1)/2W of
            # all ring-step blocks — the causal load-imbalance half)
            src = (me - s) % W
            m, l, o = lax.cond(
                src > me,
                lambda *a: a[:3],
                attend,
                m, l, o, k_blk, v_blk, mask_blk, s,
            )
        else:
            m, l, o = attend(m, l, o, k_blk, v_blk, mask_blk, s)
        # rotate K/V/mask to the next rank (one ICI neighbor hop)
        perm = [(i, (i + 1) % W) for i in range(W)]
        k_blk, v_blk, mask_blk = (
            lax.ppermute(t, axis_name, perm) for t in (k_blk, v_blk, mask_blk)
        )
        return (m, l, o, k_blk, v_blk, mask_blk), None

    # K/V rotate in their INPUT dtype (bf16 halves the per-hop ICI bytes and
    # the scan-carry memory); _block_attend upcasts per block, so numerics
    # are unchanged
    (m, l, o, _, _, _), _ = lax.scan(
        step, (m0, l0, o0, k, v, kv_mask), jnp.arange(W)
    )
    # fully-masked rows (all-padding shard under kv_mask) have l == 0
    out = o / jnp.maximum(l, 1e-30)[..., None]
    if had_mask:
        # kv_mask here is the un-rotated local shard mask = this shard's
        # query-row mask
        out = _zero_padded_rows(out, kv_mask)
    return out.astype(q.dtype)


def _zero_padded_rows(out: jax.Array, kv_mask: jax.Array) -> jax.Array:
    """The contract every attention impl shares (dense/ring/ulysses/flash):
    PADDED QUERY ROWS ARE ZERO, so full-tensor outputs agree across
    implementations instead of diverging on don't-care rows (ADVICE r3 #2).
    ``out`` is [T, H, D]; ``kv_mask`` is the [T] query-position mask."""
    return out * (kv_mask > 0).astype(out.dtype)[:, None, None]


def dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    causal: bool = False, scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Single-device oracle: softmax(q k^T) v over the FULL sequence
    ([T, H, D] inputs). The equivalence target for :func:`ring_attention`
    (tests/test_sequence.py) and the small-sequence fallback."""
    T, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    logits = jnp.einsum(
        "thd,shd->ths", q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * scale
    allowed = jnp.ones((T, T), bool) if kv_mask is None else (kv_mask[None, :] > 0)
    if causal:
        allowed = allowed & (
            jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]
        )
    logits = jnp.where(allowed[:, None, :], logits, NEG_BIG)  # bcast to heads
    p = jax.nn.softmax(logits, axis=-1)
    p = p * allowed[:, None, :]
    out = jnp.einsum("ths,shd->thd", p, v.astype(jnp.float32))
    if kv_mask is not None:
        out = _zero_padded_rows(out, kv_mask)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,  # [T_loc, H, D]
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,  # [T_loc]
) -> jax.Array:
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses layout swap):
    one ``all_to_all`` re-shards [T/W, H, D] -> [T, H/W, D] (full sequence,
    subset of heads), dense attention runs per head with NO inner-loop
    communication, and a second ``all_to_all`` restores sequence sharding.

    vs the ring: 2 big collectives + O(T) memory/device instead of W
    neighbor hops + O(T/W) memory. The ring wins at long context (memory)
    and maps onto ICI neighbor links; Ulysses wins when heads are plentiful
    and T fits — both are exact. Requires H divisible by the axis size.
    Same contract as :func:`ring_attention` (call inside shard_map,
    contiguous-block sequence sharding).
    """
    H = q.shape[1]
    W = lax.psum(1, axis_name)  # static (mesh axis size)
    if H % W:
        raise ValueError(
            f"ulysses_attention needs heads ({H}) divisible by the "
            f"{axis_name!r} axis size ({W}); use ring_attention otherwise"
        )

    def seq_to_head(x):  # [T_loc, H, D] -> [W*T_loc, H/W, D]
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=0, tiled=True
        )

    def head_to_seq(x):  # [W*T_loc, H/W, D] -> [T_loc, H, D]
        return lax.all_to_all(
            x, axis_name, split_axis=0, concat_axis=1, tiled=True
        )

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    if kv_mask is None:
        mask_full = None
    else:
        # every device needs the FULL-sequence mask once heads are sharded
        mask_full = lax.all_gather(kv_mask, axis_name, tiled=True)
    if _flash_applicable(qh):
        out = _flash_dense(qh, kh, vh, causal=causal, scale=scale,
                           kv_mask=mask_full)
        return head_to_seq(out)
    out = dense_attention(
        qh, kh, vh, causal=causal, scale=scale, kv_mask=mask_full
    )
    return head_to_seq(out)


def _flash_applicable(qh: jax.Array, *, require_pinned: bool = False) -> bool:
    """Use the Mosaic flash-attention kernel for a full-sequence dense
    attention site?

    Trace-time decision: config tri-state (``DGRAPH_TPU_FLASH_ATTN``) +
    shape constraints of the TPU kernel (T a multiple of its 128 query
    block, head_dim lane-friendly). ``require_pinned=True`` (the
    single-comm ORACLE site) engages only on an explicit config True —
    never on auto — so an unverified Mosaic kernel can't silently replace
    the dense reference that parity harnesses compare against.
    """
    from dgraph_tpu import config as _cfg

    if jax.default_backend() != "tpu":
        return False  # the kernel is Mosaic-only; a pinned flag on CPU
        # must not trace it (every other Pallas gate has this check)
    pinned = _cfg.use_flash_attention is True
    if require_pinned and not pinned:
        return False
    if not pinned and not (
        _cfg.flash_attention_enabled() and _flash_verified
    ):
        # auto engages only after a chip self-check latched success this
        # process (the scatter kernels' central-veto discipline); an
        # explicit pinned True is the operator's override
        return False
    T, _, D = qh.shape
    return T % 128 == 0 and D % 128 == 0


def _flash_dense(qh, kh, vh, *, causal, scale, kv_mask):
    """[T, H_loc, D] full-sequence attention via
    ``jax.experimental.pallas.ops.tpu.flash_attention`` (forward AND
    backward are Mosaic kernels with their own custom VJP — memory stays
    O(T * block) instead of the [T, H, T] logits tensor). Padded tail
    positions are excluded by giving them a second segment id."""
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    T, H, D = qh.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    # kernel layout: [batch, heads, T, D]
    to_k = lambda x: x.transpose(1, 0, 2)[None]
    seg = None
    if kv_mask is not None:
        ids = (kv_mask <= 0).astype(jnp.int32)[None]  # padding -> segment 1
        seg = fa.SegmentIds(q=ids, kv=ids)
    out = fa.flash_attention(
        to_k(qh), to_k(kh), to_k(vh), segment_ids=seg, causal=causal,
        sm_scale=float(scale),
    )
    res = out[0].transpose(1, 0, 2)
    if kv_mask is not None:
        # without this the flash path's padded rows attend the padding
        # SEGMENT while the dense oracle's attend real keys
        res = _zero_padded_rows(res, kv_mask)
    return res.astype(qh.dtype)


# Auto-mode flash engages only after flash_attention_selfcheck() passes
# in this process (pinned config True bypasses — operator override).
_flash_verified = False


def flash_attention_selfcheck() -> bool:
    """Chip-gated equivalence check vs :func:`dense_attention` (the same
    Mosaic-divergence rationale as bench.py's scatter self-checks: the
    kernel class is invisible to CPU CI). Passing LATCHES auto-mode flash
    on for this process; returns False off-TPU.
    """
    global _flash_verified
    import numpy as np

    if jax.default_backend() != "tpu":
        return False
    rng = np.random.default_rng(3)
    T, H, D = 256, 2, 128
    q, k, v = (
        jnp.asarray(rng.standard_normal((T, H, D)), jnp.bfloat16)
        for _ in range(3)
    )
    mask = jnp.asarray((np.arange(T) < T - 32).astype(np.float32))
    try:
        for causal in (False, True):
            got = _flash_dense(q, k, v, causal=causal, scale=None,
                               kv_mask=mask)
            want = dense_attention(q, k, v, causal=causal, kv_mask=mask)
            real = np.asarray(mask) > 0
            if not np.allclose(
                np.asarray(got, np.float32)[real],
                np.asarray(want, np.float32)[real], rtol=5e-2, atol=5e-2,
            ):
                return False
    except Exception:
        return False
    _flash_verified = True
    return True


def ring_attention_sharded(
    q: jax.Array,  # [T, H, D] FULL sequence (host/global view)
    k: jax.Array,
    v: jax.Array,
    mesh: jax.sharding.Mesh,
    axis_name: str = "seq",
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,  # [T] 1.0 = real position
) -> jax.Array:
    """Convenience wrapper: shard the sequence dim over ``mesh[axis_name]``
    and run :func:`ring_attention` under ``shard_map``. T must divide by
    the axis size; ragged sequences pad T upstream and mark real positions
    in ``kv_mask`` (static shapes are the contract everywhere in this
    framework)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    W = mesh.shape[axis_name]
    if q.shape[0] % W:
        raise ValueError(
            f"sequence length {q.shape[0]} not divisible by {axis_name}={W}"
        )
    if kv_mask is None:
        kv_mask = jnp.ones((q.shape[0],), jnp.float32)
    from dgraph_tpu.comm.collectives import shard_map_checks

    fn = shard_map(
        lambda q, k, v, m: ring_attention(
            q, k, v, axis_name, causal=causal, scale=scale, kv_mask=m
        ),
        mesh=mesh,
        in_specs=(P(axis_name),) * 4,
        out_specs=P(axis_name),
        # audited (ISSUE 12): the blanket RELAXED_CHECKS splat is the
        # routed escape now — out is fully sharded, so the rep checker
        # protects nothing here, and 0.4.x's raises a false cond-branch
        # mismatch when AD re-traces the causal lax.cond
        **shard_map_checks(relax="ring-attention causal cond false "
                                 "positive under AD; out fully sharded"),
    )
    return fn(q, k, v, kv_mask)
