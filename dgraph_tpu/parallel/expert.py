"""Expert parallelism: top-1 token-dispatch mixture-of-experts over a mesh
axis.

Beyond-reference (SURVEY.md §2.3 lists expert parallelism as absent in the
reference). One expert lives on each rank of an ``expert`` axis; a learned
router picks an expert per token; tokens travel to their expert and back
with the SAME padded ``all_to_all`` discipline as the halo exchange
(static per-peer capacity, masked overflow) — XLA's compile-once model
wants fixed shapes, so the classic "capacity factor" of production MoE
layers is the exact analogue of this framework's ``s_pad`` halo padding.

Dispatch math is all segment/one-hot primitives already used by the graph
side: position-within-expert via a cumulative sum over the one-hot routing
matrix, inverse routing by scatter into the dispatch slots' origin rows.
Differentiable end to end (routing probabilities scale the expert outputs
— the standard top-1 switch estimator; the all_to_all transposes are
all_to_alls).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def top1_dispatch(
    x: jax.Array,  # [T, F] this shard's tokens
    router_logits: jax.Array,  # [T, E] router scores (E = axis size)
    capacity: int,  # per-(src shard -> expert) slot budget (static)
    axis_name: str,
):
    """Route each token to its argmax expert; returns everything the
    combine step needs.

    Returns (expert_in, combine): ``expert_in`` [W*capacity, F] — the
    tokens THIS rank's expert must process (from every peer, peer p's
    block at rows [p*capacity, (p+1)*capacity)); ``combine(expert_out)``
    scatters processed rows back to their origin tokens, scaled by the
    router probability (zeros for dropped/overflow tokens).
    """
    T, F = x.shape
    E = lax.psum(1, axis_name)
    if router_logits.shape[-1] != E:  # both static under shard_map
        raise ValueError(
            f"router width {router_logits.shape[-1]} != expert-axis size "
            f"{E}: out-of-range expert ids would be silently dropped"
        )
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]  # [T]

    # position of each token within its expert's send block (one-hot cumsum
    # — same trick as the plan builder's slot numbering, done in-jit)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # [T, E]
    pos = (jnp.cumsum(onehot, axis=0) - 1)[jnp.arange(T), expert]  # [T]
    keep = pos < capacity  # overflow tokens are dropped (capacity factor)

    # build the per-expert send buffer [E, capacity, F]
    slot = jnp.where(keep, expert * capacity + pos, E * capacity)
    send = jnp.zeros((E * capacity, F), x.dtype).at[slot].set(
        x, mode="drop"
    ).reshape(E, capacity, F)
    # tokens land on their expert's rank, peer blocks in rank order — the
    # halo-exchange landing discipline
    expert_in = lax.all_to_all(
        send, axis_name, split_axis=0, concat_axis=0
    ).reshape(E * capacity, F)

    def combine(expert_out: jax.Array) -> jax.Array:  # [W*capacity, F']
        back = lax.all_to_all(
            expert_out.reshape(E, capacity, -1), axis_name,
            split_axis=0, concat_axis=0,
        ).reshape(E * capacity, -1)
        rows = jnp.take(back, jnp.minimum(slot, E * capacity - 1), axis=0)
        rows = jnp.where(keep[:, None], rows, 0.0)
        # scale by the router prob: the top-1 switch gradient estimator —
        # the router learns through this product
        return rows * gate[:, None].astype(rows.dtype)

    return expert_in, combine


def moe_apply(
    x: jax.Array,  # [T, F] this shard's tokens
    router_logits: jax.Array,  # [T, E]
    expert_fn: Callable,  # (params, [N, F]) -> [N, F'] THIS rank's expert
    expert_params,
    capacity: int,
    axis_name: str,
) -> jax.Array:
    """Full top-1 MoE layer: dispatch -> local expert -> combine.

    ONE ``all_to_all`` each way — two per layer, the textbook MoE cost;
    overflow beyond ``capacity`` per (shard, expert) pair contributes zeros (route
    a residual around the layer upstream, as switch transformers do).
    """
    expert_in, combine = top1_dispatch(x, router_logits, capacity, axis_name)
    return combine(expert_fn(expert_params, expert_in))


def load_balance_loss(router_logits: jax.Array, axis_name: str) -> jax.Array:
    """Switch-transformer auxiliary loss: E * Σ_e (frac_tokens_e ·
    mean_prob_e), psum-averaged over the axis. Add to the task loss to keep
    routing spread across experts."""
    E = lax.psum(1, axis_name)
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    frac = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, -1), E, dtype=jnp.float32), axis=0
    )
    mean_p = probs.mean(axis=0)
    frac = lax.pmean(frac, axis_name)
    mean_p = lax.pmean(mean_p, axis_name)
    return E * jnp.sum(frac * mean_p)
