"""Expert parallelism: top-k token-dispatch mixture-of-experts over a mesh
axis (k=1 switch routing and k>=2 GShard/Mixtral-style mixtures).

Beyond-reference (SURVEY.md §2.3 lists expert parallelism as absent in the
reference). One expert lives on each rank of an ``expert`` axis; a learned
router picks an expert per token; tokens travel to their expert and back
with the SAME padded ``all_to_all`` discipline as the halo exchange
(static per-peer capacity, masked overflow) — XLA's compile-once model
wants fixed shapes, so the classic "capacity factor" of production MoE
layers is the exact analogue of this framework's ``s_pad`` halo padding.

Dispatch math is all segment/one-hot primitives already used by the graph
side: position-within-expert via a cumulative sum over the one-hot routing
matrix (choice-major, so 1st choices claim capacity first), inverse
routing by scatter into the dispatch slots' origin rows. Differentiable
end to end — the router learns through the gate product: raw softmax
probability at k=1 (the switch estimator), renormalized top-k gates at
k>1 (GShard/Mixtral); the all_to_all transposes are all_to_alls.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def topk_dispatch(
    x: jax.Array,  # [T, F] this shard's tokens
    router_logits: jax.Array,  # [T, E] router scores (E = axis size)
    capacity: int,  # per-(src shard -> expert) slot budget (static)
    axis_name: str,
    *,
    k: int = 2,
    normalize_gates: bool = True,
):
    """Route each token to its top-k experts; returns everything the
    combine step needs.

    Slot assignment is CHOICE-MAJOR: every token's 1st choice claims
    capacity before any 2nd choice does (the GShard priority rule), so
    under pressure the layer degrades toward top-1 rather than dropping
    primary routes. ``normalize_gates=True`` renormalizes the selected
    gates to sum to 1 per token (the GShard/Mixtral convention);
    ``False`` keeps raw softmax probabilities (the top-1 switch
    estimator uses this).

    Returns (expert_in, combine): ``expert_in`` [E*capacity, F] — the
    tokens THIS rank's expert must process (peer p's block at rows
    [p*capacity, (p+1)*capacity)); ``combine(expert_out)`` returns each
    token's gate-weighted SUM over its k expert outputs (zeros for
    dropped/overflow routes).
    """
    T, F = x.shape
    E = lax.psum(1, axis_name)
    if router_logits.shape[-1] != E:  # both static under shard_map
        raise ValueError(
            f"router width {router_logits.shape[-1]} != expert-axis size "
            f"{E}: out-of-range expert ids would be silently dropped"
        )
    if not 1 <= k <= E:
        raise ValueError(f"top-k k={k} must be in [1, {E}]")
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gates_k, experts_k = lax.top_k(probs, k)  # [T, k] each
    if normalize_gates:
        gates_k = gates_k / jnp.maximum(
            gates_k.sum(axis=-1, keepdims=True), 1e-20)

    # flatten routes CHOICE-major: row c*T + t = token t's c-th choice
    ec = experts_k.T.reshape(k * T)  # [k*T]
    gc = gates_k.T.reshape(k * T)
    # position of each route within its expert's send block (one-hot
    # cumsum — the plan builder's slot numbering, done in-jit)
    onehot = jax.nn.one_hot(ec, E, dtype=jnp.int32)  # [k*T, E]
    pos = (jnp.cumsum(onehot, axis=0) - 1)[jnp.arange(k * T), ec]
    keep = pos < capacity  # overflow routes are dropped (capacity factor)

    # build the per-expert send buffer [E, capacity, F]; distinct routes
    # always land in distinct slots, so the scatter has no conflicts
    slot = jnp.where(keep, ec * capacity + pos, E * capacity)
    x_rep = jnp.tile(x, (k, 1))  # choice-major replication
    send = jnp.zeros((E * capacity, F), x.dtype).at[slot].set(
        x_rep, mode="drop"
    ).reshape(E, capacity, F)
    # tokens land on their expert's rank, peer blocks in rank order — the
    # halo-exchange landing discipline
    expert_in = lax.all_to_all(
        send, axis_name, split_axis=0, concat_axis=0
    ).reshape(E * capacity, F)

    def combine(expert_out: jax.Array) -> jax.Array:  # [E*capacity, F']
        back = lax.all_to_all(
            expert_out.reshape(E, capacity, -1), axis_name,
            split_axis=0, concat_axis=0,
        ).reshape(E * capacity, -1)
        rows = jnp.take(back, jnp.minimum(slot, E * capacity - 1), axis=0)
        rows = jnp.where(keep[:, None], rows, 0.0)
        # scale by the router gate: the router learns through this
        # product (switch estimator at k=1; weighted mixture at k>1)
        rows = rows * gc[:, None].astype(rows.dtype)
        return rows.reshape(k, T, -1).sum(axis=0)

    return expert_in, combine


def top1_dispatch(
    x: jax.Array,
    router_logits: jax.Array,
    capacity: int,
    axis_name: str,
):
    """Top-1 switch routing = :func:`topk_dispatch` with k=1 and RAW
    softmax gates (the switch gradient estimator)."""
    return topk_dispatch(
        x, router_logits, capacity, axis_name, k=1, normalize_gates=False
    )


def moe_apply(
    x: jax.Array,  # [T, F] this shard's tokens
    router_logits: jax.Array,  # [T, E]
    expert_fn: Callable,  # (params, [N, F]) -> [N, F'] THIS rank's expert
    expert_params,
    capacity: int,
    axis_name: str,
    *,
    k: int = 1,
    normalize_gates: bool | None = None,
) -> jax.Array:
    """Full MoE layer: dispatch -> local expert -> combine.

    ONE ``all_to_all`` each way — two per layer regardless of k (the
    routes multiplex into the same padded buffers); overflow beyond
    ``capacity`` per (shard, expert) pair contributes zeros (route a
    residual around the layer upstream, as switch transformers do).
    k=1 keeps the raw-probability switch estimator; k>1 defaults to
    gate renormalization (GShard/Mixtral) unless overridden.
    """
    if normalize_gates is None:
        normalize_gates = k > 1
    expert_in, combine = topk_dispatch(
        x, router_logits, capacity, axis_name, k=k,
        normalize_gates=normalize_gates,
    )
    return combine(expert_fn(expert_params, expert_in))


def load_balance_loss(router_logits: jax.Array, axis_name: str) -> jax.Array:
    """Switch-transformer auxiliary loss: E * Σ_e (frac_tokens_e ·
    mean_prob_e), psum-averaged over the axis. Add to the task loss to keep
    routing spread across experts."""
    E = lax.psum(1, axis_name)
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    frac = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, -1), E, dtype=jnp.float32), axis=0
    )
    mean_p = probs.mean(axis=0)
    frac = lax.pmean(frac, axis_name)
    mean_p = lax.pmean(mean_p, axis_name)
    return E * jnp.sum(frac * mean_p)
