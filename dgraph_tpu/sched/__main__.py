"""Schedule-compiler selftest CLI (compile-free, jax-free).

``python -m dgraph_tpu.sched --selftest true`` proves on fixed fixture
matrices, with zero XLA compiles and without importing jax:

- IR round-trip: to_dict -> JSON -> from_dict is identity, and
  ``schedule_id`` is stable across the trip (the equality the SPMD
  auditor and the ledger's byte-exact gate key on);
- pass-pipeline invariants: every compiled fixture verifies clean,
  conflict-freedom and exact pair coverage hold, a skewed hub pair is
  recursive-doubling split while a uniform matrix compiles unsplit,
  and compilation is deterministic (same matrix -> same id);
- vacuity mutants: a hand-built conflicting round and a dropped
  transfer must each turn :func:`~dgraph_tpu.sched.ir.verify_schedule`
  RED — a verifier that cannot fail proves nothing.

Wired as a ``scripts/check.py`` pass next to the other jsonified
selftests.
"""

from __future__ import annotations

import dataclasses
import json
import sys

from dgraph_tpu.sched.ir import (
    HaloSchedule,
    Round,
    Transfer,
    verify_schedule,
)
from dgraph_tpu.sched.passes import compile_halo_schedule

# Fixture traffic matrices: name -> (pair_rows, s_pad).
_FIXTURES = {
    # uniform 4-rank ring: every off-diagonal neighbour pair live
    "uniform_ring": (
        ((0, 5, 0, 5), (5, 0, 5, 0), (0, 5, 0, 5), (5, 0, 5, 0)),
        8,
    ),
    # the motivating skew: one hub-heavy pair among tiny ones
    "skewed_hub": (
        ((0, 64, 1, 2), (1, 0, 1, 0), (2, 1, 0, 1), (0, 2, 1, 0)),
        64,
    ),
    # dense all-pairs
    "dense": (
        ((0, 3, 4, 2), (3, 0, 2, 4), (4, 2, 0, 3), (2, 4, 3, 0)),
        6,
    ),
    # two ranks, one direction
    "one_way_pair": (((0, 7), (0, 0)), 8),
    # no traffic at all
    "empty": (((0, 0), (0, 0)), 4),
}


def _selftest() -> dict:
    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    jax_preloaded = "jax" in sys.modules

    for name, (rows, s_pad) in _FIXTURES.items():
        sched = compile_halo_schedule(rows, s_pad=s_pad)
        check(verify_schedule(sched, rows) == [],
              f"{name}: compiled schedule fails its own verifier")
        # round-trip: dict -> JSON -> dict -> object is identity
        wire = json.loads(json.dumps(sched.to_dict()))
        back = HaloSchedule.from_dict(wire)
        check(back == sched, f"{name}: JSON round-trip lost structure")
        check(back.schedule_id == sched.schedule_id,
              f"{name}: schedule_id unstable across round-trip")
        # determinism: recompile -> identical id
        check(compile_halo_schedule(rows, s_pad=s_pad).schedule_id
              == sched.schedule_id,
              f"{name}: compilation is not deterministic")
        total = sum(v for row in rows for v in row)
        check(sum(t.row_count for r in sched.rounds for t in r.transfers)
              == total,
              f"{name}: scheduled rows != live rows (coverage leak)")

    # empty matrix -> empty schedule (halo_impl='none' territory)
    check(compile_halo_schedule(_FIXTURES["empty"][0],
                                s_pad=4).num_rounds == 0,
          "empty matrix compiled to non-empty schedule")

    # skew invariant: the 64-row hub pair must be split (several chunks)
    # and must NOT drag every round's padded height to hub size
    hub_rows, hub_s = _FIXTURES["skewed_hub"]
    hub = compile_halo_schedule(hub_rows, s_pad=hub_s)
    hub_chunks = [t for r in hub.rounds for t in r.transfers
                  if (t.src, t.dst) == (0, 1)]
    check(len(hub_chunks) > 1,
          "skewed hub pair was not recursive-doubling split")
    check(min(hub.round_rows()) < 64,
          "every round inherited hub height — small pairs not merged "
          "into cheaper rounds")

    # uniform matrix must compile unsplit: one transfer per live pair
    uni_rows, uni_s = _FIXTURES["uniform_ring"]
    uni = compile_halo_schedule(uni_rows, s_pad=uni_s)
    check(uni.num_transfers
          == sum(1 for row in uni_rows for v in row if v),
          "uniform matrix was split (threshold not skew-relative)")

    # explicit threshold is honoured
    forced = compile_halo_schedule(uni_rows, s_pad=uni_s,
                                   split_threshold=2)
    check(all(t.row_count <= 2 for r in forced.rounds
              for t in r.transfers),
          "explicit split_threshold not honoured")

    # --- vacuity mutants: the verifier must be able to go RED --------
    rows2 = ((0, 4, 3, 0), (2, 0, 0, 0), (0, 0, 0, 0), (0, 0, 0, 0))
    # mutant 1: conflicting round — rank 0 sends twice in one round
    conflict = HaloSchedule(world_size=4, s_pad=4, rounds=(
        Round(transfers=(Transfer(0, 1, 0, 4), Transfer(0, 2, 0, 3))),
        Round(transfers=(Transfer(1, 0, 0, 2),)),
    ))
    check(any("sends twice" in f for f in verify_schedule(conflict, rows2)),
          "vacuity: conflicting round (double sender) not flagged RED")
    # mutant 1b: double receiver
    conflict_rx = HaloSchedule(world_size=4, s_pad=4, rounds=(
        Round(transfers=(Transfer(0, 1, 0, 4), Transfer(2, 1, 0, 1))),
        Round(transfers=(Transfer(1, 0, 0, 2), Transfer(0, 2, 0, 3))),
    ))
    rows2b = ((0, 4, 3, 0), (2, 0, 0, 0), (0, 1, 0, 0), (0, 0, 0, 0))
    check(any("receives twice" in f
              for f in verify_schedule(conflict_rx, rows2b)),
          "vacuity: conflicting round (double receiver) not flagged RED")
    # mutant 2: dropped transfer — the 1->0 block never ships
    dropped = HaloSchedule(world_size=4, s_pad=4, rounds=(
        Round(transfers=(Transfer(0, 1, 0, 4),)),
        Round(transfers=(Transfer(0, 2, 0, 3),)),
    ))
    check(any("uncovered" in f for f in verify_schedule(dropped, rows2)),
          "vacuity: dropped transfer not flagged RED")
    # mutant 3: double-covered rows (reverse reduce would double-count)
    doubled = HaloSchedule(world_size=4, s_pad=4, rounds=(
        Round(transfers=(Transfer(0, 1, 0, 4),)),
        Round(transfers=(Transfer(0, 1, 2, 2), Transfer(1, 0, 0, 2))),
        Round(transfers=(Transfer(0, 2, 0, 3),)),
    ))
    check(any("covered twice" in f for f in verify_schedule(doubled, rows2)),
          "vacuity: double-covered rows not flagged RED")
    # mutant 4: ragged matrix rejected loudly, not truncated silently
    try:
        compile_halo_schedule(((0, 1), (1, 0, 0)), s_pad=2)
        failures.append("vacuity: ragged pair_rows accepted")
    except ValueError:
        pass

    # the compiler core must run without pulling jax in (lint enforces
    # the import graph; this pins the runtime fact when we own the
    # process — under pytest jax may already be resident, skip then)
    if not jax_preloaded:
        check("jax" not in sys.modules,
              "selftest imported jax — compiler core is not jax-free")

    return {"kind": "sched_selftest", "fixtures": sorted(_FIXTURES),
            "failures": failures, "ok": not failures}


@dataclasses.dataclass
class Config:
    """Schedule-compiler CLI: ``--selftest true`` runs the compile-free
    invariant + vacuity-mutant suite; exit 1 on any failure."""

    selftest: bool = False
    indent: int = 0


def main(cfg: Config) -> None:
    if not cfg.selftest:
        print(__doc__)
        return
    out = _selftest()
    print(json.dumps(out, indent=cfg.indent or None))
    if out["failures"]:
        raise SystemExit(1)


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
