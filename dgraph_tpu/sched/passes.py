"""Pass pipeline: traffic matrix -> verified multi-round HaloSchedule.

The compiler proper. Input is the EdgePlan's static rank-to-rank
traffic matrix ``pair_rows[src][dst]`` (deduped live halo rows the
plan packs into the (src -> dst) send block) plus the slot height
``s_pad``; output is a :class:`~dgraph_tpu.sched.ir.HaloSchedule` that
:func:`~dgraph_tpu.sched.ir.verify_schedule` accepts against the same
matrix. Three passes, in order:

1. **normalize** — one :class:`~dgraph_tpu.sched.ir.Transfer` per live
   pair, covering rows ``[0, count)``. Dead pairs (zero rows, incl. the
   diagonal) emit nothing: this is the delta-skip the fixed lowerings
   can't express per-pair (all_to_all ships every block dense; a
   ppermute ring ships a full [S] operand for every rank on the ring
   even when only one pair is live).
2. **split** — recursive-doubling decomposition ("The Big Send-off",
   PAPERS.md): any transfer wider than the split threshold is halved
   recursively, so one hub-heavy pair becomes several round-sized
   chunks that pack alongside the small pairs instead of forcing every
   round's padded operand to hub height. Default threshold: twice the
   median live pair count (skew-relative — a uniform matrix never
   splits), floor 1.
3. **pack + order** — greedy first-fit-decreasing into conflict-free
   rounds (no rank twice as src or twice as dst per round; chunk must
   fit under the round's padded height C inside ``s_pad``), then rounds
   ordered by descending estimated ICI load ``C * len(transfers)`` so
   the heavy rounds issue first and the serial tail is the cheap tail
   (mirrors the overlap executor's absorb-behind-interior story, which
   footprint prices per-round).

Everything is deterministic pure-stdlib arithmetic on ints — ties break
on (src, dst, row_start) — so every rank compiling the same full-world
matrix gets the byte-identical schedule (same ``schedule_id``), which is
what makes attach-at-plan-build safe under SPMD: rank-divergent round
order is the deadlock class the issue-sequence auditor checks.
"""

from __future__ import annotations

from dgraph_tpu.sched.ir import (
    HaloSchedule,
    Round,
    Transfer,
    normalize_pair_rows,
    verify_schedule,
)


def normalize_transfers(pair_rows) -> list:
    """Pass 1: one whole-pair Transfer per live (src, dst), rows
    ``[0, count)``; dead pairs emit nothing."""
    out = []
    for src, row in enumerate(pair_rows):
        for dst, count in enumerate(row):
            if count > 0 and src != dst:
                out.append(Transfer(src=src, dst=dst, row_start=0,
                                    row_count=int(count)))
    return out


def default_split_threshold(transfers: list) -> int:
    """Twice the median live row count: skew-relative, so a uniform
    matrix compiles unsplit while one hub pair among small ones is
    chopped down to ride the small rounds."""
    counts = sorted(t.row_count for t in transfers)
    if not counts:
        return 1
    median = counts[len(counts) // 2]
    return max(1, 2 * median)


def split_transfers(transfers: list, threshold: int) -> list:
    """Pass 2: recursively halve any transfer wider than ``threshold``.
    Halving (not fixed-size chunking) keeps the chunk count a power of
    two per pair and the chunk sizes within 1 row of each other."""
    out = []

    def rec(t: Transfer):
        if t.row_count <= threshold:
            out.append(t)
            return
        half = t.row_count // 2
        rec(Transfer(t.src, t.dst, t.row_start, half))
        rec(Transfer(t.src, t.dst, t.row_start + half, t.row_count - half))

    for t in transfers:
        rec(t)
    return out


def pack_rounds(transfers: list, s_pad: int) -> list:
    """Pass 3a: first-fit-decreasing into conflict-free rounds.

    Sorted descending by row_count, each round's padded height C is set
    by its first (largest) member, so the fit check for a later chunk is
    only ``row_start + C <= s_pad`` (its own rows always fit under C)
    plus src/dst conflict-freedom. FFD keeps same-height chunks of a
    split hub pair in consecutive rounds while small pairs fill the
    leftover src/dst slots of every round — the merge the issue asks
    for.
    """
    order = sorted(transfers,
                   key=lambda t: (-t.row_count, t.src, t.dst, t.row_start))
    rounds = []  # each: {"C": int, "srcs": set, "dsts": set, "ts": list}
    for t in order:
        placed = False
        for r in rounds:
            if (t.src not in r["srcs"] and t.dst not in r["dsts"]
                    and t.row_start + r["C"] <= s_pad):
                r["srcs"].add(t.src)
                r["dsts"].add(t.dst)
                r["ts"].append(t)
                placed = True
                break
        if not placed:
            rounds.append({"C": t.row_count, "srcs": {t.src},
                           "dsts": {t.dst}, "ts": [t]})
    return rounds


def order_rounds(rounds: list) -> tuple:
    """Pass 3b: heaviest estimated ICI load first (``C * transfers``),
    deterministic tie-break on the round's sorted transfer keys."""

    def key(r):
        ts = sorted(r["ts"], key=lambda t: (t.src, t.dst, t.row_start))
        return (-r["C"] * len(ts),
                tuple((t.src, t.dst, t.row_start) for t in ts))

    out = []
    for r in sorted(rounds, key=key):
        ts = sorted(r["ts"], key=lambda t: (t.src, t.dst, t.row_start))
        out.append(Round(transfers=tuple(ts)))
    return tuple(out)


def compile_halo_schedule(pair_rows, *, s_pad: int,
                          world_size: int = None,
                          split_threshold: int = None) -> HaloSchedule:
    """The full pipeline; the result is verified against ``pair_rows``
    before return, so a compiler bug is a loud ValueError at plan build,
    never a silently-dropped halo block at exchange time."""
    rows = normalize_pair_rows(pair_rows, world_size)
    W = len(rows)
    transfers = normalize_transfers(rows)
    if transfers:
        threshold = (split_threshold if split_threshold is not None
                     else default_split_threshold(transfers))
        threshold = min(threshold, int(s_pad))
        transfers = split_transfers(transfers, max(1, threshold))
    schedule = HaloSchedule(
        world_size=W,
        s_pad=int(s_pad),
        rounds=order_rounds(pack_rounds(transfers, int(s_pad))),
    )
    failures = verify_schedule(schedule, rows)
    if failures:
        raise ValueError(
            "compile_halo_schedule produced an unverifiable schedule "
            f"(compiler bug): {failures[:5]}"
        )
    return schedule
