"""Halo schedule compiler: EdgePlan traffic matrix -> verified
multi-round collective schedules (ROADMAP item 2, a GC3 for the halo).

jax-free by the lint-enforced contract — the IR and passes import
cleanly where jax is absent; the jax-side round executor lives in
:mod:`dgraph_tpu.comm.collectives` and replays the schedule under
``halo_impl="sched"``.
"""

from dgraph_tpu.sched.ir import (
    SCHED_IR_VERSION,
    HaloSchedule,
    Round,
    Transfer,
    normalize_pair_rows,
    verify_schedule,
)
from dgraph_tpu.sched.passes import (
    compile_halo_schedule,
    default_split_threshold,
    normalize_transfers,
    pack_rounds,
    split_transfers,
)

__all__ = [
    "SCHED_IR_VERSION",
    "HaloSchedule",
    "Round",
    "Transfer",
    "compile_halo_schedule",
    "default_split_threshold",
    "normalize_pair_rows",
    "normalize_transfers",
    "pack_rounds",
    "split_transfers",
    "verify_schedule",
]
