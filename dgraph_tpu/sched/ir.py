"""Halo schedule IR: the compiled multi-round collective schedule, as data.

The schedule-as-data compilation model of "GC3: An Optimizing Compiler
for GPU Collective Communication" (PAPERS.md), applied to the halo
exchange: instead of one fixed lowering shape (dense ``all_to_all``, one
``ppermute`` ring per delta), the EdgePlan's sparse rank-to-rank traffic
matrix (``plan.halo_pair_rows``) is compiled by :mod:`dgraph_tpu.sched.
passes` into an explicit :class:`HaloSchedule` — a list of
:class:`Round`\\ s, each a set of non-conflicting (src, dst, row-slice)
:class:`Transfer`\\ s — that the generic round executor in
``comm.collectives`` replays under ``halo_impl="sched"``.

Contracts:

- **jax-free + stdlib-only** (``analysis.lint``'s ``jax-free-module``
  rule): the IR must construct, serialize, and VERIFY on a host where
  jax is wedged or absent — the compiler and its selftest perform zero
  XLA compiles by construction.
- **Hashable**: every node is a frozen dataclass of ints/tuples, so a
  schedule can ride an :class:`~dgraph_tpu.plan.EdgePlan`'s STATIC aux
  (jit cache keys, ``functools.lru_cache``'d executor factories) without
  ceremony.
- **Serializable**: ``to_dict``/``from_dict`` round-trip through plain
  JSON; :attr:`HaloSchedule.schedule_id` is a content hash of the
  canonical serialization, so two ranks (or two commits) holding the
  same id provably hold the same round order — the identity the SPMD
  issue-sequence auditor and ``obs.regress``'s byte-exact gate key on.

Row-slice semantics: transfer rows index the PACKED (src -> dst) send
block — the plan packs each (sender, needer) pair's live rows from row 0
of its ``s_pad`` slot block, so rows ``[0, halo_pair_rows[src][dst])``
are live and rows beyond are mask-zero padding. A round ships one
uniform ``[row_count, F]`` operand per rank (``lax.ppermute`` requires a
single shape), so smaller transfers in a round ride padded rows — value-
safe because padded rows are masked zero on send and masked zero again
on the reverse reduce.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

# Bump when a serialized field changes meaning; additive fields do not
# bump (from_dict ignores unknown keys). Stamped into every to_dict().
SCHED_IR_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Transfer:
    """One (src, dst, row-slice) move: rows ``[row_start, row_start +
    row_count)`` of the packed (src -> dst) send block. ``src != dst``
    always — the self block never rides the wire (same convention as the
    all_to_all lowering's self-block accounting in obs.footprint)."""

    src: int
    dst: int
    row_start: int
    row_count: int

    def to_dict(self) -> dict:
        return {"src": self.src, "dst": self.dst,
                "row_start": self.row_start, "row_count": self.row_count}

    @classmethod
    def from_dict(cls, d: dict) -> "Transfer":
        return cls(src=int(d["src"]), dst=int(d["dst"]),
                   row_start=int(d["row_start"]),
                   row_count=int(d["row_count"]))


@dataclasses.dataclass(frozen=True)
class Round:
    """One collective round: a set of transfers no two of which share a
    sender or a receiver — exactly the conflict-freedom one
    ``lax.ppermute`` with partial pairs can carry."""

    transfers: tuple  # tuple[Transfer, ...]

    @property
    def row_count(self) -> int:
        """The round's uniform padded operand height C: every rank ships
        ``[C, F]`` (ppermute is single-shape), so C is the max member
        row_count and smaller members ride masked padding."""
        return max((t.row_count for t in self.transfers), default=0)

    @property
    def pairs(self) -> tuple:
        """Static ``lax.ppermute`` permutation: one (src, dst) per
        transfer, in transfer order."""
        return tuple((t.src, t.dst) for t in self.transfers)

    def to_dict(self) -> dict:
        return {"transfers": [t.to_dict() for t in self.transfers]}

    @classmethod
    def from_dict(cls, d: dict) -> "Round":
        return cls(transfers=tuple(
            Transfer.from_dict(t) for t in d["transfers"]
        ))


@dataclasses.dataclass(frozen=True)
class HaloSchedule:
    """A compiled halo-exchange schedule for one plan's traffic matrix.

    ``s_pad`` is the plan's per-pair slot height (every row index below
    lives in ``[0, s_pad)``); the executor lands round operands at
    ``src * s_pad + row_start`` of the ``[W * s_pad, F]`` halo buffer —
    the same slot numbering the all_to_all lowering produces, which is
    what makes the two bit-identical.
    """

    world_size: int
    s_pad: int
    rounds: tuple  # tuple[Round, ...]
    version: int = SCHED_IR_VERSION

    @property
    def num_rounds(self) -> int:
        return len(self.rounds)

    @property
    def num_transfers(self) -> int:
        return sum(len(r.transfers) for r in self.rounds)

    def round_rows(self) -> tuple:
        """Per-round padded operand height C_k — the row count every rank
        ships in round k (obs.footprint prices ``C_k * row_bytes``)."""
        return tuple(r.row_count for r in self.rounds)

    def operand_rows(self) -> int:
        """Total rows one shard ships across all rounds (the 'sched' row
        of footprint's ``wire_bytes_per_shard`` at ``* row_bytes``)."""
        return sum(self.round_rows())

    def rank_arrays(self, k: int) -> dict:
        """Round k's per-rank STATIC placement tables, one int per rank —
        the executor indexes them with the traced ``lax.axis_index`` so
        every rank traces the IDENTICAL program (the SPMD-divergence
        class the issue-sequence auditor proves absent):

        - ``send_dst[r]``: peer row r gathers its send block for (its own
          transfer's dst; r itself when r does not send — the self row's
          mask is all-zero, so the unused operand is zeros).
        - ``send_start[r]``: row offset of r's outgoing slice (0 when
          idle).
        - ``place_off[r]``: where r's received block lands in the
          ``[W*s_pad + C, F]`` halo buffer (``src*s_pad + row_start``;
          the scratch tail ``W*s_pad`` when r receives nothing — ppermute
          hands non-receivers zeros, which the dropped tail absorbs).
        - ``slice_off[r]``: where r slices the reverse leg's cotangent
          block from (0 when r receives nothing — the slice feeds a
          reversed permutation that drops it).
        - ``back_plane[r]``: which ``[W+1, s_pad]`` reduce-buffer plane
          r's returning reverse block lands in (its transfer's dst; the
          scratch plane W when r sent nothing this round).
        """
        W, S = self.world_size, self.s_pad
        send_dst = list(range(W))
        send_start = [0] * W
        place_off = [W * S] * W
        slice_off = [0] * W
        back_plane = [W] * W
        for t in self.rounds[k].transfers:
            send_dst[t.src] = t.dst
            send_start[t.src] = t.row_start
            back_plane[t.src] = t.dst
            place_off[t.dst] = t.src * S + t.row_start
            slice_off[t.dst] = t.src * S + t.row_start
        return {
            "send_dst": tuple(send_dst),
            "send_start": tuple(send_start),
            "place_off": tuple(place_off),
            "slice_off": tuple(slice_off),
            "back_plane": tuple(back_plane),
        }

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "world_size": self.world_size,
            "s_pad": self.s_pad,
            "rounds": [r.to_dict() for r in self.rounds],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "HaloSchedule":
        return cls(
            world_size=int(d["world_size"]),
            s_pad=int(d["s_pad"]),
            rounds=tuple(Round.from_dict(r) for r in d["rounds"]),
            version=int(d.get("version", SCHED_IR_VERSION)),
        )

    @property
    def schedule_id(self) -> str:
        """Content hash of the canonical serialization: equal ids imply
        equal round order on every holder (rank, commit, ledger row)."""
        key = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha1(key.encode()).hexdigest()[:12]


def normalize_pair_rows(pair_rows, world_size: int = None) -> tuple:
    """Canonical ``[W][W]`` tuple-of-tuples traffic matrix from any
    nested int sequence (numpy rows, JSON lists, tuples). Raises on a
    ragged or mis-sized matrix — a silently truncated traffic matrix
    would compile a schedule that drops transfers, the exact vacuity the
    verifier exists to catch."""
    rows = tuple(tuple(int(v) for v in row) for row in pair_rows)
    W = world_size if world_size is not None else len(rows)
    if len(rows) != W or any(len(r) != W for r in rows):
        raise ValueError(
            f"pair_rows must be [{W}][{W}]; got "
            f"{len(rows)} rows of lengths {sorted({len(r) for r in rows})}"
        )
    if any(v < 0 for row in rows for v in row):
        raise ValueError("pair_rows entries must be non-negative row counts")
    return rows


def verify_schedule(schedule: HaloSchedule, pair_rows) -> list:
    """Every invariant the executor's bit-parity with all_to_all rides
    on, as a failure list (empty == verified):

    - bounds: ranks in ``[0, W)``, no self transfers, live rows only
      (``row_start + row_count <= pair_rows[src][dst]``), and the padded
      round operand stays inside the slot block
      (``row_start + round C <= s_pad``);
    - conflict-freedom: no rank appears twice as sender or twice as
      receiver inside one round (one ppermute carries at most one
      outgoing and one incoming block per rank);
    - coverage: every live (src, dst) pair's rows ``[0, count)`` are
      covered by its transfers exactly once (a gap is a silently dropped
      halo block; an overlap of LIVE ranges would make the reverse
      reduce double-count) and dead pairs carry no transfers.

    The selftest's vacuity mutants (a conflicting round, a dropped
    transfer) must turn this list non-empty — a verifier that cannot go
    RED proves nothing.
    """
    failures = []
    W, S = schedule.world_size, schedule.s_pad
    try:
        rows = normalize_pair_rows(pair_rows, W)
    except ValueError as e:
        return [f"pair_rows: {e}"]
    covered: dict = {}
    for k, rnd in enumerate(schedule.rounds):
        C = rnd.row_count
        if not rnd.transfers:
            failures.append(f"round {k}: empty round (dead launch)")
        senders: set = set()
        receivers: set = set()
        for t in rnd.transfers:
            tag = f"round {k}: transfer {t.src}->{t.dst}"
            if not (0 <= t.src < W and 0 <= t.dst < W):
                failures.append(f"{tag}: rank out of [0, {W})")
                continue
            if t.src == t.dst:
                failures.append(f"{tag}: self transfer (never on the wire)")
            if t.row_count < 1 or t.row_start < 0:
                failures.append(f"{tag}: empty or negative row slice")
            if t.row_start + t.row_count > rows[t.src][t.dst]:
                failures.append(
                    f"{tag}: rows [{t.row_start}, "
                    f"{t.row_start + t.row_count}) exceed the pair's "
                    f"{rows[t.src][t.dst]} live rows"
                )
            if t.row_start + C > S:
                failures.append(
                    f"{tag}: row_start {t.row_start} + round C {C} "
                    f"exceeds s_pad {S} (padded operand leaves the slot)"
                )
            if t.src in senders:
                failures.append(
                    f"round {k}: rank {t.src} sends twice (conflicting "
                    f"round — one ppermute carries one block per sender)"
                )
            if t.dst in receivers:
                failures.append(
                    f"round {k}: rank {t.dst} receives twice (conflicting "
                    f"round — two blocks cannot land in one operand)"
                )
            senders.add(t.src)
            receivers.add(t.dst)
            covered.setdefault((t.src, t.dst), []).append(
                (t.row_start, t.row_start + t.row_count)
            )
    for s in range(W):
        for d in range(W):
            count = rows[s][d]
            ranges = sorted(covered.get((s, d), []))
            if count == 0:
                if ranges:
                    failures.append(
                        f"pair {s}->{d}: transfers scheduled for a pair "
                        f"with zero live rows"
                    )
                continue
            pos = 0
            for lo, hi in ranges:
                if lo > pos:
                    failures.append(
                        f"pair {s}->{d}: rows [{pos}, {lo}) uncovered "
                        f"(dropped transfer — the halo block silently "
                        f"never arrives)"
                    )
                elif lo < pos:
                    failures.append(
                        f"pair {s}->{d}: rows [{lo}, {pos}) covered twice "
                        f"(the reverse reduce would double-count)"
                    )
                pos = max(pos, hi)
            if pos < count:
                failures.append(
                    f"pair {s}->{d}: rows [{pos}, {count}) uncovered "
                    f"(dropped transfer — the halo block silently never "
                    f"arrives)"
                )
    return failures
