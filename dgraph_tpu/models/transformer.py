"""Sequence transformer LM over a sequence-sharded mesh axis — the
long-context training demonstrator for :mod:`dgraph_tpu.parallel.sequence`.

Beyond-reference (the reference has no sequence models, SURVEY.md §2.5):
this is the framework's long-context story made end-to-end trainable. The
sequence dimension is sharded over a mesh axis exactly like graph vertices
are; every attention layer runs EXACT causal attention over the full
sequence via ring attention (K/V blocks streaming over ppermute, O(T/W)
memory per device; the comm facade's ``seq_attention``, which is the dense
oracle under a single-device comm). All other ops (LN, FFN, embedding,
head) are token-local, so the ONLY communication per layer is the
attention collective itself. The Ulysses all-to-all lowering
(:func:`dgraph_tpu.parallel.sequence.ulysses_attention`) is available for
hand-rolled blocks; this model uses the ring.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class TransformerBlock(nn.Module):
    latent: int
    num_heads: int
    comm: Any  # _BaseComm: seq_attention routes ring/ulysses/dense by mode
    dtype: Any = None
    causal: bool = True
    attn_impl: str = "ring"  # or 'ulysses' (heads % axis == 0)
    # MoE FFN: one expert per rank of the SEQUENCE axis (the classic
    # DeepSpeed-MoE axis fusion — tokens are already sharded over it, so
    # routing is the standard two all_to_alls). 0 = dense FFN.
    moe_k: int = 0  # top-k routing (1 = switch, 2 = GShard/Mixtral)
    moe_capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, x):  # [T_loc, L]
        from dgraph_tpu import config as _cfg

        dt = _cfg.resolve_compute_dtype(self.dtype)
        L, Hh = self.latent, self.num_heads
        if L % Hh:
            raise ValueError(f"latent {L} not divisible by heads {Hh}")
        dh = L // Hh
        y = nn.LayerNorm(dtype=dt, name="ln_attn")(x)
        qkv = nn.Dense(3 * L, dtype=dt, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        n = x.shape[0]
        attn = self.comm.seq_attention(
            q.reshape(n, Hh, dh), k.reshape(n, Hh, dh), v.reshape(n, Hh, dh),
            causal=self.causal, impl=self.attn_impl,
        )
        x = x + nn.Dense(L, dtype=dt, name="attn_out")(attn.reshape(n, L))
        y = nn.LayerNorm(dtype=dt, name="ln_ffn")(x)
        if self.moe_k > 0:
            if self.comm.graph_axis is None:
                # a silent dense fallback would be a DIFFERENT architecture
                # (no router/expert params) masquerading as the same config
                # (ADVICE r3 #3) — fail loudly instead
                raise ValueError(
                    "moe_k > 0 needs a sharded communicator (graph_axis); "
                    "SingleComm has no expert axis. Run with world_size > 1 "
                    "or set moe_k=0."
                )
            return x + self._moe_ffn(y, dt)
        h = nn.silu(nn.Dense(4 * L, dtype=dt, name="ffn_up")(y))
        return x + nn.Dense(L, dtype=dt, name="ffn_down")(h)

    def _moe_ffn(self, y, dt):
        """Expert-parallel FFN over the sequence axis. Expert weights carry
        a leading [1] axis per shard (global [E, ...], sharded over the
        axis — :func:`moe_param_specs` derives the per-leaf partition
        specs); all experts share the
        same init and diverge through routing. The router's load-balance
        loss is stashed in a mutable 'losses' collection."""
        from dgraph_tpu.parallel.expert import load_balance_loss, moe_apply

        L = self.latent
        E = self.comm.get_world_size()
        T_loc = y.shape[0]
        cap = max(1, int(self.moe_capacity_factor * self.moe_k * T_loc / E))
        logits = nn.Dense(E, dtype=dt, name="router")(y)
        w1 = self.param(
            "moe_w1", nn.initializers.lecun_normal(), (1, L, 4 * L))
        w2 = self.param(
            "moe_w2", nn.initializers.lecun_normal(), (1, 4 * L, L))

        def expert_fn(p, z):
            h = nn.silu(z @ p["w1"].astype(z.dtype))
            return h @ p["w2"].astype(z.dtype)

        out = moe_apply(
            y, logits, expert_fn, {"w1": w1[0], "w2": w2[0]}, cap,
            self.comm.graph_axis, k=self.moe_k,
        )
        if self.is_mutable_collection("losses"):
            self.sow(
                "losses", "moe_aux",
                load_balance_loss(logits, self.comm.graph_axis),
            )
        return out


class SeqTransformerLM(nn.Module):
    """Token-in, next-token-logits-out causal LM. Per-shard inputs: this
    shard's [T_loc] token ids plus its global position offset (rank *
    T_loc) baked into the learned positional embedding lookup."""

    vocab: int
    latent: int
    num_layers: int = 2
    num_heads: int = 4
    max_len: int = 4096
    comm: Any = None
    dtype: Any = None
    attn_impl: str = "ring"
    moe_k: int = 0  # >0: expert-parallel FFN over the sequence axis
    moe_capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, tokens, positions):  # [T_loc] int32, [T_loc] int32
        h = nn.Embed(self.vocab, self.latent, name="tok_embed")(tokens)
        h = h + nn.Embed(self.max_len, self.latent, name="pos_embed")(positions)
        for i in range(self.num_layers):
            h = TransformerBlock(
                self.latent, self.num_heads, comm=self.comm,
                dtype=self.dtype, attn_impl=self.attn_impl,
                moe_k=self.moe_k,
                moe_capacity_factor=self.moe_capacity_factor,
                name=f"block_{i}",
            )(h)
        h = nn.LayerNorm(name="ln_out")(h)
        return nn.Dense(self.vocab, name="head")(h).astype(jnp.float32)


def moe_param_specs(params_or_shapes, axis_name: str = "graph"):
    """Per-leaf PartitionSpecs for an LM param tree: MoE expert weights
    (``moe_w*`` leaves, global [E, ...]) shard over ``axis_name``;
    everything else replicates. The ONE place the leading-[1]-per-shard
    convention and the ``moe_w`` naming are interpreted — derive specs
    here, never by hand (a silently replicated expert leaf trains one
    shared expert while reporting E of them)."""
    from jax.sharding import PartitionSpec as P
    from jax.tree_util import tree_map_with_path

    def spec(path, _leaf):
        # match the FINAL path component exactly: a future 'moe_weight_norm'
        # or a parent module named 'moe_w*' must not silently shard
        # (ADVICE r3 #4)
        leaf_name = str(getattr(path[-1], "key", path[-1])) if path else ""
        return P(axis_name) if leaf_name in ("moe_w1", "moe_w2") else P()

    return tree_map_with_path(spec, params_or_shapes)
