"""Sequence transformer LM over a sequence-sharded mesh axis — the
long-context training demonstrator for :mod:`dgraph_tpu.parallel.sequence`.

Beyond-reference (the reference has no sequence models, SURVEY.md §2.5):
this is the framework's long-context story made end-to-end trainable. The
sequence dimension is sharded over a mesh axis exactly like graph vertices
are; every attention layer runs EXACT causal attention over the full
sequence via ring attention (K/V blocks streaming over ppermute, O(T/W)
memory per device; the comm facade's ``seq_attention``, which is the dense
oracle under a single-device comm). All other ops (LN, FFN, embedding,
head) are token-local, so the ONLY communication per layer is the
attention collective itself. The Ulysses all-to-all lowering
(:func:`dgraph_tpu.parallel.sequence.ulysses_attention`) is available for
hand-rolled blocks; this model uses the ring.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class TransformerBlock(nn.Module):
    latent: int
    num_heads: int
    comm: Any  # _BaseComm: seq_attention routes ring/ulysses/dense by mode
    dtype: Any = None
    causal: bool = True
    attn_impl: str = "ring"  # or 'ulysses' (heads % axis == 0)

    @nn.compact
    def __call__(self, x):  # [T_loc, L]
        from dgraph_tpu import config as _cfg

        dt = _cfg.resolve_compute_dtype(self.dtype)
        L, Hh = self.latent, self.num_heads
        if L % Hh:
            raise ValueError(f"latent {L} not divisible by heads {Hh}")
        dh = L // Hh
        y = nn.LayerNorm(dtype=dt, name="ln_attn")(x)
        qkv = nn.Dense(3 * L, dtype=dt, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        n = x.shape[0]
        attn = self.comm.seq_attention(
            q.reshape(n, Hh, dh), k.reshape(n, Hh, dh), v.reshape(n, Hh, dh),
            causal=self.causal, impl=self.attn_impl,
        )
        x = x + nn.Dense(L, dtype=dt, name="attn_out")(attn.reshape(n, L))
        y = nn.LayerNorm(dtype=dt, name="ln_ffn")(x)
        h = nn.silu(nn.Dense(4 * L, dtype=dt, name="ffn_up")(y))
        return x + nn.Dense(L, dtype=dt, name="ffn_down")(h)


class SeqTransformerLM(nn.Module):
    """Token-in, next-token-logits-out causal LM. Per-shard inputs: this
    shard's [T_loc] token ids plus its global position offset (rank *
    T_loc) baked into the learned positional embedding lookup."""

    vocab: int
    latent: int
    num_layers: int = 2
    num_heads: int = 4
    max_len: int = 4096
    comm: Any = None
    dtype: Any = None
    attn_impl: str = "ring"

    @nn.compact
    def __call__(self, tokens, positions):  # [T_loc] int32, [T_loc] int32
        h = nn.Embed(self.vocab, self.latent, name="tok_embed")(tokens)
        h = h + nn.Embed(self.max_len, self.latent, name="pos_embed")(positions)
        for i in range(self.num_layers):
            h = TransformerBlock(
                self.latent, self.num_heads, comm=self.comm,
                dtype=self.dtype, attn_impl=self.attn_impl,
                name=f"block_{i}",
            )(h)
        h = nn.LayerNorm(name="ln_out")(h)
        return nn.Dense(self.vocab, name="head")(h).astype(jnp.float32)
