"""Relational GAT over heterogeneous graphs (the OGB-LSC MAG240M model).

Reference parity: ``experiments/OGB-LSC/RGAT.py`` — ``CommAwareGAT``
(``RGAT.py:127-268``: per-relation edge attention) and ``CommAwareRGAT``
(``:271-382``: multi-layer with skip connections and DistributedBatchNorm).

TPU-first delta: the reference's attention needs 6 network ops per layer per
relation (gathers of h_i/h_j, scatter+gather of the softmax denominator,
message scatter — ``RGAT.py:174-206``) because edges live on the src rank.
With dst-owned edges the softmax over incoming edges is rank-local
(``dgraph_tpu.ops.local.segment_softmax``), so each relation needs exactly
ONE collective (the src-feature halo gather) per layer.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.models.norm import DistributedBatchNorm


class RelationalAttention(nn.Module):
    """One relation's attention message pass: src-type features -> dst-type
    aggregated messages (un-normalized heads averaged)."""

    out_features: int
    comm: Any
    num_heads: int = 2
    negative_slope: float = 0.2
    dtype: Any = None  # None -> config.default_compute_dtype

    @nn.compact
    def __call__(self, x_src: jax.Array, x_dst: jax.Array, plan) -> jax.Array:
        from dgraph_tpu import config as _cfg

        dt = _cfg.resolve_compute_dtype(self.dtype)
        H, D = self.num_heads, self.out_features
        hs = nn.Dense(H * D, use_bias=False, name="src_proj", dtype=dt)(x_src)
        hd = nn.Dense(H * D, use_bias=False, name="dst_proj", dtype=dt)(x_dst)
        a_src = self.param("att_src", nn.initializers.glorot_uniform(), (H, D))
        a_dst = self.param("att_dst", nn.initializers.glorot_uniform(), (H, D))
        # cast params to the compute dtype: f32 attention params would
        # promote the [e_pad, H, D] tensors (the HBM-dominant ones) back
        # to f32 and forfeit the bf16 bandwidth win
        a_src = a_src.astype(hs.dtype)
        a_dst = a_dst.astype(hd.dtype)

        from dgraph_tpu.models.message_passing import head_chunked_attention

        out = head_chunked_attention(
            self.comm, hs, hd, a_src, a_dst, plan, self.negative_slope
        )
        return out.mean(axis=1)


class RGATLayer(nn.Module):
    """One hetero layer: per-relation attention, per-dst-type sum over
    relations + self projection + skip, optional distributed BN
    (``RGAT.py:271-382``)."""

    out_features: int
    comm: Any
    relations: Sequence[tuple]  # RelKeys
    num_heads: int = 2
    use_batch_norm: bool = True
    bn_recompute: bool = False  # reference's DistributedBN_with_Recompute
    dtype: Any = None

    @nn.compact
    def __call__(self, feats: dict, plans: dict, vertex_masks: dict, train: bool = False):
        from dgraph_tpu import config as _cfg

        cdt = _cfg.resolve_compute_dtype(self.dtype)  # for this layer's Denses
        agg = {
            t: nn.Dense(self.out_features, name=f"self_{t}", dtype=cdt)(x)
            for t, x in feats.items()
        }
        for key in self.relations:
            st, name, dt = key
            msg = RelationalAttention(
                self.out_features,
                comm=self.comm,
                num_heads=self.num_heads,
                dtype=self.dtype,
                name=f"rel_{st}_{name}_{dt}",
            )(feats[st], feats[dt], plans[key])
            agg[dt] = agg[dt] + msg
        out = {}
        for t, h in agg.items():
            h = nn.relu(h)
            if self.use_batch_norm:
                h = DistributedBatchNorm(
                    comm=self.comm, recompute=self.bn_recompute,
                    name=f"bn_{t}",
                )(h, vertex_masks[t], use_running_average=not train)
            out[t] = h
        return out


class RGAT(nn.Module):
    """Multi-layer relational GAT with a classification head on one target
    node type (paper classification on MAG240M — ``OGB-LSC/main.py``)."""

    hidden_features: int
    out_features: int
    comm: Any
    relations: Sequence[tuple]
    target_type: str = "paper"
    num_layers: int = 2
    num_heads: int = 2
    use_batch_norm: bool = True
    bn_recompute: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, feats: dict, plans: dict, vertex_masks: dict, train: bool = False):
        from dgraph_tpu import config as _cfg

        h = feats
        for i in range(self.num_layers):
            h = RGATLayer(
                self.hidden_features,
                comm=self.comm,
                relations=tuple(self.relations),
                num_heads=self.num_heads,
                use_batch_norm=self.use_batch_norm,
                bn_recompute=self.bn_recompute,
                dtype=self.dtype,
                name=f"layer_{i}",
            )(h, plans, vertex_masks, train)
        head_dt = _cfg.resolve_compute_dtype(self.dtype)
        return nn.Dense(self.out_features, name="head", dtype=head_dt)(
            h[self.target_type]
        ).astype(jnp.float32)
