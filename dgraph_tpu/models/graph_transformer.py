"""Graph transformer: local message passing + GLOBAL attention over all
vertices, per layer (the GraphGPS recipe: MPNN branch + transformer branch,
both residual).

Beyond-reference model family: the reference's models are all local-k-hop
(GCN/RGAT/GraphCast — SURVEY.md §2.5); long-range interactions need as
many layers as the graph diameter. A global-attention branch captures them
in one layer — and on TPU it rides the framework's sequence-parallel
primitive: vertices are ALREADY sharded over the ``graph`` mesh axis, so
global attention over the vertex set is exactly ring attention over that
axis (:mod:`dgraph_tpu.parallel.sequence`, K/V blocks streaming via
ppermute) — the same mesh, zero re-sharding. The local branch is the
plan-based gather→dense→scatter every other model uses.

Padded vertex slots are excluded from attention keys via ``kv_mask``
(=DistributedGraph.vertex_mask); attention is permutation-equivariant, so
the renumbered/sharded vertex order computes the same per-vertex function
as the dense single-device oracle (pinned in tests/test_graph_transformer.py).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.models.mlp import MLP
from dgraph_tpu.plan import EdgePlan


class GPSLayer(nn.Module):
    """One [local MPNN + global attention + FFN] block, all residual.

    Pre-LN transformer convention; the MPNN branch is the split-projection
    conv (same algebra as :class:`~dgraph_tpu.models.gcn.GraphConvLayer`).
    """

    latent: int
    comm: Any
    num_heads: int = 4
    dtype: Any = None

    @nn.compact
    def __call__(self, x, plan: EdgePlan, vmask):  # x: [n_pad, L]
        from dgraph_tpu import config as _cfg

        dt = _cfg.resolve_compute_dtype(self.dtype)
        L, Hh = self.latent, self.num_heads
        if L % Hh:
            raise ValueError(f"latent {L} not divisible by heads {Hh}")
        dh = L // Hh

        # --- local branch: gather -> message -> scatter (dst-owned) ---
        y = nn.LayerNorm(dtype=dt, name="ln_local")(x)
        h_s = nn.Dense(L, use_bias=False, dtype=dt, name="src_proj")(y)
        h_d = nn.Dense(L, dtype=dt, name="dst_proj")(y)
        from dgraph_tpu.comm.collectives import map_feature_chunks

        if plan.halo_side != "dst":
            # feature-chunked local pipeline (models/gcn.py rationale):
            # silu is elementwise, so chunking is exact; one full-width
            # halo exchange, every [E, *] intermediate <= col_block wide
            hs_ext = self.comm.halo_extend(h_s, plan, side="src")
            local = map_feature_chunks(
                lambda sl: self.comm.scatter_sum(
                    nn.silu(
                        self.comm.local_take(hs_ext[:, sl], plan, side="src")
                        + self.comm.local_take(h_d[:, sl], plan, side="dst")
                    ),
                    plan, side="dst",
                ),
                L,
            )
        else:
            m = nn.silu(
                self.comm.gather(h_s, plan, side="src")
                + self.comm.gather(h_d, plan, side="dst")
            )
            local = self.comm.scatter_sum(m, plan, side="dst")
        x = x + nn.Dense(L, dtype=dt, name="local_out")(local)

        # --- global branch: ring attention over the vertex dimension ---
        y = nn.LayerNorm(dtype=dt, name="ln_attn")(x)
        qkv = nn.Dense(3 * L, dtype=dt, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        n = x.shape[0]
        attn = self.comm.seq_attention(
            q.reshape(n, Hh, dh), k.reshape(n, Hh, dh), v.reshape(n, Hh, dh),
            kv_mask=vmask,
        )
        x = x + nn.Dense(L, dtype=dt, name="attn_out")(attn.reshape(n, L))

        # --- FFN ---
        y = nn.LayerNorm(dtype=dt, name="ln_ffn")(x)
        x = x + MLP([2 * L, L], dtype=dt, name="ffn")(y)
        # padded slots must stay exactly zero: they feed the NEXT layer's
        # local scatter as src rows of cross-shard edges' padding and the
        # residual stream would otherwise leak LayerNorm/FFN bias terms
        # into them (real vertices are unaffected)
        return x * vmask[:, None].astype(x.dtype)


class GraphTransformer(nn.Module):
    """Embed -> N x GPSLayer -> head. Signature matches the other model
    families (x, plan, [edge_weight]) plus the vertex mask."""

    latent: int
    out_features: int
    comm: Any
    num_layers: int = 3
    num_heads: int = 4
    dtype: Any = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,  # [n_pad, F]
        plan: EdgePlan,
        vmask: Optional[jax.Array] = None,  # [n_pad] 1.0 = real vertex
    ) -> jax.Array:
        from dgraph_tpu import config as _cfg

        dt = _cfg.resolve_compute_dtype(self.dtype)
        if vmask is None:
            if getattr(self.comm, "graph_axis", None) is not None:
                # distributed shards ALWAYS contain padded vertex slots;
                # an all-ones default would let every real vertex attend to
                # padding — silent logit corruption, so fail loudly
                raise ValueError(
                    "GraphTransformer requires vmask (DistributedGraph."
                    "vertex_mask) in distributed mode"
                )
            vmask = jnp.ones((x.shape[0],), jnp.float32)
        h = nn.Dense(self.latent, dtype=dt, name="embed")(x)
        h = h * vmask[:, None].astype(h.dtype)
        for i in range(self.num_layers):
            h = GPSLayer(
                self.latent, comm=self.comm, num_heads=self.num_heads,
                dtype=self.dtype, name=f"gps_{i}",
            )(h, plan, vmask)
        return nn.Dense(self.out_features, dtype=dt, name="head")(h).astype(
            jnp.float32
        )
