"""Generic message-passing wrapper over halo exchange.

Reference parity: ``DGraph/distributed/haloExchange.py:142-223``
(``DGraphMessagePassing``: halo-exchange -> concat(local, halo) -> user
message-passing layer). The TPU version exposes the same two-step shape —
exchange then a user function over the concatenated buffer — so layers
written against the reference's API have a direct home. New code should
usually prefer the plan-based :meth:`comm.gather`/:meth:`comm.scatter_sum`
(one fused pipeline, no materialized halo concat).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.plan import EdgePlan


def head_chunked_attention(
    comm, hs, hd, a_src, a_dst, plan, negative_slope: float
) -> jax.Array:
    """GAT-style per-dst-vertex softmax attention, chunked by head groups.

    The ONE copy of the attention edge pipeline shared by GATConv and
    RGAT's RelationalAttention: per-head logits + leaky-relu + rank-local
    segment softmax + weighted scatter, with heads processed in groups of
    ``gather_col_block // D`` so every [e_pad, *] intermediate stays
    <= col_block wide (the models/gcn.py chunking rationale; softmax
    couples features within a head, never across heads, so grouping is
    exact). Enforces dst-owned edges (halo_side == 'src') itself — a
    src-owned plan would make the rank-local softmax silently wrong.

    Args:
      hs/hd: [n_pad, H*D] src-/dst-side projections.
      a_src/a_dst: [H, D] attention parameters (already compute-dtype).
    Returns: [n_dst_pad, H, D] attended sums.
    """
    from dgraph_tpu import config as _cfg
    from dgraph_tpu.comm.collectives import map_feature_chunks
    from dgraph_tpu.ops import local as local_ops

    if plan.halo_side != "src":
        raise ValueError(
            "head_chunked_attention requires dst-owned edges "
            "(halo_side='src'): with src-owned plans the dst index uses "
            "halo-slot numbering, so a rank-local softmax over n_dst_pad "
            "segments would silently drop remote contributions from the "
            "normalizer"
        )

    H, D = a_src.shape
    gh = max(1, (_cfg.gather_col_block or H * D) // D)  # heads per chunk
    hs_ext = comm.halo_extend(hs, plan, side="src")

    def group(sl):
        h0, h1 = sl.start // D, sl.stop // D
        hs_c = comm.local_take(
            hs_ext[:, sl], plan, side="src").reshape(-1, h1 - h0, D)
        hd_c = comm.local_take(
            hd[:, sl], plan, side="dst").reshape(-1, h1 - h0, D)
        logits = (hs_c * a_src[h0:h1]).sum(-1) + (hd_c * a_dst[h0:h1]).sum(-1)
        logits = nn.leaky_relu(logits, negative_slope)
        alpha = local_ops.segment_softmax(
            logits, plan.dst_index, plan.n_dst_pad, plan.edge_mask,
            indices_are_sorted=plan.ids_sorted("dst"),
        )
        msg = (alpha[..., None] * hs_c).reshape(-1, (h1 - h0) * D)
        return comm.scatter_sum(msg, plan, side="dst")

    return map_feature_chunks(group, H * D, chunk=gh * D).reshape(-1, H, D)


class MessagePassing(nn.Module):
    """halo-exchange -> [local ; halo] -> ``layer_fn(full, plan)``.

    ``layer_fn`` is a flax module or callable taking the concatenated
    feature buffer (indices in the plan's halo-slot numbering are valid row
    ids into it) and the per-shard plan.
    """

    layer: Any
    comm: Any

    @nn.compact
    def __call__(self, x: jax.Array, plan: EdgePlan) -> jax.Array:
        # resolve the halo lowering ONCE from the plan (env pin > tuning
        # record > heuristic, incl. the overlap double-buffered rounds
        # when the plan carries an interior/boundary split) and thread it
        # — the plan-less facade default would always pay the padded
        # all_to_all
        from dgraph_tpu.comm.collectives import (
            resolve_plan_impl,
            resolve_plan_wire_format,
        )

        impl = resolve_plan_impl(plan, self.comm.graph_axis)
        halo = self.comm.halo_exchange(
            x, plan.halo, deltas=plan.halo_deltas, impl=impl,
            wire_format=resolve_plan_wire_format(plan, self.comm.graph_axis),
        )
        full = jnp.concatenate([x, halo], axis=0)
        return self.layer(full, plan)
