"""Generic message-passing wrapper over halo exchange.

Reference parity: ``DGraph/distributed/haloExchange.py:142-223``
(``DGraphMessagePassing``: halo-exchange -> concat(local, halo) -> user
message-passing layer). The TPU version exposes the same two-step shape —
exchange then a user function over the concatenated buffer — so layers
written against the reference's API have a direct home. New code should
usually prefer the plan-based :meth:`comm.gather`/:meth:`comm.scatter_sum`
(one fused pipeline, no materialized halo concat).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.plan import EdgePlan


class MessagePassing(nn.Module):
    """halo-exchange -> [local ; halo] -> ``layer_fn(full, plan)``.

    ``layer_fn`` is a flax module or callable taking the concatenated
    feature buffer (indices in the plan's halo-slot numbering are valid row
    ids into it) and the per-shard plan.
    """

    layer: Any
    comm: Any

    @nn.compact
    def __call__(self, x: jax.Array, plan: EdgePlan) -> jax.Array:
        halo = self.comm.halo_exchange(x, plan.halo)
        full = jnp.concatenate([x, halo], axis=0)
        return self.layer(full, plan)
