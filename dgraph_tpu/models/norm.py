"""Distributed BatchNorm over vertex-sharded activations.

Reference parity: ``experiments/OGB-LSC/distributed_layers.py:22-207``
(DistributedBatchNorm1D): mean/var all-reduced across ranks with a custom
fwd/bwd. In JAX the psum is differentiable, so no hand-written backward is
needed; masking excludes padded vertices from the statistics (the reference
has no padding so it divides by global count directly,
``distributed_layers.py:29-68``).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class DistributedBatchNorm(nn.Module):
    """``recompute=True`` is the reference's DistributedBN_with_Recompute
    (``distributed_layers.py:77-107``): the backward saves only the raw
    input plus the [F]-sized stats and REMATERIALIZES the normalized
    tensor, instead of keeping the [n_pad, F] x_hat residual alive
    through the whole backward. Here that is ``jax.checkpoint`` with a
    nothing-saved policy around the pure-local normalization — the stats
    collectives stay OUTSIDE the remat region (like the reference, which
    reuses forward's mean/var in backward), so recompute adds zero extra
    communication."""

    comm: Any
    momentum: float = 0.9
    epsilon: float = 1e-5
    use_running_average: bool = False
    recompute: bool = False

    @nn.compact
    def __call__(
        self,
        x: jax.Array,  # [n_pad, F] per-shard
        mask: Optional[jax.Array] = None,  # [n_pad] 1.0 for real vertices
        use_running_average: Optional[bool] = None,
    ) -> jax.Array:
        use_ra = (
            use_running_average
            if use_running_average is not None
            else self.use_running_average
        )
        F = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean", lambda: jnp.zeros(F))
        ra_var = self.variable("batch_stats", "var", lambda: jnp.ones(F))
        scale = self.param("scale", nn.initializers.ones, (F,))
        bias = self.param("bias", nn.initializers.zeros, (F,))

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            if mask is None:
                mask = jnp.ones(x.shape[0], x.dtype)
            m = mask[:, None]
            count = self.comm.all_reduce_sum(mask.sum())
            mean = self.comm.all_reduce_sum((x * m).sum(0)) / jnp.maximum(count, 1.0)
            var = self.comm.all_reduce_sum(((x - mean) ** 2 * m).sum(0)) / jnp.maximum(
                count, 1.0
            )
            if not self.is_initializing():
                ra_mean.value = self.momentum * ra_mean.value + (1 - self.momentum) * mean
                ra_var.value = self.momentum * ra_var.value + (1 - self.momentum) * var

        eps = self.epsilon

        def _normalize(x, mean, var, scale, bias):
            return scale * (x - mean) * jax.lax.rsqrt(var + eps) + bias

        if self.recompute:
            # save NOTHING from inside the region: backward recomputes the
            # normalization from (x, mean, var, scale, bias), all of which
            # the surrounding graph already keeps
            _normalize = jax.checkpoint(
                _normalize, policy=jax.checkpoint_policies.nothing_saveable)
        return _normalize(x, mean, var, scale, bias)
