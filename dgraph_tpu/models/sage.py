"""GraphSAGE (mean aggregator) — one of the reference's tracked configs
(BASELINE.md: "ogbn-arxiv GraphSAGE (4-way)").

SAGEConv: h_v = act(W_self x_v + W_nbr mean_{u->v} x_u). The neighbor mean is
a distributed gather (src side, halo exchange) + local segment mean on the
dst-owner side.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.plan import EdgePlan


class SAGEConv(nn.Module):
    out_features: int
    comm: Any
    activation: Any = nn.relu
    dtype: Any = None  # None -> config.default_compute_dtype

    @nn.compact
    def __call__(self, x: jax.Array, plan: EdgePlan) -> jax.Array:
        from dgraph_tpu import config as _cfg

        from dgraph_tpu.comm.collectives import map_feature_chunks

        dt = _cfg.resolve_compute_dtype(self.dtype)
        F = x.shape[-1]
        # cast BEFORE the edge pipeline: aggregating the raw f32 input
        # would run every [e_pad, F] take/scatter at double width (the
        # dtype-discipline rule — see tests/test_dtype_discipline.py)
        xa = x.astype(dt) if dt is not None else x
        if plan.halo_side != "dst" and self.comm.split_active(plan):
            # split route (overlap rounds or pallas_p2p one-sided puts;
            # halo_exchange_split decides): the boundary exchange goes out
            # first; the interior
            # neighbor sum (reading only the local table) runs while they
            # fly; boundary contributions merge once landed. One exchange
            # per layer, chunk-local work exactly as below.
            halo_buf = self.comm.halo_exchange_split(xa, plan)
            agg = map_feature_chunks(
                lambda sl: self.comm.gather_scatter_overlap(
                    xa[:, sl], halo_buf[:, sl], plan
                ),
                F,
            )
        elif plan.halo_side != "dst":
            # feature-chunked neighbor sum (models/gcn.py rationale): the
            # per-edge op here is IDENTITY, so chunking is exact for any
            # activation; one full-width halo exchange, local work in
            # <=col_block-wide slices, concat only at the vertex level
            x_ext = self.comm.halo_extend(xa, plan, side="src")
            agg = map_feature_chunks(
                lambda sl: self.comm.scatter_sum(
                    self.comm.local_take(x_ext[:, sl], plan, side="src"),
                    plan, side="dst",
                ),
                F,
            )
        else:
            h_src = self.comm.gather(xa, plan, side="src")  # [e_pad, F]
            agg = self.comm.scatter_sum(h_src, plan, side="dst")  # [n_pad, F]
        ones = plan.edge_mask[:, None]
        deg = self.comm.scatter_sum(ones, plan, side="dst")  # [n_pad, 1]
        # divide in agg's dtype: a f32 degree would promote mean_nbr to a
        # full-width f32 vertex tensor
        mean_nbr = agg / jnp.maximum(deg, 1.0).astype(agg.dtype)
        out = nn.Dense(self.out_features, dtype=dt)(x) + nn.Dense(
            self.out_features, use_bias=False, dtype=dt
        )(mean_nbr)
        return self.activation(out)


class GraphSAGE(nn.Module):
    hidden_features: int
    out_features: int
    comm: Any
    num_layers: int = 2
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, plan: EdgePlan) -> jax.Array:
        from dgraph_tpu import config as _cfg

        for _ in range(self.num_layers):
            x = SAGEConv(self.hidden_features, comm=self.comm, dtype=self.dtype)(x, plan)
        head_dt = _cfg.resolve_compute_dtype(self.dtype)
        return nn.Dense(self.out_features, dtype=head_dt)(x).astype(jnp.float32)
