"""Graph attention (GAT) with distributed softmax-over-incoming-edges.

Reference parity: ``experiments/OGB-LSC/RGAT.py:127-268`` (CommAwareGAT).
The reference, with src-owned edges, needs SIX comm ops per layer (gather
h_i, gather h_j, scatter denominator, gather denominator, scatter messages,
plus norm round-trips — ``RGAT.py:174-206``). With dst-owned edges (this
framework's default) the attention softmax is a purely LOCAL segment
operation on each shard — only the initial src-feature gather communicates.
One collective per layer instead of six; same math.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.models.message_passing import head_chunked_attention
from dgraph_tpu.plan import EdgePlan


class GATConv(nn.Module):
    out_features: int
    comm: Any
    num_heads: int = 1
    negative_slope: float = 0.2
    residual: bool = False
    dtype: Any = None  # None -> config.default_compute_dtype

    @nn.compact
    def __call__(self, x: jax.Array, plan: EdgePlan) -> jax.Array:
        from dgraph_tpu import config as _cfg

        dt = _cfg.resolve_compute_dtype(self.dtype)
        H, D = self.num_heads, self.out_features
        w = nn.Dense(H * D, use_bias=False, name="proj", dtype=dt)
        hx = w(x).reshape(-1, H, D)  # [n_pad, H, D]

        a_src = self.param("att_src", nn.initializers.glorot_uniform(), (H, D))
        a_dst = self.param("att_dst", nn.initializers.glorot_uniform(), (H, D))
        # cast params to the compute dtype: f32 attention params would
        # promote the [e_pad, H, D] tensors (the HBM-dominant ones) back
        # to f32 and forfeit the bf16 bandwidth win
        a_src = a_src.astype(hx.dtype)
        a_dst = a_dst.astype(hx.dtype)

        flat = hx.reshape(-1, H * D)
        out = head_chunked_attention(
            self.comm, flat, flat, a_src, a_dst, plan, self.negative_slope
        )
        out = out.mean(axis=1)  # head-mean (reference RGAT uses concat+proj; mean keeps D)
        if self.residual:
            out = out + nn.Dense(D, use_bias=False, name="res", dtype=dt)(x)
        return out


class GAT(nn.Module):
    hidden_features: int
    out_features: int
    comm: Any
    num_layers: int = 2
    num_heads: int = 4
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, plan: EdgePlan) -> jax.Array:
        from dgraph_tpu import config as _cfg

        # children resolve None themselves; only the head Dense needs the
        # concrete dtype here
        for _ in range(self.num_layers):
            x = GATConv(
                self.hidden_features, comm=self.comm, num_heads=self.num_heads,
                dtype=self.dtype,
            )(x, plan)
            x = nn.elu(x)
        head_dt = _cfg.resolve_compute_dtype(self.dtype)
        return nn.Dense(self.out_features, dtype=head_dt)(x).astype(jnp.float32)
