"""Graph attention (GAT) with distributed softmax-over-incoming-edges.

Reference parity: ``experiments/OGB-LSC/RGAT.py:127-268`` (CommAwareGAT).
The reference, with src-owned edges, needs SIX comm ops per layer (gather
h_i, gather h_j, scatter denominator, gather denominator, scatter messages,
plus norm round-trips — ``RGAT.py:174-206``). With dst-owned edges (this
framework's default) the attention softmax is a purely LOCAL segment
operation on each shard — only the initial src-feature gather communicates.
One collective per layer instead of six; same math.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.ops import local as local_ops
from dgraph_tpu.plan import EdgePlan


class GATConv(nn.Module):
    out_features: int
    comm: Any
    num_heads: int = 1
    negative_slope: float = 0.2
    residual: bool = False
    dtype: Any = None  # None -> config.default_compute_dtype

    @nn.compact
    def __call__(self, x: jax.Array, plan: EdgePlan) -> jax.Array:
        if plan.halo_side != "src":
            raise ValueError(
                "GATConv requires dst-owned edges (halo_side='src') so the "
                "attention softmax is rank-local; build the plan with "
                "edge_owner='dst'"
            )
        from dgraph_tpu import config as _cfg

        dt = _cfg.resolve_compute_dtype(self.dtype)
        H, D = self.num_heads, self.out_features
        w = nn.Dense(H * D, use_bias=False, name="proj", dtype=dt)
        hx = w(x).reshape(-1, H, D)  # [n_pad, H, D]

        a_src = self.param("att_src", nn.initializers.glorot_uniform(), (H, D))
        a_dst = self.param("att_dst", nn.initializers.glorot_uniform(), (H, D))
        # cast params to the compute dtype: f32 attention params would
        # promote the [e_pad, H, D] tensors (the HBM-dominant ones) back
        # to f32 and forfeit the bf16 bandwidth win
        a_src = a_src.astype(hx.dtype)
        a_dst = a_dst.astype(hx.dtype)

        def head_group(hs_c, hd_c, a_s, a_d):
            """Attention for a contiguous head group — heads are fully
            independent (per-head logits, per-head softmax), so the math
            is exact for any grouping (models/gcn.py chunking rationale:
            keeps every [e_pad, *] intermediate <= gather_col_block wide)."""
            logits = (hs_c * a_s).sum(-1) + (hd_c * a_d).sum(-1)  # [e_pad, Hg]
            logits = nn.leaky_relu(logits, self.negative_slope)
            # local softmax over incoming edges of each dst vertex
            alpha = local_ops.segment_softmax(
                logits, plan.dst_index, plan.n_dst_pad, plan.edge_mask,
                indices_are_sorted=plan.ids_sorted("dst"),
            )  # [e_pad, Hg]
            hg = hs_c.shape[1]
            msg = (alpha[..., None] * hs_c).reshape(-1, hg * D)
            return self.comm.scatter_sum(msg, plan, side="dst").reshape(
                -1, hg, D)

        from dgraph_tpu.comm.collectives import map_feature_chunks

        # heads per chunk: head groups are the chunking granularity (the
        # softmax couples features within a head, never across heads);
        # halo_side == "src" is guaranteed by the guard above
        gh = max(1, (_cfg.gather_col_block or H * D) // D)
        flat = hx.reshape(-1, H * D)
        hx_ext = self.comm.halo_extend(flat, plan, side="src")

        def group(sl):
            h0, h1 = sl.start // D, sl.stop // D
            hs_c = self.comm.local_take(
                hx_ext[:, sl], plan, side="src").reshape(-1, h1 - h0, D)
            hd_c = self.comm.local_take(
                flat[:, sl], plan, side="dst").reshape(-1, h1 - h0, D)
            agg = head_group(hs_c, hd_c, a_src[h0:h1], a_dst[h0:h1])
            return agg.reshape(-1, (h1 - h0) * D)

        out = map_feature_chunks(group, H * D, chunk=gh * D).reshape(-1, H, D)
        out = out.mean(axis=1)  # head-mean (reference RGAT uses concat+proj; mean keeps D)
        if self.residual:
            out = out + nn.Dense(D, use_bias=False, name="res", dtype=dt)(x)
        return out


class GAT(nn.Module):
    hidden_features: int
    out_features: int
    comm: Any
    num_layers: int = 2
    num_heads: int = 4
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, plan: EdgePlan) -> jax.Array:
        from dgraph_tpu import config as _cfg

        # children resolve None themselves; only the head Dense needs the
        # concrete dtype here
        for _ in range(self.num_layers):
            x = GATConv(
                self.hidden_features, comm=self.comm, num_heads=self.num_heads,
                dtype=self.dtype,
            )(x, plan)
            x = nn.elu(x)
        head_dt = _cfg.resolve_compute_dtype(self.dtype)
        return nn.Dense(self.out_features, dtype=head_dt)(x).astype(jnp.float32)
