"""Plain MLP building block (GraphCast's MeshGraphMLP analogue,
``experiments/GraphCast/layers.py:24-79``: hidden layers + optional
LayerNorm on the output)."""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import flax.linen as nn
import jax


class MLP(nn.Module):
    features: Sequence[int]
    activation: Callable = nn.silu
    use_layer_norm: bool = False
    dtype: Optional[object] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from dgraph_tpu import config as _cfg

        dtype = _cfg.resolve_compute_dtype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=dtype)(x)
            if i < len(self.features) - 1:
                x = self.activation(x)
        if self.use_layer_norm:
            x = nn.LayerNorm(dtype=dtype)(x)
        return x
