"""Distributed graph convolution (GCN).

Reference parity: ``experiments/OGB/GCN.py`` —
``GraphConvLayer`` (``GCN.py:28-67``): per-edge concat of src/dst features →
Linear → ReLU → scatter_add aggregation; ``CommAwareGCN`` (``GCN.py:70-118``):
two conv layers with halo exchanges + final fc.

TPU-first: the layer is written per-shard against the
:class:`~dgraph_tpu.comm.communicator._BaseComm` API, so the same module runs
single-device (SingleComm) or mesh-sharded inside shard_map (TpuComm) — the
reference's dummy-communicator pattern (``GraphCast/dist_utils.py:8-39``).
Aggregation defaults to the edge-owner side ('dst'), where the segment-sum is
rank-local; an optional symmetric-normalization edge weight reproduces
standard GCN (Kipf-Welling) semantics.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.plan import EdgePlan


class GraphConvLayer(nn.Module):
    """concat(src, dst) -> Dense -> activation -> scatter-sum to `aggregate_to`.

    Parity: ``experiments/OGB/GCN.py:28-67``, which fuses the ReLU into the
    CUDA scatter kernel (``local_data_kernels.cuh:34-72``). Here that fusion
    lives inside the Pallas kernel too (``pallas_call`` is an XLA fusion
    barrier, so without it the [E, F] message tensor round-trips HBM):
    relu default + owner-side aggregation takes the fused
    ``scatter_bias_relu`` path below.
    """

    out_features: int
    comm: Any  # _BaseComm (static dataclass)
    aggregate_to: str = "dst"
    activation: Any = nn.relu
    dtype: Any = None  # compute dtype (e.g. jnp.bfloat16); params stay f32

    @nn.compact
    def __call__(
        self,
        x: jax.Array,  # [n_pad, F] per-shard vertex features
        plan: EdgePlan,  # per-shard plan
        edge_weight: Optional[jax.Array] = None,  # [e_pad]
    ) -> jax.Array:
        # TPU-first algebra: Dense(concat(h_src, h_dst)) == Dense_s(h_src) +
        # Dense_d(h_dst), so project at the VERTEX level ([N,F]@[F,D], N << E)
        # and gather the projected D-dim rows — instead of materializing the
        # [E, 2F] concat the reference builds per edge (GCN.py:34-67). Saves
        # ~(E/N)x matmul FLOPs and the [E,2F] HBM round trip; exact same math.
        from dgraph_tpu import config as _cfg

        dt = _cfg.resolve_compute_dtype(self.dtype)
        h_s = nn.Dense(self.out_features, name="src_proj", dtype=dt)(x)
        h_d = nn.Dense(self.out_features, use_bias=False, name="dst_proj", dtype=dt)(x)
        # fused path (relu + owner-side aggregation, homogeneous plans):
        # the owner-side projection rides into the scatter kernel as a
        # per-vertex-block bias, so the [E, F] message tensor never exists
        # (collectives.scatter_bias_relu; falls back to composed ops
        # off-TPU — same math, pinned by the equivalence tests)
        # Feature-chunked edge pipeline: every per-edge intermediate is at
        # most gather_col_block (128) wide. The r3 jaxpr audit showed the
        # epoch's HBM traffic dominated by [E, D]-sized tensors that exist
        # only as glue — the col-split gather's concat, the activation
        # round trip — and none of them fuse past a gather/pallas_call
        # boundary. Chunking the LOCAL work (take -> activation -> scatter
        # per 128-wide slice) removes every edge-level concat: the only
        # concat left is [N, D] at the vertex level (~E/N smaller). The
        # halo exchange is hoisted to ONE full-width collective per side
        # (comm.halo_extend) so chunking never multiplies all_to_alls.
        # Gated on: feature-separable activation (relu — softmax-style
        # activations normalize ACROSS features and must see full width)
        # and a collective-free aggregation side.
        from dgraph_tpu.comm.collectives import map_feature_chunks

        D = self.out_features

        def over_chunks(fn):
            return map_feature_chunks(fn, D)

        # Split routing (plans carrying an interior/boundary split whose
        # resolved halo lowering is 'overlap' or 'pallas_p2p'): issue the
        # boundary exchange FIRST — double-buffered ppermute rounds or
        # device-initiated one-sided puts, halo_exchange_split decides —
        # aggregate interior edges from the local tables while it flies,
        # merge the landed boundary contributions last. Same math — relu
        # is per-edge and the aggregation sums over a partitioned edge
        # set — with the collective hidden behind the interior work.
        use_overlap = self.comm.split_active(plan)

        if (
            self.activation is nn.relu
            and plan.homogeneous
            and self.aggregate_to != plan.halo_side
        ):
            owner, stream = self.aggregate_to, (
                "src" if self.aggregate_to == "dst" else "dst"
            )
            h_bias = h_d if owner == "dst" else h_s
            h_stream = h_s if owner == "dst" else h_d
            if use_overlap:
                halo_buf = self.comm.halo_exchange_split(h_stream, plan)
                return over_chunks(
                    lambda sl: self.comm.scatter_bias_relu_overlap(
                        h_stream[:, sl], halo_buf[:, sl], h_bias[:, sl],
                        plan, side=owner, edge_weight=edge_weight,
                    )
                )
            h_ext = self.comm.halo_extend(h_stream, plan, side=stream)
            return over_chunks(
                lambda sl: self.comm.scatter_bias_relu(
                    self.comm.local_take(h_ext[:, sl], plan, side=stream),
                    h_bias[:, sl], plan, side=owner, edge_weight=edge_weight,
                )
            )

        separable = self.activation in (nn.relu, jax.nn.relu)
        if separable and self.aggregate_to != plan.halo_side:
            if use_overlap:
                owner = self.aggregate_to
                h_halo = h_s if plan.halo_side == "src" else h_d
                h_own = h_d if plan.halo_side == "src" else h_s
                halo_buf = self.comm.halo_exchange_split(h_halo, plan)
                from dgraph_tpu.comm.collectives import overlap_edge_weight

                w_int, w_bnd = overlap_edge_weight(edge_weight, plan)

                def chunked_ov(sl):
                    m_i = self.comm.interior_take(
                        h_halo[:, sl], plan, side=plan.halo_side
                    ) + self.comm.interior_take(h_own[:, sl], plan, side=owner)
                    m_i = self.activation(m_i)
                    if w_int is not None:
                        m_i = m_i * w_int[:, None]
                    agg = self.comm.interior_scatter_sum(m_i, plan, side=owner)
                    m_b = self.comm.boundary_take(
                        halo_buf[:, sl], plan, side=plan.halo_side
                    ) + self.comm.boundary_take(h_own[:, sl], plan, side=owner)
                    m_b = self.activation(m_b)
                    if w_bnd is not None:
                        m_b = m_b * w_bnd[:, None]
                    return agg + self.comm.boundary_scatter_sum(
                        m_b, plan, side=owner
                    )

                return over_chunks(chunked_ov)
            hs_ext = self.comm.halo_extend(h_s, plan, side="src")
            hd_ext = self.comm.halo_extend(h_d, plan, side="dst")

            def chunked(sl):
                m = self.comm.local_take(
                    hs_ext[:, sl], plan, side="src"
                ) + self.comm.local_take(hd_ext[:, sl], plan, side="dst")
                m = self.activation(m)
                if edge_weight is not None:
                    m = m * edge_weight[:, None]
                return self.comm.scatter_sum(m, plan, side=self.aggregate_to)

            return over_chunks(chunked)

        # full-width fallback: non-separable activation or halo-side
        # aggregation (chunking would repeat the reverse exchange)
        m = self.comm.gather(h_s, plan, side="src") + self.comm.gather(
            h_d, plan, side="dst"
        )
        m = self.activation(m)
        if edge_weight is not None:
            m = m * edge_weight[:, None]
        return self.comm.scatter_sum(m, plan, side=self.aggregate_to)


class GCN(nn.Module):
    """Two GraphConv layers + linear head (``CommAwareGCN``, GCN.py:70-118)."""

    hidden_features: int
    out_features: int
    comm: Any
    num_layers: int = 2
    aggregate_to: str = "dst"
    dropout_rate: float = 0.0
    dtype: Any = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        plan: EdgePlan,
        edge_weight: Optional[jax.Array] = None,
        deterministic: bool = True,
    ) -> jax.Array:
        from dgraph_tpu import config as _cfg

        for _ in range(self.num_layers):
            x = GraphConvLayer(
                self.hidden_features,
                comm=self.comm,
                aggregate_to=self.aggregate_to,
                dtype=self.dtype,
            )(x, plan, edge_weight)
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        head_dt = _cfg.resolve_compute_dtype(self.dtype)
        return nn.Dense(self.out_features, dtype=head_dt)(x).astype(jnp.float32)
