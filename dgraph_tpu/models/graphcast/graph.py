"""Distributed GraphCast graph assembly: three partitioned edge sets + static
features, sharded over the mesh.

Reference parity: ``experiments/GraphCast/data_utils/graphcast_graph.py``
(DistributedGraphCastGraph + generator: icosahedral multimesh, METIS mesh
partition + renumber, grid2mesh/mesh2grid builders; ``:197-437``), with the
§2.6-noted constructor bugs fixed by construction (our plans are built in one
place with validated kwargs).

Partitioning: mesh vertices by RCM/greedy locality (METIS substitute); grid
points by latitude-band blocks (contiguous lat-major ids => block partition
is geographically contiguous). Edge ownership is 'dst' everywhere, so
aggregation in every NodeBlock is rank-local, and the only collectives are
the src-side halo gathers of the three relations.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from dgraph_tpu import partition as pt
from dgraph_tpu.models.graphcast import mesh as mesh_lib
from dgraph_tpu.plan import (
    EdgePlan,
    EdgePlanLayout,
    _pad_to,
    build_edge_plan,
    shard_edge_data,
    shard_vertex_data,
)


@dataclasses.dataclass
class GraphCastGraphs:
    world_size: int
    mesh_level: int
    num_grid: int
    num_mesh: int
    # plans
    mesh_plan: EdgePlan
    g2m_plan: EdgePlan
    m2g_plan: EdgePlan
    mesh_layout: EdgePlanLayout
    g2m_layout: EdgePlanLayout
    m2g_layout: EdgePlanLayout
    # renumberings
    grid_ren: pt.Renumbering
    mesh_ren: pt.Renumbering
    # static sharded features
    grid_node_static: np.ndarray  # [W, n_grid_pad, 4]
    mesh_node_static: np.ndarray  # [W, n_mesh_pad, 4]
    mesh_edge_static: np.ndarray  # [W, e_pad, 4]
    g2m_edge_static: np.ndarray
    m2g_edge_static: np.ndarray
    grid_mask: np.ndarray  # [W, n_grid_pad]
    mesh_mask: np.ndarray  # [W, n_mesh_pad]

    @property
    def n_grid_pad(self) -> int:
        return self.g2m_plan.n_src_pad

    @property
    def n_mesh_pad(self) -> int:
        return self.mesh_plan.n_src_pad


def node_static_features(xyz: np.ndarray, latlon: np.ndarray) -> np.ndarray:
    """[cos lat, sin lon * cos lat, cos lon * cos lat, sin lat] — the standard
    GraphCast node geometry features (rotation-aware variant of the
    reference's spherical features)."""
    lat = np.deg2rad(latlon[:, 0])
    lon = np.deg2rad(latlon[:, 1])
    return np.stack(
        [np.cos(lat), np.sin(lon) * np.cos(lat), np.cos(lon) * np.cos(lat), np.sin(lat)],
        axis=1,
    ).astype(np.float32)


def edge_static_features(
    src_xyz: np.ndarray, dst_xyz: np.ndarray, edges: np.ndarray
) -> np.ndarray:
    """[length, dx, dy, dz] per edge, length-normalized by the max length
    (the reference normalizes by max edge length too)."""
    d = src_xyz[edges[0]] - dst_xyz[edges[1]]
    length = np.linalg.norm(d, axis=1, keepdims=True)
    scale = max(length.max(), 1e-12)
    return np.concatenate([length / scale, d / scale], axis=1).astype(np.float32)


def build_graphcast_graphs(
    mesh_level: int,
    num_lat: int,
    num_lon: int,
    world_size: int,
    *,
    mesh_partition_method: str = "multilevel",  # ≙ reference's METIS mesh
    # partition; measured on the level-4 multimesh: cut 0.065 vs rcm's 0.38
    # at W=4 — halo volume scales with cut
    pad_multiple: int = 8,
) -> GraphCastGraphs:
    mm = mesh_lib.build_multimesh(mesh_level)
    grid_latlon, grid_xyz = mesh_lib.latlon_grid(num_lat, num_lon)
    g2m = mesh_lib.grid2mesh_edges(grid_xyz, mm)
    m2g = mesh_lib.mesh2grid_edges(grid_xyz, mm)
    num_grid, num_mesh = len(grid_xyz), len(mm.vertices)

    # --- partitions ---
    if world_size == 1:
        mesh_part = np.zeros(num_mesh, np.int32)
    elif mesh_partition_method == "rcm":
        mesh_part = pt.rcm_partition(mm.edges, num_mesh, world_size)
    elif mesh_partition_method in ("multilevel", "metis"):
        # the reference partitions its mesh with METIS
        # (GraphCast/data_utils/preprocess.py:14-31); the native multilevel
        # partitioner is its stand-in here
        mesh_part = pt.multilevel_partition(mm.edges, num_mesh, world_size)
    else:
        mesh_part = pt.greedy_bfs_partition(mm.edges, num_mesh, world_size)
    mesh_ren = pt.renumber_contiguous(mesh_part, world_size)
    grid_part = pt.block_partition(num_grid, world_size)  # latitude bands
    grid_ren = pt.renumber_contiguous(grid_part, world_size)

    n_mesh_pad = _pad_to(int(mesh_ren.counts.max(initial=1)), pad_multiple)
    n_grid_pad = _pad_to(int(grid_ren.counts.max(initial=1)), pad_multiple)

    def remap(edges, src_ren, dst_ren):
        return np.stack([src_ren.perm[edges[0]], dst_ren.perm[edges[1]]])

    mesh_edges_r = remap(mm.edges, mesh_ren, mesh_ren)
    g2m_r = remap(g2m, grid_ren, mesh_ren)
    m2g_r = remap(m2g, mesh_ren, grid_ren)

    mesh_plan, mesh_layout = build_edge_plan(
        mesh_edges_r, mesh_ren.partition, world_size=world_size, edge_owner="dst",
        n_src_pad=n_mesh_pad, n_dst_pad=n_mesh_pad, pad_multiple=pad_multiple,
    )
    g2m_plan, g2m_layout = build_edge_plan(
        g2m_r, grid_ren.partition, mesh_ren.partition, world_size=world_size,
        edge_owner="dst", n_src_pad=n_grid_pad, n_dst_pad=n_mesh_pad,
        pad_multiple=pad_multiple,
    )
    m2g_plan, m2g_layout = build_edge_plan(
        m2g_r, mesh_ren.partition, grid_ren.partition, world_size=world_size,
        edge_owner="dst", n_src_pad=n_mesh_pad, n_dst_pad=n_grid_pad,
        pad_multiple=pad_multiple,
    )

    # --- static features (renumbered order!) ---
    mesh_xyz_r = mm.vertices[mesh_ren.inv]
    grid_xyz_r = grid_xyz[grid_ren.inv]
    grid_latlon_r = grid_latlon[grid_ren.inv]
    mesh_latlon_r = xyz_to_latlon(mesh_xyz_r)

    grid_node_static = shard_vertex_data(
        node_static_features(grid_xyz_r, grid_latlon_r), grid_ren.counts, n_grid_pad
    )
    mesh_node_static = shard_vertex_data(
        node_static_features(mesh_xyz_r, mesh_latlon_r), mesh_ren.counts, n_mesh_pad
    )
    mesh_edge_static = shard_edge_data(
        edge_static_features(mesh_xyz_r, mesh_xyz_r, mesh_edges_r),
        mesh_layout, mesh_plan.e_pad,
    )
    g2m_edge_static = shard_edge_data(
        edge_static_features(grid_xyz_r, mesh_xyz_r, g2m_r), g2m_layout, g2m_plan.e_pad
    )
    m2g_edge_static = shard_edge_data(
        edge_static_features(mesh_xyz_r, grid_xyz_r, m2g_r), m2g_layout, m2g_plan.e_pad
    )
    grid_mask = shard_vertex_data(np.ones(num_grid, np.float32), grid_ren.counts, n_grid_pad)
    mesh_mask = shard_vertex_data(np.ones(num_mesh, np.float32), mesh_ren.counts, n_mesh_pad)

    return GraphCastGraphs(
        world_size=world_size,
        mesh_level=mesh_level,
        num_grid=num_grid,
        num_mesh=num_mesh,
        mesh_plan=mesh_plan,
        g2m_plan=g2m_plan,
        m2g_plan=m2g_plan,
        mesh_layout=mesh_layout,
        g2m_layout=g2m_layout,
        m2g_layout=m2g_layout,
        grid_ren=grid_ren,
        mesh_ren=mesh_ren,
        grid_node_static=grid_node_static,
        mesh_node_static=mesh_node_static,
        mesh_edge_static=mesh_edge_static,
        g2m_edge_static=g2m_edge_static,
        m2g_edge_static=m2g_edge_static,
        grid_mask=grid_mask,
        mesh_mask=mesh_mask,
    )


def xyz_to_latlon(xyz: np.ndarray) -> np.ndarray:
    lat = np.rad2deg(np.arcsin(np.clip(xyz[:, 2], -1, 1)))
    lon = np.rad2deg(np.arctan2(xyz[:, 1], xyz[:, 0])) % 360.0
    return np.stack([lat, lon], axis=1)
