from dgraph_tpu.models.graphcast.mesh import build_multimesh, icosahedron, MultiMesh
from dgraph_tpu.models.graphcast.graph import GraphCastGraphs, build_graphcast_graphs
from dgraph_tpu.models.graphcast.model import (
    GraphCast,
    MeshEdgeBlock,
    MeshNodeBlock,
    rollout,
)

__all__ = [
    "MultiMesh",
    "icosahedron",
    "build_multimesh",
    "GraphCastGraphs",
    "build_graphcast_graphs",
    "GraphCast",
    "MeshEdgeBlock",
    "MeshNodeBlock",
    "rollout",
]
