"""Icosahedral multimesh generation (pure numpy, written from scratch).

Reference behavior parity: ``experiments/GraphCast/data_utils/icosahedral_mesh.py``
(which vendors DeepMind's generator): repeatedly subdivide an icosahedron,
keep vertices of level l as a prefix of level l+1's vertices, and form the
MULTIMESH by merging the (bidirectional) edge sets of every level expressed
in the finest level's vertex numbering.

Structural anchors (asserted in tests, same constants as
``experiments/GraphCast/tests/test_single_graph_data.py:20-34``):
level 6 -> 40 962 vertices, 655 320 multimesh edges (= 2 * 30 * (4^7-1)/3).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class MultiMesh:
    vertices: np.ndarray  # [V, 3] unit-sphere positions (finest level)
    faces: np.ndarray  # [F, 3] finest-level triangles
    edges: np.ndarray  # [2, E] multimesh edges, bidirectional, deduped
    level: int


def icosahedron() -> tuple[np.ndarray, np.ndarray]:
    """Unit icosahedron: 12 vertices, 20 faces."""
    phi = (1.0 + np.sqrt(5.0)) / 2.0
    verts = np.array(
        [
            [-1, phi, 0], [1, phi, 0], [-1, -phi, 0], [1, -phi, 0],
            [0, -1, phi], [0, 1, phi], [0, -1, -phi], [0, 1, -phi],
            [phi, 0, -1], [phi, 0, 1], [-phi, 0, -1], [-phi, 0, 1],
        ],
        dtype=np.float64,
    )
    verts /= np.linalg.norm(verts, axis=1, keepdims=True)
    faces = np.array(
        [
            [0, 11, 5], [0, 5, 1], [0, 1, 7], [0, 7, 10], [0, 10, 11],
            [1, 5, 9], [5, 11, 4], [11, 10, 2], [10, 7, 6], [7, 1, 8],
            [3, 9, 4], [3, 4, 2], [3, 2, 6], [3, 6, 8], [3, 8, 9],
            [4, 9, 5], [2, 4, 11], [6, 2, 10], [8, 6, 7], [9, 8, 1],
        ],
        dtype=np.int64,
    )
    return verts, faces


def subdivide(verts: np.ndarray, faces: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """One 4-to-1 triangle subdivision; parent vertices keep their indices,
    midpoints are appended (prefix property the multimesh relies on)."""
    edge_mid: dict[tuple[int, int], int] = {}
    new_verts = [verts]
    next_id = len(verts)
    appended = []

    def midpoint(a: int, b: int) -> int:
        nonlocal next_id
        key = (a, b) if a < b else (b, a)
        if key not in edge_mid:
            m = verts[a] + verts[b]
            m /= np.linalg.norm(m)
            appended.append(m)
            edge_mid[key] = next_id
            next_id += 1
        return edge_mid[key]

    new_faces = np.empty((len(faces) * 4, 3), dtype=np.int64)
    for i, (a, b, c) in enumerate(faces):
        ab, bc, ca = midpoint(a, b), midpoint(b, c), midpoint(c, a)
        new_faces[4 * i + 0] = (a, ab, ca)
        new_faces[4 * i + 1] = (b, bc, ab)
        new_faces[4 * i + 2] = (c, ca, bc)
        new_faces[4 * i + 3] = (ab, bc, ca)
    all_verts = np.concatenate([verts, np.asarray(appended)], axis=0)
    return all_verts, new_faces


def faces_to_edges(faces: np.ndarray) -> np.ndarray:
    """Bidirectional unique edge list [2, E] of a triangle mesh."""
    e = np.concatenate(
        [faces[:, [0, 1]], faces[:, [1, 2]], faces[:, [2, 0]]], axis=0
    )
    e = np.concatenate([e, e[:, ::-1]], axis=0)
    e = np.unique(e, axis=0)
    return e.T.copy()


def build_multimesh(level: int) -> MultiMesh:
    """All-level merged mesh: vertices of the finest level, union of every
    level's bidirectional edges (the GraphCast 'multimesh')."""
    verts, faces = icosahedron()
    edge_sets = [faces_to_edges(faces)]
    for _ in range(level):
        verts, faces = subdivide(verts, faces)
        edge_sets.append(faces_to_edges(faces))
    edges = np.unique(np.concatenate(edge_sets, axis=1).T, axis=0).T.copy()
    return MultiMesh(vertices=verts, faces=faces, edges=edges, level=level)


def latlon_grid(num_lat: int, num_lon: int) -> tuple[np.ndarray, np.ndarray]:
    """Equiangular lat-lon grid -> (latlon [N, 2] degrees, xyz [N, 3]).

    Latitudes include both poles (721 rows = 0.25deg for ERA5, matching the
    reference's 721x1440 grid, ``graphcast_config.py``); longitudes wrap.
    Row-major (lat-major) flattening.
    """
    lats = np.linspace(90.0, -90.0, num_lat)
    lons = np.linspace(0.0, 360.0, num_lon, endpoint=False)
    lat_g, lon_g = np.meshgrid(lats, lons, indexing="ij")
    latlon = np.stack([lat_g.ravel(), lon_g.ravel()], axis=1)
    xyz = latlon_to_xyz(latlon)
    return latlon, xyz


def latlon_to_xyz(latlon: np.ndarray) -> np.ndarray:
    lat = np.deg2rad(latlon[:, 0])
    lon = np.deg2rad(latlon[:, 1])
    return np.stack(
        [np.cos(lat) * np.cos(lon), np.cos(lat) * np.sin(lon), np.sin(lat)], axis=1
    )


def grid2mesh_edges(
    grid_xyz: np.ndarray, mesh: MultiMesh, radius_fraction: float = 0.6
) -> np.ndarray:
    """Connect each grid point to all mesh vertices within
    ``radius_fraction * max_mesh_edge_length`` (the reference's 0.6 x max-edge
    radius graph, ``data_utils/utils.py:148-187``). Returns [2, E] with
    src=grid index, dst=mesh vertex index.
    """
    from scipy.spatial import cKDTree

    edge_vec = mesh.vertices[mesh.edges[0]] - mesh.vertices[mesh.edges[1]]
    max_len = np.linalg.norm(edge_vec, axis=1).max()
    radius = radius_fraction * max_len
    tree = cKDTree(mesh.vertices)
    nbrs = tree.query_ball_point(grid_xyz, r=radius)
    src = np.repeat(np.arange(len(grid_xyz)), [len(n) for n in nbrs])
    dst = np.concatenate([np.asarray(n, dtype=np.int64) for n in nbrs])
    return np.stack([src, dst]).astype(np.int64)


def mesh2grid_edges(grid_xyz: np.ndarray, mesh: MultiMesh) -> np.ndarray:
    """Connect each grid point to the 3 vertices of its nearest mesh face
    (face found by 1-NN on face centroids — the reference's scheme,
    ``data_utils/utils.py:112-145``). Returns [2, E] with src=mesh vertex,
    dst=grid index; exactly 3 edges per grid point.
    """
    from scipy.spatial import cKDTree

    centroids = mesh.vertices[mesh.faces].mean(axis=1)
    centroids /= np.linalg.norm(centroids, axis=1, keepdims=True)
    tree = cKDTree(centroids)
    _, fidx = tree.query(grid_xyz, k=1)
    tri = mesh.faces[fidx]  # [N, 3]
    dst = np.repeat(np.arange(len(grid_xyz)), 3)
    src = tri.ravel()
    return np.stack([src, dst]).astype(np.int64)
