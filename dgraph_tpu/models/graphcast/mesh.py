"""Icosahedral multimesh generation (pure numpy, written from scratch).

Reference behavior parity: ``experiments/GraphCast/data_utils/icosahedral_mesh.py``
(which vendors DeepMind's generator): repeatedly subdivide an icosahedron,
keep vertices of level l as a prefix of level l+1's vertices, and form the
MULTIMESH by merging the (bidirectional) edge sets of every level expressed
in the finest level's vertex numbering.

Structural anchors (asserted in tests, same constants as
``experiments/GraphCast/tests/test_single_graph_data.py:20-34``):
level 6 -> 40 962 vertices, 655 320 multimesh edges (= 2 * 30 * (4^7-1)/3).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class MultiMesh:
    vertices: np.ndarray  # [V, 3] unit-sphere positions (finest level)
    faces: np.ndarray  # [F, 3] finest-level triangles
    edges: np.ndarray  # [2, E] multimesh edges, bidirectional, deduped
    level: int


def icosahedron() -> tuple[np.ndarray, np.ndarray]:
    """Unit icosahedron: 12 vertices, 20 faces — in the GraphCast paper's
    orientation.

    The vertex set is the standard cyclic-permutation construction
    (Wikipedia "Regular icosahedron" Cartesian coordinates), rotated about
    the y-axis by (pi - angle_between_faces)/2 so a face plane (not an
    edge) is horizontal at the top. The orientation matters: the grid2mesh
    radius-graph edge COUNT depends on where mesh vertices sit relative to
    the lat-lon grid, and the paper's 1 618 824 anchor is only reproduced
    in this orientation (reference vendored generator,
    ``data_utils/icosahedral_mesh.py:100-181``).

    Faces are derived from the convex hull (outward-oriented) rather than a
    hand-checked table; only vertex POSITIONS affect downstream edge
    counts (midpoint vertices are position-determined).
    """
    from scipy.spatial import ConvexHull

    phi = (1.0 + np.sqrt(5.0)) / 2.0
    verts = []
    for c1 in (1.0, -1.0):
        for c2 in (phi, -phi):
            verts.extend([(c1, c2, 0.0), (0.0, c1, c2), (c2, 0.0, c1)])
    verts = np.asarray(verts, dtype=np.float64)
    verts /= np.linalg.norm([1.0, phi])
    # rotate about y: top becomes a face plane (angle between adjacent
    # faces of an icosahedron = 2*arcsin(phi/sqrt(3)))
    angle = (np.pi - 2.0 * np.arcsin(phi / np.sqrt(3.0))) / 2.0
    c, s = np.cos(angle), np.sin(angle)
    rot_y = np.array([[c, 0.0, s], [0.0, 1.0, 0.0], [-s, 0.0, c]])
    verts = verts @ rot_y
    hull = ConvexHull(verts)
    faces = hull.simplices.astype(np.int64)
    # orient each face counter-clockwise seen from outside
    n = np.cross(
        verts[faces[:, 1]] - verts[faces[:, 0]],
        verts[faces[:, 2]] - verts[faces[:, 0]],
    )
    centers = verts[faces].mean(axis=1)
    flip = (n * centers).sum(axis=1) < 0
    faces[flip] = faces[flip][:, ::-1]
    return verts, faces


def subdivide(verts: np.ndarray, faces: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """One 4-to-1 triangle subdivision; parent vertices keep their indices,
    midpoints are appended (prefix property the multimesh relies on)."""
    edge_mid: dict[tuple[int, int], int] = {}
    new_verts = [verts]
    next_id = len(verts)
    appended = []

    def midpoint(a: int, b: int) -> int:
        nonlocal next_id
        key = (a, b) if a < b else (b, a)
        if key not in edge_mid:
            m = verts[a] + verts[b]
            m /= np.linalg.norm(m)
            appended.append(m)
            edge_mid[key] = next_id
            next_id += 1
        return edge_mid[key]

    new_faces = np.empty((len(faces) * 4, 3), dtype=np.int64)
    for i, (a, b, c) in enumerate(faces):
        ab, bc, ca = midpoint(a, b), midpoint(b, c), midpoint(c, a)
        new_faces[4 * i + 0] = (a, ab, ca)
        new_faces[4 * i + 1] = (b, bc, ab)
        new_faces[4 * i + 2] = (c, ca, bc)
        new_faces[4 * i + 3] = (ab, bc, ca)
    all_verts = np.concatenate([verts, np.asarray(appended)], axis=0)
    return all_verts, new_faces


def faces_to_edges(faces: np.ndarray) -> np.ndarray:
    """Bidirectional unique edge list [2, E] of a triangle mesh."""
    e = np.concatenate(
        [faces[:, [0, 1]], faces[:, [1, 2]], faces[:, [2, 0]]], axis=0
    )
    e = np.concatenate([e, e[:, ::-1]], axis=0)
    e = np.unique(e, axis=0)
    return e.T.copy()


def build_multimesh(level: int) -> MultiMesh:
    """All-level merged mesh: vertices of the finest level, union of every
    level's bidirectional edges (the GraphCast 'multimesh')."""
    verts, faces = icosahedron()
    edge_sets = [faces_to_edges(faces)]
    for _ in range(level):
        verts, faces = subdivide(verts, faces)
        edge_sets.append(faces_to_edges(faces))
    edges = np.unique(np.concatenate(edge_sets, axis=1).T, axis=0).T.copy()
    return MultiMesh(vertices=verts, faces=faces, edges=edges, level=level)


def latlon_grid(num_lat: int, num_lon: int) -> tuple[np.ndarray, np.ndarray]:
    """Equiangular lat-lon grid -> (latlon [N, 2] degrees, xyz [N, 3]).

    Latitudes include both poles (721 rows = 0.25deg for ERA5, matching the
    reference's 721x1440 grid, ``graphcast_config.py``); longitudes wrap.
    Row-major (lat-major) flattening.
    """
    lats = np.linspace(90.0, -90.0, num_lat)
    lons = np.linspace(0.0, 360.0, num_lon, endpoint=False)
    lat_g, lon_g = np.meshgrid(lats, lons, indexing="ij")
    latlon = np.stack([lat_g.ravel(), lon_g.ravel()], axis=1)
    xyz = latlon_to_xyz(latlon)
    return latlon, xyz


def latlon_to_xyz(latlon: np.ndarray) -> np.ndarray:
    lat = np.deg2rad(latlon[:, 0])
    lon = np.deg2rad(latlon[:, 1])
    return np.stack(
        [np.cos(lat) * np.cos(lon), np.cos(lat) * np.sin(lon), np.sin(lat)], axis=1
    )


def grid2mesh_edges(
    grid_xyz: np.ndarray,
    mesh: MultiMesh,
    radius_fraction: float = 0.6,
    max_neighbors: int = 4,
) -> np.ndarray:
    """Connect each grid point to its <=``max_neighbors`` nearest mesh
    vertices that lie strictly within
    ``radius_fraction * max_FINEST_mesh_edge_length``.

    Exact behavior parity with the reference
    (``data_utils/utils.py:143-187``: 4-NN query, strict ``<`` radius test)
    including two subtleties that change the edge count:
    - the radius is measured on the FINEST-level mesh
      (``graphcast_graph.py:299-301`` / ``spatial_utils.py:21-44``), not the
      multimesh — the multimesh contains level-0 icosahedron edges whose
      ~1.05 chord length would inflate the radius ~6x and the edge count ~40x;
    - neighbors are capped at 4 per grid point, so the count at level 6 /
      721x1440 is exactly 1 618 824 (the reference's anchor,
      ``tests/test_single_graph_data.py:27-29``), not the ~1.63M an
      uncapped radius query yields.

    Vectorized as one batched k-NN query instead of ``query_ball_point``'s
    per-point Python lists (VERDICT r1 flagged the list-of-lists path at 1M+
    grid points). Returns [2, E] with src=grid index, dst=mesh vertex index.
    """
    from scipy.spatial import cKDTree

    finest = faces_to_edges(mesh.faces)
    edge_vec = mesh.vertices[finest[0]] - mesh.vertices[finest[1]]
    radius = radius_fraction * np.linalg.norm(edge_vec, axis=1).max()
    tree = cKDTree(mesh.vertices)
    dist, idx = tree.query(grid_xyz, k=max_neighbors, workers=-1)
    in_range = dist < radius  # strict <, reference utils.py:157
    src = np.broadcast_to(
        np.arange(len(grid_xyz), dtype=np.int64)[:, None], idx.shape
    )[in_range]
    dst = idx[in_range]
    return np.stack([src, dst]).astype(np.int64)


def mesh2grid_edges(grid_xyz: np.ndarray, mesh: MultiMesh) -> np.ndarray:
    """Connect each grid point to the 3 vertices of its nearest mesh face
    (face found by 1-NN on face centroids — the reference's scheme,
    ``data_utils/utils.py:112-145``). Returns [2, E] with src=mesh vertex,
    dst=grid index; exactly 3 edges per grid point.
    """
    from scipy.spatial import cKDTree

    centroids = mesh.vertices[mesh.faces].mean(axis=1)
    centroids /= np.linalg.norm(centroids, axis=1, keepdims=True)
    tree = cKDTree(centroids)
    _, fidx = tree.query(grid_xyz, k=1)
    tri = mesh.faces[fidx]  # [N, 3]
    dst = np.repeat(np.arange(len(grid_xyz)), 3)
    src = tri.ravel()
    return np.stack([src, dst]).astype(np.int64)
