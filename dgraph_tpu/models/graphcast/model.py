"""GraphCast model: encode grid->mesh, process multimesh, decode mesh->grid.

Reference parity: ``experiments/GraphCast/model.py`` — ``DGraphCast``
(Embedder + Encoder + Processor(N layers) + Decoder + final MLP with residual
grid prediction, ``model.py:311-394``) built from ``MeshGraphMLP`` /
``MeshEdgeBlock`` / ``MeshNodeBlock`` (``layers.py:24-216``).

Each EdgeBlock gathers both endpoint features (2 comm ops in the reference,
``layers.py:182-216``; here only the src side communicates since edges are
dst-owned) and each NodeBlock is a rank-local segment sum.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from dgraph_tpu.models.mlp import MLP


class MeshEdgeBlock(nn.Module):
    """e' = e + MLP([e, h_src(gathered), h_dst(gathered)]) — layers.py:146-216.

    TPU-first algebra: the first MLP layer is computed as
    ``act(D_e(e) + gather(D_s(x_src)) + gather(D_d(x_dst)))`` — splitting
    the [3L -> L] Dense by input rows — instead of materializing the
    [E, 3L] concat the reference builds (``layers.py:182-216``). Exact same
    math (the concat-Dense's weight matrix split into three blocks), but
    the projections run at the VERTEX level (N << E) and the [E, 3L]
    tensor never exists: at m2g scale (3.11M edges, latent 256, bf16) that
    single tensor is 4.8 GB and its elimination is what lets level-6 AD
    fit one v5e chip."""

    latent: int
    comm: Any
    dtype: Any = None

    @nn.compact
    def __call__(self, e, x_src, x_dst, plan):
        L = self.latent
        hs = nn.Dense(L, use_bias=False, name="src_proj", dtype=self.dtype)(x_src)
        hd = nn.Dense(L, use_bias=False, name="dst_proj", dtype=self.dtype)(x_dst)
        e_proj = nn.Dense(L, name="edge_proj", dtype=self.dtype)(e)
        # feature-chunked first stage (models/gcn.py rationale): silu and
        # the 3-way add are elementwise, so each <=col_block-wide slice is
        # computed independently from chunk-wide takes — the two
        # per-gather col-split concats collapse into the single [E, L] h
        # tensor the MLP needs anyway. halo_extend is the identity on the
        # non-halo side, so ONE exchange happens regardless of which side
        # carries the halo.
        from dgraph_tpu.comm.collectives import map_feature_chunks

        hs_ext = self.comm.halo_extend(hs, plan, side="src")
        hd_ext = self.comm.halo_extend(hd, plan, side="dst")
        h = map_feature_chunks(
            lambda sl: nn.silu(
                e_proj[:, sl]
                + self.comm.local_take(hs_ext[:, sl], plan, side="src")
                + self.comm.local_take(hd_ext[:, sl], plan, side="dst")
            ),
            L,
        )
        upd = MLP([self.latent], use_layer_norm=True, dtype=self.dtype)(h)
        return e + upd


class MeshNodeBlock(nn.Module):
    """x' = x + MLP([x, sum of incoming edge features]) — layers.py:82-143."""

    latent: int
    comm: Any
    dtype: Any = None

    @nn.compact
    def __call__(self, x_dst, e, plan):
        agg = self.comm.scatter_sum(e, plan, side="dst")
        upd = MLP([self.latent, self.latent], use_layer_norm=True, dtype=self.dtype)(
            jnp.concatenate([x_dst, agg], axis=-1)
        )
        return x_dst + upd


class GraphCast(nn.Module):
    """Full model. Inputs are per-shard; statics come from
    :class:`~dgraph_tpu.models.graphcast.graph.GraphCastGraphs`.

    Args to __call__:
      grid_feats: [n_grid_pad, C_in] dynamic grid state (weather channels).
      statics: dict with grid_node_static / mesh_node_static /
        {mesh,g2m,m2g}_edge_static per-shard arrays.
      plans: dict with 'mesh', 'g2m', 'm2g' per-shard EdgePlans.
    Returns [n_grid_pad, C_out] residual prediction added to the input
    channels (``model.py:392-394``).
    """

    latent: int = 64
    processor_layers: int = 4
    out_channels: int = 73
    comm: Any = None
    dtype: Any = None  # compute dtype (bfloat16 recommended on TPU)
    remat: bool = True  # rematerialize EVERY block under AD, not just the
    # processor: at level-6 scale the encoder/decoder blocks and the edge
    # embedders each hold several [3.11M, L] intermediates (1.6 GB apiece in
    # bf16 at L=256) for the backward — without remat the decoder alone
    # overflows a 16 GB chip. Saved state drops to the residual streams;
    # trades ~2x recompute FLOPs for the memory that lets 16-layer level-6
    # training fit one v5e (jax.checkpoint, SURVEY §5 memory knobs)

    @nn.compact
    def __call__(self, grid_feats, statics, plans):
        L = self.latent
        EdgeB = nn.remat(MeshEdgeBlock) if self.remat else MeshEdgeBlock
        NodeB = nn.remat(MeshNodeBlock) if self.remat else MeshNodeBlock
        Emb = nn.remat(MLP) if self.remat else MLP
        # --- Embedder: 5 MLPs (model.py:79-105) ---
        g = Emb([L, L], use_layer_norm=True, dtype=self.dtype, name="embed_grid")(
            jnp.concatenate([grid_feats, statics["grid_node_static"]], axis=-1)
        )
        m = Emb([L, L], use_layer_norm=True, dtype=self.dtype, name="embed_mesh")(
            statics["mesh_node_static"]
        )
        e_mesh = Emb([L, L], use_layer_norm=True, dtype=self.dtype, name="embed_mesh_edges")(
            statics["mesh_edge_static"]
        )
        e_g2m = Emb([L, L], use_layer_norm=True, dtype=self.dtype, name="embed_g2m_edges")(
            statics["g2m_edge_static"]
        )
        e_m2g = Emb([L, L], use_layer_norm=True, dtype=self.dtype, name="embed_m2g_edges")(
            statics["m2g_edge_static"]
        )

        # --- Encoder: grid -> mesh (model.py:142-168) ---
        e_g2m = EdgeB(L, self.comm, dtype=self.dtype, name="enc_edge")(e_g2m, g, m, plans["g2m"])
        m = NodeB(L, self.comm, dtype=self.dtype, name="enc_node")(m, e_g2m, plans["g2m"])
        g = g + Emb([L, L], use_layer_norm=True, dtype=self.dtype, name="enc_grid_mlp")(g)

        # --- Processor: multimesh message passing (model.py:208-230) ---
        for i in range(self.processor_layers):
            e_mesh = EdgeB(L, self.comm, dtype=self.dtype, name=f"proc_edge_{i}")(
                e_mesh, m, m, plans["mesh"]
            )
            m = NodeB(L, self.comm, dtype=self.dtype, name=f"proc_node_{i}")(
                m, e_mesh, plans["mesh"]
            )

        # --- Decoder: mesh -> grid (model.py:268-308) ---
        e_m2g = EdgeB(L, self.comm, dtype=self.dtype, name="dec_edge")(e_m2g, m, g, plans["m2g"])
        g = NodeB(L, self.comm, dtype=self.dtype, name="dec_node")(g, e_m2g, plans["m2g"])

        # --- prediction head: residual over input channels (model.py:392-394) ---
        delta = MLP([L, self.out_channels], dtype=self.dtype, name="head")(g)
        return grid_feats[..., : self.out_channels] + delta.astype(jnp.float32)


def rollout(model: GraphCast, params, x0, statics, plans, num_steps: int):
    """Autoregressive multi-step forecast: ``x_{t+1} = model(x_t)``.

    The model's output IS the next full state (residual head over the
    input channels), so chaining requires ``out_channels`` == the input
    channel count. One ``lax.scan`` — the whole rollout is a single
    compiled program (GraphCast's eval protocol; the reference repo
    trains one-step only and has no rollout driver).

    Returns [num_steps, n_grid_pad, C]: the predicted trajectory
    x_1 .. x_{num_steps} (x0 excluded).
    """

    def step(x, _):
        nxt = model.apply(params, x, statics, plans)
        return nxt, nxt

    _, traj = jax.lax.scan(step, x0, None, length=num_steps)
    return traj
