from dgraph_tpu.models.mlp import MLP
from dgraph_tpu.models.gcn import GraphConvLayer, GCN
from dgraph_tpu.models.sage import SAGEConv, GraphSAGE
from dgraph_tpu.models.gat import GATConv, GAT
from dgraph_tpu.models.norm import DistributedBatchNorm
from dgraph_tpu.models.rgat import RGAT, RGATLayer, RelationalAttention
from dgraph_tpu.models.graph_transformer import GPSLayer, GraphTransformer
from dgraph_tpu.models.transformer import SeqTransformerLM, TransformerBlock

__all__ = [
    "GPSLayer",
    "SeqTransformerLM",
    "TransformerBlock",
    "GraphTransformer",
    "RGAT",
    "RGATLayer",
    "RelationalAttention",
    "MLP",
    "GraphConvLayer",
    "GCN",
    "SAGEConv",
    "GraphSAGE",
    "GATConv",
    "GAT",
    "DistributedBatchNorm",
]
