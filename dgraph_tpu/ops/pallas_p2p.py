"""Pallas TPU kernel: device-initiated one-sided halo transport.

The TPU analogue of DGraph's NVSHMEM backend — its fastest, precisely
because the halo exchange is GPU-initiated one-sided puts instead of
host-scheduled collectives (PAPER.md L1/L2; "Demystifying NVSHMEM",
PAPERS.md). Here the boundary tiles move as ``pltpu.make_async_remote_copy``
puts issued from INSIDE one Pallas kernel:

- One put per live ``halo_delta``: tile ``k`` (the ``[S, F]`` block headed
  to peer ``(me + sign*deltas[k]) % W``) DMAs straight into the
  destination shard's ``[W*S, F]`` halo buffer at rows
  ``[me*S, (me+1)*S)`` — the plan's halo-slot numbering, so no receive
  placement pass and no separate exchange buffer staged through HBM
  (``ppermute`` rounds stage one send block + one recv block per round;
  ``all_to_all`` stages the full padded ``[W, S, F]`` operand).
- DMA semaphores live in kernel scratch (one send/recv pair per delta);
  every put is started before any is waited on, so all tiles are on the
  wire concurrently — "The Big Send-off" (PAPERS.md) motivates exactly
  this per-tile DMA shape for sparse neighbor traffic.
- The fused-mask variant stages tile ``k`` in a two-slot VMEM buffer,
  applies the plan's ``send_mask`` there (an exact elementwise multiply —
  bit-parity with the jnp path is free), and puts from VMEM: tile
  ``k+1``'s stage+mask overlaps tile ``k``'s in-flight put (double
  buffering; slot reuse waits the put two tiles back). The masked send
  block never exists in HBM at all.
- A barrier semaphore (``pltpu.get_barrier_semaphore``) makes every
  sender wait until each shard it writes to has entered the kernel — a
  put must never land in a buffer the receiver has not allocated+zeroed
  yet. (Pallas interpret mode executes shards lock-step and does not
  model the race; the barrier is compiled only for real Mosaic
  lowerings.)

Off-TPU the kernels run in Pallas ``interpret=True`` mode — that is how
the tier-1 parity pins (bit-identical fwd+bwd vs the ``all_to_all``
lowering, ``tests/test_pallas_p2p.py``) run on the CPU backend without a
chip. The transport itself is a pure data movement: every arithmetic op
that decides a bit (gather, mask multiply, segment-sum) is either the
exact same jnp op the ``all_to_all`` path runs or an exact elementwise
multiply inside the kernel.

``python -m dgraph_tpu.ops.pallas_p2p --selftest true`` is the
interpret-mode smoke ``scripts/check.py`` runs (tiny CPU compiles only).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Largest [n_deltas, S, F] send stack (bytes) the fused-mask variant will
# hold in VMEM (two staging slots ride alongside it). Bigger stacks fall
# back to pre-masked HBM-direct puts — same values, no VMEM staging.
FUSED_MASK_VMEM_BUDGET = 4 * 1024 * 1024

# collective_id for the kernel's barrier semaphore (one id is enough: the
# barrier self-resets — each wait decrements what the signals added — and
# XLA orders the kernels within a program by their data dependencies)
P2P_COLLECTIVE_ID = 7


def p2p_interpret_mode() -> bool:
    """True when the p2p kernels must run under the Pallas interpreter
    (any non-TPU backend — the tier-1/CPU path)."""
    return jax.default_backend() != "tpu"


def _logical_device_ids(axis_name, graph_ids):
    """Raveled LOGICAL device ids over the FULL axis env (row-major in
    env order) with the ``axis_name`` component replaced by ``graph_ids``
    — a ``('replica', 'graph')`` mesh must target
    ``replica_idx * W + graph_rank``, not the bare graph rank (both real
    Mosaic lowerings and the interpret discharge shim in
    :func:`dgraph_tpu.compat.install_multiaxis_remote_dma` number devices
    this way)."""
    try:
        from jax._src import core as jax_core

        sizes = jax_core.get_axis_env().axis_sizes
        axes = [(a, s) for a, s in sizes.items() if a is not None]
    except Exception as e:  # axis env introspection is jax-internal —
        # fail LOUDLY: silently falling back to bare graph ranks would
        # address replica 0's devices from every replica on a
        # ('replica', 'graph') mesh (corrupted halos, no error raised)
        raise RuntimeError(
            "pallas_p2p cannot introspect the mesh axis env to compute "
            "logical device ids (jax-internal API changed?); update "
            "dgraph_tpu.ops.pallas_p2p._logical_device_ids for this jax "
            f"version ({jax.__version__})"
        ) from e
    ids = jnp.zeros((), jnp.int32)
    for a, s in axes:
        comp = graph_ids if a == axis_name else lax.axis_index(a)
        ids = ids * s + comp
    return jnp.atleast_1d(ids)


def _transport_kernel(
    meta_ref,  # SMEM i32[3n+1]: target logical ids[n] | source logical
    # ids[n] | source graph ranks[n] | dst_row
    mask_ref,  # [n, S] f32 send mask (VMEM; only read when fused_mask)
    blocks_ref,  # [n, S, F] send tiles (VMEM when fused_mask else ANY/HBM)
    zeros_ref,  # [W*S, F] zeroed landing buffer (aliased to the output)
    out_ref,  # [W*S, F] halo buffer (this shard's; peers put into it)
    staging,  # VMEM (2, S, F) double buffer (fused_mask)
    send_sems,  # DMA sem per outbound put
    recv_sems,  # DMA sem per inbound put
    *,
    n: int,
    S: int,
    fused_mask: bool,
    interpret: bool,
):
    del zeros_ref
    if not interpret:
        # ready barrier: signal every shard that will put into MY buffer,
        # then wait for one signal from each shard I put into (senders and
        # receivers are the same delta set, mirrored)
        barrier = pltpu.get_barrier_semaphore()
        for k in range(n):
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=meta_ref[n + k],
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
        pltpu.semaphore_wait(barrier, n)
    dst_row = meta_ref[3 * n]
    copies = []
    for k in range(n):
        if fused_mask:
            slot = k % 2
            if k >= 2:
                # slot reuse: the put issued two tiles back read this slot
                # — wait its send semaphore before overwriting (classic
                # double buffering; tile k's stage+mask runs while tile
                # k-1's put is still on the wire)
                copies[k - 2].wait_send()
            staging[slot] = blocks_ref[k] * mask_ref[k][:, None].astype(
                blocks_ref.dtype
            )
            src = staging.at[slot]
        else:
            src = blocks_ref.at[k]
        c = pltpu.make_async_remote_copy(
            src_ref=src,
            dst_ref=out_ref.at[pl.ds(dst_row, S)],
            send_sem=send_sems.at[k],
            recv_sem=recv_sems.at[k],
            device_id=meta_ref[k],
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        c.start()
        copies.append(c)
    # drain outbound sends; under fused_mask the slot-reuse waits above
    # already consumed every send semaphore but the last two slots'
    for c in (copies[-2:] if fused_mask else copies):
        c.wait_send()
    for k in range(n):
        # wait each inbound tile: same-size descriptor on the recv
        # semaphore over the rows peer sources[k] lands in
        src_row = meta_ref[2 * n + k] * S
        landing = out_ref.at[pl.ds(src_row, S)]
        pltpu.make_async_copy(landing, landing, recv_sems.at[k]).wait()


@functools.lru_cache(maxsize=None)
def _make_transport(n, W, S, F, dtype_name, fused_mask, interpret):
    ANY = pltpu.TPUMemorySpace.ANY
    dtype = jnp.dtype(dtype_name)
    kern = functools.partial(
        _transport_kernel, n=n, S=S, fused_mask=fused_mask,
        interpret=interpret,
    )
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((W * S, F), dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.VMEM),
            pl.BlockSpec(
                memory_space=pltpu.TPUMemorySpace.VMEM if fused_mask else ANY
            ),
            pl.BlockSpec(memory_space=ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=ANY),
        scratch_shapes=[
            # the two-slot staging buffer exists only on the fused-mask
            # path; the non-fused path (reverse legs, over-budget stacks)
            # must not carry 2*S*F of dead VMEM — that is exactly the
            # large-tile case it falls back for
            pltpu.VMEM((2, S, F) if fused_mask else (1, 1), dtype),
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.SemaphoreType.DMA((n,)),
        ],
        # the zeroed landing buffer IS the output: it must be materialized
        # before the kernel (and so before any peer's put) — rows no put
        # covers stay exactly 0, matching the round lowerings
        input_output_aliases={3: 0},
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=P2P_COLLECTIVE_ID
        ),
        interpret=interpret,
        name=f"dgraph_p2p_transport_n{n}",
    )


def transport_fused_mask(blocks, S: int, F: int, dtype) -> bool:
    """Whether the fused in-kernel masking variant engages for this tile
    stack (it must fit the VMEM staging budget)."""
    n = blocks.shape[0]
    return n * S * F * jnp.dtype(dtype).itemsize <= FUSED_MASK_VMEM_BUDGET


def p2p_transport(
    blocks: jax.Array,  # [n_deltas, S, F] send tiles, one per live delta
    axis_name: str,
    deltas: tuple,  # static live rank offsets (EdgePlan.halo_deltas)
    W: int,
    S: int,
    *,
    sign: int = 1,  # +1: tile k -> (me + deltas[k]) % W (the exchange);
    # -1: tile k -> (me - deltas[k]) % W (its transpose / reverse leg)
    mask=None,  # [n_deltas, S] send mask; None = tiles are pre-masked
) -> jax.Array:
    """One-sided delivery of per-delta halo tiles; returns the ``[W*S, F]``
    halo buffer (rows ``[p*S, (p+1)*S)`` hold the tile peer ``p`` put,
    zeros where no put landed — the exact layout/values of the
    ``all_to_all`` and ``ppermute`` lowerings).

    Pure data movement: when ``mask`` is given (and the stack fits VMEM)
    the masking multiply runs in-kernel, overlapped with the previous
    tile's put; otherwise the caller pre-masks and the kernel only moves
    bytes. Not differentiable by itself — ``comm.collectives`` wraps the
    two directions into an explicit custom-VJP pair.
    """
    n = len(deltas)
    F = blocks.shape[-1]
    interpret = p2p_interpret_mode()
    if interpret:
        from dgraph_tpu.compat import install_multiaxis_remote_dma

        install_multiaxis_remote_dma()
    fused = mask is not None and transport_fused_mask(blocks, S, F, blocks.dtype)
    if mask is not None and not fused:
        blocks = blocks * mask[..., None].astype(blocks.dtype)
    if mask is None or not fused:
        # never read on the non-fused path — keep the VMEM operand tiny
        mask = jnp.ones((1, 1), jnp.float32)
    me = lax.axis_index(axis_name)
    d = jnp.asarray(deltas, jnp.int32)
    targets = (me + sign * d) % W
    sources = (me - sign * d) % W
    meta = jnp.concatenate([
        _logical_device_ids(axis_name, targets),
        _logical_device_ids(axis_name, sources),
        sources,
        (me * S)[None],
    ]).astype(jnp.int32)
    zeros = jnp.zeros((W * S, F), blocks.dtype)
    fn = _make_transport(
        n, W, S, F, jnp.dtype(blocks.dtype).name, fused, interpret
    )
    return fn(meta, mask, blocks, zeros)


# ---------------------------------------------------------------------------
# selftest CLI (scripts/check.py's interpret-mode smoke)
# ---------------------------------------------------------------------------


def _selftest_failures(seed: int = 0) -> list:
    """Interpret-mode transport parity on 2- and 4-shard rings: the kernel
    must deliver exactly what one masked ``all_to_all`` delivers, both
    put directions, fused and pre-masked. Tiny CPU compiles only."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu import compat as _compat  # noqa: F401  jax.shard_map

    failures = []
    if jax.default_backend() == "tpu":
        # the smoke validates the INTERPRET path; on a real chip the
        # parity pins in tests/test_pallas_p2p.py are the authority
        return failures
    for W, deltas in ((2, (1,)), (4, (1, 3))):
        if len(jax.devices()) < W:
            failures.append(
                f"need {W} devices for the {W}-shard smoke; have "
                f"{len(jax.devices())} (set XLA_FLAGS="
                f"--xla_force_host_platform_device_count=8)"
            )
            continue
        S, F = 8, 32
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(W, W, S, F)).astype(np.float32)
        m = (rng.random((W, W, S)) > 0.3).astype(np.float32)
        for r in range(W):
            for p in range(W):
                if (p - r) % W not in deltas:
                    m[r, p] = 0.0
        xj = jnp.asarray(x.reshape(W, W * S, F))
        mj = jnp.asarray(m.reshape(W, W * S))
        mesh = jax.make_mesh((W,), ("x",))

        def run(body):
            from dgraph_tpu.comm.collectives import shard_map_checks

            f = jax.shard_map(
                body, mesh=mesh, in_specs=(P("x"), P("x")),
                out_specs=P("x"),
                # both smoke bodies (p2p and its all_to_all oracle) share
                # this runner, and the p2p one needs the 0.4.x relaxation
                **shard_map_checks(impl="pallas_p2p"),
            )
            return np.asarray(jax.jit(f)(xj, mj))

        def ref_body(xb, mb):
            xb, mb = xb.reshape(W, S, F), mb.reshape(W, S)
            send = xb * mb[..., None]
            recv = lax.all_to_all(send, "x", split_axis=0, concat_axis=0)
            return recv.reshape(W * S, F)

        want = run(ref_body)
        for premask in (False, True):
            def p2p_body(xb, mb, premask=premask):
                xb, mb = xb.reshape(W, S, F), mb.reshape(W, S)
                me = lax.axis_index("x")
                rows = (me + jnp.asarray(deltas, jnp.int32)) % W
                blocks, msk = xb[rows], mb[rows]
                if premask:
                    blocks = blocks * msk[..., None]
                    return p2p_transport(blocks, "x", deltas, W, S)
                return p2p_transport(blocks, "x", deltas, W, S, mask=msk)

            got = run(p2p_body)
            if not (got == want).all():
                failures.append(
                    f"W={W} premask={premask}: transport != all_to_all "
                    f"({int((got != want).sum())} differing elements)"
                )
    return failures


def main(cfg) -> dict:
    import json

    from dgraph_tpu.obs.health import RunHealth

    health = RunHealth.begin("ops.pallas_p2p")
    try:
        failures = _selftest_failures(cfg.seed) if cfg.selftest else []
        out = {
            "kind": "pallas_p2p_selftest",
            "backend": jax.default_backend(),
            "failures": failures,
            "run_health": health.finish(
                "; ".join(failures) if failures else None,
                wedge="stage_failure" if failures else None,
            ),
        }
        print(json.dumps(out, indent=cfg.indent or None))
        if failures:
            raise SystemExit(
                "pallas_p2p selftest FAILED: " + "; ".join(failures)
            )
        return out
    except SystemExit:
        raise
    except BaseException as e:
        print(json.dumps({
            "kind": "pallas_p2p_selftest",
            "failures": [f"{type(e).__name__}: {e}"],
            "run_health": health.finish(
                f"pallas_p2p selftest crashed: {type(e).__name__}: {e}",
                wedge="stage_failure",
            ),
        }))
        raise


if __name__ == "__main__":
    import dataclasses

    from dgraph_tpu.utils.cli import parse_config

    @dataclasses.dataclass
    class Config:
        """Device-initiated one-sided halo transport (``--selftest`` runs
        the interpret-mode parity smoke)."""

        selftest: bool = False
        seed: int = 0
        indent: int = 0

    main(parse_config(Config))
