"""Rank-local gather/scatter primitives (the ``torch_local`` CUDA kernels'
TPU equivalents).

Reference: ``DGraph/distributed/RankLocalOps.py`` +
``DGraph/distributed/csrc/local_data_kernels.cuh`` — masked gather
(``Rank_Local_Gather_Kernel``, ``local_data_kernels.cuh:160-206``),
atomicAdd scatter (``:208-253``), generic set/add masked scatter-gather
(``:301-342``) with a float4-vectorized variant (``:353-406``).

TPU-first: there are no atomics on TPU; scatter-add is expressed as a
segment reduction, which XLA lowers to an efficient sorted/one-hot scheme
on the MXU/VPU, and which a Pallas kernel (``dgraph_tpu.ops.pallas_segment``)
can further specialize for sorted-by-destination edge plans (the plan
builder already emits dst-sorted edges within each rank — same prerequisite
the reference's dedup/renumbering establishes for its alltoallv path).

The reference keeps a torch fallback beside its CUDA kernels
(``RankLocalOps.py:21-31,66-70``); we keep jnp implementations beside the
Pallas kernels the same way — the jnp path is also the oracle in tests.

This module is the single dispatch point: swap ``segment_sum`` here and
every collective / model picks it up.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def row_take(
    x: jax.Array,
    idx: jax.Array,
    col_block: int | None = None,
    *,
    oob: str = "clamp",  # "clamp" (x[idx] semantics) | "fill" (OOB rows -> 0)
) -> jax.Array:
    """``x[idx]`` for [N, F] row gathers, split into <=``col_block``-wide
    column chunks.

    XLA's TPU row-gather fast path covers one (8,128) lane tile per row;
    rows wider than 128 f32 lanes fall off it (measured 28.9 ms plain vs
    4.3 ms split for [2.33M, 256] f32 on v5e, logs/kernels_r2.jsonl).
    Chunking the minor dim keeps every piece on the fast path — the TPU
    analogue of the reference's float4-vectorized gather
    (``local_data_kernels.cuh:353-406``): reshape the access so the memory
    system moves full-width units.

    ``col_block=None`` reads :data:`dgraph_tpu.config.gather_col_block`;
    0 disables splitting. ``oob="fill"`` zeroes out-of-range rows (the
    padding convention VJPs need); "clamp" keeps plain-indexing semantics.
    """
    if col_block is None:
        from dgraph_tpu import config as _cfg

        col_block = _cfg.gather_col_block

    def one(chunk):
        if oob == "fill":
            return jnp.take(chunk, idx, axis=0, mode="fill", fill_value=0)
        return chunk[idx]

    F = x.shape[-1]
    if not col_block or F <= col_block:
        return one(x)
    return jnp.concatenate(
        [one(x[..., j : j + col_block]) for j in range(0, F, col_block)], axis=-1
    )


@functools.lru_cache(maxsize=None)
def _make_take_rows(n_rows, sorted_ids, col_block, pallas, block_e, block_n,
                    mc, gather_mv=0):
    """Row gather whose VJP is an explicitly-routed segment reduction.

    JAX's default transpose of ``x[idx]`` is a generic XLA scatter-add —
    measured 56 ms for [2.33M, 256] f32 on v5e, ~2x slower than a
    sorted-segment reduction and blind to both the plan's monotone owner
    ordering and the >128-lane gather cliff. This wrapper pins the
    backward to the same fast paths the forward collectives use (the
    reference hand-writes these transposes for the identical reason,
    ``_torch_func_impl.py:112-191``):
      - sorted ids + Pallas available -> one-hot MXU sorted_segment_sum
      - otherwise -> jax.ops.segment_sum (with the sortedness hint)

    The FORWARD can additionally run as the Pallas sorted-row-gather
    kernel when ``gather_mv > 0`` (the caller resolves
    ``config.use_pallas_gather`` — explicit opt-in until on-chip A/B data
    exists — BEFORE this lru-cached factory, so the flag is part of the
    cache key); it defines its own exact-transpose VJP, so the custom-VJP
    wrapper below is bypassed entirely in that case.
    """
    if pallas and gather_mv > 0:
        from dgraph_tpu.ops.pallas_segment import sorted_row_gather

        def take_kernel(x, idx):
            prec = "default" if x.dtype == jnp.bfloat16 else "highest"
            return sorted_row_gather(
                x, idx, max_vblocks=gather_mv, block_e=block_e,
                block_n=block_n, scatter_mc=mc, precision=prec,
            )

        return take_kernel

    @jax.custom_vjp
    def take(x, idx):
        return row_take(x, idx, col_block, oob="fill")

    def fwd(x, idx):
        return take(x, idx), idx

    def bwd(idx, g):
        if pallas:
            from dgraph_tpu.ops.pallas_segment import sorted_segment_sum

            prec = "default" if g.dtype == jnp.bfloat16 else "highest"
            dx = sorted_segment_sum(
                g, idx, n_rows, max_chunks_per_block=mc,
                block_e=block_e, block_n=block_n, precision=prec,
            )
        else:
            dx = _acc_segment_sum(g, idx, n_rows, sorted_ids)
        return dx, None

    take.defvjp(fwd, bwd)
    return take


def take_rows(
    x: jax.Array,
    idx: jax.Array,
    *,
    indices_are_sorted: bool = False,
    col_block: int | None = None,
    pallas_hints: tuple | None = None,  # (block_e, block_n, max_chunks) or None
    gather_mv: int = 0,  # >0 + config.use_pallas_gather: Pallas fwd kernel
) -> jax.Array:
    """``x[idx]`` row gather with a fast-path VJP (see
    :func:`_make_take_rows`). Out-of-range ids produce zero rows (padding
    convention). ``pallas_hints`` enables the sorted one-hot MXU kernel for
    the backward when ids are monotone (plan-guaranteed); ``gather_mv``
    additionally enables the sorted-row-gather FORWARD kernel when
    ``config.use_pallas_gather`` is pinned on."""
    from dgraph_tpu import config as _cfg

    if col_block is None:
        col_block = _cfg.gather_col_block
    use_pallas = (
        pallas_hints is not None
        and indices_are_sorted
        and jax.default_backend() == "tpu"
    )
    be, bn, mc = pallas_hints if use_pallas else (0, 0, 0)
    mv = gather_mv if (use_pallas and _cfg.pallas_gather_enabled()) else 0
    return _make_take_rows(
        x.shape[0], indices_are_sorted, col_block, use_pallas, be, bn, mc, mv
    )(x, idx)


def sorted_segment_sum_any(data, sorted_ids, n_rows, be, bn, mc, gather_mv=0):
    """Sorted segment-sum via the Pallas MXU kernel when it's enabled AND
    the backend is TPU, jnp elsewhere. The single dispatch point for every
    sorted reduction (owner-side scatter and the halo sort route) so the
    kill switch (``config.use_pallas_scatter``, e.g. bench's failed
    self-check fallback) and the precision policy cannot diverge between
    call sites."""
    from dgraph_tpu import config as _cfg

    if _cfg.pallas_scatter_enabled() and jax.default_backend() == "tpu":
        from dgraph_tpu.ops.pallas_segment import sorted_segment_sum

        prec = "default" if data.dtype == jnp.bfloat16 else "highest"
        return sorted_segment_sum(
            data, sorted_ids, n_rows, max_chunks_per_block=mc,
            block_e=be, block_n=bn, gather_mv=gather_mv, precision=prec,
        )
    # fallback keeps the col-split-take VJP pinning (segment_sum wrapper),
    # not jax.ops.segment_sum's plain wide-gather transpose; the wrapper's
    # reduction runs through _acc_segment_sum, so low-precision inputs
    # accumulate in f32 exactly like the kernel's VMEM accumulator.
    return segment_sum(data, sorted_ids, n_rows, indices_are_sorted=True)


def sorted_segment_sum_bias_relu_any(
    edata, sorted_ids, bias, n_rows, be, bn, mc, edge_weight=None,
    gather_mv=0,
):
    """Fused Σ w·relu(edata + bias[id]) for sorted ids — Pallas on TPU
    (``ops.pallas_segment.sorted_segment_sum_bias_relu``), composed jnp ops
    elsewhere. Same single-dispatch-point contract as
    :func:`sorted_segment_sum_any`: kill switch + precision policy live
    HERE, not at call sites."""
    from dgraph_tpu import config as _cfg

    # precision policy lives HERE: the kernel casts bias to the data dtype
    # internally; the composed fallback must match, or a f32 bias with
    # bf16 edata would promote every [e_pad, F] tensor of the fallback
    bias = bias.astype(edata.dtype)
    if _cfg.pallas_fused_enabled() and jax.default_backend() == "tpu":
        from dgraph_tpu.ops.pallas_segment import sorted_segment_sum_bias_relu

        prec = "default" if edata.dtype == jnp.bfloat16 else "highest"
        return sorted_segment_sum_bias_relu(
            edata, sorted_ids, bias, n_rows, edge_weight=edge_weight,
            max_chunks_per_block=mc, block_e=be, block_n=bn,
            gather_mv=gather_mv, precision=prec,
        )
    # take via take_rows WITH the sorted hints so the bias-gradient
    # transpose rides the sorted segment-sum path, not XLA scatter-add;
    # hints honor the scatter kill switch (a vetoed kernel must not keep
    # running via the hinted VJP, and the noscatter A/Bs must really
    # measure the XLA path)
    hints = ((be, bn, mc)
             if _cfg.pallas_scatter_enabled() else None)
    bias_rows = take_rows(
        bias, sorted_ids, indices_are_sorted=True,
        pallas_hints=hints, gather_mv=gather_mv,
    )
    m = jax.nn.relu(edata + bias_rows)
    if edge_weight is not None:
        m = m * edge_weight[:, None].astype(m.dtype)
    # route the reduction through sorted_segment_sum_any, NOT the plain
    # wrapper: with the fused kernel off but the plain scatter on (the
    # r4 bench exactly — fused self-check vetoed by the Mosaic bf16 bug)
    # the wrapper sent the model's MAIN aggregation to XLA scatter-add,
    # bypassing the healthy Pallas kernel
    return sorted_segment_sum_any(m, sorted_ids, n_rows, be, bn, mc,
                                  gather_mv=gather_mv)


@functools.lru_cache(maxsize=None)
def _make_take_rows_sortroute(n_rows, col_block, be, bn, mc):
    """Row gather for UNSORTED ids whose VJP still runs the sorted fast
    path: the plan carries a static permutation ``perm`` with
    ``ids[perm]`` monotone (``EdgePlan.halo_sort_perm``), so the transpose
    is gather-by-perm (cheap, col-split) + sorted segment-sum (Pallas MXU)
    instead of XLA's generic scatter-add (~2x slower at arxiv scale)."""

    @jax.custom_vjp
    def take(x, idx, perm, sorted_ids):
        return row_take(x, idx, col_block, oob="fill")

    def fwd(x, idx, perm, sorted_ids):
        return take(x, idx, perm, sorted_ids), (perm, sorted_ids)

    def bwd(res, g):
        perm, sorted_ids = res
        gp = row_take(g, perm, col_block)  # static permutation, in-range
        dx = sorted_segment_sum_any(gp, sorted_ids, n_rows, be, bn, mc)
        return dx, None, None, None

    take.defvjp(fwd, bwd)
    return take


def take_rows_sort_route(x, idx, perm, sorted_ids, *, pallas_hints,
                         col_block=None):
    """``x[idx]`` (OOB -> 0) with the VJP routed through a plan-provided
    sorting permutation of ``idx`` (see :func:`_make_take_rows_sortroute`)."""
    if col_block is None:
        from dgraph_tpu import config as _cfg

        col_block = _cfg.gather_col_block
    be, bn, mc = pallas_hints
    return _make_take_rows_sortroute(x.shape[0], col_block, be, bn, mc)(
        x, idx, perm, sorted_ids
    )


@functools.lru_cache(maxsize=None)
def _make_segment_sum_sortroute(n_rows, col_block, be, bn, mc):
    """segment-sum for UNSORTED ids via the plan's sorting permutation:
    forward = gather-by-perm + sorted segment-sum (Pallas MXU); VJP = plain
    row gather by the original ids (the composite's exact transpose —
    d_data[i] = g[ids[i]] — so the permutation drops out of the backward)."""

    @jax.custom_vjp
    def segsum(data, ids, perm, sorted_ids):
        dp = row_take(data, perm, col_block)
        return sorted_segment_sum_any(dp, sorted_ids, n_rows, be, bn, mc)

    def fwd(data, ids, perm, sorted_ids):
        return segsum(data, ids, perm, sorted_ids), ids

    def bwd(ids, g):
        return row_take(g, ids, col_block, oob="fill"), None, None, None

    segsum.defvjp(fwd, bwd)
    return segsum


def segment_sum_sort_route(data, ids, perm, sorted_ids, n_rows, *,
                           pallas_hints, col_block=None):
    """Segment-sum of rows with unsorted ``ids`` routed through the plan's
    sorting permutation (see :func:`_make_segment_sum_sortroute`)."""
    if col_block is None:
        from dgraph_tpu import config as _cfg

        col_block = _cfg.gather_col_block
    be, bn, mc = pallas_hints
    return _make_segment_sum_sortroute(n_rows, col_block, be, bn, mc)(
        data, ids, perm, sorted_ids
    )


def _acc_segment_sum(data, ids, num_segments, indices_are_sorted):
    """``jax.ops.segment_sum`` with a 32-bit accumulator for low-precision
    data: a bf16 running sum saturates (1.0 < ulp(256) = 2, so summing
    0/1 masks stalls at 256 and hub-vertex feature sums lose terms the
    same way). The Pallas kernels accumulate f32 in VMEM and the
    reference accumulates via f32 atomicAdd — every XLA reduction path
    goes through here so the three implementations agree to one output
    rounding."""
    if data.dtype in (jnp.bfloat16, jnp.float16):
        return jax.ops.segment_sum(
            data.astype(jnp.float32), ids, num_segments=num_segments,
            indices_are_sorted=indices_are_sorted,
        ).astype(data.dtype)
    return jax.ops.segment_sum(
        data, ids, num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


@functools.lru_cache(maxsize=None)
def _make_segment_sum(num_segments, sorted_ids, col_block):
    """segment_sum whose VJP is a column-split take (the >128-lane row
    gather cliff applies to the backward's ``g[ids]`` exactly as it does to
    forward gathers — measured 28.9 ms plain vs 4.3 ms col-split for
    [2.33M, 256] f32 on v5e)."""

    @jax.custom_vjp
    def segsum(data, ids):
        return _acc_segment_sum(data, ids, num_segments, sorted_ids)

    def fwd(data, ids):
        return segsum(data, ids), ids

    def bwd(ids, g):
        return row_take(g, ids, col_block, oob="fill"), None

    segsum.defvjp(fwd, bwd)
    return segsum


def masked_gather(src: jax.Array, idx: jax.Array, mask: jax.Array) -> jax.Array:
    """out[i] = src[idx[i]] * mask[i] — ``Rank_Local_Gather_Kernel`` parity."""
    return row_take(src, idx) * mask[..., None].astype(src.dtype)


def masked_scatter(
    dst: jax.Array, idx: jax.Array, src: jax.Array, mask: jax.Array
) -> jax.Array:
    """dst[idx[i]] = src[i] where mask[i] — ``Masked_Scatter_Gather_Kernel``
    with the Set op (``local_data_kernels.cuh:301-342``); set semantics, last
    writer wins on duplicates (XLA scatter)."""
    safe_idx = jnp.where(mask > 0, idx, dst.shape[0])  # OOB rows dropped
    return dst.at[safe_idx].set(src, mode="drop")


def segment_sum(
    data: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """Sum rows of ``data`` into ``num_segments`` buckets by ``segment_ids``.

    The TPU replacement for atomicAdd scatter (``local_data_kernels.cuh:208-253``).
    ``indices_are_sorted=True`` (plan-guaranteed when
    ``EdgePlan.owner_sorted``) lets XLA use the cheaper monotone-scatter path.

    For [E, F] data the VJP is pinned to a column-split take
    (:func:`_make_segment_sum`) instead of JAX's default plain gather.
    """
    if data.ndim == 2:
        from dgraph_tpu import config as _cfg

        return _make_segment_sum(
            num_segments, indices_are_sorted, _cfg.gather_col_block
        )(data, segment_ids)
    return _acc_segment_sum(data, segment_ids, num_segments,
                            indices_are_sorted)


def scatter_add_relu(
    data: jax.Array, segment_ids: jax.Array, num_segments: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """out[s] = Σ max(data[i], 0) over segment s — parity with the reference's
    fused ReLU+atomicAdd kernel (``Fused_ReLU_Scatter_Kernel``,
    ``local_data_kernels.cuh:34-72``). On TPU the ReLU fuses into the
    segment reduction's input by XLA; expressing it as one call keeps the
    reference's fused API surface."""
    return segment_sum(
        jax.nn.relu(data), segment_ids, num_segments, indices_are_sorted
    )


def scatter_add_sum_relu(
    data1: jax.Array, data2: jax.Array, segment_ids: jax.Array, num_segments: int,
    indices_are_sorted: bool = False,
) -> jax.Array:
    """out[s] = Σ max(data1[i] + data2[i], 0) — parity with
    ``Fused_Sum_Norm_Scatter_Kernel`` (``local_data_kernels.cuh:74-116``):
    residual-add + ReLU fused into the scatter. One XLA fusion on TPU."""
    return segment_sum(
        jax.nn.relu(data1 + data2), segment_ids, num_segments, indices_are_sorted
    )


def sparse_scatter_add(dst: jax.Array, idx: jax.Array, src: jax.Array) -> jax.Array:
    """dst[idx[i]] += src[i], rows with idx < 0 (or >= len(dst)) dropped —
    parity with ``Sparse_Scatter_Kernel`` (``local_data_kernels.cuh:117-158``),
    the reference's "-1 means skip" masking convention (SURVEY §7).

    Negative indices would WRAP under JAX's .at[] semantics, so they are
    remapped to an out-of-bounds sentinel that mode="drop" discards.
    """
    idx = jnp.where(idx < 0, dst.shape[0], idx)
    return dst.at[idx].add(src, mode="drop")


def segment_max(data: jax.Array, segment_ids: jax.Array, num_segments: int,
                indices_are_sorted: bool = False) -> jax.Array:
    """Per-segment max (for attention softmax stabilization). Empty segments
    produce -inf; callers mask afterwards."""
    return jax.ops.segment_max(
        data, segment_ids, num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def segment_mean(
    data: jax.Array, segment_ids: jax.Array, num_segments: int, eps: float = 1e-12
) -> jax.Array:
    """Per-segment mean with safe division for empty segments."""
    sums = segment_sum(data, segment_ids, num_segments)
    counts = segment_sum(jnp.ones((data.shape[0], 1), data.dtype), segment_ids, num_segments)
    return sums / jnp.maximum(counts, eps)


def segment_softmax(
    logits: jax.Array, segment_ids: jax.Array, num_segments: int, mask: jax.Array,
    indices_are_sorted: bool = False,  # plan-guaranteed for owner-side ids
) -> jax.Array:
    """Numerically-stable softmax over segments (per-dst-vertex attention).

    The reference RGAT computes this with an explicit gather/scatter round
    trip over the network (denominator scatter + gather,
    ``experiments/OGB-LSC/RGAT.py:174-206``); with dst-owned edges it is a
    purely local segment operation.

    Args:
      logits: [E, H] per-edge (per-head) attention logits.
      mask: [E] 1.0 for real edges.
    Returns [E, H] normalized weights (masked edges -> 0).
    """
    logits = jnp.where(mask[..., None] > 0, logits, -jnp.inf)
    seg_max = segment_max(logits, segment_ids, num_segments, indices_are_sorted)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    shifted = jnp.where(mask[..., None] > 0, logits - seg_max[segment_ids], -jnp.inf)
    expd = jnp.where(mask[..., None] > 0, jnp.exp(shifted), 0.0)
    denom = segment_sum(expd, segment_ids, num_segments, indices_are_sorted)
    return expd / jnp.maximum(denom[segment_ids], 1e-12)
