from dgraph_tpu.ops import local

__all__ = ["local"]
