from dgraph_tpu.ops import local

# dgraph_tpu.ops.pallas_segment and dgraph_tpu.ops.pallas_p2p are
# imported lazily by their dispatch points (ops.local, comm.collectives)
# so importing the package never pays the Pallas import on paths that
# don't run kernels.
__all__ = ["local"]
