"""Pallas TPU kernel: sorted-segment sum via blocked one-hot MXU matmuls.

This is the TPU-native replacement for the reference's CUDA scatter-add
kernels (``Rank_Local_Scatter_Kernel`` / ``Masked_Scatter_Gather_Kernel``,
``DGraph/distributed/csrc/local_data_kernels.cuh:208-342``): TPU has no
atomics, so the kernel exploits the plan-guaranteed MONOTONE segment ids
(``EdgePlan.owner_sorted``) instead:

- Edges are processed in chunks of ``block_e``; output vertices in blocks of
  ``block_n``. Because ids are sorted, each vertex block's edges form ONE
  contiguous chunk range, found with a cheap in-jit searchsorted and handed
  to the kernel via scalar prefetch (``pltpu.PrefetchScalarGridSpec``).
- Within a chunk, scatter becomes a one-hot [block_e, block_n] matmul
  against the data chunk — an MXU contraction, not a serial scatter. This
  is the TPU analogue of the reference's float4-vectorized atomic kernel
  (``local_data_kernels.cuh:353-406``): same "make the memory system move
  wide rows" idea, expressed as systolic-array work.
- The grid is (num_vertex_blocks, max_chunks_per_block); the output block
  stays resident in VMEM across its chunk iterations (sequential TPU grid),
  accumulating partials, and spills to HBM once per vertex block.

The jnp ``segment_sum`` path remains the oracle and fallback
(``dgraph_tpu.ops.local``), mirroring the reference's dual CUDA/torch
implementation pattern (``RankLocalOps.py:21-31,66-70``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(
    starts_ref, counts_ref, ids_ref, data_ref, out_ref, *, block_n, block_e, input_op,
    precision,
):
    b = pl.program_id(0)
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(k < counts_ref[b])
    def _accumulate():
        ids = ids_ref[0, 0]  # [block_e] int32 (global segment ids)
        chunk = data_ref[0]  # [block_e, F]
        if input_op == "relu":
            # fused ReLU epilogue on the scatter input — the reference's
            # Fused_ReLU_Scatter_Kernel (local_data_kernels.cuh:34-72) done
            # in-VMEM before the one-hot contraction
            chunk = jnp.maximum(chunk, 0)
        rel = ids - b * block_n
        # Mosaic can't insert a minor dim on 1-D bool vectors ("only
        # supported for 32-bit types"), so build the mask in 2-D int32
        # space: rel[:, None] is a 32-bit reshape, comparisons stay 2-D.
        rel2 = rel[:, None]  # [block_e, 1] int32
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_e, block_n), 1)
        onehot = jnp.where(
            (cols == rel2) & (rel2 >= 0) & (rel2 < block_n), 1.0, 0.0
        ).astype(chunk.dtype)
        out_ref[...] += jax.lax.dot_general(
            onehot,
            chunk,
            (((0,), (0,)), ((), ())),  # contract over block_e: [BN, F]
            preferred_element_type=out_ref.dtype,
            precision=precision,
        )


class _ChunkSchedule:
    """Shared scaffold of the sorted-CSR kernels: pad edges to chunk
    multiples, compute per-vertex-block chunk ranges (in-jit searchsorted;
    ids sorted), and hand out BlockSpecs over the (nb, max_chunks) grid.

    Iterations past counts[b] clamp to the block's LAST VALID chunk: Mosaic
    skips the DMA when consecutive grid steps map to the same block index,
    so the padded tail of the grid costs no HBM traffic (each kernel's
    @pl.when guard skips its compute).

    ids are carried as [num_chunks, 1, block_e]: Mosaic requires the last
    two block dims to be (8,128)-tileable OR equal to the array dims — a
    (1, block_e) block over [num_chunks, block_e] violates the sublane rule
    on real TPU (interpret mode doesn't check), so the explicit singleton
    sublane dim IS the full array dim.
    """

    def __init__(self, segment_ids, num_segments, E, *, block_e, block_n,
                 max_chunks_per_block):
        self.block_e, self.block_n = block_e, block_n
        self.max_chunks = max_chunks_per_block
        self.E_pad = pl.cdiv(E, block_e) * block_e
        self.N_pad = pl.cdiv(num_segments, block_n) * block_n
        self.num_chunks = self.E_pad // block_e
        self.nb = self.N_pad // block_n
        if self.E_pad != E:
            segment_ids = jnp.pad(
                segment_ids, (0, self.E_pad - E), constant_values=num_segments + 1
            )
        self.ids = segment_ids
        self.ids3d = segment_ids.reshape(self.num_chunks, 1, block_e)
        starts = jnp.searchsorted(segment_ids, jnp.arange(self.nb) * block_n)
        ends = jnp.searchsorted(
            segment_ids, jnp.arange(1, self.nb + 1) * block_n, side="left"
        )
        self.chunk_start = (starts // block_e).astype(jnp.int32)
        self.chunk_counts = jnp.minimum(
            pl.cdiv(ends, block_e).astype(jnp.int32) - self.chunk_start,
            max_chunks_per_block,
        ).astype(jnp.int32)

    def pad_edges(self, arr):
        """Pad an [E, ...] per-edge operand to E_pad rows."""
        pad = self.E_pad - arr.shape[0]
        if pad:
            arr = jnp.pad(arr, ((0, pad),) + ((0, 0),) * (arr.ndim - 1))
        return arr

    def chunk_spec(self, block_shape):
        """BlockSpec streaming a per-chunk operand ([num_chunks, ...])."""
        num_chunks = self.num_chunks

        def index(b, k, starts, counts):
            return (
                jnp.minimum(
                    starts[b]
                    + jnp.minimum(k, jnp.maximum(counts[b] - 1, 0)),
                    num_chunks - 1,
                ),
            ) + (0,) * (len(block_shape) - 1)

        return pl.BlockSpec(block_shape, index)

    def block_spec(self, F):
        """BlockSpec for an [N_pad, F] owner-side operand/output."""
        return pl.BlockSpec((self.block_n, F), lambda b, k, s, c: (b, 0))


def _precision(precision: str):
    return (
        jax.lax.Precision.HIGHEST if precision == "highest"
        else jax.lax.Precision.DEFAULT
    )


def _sorted_segment_sum_impl(
    data, segment_ids, num_segments, *, max_chunks_per_block, block_e, block_n,
    interpret, input_op, precision,
):
    if input_op not in ("none", "relu"):
        raise ValueError(f"input_op must be 'none' or 'relu', got {input_op!r}")
    E, F = data.shape
    sched = _ChunkSchedule(
        segment_ids, num_segments, E, block_e=block_e, block_n=block_n,
        max_chunks_per_block=max_chunks_per_block,
    )
    data3d = sched.pad_edges(data).reshape(sched.num_chunks, block_e, F)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(sched.nb, sched.max_chunks),
        in_specs=[
            sched.chunk_spec((1, 1, block_e)),
            sched.chunk_spec((1, block_e, F)),
        ],
        out_specs=sched.block_spec(F),
    )
    # The MXU accumulator must be 32-bit ('tpu.matmul' rejects a bf16 acc),
    # and f32 accumulation over long segments is the atomicAdd-parity
    # semantics anyway — so the VMEM-resident output block is ALWAYS f32
    # (bf16 inputs still ride the fast bf16 MXU passes under
    # precision='default'); cast back to the input dtype on the way out.
    out = pl.pallas_call(
        functools.partial(
            _kernel, block_n=block_n, block_e=block_e, input_op=input_op,
            precision=_precision(precision),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((sched.N_pad, F), jnp.float32),
        interpret=interpret,
    )(sched.chunk_start, sched.chunk_counts, sched.ids3d, data3d)
    return out[:num_segments].astype(data.dtype)


@functools.lru_cache(maxsize=None)
def _make_sss(num_segments, max_chunks_per_block, block_e, block_n, interpret,
              input_op, precision, gather_mv=0):
    impl = functools.partial(
        _sorted_segment_sum_impl,
        num_segments=num_segments, max_chunks_per_block=max_chunks_per_block,
        block_e=block_e, block_n=block_n, interpret=interpret,
        input_op=input_op, precision=precision,
    )

    @jax.custom_vjp
    def f(data, segment_ids):
        return impl(data, segment_ids)

    def fwd(data, segment_ids):
        res = (segment_ids, data if input_op == "relu" else None)
        return impl(data, segment_ids), res

    def bwd(res, g):
        segment_ids, data = res
        # grad gather of the cotangent rows: sorted-row-gather kernel
        # when pinned on (config read at trace time; bench sets flags
        # before compiling), else the column-chunked take (the >128-lane
        # row-gather cliff applies to the grad gather too)
        gd = _take_sorted(g, segment_ids, gather_mv,
                          block_e, block_n, max_chunks_per_block)
        if input_op == "relu":
            gd = gd * (data > 0).astype(gd.dtype)
        return gd, None

    f.defvjp(fwd, bwd)
    return f


def sorted_segment_sum(
    data: jax.Array,  # [E, F]
    segment_ids: jax.Array,  # [E] int32, MONOTONE non-decreasing
    num_segments: int,
    *,
    max_chunks_per_block: int,
    block_e: int = 512,
    block_n: int = 256,
    interpret: bool = False,
    input_op: str = "none",  # "none" | "relu" (fused input epilogue)
    gather_mv: int = 0,  # >0: the VJP's cotangent-row gather may use the
    # sorted-row-gather kernel (explicit config opt-in; plan.gather_mv)
    precision: str = "highest",  # MXU passes for the one-hot contraction:
    # "highest" = f32-faithful accumulation (matches the CUDA atomicAdd
    # semantics, ~1.4x XLA's scatter path on v5e); "default" = bf16 input
    # truncation (fastest; right when the model already computes in bf16)
) -> jax.Array:
    """Segment sum for sorted ids. Rows with ids outside [0, num_segments)
    are dropped (use an out-of-range id for masked edges).

    Differentiable: the VJP is the gather transpose ``g[ids]`` (exactly the
    reference's gather-bwd = scatter-sum duality, ``_torch_func_impl.py``),
    with OOB ids contributing zero.

    ``max_chunks_per_block`` must be >= the true maximum
    ceil(edges_in_any_block/block_e) + 1 (the +1 covers chunk misalignment);
    compute it at plan-build time with :func:`max_chunks_hint`.
    """
    return _make_sss(
        num_segments, max_chunks_per_block, block_e, block_n, interpret,
        input_op, precision, gather_mv,
    )(data, segment_ids)


def _kernel_bias_relu(
    starts_ref, counts_ref, ids_ref, *refs,
    block_n, block_e, precision, has_weight, epilogue="relu",
):
    """out[v] += sum_e onehot[e,v] * w[e] * relu(data[e] + bias[v]).

    The bias lookup bias[ids[e]] is itself a one-hot matmul against the
    block's resident bias tile — per-edge rows of the OWNER-side vertex
    operand never touch HBM. This is the full TPU analogue of the
    reference's fused scatter family (``Fused_ReLU_Scatter_Kernel`` /
    ``Fused_Sum_Norm_Scatter_Kernel``, ``local_data_kernels.cuh:34-116``):
    XLA alone cannot do it because ``pallas_call`` is a fusion barrier, so
    the [E, F] message tensor would round-trip HBM.

    ``epilogue="act"`` accumulates w[e] * 1[data[e]+bias[v] > 0] instead —
    the VJP's d_bias reduction (d_bias[v] = g[v] * Σ w·act), computed from
    ONE pass over data with no [E, F] HBM intermediates.
    """
    if has_weight:
        wgt_ref, data_ref, bias_ref, out_ref = refs
    else:
        (data_ref, bias_ref, out_ref), wgt_ref = refs, None
    b = pl.program_id(0)
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(k < counts_ref[b])
    def _accumulate():
        ids = ids_ref[0, 0]  # [block_e]
        chunk = data_ref[0]  # [block_e, F]
        rel2 = (ids - b * block_n)[:, None]
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_e, block_n), 1)
        onehot = jnp.where(
            (cols == rel2) & (rel2 >= 0) & (rel2 < block_n), 1.0, 0.0
        ).astype(chunk.dtype)
        # bias[ids[e]] for in-block edges (OOB rows get 0 — they're dropped
        # by the output contraction anyway)
        bias_rows = jax.lax.dot_general(
            onehot, bias_ref[...].astype(chunk.dtype),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        in_dtype = data_ref.dtype
        pre = chunk.astype(jnp.float32) + bias_rows
        if epilogue == "act":
            chunk = (pre > 0).astype(jnp.float32)
        else:
            chunk = jnp.maximum(pre, 0)
        if has_weight:
            # cast BEFORE the [:, None]: Mosaic can only insert a minor dim
            # on 32-bit vectors (bf16 here fails "Insertion of minor dim
            # that is not a no-op only supported for 32-bit types")
            chunk = chunk * wgt_ref[0, 0].astype(jnp.float32)[:, None]
        # back to the input dtype for the contraction (bf16 inputs keep the
        # fast MXU passes; matches the unfused path where m was bf16)
        chunk = chunk.astype(in_dtype)
        out_ref[...] += jax.lax.dot_general(
            onehot,
            chunk,
            (((0,), (0,)), ((), ())),
            preferred_element_type=out_ref.dtype,
            precision=precision,
        )


def _take_sorted(g, ids, gather_mv, block_e, block_n, mc):
    """Bwd-side row take by PLAN-SORTED ids: the Pallas sorted-row-gather
    kernel when the explicit opt-in flag is pinned and the plan carried a
    span hint, ops.local.row_take otherwise (OOB ids -> zero rows)."""
    from dgraph_tpu import config as _cfg
    from dgraph_tpu.ops.local import row_take

    if gather_mv > 0 and _cfg.pallas_gather_enabled():
        prec = "default" if g.dtype == jnp.bfloat16 else "highest"
        return sorted_row_gather(
            g, ids, max_vblocks=gather_mv, block_e=block_e, block_n=block_n,
            scatter_mc=mc, precision=prec,
        )
    return row_take(g, ids, oob="fill")


@functools.lru_cache(maxsize=None)
def _make_ssbr(num_segments, max_chunks_per_block, block_e, block_n, interpret,
               precision, has_weight, gather_mv=0):
    def impl(data, segment_ids, bias, edge_weight, epilogue="relu"):
        E, F = data.shape
        sched = _ChunkSchedule(
            segment_ids, num_segments, E, block_e=block_e, block_n=block_n,
            max_chunks_per_block=max_chunks_per_block,
        )
        data3d = sched.pad_edges(data).reshape(sched.num_chunks, block_e, F)
        if sched.N_pad != num_segments:
            bias = jnp.pad(bias, ((0, sched.N_pad - num_segments), (0, 0)))
        in_specs = [
            sched.chunk_spec((1, 1, block_e)),
            sched.chunk_spec((1, block_e, F)),
            sched.block_spec(F),
        ]
        operands = [sched.ids3d, data3d, bias]
        if has_weight:
            wgt3d = sched.pad_edges(edge_weight).reshape(
                sched.num_chunks, 1, block_e
            )
            in_specs.insert(1, sched.chunk_spec((1, 1, block_e)))
            operands.insert(1, wgt3d)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(sched.nb, sched.max_chunks),
            in_specs=in_specs,
            out_specs=sched.block_spec(F),
        )
        out = pl.pallas_call(
            functools.partial(
                _kernel_bias_relu, block_n=block_n, block_e=block_e,
                precision=_precision(precision), has_weight=has_weight,
                epilogue=epilogue,
            ),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((sched.N_pad, F), jnp.float32),
            interpret=interpret,
        )(sched.chunk_start, sched.chunk_counts, *operands)
        if epilogue != "relu":
            # the act-count reduction is bwd-internal and vertex-sized —
            # keep the f32 accumulator precision (a bf16 count saturates)
            return out[:num_segments]
        return out[:num_segments].astype(data.dtype)

    @jax.custom_vjp
    def f(data, segment_ids, bias, edge_weight):
        return impl(data, segment_ids, bias, edge_weight)

    def fwd(data, segment_ids, bias, edge_weight):
        return impl(data, segment_ids, bias, edge_weight), (
            data, segment_ids, bias, edge_weight,
        )

    def bwd(res, g):
        data, segment_ids, bias, edge_weight = res
        cdt = data.dtype

        # fused-bwd kernel pair (unweighted path): gd from ONE chunk-major
        # pass (no bias-rows take, no g-rows take, no act tensor — the
        # composed bwd streams all three through HBM), d_bias's Σact from
        # ONE vblock-major pass (epilogue="act"). Engages when the plan
        # carried the vblock-span hint (gather_mv) and the kernels can run
        # (TPU, or interpret mode for tests); the fused kill switch
        # already gated entry into this op at the dispatch point, and
        # config.pallas_fused_bwd_enabled() (trace-time read) disables
        # just this pair for debugging/A-B without losing the fused fwd.
        from dgraph_tpu import config as _config

        if (not has_weight and gather_mv > 0
                and _config.pallas_fused_bwd_enabled()
                and (interpret or jax.default_backend() == "tpu")):
            gd = _make_fused_bwd(
                num_segments, gather_mv, block_e, block_n, interpret,
                precision,
            )(data, g.astype(cdt), bias.astype(cdt), segment_ids)
            sum_act = impl(data, segment_ids, bias, edge_weight,
                           epilogue="act")  # f32 [N, F]
            d_bias = sum_act * g.astype(jnp.float32)
            return (gd, None, d_bias.astype(bias.dtype),
                    jnp.zeros_like(edge_weight))

        # composed fallback: recompute the activation mask (remat: the
        # [E,F] pre-activation was never materialized in the forward —
        # that's the point); both row takes are by the plan's sorted ids
        # -> kernel-upgradeable.
        # Every [E, F] tensor that REACHES HBM stays in the COMPUTE dtype:
        # upcasting the gathers/products to f32 doubled every bwd HBM
        # stream (the r4 TPU export showed six 1.2 GB f32 [E,128] gathers
        # per step from exactly this block). The mask itself is still
        # DECIDED in f32 — the forward kernel computes data+bias[id] in
        # f32, and a bf16 recompute can flip edges at the ReLU boundary
        # (an O(|g|) error, not rounding). The f32 add/compare lives in
        # the fusion's registers; its input streams are bf16.
        # bias.astype(cdt) matches the FORWARD's rounding, not a new one:
        # the kernel computes bias_rows = dot(onehot, bias_ref.astype(
        # chunk.dtype)) — i.e. the forward's mask also sees bias rounded
        # to the data dtype (a one-hot contraction of cdt values under a
        # f32 preferred_element_type is exact), so fwd/bwd masks agree
        # even for an f32 bias passed with bf16 data.
        bias_rows = _take_sorted(
            bias.astype(cdt), segment_ids, gather_mv,
            block_e, block_n, max_chunks_per_block,
        )
        pre = data.astype(jnp.float32) + bias_rows.astype(jnp.float32)
        act = (pre > 0).astype(cdt)
        g_rows = _take_sorted(
            g.astype(cdt), segment_ids, gather_mv,
            block_e, block_n, max_chunks_per_block,
        )
        w = edge_weight[:, None].astype(cdt) if has_weight else 1.0
        gd = g_rows * act * w  # d/d(data)
        # d/d(bias[v]) = g[v] * sum_e w_e*act_e  (sorted ids -> fast path;
        # f32 accumulation guaranteed by sorted_segment_sum_any for BOTH
        # the kernel path (VMEM acc) and the jnp fallback — a bf16
        # accumulate would saturate the count at vertex degree ~256)
        from dgraph_tpu.ops.local import sorted_segment_sum_any

        d_bias = sorted_segment_sum_any(
            act * w if has_weight else act, segment_ids, num_segments,
            block_e, block_n, max_chunks_per_block,
        ).astype(jnp.float32) * g.astype(jnp.float32)
        if has_weight:
            d_w = (g_rows * jnp.maximum(pre, 0)).sum(axis=-1).astype(
                edge_weight.dtype
            )
        else:
            d_w = jnp.zeros_like(edge_weight)
        return gd.astype(data.dtype), None, d_bias.astype(bias.dtype), d_w

    f.defvjp(fwd, bwd)
    return f


def sorted_segment_sum_bias_relu(
    data: jax.Array,  # [E, F] per-edge partial messages (e.g. gathered src proj)
    segment_ids: jax.Array,  # [E] int32 MONOTONE owner-side ids
    bias: jax.Array,  # [num_segments, F] owner-side vertex operand
    num_segments: int,
    *,
    edge_weight: Optional[jax.Array] = None,  # [E] post-activation scale
    max_chunks_per_block: int,
    block_e: int = 512,
    block_n: int = 256,
    interpret: bool = False,
    gather_mv: int = 0,  # vblock-span hint (plan.gather_mv). >0 selects
    # the UNWEIGHTED op's Pallas backward KERNEL PAIR on TPU
    # (_fused_bwd_kernel gd + epilogue="act" d_bias), additionally gated
    # by config.pallas_fused_bwd_enabled() read at trace time
    # (DGRAPH_TPU_PALLAS_FUSED_BWD — the pair's own kill switch; the
    # fused op as a whole still gates at the dispatch point). In
    # the composed/weighted backward it additionally lets the cotangent
    # gather use sorted_row_gather under DGRAPH_TPU_PALLAS_GATHER.
    precision: str = "default",
) -> jax.Array:
    """out[v] = Σ_{e: ids[e]=v} w[e] * relu(data[e] + bias[v]) without ever
    materializing the [E, F] message tensor in HBM (see
    :func:`_kernel_bias_relu`). Differentiable (remat-style VJP)."""
    has_w = edge_weight is not None
    fn = _make_ssbr(
        num_segments, max_chunks_per_block, block_e, block_n, interpret,
        precision, has_w, gather_mv,
    )
    if not has_w:
        edge_weight = jnp.zeros((data.shape[0],), data.dtype)
    return fn(data, segment_ids, bias, edge_weight)


def max_chunks_hint(
    segment_ids, num_segments: int, block_e: int = 512, block_n: int = 256
) -> int:
    """Host-side (concrete ids) bound for ``max_chunks_per_block``."""
    import numpy as np

    ids = np.asarray(segment_ids)
    nb = -(-num_segments // block_n)
    starts = np.searchsorted(ids, np.arange(nb) * block_n)
    ends = np.searchsorted(ids, np.arange(1, nb + 1) * block_n, side="left")
    cs = starts // block_e
    ce = -(-ends // block_e)
    return max(1, int((ce - cs).max(initial=1)))


# --- sorted row gather: the transpose kernel -------------------------------


class _VBlockSchedule:
    """Chunk-major scheduling for sorted-id kernels whose output block is
    an EDGE chunk and whose inner grid dim iterates the chunk's vertex-
    block span (sorted_row_gather, the fused-bwd gd kernel). The shared
    scaffold: edge/vertex padding, per-chunk span bounds, and the clamped
    vertex-block index map."""

    def __init__(self, ids, num_rows, E, *, block_e, block_n, max_vblocks):
        self.E = E
        self.E_pad = pl.cdiv(E, block_e) * block_e
        self.N_pad = pl.cdiv(num_rows, block_n) * block_n
        self.nb = self.N_pad // block_n
        self.num_chunks = self.E_pad // block_e
        self.block_e, self.block_n = block_e, block_n
        ids_p = ids
        if self.E_pad != E:
            ids_p = jnp.pad(ids, (0, self.E_pad - E),
                            constant_values=num_rows + 1)
        self.ids3d = ids_p.reshape(self.num_chunks, 1, block_e)
        # per-chunk vertex-block span (ids sorted within each chunk):
        # first/last element of the chunk, clamped into [0, nb)
        firsts = jnp.clip(ids_p.reshape(self.num_chunks, block_e)[:, 0], 0,
                          self.N_pad - 1)
        lasts = jnp.clip(ids_p.reshape(self.num_chunks, block_e)[:, -1], 0,
                         self.N_pad - 1)
        self.vb_start = (firsts // block_n).astype(jnp.int32)
        self.vb_counts = jnp.minimum(
            (lasts // block_n).astype(jnp.int32) - self.vb_start + 1,
            max_vblocks,
        ).astype(jnp.int32)

    def pad_vertices(self, x):
        if self.N_pad != x.shape[0]:
            x = jnp.pad(x, ((0, self.N_pad - x.shape[0]), (0, 0)))
        return x

    def pad_edges(self, arr):
        if self.E_pad != arr.shape[0]:
            arr = jnp.pad(
                arr, ((0, self.E_pad - arr.shape[0]),)
                + ((0, 0),) * (arr.ndim - 1))
        return arr

    def vtx_index(self, k, j, starts, counts):
        # clamp past-count iterations onto the last valid block: Mosaic
        # skips the DMA when consecutive steps map to the same block
        return (
            jnp.minimum(
                starts[k] + jnp.minimum(j, jnp.maximum(counts[k] - 1, 0)),
                self.nb - 1,
            ),
            0,
        )

    def vtx_spec(self, F):
        return pl.BlockSpec((self.block_n, F), self.vtx_index)

    def ids_spec(self):
        return pl.BlockSpec((1, 1, self.block_e), lambda k, j, s, c: (k, 0, 0))

    def out_spec(self, F):
        return pl.BlockSpec((self.block_e, F), lambda k, j, s, c: (k, 0))


def _gather_kernel(
    vb_starts_ref, vb_counts_ref, ids_ref, x_ref, out_ref, *,
    block_n, block_e, precision,
):
    k = pl.program_id(0)  # edge chunk (owns the resident out block)
    j = pl.program_id(1)  # vertex-block iteration within the chunk's span

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(j < vb_counts_ref[k])
    def _accumulate():
        ids = ids_ref[0, 0]  # [block_e] int32 (global, sorted)
        vb = vb_starts_ref[k] + j  # this iteration's vertex block
        rel2 = (ids - vb * block_n)[:, None]  # [block_e, 1] (2-D: Mosaic)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_e, block_n), 1)
        onehot = jnp.where(
            (cols == rel2) & (rel2 >= 0) & (rel2 < block_n), 1.0, 0.0
        ).astype(x_ref.dtype)
        # [block_e, block_n] @ [block_n, F] -> rows selected on the MXU;
        # OOB/masked ids match no column and stay zero
        out_ref[...] += jax.lax.dot_general(
            onehot, x_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=out_ref.dtype,
            precision=precision,
        )


@functools.lru_cache(maxsize=None)
def _make_srg(num_rows, max_vblocks, block_e, block_n, interpret, precision,
              scatter_mc):
    def impl(x, ids):
        E = ids.shape[0]
        F = x.shape[1]
        vs = _VBlockSchedule(ids, num_rows, E, block_e=block_e,
                             block_n=block_n, max_vblocks=max_vblocks)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(vs.num_chunks, max_vblocks),
            in_specs=[vs.ids_spec(), vs.vtx_spec(F)],
            out_specs=vs.out_spec(F),
        )
        out = pl.pallas_call(
            functools.partial(
                _gather_kernel, block_n=block_n, block_e=block_e,
                precision=_precision(precision),
            ),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((vs.E_pad, F), jnp.float32),
            interpret=interpret,
        )(vs.vb_start, vs.vb_counts, vs.ids3d, vs.pad_vertices(x))
        return out[:E].astype(x.dtype)

    @jax.custom_vjp
    def f(x, ids):
        return impl(x, ids)

    def fwd(x, ids):
        return impl(x, ids), ids

    def bwd(ids, g):
        # exact transpose: segment-sum of the cotangent rows back onto the
        # gathered vertices — the EXISTING sorted scatter kernel
        from dgraph_tpu.ops.local import sorted_segment_sum_any

        dx = sorted_segment_sum_any(
            g, ids, num_rows, block_e, block_n, scatter_mc
        )
        return dx, None

    f.defvjp(fwd, bwd)
    return f


def _fused_bwd_kernel(
    vb_starts_ref, vb_counts_ref, ids_ref, data_ref, g_ref, bias_ref,
    out_ref, g_acc, bias_acc, *, block_n, block_e, precision,
):
    """gd[e] = g[ids[e]] * 1[data[e] + bias[ids[e]] > 0] in ONE
    chunk-major pass: the fused scatter's data-gradient with no [E, F]
    HBM intermediates (no bias-rows take, no g-rows take, no act
    materialization — the r4 composed bwd streamed all three). The
    WEIGHTED fused op keeps the composed backward (it additionally needs
    d_w, whose row-dot requires the very intermediates this kernel
    avoids), so there is deliberately no edge-weight input here.

    Chunk-major grid like :func:`_gather_kernel`; g and bias rows are
    accumulated per vertex-block via one-hot matmuls (disjoint per edge,
    so plain += is exact), and the activation mask is decided in f32 at
    the last vertex block of the chunk's span — the same rounding story
    as the forward kernel (operands rounded to the data dtype, compare
    in f32)."""
    k = pl.program_id(0)  # edge chunk (owns the resident out block)
    j = pl.program_id(1)  # vertex-block iteration within the chunk's span

    @pl.when(j == 0)
    def _init():
        # accumulate in f32 VMEM SCRATCH, not in the output: an f32
        # [E, F] out would be an f32 HBM stream (the discipline the gd
        # kernel exists to avoid) — out is written once, in the compute
        # dtype, at the last step of the span
        g_acc[...] = jnp.zeros_like(g_acc)
        bias_acc[...] = jnp.zeros_like(bias_acc)

    @pl.when(j < vb_counts_ref[k])
    def _accumulate():
        ids = ids_ref[0, 0]  # [block_e] int32 (global, sorted)
        vb = vb_starts_ref[k] + j
        rel2 = (ids - vb * block_n)[:, None]  # [block_e, 1] (2-D: Mosaic)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_e, block_n), 1)
        onehot = jnp.where(
            (cols == rel2) & (rel2 >= 0) & (rel2 < block_n), 1.0, 0.0
        ).astype(g_ref.dtype)
        g_acc[...] += jax.lax.dot_general(
            onehot, g_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=g_acc.dtype, precision=precision,
        )
        bias_acc[...] += jax.lax.dot_general(
            onehot, bias_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=bias_acc.dtype, precision=precision,
        )

    # runs AFTER this step's accumulation (kernel body is sequential), so
    # the span's g/bias sums are complete exactly once per chunk
    @pl.when(j == vb_counts_ref[k] - 1)
    def _finish():
        chunk = data_ref[0]  # [block_e, F]
        pre = chunk.astype(jnp.float32) + bias_acc[...]
        act = (pre > 0).astype(jnp.float32)
        out_ref[...] = (g_acc[...] * act).astype(out_ref.dtype)


@functools.lru_cache(maxsize=None)
def _make_fused_bwd(num_rows, max_vblocks, block_e, block_n, interpret,
                    precision):
    """Builder for the (unweighted) fused scatter's data-gradient kernel
    (see :func:`_fused_bwd_kernel`). Returns fn(data, g, bias, ids) ->
    [E, F] gd in data's dtype."""

    def impl(data, g, bias, ids):
        E, F = data.shape
        vs = _VBlockSchedule(ids, num_rows, E, block_e=block_e,
                             block_n=block_n, max_vblocks=max_vblocks)
        data3d = vs.pad_edges(data).reshape(vs.num_chunks, block_e, F)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(vs.num_chunks, max_vblocks),
            in_specs=[
                vs.ids_spec(),
                pl.BlockSpec((1, block_e, F), lambda k, j, s, c: (k, 0, 0)),
                vs.vtx_spec(F),
                vs.vtx_spec(F),
            ],
            out_specs=vs.out_spec(F),
            scratch_shapes=[
                pltpu.VMEM((block_e, F), jnp.float32),  # g-rows acc
                pltpu.VMEM((block_e, F), jnp.float32),  # bias-rows acc
            ],
        )
        out = pl.pallas_call(
            functools.partial(
                _fused_bwd_kernel, block_n=block_n, block_e=block_e,
                precision=_precision(precision),
            ),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((vs.E_pad, F), data.dtype),
            interpret=interpret,
        )(vs.vb_start, vs.vb_counts, vs.ids3d, data3d,
          vs.pad_vertices(g), vs.pad_vertices(bias))
        return out[:E]

    return impl


def sorted_row_gather(
    x: jax.Array,  # [N, F] vertex table
    ids: jax.Array,  # [E] int32 MONOTONE non-decreasing row ids
    *,
    max_vblocks: int,  # >= max vertex blocks any edge chunk spans
    block_e: int = 512,
    block_n: int = 256,
    scatter_mc: int = 1,  # max_chunks hint for the VJP's segment sum
    interpret: bool = False,
    precision: str = "highest",  # the op is a pure row COPY: f32 inputs
    # must come back bit-faithful by default; callers in a bf16 compute
    # path pass "default" explicitly (the shared dtype->precision policy)
) -> jax.Array:
    """``x[ids]`` for sorted ids as blocked one-hot MXU matmuls — the exact
    TRANSPOSE of :func:`sorted_segment_sum` (same tiles, roles of the
    resident/streamed operands swapped). Rows whose id falls outside
    [0, N) come back zero (masked-edge convention). Differentiable: the
    VJP is the sorted segment-sum kernel.

    Compute ``max_vblocks`` at plan-build time with
    :func:`max_vblocks_hint`; the schedule reads only each chunk's
    first/last id (sortedness), so it is computed in-jit.
    """
    return _make_srg(
        x.shape[0], max_vblocks, block_e, block_n, interpret, precision,
        scatter_mc,
    )(x, ids)


def max_vblocks_hint(
    segment_ids, num_rows: int, block_e: int = 512, block_n: int = 256
) -> int:
    """Host-side (concrete sorted ids) bound for
    :func:`sorted_row_gather`'s ``max_vblocks``: the max number of
    ``block_n``-row vertex blocks any ``block_e`` edge chunk spans."""
    import numpy as np

    ids = np.clip(np.asarray(segment_ids), 0, max(num_rows - 1, 0))
    E = ids.shape[0]
    if E == 0:
        return 1
    E_pad = -(-E // block_e) * block_e
    ids_p = np.pad(ids, (0, E_pad - E), constant_values=ids[-1])
    chunks = ids_p.reshape(-1, block_e)
    span = chunks[:, -1] // block_n - chunks[:, 0] // block_n + 1
    return max(1, int(span.max(initial=1)))
