"""Pallas TPU kernel: sorted-segment sum via blocked one-hot MXU matmuls.

This is the TPU-native replacement for the reference's CUDA scatter-add
kernels (``Rank_Local_Scatter_Kernel`` / ``Masked_Scatter_Gather_Kernel``,
``DGraph/distributed/csrc/local_data_kernels.cuh:208-342``): TPU has no
atomics, so the kernel exploits the plan-guaranteed MONOTONE segment ids
(``EdgePlan.owner_sorted``) instead:

- Edges are processed in chunks of ``block_e``; output vertices in blocks of
  ``block_n``. Because ids are sorted, each vertex block's edges form ONE
  contiguous chunk range, found with a cheap in-jit searchsorted and handed
  to the kernel via scalar prefetch (``pltpu.PrefetchScalarGridSpec``).
- Within a chunk, scatter becomes a one-hot [block_e, block_n] matmul
  against the data chunk — an MXU contraction, not a serial scatter. This
  is the TPU analogue of the reference's float4-vectorized atomic kernel
  (``local_data_kernels.cuh:353-406``): same "make the memory system move
  wide rows" idea, expressed as systolic-array work.
- The grid is (num_vertex_blocks, max_chunks_per_block); the output block
  stays resident in VMEM across its chunk iterations (sequential TPU grid),
  accumulating partials, and spills to HBM once per vertex block.

The jnp ``segment_sum`` path remains the oracle and fallback
(``dgraph_tpu.ops.local``), mirroring the reference's dual CUDA/torch
implementation pattern (``RankLocalOps.py:21-31,66-70``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(
    starts_ref, counts_ref, ids_ref, data_ref, out_ref, *, block_n, block_e, input_op,
    precision,
):
    b = pl.program_id(0)
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(k < counts_ref[b])
    def _accumulate():
        ids = ids_ref[0, 0]  # [block_e] int32 (global segment ids)
        chunk = data_ref[0]  # [block_e, F]
        if input_op == "relu":
            # fused ReLU epilogue on the scatter input — the reference's
            # Fused_ReLU_Scatter_Kernel (local_data_kernels.cuh:34-72) done
            # in-VMEM before the one-hot contraction
            chunk = jnp.maximum(chunk, 0)
        rel = ids - b * block_n
        # Mosaic can't insert a minor dim on 1-D bool vectors ("only
        # supported for 32-bit types"), so build the mask in 2-D int32
        # space: rel[:, None] is a 32-bit reshape, comparisons stay 2-D.
        rel2 = rel[:, None]  # [block_e, 1] int32
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_e, block_n), 1)
        onehot = jnp.where(
            (cols == rel2) & (rel2 >= 0) & (rel2 < block_n), 1.0, 0.0
        ).astype(chunk.dtype)
        out_ref[...] += jax.lax.dot_general(
            onehot,
            chunk,
            (((0,), (0,)), ((), ())),  # contract over block_e: [BN, F]
            preferred_element_type=out_ref.dtype,
            precision=precision,
        )


def _sorted_segment_sum_impl(
    data, segment_ids, num_segments, *, max_chunks_per_block, block_e, block_n,
    interpret, input_op, precision,
):
    if input_op not in ("none", "relu"):
        raise ValueError(f"input_op must be 'none' or 'relu', got {input_op!r}")
    E, F = data.shape
    E_pad = pl.cdiv(E, block_e) * block_e
    N_pad = pl.cdiv(num_segments, block_n) * block_n
    num_chunks = E_pad // block_e
    nb = N_pad // block_n
    if E_pad != E:
        pad = E_pad - E
        data = jnp.pad(data, ((0, pad), (0, 0)))
        segment_ids = jnp.pad(segment_ids, (0, pad), constant_values=num_segments + 1)

    # ids as [num_chunks, 1, block_e]: Mosaic requires the last two block
    # dims to be (8,128)-tileable OR equal to the array dims — a (1, block_e)
    # block over a [num_chunks, block_e] array violates the sublane rule on
    # real TPU (interpret mode doesn't check), so carry an explicit
    # singleton sublane dim that IS the full array dim.
    ids3d = segment_ids.reshape(num_chunks, 1, block_e)
    data3d = data.reshape(num_chunks, block_e, F)

    # per-vertex-block chunk ranges (in-jit; ids sorted)
    block_edges_start = jnp.searchsorted(segment_ids, jnp.arange(nb) * block_n)
    block_edges_end = jnp.searchsorted(
        segment_ids, jnp.arange(1, nb + 1) * block_n, side="left"
    )
    chunk_start = (block_edges_start // block_e).astype(jnp.int32)
    chunk_end = (pl.cdiv(block_edges_end, block_e)).astype(jnp.int32)
    chunk_counts = jnp.minimum(chunk_end - chunk_start, max_chunks_per_block).astype(
        jnp.int32
    )

    # Iterations past counts[b] clamp to the block's LAST VALID chunk:
    # Mosaic skips the DMA when consecutive grid steps map to the same block
    # index, so the padded tail of the (nb, max_chunks) grid costs no HBM
    # traffic (the @pl.when guard already skips its compute).
    def _chunk_index(b, k, starts, counts):
        return jnp.minimum(
            starts[b] + jnp.minimum(k, jnp.maximum(counts[b] - 1, 0)),
            num_chunks - 1,
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nb, max_chunks_per_block),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_e),
                lambda b, k, starts, counts: (_chunk_index(b, k, starts, counts), 0, 0),
            ),
            pl.BlockSpec(
                (1, block_e, F),
                lambda b, k, starts, counts: (_chunk_index(b, k, starts, counts), 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec((block_n, F), lambda b, k, starts, counts: (b, 0)),
    )
    prec = jax.lax.Precision.HIGHEST if precision == "highest" else jax.lax.Precision.DEFAULT
    # The MXU accumulator must be 32-bit ('tpu.matmul' rejects a bf16 acc),
    # and f32 accumulation over long segments is the atomicAdd-parity
    # semantics anyway — so the VMEM-resident output block is ALWAYS f32
    # (bf16 inputs still ride the fast bf16 MXU passes under
    # precision='default'); cast back to the input dtype on the way out.
    out = pl.pallas_call(
        functools.partial(
            _kernel, block_n=block_n, block_e=block_e, input_op=input_op, precision=prec
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N_pad, F), jnp.float32),
        interpret=interpret,
    )(chunk_start, chunk_counts, ids3d, data3d)
    return out[:num_segments].astype(data.dtype)


@functools.lru_cache(maxsize=None)
def _make_sss(num_segments, max_chunks_per_block, block_e, block_n, interpret,
              input_op, precision):
    impl = functools.partial(
        _sorted_segment_sum_impl,
        num_segments=num_segments, max_chunks_per_block=max_chunks_per_block,
        block_e=block_e, block_n=block_n, interpret=interpret,
        input_op=input_op, precision=precision,
    )

    @jax.custom_vjp
    def f(data, segment_ids):
        return impl(data, segment_ids)

    def fwd(data, segment_ids):
        res = (segment_ids, data if input_op == "relu" else None)
        return impl(data, segment_ids), res

    def bwd(res, g):
        segment_ids, data = res
        # column-chunked take: the same >128-lane row-gather cliff the
        # forward path avoids applies to the grad gather (shared impl:
        # ops.local.row_take, OOB ids -> zero grad rows)
        from dgraph_tpu.ops.local import row_take

        gd = row_take(g, segment_ids, oob="fill")
        if input_op == "relu":
            gd = gd * (data > 0).astype(gd.dtype)
        return gd, None

    f.defvjp(fwd, bwd)
    return f


def sorted_segment_sum(
    data: jax.Array,  # [E, F]
    segment_ids: jax.Array,  # [E] int32, MONOTONE non-decreasing
    num_segments: int,
    *,
    max_chunks_per_block: int,
    block_e: int = 512,
    block_n: int = 256,
    interpret: bool = False,
    input_op: str = "none",  # "none" | "relu" (fused input epilogue)
    precision: str = "highest",  # MXU passes for the one-hot contraction:
    # "highest" = f32-faithful accumulation (matches the CUDA atomicAdd
    # semantics, ~1.4x XLA's scatter path on v5e); "default" = bf16 input
    # truncation (fastest; right when the model already computes in bf16)
) -> jax.Array:
    """Segment sum for sorted ids. Rows with ids outside [0, num_segments)
    are dropped (use an out-of-range id for masked edges).

    Differentiable: the VJP is the gather transpose ``g[ids]`` (exactly the
    reference's gather-bwd = scatter-sum duality, ``_torch_func_impl.py``),
    with OOB ids contributing zero.

    ``max_chunks_per_block`` must be >= the true maximum
    ceil(edges_in_any_block/block_e) + 1 (the +1 covers chunk misalignment);
    compute it at plan-build time with :func:`max_chunks_hint`.
    """
    return _make_sss(
        num_segments, max_chunks_per_block, block_e, block_n, interpret,
        input_op, precision,
    )(data, segment_ids)


def max_chunks_hint(
    segment_ids, num_segments: int, block_e: int = 512, block_n: int = 256
) -> int:
    """Host-side (concrete ids) bound for ``max_chunks_per_block``."""
    import numpy as np

    ids = np.asarray(segment_ids)
    nb = -(-num_segments // block_n)
    starts = np.searchsorted(ids, np.arange(nb) * block_n)
    ends = np.searchsorted(ids, np.arange(1, nb + 1) * block_n, side="left")
    cs = starts // block_e
    ce = -(-ends // block_e)
    return max(1, int((ce - cs).max(initial=1)))
