"""Pallas TPU kernel: sorted-segment sum via blocked one-hot MXU matmuls.

This is the TPU-native replacement for the reference's CUDA scatter-add
kernels (``Rank_Local_Scatter_Kernel`` / ``Masked_Scatter_Gather_Kernel``,
``DGraph/distributed/csrc/local_data_kernels.cuh:208-342``): TPU has no
atomics, so the kernel exploits the plan-guaranteed MONOTONE segment ids
(``EdgePlan.owner_sorted``) instead:

- Edges are processed in chunks of ``block_e``; output vertices in blocks of
  ``block_n``. Because ids are sorted, each vertex block's edges form ONE
  contiguous chunk range, found with a cheap in-jit searchsorted and handed
  to the kernel via scalar prefetch (``pltpu.PrefetchScalarGridSpec``).
- Within a chunk, scatter becomes a one-hot [block_e, block_n] matmul
  against the data chunk — an MXU contraction, not a serial scatter. This
  is the TPU analogue of the reference's float4-vectorized atomic kernel
  (``local_data_kernels.cuh:353-406``): same "make the memory system move
  wide rows" idea, expressed as systolic-array work.
- The grid is (num_vertex_blocks, max_chunks_per_block); the output block
  stays resident in VMEM across its chunk iterations (sequential TPU grid),
  accumulating partials, and spills to HBM once per vertex block.

The jnp ``segment_sum`` path remains the oracle and fallback
(``dgraph_tpu.ops.local``), mirroring the reference's dual CUDA/torch
implementation pattern (``RankLocalOps.py:21-31,66-70``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(
    starts_ref, counts_ref, ids_ref, data_ref, out_ref, *, block_n, block_e, input_op
):
    b = pl.program_id(0)
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(k < counts_ref[b])
    def _accumulate():
        ids = ids_ref[0]  # [block_e] int32 (global segment ids)
        chunk = data_ref[0]  # [block_e, F]
        if input_op == "relu":
            # fused ReLU epilogue on the scatter input — the reference's
            # Fused_ReLU_Scatter_Kernel (local_data_kernels.cuh:34-72) done
            # in-VMEM before the one-hot contraction
            chunk = jnp.maximum(chunk, 0)
        rel = ids - b * block_n
        valid = (rel >= 0) & (rel < block_n)
        rel = jnp.where(valid, rel, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_e, block_n), 1)
        onehot = jnp.where(
            valid[:, None] & (cols == rel[:, None]), 1.0, 0.0
        ).astype(chunk.dtype)
        out_ref[...] += jax.lax.dot_general(
            onehot,
            chunk,
            (((0,), (0,)), ((), ())),  # contract over block_e: [BN, F]
            preferred_element_type=out_ref.dtype,
        )


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_segments", "max_chunks_per_block", "block_e", "block_n", "interpret",
        "input_op",
    ),
)
def sorted_segment_sum(
    data: jax.Array,  # [E, F]
    segment_ids: jax.Array,  # [E] int32, MONOTONE non-decreasing
    num_segments: int,
    *,
    max_chunks_per_block: int,
    block_e: int = 256,
    block_n: int = 256,
    interpret: bool = False,
    input_op: str = "none",  # "none" | "relu" (fused input epilogue)
) -> jax.Array:
    """Segment sum for sorted ids. Rows with ids outside [0, num_segments)
    are dropped (use an out-of-range id for masked edges).

    ``max_chunks_per_block`` must be >= the true maximum
    ceil(edges_in_any_block/block_e) + 1 (the +1 covers chunk misalignment);
    compute it at plan-build time with :func:`max_chunks_hint`.
    """
    if input_op not in ("none", "relu"):
        raise ValueError(f"input_op must be 'none' or 'relu', got {input_op!r}")
    E, F = data.shape
    E_pad = pl.cdiv(E, block_e) * block_e
    N_pad = pl.cdiv(num_segments, block_n) * block_n
    num_chunks = E_pad // block_e
    nb = N_pad // block_n
    if E_pad != E:
        pad = E_pad - E
        data = jnp.pad(data, ((0, pad), (0, 0)))
        segment_ids = jnp.pad(segment_ids, (0, pad), constant_values=num_segments + 1)

    ids2d = segment_ids.reshape(num_chunks, block_e)
    data3d = data.reshape(num_chunks, block_e, F)

    # per-vertex-block chunk ranges (in-jit; ids sorted)
    block_edges_start = jnp.searchsorted(segment_ids, jnp.arange(nb) * block_n)
    block_edges_end = jnp.searchsorted(
        segment_ids, jnp.arange(1, nb + 1) * block_n, side="left"
    )
    chunk_start = (block_edges_start // block_e).astype(jnp.int32)
    chunk_end = (pl.cdiv(block_edges_end, block_e)).astype(jnp.int32)
    chunk_counts = jnp.minimum(chunk_end - chunk_start, max_chunks_per_block).astype(
        jnp.int32
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nb, max_chunks_per_block),
        in_specs=[
            pl.BlockSpec(
                (1, block_e),
                lambda b, k, starts, counts: (
                    jnp.minimum(starts[b] + k, num_chunks - 1),
                    0,
                ),
            ),
            pl.BlockSpec(
                (1, block_e, F),
                lambda b, k, starts, counts: (
                    jnp.minimum(starts[b] + k, num_chunks - 1),
                    0,
                    0,
                ),
            ),
        ],
        out_specs=pl.BlockSpec((block_n, F), lambda b, k, starts, counts: (b, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_kernel, block_n=block_n, block_e=block_e, input_op=input_op),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N_pad, F), data.dtype),
        interpret=interpret,
    )(chunk_start, chunk_counts, ids2d, data3d)
    return out[:num_segments]


def max_chunks_hint(
    segment_ids, num_segments: int, block_e: int = 256, block_n: int = 256
) -> int:
    """Host-side (concrete ids) bound for ``max_chunks_per_block``."""
    import numpy as np

    ids = np.asarray(segment_ids)
    nb = -(-num_segments // block_n)
    starts = np.searchsorted(ids, np.arange(nb) * block_n)
    ends = np.searchsorted(ids, np.arange(1, nb + 1) * block_n, side="left")
    cs = starts // block_e
    ce = -(-ends // block_e)
    return max(1, int((ce - cs).max(initial=1)))
