"""``python -m dgraph_tpu.chaos`` — fault-injection registry CLI.

``--selftest`` (the tier-1 registration, compile-free like the tune/serve
selftests) checks the whole registry contract in-process with hard
assertions: grammar acceptance/rejection, exact-index firing, external
(step) indices, attempt gating, count windows, seeded-probability
determinism, poison injection, SIGTERM delivery, wedge sleeping, and the
inert fast path.  Exit 0 only if every assertion holds; the result is one
JSON line carrying a RunHealth record either way.

``--show`` (default when no mode flag is given) prints the currently armed
spec (from ``DGRAPH_CHAOS``) and the known fault points — the operator's
"is chaos on?" probe.
"""

from __future__ import annotations

import dataclasses
import json
import signal
import time


@dataclasses.dataclass
class Config:
    """Chaos fault-injection registry (``--selftest`` for the compile-free
    tier-1 smoke; default shows the armed spec and known points)."""

    selftest: bool = False
    indent: int = 0


def _check(failures, cond, msg):
    if not cond:
        failures.append(msg)


def _selftest() -> dict:
    from dgraph_tpu import chaos

    failures = []
    try:
        # --- grammar ---
        cl = chaos.parse_spec("step=wedge@3:sleep_s=60:attempt=0;grads=poison@5")
        _check(failures, len(cl) == 2, f"expected 2 clauses, got {len(cl)}")
        _check(
            failures,
            cl[0].action == "wedge" and cl[0].index == 3
            and cl[0].sleep_s == 60.0 and cl[0].attempt == 0,
            f"wedge clause misparsed: {cl[0]}",
        )
        for bad in (
            "nonsense",
            "unknown.point=raise@0",
            "step=explode@0",
            "step=raise@-1",
            "step=raise@x",
            "step=raise@0:count=0",
            "step=raise@0:prob=1.5",
            "step=raise@0:bogus=1",
            "",
        ):
            try:
                chaos.parse_spec(bad)
                failures.append(f"spec {bad!r} parsed but should be rejected")
            except ValueError:
                pass

        # --- inert fast path ---
        chaos.disarm()
        _check(failures, chaos.fire("step") is False, "disarmed fire() fired")
        _check(failures, chaos.active_spec() is None, "disarmed spec not None")

        # --- exact-index raise via the per-point call counter ---
        chaos.arm("ckpt.save=raise@2")
        fired_at = []
        for i in range(4):
            try:
                chaos.fire("ckpt.save")
            except chaos.ChaosFault as e:
                fired_at.append(i)
                _check(failures, e.index == 2, f"fault index {e.index} != 2")
                _check(
                    failures, e.record()["kind"] == "chaos_fault",
                    "ChaosFault.record() malformed",
                )
        _check(failures, fired_at == [2], f"raise fired at {fired_at}, want [2]")
        _check(
            failures, chaos.call_count("ckpt.save") == 4,
            f"call_count {chaos.call_count('ckpt.save')} != 4",
        )

        # --- external (step) index + count window ---
        chaos.arm("grads=poison@5:count=2")
        got = [s for s in range(10) if chaos.fire("grads", index=s)]
        _check(failures, got == [5, 6], f"poison window {got}, want [5, 6]")

        # --- sharded-plan points (plan_shards.py / build_edge_plan_sharded):
        # registered, parseable, and firing like any host boundary ---
        for pt in ("plan.build_shard", "plan.write", "plan.load"):
            _check(
                failures, pt in chaos.KNOWN_POINTS,
                f"plan point {pt!r} missing from KNOWN_POINTS",
            )
            (cl,) = chaos.parse_spec(f"{pt}=sigterm@2")
            _check(
                failures, cl.point == pt and cl.action == "sigterm",
                f"plan point clause misparsed: {cl}",
            )
        chaos.arm("plan.write=raise@1")
        plan_fired = []
        for i in range(3):
            try:
                chaos.fire("plan.write")
            except chaos.ChaosFault:
                plan_fired.append(i)
        _check(
            failures, plan_fired == [1],
            f"plan.write fired at {plan_fired}, want [1]",
        )

        # --- serving control-plane points (serve/rollover.py + deltas.py):
        # registered, parseable, and firing like any host boundary ---
        for pt in ("serve.swap", "serve.delta_append", "serve.replan"):
            _check(
                failures, pt in chaos.KNOWN_POINTS,
                f"serve point {pt!r} missing from KNOWN_POINTS",
            )
            (cl,) = chaos.parse_spec(f"{pt}=raise@0")
            _check(
                failures, cl.point == pt and cl.action == "raise",
                f"serve point clause misparsed: {cl}",
            )
        # the replan commit-boundary clause: replan consults the point
        # TWICE per call (entry, then pre-flip), so sigterm@1 is the
        # torn-window injection — prove index-1 gating fires exactly there
        chaos.arm("serve.replan=raise@1")
        replan_fired = []
        for i in range(3):
            try:
                chaos.fire("serve.replan")
            except chaos.ChaosFault:
                replan_fired.append(i)
        _check(
            failures, replan_fired == [1],
            f"serve.replan fired at {replan_fired}, want [1]",
        )

        # --- membership points (comm/membership.py): registered, parseable,
        # firing like any host boundary ---
        for pt in ("comm.heartbeat", "comm.rendezvous", "comm.join"):
            _check(
                failures, pt in chaos.KNOWN_POINTS,
                f"membership point {pt!r} missing from KNOWN_POINTS",
            )
            (cl,) = chaos.parse_spec(f"{pt}=raise@1")
            _check(
                failures, cl.point == pt and cl.action == "raise",
                f"membership point clause misparsed: {cl}",
            )

        # --- grow-to-fit points (train/grow.py): registered, parseable,
        # firing like any host boundary.  grow.adopt is consulted ONCE per
        # transition at the commit boundary (artifacts durable, pointer
        # flip pending), so sigterm@0 is the torn-window injection —
        # prove index-0 gating fires exactly on the first consult ---
        for pt in ("grow.replan", "grow.adopt"):
            _check(
                failures, pt in chaos.KNOWN_POINTS,
                f"grow point {pt!r} missing from KNOWN_POINTS",
            )
            (cl,) = chaos.parse_spec(f"{pt}=sigterm@0")
            _check(
                failures, cl.point == pt and cl.action == "sigterm",
                f"grow point clause misparsed: {cl}",
            )
        chaos.arm("grow.adopt=raise@0")
        adopt_fired = []
        for i in range(3):
            try:
                chaos.fire("grow.adopt")
            except chaos.ChaosFault:
                adopt_fired.append(i)
        _check(
            failures, adopt_fired == [0],
            f"grow.adopt fired at {adopt_fired}, want [0]",
        )

        # --- delay action: seeded sleep-jitter (straggler injection) ---
        (cl,) = chaos.parse_spec("comm.heartbeat=delay@0:count=4:seed=3")
        _check(
            failures, cl.sleep_s == chaos.DEFAULT_DELAY_SLEEP_S,
            f"delay default jitter ceiling {cl.sleep_s} != "
            f"{chaos.DEFAULT_DELAY_SLEEP_S}",
        )
        (cl,) = chaos.parse_spec("comm.heartbeat=delay@0:sleep_s=0.2")
        _check(failures, cl.sleep_s == 0.2, "delay sleep_s override lost")

        class _SleepSpy:
            def __init__(self):
                self.slept = []

            def sleep(self, s):
                self.slept.append(s)

            def __getattr__(self, name):  # monotonic etc. pass through
                return getattr(time, name)

        def delay_schedule():
            spy = _SleepSpy()
            orig_time = chaos.time
            chaos.time = spy
            try:
                chaos.arm("comm.heartbeat=delay@0:count=8:sleep_s=0.5:seed=11")
                for i in range(8):
                    chaos.fire("comm.heartbeat", index=i)
            finally:
                chaos.time = orig_time
            return spy.slept

        a, b = delay_schedule(), delay_schedule()
        _check(failures, len(a) == 8, f"delay fired {len(a)}/8 times")
        _check(failures, a == b, f"delay jitter not deterministic: {a} vs {b}")
        _check(
            failures, all(0.0 <= s < 0.5 for s in a),
            f"delay jitter out of [0, sleep_s): {a}",
        )

        # --- rank gating (the group supervisor's member ordinal) ---
        chaos.arm("step=raise@1:rank=2", rank=0)
        try:
            for s in range(4):
                chaos.fire("step", index=s)
        except chaos.ChaosFault:
            failures.append("rank=2 clause fired on rank 0")
        chaos.arm("step=raise@1:rank=2", rank=2)
        try:
            for s in range(4):
                chaos.fire("step", index=s)
            failures.append("rank=2 clause never fired on rank 2")
        except chaos.ChaosFault:
            pass

        # --- attempt gating (the supervisor's restart ordinal) ---
        chaos.arm("step=raise@1:attempt=0", attempt=1)
        try:
            for s in range(4):
                chaos.fire("step", index=s)
        except chaos.ChaosFault:
            failures.append("attempt=0 clause fired on attempt 1")
        chaos.arm("step=raise@1:attempt=1", attempt=1)
        try:
            for s in range(4):
                chaos.fire("step", index=s)
            failures.append("attempt=1 clause never fired on attempt 1")
        except chaos.ChaosFault:
            pass

        # --- seeded probability: deterministic schedule ---
        def schedule():
            chaos.arm("grads=poison@0:prob=0.5:seed=7")
            return [s for s in range(32) if chaos.fire("grads", index=s)]

        a, b = schedule(), schedule()
        _check(failures, a == b, f"prob schedule not deterministic: {a} vs {b}")
        _check(failures, 0 < len(a) < 32, f"prob=0.5 fired {len(a)}/32 times")

        # --- poison helpers ---
        import numpy as np

        x = chaos.poison_array(np.ones(4, np.float32))
        _check(
            failures,
            np.isnan(x[0]) and x.shape == (4,) and np.all(x[1:] == 1.0),
            f"poison_array wrong: {x}",
        )
        y = chaos.poison_array(np.ones(3, np.int32))
        _check(failures, np.all(y == 1), "poison_array touched an int array")
        tree = chaos.poison_pytree({"x": np.ones(2, np.float64), "y": np.arange(2)})
        _check(
            failures,
            np.isnan(tree["x"][0]) and tree["y"][0] == 0,
            "poison_pytree wrong",
        )

        # --- sigterm delivery ---
        seen = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
        try:
            chaos.arm("step=sigterm@0")
            chaos.fire("step", index=0)
            _check(failures, seen == [signal.SIGTERM], "SIGTERM not delivered")
        finally:
            signal.signal(signal.SIGTERM, prev)

        # --- wedge sleeps in place ---
        chaos.arm("step=wedge@0:sleep_s=0.05")
        t0 = time.monotonic()
        chaos.fire("step", index=0)
        _check(
            failures, time.monotonic() - t0 >= 0.05,
            "wedge returned before its sleep",
        )

        # --- snapshot + RunHealth env field ---
        chaos.arm("step=raise@9")
        snap = chaos.snapshot()
        _check(failures, snap["spec"] == "step=raise@9", f"snapshot {snap}")
        from dgraph_tpu.obs.health import RunHealth

        env = RunHealth.begin("chaos.selftest").env
        _check(
            failures, env.get("chaos") == "step=raise@9",
            f"RunHealth env chaos field = {env.get('chaos')!r}",
        )
        chaos.disarm()
        env = RunHealth.begin("chaos.selftest").env
        _check(
            failures, env.get("chaos") is None,
            "RunHealth env chaos field not None when inert",
        )
    finally:
        chaos.reset()  # leave the process on env-driven behavior

    return {"kind": "chaos_selftest", "failures": failures}


def main(cfg: Config) -> dict:
    from dgraph_tpu import chaos
    from dgraph_tpu.obs.health import RunHealth

    health = RunHealth.begin("chaos.cli")
    if not cfg.selftest:
        out = {
            **chaos.snapshot(),
            "known_points": dict(chaos.KNOWN_POINTS),
            "run_health": health.finish(),
        }
        print(json.dumps(out, indent=cfg.indent or None))
        return out
    try:
        out = _selftest()
    except BaseException as e:  # every exit path carries a RunHealth record
        rec = {
            "kind": "chaos_selftest",
            "failures": [f"crashed: {type(e).__name__}: {e}"],
            "run_health": health.finish(
                f"chaos selftest crashed: {type(e).__name__}: {e}",
                wedge="stage_failure",
            ),
        }
        print(json.dumps(rec, indent=cfg.indent or None))
        raise
    failures = out["failures"]
    out["run_health"] = health.finish(
        "; ".join(failures) if failures else None,
        wedge="stage_failure" if failures else None,
    )
    print(json.dumps(out, indent=cfg.indent or None))
    if failures:
        raise SystemExit("chaos selftest FAILED: " + "; ".join(failures))
    return out


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
