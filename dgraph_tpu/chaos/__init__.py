"""Deterministic fault injection at host boundaries.

PR 1-4 built the *detection* half of the resilience story (RunHealth wedge
classification, ``StepWatchdog`` exiting :data:`~dgraph_tpu.train.elastic.
WEDGED_EXIT_CODE`, corrupt-checkpoint fallback, serve backpressure) — but
none of it was driven by reproducible faults.  This module is the missing
*cause* side: named fault points at host boundaries that fire
deterministically by step/call index, so every recovery path is testable
bit-for-bit instead of waiting for a real lease wedge.

Design rules:

- **Host boundaries only.** A fault point is consulted between device
  dispatches (checkpoint save/read, data load, step boundary, serving
  dispatch) — never inside a traced function, so arming chaos changes zero
  XLA programs and costs zero recompiles.
- **Inert by default, near-zero overhead.** With ``DGRAPH_CHAOS`` unset and
  nothing armed, :func:`fire` is one module-attribute read and a falsy
  check.
- **Deterministic.** A clause fires at an exact call/step index (``@K``),
  optionally for ``count`` consecutive indices, optionally only on a given
  supervisor ``attempt`` (the restart ordinal the train supervisor exports
  as ``DGRAPH_CHAOS_ATTEMPT``).  Probabilistic clauses (``prob=``) draw
  from a per-clause seeded RNG, so a given seed replays the identical
  fault schedule.

Spec grammar (``DGRAPH_CHAOS`` env var, or :func:`arm`)::

    spec    := clause (';' clause)*
    clause  := point '=' action '@' index (':' param '=' value)*
    point   := one of KNOWN_POINTS (e.g. 'step', 'ckpt.save', 'grads')
    action  := 'raise' | 'wedge' | 'sigterm' | 'poison' | 'delay'
    index   := non-negative int: the call index (or caller-supplied step
               index) at which the clause starts firing
    params  := count=N    fire for N consecutive indices (default 1)
               attempt=K  fire only on supervisor attempt K
               rank=K     fire only on group-supervisor rank K
               sleep_s=S  wedge hold seconds (default 3600); for 'delay'
                          the jitter ceiling (default 0.05)
               prob=P     fire with probability P at each index >= index
               seed=S     RNG seed for prob/delay clauses (default 0)

Examples::

    DGRAPH_CHAOS="step=wedge@3:sleep_s=60:attempt=0"   # wedge step 3, 1st run
    DGRAPH_CHAOS="ckpt.save=raise@1;data.load=raise@0" # two points at once
    DGRAPH_CHAOS="grads=poison@5"                      # NaN grads at step 5
    DGRAPH_CHAOS="serve.infer=raise@0:count=2"         # 2 transient errors

Actions: ``raise`` raises :class:`ChaosFault` (a transient host error);
``wedge`` sleeps ``sleep_s`` in place, simulating the hung dispatch a lost
TPU lease produces (the :class:`~dgraph_tpu.train.elastic.StepWatchdog`
is what must catch it); ``sigterm`` delivers SIGTERM to this process (a
simulated preemption, caught by :class:`~dgraph_tpu.train.elastic.
PreemptionGuard`); ``poison`` makes :func:`fire` return True so the call
site injects a non-finite value host-side (see :func:`poison_array`);
``delay`` sleeps a seeded uniform jitter in ``[0, sleep_s)`` — the
deterministic straggler, meant for ``comm.heartbeat`` so membership's
straggler detection (not its loss path) is what must notice.

Every RunHealth env snapshot records the active spec (or None) as its
``chaos`` field, so a perf artifact can never silently include a
fault-injected run (:mod:`dgraph_tpu.obs.health`).
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import threading
import time
from typing import Optional

ENV_VAR = "DGRAPH_CHAOS"
# the supervisor's restart ordinal, exported to each child so a clause can
# target one attempt (a wedge that re-fired on every resume would loop the
# restart budget away)
ATTEMPT_ENV_VAR = "DGRAPH_CHAOS_ATTEMPT"
# the group supervisor's member ordinal (``supervise_group`` exports it to
# each rank child) — shared group identity, not chaos-owned (the constant
# lives in the jax-free ``dgraph_tpu.utils.env``; re-exported here because
# chaos is where a clause's ``rank=K`` param matches against it)
from dgraph_tpu.utils.env import RANK_ENV_VAR  # noqa: E402

# point name -> where it is consulted (documentation + typo guard: a spec
# naming an unknown point is rejected at parse time, not silently inert)
KNOWN_POINTS = {
    "ckpt.save": "train/checkpoint.py::save_checkpoint entry",
    "ckpt.read": "train/checkpoint.py::restore_checkpoint entry",
    "data.load": "data/graph.py::DistributedGraph.from_global entry",
    "step": "train/elastic.py::run_elastic, before each step (index=step)",
    "grads": "batch-owning loops, per step (poison -> non-finite grads)",
    "serve.infer": "serve/engine.py::ServeEngine.infer, before dispatch",
    # sharded plan artifacts (plan_shards.py + plan.build_edge_plan_sharded):
    # kill/poison/torn-write scenarios over the streaming per-rank build
    # and the shard-aware loaders are deterministic through these
    "plan.build_shard": "plan.py::build_plan_shards, before each "
                        "rank's shard assembly (index=rank)",
    "plan.write": "plan_shards.py::write_shard, before each shard write",
    "plan.load": "plan_shards.py::read_shard, before each shard read",
    # elastic world membership (comm/membership.py): heartbeat/lease and
    # rendezvous faults — a 'delay' clause on comm.heartbeat is the
    # deterministic straggler, a 'raise' on comm.rendezvous exercises the
    # retrying-join backoff path, a 'sigterm' on step + rank=K is the
    # rank-kill the shrink-to-fit acceptance test drives
    "comm.heartbeat": "comm/membership.py::Membership.heartbeat, before "
                      "each lease write (index=seq)",
    "comm.rendezvous": "comm/membership.py::Membership.rendezvous, per "
                       "join attempt (index=attempt)",
    # grow-to-fit world expansion (train/grow.py + the membership join
    # rendezvous): a 'sigterm' on comm.join is a joiner preempted
    # mid-announcement, on grow.replan a recovery killed before any new
    # artifact exists, on grow.adopt the torn-window injection — killed
    # after every new-generation artifact is durable but before the
    # world.json pointer flips (old world must stay cleanly adoptable)
    "comm.join": "comm/membership.py::Joiner.announce, before each "
                 "join-lease write (index=seq)",
    "grow.replan": "train/grow.py::grow_world at recovery entry, before "
                   "any new-generation artifact is written",
    "grow.adopt": "train/grow.py::grow_world at the commit boundary — "
                  "artifacts durable, pointer flip still pending",
    # serving control plane (serve/rollover.py + serve/deltas.py): a
    # 'raise' on serve.swap proves rollback-to-prior-params with zero
    # dropped in-flight requests; a 'sigterm' on serve.replan (fired at
    # entry AND at the commit boundary after artifacts are durable but
    # before the pointer flips) proves generation adoption is atomic —
    # old or new adopted, never torn
    "serve.swap": "serve/rollover.py::swap_params, between checkpoint "
                  "staging and validation (the mid-swap rollback window)",
    "serve.delta_append": "serve/deltas.py::append_delta entry, before "
                          "the staged write",
    "serve.replan": "serve/deltas.py::replan — consulted at entry and "
                    "again at the pre-pointer-flip commit boundary",
}

ACTIONS = ("raise", "wedge", "sigterm", "poison", "delay")

DEFAULT_WEDGE_SLEEP_S = 3600.0
# 'delay' reuses sleep_s as the jitter CEILING; a wedge-scale default
# would turn an injected straggler into an injected wedge
DEFAULT_DELAY_SLEEP_S = 0.05


class ChaosFault(RuntimeError):
    """The synthetic transient failure an armed ``raise`` clause throws."""

    def __init__(self, point: str, index: int):
        super().__init__(
            f"chaos: injected fault at point {point!r} (call index {index})"
        )
        self.point = point
        self.index = index

    def record(self) -> dict:
        """Structured JSONL form (the serve-errors ``record()`` discipline)."""
        return {
            "kind": "chaos_fault",
            "point": self.point,
            "index": self.index,
            "detail": str(self),
        }


@dataclasses.dataclass(frozen=True)
class Clause:
    """One parsed fault clause. See the module docstring for the grammar."""

    point: str
    action: str
    index: int
    count: int = 1
    attempt: Optional[int] = None
    rank: Optional[int] = None
    sleep_s: float = DEFAULT_WEDGE_SLEEP_S
    prob: Optional[float] = None
    seed: int = 0

    def matches(
        self, index: int, attempt: int, rng: Optional[random.Random],
        rank: int = 0,
    ) -> bool:
        if self.attempt is not None and attempt != self.attempt:
            return False
        if self.rank is not None and rank != self.rank:
            return False
        if self.prob is not None:
            # eligible from the start index on; one deterministic draw per
            # eligible call keeps a given seed replaying the same schedule
            if index < self.index:
                return False
            return rng.random() < self.prob
        return self.index <= index < self.index + self.count


def parse_spec(spec: str) -> tuple:
    """Parse a ``DGRAPH_CHAOS`` spec into a tuple of :class:`Clause`.

    Raises ValueError on unknown points/actions or malformed clauses — a
    typo'd spec must fail loudly at arm time, not run fault-free.
    """
    clauses = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        head, _, params = raw.partition(":")
        if "=" not in head or "@" not in head.split("=", 1)[1]:
            raise ValueError(
                f"chaos clause {raw!r} is not 'point=action@index[:k=v...]'"
            )
        point, rhs = head.split("=", 1)
        action, idx_s = rhs.split("@", 1)
        point, action = point.strip(), action.strip()
        if point not in KNOWN_POINTS:
            raise ValueError(
                f"unknown chaos point {point!r} (known: "
                f"{', '.join(sorted(KNOWN_POINTS))})"
            )
        if action not in ACTIONS:
            raise ValueError(
                f"unknown chaos action {action!r} (known: {', '.join(ACTIONS)})"
            )
        try:
            index = int(idx_s)
        except ValueError:
            raise ValueError(f"chaos clause {raw!r}: index {idx_s!r} not an int")
        if index < 0:
            raise ValueError(f"chaos clause {raw!r}: index must be >= 0")
        kw = {}
        if params:
            for pair in params.split(":"):
                if "=" not in pair:
                    raise ValueError(
                        f"chaos clause {raw!r}: param {pair!r} is not k=v"
                    )
                k, v = pair.split("=", 1)
                k = k.strip()
                if k == "count":
                    kw["count"] = int(v)
                elif k == "attempt":
                    kw["attempt"] = int(v)
                elif k == "rank":
                    kw["rank"] = int(v)
                elif k == "sleep_s":
                    kw["sleep_s"] = float(v)
                elif k == "prob":
                    kw["prob"] = float(v)
                elif k == "seed":
                    kw["seed"] = int(v)
                else:
                    raise ValueError(
                        f"chaos clause {raw!r}: unknown param {k!r} "
                        "(count, attempt, rank, sleep_s, prob, seed)"
                    )
        if action == "delay" and "sleep_s" not in kw:
            kw["sleep_s"] = DEFAULT_DELAY_SLEEP_S
        c = Clause(point=point, action=action, index=index, **kw)
        if c.count < 1:
            raise ValueError(f"chaos clause {raw!r}: count must be >= 1")
        if c.prob is not None and not 0.0 <= c.prob <= 1.0:
            raise ValueError(f"chaos clause {raw!r}: prob must be in [0, 1]")
        clauses.append(c)
    if not clauses:
        raise ValueError(f"chaos spec {spec!r} contains no clauses")
    return tuple(clauses)


class _State:
    """An armed fault plan: clauses + per-point call counters + per-clause
    RNGs (prob and delay clauses). One per process; counters are
    thread-safe."""

    def __init__(self, clauses: tuple, spec: str, attempt: int, rank: int = 0):
        self.clauses = clauses
        self.spec = spec
        self.attempt = attempt
        self.rank = rank
        self.counts: dict = {}
        self.rngs = {
            i: random.Random(c.seed)
            for i, c in enumerate(clauses)
            if c.prob is not None or c.action == "delay"
        }


_LOCK = threading.Lock()
# None = env not yet consulted; False = inert (cached); _State = armed
_STATE = None


def _resolve():
    global _STATE
    with _LOCK:
        if _STATE is None:
            spec = os.environ.get(ENV_VAR, "").strip()
            if spec:
                att = os.environ.get(ATTEMPT_ENV_VAR, "").strip()
                rnk = os.environ.get(RANK_ENV_VAR, "").strip()
                _STATE = _State(
                    parse_spec(spec), spec,
                    int(att) if att else 0, int(rnk) if rnk else 0,
                )
            else:
                _STATE = False
        return _STATE


def arm(spec: str, attempt: Optional[int] = None,
        rank: Optional[int] = None) -> None:
    """Programmatically arm a fault plan (tests, selftest). ``attempt``
    defaults to ``DGRAPH_CHAOS_ATTEMPT`` (0 when unset), ``rank`` to
    ``DGRAPH_RANK`` (0 when unset)."""
    global _STATE
    clauses = parse_spec(spec)
    if attempt is None:
        att = os.environ.get(ATTEMPT_ENV_VAR, "").strip()
        attempt = int(att) if att else 0
    if rank is None:
        rnk = os.environ.get(RANK_ENV_VAR, "").strip()
        rank = int(rnk) if rnk else 0
    with _LOCK:
        _STATE = _State(clauses, spec, attempt, rank)


def disarm() -> None:
    """Make every fault point inert (regardless of the env var)."""
    global _STATE
    with _LOCK:
        _STATE = False


def reset() -> None:
    """Forget any armed/cached plan; the next :func:`fire` re-reads the
    environment (tests that mutate ``DGRAPH_CHAOS`` in-process)."""
    global _STATE
    with _LOCK:
        _STATE = None


def active_spec() -> Optional[str]:
    """The armed spec string, or None when inert — the value RunHealth env
    snapshots record as their ``chaos`` field."""
    st = _STATE
    if st is None:
        st = _resolve()
    return st.spec if st else None


def call_count(point: str) -> int:
    """Calls observed at ``point`` since arming (diagnostics/selftest)."""
    st = _STATE
    return st.counts.get(point, 0) if st else 0


def snapshot() -> dict:
    """One JSON-able diagnostic record of the armed plan and its counters."""
    st = _STATE
    if st is None:
        st = _resolve()
    if not st:
        return {"kind": "chaos", "spec": None}
    return {
        "kind": "chaos",
        "spec": st.spec,
        "attempt": st.attempt,
        "rank": st.rank,
        "counts": dict(st.counts),
    }


def fire(point: str, index: Optional[int] = None) -> bool:
    """Consult fault point ``point``; returns True iff a ``poison`` clause
    fired (the caller then injects the non-finite value host-side).

    ``index=None`` uses (and advances) the per-process call counter for the
    point; passing an explicit ``index`` (e.g. the global training step)
    makes the schedule survive process restarts — a resumed run re-fires by
    *global* step, and the ``attempt`` param is what keeps a wedge from
    re-firing forever across restarts.

    ``raise`` clauses raise :class:`ChaosFault`; ``wedge`` sleeps in place;
    ``sigterm`` delivers SIGTERM to this process. Inert (nothing armed):
    returns False at the cost of one attribute read.
    """
    st = _STATE
    if st is None:
        st = _resolve()
    if not st:
        return False
    if point not in KNOWN_POINTS:
        raise ValueError(f"unknown chaos point {point!r}")
    with _LOCK:
        seen = st.counts.get(point, 0)
        st.counts[point] = seen + 1
        idx = seen if index is None else int(index)
        fired = [
            (i, c) for i, c in enumerate(st.clauses)
            if c.point == point
            and c.matches(idx, st.attempt, st.rngs.get(i), st.rank)
        ]
        # delay jitter is drawn under the lock so concurrent fire()s keep
        # a given seed replaying one deterministic schedule
        delays = {
            i: st.rngs[i].uniform(0.0, c.sleep_s)
            for i, c in fired if c.action == "delay"
        }
    poison = False
    for i, c in fired:
        if c.action == "poison":
            poison = True
        elif c.action == "raise":
            raise ChaosFault(point, idx)
        elif c.action == "sigterm":
            print(f"[chaos] SIGTERM at {point} index {idx}", flush=True)
            os.kill(os.getpid(), signal.SIGTERM)
        elif c.action == "delay":
            print(
                f"[chaos] delaying at {point} index {idx} for "
                f"{delays[i]:.3f}s (injected straggler)",
                flush=True,
            )
            time.sleep(delays[i])
        elif c.action == "wedge":
            print(
                f"[chaos] wedging at {point} index {idx} for {c.sleep_s}s "
                "(simulated hung dispatch)",
                flush=True,
            )
            time.sleep(c.sleep_s)
    return poison


# --- poison helpers (host-side non-finite injection) ---


def poison_array(arr):
    """Copy of ``arr`` with its first element set to NaN (float arrays) —
    the deterministic host-side poison a ``grads=poison@K`` clause asks the
    batch-owning loop to apply to that step's inputs. Non-float arrays come
    back unchanged (labels/masks of integer dtype cannot carry a NaN)."""
    import numpy as np

    a = np.array(arr, copy=True)
    if a.dtype.kind != "f" or a.size == 0:
        return a
    a.reshape(-1)[0] = np.nan
    return a


def poison_pytree(tree):
    """``poison_array`` over every float leaf of a pytree (dict batches).

    Hand-rolled recursion over the container types host batches actually
    use (dict/list/tuple) instead of ``jax.tree.map``: this module is
    jax-free by contract (``analysis.lint``'s ``jax-free-module`` rule —
    a wedged lease can hang any jax call, and chaos must keep firing in
    processes that never dial a backend). Exotic pytree nodes would need
    jax and are not host-batch material."""
    if isinstance(tree, dict):
        return {k: poison_pytree(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(poison_pytree(v) for v in tree)
    return poison_array(tree)
