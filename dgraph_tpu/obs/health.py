"""Structured run/probe health diagnostics.

BENCH_r05.json was ``value: null`` after seven wedged-lease probes, and the
only evidence was free-text stderr.  :class:`RunHealth` is the structured
replacement: one JSON-able record accumulating probe attempts (with
wall-times and outcomes), a backend/topology snapshot, and a wedge
classification — embedded in bench.py's output on every exit path and
written by the experiment CLIs at startup, so a dead run is diagnosable
from its artifact alone.

Wedge taxonomy (``classify_wedge``):

- ``none``            — no error.
- ``init_wedge``      — backend init probes HANG (the wedged-lease
                        signature: PJRT dials a dead tunnel forever).
- ``init_failure``    — probes fail fast with an error (bad platform,
                        missing plugin) — recoverable by config, not time.
- ``dispatch_wedge``  — backend came up but a device op hung (lease wedged
                        after init; the r1/r2 probe-then-hang pattern).
- ``backend_lost``    — the backend is not the one the run needs: it
                        initialized then disappeared (child lost its lease
                        between probe and run) or came up on the wrong
                        platform (the silent CPU-fallback signature). Both
                        exit fail-fast and are retried by respawn.
- ``watchdog_timeout``— the run's own deadline fired mid-stage.
- ``interrupted``     — an outer signal (timeout wrapper, ^C) ended it.
- ``stage_failure``   — device work ran but a stage raised.
- ``unknown``         — anything else; the error text is still recorded.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

SCHEMA_VERSION = 1

WEDGE_KINDS = (
    "none",
    "init_wedge",
    "init_failure",
    "dispatch_wedge",
    "backend_lost",
    "watchdog_timeout",
    "interrupted",
    "stage_failure",
    "unknown",
)

# env prefixes worth snapshotting (flags that change behavior; no secrets)
_ENV_PREFIXES = ("JAX_", "DGRAPH_", "XLA_FLAGS", "TPU_")


def classify_wedge(error: Optional[str], probes: Optional[list] = None) -> str:
    """Map an exit-path error string + probe history to the taxonomy."""
    if not error:
        return "none"
    e = error.lower()
    probes = probes or []
    hung_probes = any(p.get("outcome") == "hang" for p in probes)
    # FIRST: the literal phrase bench's _emit_json_and_exit produces for a
    # stage exception ("gcn stage failed: <arbitrary exception text>").
    # The interpolated text can contain any of the substrings the generic
    # scans below look for ("hung", "interrupt", ...), and a stage crash
    # must never be misread as a lease wedge.
    if "stage failed" in e:
        return "stage_failure"
    if "watchdog" in e and "past its own watchdog" not in e:
        return "watchdog_timeout"
    if "never initialized" in e or "backend init failed" in e:
        return "init_wedge" if hung_probes else "init_failure"
    # platform-mismatch must be checked BEFORE the substring-'wedge' scan:
    # bench's "backend is 'cpu', need 'tpu' (... wedged lease?)" is a
    # fail-fast config problem, and calling it a wedge would tell the
    # operator to wait for a recovery that can never come
    if "backend is" in e or ("backend" in e and "lost" in e):
        return "backend_lost"
    if "hung" in e or "wedge" in e:
        return "dispatch_wedge"
    if "signal" in e or "interrupt" in e:
        return "interrupted"
    return "unknown"


def _active_trace_id() -> Optional[str]:
    """The ambient span-trace id (:mod:`dgraph_tpu.obs.spans`), so health
    records are joinable against span/step JSONL across a restart chain.
    Looked up via sys.modules — never imported — for the same reason as
    the chaos field: bench's supervisor loads this file standalone (by
    path, registering the spans twin as ``_dgraph_obs_spans``), and that
    load must never trigger the package ``__init__``'s jax import. The
    env var is the fallback for children that inherit a trace without
    ever importing the tracer."""
    import sys

    for name in ("dgraph_tpu.obs.spans", "_dgraph_obs_spans"):
        mod = sys.modules.get(name)
        if mod is not None:
            try:
                return mod.current_trace_id()
            except Exception:  # diagnostics must never break the run
                return None
    return os.environ.get("DGRAPH_TRACE_ID") or None


_GIT_REV: Optional[str] = None


def git_rev() -> str:
    """The current ``git rev-parse --short HEAD`` of the repo this file
    lives in, or ``"unknown"`` (no git, no .git dir, detached tarball —
    never an exception). Cached per process; stamped into every
    :class:`RunHealth` record and bench round JSON so a perf artifact is
    attributable to a commit (the ledger keys on it; any bisect wants
    it)."""
    global _GIT_REV
    if _GIT_REV is None:
        import subprocess

        try:
            p = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            rev = (p.stdout or "").strip()
            _GIT_REV = rev if p.returncode == 0 and rev else "unknown"
        except Exception:
            _GIT_REV = "unknown"
    return _GIT_REV


def _host_snapshot() -> dict:
    import platform
    import socket

    return {
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "platform": platform.platform(),
        "python": platform.python_version(),
    }


def _env_snapshot() -> dict:
    out = {}
    for k, v in os.environ.items():
        if any(k.startswith(p) for p in _ENV_PREFIXES):
            out[k] = v
    # presence only: the value is a pool of internal tunnel IPs
    out["PALLAS_AXON_POOL_IPS_set"] = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
    # the ACTIVE fault-injection spec, not just the env var: chaos can be
    # armed programmatically, and a perf artifact produced under injected
    # faults must be identifiable from its health record alone. Looked up
    # via sys.modules, NOT imported: bench.py's supervisor loads this file
    # standalone precisely so it never triggers the package __init__'s jax
    # import, and that must stay true (the env var is the fallback there).
    import sys

    chaos_mod = sys.modules.get("dgraph_tpu.chaos")
    try:
        out["chaos"] = (
            chaos_mod.active_spec() if chaos_mod is not None
            else (os.environ.get("DGRAPH_CHAOS") or None)
        )
    except Exception:  # never let diagnostics break the diagnosed run
        out["chaos"] = None
    return out


@dataclasses.dataclass
class RunHealth:
    """Accumulating health record for one run component (supervisor,
    bench child, or an experiment CLI). All fields JSON-serializable."""

    component: str
    started_at: str
    host: dict
    env: dict
    probes: list = dataclasses.field(default_factory=list)
    # structured lifecycle events (membership rank_lost/membership_changed,
    # shrink adoption, ...) — additive to schema 1, readers ignore it
    events: list = dataclasses.field(default_factory=list)
    backend: Optional[dict] = None
    wedge: str = "none"
    error: Optional[str] = None
    wall_s: Optional[float] = None
    # the active span-trace id (obs.spans) when tracing is on — the join
    # key against supervise_lineage / span / step JSONL; None otherwise.
    # Additive to schema 1 (readers ignore unknown fields).
    trace_id: Optional[str] = None
    # the commit the record was produced at (git_rev(); "unknown" outside
    # a checkout) — the ledger's bisect key. Additive to schema 1.
    git_rev: Optional[str] = None
    schema: int = SCHEMA_VERSION
    _t0: float = dataclasses.field(default=0.0, repr=False)

    @classmethod
    def begin(cls, component: str) -> "RunHealth":
        return cls(
            component=component,
            started_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            host=_host_snapshot(),
            env=_env_snapshot(),
            trace_id=_active_trace_id(),
            git_rev=git_rev(),
            _t0=time.perf_counter(),
        )

    def record_probe(
        self, attempt: int, wall_s: float, outcome: str, detail: str = ""
    ) -> None:
        """outcome: 'ok' | 'error' | 'hang'."""
        self.probes.append(
            {
                "attempt": int(attempt),
                "wall_s": round(float(wall_s), 2),
                "outcome": outcome,
                "detail": detail[-500:],
            }
        )

    def record_event(self, rec: dict) -> None:
        """Append one structured lifecycle event (a ``.record()`` dict —
        membership's ``rank_lost``/``membership_changed``, shrink-to-fit
        adoption, ...) so the health artifact alone tells the recovery
        story. Bounded: after 200 events the oldest are dropped (a flapping
        member must not grow the record without bound)."""
        self.events.append(rec)
        if len(self.events) > 200:
            del self.events[: len(self.events) - 200]

    def snapshot_backend(self) -> Optional[dict]:
        """Best-effort jax backend/topology snapshot. Initializes the
        backend if it isn't already — only call where device work is about
        to happen anyway. Never raises; failure is itself recorded."""
        try:
            # the ONE sanctioned jax touch in this module: callers opt in
            # to a backend dial; module import and every other path stay
            # jax-free (bench's standalone loader depends on it)
            import jax  # lint: allow(jax-free-module)

            devs = jax.devices()
            self.backend = {
                "platform": jax.default_backend(),
                "jax_version": jax.__version__,
                "device_count": len(devs),
                "device_kinds": sorted({d.device_kind for d in devs}),
                "process_index": jax.process_index(),
                "process_count": jax.process_count(),
            }
        except Exception as e:  # a dead backend is exactly what we record
            self.backend = {"error": f"{type(e).__name__}: {e}"}
        return self.backend

    def finish(
        self, error: Optional[str] = None, wedge: Optional[str] = None
    ) -> dict:
        """Seal the record: stamp wall time, classify, return to_dict()."""
        self.error = error
        self.wedge = wedge if wedge is not None else classify_wedge(
            error, self.probes
        )
        self.wall_s = round(time.perf_counter() - self._t0, 1)
        return self.to_dict()

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("_t0")
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "RunHealth":
        known = {f.name for f in dataclasses.fields(cls)} - {"_t0"}
        return cls(**{k: v for k, v in d.items() if k in known})


def startup_record(component: str, *, snapshot_backend: bool = True) -> dict:
    """The one-line health record every experiment CLI writes on startup
    (kind="run_health"): host/env/topology context for the JSONL that
    follows. ``snapshot_backend=False`` keeps host-only flows (offline
    plan builds) from ever dialing the accelerator."""
    h = RunHealth.begin(component)
    if snapshot_backend:
        h.snapshot_backend()
    return {"kind": "run_health", **h.finish()}
