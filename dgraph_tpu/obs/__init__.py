"""Unified observability layer.

Three pillars, one import:

- :mod:`dgraph_tpu.obs.footprint` — static comm-traffic accounting: walk an
  :class:`~dgraph_tpu.plan.EdgePlan` and report per-collective bytes, shard
  imbalance, and an analytic ICI/HBM roofline before a single step runs.
  Also a CLI: ``python -m dgraph_tpu.obs.footprint``.
- :mod:`dgraph_tpu.obs.metrics` — runtime metrics: a host-side
  :class:`Metrics` registry (counters/gauges/histograms) and the
  :class:`StepMetrics` aux-pytree the jitted train step threads out
  (loss, grad-norm, mask counts), emitted as one structured JSONL record
  per step through :class:`~dgraph_tpu.utils.logging.ExperimentLog`.
- :mod:`dgraph_tpu.obs.health` — run/probe health diagnostics: the
  structured :class:`RunHealth` record (probe attempts, wall-times, backend
  state, wedge classification, topology snapshot) bench.py and the
  experiment CLIs embed in their artifacts, so a null benchmark is
  diagnosable from the JSON alone.
"""

from dgraph_tpu.obs.footprint import plan_footprint
from dgraph_tpu.obs.health import RunHealth, classify_wedge, startup_record
from dgraph_tpu.obs.metrics import Metrics, StepMetrics, default_registry

__all__ = [
    "plan_footprint",
    "RunHealth",
    "classify_wedge",
    "startup_record",
    "Metrics",
    "StepMetrics",
    "default_registry",
]
