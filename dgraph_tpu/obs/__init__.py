"""Unified observability layer.

Three pillars, one import:

- :mod:`dgraph_tpu.obs.footprint` — static comm-traffic accounting: walk an
  :class:`~dgraph_tpu.plan.EdgePlan` and report per-collective bytes, shard
  imbalance, and an analytic ICI/HBM roofline before a single step runs.
  Also a CLI: ``python -m dgraph_tpu.obs.footprint``.
- :mod:`dgraph_tpu.obs.metrics` — runtime metrics: a host-side
  :class:`Metrics` registry (counters/gauges/histograms) and the
  :class:`StepMetrics` aux-pytree the jitted train step threads out
  (loss, grad-norm, mask counts), emitted as one structured JSONL record
  per step through :class:`~dgraph_tpu.utils.logging.ExperimentLog`.
- :mod:`dgraph_tpu.obs.health` — run/probe health diagnostics: the
  structured :class:`RunHealth` record (probe attempts, wall-times, backend
  state, wedge classification, topology snapshot) bench.py and the
  experiment CLIs embed in their artifacts, so a null benchmark is
  diagnosable from the JSON alone.
- :mod:`dgraph_tpu.obs.spans` — the flight recorder: hierarchical
  host-side spans with trace/span/parent ids shared across train, serve,
  and bench (and across process restarts), JSONL records, and a Perfetto
  (Chrome trace) exporter. One attribute read when disabled; never inside
  traced code (lint-enforced).
- :mod:`dgraph_tpu.obs.attribution` — CPU scan-delta step-time
  attribution: per-phase ``{interior, exchange, optimizer, other}``
  timing per halo lowering on the virtual-CPU backend — bench.py's
  non-null timing tier for wedged rounds.
"""

# spans is deliberately NOT imported here: `python -m dgraph_tpu.obs.spans`
# (the perfetto-export/selftest CLI) would otherwise execute the module
# twice — once via this package import, once as __main__ — leaving two
# default tracers in one process. Use `from dgraph_tpu.obs import spans`.
from dgraph_tpu.obs.footprint import plan_footprint
from dgraph_tpu.obs.health import RunHealth, classify_wedge, startup_record
from dgraph_tpu.obs.metrics import Metrics, StepMetrics, default_registry

__all__ = [
    "plan_footprint",
    "RunHealth",
    "classify_wedge",
    "startup_record",
    "Metrics",
    "StepMetrics",
    "default_registry",
]
