"""Static comm-traffic accounting for :class:`~dgraph_tpu.plan.EdgePlan`.

The plan is fully static, so every byte a training step will move over ICI
— halo send/recv per shard, all_to_all operand volume, the gradient-sync
psum — is computable on the host before any device work, the way "The Big
Send-off" / array-redistribution work (PAPERS.md) plans collectives from
traffic tables. :func:`plan_footprint` walks a plan (plus feature width and
dtype) and reports:

- per-collective bytes: the useful (masked) halo payload, the padded
  operand each lowering actually carries (``all_to_all`` moves all
  ``W*S_pad`` rows per shard, live or not; ppermute rounds move
  ``len(halo_deltas)*S_pad``), and the remote (cross-chip) fraction;
- per-shard send/recv row counts and max/mean imbalance — the number that
  says whether one hub-heavy shard serializes the exchange;
- an analytic roofline: time lower bounds for the ICI wire and the HBM
  streams each collective implies, and which resource binds.

Byte conventions (pinned by tests/test_obs.py against the lowered HLO):

- ``operand_bytes_per_shard`` is the size of the array handed to the
  collective on ONE shard — what a Perfetto trace or HLO dump shows.
- ``ici_bytes_per_shard`` counts only rows that leave the chip: the
  all_to_all self-block stays local, so it is ``(W-1)/W`` of the operand;
  every ppermute round is fully remote.
- "real"/"useful" bytes count mask-live rows only (padding excluded).

CLI::

    python -m dgraph_tpu.obs.footprint --nodes 4096 --edges 16384 --world 8
    python -m dgraph_tpu.obs.footprint --arxiv          # the bench shape

prints the same report as JSON.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

# v5e chip ceilings (bench.py uses the same HBM number). ICI: aggregate
# per-chip interconnect bandwidth; one direction of the 4-link torus is
# half, but collectives drive links bidirectionally, so the aggregate is
# the roofline's optimistic bound.
V5E_PEAK_HBM_GBPS = 819.0
V5E_ICI_GBPS = 200.0


def dtype_bytes(dtype) -> int:
    """Itemsize for numpy dtypes, jax dtypes, and the bf16 family names
    numpy doesn't know. (Canonical implementation lives in
    :func:`dgraph_tpu.plan.dtype_nbytes` — the base layer — so plan-side
    byte accounting never imports upward into obs.)"""
    from dgraph_tpu.plan import dtype_nbytes

    return dtype_nbytes(dtype)


def _imbalance(per_shard: np.ndarray) -> dict:
    per_shard = np.asarray(per_shard, dtype=np.float64)
    mean = float(per_shard.mean()) if per_shard.size else 0.0
    return {
        "max": float(per_shard.max(initial=0.0)),
        "mean": mean,
        "max_over_mean": float(per_shard.max(initial=0.0) / mean) if mean else 1.0,
    }


def plan_footprint(
    plan,
    dtype="float32",
    feat_dim: int = 128,
    *,
    param_count: int = 0,
    ici_gbps: float = V5E_ICI_GBPS,
    hbm_gbps: float = V5E_PEAK_HBM_GBPS,
) -> dict:
    """Static byte/imbalance/roofline report for one plan at one feature
    width. Pure host numpy — never touches a device. JSON-serializable.

    Args:
      plan: an :class:`~dgraph_tpu.plan.EdgePlan` (numpy or device leaves).
      dtype: activation dtype of the exchanged features.
      feat_dim: feature width F the exchange will run at.
      param_count: when > 0, also accounts the per-step gradient-sync psum
        (ring all-reduce volume) at f32.
    """
    from dgraph_tpu.plan import (
        interior_boundary_edge_counts,
        plan_memory_usage,
        resolve_halo_impl,
    )

    from dgraph_tpu.wire.spec import get_format, resolve_wire_format

    W, S = plan.world_size, plan.halo.s_pad
    b = dtype_bytes(dtype)
    F = int(feat_dim)
    row_bytes = F * b
    # wire rows are priced at the RESOLVED codec's encoded width (the
    # same ladder the runtime walks: env pin > tuned record > plan-
    # attached > fp32 identity); HBM-side quantities stay at the
    # activation row_bytes — only the collective operand is encoded.
    # With the fp32 identity wire_row_bytes == row_bytes and every
    # number below reproduces the pre-codec report exactly.
    wf_name, wf_source = resolve_wire_format(
        W, tuple(plan.halo_deltas),
        plan_format=getattr(plan, "wire_format", "fp32"),
    )
    wire_fmt = get_format(wf_name)
    wire_row_bytes = wire_fmt.wire_row_bytes(F, b)

    send_mask = np.asarray(plan.halo.send_mask) > 0  # [W, W, S]
    real_counts = send_mask.sum(axis=2).astype(np.int64)  # [sender, needer]
    send_rows = real_counts.sum(axis=1)  # [W]
    recv_rows = real_counts.sum(axis=0)  # [W]
    real_rows = int(real_counts.sum())
    n_deltas = len(plan.halo_deltas)
    # mirror the runtime's lowering choice (comm.collectives.
    # resolve_plan_impl): env pin > adopted tuning record > heuristic —
    # the report must account the lowering the run actually executes,
    # whoever chose it (incl. 'overlap' when the plan carries its split)
    overlap_available = getattr(plan, "overlap", None) is not None
    schedule = getattr(plan, "halo_schedule", None)
    impl, impl_source = resolve_halo_impl(
        W, plan.halo_deltas, overlap_available=overlap_available,
        sched_available=schedule is not None,
        pair_rows=getattr(plan, "halo_pair_rows", ()),
    )
    edge_split = interior_boundary_edge_counts(plan)
    # compiled schedule (dgraph_tpu.sched): per-round padded operand rows
    # C_k; every round is a ppermute, fully remote. () when unattached.
    sched_rows = schedule.round_rows() if schedule is not None else ()
    sched_wire = sum(sched_rows) * wire_row_bytes

    # one halo_exchange (the gather's comm leg); halo_scatter_sum (the
    # scatter's reverse leg / the exchange's transpose) moves the same.
    a2a_operand = W * S * wire_row_bytes  # [W, S, F_wire] per shard
    a2a_ici = (W - 1) * S * wire_row_bytes  # self block never leaves chip
    pp_operand = n_deltas * S * wire_row_bytes  # one [S, F_wire] per delta
    # the overlap lowering sends the same boundary-only round payloads as
    # ppermute — its win is SCHEDULING (exposed time), not wire bytes.
    # pallas_p2p moves the same boundary-only tiles as one-sided puts:
    # its win is the transport (device-initiated per-tile DMA, one kernel
    # launch, no exchange buffer staged through HBM), not wire bytes.
    wire_per_shard = {
        "all_to_all": a2a_ici, "ppermute": pp_operand, "overlap": pp_operand,
        "pallas_p2p": pp_operand, "sched": sched_wire,
    }
    chosen_wire = wire_per_shard.get(impl, 0)
    real_bytes = real_rows * wire_row_bytes
    # analytic-min HBM streams per shard per exchange, LOWERING-AWARE:
    # the [W*S, F] halo output buffer is written either way, but only the
    # blocks the chosen lowering actually sends are gathered and read
    # (all_to_all pads every peer; ppermute/overlap touch live deltas
    # only; 'none' never gathers a send buffer at all).
    sent_blocks = {
        "all_to_all": W, "ppermute": n_deltas, "overlap": n_deltas,
        "pallas_p2p": n_deltas, "sched": len(sched_rows),
    }.get(impl, 0)
    # pallas_p2p is billed the same (2*sent + W) streams as the rounds it
    # replaces: only the FORWARD leg's in-VMEM mask fusion can skip the
    # masked block's HBM round trip, and only when the stack fits the
    # VMEM budget — the reverse leg always pre-stages its tiles — so the
    # conservative figure is what the headline (and the tuner) must use;
    # the fused-forward saving is reported separately below.
    hbm_per_shard = (2 * sent_blocks + W) * S * row_bytes

    def _roofline(ici_bytes: float, hbm_bytes: float) -> dict:
        t_ici = ici_bytes / (ici_gbps * 1e3) if ici_gbps else 0.0  # us
        t_hbm = hbm_bytes / (hbm_gbps * 1e3) if hbm_gbps else 0.0
        return {
            "ici_us": round(t_ici, 3),
            "hbm_us": round(t_hbm, 3),
            "bound": "ici" if t_ici >= t_hbm else "hbm",
        }

    operand_by_impl = {
        "all_to_all": a2a_operand, "ppermute": pp_operand,
        "overlap": pp_operand, "pallas_p2p": pp_operand,
        "sched": sched_wire,
    }
    exchange = {
        "impl": impl,
        "impl_source": impl_source,
        "wire_format": wf_name,
        "wire_format_source": wf_source,
        "wire_row_bytes": wire_row_bytes,
        "compression_ratio": round(wire_fmt.compression_ratio(F, b), 4),
        "operand_bytes_per_shard": operand_by_impl.get(impl, 0),
        "a2a_operand_bytes_per_shard": a2a_operand,
        "ici_bytes_per_shard": chosen_wire,
        "ici_bytes_total": chosen_wire * W,
        "real_bytes_total": real_bytes,
        # same ratio plan_efficiency reports as halo_wire_fill_* — derived
        # here from send_mask instead of layout.halo_counts because
        # footprint deliberately needs only the PLAN (cache-loaded plans
        # carry no EdgePlanLayout); equivalence is pinned by test_obs.py
        "wire_efficiency": round(real_bytes / (chosen_wire * W), 4)
        if chosen_wire
        else 1.0,
        "hbm_bytes_per_shard": hbm_per_shard,
        "roofline": _roofline(chosen_wire, hbm_per_shard),
    }
    if n_deltas:
        # overlapped-schedule pricing (arxiv 2112.01075 / 2504.18658
        # framing): the exchange runs as n_deltas boundary rounds with the
        # interior aggregation interleaved, so the EXPOSED cost per round
        # is max(round comm, its interior compute share), not their sum.
        # Interior compute is modeled as the 3 HBM streams of the
        # interior-edge rows one exchange leg drives (take write, read,
        # reduce write — the per-leg half of search.py's 6-stream model).
        int_rows_max = max(edge_split["interior_per_shard"] or [0])
        round_comm_us = (
            (S * wire_row_bytes) / (ici_gbps * 1e3) if ici_gbps else 0.0
        )
        interior_us = (
            3 * int_rows_max * row_bytes / (hbm_gbps * 1e3) if hbm_gbps else 0.0
        )
        per_round_int = interior_us / n_deltas
        exposed = n_deltas * max(round_comm_us, per_round_int)
        serial = n_deltas * round_comm_us + interior_us
        exchange["overlap"] = {
            "rounds": n_deltas,
            "round_comm_us": round(round_comm_us, 3),
            "interior_compute_us": round(interior_us, 3),
            "exposed_us": round(exposed, 3),
            "serial_us": round(serial, 3),
            "hidden_us": round(serial - exposed, 3),
        }
        # pallas_p2p per-tile schedule (ISSUE 11 pricing model): each live
        # delta is one device-initiated put whose DMA overlaps the next
        # tile's stage+mask (and, at the model layer, the interior-edge
        # aggregation the split routing runs while the puts fly), so the
        # exposed cost per tile is max(tile DMA, its interior compute
        # share) — boundary-only operand bytes, one kernel launch total.
        tile_stage_us = (
            S * row_bytes / (hbm_gbps * 1e3) if hbm_gbps else 0.0
        )
        p2p_exposed = n_deltas * max(round_comm_us, per_round_int)
        exchange["pallas_p2p"] = {
            "tiles": n_deltas,
            "tile_bytes": S * wire_row_bytes,
            "tile_dma_us": round(round_comm_us, 3),
            "tile_stage_us": round(tile_stage_us, 3),
            "interior_tile_us": round(per_round_int, 3),
            "exposed_us": round(p2p_exposed, 3),
            "serial_us": round(serial, 3),
            "hidden_us": round(serial - p2p_exposed, 3),
            # FORWARD-leg-only figure, valid when the send stack fits the
            # kernel's VMEM staging budget (the mask multiply then rides
            # VMEM and the masked block never round-trips HBM); the
            # reverse leg always pays the full (2*n + W) streams, so the
            # headline hbm_bytes_per_shard above stays conservative
            "fwd_fused_hbm_bytes_per_shard": (n_deltas + W) * S * row_bytes,
        }
    if sched_rows:
        # compiled-schedule pricing: each round k ships a [C_k, F] operand
        # (every rank, fully remote — ppermute), so the wire is priced
        # per-round at the COMPILED heights, not at s_pad. Exposed time
        # under the same interior-absorption model as the overlap rounds:
        # the interior compute splits across the schedule's rounds and
        # each round exposes max(its wire time, its compute share). The
        # per-round byte list is what the trace/HLO auditors pin the
        # lowered CollectivePermute operands against, byte-exact.
        int_rows_max = max(edge_split["interior_per_shard"] or [0])
        interior_us = (
            3 * int_rows_max * row_bytes / (hbm_gbps * 1e3) if hbm_gbps
            else 0.0
        )
        round_bytes = [int(c) * wire_row_bytes for c in sched_rows]
        round_us = [
            (rb / (ici_gbps * 1e3) if ici_gbps else 0.0)
            for rb in round_bytes
        ]
        per_round_int = interior_us / len(sched_rows)
        sched_exposed = sum(max(u, per_round_int) for u in round_us)
        sched_serial = sum(round_us) + interior_us
        exchange["sched"] = {
            "schedule_id": schedule.schedule_id,
            "rounds": len(sched_rows),
            "transfers": schedule.num_transfers,
            "round_rows": [int(c) for c in sched_rows],
            "round_bytes_per_shard": round_bytes,
            "operand_bytes_per_shard": sched_wire,
            "interior_compute_us": round(interior_us, 3),
            "exposed_us": round(sched_exposed, 3),
            "serial_us": round(sched_serial, 3),
            "hidden_us": round(sched_serial - sched_exposed, 3),
        }

    psum = None
    if param_count:
        # ring all-reduce: each member sends 2*(W-1)/W of the payload
        # (reduce-scatter + all-gather), grads sync at f32
        grad_bytes = int(param_count) * 4
        per_shard = int(2 * grad_bytes * (W - 1) / max(W, 1))
        psum = {
            "param_count": int(param_count),
            "payload_bytes": grad_bytes,
            "ici_bytes_per_shard": per_shard,
            "ici_bytes_total": per_shard * W,
            "roofline": _roofline(per_shard, 2 * grad_bytes),
        }

    num_edges = np.asarray(plan.num_edges, dtype=np.int64)
    return {
        "world_size": W,
        "s_pad": int(S),
        "e_pad": int(plan.e_pad),
        "n_src_pad": int(plan.n_src_pad),
        "n_dst_pad": int(plan.n_dst_pad),
        "halo_side": plan.halo_side,
        "num_halo_deltas": n_deltas,
        "feat_dim": F,
        "dtype": getattr(dtype, "__name__", None) or str(dtype),
        "dtype_bytes": b,
        "halo": {
            "real_rows_total": real_rows,
            "real_bytes_total": real_bytes,
            "per_shard_send_rows": [int(v) for v in send_rows],
            "per_shard_recv_rows": [int(v) for v in recv_rows],
            "per_shard_send_bytes": [
                int(v) * wire_row_bytes for v in send_rows
            ],
            "per_shard_recv_bytes": [
                int(v) * wire_row_bytes for v in recv_rows
            ],
            "wire_bytes_per_shard": wire_per_shard,
            "active_peer_pairs": int((real_counts > 0).sum()),
        },
        "collectives": {
            "halo_exchange": exchange,
            # the scatter's remote leg is the exact transpose: same shapes
            "halo_scatter_sum": exchange,
            "psum_grad_sync": psum,
        },
        "imbalance": {
            "halo_send_rows": _imbalance(send_rows),
            "halo_recv_rows": _imbalance(recv_rows),
            "edges": _imbalance(num_edges),
        },
        "local_streams": {
            "edge_tensor_bytes": int(plan.e_pad) * row_bytes,
            "vertex_tensor_bytes": int(plan.n_src_pad) * row_bytes,
            "halo_buffer_bytes": W * S * row_bytes,
        },
        # interior/boundary live-edge split: the boundary fraction bounds
        # the collective payload, the interior fraction bounds how much
        # compute the overlap lowering can hide it behind
        "edge_split": edge_split,
        "overlap_available": overlap_available,
        # runtime-buffer accounting at the ACTUAL activation dtype (the
        # plan_memory_usage satellite: a bf16 run must not be billed f32)
        "plan_memory": plan_memory_usage(plan, F, dtype=dtype),
        "roofline_constants": {"ici_gbps": ici_gbps, "hbm_gbps": hbm_gbps},
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Config:
    """Static comm-footprint report for a (synthetic or cached) plan."""

    nodes: int = 4096
    edges: int = 16384  # directed edges before symmetrization
    symmetrize: bool = True
    arxiv: bool = False  # override nodes/edges with the bench's arxiv shape
    world: int = 8
    feat_dim: int = 128
    dtype: str = "float32"
    partition: str = "block"  # any dgraph_tpu.partition method
    pad_multiple: int = 128
    overlap: bool = False  # build the interior/boundary split and price
    # the overlapped schedule (False still follows an env/record pin)
    seed: int = 0
    param_count: int = 0  # >0: also account the grad-sync psum
    indent: int = 2  # 0 = one JSON line


def main(cfg: Config) -> dict:
    from dgraph_tpu import partition as pt
    from dgraph_tpu.plan import build_edge_plan

    from dgraph_tpu.data.synthetic import ARXIV_EDGES, ARXIV_NODES, random_edges

    if cfg.arxiv:
        cfg.nodes, cfg.edges = ARXIV_NODES, ARXIV_EDGES
    edge_index = random_edges(cfg.nodes, cfg.edges, cfg.seed, cfg.symmetrize)
    new_edges, ren = pt.partition_graph(
        edge_index, cfg.nodes, cfg.world, method=cfg.partition, seed=cfg.seed
    )
    plan, _ = build_edge_plan(
        new_edges, ren.partition, world_size=cfg.world,
        pad_multiple=cfg.pad_multiple, overlap=cfg.overlap or None,
    )
    report = plan_footprint(
        plan, cfg.dtype, cfg.feat_dim, param_count=cfg.param_count
    )
    print(json.dumps(report, indent=cfg.indent or None))
    return report


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
