"""Longitudinal perf-trajectory ledger: one append-only JSONL store for
every record the system emits.

The consumption side of observability. PRs built the emitters (bench
round JSONs, ``tune_<sig>.json`` TuningRecords, ``serve_health``,
``supervise_lineage``, the four wedged-round analysis tiers) — but each
artifact was write-only, and the trajectory visible to a reviewer was
empty. This module ingests them all and *normalizes* them into one
versioned schema (:data:`LEDGER_SCHEMA_VERSION`) keyed by (workload
signature, record kind, halo lowering, git rev, wall time), appended to
``ledger.jsonl`` under the plan-cache dir so the artifacts that must
travel together keep living together.

Contracts:

- **jax-free + stdlib-only** (``analysis.lint``'s ``jax-free-module``
  rule): the ledger must be writable from bench's wedge-surviving
  supervisor, which loads this file standalone by path (as
  ``_dgraph_obs_ledger``) and must never trigger the package
  ``__init__``'s jax import. Nothing here may import another dgraph_tpu
  module.
- **Durable appends**: every write flows through
  :func:`atomic_append_jsonl` (append + flush + fsync — the append-side
  sibling of ``plan_shards.atomic_write_json``'s fsync+rename), which
  the host durability auditor (``analysis.host``) recognizes as a
  blessed writer; a bare ``open(ledger_path(...), 'a')`` anywhere in
  scope goes RED.
- **Never a crash**: unrecognized or corrupt payloads become a
  structured skip-with-reason, and wedge-era probe stubs (BENCH_r05's
  ``parsed: null`` shape) ingest as ``kind="probe_wedge"`` — the wedge
  history is part of the trajectory, not noise to drop.

Ingestion at the emission sites is gated by ``DGRAPH_LEDGER_DIR``
(:func:`resolve_ledger_dir`): unset means "on with the default dir" for
bench and "off" everywhere else; a falsy value (``0``/``off``/``none``)
disables it everywhere; a path enables it everywhere.

CLI::

    python -m dgraph_tpu.obs.ledger --backfill /root/repo   # seed from
                                                # BENCH_*/MULTICHIP_*/BASELINE
    python -m dgraph_tpu.obs.ledger --dir cache/plans       # summary
    python -m dgraph_tpu.obs.ledger --selftest true
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import re
import time
from typing import Optional

# Bump when an ENTRY field changes meaning or is removed; additive fields
# do not bump (readers ignore unknown keys). The version every entry
# carries in its "schema" field.
LEDGER_SCHEMA_VERSION = 1

# The serve_health writer (dgraph_tpu/serve/health.py) stamps THIS
# constant into its records and the normalizer below validates against
# it — one constant, imported by both sides, pinned by test, so the two
# schemas cannot drift apart silently.
SERVE_HEALTH_SCHEMA_VERSION = 1

ENV_LEDGER_DIR = "DGRAPH_LEDGER_DIR"
_DISABLE_VALUES = ("", "0", "off", "none", "disabled", "false")

LEDGER_FILENAME = "ledger.jsonl"
# the plan-cache dir (tune.record.default_record_dir's default) — the
# literal is duplicated here because this module may not import
# tune.record; tests/test_ledger.py pins the two equal
DEFAULT_LEDGER_DIR = os.path.join("cache", "plans")

# every kind a normalized entry may carry (documented surface; new kinds
# are additive)
ENTRY_KINDS = (
    "bench_round",       # bench.py round JSON (value/vs_baseline/roofline)
    "probe_wedge",       # wedge-era stub: a round that never reached a chip
    "multichip_dryrun",  # MULTICHIP_r*.json per-family dryrun table
    "schedule_drift",    # fallback tier 1: traced-vs-footprint bytes
    "cpu_scan_delta",    # fallback tier 2: per-phase CPU step timing
    "hlo_drift",         # fallback tier 3: lowered-vs-footprint bytes
    "spmd_drift",        # fallback tier 4: cross-rank schedule identity
    "tune_record",       # tune_<sig>.json TuningRecord
    "sched_compile",     # compiled halo schedule: id, rounds, priced bytes
    "wire_compile",      # resolved wire format: name, priced operand bytes
    "serve_health",      # serving latency/recompile/tenant record
    "supervise_lineage",        # single-child restart lineage
    "supervise_group_lineage",  # multi-rank group lineage
    "grow_transition",   # adopted W -> W+k elastic expansion (train.grow)
    "run_health",        # standalone CLI startup/exit health record
    "reference_note",    # BASELINE.json-style reference metadata
)

# the four wedged-round analysis tiers, in bench's attach order — the
# sentinel's dropped-tier check compares rounds against this set
TIER_KINDS = ("schedule_drift", "cpu_scan_delta", "hlo_drift", "spmd_drift")

# MULTICHIP_r*.json tails carry per-family dryrun lines; step_ms appears
# when the dryrun timed (same pattern obs.attribution parses)
_DRYRUN_RE = re.compile(r"dryrun (\S+) OK:(.*)")
_STEP_MS_RE = re.compile(r"step_ms=([0-9.]+)")


# ---------------------------------------------------------------------------
# knob + paths + durable append
# ---------------------------------------------------------------------------


def resolve_ledger_dir(default_on: bool = False) -> Optional[str]:
    """The active ledger directory, or None when ingestion is off.

    ``DGRAPH_LEDGER_DIR`` set to a path wins; set to a falsy value
    (``0``/``off``/``none``/...) disables ingestion everywhere; unset
    falls back to :data:`DEFAULT_LEDGER_DIR` when the call site opted in
    with ``default_on=True`` (bench does; tune/serve/supervise don't).
    """
    raw = os.environ.get(ENV_LEDGER_DIR)
    if raw is None:
        return DEFAULT_LEDGER_DIR if default_on else None
    if raw.strip().lower() in _DISABLE_VALUES:
        return None
    return raw


def ledger_path(directory: str) -> str:
    """The one ledger file under a plan-cache dir."""
    return os.path.join(directory, LEDGER_FILENAME)


def atomic_append_jsonl(path: str, records: list) -> int:
    """Append ``records`` as JSONL with the durable-append discipline:
    one write, flushed and fsync'd before return, so a host crash can
    lose at most the trailing partial line (which readers skip with a
    reason) — never an earlier, already-acknowledged entry. The
    append-side sibling of ``plan_shards.atomic_write_json``; listed in
    ``analysis.host.ATOMIC_WRITERS`` as a blessed durable writer."""
    if not records:
        return 0
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = "".join(
        json.dumps(r, sort_keys=True, default=str) + "\n" for r in records
    )
    # self-healing append: a prior crash can leave a torn line with no
    # trailing newline — gluing onto it would corrupt THIS write too, so
    # terminate the fragment first (readers already skip it with a reason)
    try:
        with open(path, "rb") as fh:
            fh.seek(-1, os.SEEK_END)
            if fh.read(1) not in (b"\n", b""):
                payload = "\n" + payload
    except OSError:
        pass  # no file yet (or empty): nothing to heal
    with open(path, "a") as fh:
        fh.write(payload)
        fh.flush()
        os.fsync(fh.fileno())
    return len(records)


# ---------------------------------------------------------------------------
# normalized entries
# ---------------------------------------------------------------------------


def _skip(source: str, reason: str) -> dict:
    return {"source": source, "reason": reason}


def _num(v) -> Optional[float]:
    """A JSON-able finite number or None (NaN would poison baselines)."""
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)) and v == v:
        return v
    return None


def _entry(
    kind: str,
    metrics: dict,
    *,
    workload: str = "default",
    halo_impl: Optional[str] = None,
    git_rev: Optional[str] = None,
    recorded_at: Optional[str] = None,
    source: str = "",
    round_n: Optional[int] = None,
    meta: Optional[dict] = None,
) -> dict:
    """One normalized ledger entry. ``entry_id`` hashes the key fields +
    metrics so re-ingesting the same artifact (backfill is re-runnable)
    dedups instead of duplicating the trajectory."""
    clean = {k: _num(v) for k, v in metrics.items()}
    clean = {k: v for k, v in clean.items() if v is not None}
    e = {
        "schema": LEDGER_SCHEMA_VERSION,
        "kind": kind,
        "workload": workload or "default",
        "halo_impl": halo_impl,
        "git_rev": git_rev or "unknown",
        "recorded_at": recorded_at
        or time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "round": round_n,
        "source": source,
        "metrics": clean,
        "meta": meta or {},
    }
    key = json.dumps(
        [kind, e["workload"], halo_impl, e["git_rev"], recorded_at or "",
         source, round_n, clean],
        sort_keys=True,
    )
    e["entry_id"] = hashlib.sha1(key.encode()).hexdigest()[:12]
    return e


def _workload_tag(w) -> str:
    """Canonical workload string for the analysis tiers' workload dicts."""
    if not isinstance(w, dict):
        return str(w) if w else "default"
    parts = []
    for k in ("world_size", "nodes", "edges", "feat_dim", "hidden", "seed"):
        if k in w:
            parts.append(f"{k[0] if k != 'world_size' else 'ws'}{w[k]}")
    return "_".join(parts) or "default"


# ---------------------------------------------------------------------------
# per-kind normalizers — each returns (entries, skips)
# ---------------------------------------------------------------------------


def _norm_tier(obj: dict, source: str, round_n, git_rev) -> tuple:
    """schedule_drift / hlo_drift / spmd_drift: one entry per halo
    lowering from the ``train_step_by_impl`` table (the per-lowering
    bytes/identity numbers the sentinel's exact class gates)."""
    kind = obj["kind"]
    if obj.get("error") and "train_step_by_impl" not in obj:
        # bench attaches {"kind": ..., "error": "..."} when a tier's
        # subprocess failed — record the miss, don't fake numbers
        return [_entry(
            kind, {}, workload="default", source=source, round_n=round_n,
            git_rev=git_rev, meta={"error": str(obj["error"])[:300]},
        )], []
    wl = _workload_tag(obj.get("workload"))
    entries = []
    for impl, row in (obj.get("train_step_by_impl") or {}).items():
        if not isinstance(row, dict):
            continue
        metrics = {k: v for k, v in row.items()
                   if isinstance(v, (int, float, bool))}
        meta = {k: v for k, v in row.items() if k not in metrics}
        if "drift" in obj:
            metrics["drift"] = bool(obj["drift"])
        entries.append(_entry(
            kind, metrics, workload=wl, halo_impl=impl, source=source,
            round_n=round_n, git_rev=git_rev, meta=meta,
        ))
    if not entries:
        return [], [_skip(source, f"{kind} record carries no per-impl table")]
    return entries, []


def _norm_scan_delta(obj: dict, source: str, round_n, git_rev) -> tuple:
    """cpu_scan_delta (obs.attribution): per-impl phase timings, plus the
    folded multichip dryrun step_ms table when present."""
    wl = _workload_tag(obj.get("workload"))
    entries = []
    for impl, row in (obj.get("by_impl") or {}).items():
        if not isinstance(row, dict):
            continue
        metrics = {
            "full_ms": row.get("full_ms"),
            "exchange_only_ms": row.get("exchange_only_ms"),
            "exposed_exchange_ms": row.get("exposed_exchange_ms"),
        }
        for phase, v in (row.get("phases_ms") or {}).items():
            metrics[f"{phase}_ms"] = v
        entries.append(_entry(
            "cpu_scan_delta", metrics, workload=wl, halo_impl=impl,
            source=source, round_n=round_n, git_rev=git_rev,
        ))
    mc = obj.get("multichip_dryrun")
    if isinstance(mc, dict):
        fam = mc.get("step_ms_by_family") or {}
        metrics = {f"step_ms/{name}": v for name, v in fam.items()}
        if metrics:
            entries.append(_entry(
                "multichip_dryrun", metrics, workload=wl, source=source,
                round_n=round_n, git_rev=git_rev,
                meta={"folded_from": "cpu_scan_delta"},
            ))
    if not entries and obj.get("error"):
        entries.append(_entry(
            "cpu_scan_delta", {}, workload=wl, source=source,
            round_n=round_n, git_rev=git_rev,
            meta={"error": str(obj["error"])[:300]},
        ))
    if not entries:
        return [], [_skip(source, "cpu_scan_delta record has no by_impl")]
    return entries, []


def _norm_bench_round(obj: dict, source: str, round_n=None) -> tuple:
    """A bench.py round JSON (success OR structured failure): the primary
    metric + roofline context as one ``bench_round`` entry, then every
    attached fallback tier / lineage record as its own entries."""
    entries, skips = [], []
    rh = obj.get("run_health") or {}
    child = rh.get("child") or rh.get("supervisor") or {}
    git_rev = obj.get("git_rev") or child.get("git_rev")
    recorded = child.get("started_at") or obj.get("recorded")
    metrics = {
        "epoch_time_ms": obj.get("value"),
        "vs_baseline": obj.get("vs_baseline"),
        "model_tflops_s": obj.get("model_tflops_s"),
        "mfu_pct": obj.get("mfu_pct"),
        "hbm_gbps_min": obj.get("hbm_gbps_min"),
        "hbm_peak_gb_gcn": obj.get("hbm_peak_gb_gcn"),
        "graphcast_step_ms": obj.get("graphcast_step_ms"),
        "hbm_peak_gb_graphcast": obj.get("hbm_peak_gb_graphcast"),
        "wall_s": obj.get("wall_s"),
    }
    meta = {}
    for k in ("unit", "hardware", "error", "config", "graphcast_config"):
        if obj.get(k) is not None:
            meta[k] = obj[k]
    for role, h in rh.items():
        if isinstance(h, dict) and h.get("wedge") not in (None, "none"):
            meta.setdefault("wedge", {})[role] = h["wedge"]
    entries.append(_entry(
        "bench_round", metrics,
        workload=str(obj.get("metric") or "arxiv_gcn_epoch_time"),
        git_rev=git_rev, recorded_at=recorded, source=source,
        round_n=round_n, meta=meta,
    ))
    for kind in ("schedule_drift", "hlo_drift", "spmd_drift"):
        sub = obj.get(kind)
        if isinstance(sub, dict):
            es, ss = _norm_tier(dict(sub, kind=kind), source, round_n, git_rev)
            entries += es
            skips += ss
    sub = obj.get("cpu_scan_delta")
    if isinstance(sub, dict):
        es, ss = _norm_scan_delta(sub, source, round_n, git_rev)
        entries += es
        skips += ss
    sub = obj.get("supervise_lineage")
    if isinstance(sub, dict):
        es, ss = _norm_lineage(sub, source, round_n=round_n, git_rev=git_rev)
        entries += es
        skips += ss
    return entries, skips


def _norm_driver_wrapper(obj: dict, source: str) -> tuple:
    """The driver's ``BENCH_rNN.json`` wrapper ({n, cmd, rc, tail,
    parsed}): recurse into ``parsed`` when the round produced JSON;
    otherwise the round never reached a chip — ingest the stub as
    ``kind="probe_wedge"`` (the r01–r05 wedge history IS trajectory)."""
    round_n = obj.get("n")
    parsed = obj.get("parsed")
    if isinstance(parsed, dict) and parsed.get("value") is not None:
        return _norm_bench_round(parsed, source, round_n=round_n)
    if isinstance(parsed, dict):
        # the r03–r05 shape: a structured failure JSON whose value is
        # null ("backend never initialized ...; wedged TPU lease") — the
        # round never reached a chip, so it is wedge history, but any
        # attached fallback tiers / lineage are still real signal
        entries, skips = [], []
        wedge = _entry(
            "probe_wedge", {"rc": obj.get("rc")},
            workload=str(parsed.get("metric") or "arxiv_gcn_epoch_time"),
            git_rev=parsed.get("git_rev"), source=source, round_n=round_n,
            meta={"error": str(parsed.get("error") or "")[:300]},
        )
        entries.append(wedge)
        tiers_and_lineage = _norm_bench_round(parsed, source, round_n=round_n)
        # keep everything EXCEPT the empty bench_round shell
        entries += [e for e in tiers_and_lineage[0]
                    if e["kind"] != "bench_round"]
        skips += tiers_and_lineage[1]
        return entries, skips
    tail = (obj.get("tail") or "").strip().splitlines()
    return [_entry(
        "probe_wedge", {"rc": obj.get("rc")},
        workload="arxiv_gcn_epoch_time", source=source, round_n=round_n,
        meta={"last_line": tail[-1][:300] if tail else "",
              "cmd": str(obj.get("cmd", ""))[:200]},
    )], []


def _norm_multichip(obj: dict, source: str) -> tuple:
    """``MULTICHIP_rNN.json``: the per-family dryrun table parsed from the
    tail (step_ms when the dryrun timed; family presence always)."""
    tail = obj.get("tail") or ""
    metrics, families = {}, []
    for line in tail.splitlines():
        m = _DRYRUN_RE.match(line.strip())
        if not m or m.group(1) == "dryrun_multichip":
            continue
        families.append(m.group(1))
        ms = _STEP_MS_RE.search(m.group(2))
        if ms:
            metrics[f"step_ms/{m.group(1)}"] = float(ms.group(1))
    metrics["n_families"] = len(families)
    metrics["rc"] = obj.get("rc")
    return [_entry(
        "multichip_dryrun", metrics, workload="multichip_dryrun",
        source=source, round_n=obj.get("n"),
        meta={"n_devices": obj.get("n_devices"), "ok": obj.get("ok"),
              "skipped": obj.get("skipped"), "families": families},
    )], []


def _norm_tune_record(obj: dict, source: str) -> tuple:
    """A ``tune_<sig>.json`` TuningRecord: the workload key IS the
    signature (via the record_id tune.signature minted)."""
    cost = obj.get("cost") or {}
    cfg = obj.get("config") or {}
    metrics = {k: v for k, v in cost.items() if isinstance(v, (int, float))}
    return [_entry(
        "tune_record", metrics,
        workload=str(obj.get("record_id") or "tune"),
        halo_impl=cfg.get("halo_impl"),
        recorded_at=obj.get("created_at") or None,
        source=source,
        meta={"phase": obj.get("phase"),
              "partition_method": cfg.get("partition_method"),
              "pad_multiple": cfg.get("pad_multiple")},
    )], []


def _norm_serve_health(obj: dict, source: str) -> tuple:
    """A serve_health record: headline latency percentiles, per-stage
    p99s, and the steady-state SLO counters."""
    ver = obj.get("schema_version")
    if ver is not None and ver > SERVE_HEALTH_SCHEMA_VERSION:
        return [], [_skip(
            source,
            f"serve_health schema_version {ver} is newer than supported "
            f"{SERVE_HEALTH_SCHEMA_VERSION}",
        )]
    lat = obj.get("latency_ms") or {}
    metrics = {
        "p50_ms": lat.get("p50"),
        "p95_ms": lat.get("p95"),
        "p99_ms": lat.get("p99"),
        "requests": lat.get("count"),
        "recompiles_since_warmup": obj.get("recompiles_since_warmup"),
        "warmup_s": obj.get("warmup_s"),
        "n_tenants": len(obj.get("tenants") or {}) or None,
        "queue_depth": (obj.get("queue") or {}).get("depth"),
        "wall_s": obj.get("wall_s"),
    }
    for stage, hist in (obj.get("stages_ms") or {}).items():
        if isinstance(hist, dict):
            metrics[f"{stage}_p99_ms"] = hist.get("p99")
    return [_entry(
        "serve_health", metrics,
        workload=str(obj.get("tuning_record") or "serve"),
        git_rev=obj.get("git_rev"),
        recorded_at=obj.get("started_at"), source=source,
        meta={"degraded": obj.get("degraded"),
              "generation": obj.get("generation"),
              "buckets": obj.get("buckets"),
              "schema_version": ver},
    )], []


def _norm_lineage(obj: dict, source: str, round_n=None, git_rev=None) -> tuple:
    """supervise_lineage / supervise_group_lineage: restart counts and
    outcome — the availability half of the trajectory."""
    rh = obj.get("run_health") or {}
    metrics = {
        "restarts": obj.get("restarts"),
        "attempts": len(obj.get("attempts") or []),
        "final_exit_code": obj.get("final_exit_code"),
        "wall_s": rh.get("wall_s"),
        "final_world": obj.get("final_world"),
    }
    return [_entry(
        obj.get("kind", "supervise_lineage"), metrics,
        workload="supervise",
        git_rev=git_rev or rh.get("git_rev"),
        recorded_at=rh.get("started_at"), source=source, round_n=round_n,
        meta={"gave_up": obj.get("gave_up"),
              "budget_exhausted": obj.get("budget_exhausted"),
              "wedge": rh.get("wedge")},
    )], []


def _norm_sched_compile(obj: dict, source: str) -> tuple:
    """sched_compile: one compiled halo schedule (dgraph_tpu.sched) with
    its footprint pricing. The ``_bytes``/``_count`` metric suffixes put
    the compiled shape under obs.regress's byte-exact zero-tolerance
    class: a commit that silently changes what the compiler emits for
    the same workload goes RED, while ``exposed_us`` rides the
    noise-aware timing gate. The schedule_id in meta names the exact
    round order (content hash of the serialized IR)."""
    metrics = {
        "rounds_count": obj.get("rounds"),
        "transfers_count": obj.get("transfers"),
        "operand_bytes": obj.get("operand_bytes_per_shard"),
        "exposed_us": obj.get("exposed_us"),
    }
    rb = obj.get("round_bytes_per_shard")
    if isinstance(rb, (list, tuple)):
        metrics["max_round_bytes"] = max(rb, default=0)
    return [_entry(
        "sched_compile", metrics,
        workload=_workload_tag(obj.get("workload")),
        halo_impl="sched",
        git_rev=obj.get("git_rev"), recorded_at=obj.get("recorded_at"),
        source=source, round_n=obj.get("round"),
        meta={"schedule_id": obj.get("schedule_id"),
              "round_rows": list(obj.get("round_rows") or [])[:64]},
    )], []


def _norm_wire_compile(obj: dict, source: str) -> tuple:
    """wire_compile: one resolved wire format (dgraph_tpu.wire) with its
    priced exchange operand. ``operand_bytes`` rides obs.regress's
    byte-exact zero-tolerance class: a codec or pricing change that
    alters what the same workload ships on the wire goes RED across
    commits. The format name, who resolved it, and the compression ratio
    are provenance (meta), not gated numbers."""
    metrics = {
        "operand_bytes": obj.get("operand_bytes"),
    }
    return [_entry(
        "wire_compile", metrics,
        workload=_workload_tag(obj.get("workload")),
        halo_impl=obj.get("halo_impl"),
        git_rev=obj.get("git_rev"), recorded_at=obj.get("recorded_at"),
        source=source, round_n=obj.get("round"),
        meta={"wire_format": obj.get("wire_format"),
              "wire_format_source": obj.get("wire_format_source"),
              "compression_ratio": obj.get("compression_ratio")},
    )], []


def _norm_grow_transition(obj: dict, source: str) -> tuple:
    """grow_transition: one adopted W -> W+k elastic expansion
    (``train.grow.grow_record``). The world/shard counts carry the
    exact-class ``_count`` suffixes, so a transition that resharded to
    the wrong world size — or wrote a different shard count for the same
    generation — goes RED with zero tolerance, while the re-plan wall
    time rides the noise-aware timing gate. The joined tokens and the
    resume step are provenance (meta), not gated numbers."""
    replan_s = obj.get("replan_s")
    metrics = {
        "old_world_count": obj.get("old_world"),
        "new_world_count": obj.get("new_world"),
        "shards_count": obj.get("shards"),
        "replan_ms": (replan_s * 1000.0
                      if isinstance(replan_s, (int, float))
                      and not isinstance(replan_s, bool) else None),
    }
    return [_entry(
        "grow_transition", metrics,
        workload=f"grow_g{obj.get('generation')}",
        git_rev=obj.get("git_rev"), recorded_at=obj.get("recorded_at"),
        source=source,
        meta={"generation": obj.get("generation"),
              "resume_step": obj.get("resume_step"),
              "joined": obj.get("joined")},
    )], []


def _norm_run_health(obj: dict, source: str) -> tuple:
    metrics = {"wall_s": obj.get("wall_s"),
               "n_probes": len(obj.get("probes") or [])}
    return [_entry(
        "run_health", metrics,
        workload=str(obj.get("component") or "unknown"),
        git_rev=obj.get("git_rev"), recorded_at=obj.get("started_at"),
        source=source,
        meta={"wedge": obj.get("wedge"),
              "error": (obj.get("error") or "")[:300] or None},
    )], []


def _norm_reference(obj: dict, source: str) -> tuple:
    """BASELINE.json-style reference metadata: no numbers, but the
    trajectory's provenance note belongs in the store too."""
    return [_entry(
        "reference_note", {},
        workload=str(obj.get("metric") or "reference"), source=source,
        meta={k: obj[k] for k in
              ("reference_repo", "north_star", "published") if k in obj},
    )], []


# kinds intentionally not stored (high-volume or meta-artifacts), each
# with the reason the skip record carries
_DECLINED_KINDS = {
    "span": "span records are high-volume; query them via obs.spans",
    "step_metrics": "per-step metrics are high-volume; the ledger stores "
                    "round/record-level summaries",
    "lint_report": "analysis reports are regenerated by scripts/check.py",
    "check_report": "analysis reports are regenerated by scripts/check.py",
}


def normalize_record(obj, source: str = "") -> tuple:
    """Normalize one emitted record/artifact into ledger entries.

    Returns ``(entries, skips)``; never raises on payload shape — an
    unrecognized payload becomes one skip-with-reason so ingestion can
    never crash an emitting run (the BENCH_r05 lesson: a wedge-era
    artifact is still data)."""
    if not isinstance(obj, dict):
        return [], [_skip(source, f"payload is {type(obj).__name__}, "
                                  f"not an object")]
    try:
        kind = obj.get("kind")
        if kind in _DECLINED_KINDS:
            return [], [_skip(source, _DECLINED_KINDS[kind])]
        if kind in ("schedule_drift", "hlo_drift", "spmd_drift"):
            return _norm_tier(obj, source, None, obj.get("git_rev"))
        if kind == "cpu_scan_delta":
            return _norm_scan_delta(obj, source, None, obj.get("git_rev"))
        if kind == "serve_health":
            return _norm_serve_health(obj, source)
        if kind in ("supervise_lineage", "supervise_group_lineage"):
            return _norm_lineage(obj, source)
        if kind == "grow_transition":
            return _norm_grow_transition(obj, source)
        if kind == "run_health":
            return _norm_run_health(obj, source)
        if kind == "sched_compile":
            return _norm_sched_compile(obj, source)
        if kind == "wire_compile":
            return _norm_wire_compile(obj, source)
        if kind == "tune_record" or (
            kind is None and "record_id" in obj and "signature" in obj
            and "cost" in obj
        ):
            return _norm_tune_record(obj, source)
        if kind is None and "parsed" in obj and "tail" in obj and "n" in obj:
            return _norm_driver_wrapper(obj, source)
        if kind is None and "n_devices" in obj and "tail" in obj:
            return _norm_multichip(obj, source)
        if kind is None and "reference_repo" in obj:
            return _norm_reference(obj, source)
        if kind is None and "metric" in obj and "value" in obj:
            return _norm_bench_round(obj, source)
        return [], [_skip(
            source, f"unrecognized payload (kind={kind!r}, "
                    f"keys={sorted(obj)[:8]})",
        )]
    except Exception as e:  # normalization must never break the emitter
        return [], [_skip(source, f"normalizer crashed: "
                                  f"{type(e).__name__}: {e}")]


# ---------------------------------------------------------------------------
# store: append / read / ingest
# ---------------------------------------------------------------------------


def read_ledger(directory: str) -> tuple:
    """All entries in a ledger dir + skips for undecodable lines (a torn
    trailing append after a crash is expected, not fatal)."""
    path = ledger_path(directory)
    entries, skips = [], []
    if not os.path.exists(path):
        return entries, skips
    with open(path) as fh:
        for i, line in enumerate(fh, 1):
            if not line.strip():
                continue
            try:
                e = json.loads(line)
            except ValueError:
                skips.append(_skip(f"{path}:{i}",
                                   "undecodable JSONL line (torn append?)"))
                continue
            if not isinstance(e, dict) or "entry_id" not in e:
                skips.append(_skip(f"{path}:{i}",
                                   "line is not a ledger entry"))
                continue
            entries.append(e)
    return entries, skips


def ingest(obj, source: str, directory: str) -> dict:
    """Normalize ``obj`` and durably append the entries not already in
    the ledger (idempotent by ``entry_id`` — backfill is re-runnable)."""
    entries, skips = normalize_record(obj, source)
    existing, read_skips = read_ledger(directory)
    seen = {e.get("entry_id") for e in existing}
    fresh = [e for e in entries if e["entry_id"] not in seen]
    appended = atomic_append_jsonl(ledger_path(directory), fresh)
    return {
        "appended": appended,
        "deduped": len(entries) - len(fresh),
        "skipped": skips + read_skips,
    }


def maybe_ingest(obj, source: str, default_on: bool = False) -> Optional[dict]:
    """The guarded emission-site hook: resolve the knob, ingest, and
    swallow EVERYTHING — a ledger problem (read-only filesystem, torn
    store, bad payload) must never cost the run that was merely trying
    to record itself. Returns the ingest report, or None when the knob
    is off or ingestion failed."""
    try:
        directory = resolve_ledger_dir(default_on=default_on)
        if not directory:
            return None
        return ingest(obj, source, directory)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# backfill — seed the ledger from the historical artifact corpus
# ---------------------------------------------------------------------------

_BACKFILL_GLOBS = (
    "BENCH_BASELINE.json", "BENCH_r*.json", "MULTICHIP_r*.json",
    "BASELINE.json",
)


def backfill(root: str, directory: str) -> dict:
    """Ingest the repo's historical artifact corpus (``BENCH_*.json``,
    ``MULTICHIP_r*.json``, ``BASELINE.json``) so the 456.9 ms round-1
    baseline and the wedge history become the ledger's first entries.
    Idempotent: re-running dedups by entry_id."""
    report = {"kind": "ledger_backfill", "root": os.path.abspath(root),
              "dir": directory, "files": 0, "appended": 0, "deduped": 0,
              "skipped": []}
    for pat in _BACKFILL_GLOBS:
        for path in sorted(glob.glob(os.path.join(root, pat))):
            report["files"] += 1
            try:
                with open(path) as fh:
                    obj = json.load(fh)
            except (OSError, ValueError) as e:
                report["skipped"].append(_skip(
                    path, f"unreadable artifact: {type(e).__name__}: {e}"))
                continue
            r = ingest(obj, os.path.basename(path), directory)
            report["appended"] += r["appended"]
            report["deduped"] += r["deduped"]
            report["skipped"] += r["skipped"]
    return report


def summarize(directory: str) -> dict:
    """Per-kind entry counts + the read skips — the CLI's default view."""
    entries, skips = read_ledger(directory)
    by_kind: dict = {}
    for e in entries:
        by_kind[e.get("kind", "?")] = by_kind.get(e.get("kind", "?"), 0) + 1
    return {
        "kind": "ledger_summary",
        "dir": directory,
        "path": ledger_path(directory),
        "entries": len(entries),
        "by_kind": dict(sorted(by_kind.items())),
        "skipped": skips,
        "schema": LEDGER_SCHEMA_VERSION,
    }


# ---------------------------------------------------------------------------
# selftest — ingestion fixtures for every kind (the vacuity guards live
# in obs.regress's selftest; this one proves the normalizers + store)
# ---------------------------------------------------------------------------


def _fixture_bench_round(value=400.0, rnd=6, git_rev="abc1234") -> dict:
    return {
        "metric": "arxiv_gcn_epoch_time", "value": value, "unit": "ms",
        "vs_baseline": value / 456.898, "mfu_pct": 1.2,
        "git_rev": git_rev,
        "run_health": {"child": {"started_at": f"2026-08-0{rnd}T00:00:00Z",
                                 "wedge": "none"}},
        "schedule_drift": {
            "kind": "schedule_drift",
            "workload": {"world_size": 8, "nodes": 4096, "edges": 16384,
                         "feat_dim": 32, "seed": 0},
            "train_step_by_impl": {
                "all_to_all": {"collective_count": 3, "traced_bytes": 4096,
                               "footprint_bytes": 4096},
            },
        },
        "cpu_scan_delta": {
            "kind": "cpu_scan_delta",
            "workload": {"world_size": 2, "nodes": 96, "edges": 400,
                         "feat_dim": 8, "seed": 0},
            "by_impl": {"all_to_all": {
                "full_ms": 100.0, "exchange_only_ms": 20.0,
                "exposed_exchange_ms": 10.0,
                "phases_ms": {"interior": 60.0, "exchange": 20.0,
                              "optimizer": 15.0, "other": 5.0},
            }},
        },
    }


def _selftest() -> dict:
    import tempfile

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    with tempfile.TemporaryDirectory(prefix="dgraph_ledger_selftest_") as tmp:
        # every normalizer lands the right kind
        r = ingest(_fixture_bench_round(), "BENCH_r06.json", tmp)
        check(r["appended"] >= 3 and not r["skipped"],
              f"bench fixture ingest: {r}")
        entries, _ = read_ledger(tmp)
        kinds = {e["kind"] for e in entries}
        for want in ("bench_round", "schedule_drift", "cpu_scan_delta"):
            check(want in kinds, f"missing kind {want!r} after bench ingest")
        check(all(e["git_rev"] == "abc1234" for e in entries
                  if e["kind"] == "bench_round"),
              "git_rev did not propagate into the bench_round entry")

        # probe stub -> probe_wedge, never a crash
        stub = {"n": 5, "cmd": "python bench.py", "rc": 3,
                "tail": "probe attempt 7 hung (wedged lease)",
                "parsed": None}
        r = ingest(stub, "BENCH_r05.json", tmp)
        check(r["appended"] == 1, f"probe stub ingest: {r}")
        entries, _ = read_ledger(tmp)
        check(any(e["kind"] == "probe_wedge" and e["round"] == 5
                  for e in entries), "probe stub did not land as probe_wedge")

        # grow transition -> exact-class world/shard counts + timing
        grow = {"kind": "grow_transition", "generation": 1, "old_world": 2,
                "new_world": 3, "resume_step": 3, "joined": ["newcomer-a"],
                "replan_s": 0.125, "shards": 3, "git_rev": "abc1234",
                "recorded_at": "2026-08-06T00:00:00Z"}
        r = ingest(grow, "grow_g1.json", tmp)
        check(r["appended"] == 1 and not r["skipped"],
              f"grow_transition ingest: {r}")
        entries, _ = read_ledger(tmp)
        ge = next((e for e in entries if e["kind"] == "grow_transition"),
                  None)
        check(ge is not None
              and ge["metrics"].get("new_world_count") == 3
              and ge["metrics"].get("old_world_count") == 2
              and ge["metrics"].get("shards_count") == 3
              and ge["metrics"].get("replan_ms") == 125.0
              and ge["meta"].get("joined") == ["newcomer-a"],
              f"grow_transition entry malformed: {ge}")

        # idempotence: same artifact again -> all deduped
        r = ingest(_fixture_bench_round(), "BENCH_r06.json", tmp)
        check(r["appended"] == 0 and r["deduped"] >= 3,
              f"re-ingest was not idempotent: {r}")

        # unrecognized payload -> skip-with-reason, rc still fine
        r = ingest({"surprise": True}, "mystery.json", tmp)
        check(r["appended"] == 0 and r["skipped"]
              and "unrecognized" in r["skipped"][0]["reason"],
              f"unrecognized payload not skipped-with-reason: {r}")

        # torn trailing append -> one skip, earlier entries intact (the
        # bare open is the POINT here: simulate the host crash the
        # durable-write rule exists to prevent)
        n_before = len(read_ledger(tmp)[0])
        with open(ledger_path(tmp), "a") as fh:  # lint: allow(host-durable-write)
            fh.write('{"schema": 1, "kind": "bench_ro')
        entries, skips = read_ledger(tmp)
        check(len(entries) == n_before and len(skips) == 1,
              f"torn trailing line not skipped cleanly "
              f"({len(entries)} vs {n_before}, skips={skips})")

    return {"kind": "ledger_selftest", "failures": failures,
            "ok": not failures}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Config:
    """Perf-trajectory ledger CLI: ``--backfill <repo-root>`` seeds the
    store from the historical artifact corpus; the default prints a
    per-kind summary of the active ledger."""

    backfill: str = ""   # repo root to backfill from ("" = no backfill)
    dir: str = ""        # ledger dir ("" = DGRAPH_LEDGER_DIR or default)
    selftest: bool = False
    indent: int = 0


def main(cfg: Config) -> dict:
    if cfg.selftest:
        out = _selftest()
        print(json.dumps(out, indent=cfg.indent or None))
        if out["failures"]:
            raise SystemExit(1)
        return out
    # an explicit CLI invocation always has a directory: --dir wins, then
    # the env knob, then the default (even when the env knob says "off" —
    # "off" gates the emission-site hooks, not the operator's own CLI)
    directory = (cfg.dir or resolve_ledger_dir(default_on=True)
                 or DEFAULT_LEDGER_DIR)
    if cfg.backfill:
        out = backfill(cfg.backfill, directory)
    else:
        out = summarize(directory)
    print(json.dumps(out, indent=cfg.indent or None))
    return out


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
