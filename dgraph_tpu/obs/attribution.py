"""CPU scan-delta step-time attribution: the bench tier that un-blinds
wedged rounds.

BENCH_r03–r05 each burned ~1200 s on wedged-lease probes and landed
``value: null`` — zero perf signal for three straight rounds.  PR 6's
``schedule_drift`` fallback made the *comm-schedule* dimension non-null;
this module closes ROADMAP item 5's remaining gap: a **timing** tier that
runs on the virtual-CPU backend (8 forced host devices, the same backend
tier-1 uses, so the persistent XLA cache is warm) and produces a per-phase
step-time breakdown per halo lowering — comparable across rounds even when
no chip ever comes up.

Protocol: bench.py's compile-inside-scan rules verbatim (n steps inside
one ``lax.scan`` under one jit, scalar-fetch completion barrier, report
the positive delta between two scan lengths so per-call overhead cancels
— :func:`dgraph_tpu.tune.measure._timed_scan_ms` is reused as-is).

Program variants, per halo lowering (the config pin drives resolution, the
same mechanism the trace auditor uses):

- ``full``           — 2-layer GCN train step: fwd + bwd + optimizer.
- ``no_optimizer``   — fwd + bwd only (optimizer = full − no_optimizer).
- ``exchange_only``  — the isolated exchange legs: one
  ``halo_exchange`` + ``halo_scatter_sum`` pair per layer, no compute to
  hide behind.
- ``interior_only``  — fwd + bwd with the exchange elided
  (``halo_deltas=()`` makes every collective statically vanish while all
  local gather/scatter/matmul work keeps identical shapes). Lowering-
  independent: measured once and shared.

Breakdown per lowering (``phases_ms``):

- ``interior``  = interior_only (local compute)
- ``exchange``  = exchange_only (isolated collective cost)
- ``optimizer`` = full − no_optimizer
- ``other``     = full − interior − exchange − optimizer (the residual;
  NEGATIVE values are signal, not error — they mean the lowering hid part
  of the isolated exchange cost behind compute, which is exactly what the
  overlap lowering exists to do).  ``exposed_exchange_ms``
  (no_optimizer − interior_only) is the directly-measured exposed cost.

The record also folds the newest MULTICHIP dryrun's per-family step times
(``MULTICHIP_r*.json`` — ``__graft_entry__`` stamps ``step_ms=`` per
family) so one artifact carries both the phase attribution and the
model-family table.  ``python -m dgraph_tpu.obs.attribution
--bench_fallback true`` is what bench.py's wedged path spawns.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
from typing import Optional

DEFAULT_IMPLS = ("all_to_all", "overlap", "pallas_p2p")
SCHEMA_VERSION = 1


def _num(x) -> Optional[float]:
    """NaN-safe rounding: the JSON artifact must stay strictly valid (and
    schema-stable) even when a timing round never yields a positive
    delta."""
    if x is None or x != x:
        return None
    return round(float(x), 3)


def multichip_family_table(root: Optional[str] = None) -> Optional[dict]:
    """Per-family step times from the newest ``MULTICHIP_r*.json`` dryrun
    artifact (``__graft_entry__`` prints ``dryrun <family> OK: ...
    step_ms=<x>`` per family).  None when no artifact exists; families
    missing ``step_ms`` (pre-stamping rounds) simply don't appear."""
    root = root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    files = sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    if not files:
        return None
    try:
        with open(files[-1]) as fh:
            artifact = json.load(fh)
    except (OSError, ValueError):
        return None
    families = {
        m.group(1): float(m.group(2))
        for m in re.finditer(
            r"dryrun (\S+) OK:.*?step_ms=([0-9.]+)", artifact.get("tail", "")
        )
    }
    return {
        "source": os.path.basename(files[-1]),
        "ok": artifact.get("ok"),
        "n_devices": artifact.get("n_devices"),
        "step_ms_by_family": families,
    }


# ---------------------------------------------------------------------------
# workload + program variants
# ---------------------------------------------------------------------------


def _build_workload(world_size, num_nodes, num_edges, feat_dim, hidden,
                    num_classes, seed):
    """Real (device-array) 2-layer GCN workload over a ``world_size``-shard
    random graph with the interior/boundary split, so every lowering —
    including overlap — is legal. Mirrors the trace auditor's workload but
    with concrete buffers: this tier *executes*."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu import plan as pl
    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.comm.mesh import make_graph_mesh
    from dgraph_tpu.models import GCN
    from dgraph_tpu.train.loop import init_params

    devices = jax.devices()
    if len(devices) < world_size:
        raise RuntimeError(
            f"scan-delta attribution for world_size={world_size} needs that "
            f"many devices; have {len(devices)} (set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8)"
        )
    rng = np.random.default_rng(seed)
    part = np.sort(rng.integers(0, world_size, num_nodes)).astype(np.int32)
    edges = np.stack([
        rng.integers(0, num_nodes, num_edges),
        rng.integers(0, num_nodes, num_edges),
    ])
    plan, layout = pl.build_edge_plan(
        edges, part, world_size=world_size, overlap=True
    )
    mesh = make_graph_mesh(
        ranks_per_graph=world_size, devices=devices[:world_size]
    )
    comm = Communicator.init_process_group("tpu", world_size=world_size)
    model = GCN(
        hidden_features=hidden, out_features=num_classes, comm=comm,
        num_layers=2,
    )
    x = pl.shard_vertex_data(
        rng.normal(size=(num_nodes, feat_dim)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    )
    batch = {
        "x": jnp.asarray(x),
        "y": jnp.asarray(
            rng.integers(0, num_classes, (world_size, plan.n_src_pad))
            .astype(np.int32)),
        "mask": jnp.ones((world_size, plan.n_src_pad), jnp.float32),
    }
    plan_dev = jax.tree.map(jnp.asarray, plan)
    params = init_params(model, mesh, plan_dev, batch, seed=seed)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)
    return {
        "mesh": mesh, "model": model, "optimizer": optimizer,
        "plan": plan_dev, "batch": batch, "params": params,
        "opt_state": opt_state, "feat_dim": feat_dim, "hidden": hidden,
    }


def _train_scan(w, *, with_optimizer: bool, elide_exchange: bool = False):
    """(runner, initial state) for the scan-delta protocol over the train
    step. ``elide_exchange=True`` swaps in a ``halo_deltas=()`` plan: the
    collectives statically vanish (pinned by test_obs's impl-'none' spy)
    while every local op keeps its shape — the interior-only variant."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu import compat as _compat
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan

    model, optimizer, mesh = w["model"], w["optimizer"], w["mesh"]
    plan, batch = w["plan"], w["batch"]
    if elide_exchange:
        plan = dataclasses.replace(plan, halo_deltas=())
    batch_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), batch)
    plan_specs = plan_in_specs(plan)

    def shard_body(params, batch_, plan_):
        p = squeeze_plan(plan_)
        b = jax.tree.map(lambda leaf: leaf[0], batch_)

        def lf(pp):
            logits = model.apply(pp, b["x"], p)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(logp, b["y"][:, None], axis=1)[:, 0]
            cnt = lax.psum(b["mask"].sum(), GRAPH_AXIS)
            return -(ll * b["mask"]).sum() / jnp.maximum(cnt, 1.0)

        loss, grads = jax.value_and_grad(lf)(params)
        grads = _compat.sync_inbody_grads(grads, (GRAPH_AXIS,))
        return grads, lax.psum(loss, GRAPH_AXIS)

    from dgraph_tpu.comm.collectives import shard_map_checks
    from dgraph_tpu.comm.mesh import GRAPH_AXIS as _GA

    grad_fn = jax.shard_map(
        shard_body, mesh=mesh,
        in_specs=(P(), batch_specs, plan_specs), out_specs=(P(), P()),
        # pallas_p2p programs relax the 0.4.x rep checker (pallas_call
        # has no replication rule there); every other lowering keeps it
        **shard_map_checks(plan, _GA),
    )

    @functools.partial(jax.jit, static_argnames="n", donate_argnums=(0, 1))
    def steps(params, opt_state, salt, n):
        def body(carry, _):
            p, o, s = carry
            grads, loss = grad_fn(p, batch, plan)
            if with_optimizer:
                updates, o = optimizer.update(grads, o, p)
                p = optax.apply_updates(p, updates)
            else:
                # keep a live dependence on the grads so backward work
                # cannot be dead-code-eliminated out of the timing loop
                loss = loss + optax.global_norm(grads) * 1e-20
            return (p, o, s + loss * 1e-20), None

        (p, o, s), _ = lax.scan(
            body, (params, opt_state, salt), None, length=n
        )
        return p, o, s

    def run(state, n):
        p, o, s = steps(*state, n)
        float(s)  # scalar fetch: the one trustworthy completion barrier
        return (p, o, s)

    # fresh copies per program: the scan DONATES (params, opt_state), and
    # the workload's originals must survive for the next variant
    state = (
        jax.tree.map(jnp.array, w["params"]),
        jax.tree.map(jnp.array, w["opt_state"]),
        jnp.float32(0.0),
    )

    def run_in_mesh(state, n):
        with jax.set_mesh(mesh):
            return run(state, n)

    return run_in_mesh, state


def _exchange_scan(w, impl: str, num_layers: int = 2):
    """(runner, initial state) for the exchange-only variant: per scan
    iteration, one ``halo_exchange`` + ``halo_scatter_sum`` pair per layer
    at the hidden width (the width the layers exchange at), chained
    through the carry so rounds serialize instead of hoisting."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm import collectives
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan

    mesh, plan, hidden = w["mesh"], w["plan"], w["hidden"]
    plan_specs = plan_in_specs(plan)

    def shard_body(x, plan_):
        p = squeeze_plan(plan_)
        h = x[0]
        for _ in range(num_layers):
            buf = collectives.halo_exchange(
                h, p.halo, GRAPH_AXIS, deltas=p.halo_deltas, impl=impl
            )
            back = collectives.halo_scatter_sum(
                buf, p.halo, p.n_src_pad, GRAPH_AXIS,
                deltas=p.halo_deltas, impl=impl,
            )
            h = h + back * 1e-6
        return h[None]

    from dgraph_tpu.comm.collectives import shard_map_checks

    sm = jax.shard_map(
        shard_body, mesh=mesh,
        in_specs=(P(GRAPH_AXIS), plan_specs), out_specs=P(GRAPH_AXIS),
        **shard_map_checks(plan, GRAPH_AXIS),
    )

    @functools.partial(jax.jit, static_argnames="n", donate_argnums=(0,))
    def steps(x, salt, n):
        def body(carry, _):
            xx, s = carry
            # fold the carry scalar in so iterations stay data-dependent
            out = sm(xx + (s * 1e-20).astype(xx.dtype), plan)
            return (out, s + out.sum() * 1e-20), None

        (x2, s), _ = lax.scan(body, (x, salt), None, length=n)
        return x2, s

    def run(state, n):
        with jax.set_mesh(mesh):
            x, s = steps(*state, n)
        float(s)
        return (x, s)

    world = plan.world_size
    n_pad = plan.n_src_pad
    x0 = jnp.ones((world, n_pad, hidden), jnp.float32)
    return run, (x0, jnp.float32(0.0))


# ---------------------------------------------------------------------------
# the attribution record
# ---------------------------------------------------------------------------


def scan_delta_attribution(
    world_size: int = 2,
    *,
    num_nodes: int = 96,
    num_edges: int = 400,
    feat_dim: int = 8,
    hidden: int = 16,
    num_classes: int = 4,
    impls=DEFAULT_IMPLS,
    n_long: int = 6,
    reps: int = 1,
    seed: int = 0,
    fold_multichip: bool = True,
) -> dict:
    """Per-phase ``{interior, exchange, optimizer, other}`` step-time
    breakdown per halo lowering, measured with the compile-inside-scan
    protocol on the current (virtual-CPU on a wedged round) backend.
    Returns the ``kind="cpu_scan_delta"`` record bench.py attaches."""
    import jax

    from dgraph_tpu import config as _cfg
    from dgraph_tpu.tune.measure import _timed_scan_ms

    w = _build_workload(
        world_size, num_nodes, num_edges, feat_dim, hidden, num_classes, seed
    )

    def time_one(run, state):
        # warm both scan lengths before timing, THREADING the state: the
        # scans donate their inputs, so the returned buffers are the only
        # live ones. A NaN round (host jitter swallowing a sub-ms delta —
        # seen under a loaded tier-1 run) retries with a doubled scan
        # length so the per-step signal amortizes above the noise; the
        # longer scans cost one extra compile each, only on retry.
        state = run(state, 1)
        for n in (n_long, 2 * n_long, 4 * n_long):
            state = run(state, n)
            ms, state = _timed_scan_ms(run, state, n, reps=reps)
            if ms == ms:
                return ms
        return float("nan")

    saved = (_cfg.halo_impl, _cfg.tuned_halo_impl, _cfg.use_pallas_p2p)
    by_impl = {}
    try:
        # interior-only (exchange elided) is lowering-independent: one
        # measurement, shared by every impl's breakdown. Pin all_to_all so
        # overlap routing never engages on the delta-free plan.
        _cfg.set_flags(halo_impl="all_to_all", tuned_halo_impl=None)
        run, state = _train_scan(w, with_optimizer=False, elide_exchange=True)
        t_interior = time_one(run, state)

        for impl in impls:
            _cfg.set_flags(halo_impl=impl, tuned_halo_impl=None)
            # pinning pallas_p2p on the (wedged-round) CPU backend needs
            # the explicit availability opt-in: the kernels execute in
            # Pallas interpret mode, timed like any other lowering
            _cfg.set_flags(
                use_pallas_p2p=True if impl == "pallas_p2p" else saved[2]
            )
            run, state = _train_scan(w, with_optimizer=True)
            t_full = time_one(run, state)
            run, state = _train_scan(w, with_optimizer=False)
            t_no_opt = time_one(run, state)
            run, state = _exchange_scan(w, impl)
            t_exchange = time_one(run, state)

            t_opt = (
                max(t_full - t_no_opt, 0.0)
                if t_full == t_full and t_no_opt == t_no_opt else float("nan")
            )
            other = (
                t_full - t_interior - t_exchange - t_opt
                if all(v == v for v in (t_full, t_interior, t_exchange, t_opt))
                else float("nan")
            )
            exposed = (
                max(t_no_opt - t_interior, 0.0)
                if t_no_opt == t_no_opt and t_interior == t_interior
                else float("nan")
            )
            by_impl[impl] = {
                "full_ms": _num(t_full),
                "no_optimizer_ms": _num(t_no_opt),
                "exchange_only_ms": _num(t_exchange),
                "phases_ms": {
                    "interior": _num(t_interior),
                    "exchange": _num(t_exchange),
                    "optimizer": _num(t_opt),
                    "other": _num(other),
                },
                "exposed_exchange_ms": _num(exposed),
            }
    finally:
        _cfg.set_flags(
            halo_impl=saved[0], tuned_halo_impl=saved[1],
            use_pallas_p2p=saved[2],
        )

    rec = {
        "kind": "cpu_scan_delta",
        "tier": "cpu_scan_delta",
        "schema": SCHEMA_VERSION,
        "backend": jax.default_backend(),
        "workload": {
            "world_size": world_size, "nodes": num_nodes, "edges": num_edges,
            "feat_dim": feat_dim, "hidden": hidden,
            "num_classes": num_classes, "n_long": n_long, "reps": reps,
            "seed": seed,
        },
        "interior_only_ms": _num(t_interior),
        "by_impl": by_impl,
        "multichip_dryrun": (
            multichip_family_table() if fold_multichip else None
        ),
    }
    return rec


# ---------------------------------------------------------------------------
# CLI — what bench.py's wedged-path fallback spawns
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Config:
    """CPU scan-delta step-time attribution (``--bench_fallback`` prints
    the record bench.py attaches on wedged rounds)."""

    bench_fallback: bool = False
    world: int = 2
    nodes: int = 96
    edges: int = 400
    feat_dim: int = 8
    hidden: int = 16
    num_classes: int = 4
    n_long: int = 6
    reps: int = 1
    impls: str = "all_to_all,overlap,pallas_p2p"
    seed: int = 0
    log_path: str = "logs/attribution.jsonl"
    indent: int = 0


def main(cfg: Config) -> dict:
    from dgraph_tpu.obs.health import RunHealth
    from dgraph_tpu.utils import ExperimentLog

    health = RunHealth.begin("obs.attribution")
    log = ExperimentLog(cfg.log_path, echo=False)
    try:
        out = scan_delta_attribution(
            cfg.world, num_nodes=cfg.nodes, num_edges=cfg.edges,
            feat_dim=cfg.feat_dim, hidden=cfg.hidden,
            num_classes=cfg.num_classes,
            impls=tuple(s.strip() for s in cfg.impls.split(",") if s.strip()),
            n_long=cfg.n_long, reps=cfg.reps, seed=cfg.seed,
        )
        out["run_health"] = health.finish()
        log.write(out)
        print(json.dumps(out, indent=cfg.indent or None))
        return out
    except BaseException as e:  # every exit path carries a RunHealth record
        log.write({
            "kind": "run_health",
            **health.finish(
                f"attribution failed: {type(e).__name__}: {e}",
                wedge="interrupted"
                if isinstance(e, KeyboardInterrupt) else "stage_failure",
            ),
        })
        raise


if __name__ == "__main__":
    # host-side analysis pass: never dial an accelerator (the same
    # unconditional pin dgraph_tpu.analysis.__main__ uses — the env alone
    # is not enough once a sitecustomize has frozen jax_platforms)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
