"""Flight recorder: hierarchical host-side span tracing with Perfetto export.

Until this module, the repo's three stacks each emitted *isolated* JSONL —
a serve request, a supervisor restart attempt, and a bench probe shared no
ID, so "where did the time go" was unanswerable across train/serve/bench.
Spans are the join key: every record carries a ``trace`` id (one per
logical run, inherited across process boundaries via the environment) and
a ``span``/``parent`` pair (one per timed operation), so restart chains,
request lifecycles, and probe histories line up in one timeline.

Design rules (the :mod:`dgraph_tpu.obs.metrics` discipline):

- **Zero overhead when disabled.** :func:`span` on a disabled tracer is
  ONE attribute read returning the shared no-op span — no allocation, no
  clock read, no I/O, and (because this module never touches jax) zero
  recompiles. Pinned by ``tests/test_spans.py``.
- **Host boundaries only.** Spans must never appear inside traced code —
  a host clock read inside a jit/shard_map/scan body times *tracing*, not
  execution, and a span id would freeze into the cached executable. The
  ``no-span-in-trace`` lint rule (:mod:`dgraph_tpu.analysis.lint`)
  machine-checks this.
- **jax-free module.** The train supervisor and bench's standalone loader
  import this file on machines where any jax call can hang (wedged
  lease); module level is pure stdlib, enforced by the ``jax-free-module``
  lint rule.

One finished span -> one JSONL record (``kind="span"``), written through
any sink with a ``write(dict)`` method (:class:`~dgraph_tpu.utils.logging.
ExperimentLog` works as-is) or a plain path.  ``python -m
dgraph_tpu.obs.spans --export perfetto --input logs/spans.jsonl`` converts
a span log to Chrome trace JSON loadable in https://ui.perfetto.dev.

Cross-process lineage: a parent process calls :func:`child_env` and merges
the result into the child's environment; the child's tracer auto-enables
with the SAME trace id (``DGRAPH_TRACE_ID``) and roots its spans under the
parent's span (``DGRAPH_TRACE_PARENT``) — this is how one supervised train
run's restart attempts land under one trace (``train.supervise``).
"""

from __future__ import annotations

import contextvars
import dataclasses
import json
import os
import threading
import time
from typing import Any, Optional

SPAN_SCHEMA_VERSION = 1

ENV_ENABLE = "DGRAPH_TRACE"  # "1"/"true" auto-enables the default tracer
ENV_TRACE_ID = "DGRAPH_TRACE_ID"  # inherited trace id (parent -> child)
ENV_PARENT = "DGRAPH_TRACE_PARENT"  # inherited root-parent span id
ENV_PATH = "DGRAPH_TRACE_PATH"  # sink path (default logs/spans.jsonl)
DEFAULT_PATH = "logs/spans.jsonl"

# the ambient innermost OPEN span of this thread/context (set by
# Span.__enter__ only; manually-ended spans never occupy it)
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "dgraph_span", default=None
)


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class _FileSink:
    """Plain JSONL appender (stdlib-only; the jax-free stand-in for
    ExperimentLog). The file is opened lazily on first write so an
    enabled-but-idle tracer leaves no artifact behind."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def write(self, rec: dict) -> None:
        with self._lock:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a") as fh:
                fh.write(json.dumps(rec, default=str) + "\n")


class _NoopSpan:
    """The shared disabled span: every method is a no-op, identity is the
    pin (``span(...) is NOOP_SPAN`` when tracing is off)."""

    __slots__ = ()

    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs) -> None:
        pass

    def end(self, error: Optional[str] = None, **attrs) -> None:
        pass

    def __bool__(self) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Span:
    """One timed operation: started at construction, sealed by :meth:`end`
    (or context-manager exit, which also maintains the ambient
    current-span used for implicit parenting).

    Works across threads: construct on one thread (e.g. a serve request's
    submit), pass the object along, and ``end()`` wherever the operation
    completes — parenting for cross-thread spans is explicit via the
    ``parent=`` argument to :func:`span`.
    """

    __slots__ = (
        "_tracer", "name", "trace_id", "span_id", "parent_id", "attrs",
        "_t0_wall", "_t0", "_token", "_done",
    )

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional[str], attrs: dict):
        self._tracer = tracer
        self.name = name
        self.trace_id = tracer.trace_id
        self.span_id = _new_id(4)
        self.parent_id = parent
        self.attrs = attrs
        self._t0_wall = time.time()
        self._t0 = time.perf_counter()
        self._token = None
        self._done = False

    def annotate(self, **attrs) -> None:
        """Attach attributes after construction (stage timings, outcomes)."""
        self.attrs.update(attrs)

    def end(self, error: Optional[str] = None, **attrs) -> None:
        """Seal the span and write its record; idempotent (the first end
        wins — a double end from an exception path plus a finally block
        must not duplicate the record)."""
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        rec = {
            "kind": "span",
            "schema": SPAN_SCHEMA_VERSION,
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "ts_unix": round(self._t0_wall, 6),
            "dur_ms": round(dur_ms, 3),
            "status": "error" if error else "ok",
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "thread": threading.current_thread().name,
        }
        if error:
            rec["error"] = str(error)[:500]
        if self.attrs:
            rec["attrs"] = self.attrs
        self._tracer._write(rec)

    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        self.end(
            error=f"{exc_type.__name__}: {exc}" if exc_type else None
        )
        return False

    def __bool__(self) -> bool:
        return True


class Tracer:
    """Span factory bound to one trace id and one sink.

    Disabled by default; :meth:`enable` (or the ``DGRAPH_TRACE=1``
    environment, read once at import) turns it on. The hot call is
    :meth:`span`: disabled, it is one attribute read returning
    :data:`NOOP_SPAN`.
    """

    def __init__(self):
        self._enabled = False
        self.trace_id: Optional[str] = None
        self._root_parent: Optional[str] = None
        self._sink = None
        self._sink_path: Optional[str] = None

    # --- lifecycle ---

    def enable(self, sink=None, trace_id: Optional[str] = None,
               parent_id: Optional[str] = None) -> str:
        """Turn tracing on; returns the active trace id.

        ``sink`` is a path, a ``write(dict)`` object (ExperimentLog), or a
        callable taking the record dict; None keeps/creates the default
        file sink (``DGRAPH_TRACE_PATH`` or ``logs/spans.jsonl``).
        ``trace_id=None`` keeps the current id (or mints one);
        ``parent_id`` roots this process's parentless spans under an
        inherited span (cross-process lineage)."""
        if sink is not None:
            self._set_sink(sink)
        elif self._sink is None:
            self._set_sink(os.environ.get(ENV_PATH) or DEFAULT_PATH)
        if trace_id is not None:
            self.trace_id = trace_id
        elif self.trace_id is None:
            self.trace_id = _new_id(8)
        if parent_id is not None:
            self._root_parent = parent_id or None
        self._enabled = True
        return self.trace_id

    def disable(self) -> None:
        """Turn tracing off (the hot path reverts to the no-op span) and
        drop the trace context so a later enable() starts fresh."""
        self._enabled = False
        self.trace_id = None
        self._root_parent = None
        self._sink = None
        self._sink_path = None

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _set_sink(self, sink) -> None:
        if isinstance(sink, str):
            self._sink = _FileSink(sink)
            self._sink_path = sink
        else:
            self._sink = sink
            self._sink_path = getattr(sink, "path", None)

    def configure_from_env(self, environ=None) -> bool:
        """Enable iff ``DGRAPH_TRACE`` is truthy in ``environ`` (default
        ``os.environ``) — the child-process half of :func:`child_env`."""
        if environ is None:
            environ = os.environ
        if str(environ.get(ENV_ENABLE, "")).lower() not in ("1", "true", "on"):
            return False
        self.enable(
            sink=environ.get(ENV_PATH) or DEFAULT_PATH,
            trace_id=environ.get(ENV_TRACE_ID) or None,
            parent_id=environ.get(ENV_PARENT) or None,
        )
        return True

    # --- the hot call ---

    def span(self, name: str, parent=None, **attrs):
        """Start a span. Disabled: one attribute read, returns the shared
        no-op. ``parent`` accepts a Span, a span-id string, or None (the
        ambient current span, else the inherited cross-process root)."""
        if not self._enabled:
            return NOOP_SPAN
        if parent is None:
            cur = _CURRENT.get()
            parent_id = cur.span_id if cur is not None else self._root_parent
        elif isinstance(parent, str):
            parent_id = parent
        else:
            parent_id = getattr(parent, "span_id", None)
        return Span(self, name, parent_id, dict(attrs))

    def _write(self, rec: dict) -> None:
        sink = self._sink
        if sink is None:
            return
        try:
            if callable(sink) and not hasattr(sink, "write"):
                sink(rec)
            else:
                sink.write(rec)
        except Exception:  # tracing must never take down the traced run
            pass

    # --- cross-process lineage ---

    def child_env(self, parent=None) -> dict:
        """Environment fragment that makes a child process join this
        trace: empty when disabled (children inherit the off state), else
        ``DGRAPH_TRACE``/``_ID``/``_PARENT``/``_PATH``. ``parent`` pins
        the child's root parent (default: the ambient current span)."""
        if not self._enabled:
            return {}
        if parent is None:
            parent = _CURRENT.get()
        parent_id = getattr(parent, "span_id", None) or (
            parent if isinstance(parent, str) else None
        )
        env = {ENV_ENABLE: "1", ENV_TRACE_ID: self.trace_id or ""}
        env[ENV_PARENT] = parent_id or ""
        if self._sink_path:
            env[ENV_PATH] = self._sink_path
        return env


# the process-wide default tracer; auto-enabled when the parent process
# exported DGRAPH_TRACE=1 (see child_env)
default_tracer = Tracer()
default_tracer.configure_from_env()


def span(name: str, parent=None, **attrs):
    """Module-level :meth:`Tracer.span` on the default tracer (the form
    call sites use; one attr read when disabled)."""
    return default_tracer.span(name, parent=parent, **attrs)


def enable(sink=None, trace_id: Optional[str] = None,
           parent_id: Optional[str] = None) -> str:
    return default_tracer.enable(sink, trace_id, parent_id)


def disable() -> None:
    default_tracer.disable()


def enabled() -> bool:
    return default_tracer.enabled


def current_span():
    """The innermost open context-managed span of this thread, or None."""
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    """The active trace id: the default tracer's when enabled, else the
    inherited ``DGRAPH_TRACE_ID`` (a child whose own tracing is off still
    reports the lineage id), else None."""
    if default_tracer.enabled:
        return default_tracer.trace_id
    return os.environ.get(ENV_TRACE_ID) or None


def child_env(parent=None) -> dict:
    return default_tracer.child_env(parent)


# ---------------------------------------------------------------------------
# Perfetto (Chrome trace JSON) export
# ---------------------------------------------------------------------------


def read_spans(path: str) -> list:
    """Span records from a JSONL file (non-span kinds and unparseable
    lines are skipped — span logs interleave with other records when the
    sink is a shared ExperimentLog)."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "span":
                out.append(rec)
    return out


def export_perfetto(records, out_path: Optional[str] = None) -> dict:
    """Convert span records to Chrome trace JSON (the Perfetto / chrome://
    tracing format): one complete event (``ph="X"``) per span, wall-clock
    microsecond timestamps, pid/tid preserved so supervisor and child
    processes land on separate tracks. ``records`` is a list of span
    dicts or a JSONL path; ``out_path`` writes the JSON too."""
    if isinstance(records, str):
        records = read_spans(records)
    events = []
    procs = set()
    for r in records:
        if r.get("kind") != "span":
            continue
        attrs = dict(r.get("attrs") or {})
        pid = int(r.get("pid", 0))
        tid = int(r.get("tid", 0))
        args = {
            "trace": r.get("trace"),
            "span": r.get("span"),
            "parent": r.get("parent"),
            "status": r.get("status", "ok"),
            **attrs,
        }
        if r.get("error"):
            args["error"] = r["error"]
        events.append({
            "ph": "X",
            "name": r.get("name", "?"),
            "cat": str(attrs.get("component", r.get("name", "span"))
                       ).split(".")[0],
            "ts": round(float(r.get("ts_unix", 0.0)) * 1e6, 3),
            "dur": max(round(float(r.get("dur_ms", 0.0)) * 1e3, 3), 0.0),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
        procs.add(pid)
    for pid in sorted(procs):
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"dgraph pid {pid}"},
        })
    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "dgraph_tpu.obs.spans",
                      "schema": SPAN_SCHEMA_VERSION},
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as fh:
            json.dump(trace, fh)
    return trace


# ---------------------------------------------------------------------------
# CLI: --export perfetto + the compile-free selftest scripts/check.py runs
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Config:
    """Span tracing CLI (``--export perfetto`` converts a span JSONL to
    Chrome trace JSON; ``--selftest`` is the compile-free tier-1 smoke)."""

    selftest: bool = False
    export: str = ""  # "perfetto"
    input: str = DEFAULT_PATH
    output: str = ""  # default: <input>.perfetto.json
    indent: int = 0


def _selftest() -> dict:
    failures: list = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    t = Tracer()
    # disabled == the shared no-op, before AND after an enable/disable
    # round trip (one attr read is the whole cost)
    check(t.span("x") is NOOP_SPAN, "disabled tracer did not return the "
                                    "shared no-op span")
    recs: list = []
    tid = t.enable(sink=recs.append, trace_id="feedbeef00000000")
    check(tid == "feedbeef00000000", "enable() did not adopt the trace id")
    with t.span("outer", stage="s0") as outer:
        with t.span("inner") as inner:
            check(inner.parent_id == outer.span_id,
                  "nested span did not parent to the enclosing span")
        manual = t.span("manual", parent=outer)
        manual.end(error="boom", n=3)
    check(len(recs) == 3, f"expected 3 span records, got {len(recs)}")
    by_name = {r["name"]: r for r in recs}
    check(set(by_name) == {"outer", "inner", "manual"}, "span names lost")
    check(all(r["trace"] == tid for r in recs), "trace id not propagated")
    check(by_name["outer"]["parent"] is None, "root span grew a parent")
    check(by_name["manual"]["status"] == "error"
          and by_name["manual"]["attrs"]["n"] == 3,
          "manual end(error=..., **attrs) not recorded")
    check(by_name["inner"]["dur_ms"] <= by_name["outer"]["dur_ms"],
          "child span outlasted its parent")
    # cross-process lineage: a child tracer built from child_env joins
    with t.span("parent-of-child") as pspan:
        env = t.child_env()
    child = Tracer()
    check(child.configure_from_env(env), "child_env did not enable the child")
    child._set_sink(recs.append)
    child.span("child-root").end()
    check(recs[-1]["trace"] == tid and recs[-1]["parent"] == pspan.span_id,
          "child tracer did not join the parent trace/span")
    t.disable()
    check(t.span("x") is NOOP_SPAN, "disable() did not restore the no-op")
    check(t.child_env() == {}, "disabled child_env must be empty")
    # perfetto export: valid Chrome trace shape
    trace = export_perfetto(recs)
    check(isinstance(trace["traceEvents"], list), "no traceEvents list")
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    check(len(xs) == len(recs), "X-event count != span count")
    check(all(
        {"name", "ts", "dur", "pid", "tid", "args"} <= set(e) for e in xs
    ), "X event missing required fields")
    json.dumps(trace)  # must be serializable as-is
    return {"kind": "spans_selftest", "failures": failures,
            "spans_checked": len(recs)}


def main(cfg: Config) -> dict:
    if cfg.selftest:
        out = _selftest()
        print(json.dumps(out, indent=cfg.indent or None))
        if out["failures"]:
            raise SystemExit(
                "spans selftest FAILED: " + "; ".join(out["failures"])
            )
        return out
    if cfg.export:
        if cfg.export != "perfetto":
            raise SystemExit(f"unknown export format {cfg.export!r} "
                             "(supported: perfetto)")
        out_path = cfg.output or cfg.input + ".perfetto.json"
        trace = export_perfetto(cfg.input, out_path)
        traces = sorted({
            e["args"].get("trace") for e in trace["traceEvents"]
            if e["ph"] == "X"
        } - {None})
        summary = {
            "kind": "perfetto_export",
            "input": cfg.input,
            "output": out_path,
            "events": sum(1 for e in trace["traceEvents"] if e["ph"] == "X"),
            "traces": traces,
        }
        print(json.dumps(summary, indent=cfg.indent or None))
        return summary
    raise SystemExit("nothing to do: pass --export perfetto or --selftest")


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
