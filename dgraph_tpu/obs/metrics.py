"""Runtime metrics: host-side registry + the per-step aux pytree.

Two pieces with one rule — observability must cost nothing when off:

- :class:`Metrics`: a plain host-side registry of counters, gauges, and
  histograms with quantile snapshots (plan-build walltimes, cache hits,
  serve latency percentiles…).  Never traced; safe to call anywhere,
  including from the serve batcher's threads.
- :class:`StepMetrics`: the aux pytree a jitted train step returns when
  built with ``step_metrics=True`` (``train.loop.make_train_step``).  The
  flag is a Python build-time constant, so the disabled step traces to the
  byte-identical program it always had — zero device overhead and zero
  extra recompiles (pinned by tests/test_obs.py's cache-hit assertion).

One step -> one JSONL record: ``StepMetrics.record()`` coerces device
scalars to floats and stamps the schema, ``ExperimentLog.write`` appends
it.  ``StepMetrics.from_record`` round-trips the schema for readers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from dgraph_tpu.plan import pytree_dataclass

STEP_SCHEMA_VERSION = 1

# fields serialized into / parsed out of a step record, in schema order.
# nonfinite_skipped (0.0/1.0) is set only by guard-enabled steps
# (train.loop.make_train_step(nonfinite_guard=True)) — additive, so
# schema 1 readers are unaffected (unset fields never serialize).
_STEP_FIELDS = ("loss", "accuracy", "grad_norm", "mask_count",
                "nonfinite_skipped")


@pytree_dataclass
class StepMetrics:
    """Aux pytree threaded out of the jitted train step.

    Leaves are device scalars inside jit; ``record()`` is the host-side
    exit point. Unset fields (None) vanish from the pytree and the record
    — a model without a mask simply never reports ``mask_count``.
    """

    loss: Any = None
    accuracy: Any = None
    grad_norm: Any = None
    mask_count: Any = None
    nonfinite_skipped: Any = None  # 0.0/1.0 from the non-finite step guard

    # dict-style access so call sites written against the legacy metrics
    # dict (``m["loss"]``) take a StepMetrics unchanged
    def __getitem__(self, key: str):
        if key not in _STEP_FIELDS:
            raise KeyError(key)
        return getattr(self, key)

    def record(self, **extra) -> dict:
        """One JSONL-ready dict: floats only, schema-stamped. ``extra``
        carries host-side context (step index, wall_ms, lr...)."""
        out = {"kind": "step", "schema": STEP_SCHEMA_VERSION}
        for name in _STEP_FIELDS:
            v = getattr(self, name)
            if v is not None:
                out[name] = float(v)
        out.update(extra)
        return out

    @classmethod
    def from_record(cls, rec: dict) -> "StepMetrics":
        """Inverse of :meth:`record` (reader side; extras are dropped)."""
        if rec.get("kind") != "step":
            raise ValueError(f"not a step record: kind={rec.get('kind')!r}")
        return cls(**{k: rec[k] for k in _STEP_FIELDS if k in rec})


# quantiles every histogram snapshot reports: the serving SLO trio
DEFAULT_QUANTILES = (0.5, 0.95, 0.99)


def _q_label(q: float) -> str:
    """0.5 -> 'p50', 0.95 -> 'p95', 0.999 -> 'p99.9'."""
    return "p" + format(q * 100, "g")


class _Histogram:
    """Bounded-memory histogram: count/mean/min/max are exact running
    aggregates; quantiles come from a fixed-size uniform reservoir
    (Vitter's algorithm R, deterministic seed), so a serving process
    observing millions of latencies holds at most ``MAX_SAMPLES`` floats
    per histogram and a snapshot sort is O(MAX_SAMPLES log MAX_SAMPLES)
    under the registry lock. Quantiles are exact until ``MAX_SAMPLES``
    observations, then unbiased estimates."""

    MAX_SAMPLES = 4096

    __slots__ = ("count", "total", "vmin", "vmax", "values", "_rng")

    def __init__(self):
        import random

        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.values: list = []  # uniform sample of the observations
        self._rng = random.Random(0x5EED)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        self.vmin = v if v < self.vmin else self.vmin
        self.vmax = v if v > self.vmax else self.vmax
        if len(self.values) < self.MAX_SAMPLES:
            self.values.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.MAX_SAMPLES:
                self.values[j] = v

    def quantile(self, q: float) -> float:
        """Empirical quantile with linear interpolation between order
        statistics (numpy's default 'linear' method, so snapshots agree
        with offline np.percentile analysis of the same JSONL). Raises
        ValueError on an empty histogram or q outside [0, 1]."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if not self.values:
            raise ValueError("quantile of an empty histogram")
        s = sorted(self.values)
        pos = q * (len(s) - 1)
        lo = int(pos)
        frac = pos - lo
        if frac == 0.0:
            return s[lo]
        return s[lo] + (s[lo + 1] - s[lo]) * frac

    def snapshot(self, quantiles: tuple = DEFAULT_QUANTILES) -> dict:
        if not self.count:
            return {"count": 0}
        out = {
            "count": self.count,
            "mean": self.total / self.count,
            "min": self.vmin,
            "max": self.vmax,
        }
        for q in quantiles:
            out[_q_label(q)] = self.quantile(q)
        return out


class Metrics:
    """Host-side metrics registry; snapshot() is JSON-ready. Guarded by one
    lock so concurrent producers (the serve micro-batcher's worker thread +
    client submit threads) can share a registry; the per-call cost is one
    uncontended mutex, nothing on the device path."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}

    def counter(self, name: str, inc: float = 1.0) -> float:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + float(inc)
            return self._counters[name]

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def histogram(self, name: str, value: float) -> None:
        with self._lock:
            self._histograms.setdefault(name, _Histogram()).observe(value)

    def quantile(self, name: str, q: float) -> float:
        """Quantile of a recorded histogram (KeyError if it was never
        observed) — the accessor serve latency percentiles read."""
        with self._lock:
            return self._histograms[name].quantile(q)

    def snapshot(self, quantiles: tuple = DEFAULT_QUANTILES) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: h.snapshot(quantiles) for k, h in self._histograms.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


default_registry = Metrics()


def step_record(
    metrics,
    *,
    step: int,
    wall_ms: Optional[float] = None,
    **extra,
) -> dict:
    """Record-builder that takes either a :class:`StepMetrics` or the
    legacy metrics dict, so experiments can log one schema regardless of
    which form their step returns."""
    if not isinstance(metrics, StepMetrics):
        metrics = StepMetrics(
            **{k: metrics[k] for k in _STEP_FIELDS if k in metrics}
        )
    if wall_ms is not None:
        extra["wall_ms"] = round(float(wall_ms), 3)
    return metrics.record(step=int(step), **extra)
