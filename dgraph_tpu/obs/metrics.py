"""Runtime metrics: host-side registry + the per-step aux pytree.

Two pieces with one rule — observability must cost nothing when off:

- :class:`Metrics`: a plain host-side registry of counters, gauges, and
  histograms (plan-build walltimes, cache hits, probe retries…).  Never
  traced; safe to call anywhere.
- :class:`StepMetrics`: the aux pytree a jitted train step returns when
  built with ``step_metrics=True`` (``train.loop.make_train_step``).  The
  flag is a Python build-time constant, so the disabled step traces to the
  byte-identical program it always had — zero device overhead and zero
  extra recompiles (pinned by tests/test_obs.py's cache-hit assertion).

One step -> one JSONL record: ``StepMetrics.record()`` coerces device
scalars to floats and stamps the schema, ``ExperimentLog.write`` appends
it.  ``StepMetrics.from_record`` round-trips the schema for readers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from dgraph_tpu.plan import pytree_dataclass

STEP_SCHEMA_VERSION = 1

# fields serialized into / parsed out of a step record, in schema order
_STEP_FIELDS = ("loss", "accuracy", "grad_norm", "mask_count")


@pytree_dataclass
class StepMetrics:
    """Aux pytree threaded out of the jitted train step.

    Leaves are device scalars inside jit; ``record()`` is the host-side
    exit point. Unset fields (None) vanish from the pytree and the record
    — a model without a mask simply never reports ``mask_count``.
    """

    loss: Any = None
    accuracy: Any = None
    grad_norm: Any = None
    mask_count: Any = None

    # dict-style access so call sites written against the legacy metrics
    # dict (``m["loss"]``) take a StepMetrics unchanged
    def __getitem__(self, key: str):
        if key not in _STEP_FIELDS:
            raise KeyError(key)
        return getattr(self, key)

    def record(self, **extra) -> dict:
        """One JSONL-ready dict: floats only, schema-stamped. ``extra``
        carries host-side context (step index, wall_ms, lr...)."""
        out = {"kind": "step", "schema": STEP_SCHEMA_VERSION}
        for name in _STEP_FIELDS:
            v = getattr(self, name)
            if v is not None:
                out[name] = float(v)
        out.update(extra)
        return out

    @classmethod
    def from_record(cls, rec: dict) -> "StepMetrics":
        """Inverse of :meth:`record` (reader side; extras are dropped)."""
        if rec.get("kind") != "step":
            raise ValueError(f"not a step record: kind={rec.get('kind')!r}")
        return cls(**{k: rec[k] for k in _STEP_FIELDS if k in rec})


class _Histogram:
    __slots__ = ("values",)

    def __init__(self):
        self.values: list = []

    def observe(self, v: float) -> None:
        self.values.append(float(v))

    def snapshot(self) -> dict:
        import numpy as np

        if not self.values:
            return {"count": 0}
        a = np.asarray(self.values)
        return {
            "count": int(a.size),
            "mean": float(a.mean()),
            "min": float(a.min()),
            "max": float(a.max()),
            "p50": float(np.percentile(a, 50)),
            "p95": float(np.percentile(a, 95)),
        }


class Metrics:
    """Host-side metrics registry. Not thread-safe by design (the training
    driver is single-threaded); snapshot() is JSON-ready."""

    def __init__(self):
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}

    def counter(self, name: str, inc: float = 1.0) -> float:
        self._counters[name] = self._counters.get(name, 0.0) + float(inc)
        return self._counters[name]

    def gauge(self, name: str, value: float) -> None:
        self._gauges[name] = float(value)

    def histogram(self, name: str, value: float) -> None:
        self._histograms.setdefault(name, _Histogram()).observe(value)

    def snapshot(self) -> dict:
        return {
            "counters": dict(self._counters),
            "gauges": dict(self._gauges),
            "histograms": {k: h.snapshot() for k, h in self._histograms.items()},
        }

    def reset(self) -> None:
        self._counters.clear()
        self._gauges.clear()
        self._histograms.clear()


default_registry = Metrics()


def step_record(
    metrics,
    *,
    step: int,
    wall_ms: Optional[float] = None,
    **extra,
) -> dict:
    """Record-builder that takes either a :class:`StepMetrics` or the
    legacy metrics dict, so experiments can log one schema regardless of
    which form their step returns."""
    if not isinstance(metrics, StepMetrics):
        metrics = StepMetrics(
            **{k: metrics[k] for k in _STEP_FIELDS if k in metrics}
        )
    if wall_ms is not None:
        extra["wall_ms"] = round(float(wall_ms), 3)
    return metrics.record(step=int(step), **extra)
