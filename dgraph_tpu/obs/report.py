"""Longitudinal trajectory report over the perf ledger: "what did PR N
do to perf" as one command.

Renders the ledger (:mod:`dgraph_tpu.obs.ledger`) as a markdown
artifact: the bench-round table (real-chip epoch times AND the wedge
history — a round that never reached a chip is part of the trajectory,
not a gap), then one table per record kind with each metric's latest
value, its delta against the previous entry, and a sparkline over the
trailing window. jax-free + stdlib-only by the same lint-enforced
contract as the ledger: the trajectory must be readable on a machine
where jax is wedged or absent.

CLI::

    python -m dgraph_tpu.obs.report                     # active ledger
    python -m dgraph_tpu.obs.report --dir cache/plans --out TRAJECTORY.md
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from dgraph_tpu.obs.ledger import (
    DEFAULT_LEDGER_DIR,
    LEDGER_SCHEMA_VERSION,
    ledger_path,
    read_ledger,
    resolve_ledger_dir,
)

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: list, width: int = 16) -> str:
    """Unicode sparkline of a numeric series (trailing ``width`` points).
    A constant series renders mid-block — flat is a shape too."""
    vs = [v for v in values if isinstance(v, (int, float))][-width:]
    if not vs:
        return ""
    lo, hi = min(vs), max(vs)
    if hi == lo:
        return _SPARK_BLOCKS[3] * len(vs)
    scale = (len(_SPARK_BLOCKS) - 1) / (hi - lo)
    return "".join(_SPARK_BLOCKS[int((v - lo) * scale)] for v in vs)


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _delta(prev, latest) -> str:
    if not isinstance(prev, (int, float)) or not isinstance(
        latest, (int, float)
    ):
        return "—"
    d = latest - prev
    if d == 0:
        return "="
    pct = f" ({d / prev:+.1%})" if prev else ""
    return f"{d:+.4g}{pct}"


def _round_rows(entries: list) -> list:
    rows = []
    for e in entries:
        if e.get("kind") not in ("bench_round", "probe_wedge"):
            continue
        m = e.get("metrics") or {}
        meta = e.get("meta") or {}
        note = ""
        if e["kind"] == "probe_wedge":
            note = (meta.get("error") or meta.get("last_line")
                    or "wedged")[:60]
        elif meta.get("wedge"):
            note = json.dumps(meta["wedge"])[:60]
        rows.append({
            "round": e.get("round"),
            "source": e.get("source"),
            "kind": e["kind"],
            "epoch_ms": m.get("epoch_time_ms"),
            "vs_baseline": m.get("vs_baseline"),
            "graphcast_ms": m.get("graphcast_step_ms"),
            "git_rev": e.get("git_rev"),
            "note": note,
        })
    return rows


def render_trajectory(entries: list, *, directory: str = "",
                      width: int = 16) -> str:
    """The full markdown artifact for one ledger's entry list."""
    lines = [
        "# Perf trajectory",
        "",
        f"*Ledger: `{ledger_path(directory) if directory else '(in-memory)'}`"
        f" — {len(entries)} entries, schema {LEDGER_SCHEMA_VERSION}.*",
        "",
    ]
    if not entries:
        lines += ["(empty ledger — run `python -m dgraph_tpu.obs.ledger "
                  "--backfill <repo-root>` to seed it)", ""]
        return "\n".join(lines)

    # --- bench rounds: the headline table -------------------------------
    rows = _round_rows(entries)
    if rows:
        lines += ["## Bench rounds", ""]
        lines += ["| round | source | epoch ms | vs baseline | "
                  "graphcast ms | git rev | note |",
                  "|---|---|---|---|---|---|---|"]
        for r in rows:
            epoch = (f"{r['epoch_ms']:.1f}"
                     if isinstance(r["epoch_ms"], (int, float)) else
                     ("WEDGED" if r["kind"] == "probe_wedge" else "—"))
            lines.append(
                f"| {_fmt(r['round'])} | {r['source']} | {epoch} | "
                f"{_fmt(r['vs_baseline'])} | {_fmt(r['graphcast_ms'])} | "
                f"{r['git_rev']} | {r['note']} |")
        epochs = [r["epoch_ms"] for r in rows
                  if isinstance(r["epoch_ms"], (int, float))]
        if epochs:
            lines += ["",
                      f"epoch ms trend: `{sparkline(epochs, width)}` "
                      f"(latest {epochs[-1]:.1f} ms over {len(epochs)} "
                      f"measured round(s))"]
        lines.append("")

    # --- every other kind: per-(workload, lowering) metric tables -------
    by_kind: dict = {}
    for e in entries:
        if e.get("kind") in ("bench_round", "probe_wedge",
                             "reference_note"):
            continue
        key = (e["kind"], e.get("workload"), e.get("halo_impl"))
        by_kind.setdefault(e["kind"], {}).setdefault(key, []).append(e)
    for kind in sorted(by_kind):
        lines += [f"## {kind}", ""]
        for (_, workload, halo_impl), group in sorted(
            by_kind[kind].items(), key=lambda kv: str(kv[0])
        ):
            label = workload + (f" / {halo_impl}" if halo_impl else "")
            lines += [f"### {label}", "",
                      "| metric | latest | Δ prev | trend |",
                      "|---|---|---|---|"]
            series: dict = {}
            for e in group:
                for metric, v in (e.get("metrics") or {}).items():
                    series.setdefault(metric, []).append(v)
            for metric in sorted(series):
                vs = series[metric]
                prev = vs[-2] if len(vs) > 1 else None
                lines.append(
                    f"| {metric} | {_fmt(vs[-1])} | "
                    f"{_delta(prev, vs[-1])} | "
                    f"`{sparkline(vs, width)}` |")
            lines.append("")

    refs = [e for e in entries if e.get("kind") == "reference_note"]
    if refs:
        lines += ["## Reference", ""]
        for e in refs:
            meta = e.get("meta") or {}
            lines.append(f"- `{e.get('workload')}` "
                         f"(source `{e.get('source')}`): "
                         f"{meta.get('reference_repo', '')}")
        lines.append("")
    return "\n".join(lines)


def _selftest() -> dict:
    """Render the regress fixtures + an empty ledger without crashing,
    and pin the headline pieces the render must carry."""
    import tempfile

    # submodule form, not `from dgraph_tpu.obs import ...`: naming the
    # package would flag the jax-free lint (its __init__ pulls jax)
    from dgraph_tpu.obs.regress import _seed

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    check(render_trajectory([]).strip(), "empty ledger rendered nothing")
    check(sparkline([1.0, 1.0]) == _SPARK_BLOCKS[3] * 2,
          "constant-series sparkline broke")
    with tempfile.TemporaryDirectory(prefix="dgraph_report_selftest_") as tmp:
        _seed(tmp)
        entries, _ = read_ledger(tmp)
        md = render_trajectory(entries, directory=tmp)
        for want in ("## Bench rounds", "## cpu_scan_delta",
                     "## serve_health", "## sched_compile",
                     "## wire_compile", "## grow_transition",
                     "operand_bytes", "exchange_ms", "p99_ms",
                     "new_world_count", "450."):
            check(want in md, f"rendered trajectory lacks {want!r}")
    return {"kind": "report_selftest", "failures": failures,
            "ok": not failures}


@dataclasses.dataclass
class Config:
    """Trajectory report CLI: render the active ledger as markdown (to
    stdout, or ``--out <path>``)."""

    dir: str = ""    # ledger dir ("" = DGRAPH_LEDGER_DIR or default)
    out: str = ""    # output markdown path ("" = stdout)
    width: int = 16  # sparkline window
    selftest: bool = False
    indent: int = 0


def main(cfg: Config) -> Optional[str]:
    if cfg.selftest:
        out = _selftest()
        print(json.dumps(out, indent=cfg.indent or None))
        if out["failures"]:
            raise SystemExit(1)
        return None
    directory = (cfg.dir or resolve_ledger_dir(default_on=True)
                 or DEFAULT_LEDGER_DIR)
    entries, skips = read_ledger(directory)
    md = render_trajectory(entries, directory=directory, width=cfg.width)
    if skips:
        md += f"\n*({len(skips)} undecodable ledger line(s) skipped.)*\n"
    if cfg.out:
        with open(cfg.out, "w") as fh:  # a regenerable view, not a
            fh.write(md)                # durable artifact
        print(f"wrote {cfg.out} ({len(md)} chars)")
    else:
        print(md)
    return md


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
