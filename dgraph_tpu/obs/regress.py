"""Drift sentinel over the perf-trajectory ledger: noise-aware
regression gating with per-(metric, workload, halo lowering) baselines.

A priced number is only useful if drift against it is detected. This
module turns the ledger (:mod:`dgraph_tpu.obs.ledger`) into a gate:

- **Exact class** — the byte-exact metrics (traced/lowered/footprint
  bytes, collective counts, the SPMD identity bit): these are outputs of
  deterministic lowering, so they must never drift *at all*. Any change
  vs the previous entry is RED with zero tolerance.
- **Timing class** — wall-clock metrics (cpu_scan_delta phase ms, serve
  p50/p95/p99, bench epoch ms): baselined by the median of a trailing
  window with a MAD-scaled tolerance (median absolute deviation × 1.4826
  estimates sigma for normal noise), floored so shared-CPU jitter can't
  flap the gate. Only regressions (latest above median + tolerance) go
  RED — getting faster is the point, not an alarm.
- **Dropped-tier** — a bench round that silently loses one of the four
  fallback tiers (schedule_drift / cpu_scan_delta / hlo_drift /
  spmd_drift) regressed the *observability*, which is exactly how a perf
  regression next hides; the sentinel compares each round's tier set
  against the previous round's.

Verdicts are structured (GREEN / RED / NO_BASELINE) and carry the
offending ledger entry ids. ``python -m dgraph_tpu.obs.regress`` exits
nonzero on any RED and writes a RunHealth + report record to a JSONL
log on every exit path (a stdlib sink with the ExperimentLog line
format — ``utils.logging.ExperimentLog`` itself imports jax, which this
module may not: it is jax-free by the same lint-enforced contract as the
ledger, and runs on a machine where jax is wedged or absent).

``--selftest`` seeds a synthetic trajectory and seven drifted mutants
(inflated wire bytes, slowed scan-delta, fattened p99, dropped tier,
drifted compiled schedule, drifted wire-format bytes, drifted grown
world) — each must go RED, and the clean trajectory must stay GREEN, or
the selftest itself fails (the vacuity guard: a sentinel that can't see
seeded drift gates nothing).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from dgraph_tpu.obs.health import RunHealth
from dgraph_tpu.obs.ledger import (
    DEFAULT_LEDGER_DIR,
    TIER_KINDS,
    atomic_append_jsonl,
    ingest,
    read_ledger,
    resolve_ledger_dir,
)

# --- metric classification -------------------------------------------------

# byte-exact outputs of deterministic lowering: zero tolerance
EXACT_SUFFIXES = ("_bytes", "_count", "_collectives")
EXACT_NAMES = frozenset({
    "identical",                 # spmd_drift: ranks agree on the schedule
    "drift",                     # any tier's own drift verdict bit
    "n_families",                # multichip dryrun family coverage
    "recompiles_since_warmup",   # serving steady-state SLO: must be 0
})

# wall-clock metrics: median + MAD window
TIMING_SUFFIXES = ("_ms", "_us")
TIMING_NAMES = frozenset({"vs_baseline"})  # ratio of the primary metric

# numbers stored for context, not gated (wall budgets, exit codes, ...)
IGNORE_NAMES = frozenset({
    "wall_s", "warmup_s", "rc", "final_exit_code", "restarts", "attempts",
    "requests", "queue_depth", "n_tenants", "n_probes", "final_world",
})

# tolerance model (documented in docs/perf-ledger.md; tests pin the math)
MIN_TIMING_BASELINE = 3   # fewer prior points -> NO_BASELINE
K_MAD = 4.0               # tolerance = K_MAD * 1.4826 * MAD ...
REL_FLOOR = 0.25          # ... floored at 25% of the median ...
ABS_FLOOR = 0.5           # ... and at 0.5 (ms/us) absolute

_MAD_SIGMA = 1.4826  # MAD -> sigma for normally-distributed noise


def metric_class(name: str) -> str:
    """'exact' | 'timing' | 'info' for one normalized metric name."""
    if name in IGNORE_NAMES:
        return "info"
    base = name.split("/", 1)[0]  # "step_ms/GCN" classifies as step_ms
    if base in EXACT_NAMES or base.endswith(EXACT_SUFFIXES):
        return "exact"
    if base in TIMING_NAMES or base.endswith(TIMING_SUFFIXES):
        return "timing"
    return "info"


def baseline_stats(values: list) -> dict:
    """Median + MAD of a series (the noise-aware baseline for the timing
    class), plus the derived tolerance."""
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    median = vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0
    devs = sorted(abs(v - median) for v in values)
    mad = devs[mid] if n % 2 else (devs[mid - 1] + devs[mid]) / 2.0
    tol = max(K_MAD * _MAD_SIGMA * mad, REL_FLOOR * abs(median), ABS_FLOOR)
    return {"median": median, "mad": mad, "tolerance": tol, "n": n}


# --- verdicts --------------------------------------------------------------


def _series(entries: list) -> dict:
    """(kind, workload, halo_impl, metric) -> ordered [(value, entry_id)].
    File order is ingestion order — the trajectory's arrow of time."""
    out: dict = {}
    for e in entries:
        for metric, value in (e.get("metrics") or {}).items():
            key = (e.get("kind"), e.get("workload"), e.get("halo_impl"),
                   metric)
            out.setdefault(key, []).append((value, e.get("entry_id")))
    return out


def _verdict(key, points, window: int) -> Optional[dict]:
    kind, workload, halo_impl, metric = key
    cls = metric_class(metric)
    if cls == "info" or len(points) == 0:
        return None
    latest_v, latest_id = points[-1]
    history = points[:-1][-window:]
    base = {
        "kind": kind, "workload": workload, "halo_impl": halo_impl,
        "metric": metric, "class": cls, "latest": latest_v,
        "entry_id": latest_id,
        "baseline_ids": [pid for _, pid in history],
    }
    if cls == "exact":
        if not history:
            return {**base, "verdict": "NO_BASELINE",
                    "reason": "no prior entry for an exact-class metric"}
        prev_v, prev_id = history[-1]
        if latest_v != prev_v:
            return {**base, "verdict": "RED",
                    "baseline": {"value": prev_v, "entry_id": prev_id},
                    "reason": f"exact-class metric drifted: {prev_v!r} -> "
                              f"{latest_v!r} (zero tolerance)"}
        return {**base, "verdict": "GREEN",
                "baseline": {"value": prev_v, "entry_id": prev_id}}
    # timing
    if len(history) < MIN_TIMING_BASELINE:
        return {**base, "verdict": "NO_BASELINE",
                "reason": f"{len(history)} prior points < "
                          f"{MIN_TIMING_BASELINE} needed for a "
                          f"median+MAD baseline"}
    stats = baseline_stats([v for v, _ in history])
    limit = stats["median"] + stats["tolerance"]
    if latest_v > limit:
        return {**base, "verdict": "RED", "baseline": stats,
                "reason": f"timing regression: {latest_v:.4g} > median "
                          f"{stats['median']:.4g} + tolerance "
                          f"{stats['tolerance']:.4g}"}
    return {**base, "verdict": "GREEN", "baseline": stats}


def round_groups(entries: list) -> list:
    """Bench rounds in trajectory order, each with the tier kinds that
    landed for it (a bench_round/probe_wedge entry heads a round; the
    tier entries ingested with it follow in file order)."""
    groups: list = []
    cur = None
    for e in entries:
        if e.get("kind") in ("bench_round", "probe_wedge"):
            cur = {"head_id": e.get("entry_id"), "round": e.get("round"),
                   "source": e.get("source"), "tiers": []}
            groups.append(cur)
        elif e.get("kind") in TIER_KINDS and cur is not None:
            if e["kind"] not in cur["tiers"]:
                cur["tiers"].append(e["kind"])
    return groups


def dropped_tier_verdicts(entries: list) -> list:
    """RED when the latest round lost a fallback tier the previous
    tier-bearing round had — silent observability loss is itself drift."""
    groups = round_groups(entries)
    if len(groups) < 2:
        return []
    last = groups[-1]
    prev = next((g for g in reversed(groups[:-1]) if g["tiers"]), None)
    if prev is None:
        return []
    missing = [t for t in prev["tiers"] if t not in last["tiers"]]
    if not missing:
        return []
    return [{
        "kind": "bench_round", "workload": "tiers", "halo_impl": None,
        "metric": "fallback_tiers", "class": "exact",
        "verdict": "RED", "entry_id": last["head_id"],
        "baseline_ids": [prev["head_id"]],
        "latest": last["tiers"], "baseline": {"tiers": prev["tiers"]},
        "reason": f"round dropped fallback tier(s) {missing} that the "
                  f"previous round ({prev['source']}) landed",
    }]


def check_ledger(
    directory: Optional[str] = None, entries: Optional[list] = None,
    *, window: int = 20,
) -> dict:
    """The sentinel: one structured ``regress_report`` over a ledger dir
    (or a pre-read entry list), RED iff any gated metric regressed."""
    skips: list = []
    if entries is None:
        entries, skips = read_ledger(directory)
    verdicts = [v for v in (
        _verdict(key, pts, window) for key, pts in _series(entries).items()
    ) if v is not None]
    verdicts += dropped_tier_verdicts(entries)
    order = {"RED": 0, "NO_BASELINE": 1, "GREEN": 2}
    verdicts.sort(key=lambda v: (order[v["verdict"]], str(v["metric"])))
    counts = {"RED": 0, "GREEN": 0, "NO_BASELINE": 0}
    for v in verdicts:
        counts[v["verdict"]] += 1
    return {
        "kind": "regress_report",
        "ok": counts["RED"] == 0,
        "dir": directory,
        "entries": len(entries),
        "counts": counts,
        "window": window,
        "verdicts": verdicts,
        "read_skips": skips,
    }


# ---------------------------------------------------------------------------
# selftest — seeded-drift vacuity mutants
# ---------------------------------------------------------------------------


def _fx_round(i: int, *, traced_bytes: int = 4096, exchange_ms: float = 20.0,
              include_hlo: bool = True) -> dict:
    """One synthetic bench round with the tiers the mutants perturb.
    ``i`` varies the timestamp (entry ids must differ per round) and adds
    deterministic sub-tolerance jitter to the timing series."""
    jitter = [0.0, 0.4, -0.2, 0.1, 0.3, -0.1, 0.2][i % 7]
    wl = {"world_size": 2, "nodes": 96, "edges": 400, "feat_dim": 8,
          "seed": 0}
    rec = {
        "metric": "arxiv_gcn_epoch_time", "value": 450.0 + jitter,
        "unit": "ms", "vs_baseline": (450.0 + jitter) / 456.898,
        "git_rev": f"rev{i:04d}",
        "run_health": {"child": {
            "started_at": f"2026-08-01T00:{i:02d}:00Z", "wedge": "none"}},
        "schedule_drift": {
            "kind": "schedule_drift", "workload": wl,
            "train_step_by_impl": {
                "all_to_all": {"collective_count": 3,
                               "traced_bytes": traced_bytes,
                               "footprint_bytes": traced_bytes},
                "overlap": {"collective_count": 4,
                            "traced_bytes": traced_bytes + 512,
                            "footprint_bytes": traced_bytes + 512},
            },
        },
        "cpu_scan_delta": {
            "kind": "cpu_scan_delta", "workload": wl,
            "by_impl": {"all_to_all": {
                "full_ms": 100.0 + jitter,
                "exchange_only_ms": exchange_ms + jitter,
                "exposed_exchange_ms": 10.0 + jitter,
                "phases_ms": {"interior": 60.0 + jitter,
                              "exchange": exchange_ms + jitter,
                              "optimizer": 15.0, "other": 5.0},
            }},
        },
    }
    if include_hlo:
        rec["hlo_drift"] = {
            "kind": "hlo_drift", "workload": wl,
            "train_step_by_impl": {
                "all_to_all": {"collective_count": 3, "lowered_bytes": 8192,
                               "footprint_bytes": 8192},
            },
        }
    return rec


def _fx_serve(i: int, *, p99: float = 50.0) -> dict:
    jitter = [0.0, 1.0, -0.5, 0.5, 0.8, -0.3, 0.2][i % 7]
    return {
        "kind": "serve_health", "schema_version": 1,
        "started_at": f"2026-08-01T01:{i:02d}:00Z",
        "tuning_record": "tune-fixture-v1",
        "recompiles_since_warmup": 0, "warmup_s": 2.0,
        "latency_ms": {"count": 100, "p50": 10.0 + jitter,
                       "p95": 30.0 + jitter, "p99": p99 + jitter},
        "stages_ms": {"infer": {"count": 100, "p99": 8.0 + jitter}},
    }


def _fx_sched(i: int, *, operand_bytes: int = 2048, rounds: int = 3) -> dict:
    """One compiled-schedule record (dgraph_tpu.sched -> obs.ledger
    ``sched_compile``). Shape metrics carry the exact-class suffixes, so
    the mutant's +64 bytes must go RED with zero tolerance."""
    jitter = [0.0, 0.4, -0.2, 0.1, 0.3, -0.1, 0.2][i % 7]
    return {
        "kind": "sched_compile",
        "workload": {"world_size": 2, "nodes": 96, "edges": 400,
                     "feat_dim": 8, "seed": 0},
        "schedule_id": "fixture0sched",
        "rounds": rounds, "transfers": 4,
        "operand_bytes_per_shard": operand_bytes,
        "round_rows": [64, 32, 32],
        "exposed_us": 12.0 + jitter,
        "git_rev": f"rev{i:04d}",
        "recorded_at": f"2026-08-01T02:{i:02d}:00Z",
    }


def _fx_wire(i: int, *, operand_bytes: int = 1024) -> dict:
    """One resolved-wire-format record (dgraph_tpu.wire -> obs.ledger
    ``wire_compile``). ``operand_bytes`` carries the exact-class suffix,
    so the mutant's +64 bytes must go RED with zero tolerance."""
    return {
        "kind": "wire_compile",
        "workload": {"world_size": 2, "nodes": 96, "edges": 400,
                     "feat_dim": 8, "seed": 0},
        "wire_format": "bf16", "wire_format_source": "tune",
        "operand_bytes": operand_bytes, "compression_ratio": 2.0,
        "git_rev": f"rev{i:04d}",
        "recorded_at": f"2026-08-01T03:{i:02d}:00Z",
    }


def _fx_grow(i: int, *, new_world: int = 3) -> dict:
    """One adopted grow transition (train.grow -> obs.ledger
    ``grow_transition``). The world/shard counts carry the exact-class
    ``_count`` suffixes, so the mutant's drifted world size must go RED
    with zero tolerance; ``replan_ms`` rides the timing gate."""
    jitter = [0.0, 0.4, -0.2, 0.1, 0.3, -0.1, 0.2][i % 7]
    return {
        "kind": "grow_transition",
        "generation": 1, "old_world": 2, "new_world": new_world,
        "resume_step": 3, "joined": ["newcomer-a"],
        "replan_s": (120.0 + jitter) / 1000.0, "shards": new_world,
        "git_rev": f"rev{i:04d}",
        "recorded_at": f"2026-08-01T04:{i:02d}:00Z",
    }


def _seed(tmp: str, n: int = 6) -> None:
    for i in range(n):
        ingest(_fx_round(i), f"fixture_r{i:02d}", tmp)
        ingest(_fx_serve(i), f"fixture_serve_r{i:02d}", tmp)
        ingest(_fx_sched(i), f"fixture_sched_r{i:02d}", tmp)
        ingest(_fx_wire(i), f"fixture_wire_r{i:02d}", tmp)
        ingest(_fx_grow(i), f"fixture_grow_r{i:02d}", tmp)


def _selftest() -> dict:
    """Clean trajectory GREEN + the seeded-drift mutants each RED."""
    import tempfile

    failures: list = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    def reds(report):
        return [v for v in report["verdicts"] if v["verdict"] == "RED"]

    # clean trajectory: zero RED, real GREENs, and every RED-capable
    # metric actually baselined (a gate with no baselines gates nothing)
    with tempfile.TemporaryDirectory(prefix="dgraph_regress_clean_") as tmp:
        _seed(tmp)
        report = check_ledger(tmp)
        check(report["ok"] and not reds(report),
              f"clean trajectory went RED: "
              f"{[v['reason'] for v in reds(report)]}")
        check(report["counts"]["GREEN"] >= 8,
              f"clean trajectory produced too few GREEN verdicts "
              f"({report['counts']}) — the gate is vacuous")

    mutants = {
        # 1. inflated wire bytes: +64 traced bytes is invisible to any
        # percentage tolerance — the exact class must catch it
        "inflated_wire_bytes": (
            lambda tmp: ingest(_fx_round(6, traced_bytes=4096 + 64),
                               "fixture_r06", tmp),
            "traced_bytes",
        ),
        # 2. slowed scan-delta: exchange phase 20 -> 36 ms, well past
        # median + max(MAD-scaled, 25%) tolerance
        "slowed_scan_delta": (
            lambda tmp: ingest(_fx_round(6, exchange_ms=36.0),
                               "fixture_r06", tmp),
            "exchange",
        ),
        # 3. fattened serve p99: 50 -> 120 ms
        "fattened_p99": (
            lambda tmp: ingest(_fx_serve(6, p99=120.0),
                               "fixture_serve_r06", tmp),
            "p99_ms",
        ),
        # 4. dropped tier: the new round silently loses hlo_drift
        "dropped_tier": (
            lambda tmp: ingest(_fx_round(6, include_hlo=False),
                               "fixture_r06", tmp),
            "fallback_tiers",
        ),
        # 5. drifted compiled schedule: +64 operand bytes for the same
        # workload — a compiler change altering the emitted schedule must
        # hit the byte-exact class, not a percentage gate
        "drifted_schedule": (
            lambda tmp: ingest(_fx_sched(6, operand_bytes=2048 + 64),
                               "fixture_sched_r06", tmp),
            "operand_bytes",
        ),
        # 6. drifted wire bytes: +64 priced operand bytes for the same
        # workload at the same format — a codec/pricing change altering
        # what ships on the wire must hit the byte-exact class too
        "drifted_wire_bytes": (
            lambda tmp: ingest(_fx_wire(6, operand_bytes=1024 + 64),
                               "fixture_wire_r06", tmp),
            "operand_bytes",
        ),
        # 7. drifted grown world: a re-recorded generation-1 transition
        # whose adopted world size changed 3 -> 4 — a grow path that
        # reshards to the wrong world must hit the byte-exact class
        "drifted_world": (
            lambda tmp: ingest(_fx_grow(6, new_world=4),
                               "fixture_grow_r06", tmp),
            "world_count",
        ),
    }
    for name, (mutate, expect_metric) in mutants.items():
        with tempfile.TemporaryDirectory(
            prefix=f"dgraph_regress_{name}_"
        ) as tmp:
            _seed(tmp)
            mutate(tmp)
            report = check_ledger(tmp)
            hits = [v for v in reds(report)
                    if expect_metric in str(v["metric"])]
            check(not report["ok"] and hits,
                  f"seeded-drift mutant {name!r} stayed GREEN "
                  f"(vacuous gate): reds="
                  f"{[v['metric'] for v in reds(report)]}")
            check(all(v.get("entry_id") for v in hits),
                  f"mutant {name!r} RED verdict carries no offending "
                  f"entry id")

    return {"kind": "regress_selftest", "failures": failures,
            "ok": not failures}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Config:
    """Drift sentinel CLI: gate the active ledger (exit 1 on RED), or
    ``--selftest true`` for the seeded-drift vacuity mutants."""

    dir: str = ""        # ledger dir ("" = DGRAPH_LEDGER_DIR or default)
    window: int = 20     # trailing baseline window per metric
    log_path: str = "logs/regress.jsonl"
    selftest: bool = False
    indent: int = 0


def _write_log(path: str, health: dict, report: dict) -> None:
    """RunHealth + report JSONL on every exit path — the stdlib
    stand-in for ExperimentLog (same line format; see module header)."""
    try:
        atomic_append_jsonl(path, [{"kind": "run_health", **health}, report])
    except OSError:
        pass  # a read-only checkout must not turn the verdict into a crash


def main(cfg: Config) -> dict:
    h = RunHealth.begin("obs.regress")
    rc = 0
    try:
        if cfg.selftest:
            out = _selftest()
            rc = 1 if out["failures"] else 0
            error = (f"selftest failures: {out['failures']}"
                     if out["failures"] else None)
        else:
            directory = (cfg.dir or resolve_ledger_dir(default_on=True)
                         or DEFAULT_LEDGER_DIR)
            out = check_ledger(directory, window=cfg.window)
            rc = 0 if out["ok"] else 1
            error = None if out["ok"] else (
                f"{out['counts']['RED']} RED verdict(s)")
    except Exception as e:  # every exit path stays structured
        out = {"kind": "regress_report", "ok": False,
               "error": f"{type(e).__name__}: {e}"}
        rc, error = 2, f"sentinel crashed: {type(e).__name__}: {e}"
    out["run_health"] = h.finish(error)
    _write_log(cfg.log_path, out["run_health"], out)
    print(json.dumps(out, indent=cfg.indent or None, default=str))
    if rc:
        raise SystemExit(rc)
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
