"""Testing utilities: dense oracles and sharded<->global data movement.

Mirrors the reference's test strategy (SURVEY.md §4): golden values computed
with dense global-graph loops, then compared against the distributed path
per-rank. ``spmd_apply`` is the canonical way to run a per-shard function
over a mesh in tests.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
from dgraph_tpu.plan import EdgePlan, EdgePlanLayout


def dense_gather(x_global: np.ndarray, edge_index: np.ndarray, side: str) -> np.ndarray:
    """Oracle: per-edge endpoint features from the dense global graph."""
    vids = edge_index[0] if side == "src" else edge_index[1]
    return x_global[vids]


def dense_scatter_sum(
    edata: np.ndarray, edge_index: np.ndarray, side: str, num_vertices: int
) -> np.ndarray:
    """Oracle: per-vertex sums with a plain loop-equivalent np.add.at."""
    vids = edge_index[0] if side == "src" else edge_index[1]
    out = np.zeros((num_vertices,) + edata.shape[1:], dtype=edata.dtype)
    np.add.at(out, vids, edata)
    return out


def spmd_apply(mesh, fn, plan: EdgePlan, *arrays, static_args=()):
    """Run ``fn(*per_shard_arrays, plan_shard, *static_args)`` under shard_map.

    Matches the data-first signatures of :mod:`dgraph_tpu.comm.collectives`.
    Every array must have a leading [world_size] axis; outputs get one too.
    """

    def body(plan_, *xs):
        out = fn(*[x[0] for x in xs], squeeze_plan(plan_), *static_args)
        return jax.tree.map(lambda o: o[None], out)

    from dgraph_tpu.comm.collectives import shard_map_checks

    specs = tuple(P(GRAPH_AXIS) for _ in arrays)
    shmapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(plan_in_specs(plan),) + specs,
        out_specs=P(GRAPH_AXIS),
        **shard_map_checks(plan, GRAPH_AXIS),
    )
    from jax._src.core import trace_state_clean

    if trace_state_clean():
        with jax.set_mesh(mesh):
            return jax.jit(shmapped)(plan, *arrays)
    return shmapped(plan, *arrays)


def unshard_edge_data(
    edata: np.ndarray, layout: EdgePlanLayout
) -> np.ndarray:
    """[W, e_pad, ...] plan-layout edge data -> [E, ...] original edge order."""
    return np.asarray(edata)[layout.edge_rank, layout.edge_slot]
