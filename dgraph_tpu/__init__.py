"""dgraph_tpu — a TPU-native framework for distributed full-graph GNN training.

A ground-up JAX/XLA/Pallas re-design of the capabilities of LBANN/DGraph
(reference: /root/reference, surveyed in SURVEY.md): vertex-partitioned graphs
sharded over a TPU mesh, halo exchange and plan-based distributed
gather/scatter-sum lowered to XLA collectives (`all_to_all` / `ppermute` over
ICI/DCN) under `jax.shard_map`, and local CSR aggregation as (Pallas-backed)
segment reductions.

Architecture (vs. the reference's layer map, SURVEY.md §1):

- The reference's three backend engines (NCCL / MPI / NVSHMEM,
  ``DGraph/distributed/{nccl,mpi,nvshmem}``) collapse into ONE programming
  model on TPU: SPMD via ``jax.shard_map`` over a ``jax.sharding.Mesh`` with
  XLA collectives. There is no process-group plumbing; ``jax.distributed``
  and the XLA runtime own the wire.
- The reference's comm-plan builders (``DGraph/distributed/commInfo.py``,
  ``nccl/_NCCLCommPlan.py``) become pure host-side numpy plan builders
  (:mod:`dgraph_tpu.plan`) that emit **static-shape, padded** plans — exactly
  what XLA's compile-once model wants.
- The reference's CUDA local kernels (``DGraph/distributed/csrc``) become
  jnp gather / segment-sum with optional Pallas TPU kernels
  (:mod:`dgraph_tpu.ops`). TPU has no atomics, so scatter-add is a
  (sorted-)segment reduction, which the plan builder's dedup/sort already
  sets up.
- The user-facing :class:`~dgraph_tpu.comm.Communicator` facade keeps the
  reference's API shape (``DGraph/Communicator.py``) with backends
  ``"tpu"`` (mesh-sharded SPMD) and ``"single"`` (the reference's
  SingleProcessDummyCommunicator pattern, for tests and 1-device runs).
"""

from dgraph_tpu import compat as _compat  # installs jax API shims; keep first

from dgraph_tpu.version import __version__
from dgraph_tpu import partition
from dgraph_tpu.plan import (
    CommPattern,
    EdgePlan,
    HaloSpec,
    build_comm_pattern,
    build_edge_plan,
)
from dgraph_tpu.comm import Communicator, TpuComm, SingleComm

__all__ = [
    "__version__",
    "partition",
    "CommPattern",
    "EdgePlan",
    "HaloSpec",
    "build_comm_pattern",
    "build_edge_plan",
    "Communicator",
    "TpuComm",
    "SingleComm",
]
