"""Communication planning: host-side builders of static-shape, padded plans.

This module is the TPU-native re-design of the reference's planning layer:

- ``DGraph/distributed/commInfo.py`` (CommunicationPattern +
  build_communication_pattern): reproduced here as :class:`CommPattern` /
  :func:`build_comm_pattern` with the same semantics (per-rank local/halo
  vertex sets, local edge list with halo appended after locals, CSR send
  indices/offsets, comm_map, one-sided put offsets) — but built with a
  *global* host view (no collectives at build time; on TPU the host sees the
  whole graph, so ``compute_comm_map``'s ``dist.all_gather``
  (``commInfo.py:148-155``) becomes a pure bincount).
- ``DGraph/distributed/nccl/_NCCLCommPlan.py`` (NCCLGraphCommPlan +
  COO_to_NCCLCommPlan): its internal/boundary edge split, (rank, vertex-id)
  dedup and per-peer split bookkeeping are subsumed by :class:`EdgePlan` /
  :func:`build_edge_plan`, which additionally **pads every per-peer segment
  to a single static size** so one XLA program covers every rank and every
  step (the reference computes exact per-peer splits for alltoallv;
  XLA's static-shape model wants maxima + masks instead).

Conventions (differ from the reference where TPU-first design wins):

- Edge lists are ``[2, E]`` (src row 0, dst row 1), not ``[E, 2]``.
- Vertices must be renumbered into contiguous per-rank blocks
  (:func:`dgraph_tpu.partition.renumber_contiguous`) before plan build.
  Contiguity makes "sorted by global id" == "grouped by owner rank", the
  invariant both the reference's halo ordering and ours rely on.
- Default edge owner is the **dst** rank (the reference uses src,
  ``commInfo.py:64-78``): with dst ownership every aggregation
  (scatter-add, softmax-over-incoming-edges for attention) is rank-local
  and only the src-side feature gather communicates. The reference's RGAT
  needs 6 comm ops per layer per relation (``RGAT.py:174-206``); dst
  ownership needs 1-2. ``edge_owner="src"`` is supported for parity.
- All plan arrays are stacked with a leading ``[world_size]`` axis, ready to
  shard over the ``graph`` mesh axis with ``PartitionSpec('graph')``.

Halo slot numbering: on a rank r with ``n_pad`` padded local vertices and
send pad ``s_pad``, the halo copy of a vertex owned by rank p that appears at
position i of p's send-list-to-r lives at index ``n_pad + p*s_pad + i`` of
the concatenated ``[local ; halo]`` feature buffer. After
``lax.all_to_all`` the received block from peer p lands exactly at rows
``[p*s_pad, (p+1)*s_pad)`` of the halo buffer, so no post-exchange scatter
is needed (the reference needs an explicit recv-placement scatter,
``_torch_func_impl.py:98-107``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
from typing import Any, Optional

import numpy as np

import jax

_logger = logging.getLogger("dgraph_tpu.plan")

# ---------------------------------------------------------------------------
# pytree dataclass helper
# ---------------------------------------------------------------------------


def pytree_dataclass(cls=None, *, static: tuple[str, ...] = ()):
    """Register a frozen dataclass as a JAX pytree with some static fields."""

    def wrap(c):
        c = dataclasses.dataclass(frozen=True)(c)
        fields = [f.name for f in dataclasses.fields(c)]
        leaf_names = tuple(n for n in fields if n not in static)

        def flatten(obj):
            return tuple(getattr(obj, n) for n in leaf_names), tuple(
                getattr(obj, n) for n in static
            )

        def unflatten(aux, leaves):
            kwargs = dict(zip(leaf_names, leaves))
            kwargs.update(dict(zip(static, aux)))
            return c(**kwargs)

        jax.tree_util.register_pytree_node(c, flatten, unflatten)
        return c

    return wrap if cls is None else wrap(cls)


# ---------------------------------------------------------------------------
# Parity layer: per-rank CommPattern (reference commInfo.py semantics)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CommPattern:
    """Per-rank halo-exchange metadata, parity with the reference's
    ``CommunicationPattern`` (``DGraph/distributed/commInfo.py:7-32``).

    Unpadded, host-side (numpy). The padded SPMD plan is :class:`EdgePlan`.
    """

    rank: int
    world_size: int
    num_local_vertices: int
    num_halo_vertices: int
    # [E_r, 2] local-numbered edges; halo ids appended after locals
    local_edge_list: np.ndarray
    # CSR send indexing: local vertex ids to send, grouped by target rank
    send_local_idx: np.ndarray  # [total_sends]
    send_offset: np.ndarray  # [world_size + 1]
    recv_offset: np.ndarray  # [world_size + 1]
    comm_map: np.ndarray  # [world_size, world_size]
    # one-sided put offsets (parity with commInfo.py:29-31; on TPU these are
    # not needed at runtime — all_to_all computes placement — but they are
    # kept for API parity and test cross-checks)
    put_forward_remote_offset: np.ndarray  # [world_size]
    put_backward_remote_offset: np.ndarray  # [world_size]


def compute_local_vertices(partitioning: np.ndarray, rank: int) -> np.ndarray:
    """Global ids owned by `rank`. Parity: ``commInfo.py:35-38``."""
    return np.nonzero(np.asarray(partitioning) == rank)[0]


def compute_halo_vertices(
    edge_index: np.ndarray,
    src_partitioning: np.ndarray,
    rank: int,
    dst_partitioning: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Unique remote dst vertices of edges whose src is local to `rank`.

    Parity: ``commInfo.py:41-62`` (supports bipartite via dst_partitioning).
    """
    if dst_partitioning is None:
        dst_partitioning = src_partitioning
    src, dst = edge_index
    cross = (src_partitioning[src] == rank) & (dst_partitioning[dst] != rank)
    return np.unique(dst[cross])


def compute_local_edge_list(
    edge_index: np.ndarray,
    partitioning: np.ndarray,
    local_vertices: np.ndarray,
    halo_vertices: np.ndarray,
    rank: int,
) -> np.ndarray:
    """Edges owned by `rank` (src-local), remapped to local numbering with
    halo ids appended after locals. Parity: ``commInfo.py:64-91``.
    Returns [E_r, 2].
    """
    src, dst = edge_index
    mine = partitioning[src] == rank
    num_local = len(local_vertices)
    g2l = np.full(len(partitioning), -1, dtype=np.int64)
    g2l[local_vertices] = np.arange(num_local)
    g2l[halo_vertices] = np.arange(num_local, num_local + len(halo_vertices))
    return np.stack([g2l[src[mine]], g2l[dst[mine]]], axis=1)


def compute_boundary_vertices(
    edge_index: np.ndarray,
    src_partitioning: np.ndarray,
    local_vertices: np.ndarray,
    rank: int,
    world_size: int,
    dst_partitioning: Optional[np.ndarray] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Deduped (src, dst_rank) send list sorted by target rank then vertex id,
    remapped to local indices, with CSR offsets. Parity: ``commInfo.py:94-145``.
    """
    if dst_partitioning is None:
        dst_partitioning = src_partitioning
    src, dst = edge_index
    cross = (src_partitioning[src] == rank) & (dst_partitioning[dst] != rank)
    pairs = np.stack([dst_partitioning[dst[cross]], src[cross]], axis=1)
    pairs = np.unique(pairs, axis=0)  # sorted by (target_rank, global_src)
    target_ranks, src_global = pairs[:, 0], pairs[:, 1]
    g2l = np.full(len(src_partitioning), -1, dtype=np.int64)
    g2l[local_vertices] = np.arange(len(local_vertices))
    send_local_idx = g2l[src_global]
    send_offset = np.zeros(world_size + 1, dtype=np.int64)
    np.add.at(send_offset, target_ranks + 1, 1)
    send_offset = np.cumsum(send_offset)
    return send_local_idx, send_offset


def compute_comm_map(
    edge_index: np.ndarray,
    src_partitioning: np.ndarray,
    world_size: int,
    dst_partitioning: Optional[np.ndarray] = None,
) -> np.ndarray:
    """``comm_map[p, r]`` = number of (deduped) vertices rank p sends to rank r.

    The reference builds this with a ``dist.all_gather`` of per-rank send
    counts (``commInfo.py:148-155``); on host with the global graph it is a
    pure bincount over unique (src, dst_rank) pairs.
    """
    if dst_partitioning is None:
        dst_partitioning = src_partitioning
    src, dst = edge_index
    sp = src_partitioning[src]
    dp = dst_partitioning[dst]
    cross = sp != dp
    # unique (src_vertex, dst_rank) pairs, attributed to src's owner rank
    v_total = len(src_partitioning)
    enc = dp[cross].astype(np.int64) * v_total + src[cross].astype(np.int64)
    enc = np.unique(enc)
    senders = src_partitioning[enc % v_total]
    targets = enc // v_total
    comm_map = np.zeros((world_size, world_size), dtype=np.int64)
    np.add.at(comm_map, (senders, targets), 1)
    return comm_map


def compute_recv_offsets(comm_map: np.ndarray, rank: int) -> tuple[np.ndarray, np.ndarray]:
    """Per-source-rank recv CSR offsets. Parity: ``commInfo.py:157-164``."""
    recv_counts = comm_map[:, rank]
    recv_offset = np.zeros(comm_map.shape[0] + 1, dtype=np.int64)
    recv_offset[1:] = np.cumsum(recv_counts)
    recv_backward_offset = comm_map[:rank, :].sum(axis=0)
    return recv_offset, recv_backward_offset


def build_comm_pattern(
    edge_index: np.ndarray,
    partitioning: np.ndarray,
    rank: int,
    world_size: int,
) -> CommPattern:
    """Build the per-rank halo-exchange pattern.

    Parity: ``commInfo.py:167-207`` (build_communication_pattern), including
    the §2.6-noted fix: on TPU this is collective-free and device-agnostic
    (the reference hardcodes ``.cuda()`` in compute_comm_map).
    """
    edge_index = np.asarray(edge_index)
    partitioning = np.asarray(partitioning)
    local = compute_local_vertices(partitioning, rank)
    halo = compute_halo_vertices(edge_index, partitioning, rank)
    local_edges = compute_local_edge_list(edge_index, partitioning, local, halo, rank)
    send_idx, send_off = compute_boundary_vertices(
        edge_index, partitioning, local, rank, world_size
    )
    comm_map = compute_comm_map(edge_index, partitioning, world_size)
    recv_off, _ = compute_recv_offsets(comm_map, rank)
    return CommPattern(
        rank=rank,
        world_size=world_size,
        num_local_vertices=len(local),
        num_halo_vertices=len(halo),
        local_edge_list=local_edges,
        send_local_idx=send_idx,
        send_offset=send_off,
        recv_offset=recv_off,
        comm_map=comm_map,
        put_forward_remote_offset=comm_map[:rank, :].sum(axis=0),
        put_backward_remote_offset=comm_map[:, :rank].sum(axis=1),
    )


# ---------------------------------------------------------------------------
# SPMD padded plan: EdgePlan (the TPU-native hot-path plan)
# ---------------------------------------------------------------------------


@pytree_dataclass(static=("s_pad",))
class HaloSpec:
    """Halo-exchange spec for one vertex set, stacked over ranks.

    ``send_idx[r, p, i]`` = local vertex id (on rank r) of the i-th vertex r
    sends to rank p; ``send_mask`` marks real (non-padded) slots. After
    ``all_to_all``, rank r's received block from p occupies halo rows
    ``[p*s_pad, (p+1)*s_pad)``.
    """

    send_idx: Any  # i32[W, W, S]
    send_mask: Any  # f32[W, W, S]
    s_pad: int


@pytree_dataclass(
    static=("e_int_pad", "e_bnd_pad", "interior_mc", "boundary_mc")
)
class OverlapSpec:
    """Interior/boundary edge split for the compute–communication-overlap
    halo lowering (the reference's internal/boundary split,
    ``_NCCLCommPlan.py:14``, lifted into the padded SPMD plan).

    Per rank, the plan's live edges are partitioned into **interior**
    edges (both endpoints local — no halo slot referenced) and
    **boundary** edges (halo-side endpoint remote). Each subset keeps the
    plan's owner-sorted edge order (a subsequence of a monotone sequence
    is monotone), so owner-side aggregation over either subset still
    rides the sorted segment-sum fast path. The split lets the hot path
    issue the boundary collective first, aggregate interior edges while
    it is in flight, and merge boundary contributions last
    (``comm.collectives.halo_exchange_overlap`` / ``scatter_sum_overlap``).

    Index conventions (per rank shard):

    - ``int_src``/``int_dst``: as ``EdgePlan.src_index``/``dst_index``
      restricted to interior edges; halo-side entries are plain local row
      ids (< ``n_halo_pad``). Padded slots carry the owner-side fill
      ``n_owner_pad`` (monotone tail) / halo-side fill ``n_halo_pad``
      (out of range -> zero rows on take).
    - ``bnd_src``/``bnd_dst``: boundary edges; the halo-side entry is
      REBASED into the halo buffer, i.e. ``slot - n_halo_pad`` in
      ``[0, W*s_pad)`` — it indexes the ``[W*S, F]`` exchange output
      directly, no ``[local ; halo]`` concat needed. Padded halo-side
      slots carry ``W*s_pad`` (out of range).
    - ``int_epos``/``bnd_epos``: position of each subset edge within the
      plan's ``[0, e_pad)`` edge axis (fill ``e_pad``), for subsetting
      per-edge data (edge weights, plan-layout messages) by take.
    """

    int_src: Any  # i32[W, Ei]
    int_dst: Any  # i32[W, Ei]
    int_mask: Any  # f32[W, Ei]
    int_epos: Any  # i32[W, Ei]
    bnd_src: Any  # i32[W, Eb]
    bnd_dst: Any  # i32[W, Eb]
    bnd_mask: Any  # f32[W, Eb]
    bnd_epos: Any  # i32[W, Eb]
    num_interior: Any  # i32[W]
    num_boundary: Any  # i32[W]
    e_int_pad: int
    e_bnd_pad: int
    # Pallas max-chunks hints for owner-side sorted segment-sums over each
    # subset (same contract as EdgePlan.scatter_mc, computed for the same
    # recorded block sizes)
    interior_mc: int = 1
    boundary_mc: int = 1

    def side(self, which: str, side: str):
        """The ``side`` ('src'/'dst') index array of subset ``which``
        ('interior'/'boundary')."""
        if which == "interior":
            return self.int_src if side == "src" else self.int_dst
        return self.bnd_src if side == "src" else self.bnd_dst


@pytree_dataclass(
    static=(
        "world_size",
        "n_src_pad",
        "n_dst_pad",
        "e_pad",
        "halo_side",
        "homogeneous",
        "owner_sorted",
        "scatter_mc",
        "scatter_block_e",
        "scatter_block_n",
        "halo_deltas",
        "halo_sort_mc",
        "gather_mv",
        "halo_pair_rows",
        "halo_schedule",
        "wire_format",
    )
)
class EdgePlan:
    """Padded, static-shape plan for one edge set (relation), stacked over ranks.

    Subsumes the reference's ``NCCLGraphCommPlan``
    (``nccl/_NCCLCommPlan.py:10-58``) and the hetero
    ``NCCLEdgeConditionedGraphCommPlan`` (``:103-137``): a bipartite relation
    is just ``src`` and ``dst`` vertex sets with different partitions.

    Index spaces (per rank shard):
      - ``src_index``: [E] into ``[0, n_src_pad + W*s_pad)`` if
        ``halo_side=='src'`` else ``[0, n_src_pad)``.
      - ``dst_index``: [E] into ``[0, n_dst_pad + W*s_pad)`` if
        ``halo_side=='dst'`` else ``[0, n_dst_pad)``.
    Padded edges have both indices 0 and ``edge_mask`` 0.
    """

    # leaves (leading axis = world_size, shard over 'graph')
    src_index: Any  # i32[W, E]
    dst_index: Any  # i32[W, E]
    edge_mask: Any  # f32[W, E]
    num_local_src: Any  # i32[W]
    num_local_dst: Any  # i32[W]
    num_edges: Any  # i32[W]
    halo: HaloSpec
    # static
    world_size: int
    n_src_pad: int
    n_dst_pad: int
    e_pad: int
    halo_side: str  # 'src' or 'dst'
    homogeneous: bool
    # True when each rank's edges are sorted by the owner-side vertex index:
    # aggregation segment-ids are then monotone, enabling
    # indices_are_sorted segment reductions and sorted-CSR Pallas kernels
    # (the analogue of the sorted/deduped order the reference's plan build
    # establishes for its alltoallv path, _NCCLCommPlan.py:221-226).
    # Padded edge slots carry the out-of-range owner-side id n_pad (monotone
    # tail; dropped by scatter, clamped-and-masked by gather).
    owner_sorted: bool = True
    # Pallas scheduling hint: max edge-chunks any (scatter_block_n) vertex
    # block spans at chunk size scatter_block_e, maxed over shards (see
    # ops.pallas_segment). The block sizes the hint was computed FOR are
    # recorded alongside so kernel invocation and hint cannot desynchronize
    # (plans are pickled into the on-disk cache; a default drift would
    # otherwise silently under-visit chunks).
    scatter_mc: int = 1
    scatter_block_e: int = 512
    scatter_block_n: int = 256
    # Static tuple of rank-deltas ((peer - rank) mod W) with nonzero halo
    # traffic anywhere in the mesh. When sparse (locality partitions), the
    # halo exchange can run as len(halo_deltas) ppermute rounds instead of a
    # padded all_to_all — SURVEY §7 "ppermute rounds only to actual
    # neighbors". () means no cross-rank traffic.
    halo_deltas: tuple = ()
    # Sorted route for the HALO-side index (whose ids are NOT monotone —
    # local rows then halo slots): a static permutation putting them in
    # sorted order, so the halo-side gather's VJP and the halo-side
    # scatter's forward run as gather-by-perm + sorted segment-sum (Pallas
    # MXU kernel) instead of XLA's generic unsorted scatter-add (measured
    # ~2x slower at arxiv scale, ops/local.py). None on plans built with
    # sort_route=False (e.g. billion-edge plans where the extra 2x[W,E]
    # int32 isn't worth host RAM).
    halo_sort_perm: Any = None  # i32[W, E] or None
    halo_sorted_ids: Any = None  # i32[W, E] or None
    halo_sort_mc: int = 1  # static; max_chunks hint for the sorted route
    # Pallas sorted-row-gather hint: max vertex blocks any scatter_block_e
    # edge chunk spans (ops.pallas_segment.sorted_row_gather). 0 on plans
    # predating the kernel (stale caches rebuild via PLAN_FORMAT_VERSION).
    gather_mv: int = 0
    # Interior/boundary edge split for the compute–communication-overlap
    # lowering (an :class:`OverlapSpec`), or None on plans built without
    # it. Built on request (build_edge_plan(overlap=True)) or when the
    # resolved halo lowering asks for it (env pin / adopted tuning record
    # — see resolve_halo_impl); costs ~2x the plan's per-edge index bytes.
    overlap: Any = None
    # Static [W][W] traffic matrix: deduped live halo rows per
    # (sender, needer) pair — halo_counts as plain nested int tuples, so
    # it survives plan pickling/sharding and rides the jit cache key.
    # Feeds the row-weighted pick_halo_impl heuristic and the schedule
    # compiler (dgraph_tpu.sched). () on plans predating the compiler
    # (stale caches rebuild via PLAN_FORMAT_VERSION).
    halo_pair_rows: tuple = ()
    # Compiled multi-round halo schedule (dgraph_tpu.sched.ir.
    # HaloSchedule — frozen/hashable, so static aux is safe), attached
    # deterministically at plan build whenever halo_pair_rows is live.
    # Replayed by comm.collectives' round executor under
    # halo_impl="sched"; None when no cross-rank traffic (or on plans
    # predating the compiler).
    halo_schedule: Any = None
    # Wire format name (dgraph_tpu.wire.spec.WIRE_FORMATS) attached
    # deterministically at plan build — the build-time resolution of the
    # adoption ladder, so a cache round-trip keeps an adopted codec.
    # Runtime resolution (wire.spec.resolve_wire_format) still lets an
    # env pin or a freshly adopted record override it. "fp32" (the
    # identity) on plans predating the codec layer (stale caches rebuild
    # via PLAN_FORMAT_VERSION).
    wire_format: str = "fp32"

    def ids_sorted(self, side: str) -> bool:
        """True iff this side's per-edge index is monotone: the OWNER side
        of an owner-sorted plan. The halo side mixes local rows with halo
        slots and is never monotone — asserting sortedness there makes
        XLA's monotone-scatter path silently corrupt reductions, so every
        ``indices_are_sorted`` hint must come from here, not from a
        re-derived ``owner_sorted and ...`` expression at the call site."""
        return self.owner_sorted and side != self.halo_side


def dtype_nbytes(dtype) -> int:
    """Itemsize for numpy dtypes, jax dtypes, and the bf16 family names
    numpy doesn't know. Lives HERE (the base layer) so both this module's
    byte accounting and ``obs.footprint``'s (which re-exports it as
    ``dtype_bytes``) share one table without a downward import."""
    name = getattr(dtype, "__name__", None) or str(dtype)
    if name in ("bfloat16", "bf16"):
        return 2
    if name in ("float8_e4m3fn", "fp8", "f8E4M3FN"):
        return 1
    return int(np.dtype(name).itemsize)


def plan_memory_usage(
    plan: EdgePlan, feature_dim: int, dtype_bytes: int = 4, *, dtype=None
) -> dict:
    """Byte accounting of a plan and its runtime buffers — parity with
    ``NCCLGraphCommPlan.memory_usage`` (``_NCCLCommPlan.py:68-100``), printed
    by the reference before training (``Trainer.py:113-123``).

    ``dtype`` (a numpy/jax dtype or its name, e.g. ``"bfloat16"``), when
    given, overrides ``dtype_bytes`` — the runtime buffers scale with the
    ACTIVATION dtype, and the old fixed-4-bytes default silently doubled
    every bf16 accounting. ``obs.footprint`` passes the activation dtype
    through here.

    Returns per-shard byte counts (every shard is identical in the padded
    design, unlike the reference's per-rank variable sizes).
    """
    if dtype is not None:
        dtype_bytes = dtype_nbytes(dtype)
    W, S = plan.world_size, plan.halo.s_pad
    idx_bytes = plan.e_pad * 4 * 2 + plan.e_pad * 4  # src/dst idx + mask
    if plan.halo_sort_perm is not None:
        idx_bytes += plan.e_pad * 4 * 2  # halo_sort_perm + halo_sorted_ids
    ov = getattr(plan, "overlap", None)
    if ov is not None:
        # interior/boundary split: src+dst+epos (i32) + mask (f32) per slot
        idx_bytes += (ov.e_int_pad + ov.e_bnd_pad) * 4 * 4
    send_bytes = W * S * (4 + 4)  # send_idx + send_mask
    halo_buffer = W * S * feature_dim * dtype_bytes
    send_buffer = W * S * feature_dim * dtype_bytes
    edge_buffer = plan.e_pad * feature_dim * dtype_bytes
    return {
        "plan_index_bytes": idx_bytes + send_bytes,
        "halo_buffer_bytes": halo_buffer,
        "send_buffer_bytes": send_buffer,
        "edge_buffer_bytes": edge_buffer,
        "total_runtime_bytes": halo_buffer + send_buffer + edge_buffer,
        "dtype_bytes": dtype_bytes,
    }


def interior_boundary_edge_counts(plan: EdgePlan) -> dict:
    """Per-shard interior (both endpoints local) vs boundary (halo-side
    endpoint remote) live-edge counts, derived from the plan's index
    arrays — works on any plan, with or without an :class:`OverlapSpec`.
    The fractions are what ``bench.py`` and ``obs.footprint`` report next
    to the halo lowering: they bound how much compute the overlap
    lowering has available to hide the boundary collective behind."""
    halo_idx = np.asarray(
        plan.src_index if plan.halo_side == "src" else plan.dst_index
    )
    n_halo_pad = plan.n_src_pad if plan.halo_side == "src" else plan.n_dst_pad
    live = np.asarray(plan.edge_mask) > 0
    boundary = ((halo_idx >= n_halo_pad) & live).sum(axis=1).astype(np.int64)
    total = live.sum(axis=1).astype(np.int64)
    interior = total - boundary
    tot = int(total.sum())
    return {
        "interior_per_shard": [int(v) for v in interior],
        "boundary_per_shard": [int(v) for v in boundary],
        "interior_total": int(interior.sum()),
        "boundary_total": int(boundary.sum()),
        "interior_frac": float(interior.sum() / tot) if tot else 1.0,
        "boundary_frac": float(boundary.sum() / tot) if tot else 0.0,
    }


def pick_halo_impl(
    world_size: int, halo_deltas: tuple, pair_rows: tuple = (),
) -> str:
    """The heuristic halo-exchange lowering from the plan's active peer set.

    Cost model: one padded ``all_to_all`` moves ``(W-1) * s_pad`` remote rows
    per shard no matter how many peer pairs are actually live; ``ppermute``
    neighbor rounds move ``len(deltas) * s_pad`` rows but pay one collective
    launch per round. Rounds win when the peer set is sparse (locality
    partitions on mesh-like graphs — SURVEY §7 "ppermute rounds only to
    actual neighbors"); the crossover is ~W/2 live deltas.
    Returns 'none' | 'ppermute' | 'all_to_all'.

    ``pair_rows`` (the plan's static ``[W][W]`` live-row traffic matrix,
    ``plan.halo_pair_rows``) weights the delta count by actual traffic:
    the EFFECTIVE round count is how many max-pair-sized rounds the total
    traffic fills, ``ceil(total_rows / max_pair_rows)``, capped by the
    ring count. A single giant delta among near-empty ones used to read
    as "many deltas -> all_to_all" even though one ring carries ~all the
    bytes; weighted, it reads as ~1 effective round -> ppermute. A
    uniform matrix (and the no-matrix legacy case) reduces exactly to the
    old ``len(halo_deltas)`` rule.

    This is the FALLBACK tier only: runtime call sites resolve through
    :func:`resolve_halo_impl`, which lets an env pin or an adopted tuning
    record override the heuristic.
    """
    if not halo_deltas:
        return "none"
    n_eff = len(halo_deltas)
    if pair_rows:
        live = [int(v) for row in pair_rows for v in row if int(v) > 0]
        if live:
            n_eff = min(n_eff, -(-sum(live) // max(live)))  # ceil div
    return "ppermute" if n_eff <= max(1, world_size // 2) else "all_to_all"


def compile_plan_schedule(
    pair_rows: tuple, *, s_pad: int, world_size: int, halo_deltas: tuple,
):
    """The ONE attach rule for a plan's compiled halo schedule: both
    plan-build paths (:func:`_finalize_plan`) and the shard assembler
    (:func:`assemble_plan`) compile through here, so a monolithic build
    and a cache/shard round-trip of the same graph carry byte-identical
    schedules (same ``schedule_id``) — and, because ``pair_rows`` is
    always the FULL-WORLD static matrix (rank-subset loads keep whole-
    world statics), every rank holds the identical round order by
    construction: the rank-divergence/deadlock class the SPMD
    issue-sequence auditor proves absent. Returns ``None`` when there is
    no cross-rank traffic (or no matrix: plans predating the compiler).
    """
    if not halo_deltas or not pair_rows:
        return None
    if not any(v for row in pair_rows for v in row):
        return None
    from dgraph_tpu.sched.passes import compile_halo_schedule

    return compile_halo_schedule(
        pair_rows, s_pad=int(s_pad), world_size=int(world_size)
    )


def plan_wire_format(world_size: int, halo_deltas: tuple) -> str:
    """The ONE attach rule for a plan's wire format
    (:mod:`dgraph_tpu.wire`): both plan-build paths
    (:func:`_finalize_plan`) and the shard assembler
    (:func:`assemble_plan`, for pre-codec manifests) stamp through here,
    so a monolithic build and a cache round-trip of the same graph under
    the same adoption state carry the identical format. This is the
    build-time pass of the adoption ladder WITHOUT a plan tier (the plan
    is being built): env pin > adopted tuning record > the fp32
    identity. Runtime consumers re-resolve through
    :func:`dgraph_tpu.wire.spec.resolve_wire_format` with this value as
    the plan tier, so a later env pin or record adoption still wins.
    """
    if not halo_deltas:
        return "fp32"
    from dgraph_tpu.wire.spec import resolve_wire_format

    name, _source = resolve_wire_format(
        int(world_size), tuple(halo_deltas), plan_format="fp32"
    )
    return name


def resolve_halo_impl(
    world_size: int, halo_deltas: tuple, *, overlap_available: bool = False,
    p2p_available: "bool | None" = None, sched_available: bool = False,
    pair_rows: tuple = (),
) -> tuple[str, str]:
    """The halo lowering the run will actually execute, plus who decided.

    Returns ``(impl, source)`` with impl one of ``'none'``,
    ``'all_to_all'``, ``'ppermute'``, ``'overlap'``, ``'pallas_p2p'``,
    ``'sched'`` and source one of:

    - ``'env'``       — ``DGRAPH_TPU_HALO_IMPL`` (or ``config.set_flags``)
      pins the lowering; the operator's word is final.
    - ``'record'``    — an adopted :class:`~dgraph_tpu.tune.record.
      TuningRecord` chose it (``config.tuned_halo_impl``).
    - ``'heuristic'`` — :func:`pick_halo_impl`'s cost model (or, when the
      plan carries an interior/boundary split, the overlap lowering: its
      exposed comm time is never worse than the serial rounds it is built
      from).
    - ``'plan'``      — the plan has no cross-rank traffic at all; there is
      nothing to choose (impl is ``'none'``).

    ``overlap_available`` says whether the plan carries an
    :class:`OverlapSpec` (``plan.overlap is not None``). An ``'overlap'``
    pin (env or record) on a plan WITHOUT the split cannot lower — that
    tier is skipped (logged once per process) and the NEXT tier decides
    (an env-pin miss still honors an adopted record, then the heuristic),
    never a silent wrong answer.

    ``'pallas_p2p'`` (device-initiated one-sided puts,
    :mod:`dgraph_tpu.ops.pallas_p2p`) is gated TWICE: the plan must carry
    the overlap split (its model routing rides the interior/boundary
    streams) and the backend must be able to lower the kernels
    (``config.pallas_p2p_available()``: a TPU backend, or the explicit
    ``DGRAPH_TPU_PALLAS_P2P=1`` opt-in that runs them in Pallas interpret
    mode). A pin that misses either gate degrades with a one-time warning
    exactly like an overlap pin without the split. ``p2p_available``
    overrides the config/backend probe (the probe imports jax, so it is
    only consulted when a pallas_p2p pin or record is actually present).
    The heuristic tier never picks ``pallas_p2p`` on its own — an
    un-A/B'd kernel engages only through an explicit pin or a persisted
    tuning record (the ``use_pallas_gather`` precedent).

    ``'sched'`` (the compiled multi-round schedule,
    :mod:`dgraph_tpu.sched`, replayed by ``comm.collectives``'s round
    executor) follows the same discipline: it is legal only when the
    plan actually carries a compiled schedule (``sched_available``,
    i.e. ``plan.halo_schedule is not None``) — a pin or record naming it
    on a schedule-less plan degrades with a one-time warning to the next
    tier — and the heuristic tier never picks it on its own: a compiled
    schedule engages only through an explicit pin or a persisted tuning
    record that A/B'd it against the fixed lowerings.

    ``pair_rows`` (``plan.halo_pair_rows``) is forwarded to
    :func:`pick_halo_impl` so the heuristic tier weighs actual per-pair
    traffic, not just the ring count.

    Every consumer of the decision (``comm.collectives``'s runtime dispatch,
    ``obs.footprint``'s byte accounting, :func:`plan_efficiency`'s report)
    resolves through here, so what runs, what is accounted, and what is
    reported can never be three different lowerings.
    """
    from dgraph_tpu import config as _cfg

    if not halo_deltas:
        return "none", "plan"

    def _p2p_ok() -> bool:
        if not overlap_available:
            return False
        if p2p_available is not None:
            return p2p_available
        return _cfg.pallas_p2p_available()

    legal = ("all_to_all", "ppermute") + (
        ("overlap",) if overlap_available else ()
    ) + (("sched",) if sched_available else ())
    for impl, source in (
        (_cfg.halo_impl, "env"),
        (_cfg.tuned_halo_impl, "record"),
    ):
        if impl in legal:
            return impl, source
        if impl == "overlap":  # pinned but the plan carries no split
            _warn_overlap_unavailable(source)
        if impl == "sched":  # pinned but the plan carries no schedule
            _warn_sched_unavailable(source)
        if impl == "pallas_p2p":
            if _p2p_ok():
                return impl, source
            _warn_p2p_unavailable(source, overlap_available)
    if overlap_available:
        return "overlap", "heuristic"
    return pick_halo_impl(world_size, halo_deltas, pair_rows), "heuristic"


def resolve_overlap_intent() -> bool:
    """Whether a plan built RIGHT NOW with ``overlap=None`` (auto) would
    attach the interior/boundary split: the env pin or the adopted tuning
    record asks for the overlap lowering — or for ``pallas_p2p``, which
    rides the same split (its model routing aggregates interior edges
    while the one-sided puts are in flight). The ONE copy of this rule —
    ``build_edge_plan``'s auto default and the plan cache's fingerprint
    (``train.checkpoint.cached_edge_plan``) both resolve through here, so
    what gets built and what the cache key claims was built can never
    diverge."""
    from dgraph_tpu import config as _cfg

    intents = (_cfg.halo_impl, _cfg.tuned_halo_impl)
    return "overlap" in intents or "pallas_p2p" in intents


_overlap_warned: set = set()


def _warn_overlap_unavailable(source: str) -> None:
    if source not in _overlap_warned:
        _overlap_warned.add(source)
        _logger.warning(
            "halo_impl='overlap' requested by %s but the plan carries no "
            "interior/boundary split (built without overlap=True); the "
            "next resolution tier decides the lowering instead", source,
        )


_sched_warned: set = set()


def _warn_sched_unavailable(source: str) -> None:
    if source not in _sched_warned:
        _sched_warned.add(source)
        _logger.warning(
            "halo_impl='sched' requested by %s but the plan carries no "
            "compiled halo schedule (halo_schedule is None — plan predates "
            "the schedule compiler or has no cross-rank traffic); the next "
            "resolution tier decides the lowering instead", source,
        )


_p2p_warned: set = set()


def _warn_p2p_unavailable(source: str, overlap_available: bool) -> None:
    key = (source, overlap_available)
    if key in _p2p_warned:
        return
    _p2p_warned.add(key)
    if not overlap_available:
        why = (
            "the plan carries no interior/boundary split (built without "
            "overlap=True)"
        )
    else:
        why = (
            "the backend cannot lower the Pallas TPU kernels (set "
            "DGRAPH_TPU_PALLAS_P2P=1 to force interpret-mode kernels "
            "off-TPU)"
        )
    _logger.warning(
        "halo_impl='pallas_p2p' requested by %s but %s; the next "
        "resolution tier decides the lowering instead", source, why,
    )


def plan_efficiency(plan: EdgePlan, layout: EdgePlanLayout) -> dict:
    """Real/padded fill ratios — the padded design's skew telemetry.

    Every per-peer segment pads to the global max, so one hub vertex on a
    power-law graph can inflate ``s_pad`` for all W² peer pairs; these ratios
    are the number that decides whether that happened (and which halo
    lowering to use). The reference reports plan bytes before training
    (``Trainer.py:113-123``); this is the utilization companion.
    """
    W, S, E = plan.world_size, plan.halo.s_pad, plan.e_pad
    real_edges = int(np.asarray(plan.num_edges).sum())
    real_halo = int(layout.halo_counts.sum())
    active_pairs = int((layout.halo_counts > 0).sum())
    n_deltas = len(plan.halo_deltas)
    src_total = int(layout.src_counts.sum())
    dst_total = int(layout.dst_counts.sum())
    impl, impl_source = resolve_halo_impl(
        W, plan.halo_deltas, overlap_available=plan.overlap is not None,
        sched_available=plan.halo_schedule is not None,
        pair_rows=plan.halo_pair_rows,
    )
    return {
        "edge_fill": real_edges / max(W * E, 1),
        "src_vertex_fill": src_total / max(W * plan.n_src_pad, 1),
        "dst_vertex_fill": dst_total / max(W * plan.n_dst_pad, 1),
        # fill of the peer segments that actually carry traffic
        "halo_fill_active": real_halo / max(active_pairs * S, 1),
        # fraction of all_to_all wire bytes that are real rows (a2a moves all
        # W*(W-1) remote blocks at s_pad each, live or not)
        "halo_wire_fill_all_to_all": real_halo / max(W * (W - 1) * S, 1),
        # same for ppermute rounds (only live deltas move)
        "halo_wire_fill_ppermute": real_halo / max(n_deltas * W * S, 1) if n_deltas else 1.0,
        "active_peer_pairs": active_pairs,
        "num_halo_deltas": n_deltas,
        "halo_impl": impl,
        # who decided the lowering: 'env' pin, adopted tuning 'record',
        # cost-model 'heuristic', or 'plan' (no traffic to lower)
        "halo_impl_source": impl_source,
    }


def validate_plan(plan: EdgePlan) -> None:
    """Host-side structural validation (the index-bounds asserts the
    reference scatters through its kernels, ``RankLocalOps.py:183-184``;
    here checked once at build/load time since plans are static).
    Raises ValueError on any violation."""
    import numpy as np_

    W, S = plan.world_size, plan.halo.s_pad
    src_hi = plan.n_src_pad + (W * S if plan.halo_side == "src" else 0)
    dst_hi = plan.n_dst_pad + (W * S if plan.halo_side == "dst" else 0)
    src = np_.asarray(plan.src_index)
    dst = np_.asarray(plan.dst_index)
    mask = np_.asarray(plan.edge_mask) > 0
    errors = []
    if src[mask].size and (src[mask].min() < 0 or src[mask].max() >= src_hi):
        errors.append(f"src_index out of [0,{src_hi})")
    if dst[mask].size and (dst[mask].min() < 0 or dst[mask].max() >= dst_hi):
        errors.append(f"dst_index out of [0,{dst_hi})")
    send_idx = np_.asarray(plan.halo.send_idx)
    send_mask = np_.asarray(plan.halo.send_mask) > 0
    n_halo_owner = plan.n_src_pad if plan.halo_side == "src" else plan.n_dst_pad
    if send_idx[send_mask].size and (
        send_idx[send_mask].min() < 0 or send_idx[send_mask].max() >= n_halo_owner
    ):
        errors.append(f"halo send_idx out of [0,{n_halo_owner})")
    for r in range(W):
        if send_mask[r, r].any():
            errors.append(f"rank {r} sends to itself")
    counts = np_.asarray(plan.num_edges)
    if (counts > plan.e_pad).any():
        errors.append("num_edges exceeds e_pad")
    if plan.halo_sort_perm is not None:
        # sorted route: perm must be a permutation of [0, e_pad) per shard
        # and the recorded sorted ids must equal halo_idx[perm], monotone.
        # Vectorized WITHIN each rank (no O(E log E) sort — the old check's
        # dominant cost at billion-edge scale, VERDICT r2 #8) but looped
        # over ranks: all-at-once [W, e_pad] temporaries would multiply
        # transient host RAM W-fold on every cache load of a huge plan.
        perm = np_.asarray(plan.halo_sort_perm)
        sids = np_.asarray(plan.halo_sorted_ids)
        halo_idx = src if plan.halo_side == "src" else dst
        seen = np_.empty(plan.e_pad, bool)
        for r in range(W):
            pr = perm[r]
            in_range = (pr >= 0) & (pr < plan.e_pad)
            seen[:] = False
            seen[pr[in_range]] = True
            if not (in_range.all() and seen.all()):
                errors.append(f"halo_sort_perm[{r}] is not a permutation")
                break
            if (np_.diff(sids[r]) < 0).any():
                errors.append(f"halo_sorted_ids[{r}] not monotone")
                break
            if not np_.array_equal(halo_idx[r][pr], sids[r]):
                errors.append(f"halo_sorted_ids[{r}] != halo_index[perm]")
                break
    ov = plan.overlap
    if ov is not None:
        # interior/boundary split invariants: the two subsets must exactly
        # tile the live edge set, interior halo-side ids must be local,
        # boundary halo-side slots must land inside the halo buffer, and
        # owner-side ids must stay monotone per subset (the property the
        # overlap lowering's chunked sorted segment-sums rely on)
        n_halo_pad = plan.n_src_pad if plan.halo_side == "src" else plan.n_dst_pad
        n_owner_pad = plan.n_dst_pad if plan.halo_side == "src" else plan.n_src_pad
        im = np_.asarray(ov.int_mask) > 0
        bm = np_.asarray(ov.bnd_mask) > 0
        n_int = np_.asarray(ov.num_interior)
        n_bnd = np_.asarray(ov.num_boundary)
        if not np_.array_equal(im.sum(1), n_int):
            errors.append("overlap int_mask count != num_interior")
        if not np_.array_equal(bm.sum(1), n_bnd):
            errors.append("overlap bnd_mask count != num_boundary")
        if not np_.array_equal(n_int + n_bnd, np_.asarray(plan.num_edges)):
            errors.append("overlap split does not tile the live edge set")
        int_halo = np_.asarray(ov.side("interior", plan.halo_side))
        bnd_halo = np_.asarray(ov.side("boundary", plan.halo_side))
        if int_halo[im].size and int_halo[im].max(initial=0) >= n_halo_pad:
            errors.append("overlap interior halo-side id not local")
        if bnd_halo[bm].size and (
            bnd_halo[bm].min(initial=0) < 0
            or bnd_halo[bm].max(initial=0) >= W * S
        ):
            errors.append(f"overlap boundary slot out of [0,{W * S})")
        owner_side = "dst" if plan.halo_side == "src" else "src"
        for which, epos in (
            ("interior", np_.asarray(ov.int_epos)),
            ("boundary", np_.asarray(ov.bnd_epos)),
        ):
            own = np_.asarray(ov.side(which, owner_side))
            if plan.owner_sorted and (np_.diff(own, axis=1) < 0).any():
                errors.append(f"overlap {which} owner ids not monotone")
            if own.max(initial=0) > n_owner_pad:
                errors.append(f"overlap {which} owner id > {n_owner_pad}")
            msk = im if which == "interior" else bm
            if epos[msk].size and epos[msk].max(initial=0) >= plan.e_pad:
                errors.append(f"overlap {which} epos out of [0,{plan.e_pad})")
            # epos strictly increasing within each rank's live region
            # (subsets preserve the plan's edge order)
            live_pairs = msk[:, 1:] & msk[:, :-1]
            if live_pairs.size and (np_.diff(epos, axis=1) <= 0)[live_pairs].any():
                errors.append(f"overlap {which} epos not strictly increasing")
    if errors:
        raise ValueError("invalid EdgePlan: " + "; ".join(errors))
    impl, impl_source = resolve_halo_impl(
        W, plan.halo_deltas, overlap_available=plan.overlap is not None,
        sched_available=plan.halo_schedule is not None,
        pair_rows=plan.halo_pair_rows,
    )
    _logger.info(
        "validate_plan OK: W=%d e_pad=%d s_pad=%d; halo lowering=%s "
        "(decided by %s)", W, plan.e_pad, S, impl, impl_source,
    )


@dataclasses.dataclass
class EdgePlanLayout:
    """Host-side companion of :class:`EdgePlan` (not a pytree; build metadata).

    ``edge_rank``/``edge_slot``: for global edge i (in the caller's original
    edge order), the owning rank and its padded slot — use
    :func:`shard_edge_data` to lay per-edge features/weights into the
    ``[W, E_pad]`` plan layout (the analogue of the reference's edge
    renumber+sort, ``DGraph/data/preprocess.py:43-92``).
    """

    edge_rank: np.ndarray  # [E_total]
    edge_slot: np.ndarray  # [E_total]
    halo_counts: np.ndarray  # [W, W] (sender, needer) deduped halo vertex counts
    src_counts: np.ndarray  # [W]
    dst_counts: np.ndarray  # [W]


# v5e-tuned Pallas scatter tiles (ops.pallas_segment): block_e=1024 measured
# 29.0 ms vs 512's 34.1 ms for [2.33M, 256] f32 sorted segment-sum
# (logs/kernels_r2.jsonl). New plans carry these; old pickled plans keep the
# blocks they were built with (EdgePlan field defaults + PLAN_FORMAT_VERSION).
# Env-overridable so an on-chip tile sweep (kernel_benchmarks --sweep) can
# be applied to a fresh plan build without a code edit.
import os as _os

SCATTER_BLOCK_E = int(_os.environ.get("DGRAPH_TPU_SCATTER_BLOCK_E", "1024"))
SCATTER_BLOCK_N = int(_os.environ.get("DGRAPH_TPU_SCATTER_BLOCK_N", "256"))
del _os

# Edge count above which build_edge_plan dispatches to the native streaming
# core by default (the numpy path's lexsort/unique int64 temporaries are
# ~10x E bytes; at papers100M's 1.6e9 edges that exceeds host RAM).
NATIVE_PLAN_MIN_EDGES = 1 << 24


def _pad_to(x: int, multiple: int) -> int:
    if multiple <= 1:
        return max(x, 1)
    return max(-(-x // multiple) * multiple, multiple)


def _reject_incompatible_knobs(
    pad_multiple: int, e_pad: Optional[int], s_pad: Optional[int],
    overlap: Optional[bool] = None, sort_edges: bool = True,
) -> None:
    """Fail fast on tunable combinations that cannot lower cleanly, naming
    the conflicting knobs — the autotuner (and any caller sweeping plan
    geometry) must get a structured rejection here, not a shape error deep
    in ``_finalize_plan`` or a silent per-step re-pad inside the Pallas
    kernels. Raises ValueError."""
    if overlap and not sort_edges:
        raise ValueError(
            "overlap=True conflicts with sort_edges=False: the "
            "interior/boundary split's chunked interior aggregation relies "
            "on owner-sorted edge order (monotone segment ids per subset); "
            "drop one of the two knobs"
        )
    from dgraph_tpu import config as _cfg

    if "pallas_p2p" in (_cfg.halo_impl, _cfg.tuned_halo_impl):
        # fail the un-lowerable combos at build time, naming the knobs —
        # not at the first pallas_call deep inside a jitted step
        if not sort_edges:
            raise ValueError(
                "halo_impl='pallas_p2p' conflicts with sort_edges=False: "
                "the one-sided lowering routes through the interior/"
                "boundary split, which relies on owner-sorted edge order; "
                "drop the pin or re-enable sort_edges"
            )
        if s_pad is not None and s_pad % 8:
            raise ValueError(
                f"halo_impl='pallas_p2p' conflicts with s_pad={s_pad}: the "
                f"per-delta [s_pad, F] DMA tiles need 8-row (sublane) "
                f"alignment; pick s_pad={_pad_to(s_pad, 8)} or drop the pin"
            )
        if pad_multiple % 8 and s_pad is None:
            raise ValueError(
                f"halo_impl='pallas_p2p' conflicts with pad_multiple="
                f"{pad_multiple}: s_pad inherits this multiple and the "
                f"per-delta DMA tiles need 8-row (sublane) alignment; use "
                f"a multiple of 8 or pass an aligned explicit s_pad"
            )
    if pad_multiple < 1:
        raise ValueError(f"pad_multiple={pad_multiple} must be >= 1")
    if e_pad is not None:
        if e_pad < 1:
            raise ValueError(f"e_pad={e_pad} must be >= 1")
        if pad_multiple > 1 and e_pad % pad_multiple:
            raise ValueError(
                f"e_pad={e_pad} conflicts with pad_multiple={pad_multiple}: "
                f"an explicit e_pad must be a multiple of pad_multiple "
                f"(lane tiling); pick e_pad={_pad_to(e_pad, pad_multiple)} "
                f"or drop one of the two knobs"
            )
        if e_pad >= SCATTER_BLOCK_E and e_pad % SCATTER_BLOCK_E:
            # kernel-scale plans must align to the scatter block: a
            # non-multiple makes every pallas_call re-pad its [E, F]
            # operand — a full HBM copy per kernel per step (the r4c
            # finding _edge_pad_align exists to prevent). Sub-block plans
            # (e_pad < SCATTER_BLOCK_E) are exempt: the in-op pad there is
            # negligible and hand-analyzed test plans pin exact tiny shapes.
            raise ValueError(
                f"e_pad={e_pad} conflicts with scatter_block_e="
                f"{SCATTER_BLOCK_E}: a kernel-scale e_pad must be a "
                f"multiple of the Pallas scatter block (or stay below it); "
                f"pick e_pad={_pad_to(e_pad, SCATTER_BLOCK_E)} or set "
                f"DGRAPH_TPU_SCATTER_BLOCK_E to a divisor of e_pad"
            )
    if s_pad is not None:
        if s_pad < 1:
            raise ValueError(f"s_pad={s_pad} must be >= 1")
        if pad_multiple > 1 and s_pad % pad_multiple:
            raise ValueError(
                f"s_pad={s_pad} conflicts with pad_multiple={pad_multiple}: "
                f"an explicit s_pad must be a multiple of pad_multiple; "
                f"pick s_pad={_pad_to(s_pad, pad_multiple)}"
            )


def _edge_pad_align(e_max: int, pad_multiple: int) -> int:
    """Alignment for the per-rank edge padding (SHARED by the numpy and
    native builders — a divergence would give the two paths different
    e_pad for the same graph). Once the plan reaches kernel scale, e_pad
    aligns to the Pallas scatter block too: a non-block_e-multiple e_pad
    makes every kernel invocation re-pad its [E, F] operand — a full HBM
    copy per pallas_call per step (r4c finding; the bench plan's 2332544
    was 896 past a 1024 block). Cost: <= block_e-1 extra masked edge
    slots. Sub-block plans keep the caller's pad_multiple (the in-op pad
    there is negligible, and hand-analyzed test plans pin exact tiny
    shapes)."""
    import math

    if e_max >= SCATTER_BLOCK_E:
        return math.lcm(pad_multiple, SCATTER_BLOCK_E)
    return pad_multiple


def build_edge_plan(
    edge_index: np.ndarray,
    src_partition: np.ndarray,
    dst_partition: Optional[np.ndarray] = None,
    *,
    world_size: int,
    edge_owner: str = "dst",
    n_src_pad: Optional[int] = None,
    n_dst_pad: Optional[int] = None,
    e_pad: Optional[int] = None,
    s_pad: Optional[int] = None,
    pad_multiple: int = 8,
    sort_edges: bool = True,
    use_native: Optional[bool] = None,  # None = auto (E >= NATIVE_PLAN_MIN_EDGES)
    sort_route: Optional[bool] = None,  # None = auto (skip at billion-edge
    # scale: the two extra [W, E] int32 arrays aren't worth host RAM there)
    overlap: Optional[bool] = None,  # None = auto: build the
    # interior/boundary split when the configured halo lowering asks for
    # it (env pin DGRAPH_TPU_HALO_IMPL=overlap or an adopted tuning
    # record's tuned_halo_impl='overlap'); True/False force it
) -> tuple[EdgePlan, EdgePlanLayout]:
    """Build the padded SPMD plan for one edge set.

    Args:
      edge_index: [2, E] global edges in *contiguous-block* numbering
        (per-rank blocks; see :func:`dgraph_tpu.partition.renumber_contiguous`).
      src_partition / dst_partition: [V_src] / [V_dst] owner rank per vertex;
        dst_partition=None means homogeneous (same vertex set both sides).
      edge_owner: 'dst' (TPU-native default: local aggregations) or 'src'
        (reference parity, ``commInfo.py:64-78``).
      pad_multiple: round padded sizes up to this multiple (TPU lane tiling).
      overlap: attach an :class:`OverlapSpec` (interior/boundary edge
        split) so the runtime can lower the halo exchange as overlappable
        ppermute rounds hidden behind interior aggregation.

    Returns (plan, layout).
    """
    pro = _plan_build_prologue(
        edge_index, src_partition, dst_partition, edge_owner=edge_owner,
        sort_edges=sort_edges, sort_route=sort_route, overlap=overlap,
        pad_multiple=pad_multiple, e_pad=e_pad, s_pad=s_pad,
        world_size=world_size,
    )
    src, dst, E = pro.src, pro.dst, pro.E
    src_partition, dst_partition = pro.src_partition, pro.dst_partition
    homogeneous = pro.homogeneous
    src_counts, dst_counts = pro.src_counts, pro.dst_counts
    src_offsets, dst_offsets = pro.src_offsets, pro.dst_offsets
    sort_route, overlap = pro.sort_route, pro.overlap
    W = world_size
    from dgraph_tpu import native as _native

    if use_native is None:
        use_native = sort_edges and _native.available() and E >= NATIVE_PLAN_MIN_EDGES
    if use_native:
        if not sort_edges:
            raise ValueError("native plan core always owner-sorts (sort_edges=True)")
        return _build_edge_plan_native(
            src, dst, src_partition, dst_partition, src_offsets, dst_offsets,
            src_counts, dst_counts, W, edge_owner, homogeneous,
            n_src_pad, n_dst_pad, e_pad, s_pad, pad_multiple,
            sort_route=sort_route, overlap=overlap,
        )

    prep = _numpy_plan_prep(
        src, dst, src_partition, dst_partition, src_offsets, dst_offsets,
        src_counts, dst_counts, W, edge_owner, sort_edges,
        n_src_pad, n_dst_pad, e_pad, s_pad, pad_multiple,
    )

    # --- scatter into padded [W, E_pad] layout ---
    def to_padded(vals, dtype, fill=0):
        out = np.full((W, prep.e_pad), fill, dtype=dtype)
        out[prep.edge_rank, prep.edge_slot] = vals
        return out

    edge_mask = np.zeros((W, prep.e_pad), dtype=np.float32)
    edge_mask[prep.edge_rank, prep.edge_slot] = 1.0
    # owner-side padding = n_pad: keeps sorted order monotone through the
    # padded tail and is dropped by segment reductions
    if prep.halo_side == "src":
        src_idx_arr = to_padded(prep.halo_side_local_idx.astype(np.int32), np.int32)
        dst_idx_arr = to_padded(
            prep.own_local.astype(np.int32), np.int32, fill=prep.n_owner_pad)
    else:
        src_idx_arr = to_padded(
            prep.own_local.astype(np.int32), np.int32, fill=prep.n_owner_pad)
        dst_idx_arr = to_padded(prep.halo_side_local_idx.astype(np.int32), np.int32)

    return _finalize_plan(
        src_idx_arr=src_idx_arr, dst_idx_arr=dst_idx_arr, edge_mask=edge_mask,
        src_counts=src_counts, dst_counts=dst_counts, e_counts=prep.e_counts,
        send_idx=prep.send_idx, send_mask=prep.send_mask,
        s_pad_val=prep.s_pad, W=W, E=E,
        n_src_pad_val=prep.n_src_pad, n_dst_pad_val=prep.n_dst_pad,
        e_pad_val=prep.e_pad,
        halo_side=prep.halo_side, homogeneous=homogeneous,
        edge_owner=edge_owner, owner_sorted=sort_edges,
        halo_deltas=prep.halo_deltas,
        edge_rank=prep.edge_rank, edge_slot=prep.edge_slot,
        halo_counts=prep.halo_counts,
        tag="", sort_route=sort_route, overlap=overlap,
    )


def _plan_build_prologue(
    edge_index, src_partition, dst_partition, *, edge_owner, sort_edges,
    sort_route, overlap, pad_multiple, e_pad, s_pad, world_size,
):
    """Shared validation + derived inputs for the monolithic AND streaming
    plan builds (ONE copy, so the two entry points cannot drift): shape /
    owner / knob rejection, the resolved overlap intent, per-rank
    counts/offsets, the contiguity check, and the sort_route default."""
    import types

    edge_index = np.asarray(edge_index)
    if edge_index.ndim != 2 or edge_index.shape[0] != 2:
        raise ValueError(f"edge_index must be [2, E], got {edge_index.shape}")
    if overlap is None:
        overlap = resolve_overlap_intent()
    _reject_incompatible_knobs(pad_multiple, e_pad, s_pad, overlap, sort_edges)
    if edge_owner not in ("src", "dst"):
        raise ValueError("edge_owner must be 'src' or 'dst'")
    src_partition = np.asarray(src_partition)
    homogeneous = dst_partition is None
    dst_partition = src_partition if homogeneous else np.asarray(dst_partition)
    W = world_size
    # copy=False: at billion-edge scale a silent astype copy is 26 GB
    src = edge_index[0].astype(np.int64, copy=False)
    dst = edge_index[1].astype(np.int64, copy=False)
    E = len(src)
    src_counts = np.bincount(src_partition, minlength=W).astype(np.int64)
    dst_counts = np.bincount(dst_partition, minlength=W).astype(np.int64)
    src_offsets = np.concatenate([[0], np.cumsum(src_counts)])
    dst_offsets = np.concatenate([[0], np.cumsum(dst_counts)])
    # contiguity check (cheap): partition must be non-decreasing
    if np.any(np.diff(src_partition) < 0) or np.any(np.diff(dst_partition) < 0):
        raise ValueError(
            "partitions must be contiguous per-rank blocks; run "
            "dgraph_tpu.partition.renumber_contiguous first"
        )
    if sort_route is None:
        sort_route = E < NATIVE_PLAN_MIN_EDGES
    return types.SimpleNamespace(
        src=src, dst=dst, E=E,
        src_partition=src_partition, dst_partition=dst_partition,
        homogeneous=homogeneous,
        src_counts=src_counts, dst_counts=dst_counts,
        src_offsets=src_offsets, dst_offsets=dst_offsets,
        sort_route=sort_route, overlap=overlap,
    )


def _numpy_plan_prep(
    src, dst, src_partition, dst_partition, src_offsets, dst_offsets,
    src_counts, dst_counts, W, edge_owner, sort_edges,
    n_src_pad, n_dst_pad, e_pad, s_pad, pad_multiple,
):
    """Host-side skeleton of the numpy plan build: every per-edge / per-peer
    intermediate needed to assemble the padded index arrays, WITHOUT
    materializing any ``[W, E_pad]`` stack.  The monolithic path scatters
    the whole stack from this in one shot; the streaming path
    (:func:`build_edge_plan_sharded`) assembles one rank's rows at a time
    from the same skeleton, so the two builds cannot diverge — the
    resumed/streamed plan is bit-identical to the in-RAM one (pinned by
    ``tests/test_plan_shards.py``)."""
    import types

    E = len(src)
    if edge_owner == "dst":
        owner = dst_partition[dst]
        halo_side = "src"
        halo_vid, halo_part = src, src_partition
    else:
        owner = src_partition[src]
        halo_side = "dst"
        halo_vid, halo_part = dst, dst_partition

    # --- group edges by owner rank; optionally sort by owner-side vertex
    # within each rank so aggregation segment ids are monotone ---
    owner_side_vid = dst if edge_owner == "dst" else src
    if sort_edges:
        order = np.lexsort((owner_side_vid, owner))
    else:
        order = np.argsort(owner, kind="stable")
    e_counts = np.bincount(owner, minlength=W).astype(np.int64)
    _e_max = int(e_counts.max(initial=1))
    E_pad = e_pad if e_pad is not None else _pad_to(
        _e_max, _edge_pad_align(_e_max, pad_multiple))
    if int(e_counts.max(initial=0)) > E_pad:
        raise ValueError(f"e_pad={E_pad} < max per-rank edges {int(e_counts.max())}")
    e_starts = np.concatenate([[0], np.cumsum(e_counts)])
    # slot within owner rank (original relative order preserved)
    slot_sorted = np.arange(E, dtype=np.int64) - e_starts[owner[order]]
    edge_slot = np.empty(E, dtype=np.int64)
    edge_slot[order] = slot_sorted
    edge_rank = owner

    # --- halo sets: unique (needer_rank, halo_vertex) pairs of cross edges ---
    cross = halo_part[halo_vid] != owner
    v_total = len(halo_part)
    from dgraph_tpu import native as _native

    if _native.available() and cross.sum() > (1 << 16):
        enc_u = _native.unique_encoded_pairs(owner[cross], halo_vid[cross], v_total)
    else:
        enc = owner[cross].astype(np.int64) * v_total + halo_vid[cross]
        enc_u = np.unique(enc)  # sorted by (needer, vid); vid-sorted == owner-grouped
    needer = enc_u // v_total
    hvid = enc_u % v_total
    sender = halo_part[hvid]
    # counts per (sender p, needer r)
    halo_counts = np.zeros((W, W), dtype=np.int64)
    np.add.at(halo_counts, (sender, needer), 1)
    S_pad = s_pad if s_pad is not None else _pad_to(int(halo_counts.max(initial=1)), pad_multiple)
    if int(halo_counts.max(initial=0)) > S_pad:
        raise ValueError(f"s_pad={S_pad} < max per-peer halo {int(halo_counts.max())}")

    halo_side_offsets = src_offsets if halo_side == "src" else dst_offsets
    N_src_pad = n_src_pad if n_src_pad is not None else _pad_to(int(src_counts.max(initial=1)), pad_multiple)
    N_dst_pad = n_dst_pad if n_dst_pad is not None else _pad_to(int(dst_counts.max(initial=1)), pad_multiple)
    N_halo_pad = N_src_pad if halo_side == "src" else N_dst_pad

    # position of each (needer, vid) within its (sender->needer) segment:
    # enc_u is sorted by (needer, vid) and vid-sorted groups sender blocks
    # contiguously (contiguous renumbering), so positions are running indices
    # within (needer, sender) runs.
    seg_key = needer * W + sender
    # running position within equal-key runs of the sorted seg_key sequence
    change = np.concatenate([[True], seg_key[1:] != seg_key[:-1]])
    run_starts = np.nonzero(change)[0]
    run_id = np.cumsum(change) - 1
    pos_in_seg = np.arange(len(seg_key)) - run_starts[run_id]

    # send arrays on the sender shard: send_idx[p, r, i]
    send_idx = np.zeros((W, W, S_pad), dtype=np.int32)
    send_mask = np.zeros((W, W, S_pad), dtype=np.float32)
    send_local = hvid - halo_side_offsets[sender]
    send_idx[sender, needer, pos_in_seg] = send_local.astype(np.int32)
    send_mask[sender, needer, pos_in_seg] = 1.0

    # halo slot (on the needer shard) for each unique (needer, vid) pair
    halo_slot = N_halo_pad + sender * S_pad + pos_in_seg

    # map (needer, vid) -> halo_slot for edge remapping: edges on owner rank
    # r referencing remote vid v find their slot by searchsorted into enc_u
    edge_enc = owner.astype(np.int64) * v_total + halo_vid
    idx_in_u = np.searchsorted(enc_u, edge_enc)
    # guard for purely-local edges (no match needed)
    idx_in_u = np.clip(idx_in_u, 0, max(len(enc_u) - 1, 0))

    # --- per-edge local indices ---
    if halo_side == "src":
        own_side_vid, own_side_off = dst, dst_offsets
        halo_side_vid = src
    else:
        own_side_vid, own_side_off = src, src_offsets
        halo_side_vid = dst

    own_local = own_side_vid - own_side_off[owner]
    halo_is_local = ~cross
    local_halo_side = halo_side_vid - halo_side_offsets[owner]
    if len(enc_u) > 0:
        remote_slot = halo_slot[idx_in_u]
    else:
        remote_slot = np.zeros(E, dtype=np.int64)
    halo_side_local_idx = np.where(halo_is_local, local_halo_side, remote_slot)

    n_owner_pad = N_dst_pad if edge_owner == "dst" else N_src_pad
    return types.SimpleNamespace(
        W=W, E=E, halo_side=halo_side, e_counts=e_counts, e_pad=E_pad,
        edge_rank=edge_rank, edge_slot=edge_slot, cross=cross,
        halo_counts=halo_counts, s_pad=S_pad,
        n_src_pad=N_src_pad, n_dst_pad=N_dst_pad, n_halo_pad=N_halo_pad,
        n_owner_pad=n_owner_pad,
        send_idx=send_idx, send_mask=send_mask,
        own_local=own_local, halo_side_local_idx=halo_side_local_idx,
        src_counts=src_counts, dst_counts=dst_counts,
        halo_deltas=tuple(int(d) for d in np.unique((needer - sender) % W)),
    )


def _finalize_plan(
    *, src_idx_arr, dst_idx_arr, edge_mask, src_counts, dst_counts, e_counts,
    send_idx, send_mask, s_pad_val, W, E, n_src_pad_val, n_dst_pad_val,
    e_pad_val, halo_side, homogeneous, edge_owner, owner_sorted, halo_deltas,
    edge_rank, edge_slot, halo_counts, tag: str, sort_route: bool,
    overlap: bool = False,
) -> tuple[EdgePlan, EdgePlanLayout]:
    """Shared assembly tail of the numpy and native plan builders: Pallas
    scheduling hints, EdgePlan/EdgePlanLayout construction, efficiency log.
    Keeping it in one place means a plan-format change cannot silently
    diverge between the two paths."""
    n_owner_pad = n_dst_pad_val if edge_owner == "dst" else n_src_pad_val
    owner_idx_arr = dst_idx_arr if edge_owner == "dst" else src_idx_arr
    scatter_block_e, scatter_block_n = SCATTER_BLOCK_E, SCATTER_BLOCK_N
    if owner_sorted:
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            max_vblocks_hint,
        )

        scatter_mc = max(
            max_chunks_hint(
                owner_idx_arr[r], n_owner_pad,
                block_e=scatter_block_e, block_n=scatter_block_n,
            )
            for r in range(W)
        )
        gather_mv = max(
            max_vblocks_hint(
                owner_idx_arr[r], n_owner_pad,
                block_e=scatter_block_e, block_n=scatter_block_n,
            )
            for r in range(W)
        )
    else:
        scatter_mc = 1
        gather_mv = 0

    # halo-side sorted route (see EdgePlan.halo_sort_perm)
    halo_sort_perm = halo_sorted_ids = None
    halo_sort_mc = 1
    if sort_route:
        from dgraph_tpu.ops.pallas_segment import max_chunks_hint

        halo_idx_arr = src_idx_arr if halo_side == "src" else dst_idx_arr
        n_halo_rows = (
            n_src_pad_val if halo_side == "src" else n_dst_pad_val
        ) + W * s_pad_val
        halo_sort_perm = np.argsort(halo_idx_arr, axis=1, kind="stable").astype(
            np.int32
        )
        halo_sorted_ids = np.take_along_axis(halo_idx_arr, halo_sort_perm, axis=1)
        halo_sort_mc = max(
            max_chunks_hint(
                halo_sorted_ids[r], n_halo_rows,
                block_e=scatter_block_e, block_n=scatter_block_n,
            )
            for r in range(W)
        )

    overlap_spec = None
    if overlap:
        overlap_spec = _build_overlap_spec(
            src_idx_arr, dst_idx_arr, edge_mask, halo_side,
            n_src_pad_val, n_dst_pad_val, s_pad_val, W, e_pad_val,
            owner_sorted, scatter_block_e, scatter_block_n,
        )

    halo_pair_rows = tuple(
        tuple(int(v) for v in row) for row in np.asarray(halo_counts)
    )
    halo_schedule = compile_plan_schedule(
        halo_pair_rows, s_pad=s_pad_val, world_size=W,
        halo_deltas=halo_deltas,
    )

    plan = EdgePlan(
        src_index=src_idx_arr,
        dst_index=dst_idx_arr,
        edge_mask=edge_mask,
        num_local_src=src_counts.astype(np.int32),
        num_local_dst=dst_counts.astype(np.int32),
        num_edges=e_counts.astype(np.int32),
        halo=HaloSpec(send_idx=send_idx, send_mask=send_mask, s_pad=s_pad_val),
        world_size=W,
        n_src_pad=n_src_pad_val,
        n_dst_pad=n_dst_pad_val,
        e_pad=e_pad_val,
        halo_side=halo_side,
        homogeneous=homogeneous,
        owner_sorted=owner_sorted,
        scatter_mc=scatter_mc,
        scatter_block_e=scatter_block_e,
        scatter_block_n=scatter_block_n,
        halo_deltas=halo_deltas,
        halo_sort_perm=halo_sort_perm,
        halo_sorted_ids=halo_sorted_ids,
        halo_sort_mc=halo_sort_mc,
        gather_mv=gather_mv,
        overlap=overlap_spec,
        halo_pair_rows=halo_pair_rows,
        halo_schedule=halo_schedule,
        wire_format=plan_wire_format(W, halo_deltas),
    )
    layout = EdgePlanLayout(
        edge_rank=edge_rank,
        edge_slot=edge_slot,
        halo_counts=halo_counts,
        src_counts=src_counts,
        dst_counts=dst_counts,
    )
    eff = plan_efficiency(plan, layout)
    _logger.info(
        "EdgePlan built%s: W=%d E=%d e_pad=%d (fill %.3f) s_pad=%d "
        "halo_fill_active=%.3f wire_fill[a2a=%.3f pp=%.3f] deltas=%d -> %s",
        tag, W, E, e_pad_val, eff["edge_fill"], s_pad_val,
        eff["halo_fill_active"], eff["halo_wire_fill_all_to_all"],
        eff["halo_wire_fill_ppermute"], eff["num_halo_deltas"], eff["halo_impl"],
    )
    return plan, layout


def _overlap_rows_for_rank(
    src_row, dst_row, mask_row, *, halo_side, n_halo_pad, n_owner_pad,
    s_pad, W, e_pad, e_int_pad, e_bnd_pad, owner_sorted,
    scatter_block_e, scatter_block_n,
):
    """ONE rank's interior/boundary split rows + Pallas hints — the single
    per-rank core behind both build modes: the monolithic
    :func:`_build_overlap_spec` stacks these rows into an
    :class:`OverlapSpec`, and the streaming shard assembler
    (:func:`_assemble_overlap_rows`) ships them in the shard payload, so
    the fill/rebase/hint conventions cannot diverge between the two.

    Interior halo-side padded fill is OUT of the local table
    (``n_halo_pad``); owner-side padded fill is ``n_owner_pad`` (monotone
    tail); ``epos`` fill is ``e_pad``; the boundary halo-side entry is
    rebased into the ``[0, W*s_pad)`` exchange buffer (padded slots ->
    ``W*s_pad``, out of range of the buffer)."""
    halo_row = src_row if halo_side == "src" else dst_row
    live = mask_row > 0
    is_bnd = live & (halo_row >= n_halo_pad)
    is_int = live & ~is_bnd

    def subset(sel_mask, e_sub_pad):
        pos = np.nonzero(sel_mask)[0]
        k = len(pos)
        epos = np.full(e_sub_pad, e_pad, np.int32)
        s_arr = np.full(e_sub_pad, n_owner_pad if halo_side == "dst"
                        else n_halo_pad, np.int32)
        d_arr = np.full(e_sub_pad, n_owner_pad if halo_side == "src"
                        else n_halo_pad, np.int32)
        mask = np.zeros(e_sub_pad, np.float32)
        epos[:k] = pos
        s_arr[:k] = src_row[pos]
        d_arr[:k] = dst_row[pos]
        mask[:k] = 1.0
        return epos, s_arr, d_arr, mask

    int_epos, int_src, int_dst, int_mask = subset(is_int, e_int_pad)
    bnd_epos, bnd_src, bnd_dst, bnd_mask = subset(is_bnd, e_bnd_pad)
    bnd_halo = bnd_src if halo_side == "src" else bnd_dst
    rebased = np.where(
        bnd_mask > 0, bnd_halo - n_halo_pad, W * s_pad
    ).astype(np.int32)
    if halo_side == "src":
        bnd_src = rebased
    else:
        bnd_dst = rebased
    interior_mc = boundary_mc = 1
    if owner_sorted:
        from dgraph_tpu.ops.pallas_segment import max_chunks_hint

        int_owner = int_dst if halo_side == "src" else int_src
        bnd_owner = bnd_dst if halo_side == "src" else bnd_src
        interior_mc = max_chunks_hint(
            int_owner, n_owner_pad,
            block_e=scatter_block_e, block_n=scatter_block_n,
        )
        boundary_mc = max_chunks_hint(
            bnd_owner, n_owner_pad,
            block_e=scatter_block_e, block_n=scatter_block_n,
        )
    rows = {
        "int_src": int_src, "int_dst": int_dst, "int_mask": int_mask,
        "int_epos": int_epos,
        "bnd_src": bnd_src, "bnd_dst": bnd_dst, "bnd_mask": bnd_mask,
        "bnd_epos": bnd_epos,
        "num_interior": int(is_int.sum()),
        "num_boundary": int(is_bnd.sum()),
    }
    return rows, interior_mc, boundary_mc


def _build_overlap_spec(
    src_idx_arr, dst_idx_arr, edge_mask, halo_side, n_src_pad, n_dst_pad,
    s_pad, W, e_pad, owner_sorted, scatter_block_e, scatter_block_n,
) -> OverlapSpec:
    """Derive the interior/boundary edge split from the assembled padded
    index arrays — shared by the numpy and native builders (both feed the
    same arrays through ``_finalize_plan``, so the split cannot diverge
    between them), and each rank's rows come from the same per-rank core
    the streaming shard builder uses (:func:`_overlap_rows_for_rank`).
    See :class:`OverlapSpec` for the index conventions."""
    halo_idx = src_idx_arr if halo_side == "src" else dst_idx_arr
    n_halo_pad = n_src_pad if halo_side == "src" else n_dst_pad
    n_owner_pad = n_dst_pad if halo_side == "src" else n_src_pad
    live = edge_mask > 0
    is_bnd = live & (halo_idx >= n_halo_pad)
    n_bnd = is_bnd.sum(axis=1).astype(np.int64)
    n_int = live.sum(axis=1).astype(np.int64) - n_bnd
    int_max = int(n_int.max(initial=1))
    bnd_max = int(n_bnd.max(initial=1))
    # subset padding follows the plan's edge-pad alignment rule (lane tile
    # floor of 8; Pallas scatter-block alignment once at kernel scale)
    e_int_pad = _pad_to(int_max, _edge_pad_align(int_max, 8))
    e_bnd_pad = _pad_to(bnd_max, _edge_pad_align(bnd_max, 8))

    per_rank = [
        _overlap_rows_for_rank(
            src_idx_arr[r], dst_idx_arr[r], edge_mask[r],
            halo_side=halo_side, n_halo_pad=n_halo_pad,
            n_owner_pad=n_owner_pad, s_pad=s_pad, W=W, e_pad=e_pad,
            e_int_pad=e_int_pad, e_bnd_pad=e_bnd_pad,
            owner_sorted=owner_sorted, scatter_block_e=scatter_block_e,
            scatter_block_n=scatter_block_n,
        )
        for r in range(W)
    ]
    rows = [p[0] for p in per_rank]

    def stack(key):
        return np.stack([row[key] for row in rows])

    return OverlapSpec(
        int_src=stack("int_src"), int_dst=stack("int_dst"),
        int_mask=stack("int_mask"), int_epos=stack("int_epos"),
        bnd_src=stack("bnd_src"), bnd_dst=stack("bnd_dst"),
        bnd_mask=stack("bnd_mask"), bnd_epos=stack("bnd_epos"),
        num_interior=n_int.astype(np.int32),
        num_boundary=n_bnd.astype(np.int32),
        e_int_pad=e_int_pad, e_bnd_pad=e_bnd_pad,
        interior_mc=max(p[1] for p in per_rank),
        boundary_mc=max(p[2] for p in per_rank),
    )


def _build_edge_plan_native(
    src, dst, src_partition, dst_partition, src_offsets, dst_offsets,
    src_counts, dst_counts, W, edge_owner, homogeneous,
    n_src_pad, n_dst_pad, e_pad, s_pad, pad_multiple,
    sort_route: bool, overlap: bool = False,
) -> tuple[EdgePlan, EdgePlanLayout]:
    """Billion-edge path: the per-edge sort/dedup/fill runs in the native
    core (csrc plan_core_*, bounded-memory radix sorts) and numpy only
    assembles the (cheap) metadata. Output is identical to the numpy path —
    pinned by tests/test_plan.py::test_native_plan_matches_numpy."""
    from dgraph_tpu import native as _native

    E = len(src)
    core = _native.PlanCore(
        src, dst, src_partition, dst_partition, src_offsets, dst_offsets,
        W, edge_owner,
    )
    E_pad = e_pad if e_pad is not None else _pad_to(
        core.e_max, _edge_pad_align(core.e_max, pad_multiple))
    if core.e_max > E_pad:
        raise ValueError(f"e_pad={E_pad} < max per-rank edges {core.e_max}")
    S_pad = s_pad if s_pad is not None else _pad_to(max(core.s_max, 1), pad_multiple)
    if core.s_max > S_pad:
        raise ValueError(f"s_pad={S_pad} < max per-peer halo {core.s_max}")
    N_src_pad = n_src_pad if n_src_pad is not None else _pad_to(int(src_counts.max(initial=1)), pad_multiple)
    N_dst_pad = n_dst_pad if n_dst_pad is not None else _pad_to(int(dst_counts.max(initial=1)), pad_multiple)
    halo_side = "src" if edge_owner == "dst" else "dst"
    n_owner_pad = N_dst_pad if edge_owner == "dst" else N_src_pad
    N_halo_pad = N_src_pad if halo_side == "src" else N_dst_pad

    src_idx_arr = np.empty((W, E_pad), np.int32)
    dst_idx_arr = np.empty((W, E_pad), np.int32)
    edge_mask = np.empty((W, E_pad), np.float32)
    send_idx = np.empty((W, W, S_pad), np.int32)
    send_mask = np.empty((W, W, S_pad), np.float32)
    halo_counts = np.empty((W, W), np.int64)
    edge_rank = np.empty(E, np.int32)
    edge_slot = np.empty(E, np.int64)
    core.fill(
        E_pad, S_pad, n_owner_pad, N_halo_pad,
        src_idx_arr, dst_idx_arr, edge_mask.reshape(-1),
        send_idx.reshape(-1), send_mask.reshape(-1),
        halo_counts.reshape(-1), edge_rank, edge_slot,
    )
    e_counts = np.bincount(edge_rank, minlength=W).astype(np.int64)
    core.close()

    sender_r, needer_r = np.nonzero(halo_counts)
    return _finalize_plan(
        src_idx_arr=src_idx_arr, dst_idx_arr=dst_idx_arr, edge_mask=edge_mask,
        src_counts=src_counts, dst_counts=dst_counts, e_counts=e_counts,
        send_idx=send_idx, send_mask=send_mask, s_pad_val=S_pad, W=W, E=E,
        n_src_pad_val=N_src_pad, n_dst_pad_val=N_dst_pad, e_pad_val=E_pad,
        halo_side=halo_side, homogeneous=homogeneous, edge_owner=edge_owner,
        owner_sorted=True,
        halo_deltas=tuple(int(d) for d in np.unique((needer_r - sender_r) % W)),
        edge_rank=edge_rank.astype(np.int64), edge_slot=edge_slot,
        halo_counts=halo_counts, tag=" (native core)", sort_route=sort_route,
        overlap=overlap,
    )


# ---------------------------------------------------------------------------
# Streaming per-rank plan builds (sharded artifacts, cache format v8)
# ---------------------------------------------------------------------------


def _shard_statics(prep, *, homogeneous, edge_owner, sort_edges, sort_route,
                   overlap) -> dict:
    """The manifest's JSON-able static description of a sharded plan —
    everything :func:`assemble_plan` needs besides the per-rank payloads.
    Per-rank Pallas hints are maxed in at finalize time
    (:func:`build_edge_plan_sharded`)."""
    st = {
        "world_size": int(prep.W),
        "n_src_pad": int(prep.n_src_pad),
        "n_dst_pad": int(prep.n_dst_pad),
        "e_pad": int(prep.e_pad),
        "s_pad": int(prep.s_pad),
        "halo_side": prep.halo_side,
        "homogeneous": bool(homogeneous),
        "edge_owner": edge_owner,
        "owner_sorted": bool(sort_edges),
        "sort_route": bool(sort_route),
        "overlap": bool(overlap),
        "scatter_block_e": SCATTER_BLOCK_E,
        "scatter_block_n": SCATTER_BLOCK_N,
        "halo_deltas": [int(d) for d in prep.halo_deltas],
        # full-world traffic matrix: rank-subset loads keep whole-world
        # statics, so every host compiles the identical halo schedule
        "halo_pair_rows": [
            [int(v) for v in row] for row in np.asarray(prep.halo_counts)
        ],
        # build-time wire-format resolution (same ONE attach rule as the
        # monolithic path), stamped so a cache round-trip keeps an
        # adopted codec even if the loading process has no record
        "wire_format": plan_wire_format(prep.W, tuple(prep.halo_deltas)),
    }
    if overlap:
        # subset pads are global maxima over ranks — computable from the
        # skeleton alone (boundary == cross edges), so every shard pads
        # its subsets identically whether built in one run or resumed
        n_bnd = np.bincount(
            prep.edge_rank[prep.cross], minlength=prep.W
        ).astype(np.int64)
        n_int = prep.e_counts - n_bnd
        int_max = int(n_int.max(initial=1))
        bnd_max = int(n_bnd.max(initial=1))
        st["e_int_pad"] = _pad_to(int_max, _edge_pad_align(int_max, 8))
        st["e_bnd_pad"] = _pad_to(bnd_max, _edge_pad_align(bnd_max, 8))
    return st


def shard_nbytes_estimate(statics: dict) -> int:
    """Upper-bound bytes of ONE rank's shard payload, from the manifest
    statics alone — the number the streaming build's upfront memory-budget
    check uses (so an over-budget build fails before assembling anything)."""
    e_pad, W, s_pad = statics["e_pad"], statics["world_size"], statics["s_pad"]
    n = e_pad * (4 + 4 + 4)  # src/dst idx + mask
    if statics.get("sort_route"):
        n += 2 * e_pad * 4  # halo_sort_perm + halo_sorted_ids
    if statics.get("overlap"):
        n += (statics["e_int_pad"] + statics["e_bnd_pad"]) * 4 * 4
    n += 2 * W * s_pad * 4  # send_idx + send_mask rows
    return n


def _assemble_shard_payload(prep, r: int, *, sort_edges: bool,
                            sort_route: bool, overlap: bool,
                            overlap_pads: tuple = (None, None)):
    """One rank's plan arrays + Pallas hints, assembled from the shared
    numpy skeleton. Row-for-row identical to what the monolithic path's
    ``[W, E_pad]`` stack holds at index ``r`` (the property the
    kill-and-resume bit-parity pin rides on)."""
    W, E_pad = prep.W, prep.e_pad
    sel = prep.edge_rank == r
    slots = prep.edge_slot[sel]
    halo_row = np.zeros(E_pad, np.int32)
    halo_row[slots] = prep.halo_side_local_idx[sel].astype(np.int32)
    own_row = np.full(E_pad, prep.n_owner_pad, np.int32)
    own_row[slots] = prep.own_local[sel].astype(np.int32)
    mask_row = np.zeros(E_pad, np.float32)
    mask_row[slots] = 1.0
    if prep.halo_side == "src":
        src_row, dst_row = halo_row, own_row
    else:
        src_row, dst_row = own_row, halo_row

    hints = {"scatter_mc": 1, "gather_mv": 0, "halo_sort_mc": 1,
             "interior_mc": 1, "boundary_mc": 1}
    if sort_edges:
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            max_vblocks_hint,
        )

        hints["scatter_mc"] = max_chunks_hint(
            own_row, prep.n_owner_pad,
            block_e=SCATTER_BLOCK_E, block_n=SCATTER_BLOCK_N,
        )
        hints["gather_mv"] = max_vblocks_hint(
            own_row, prep.n_owner_pad,
            block_e=SCATTER_BLOCK_E, block_n=SCATTER_BLOCK_N,
        )

    perm = sorted_ids = None
    if sort_route:
        from dgraph_tpu.ops.pallas_segment import max_chunks_hint

        n_halo_rows = prep.n_halo_pad + W * prep.s_pad
        perm = np.argsort(halo_row, kind="stable").astype(np.int32)
        sorted_ids = halo_row[perm]
        hints["halo_sort_mc"] = max_chunks_hint(
            sorted_ids, n_halo_rows,
            block_e=SCATTER_BLOCK_E, block_n=SCATTER_BLOCK_N,
        )

    payload = {
        "src_index": src_row,
        "dst_index": dst_row,
        "edge_mask": mask_row,
        "num_local_src": int(prep.src_counts[r]),
        "num_local_dst": int(prep.dst_counts[r]),
        "num_edges": int(prep.e_counts[r]),
        "send_idx": prep.send_idx[r],
        "send_mask": prep.send_mask[r],
        "halo_sort_perm": perm,
        "halo_sorted_ids": sorted_ids,
        "overlap": None,
    }
    if overlap:
        payload["overlap"], ov_hints = _assemble_overlap_rows(
            prep, src_row, dst_row, mask_row, sort_edges,
            e_int_pad=overlap_pads[0], e_bnd_pad=overlap_pads[1],
        )
        hints.update(ov_hints)
    return payload, hints


def _assemble_overlap_rows(prep, src_row, dst_row, mask_row,
                           sort_edges: bool, *, e_int_pad: int,
                           e_bnd_pad: int):
    """Per-rank interior/boundary split rows for one shard — a thin
    wrapper over :func:`_overlap_rows_for_rank` (the same core the
    monolithic :func:`_build_overlap_spec` stacks, so streamed and
    monolithic splits are structurally identical). The subset pads are
    the global maxima the manifest statics record
    (:func:`_shard_statics`)."""
    rows, interior_mc, boundary_mc = _overlap_rows_for_rank(
        src_row, dst_row, mask_row,
        halo_side=prep.halo_side, n_halo_pad=prep.n_halo_pad,
        n_owner_pad=prep.n_owner_pad, s_pad=prep.s_pad, W=prep.W,
        e_pad=prep.e_pad, e_int_pad=e_int_pad, e_bnd_pad=e_bnd_pad,
        owner_sorted=sort_edges,
        scatter_block_e=SCATTER_BLOCK_E, scatter_block_n=SCATTER_BLOCK_N,
    )
    return rows, {"interior_mc": interior_mc, "boundary_mc": boundary_mc}


def _content_fingerprint(edge_index, src_partition, dst_partition) -> str:
    """Streaming SHA-256 of the build inputs (dtype, shape, bytes) —
    chunked, so a memmap'd edge list is read through in windows and
    never materialized.  The default shard-build fingerprint when the
    caller supplies none: without it, a resumed manifest could adopt
    shards built from DIFFERENT edges that happen to share statics
    (same per-rank counts and pads)."""
    h = hashlib.sha256()
    for arr in (edge_index, src_partition, dst_partition):
        if arr is None:
            h.update(b"|none")
            continue
        a = np.asarray(arr)
        h.update(f"|{a.dtype.str}{a.shape}".encode())
        if not a.flags.c_contiguous:
            a = np.ascontiguousarray(a)
        flat = a.reshape(-1)
        step = max(1, (1 << 26) // max(a.itemsize, 1))  # 64 MiB windows
        for i in range(0, flat.size, step):
            h.update(flat[i:i + step].data)
    return "content:" + h.hexdigest()[:24]


def build_plan_shards(
    edge_index: np.ndarray,
    src_partition: np.ndarray,
    dst_partition: Optional[np.ndarray] = None,
    *,
    out_dir: str,
    world_size: int,
    memory_budget_bytes: Optional[int] = None,
    resume: bool = True,
    rebuild_ranks: tuple = (),
    write_layout: bool = True,
    fingerprint: str = "",
    edge_owner: str = "dst",
    n_src_pad: Optional[int] = None,
    n_dst_pad: Optional[int] = None,
    e_pad: Optional[int] = None,
    s_pad: Optional[int] = None,
    pad_multiple: int = 8,
    sort_edges: bool = True,
    sort_route: Optional[bool] = None,
    overlap: Optional[bool] = None,
    use_native: Optional[bool] = None,
) -> dict:
    """Streaming-mode plan build: assemble ONE rank's shard at a time
    (directly off a memmap'd edge list — nothing here forces the ``[2, E]``
    input resident) and write it durably under ``out_dir`` (cache format
    v8: ``shard_XXXX.pkl`` + checksummed ``manifest.json`` +
    ``layout.pkl``, :mod:`dgraph_tpu.plan_shards`).  Returns the final
    manifest WITHOUT assembling an in-RAM :class:`EdgePlan` — at real
    papers100M scale the assembled stack is the ~40+ GB allocation this
    mode exists to avoid; use :func:`build_edge_plan_sharded` (or
    :func:`load_sharded_plan` with a rank subset) when you want one.

    Peak RSS beyond the O(E) skeleton is ONE shard's arrays, enforced by
    the memory budget (``memory_budget_bytes`` /
    ``$DGRAPH_PLAN_MEMORY_BUDGET_MB``) which raises a structured
    :class:`~dgraph_tpu.plan_shards.PlanBuildMemoryExceeded` instead of
    getting OOM-killed — the r5 papers100M failure mode (ROADMAP item 3).
    A killed build **resumes**: shards already durable in the manifest
    (same fingerprint/format/statics, checksums intact) are skipped, and
    the resumed result is bit-identical to an uninterrupted build.
    ``rebuild_ranks`` forces named shards to rebuild even when the
    manifest says they are done (the loaders' single-corrupt-shard repair
    path).

    The ``plan.build_shard`` chaos point fires before each rank's
    assembly (index = rank), ``plan.write`` before each shard write.

    The per-rank streaming core is the numpy skeleton
    (:func:`_numpy_plan_prep`); ``use_native=True`` is rejected — the
    native core fills the whole ``[W, E_pad]`` stack at once, which is
    exactly the allocation this mode exists to avoid.

    ``fingerprint`` defaults to a streaming content hash of the inputs
    (:func:`_content_fingerprint`); pass an explicit value only when it
    is already content-derived — a constant label would let a resumed
    build adopt shards from different inputs with coinciding statics.
    """
    from dgraph_tpu import chaos
    from dgraph_tpu import plan_shards as ps

    if use_native:
        raise ValueError(
            "build_plan_shards streams through the numpy per-rank "
            "core; use_native=True would materialize the full [W, E_pad] "
            "stack this mode exists to avoid"
        )
    if not fingerprint:
        # an un-keyed manifest must still be bound to the build INPUTS:
        # statics (counts, pads) can coincide between two different edge
        # lists, and a resumed build that adopts shards from the other
        # one is a silently wrong comm plan
        fingerprint = _content_fingerprint(
            edge_index, src_partition, dst_partition
        )
    pro = _plan_build_prologue(
        edge_index, src_partition, dst_partition, edge_owner=edge_owner,
        sort_edges=sort_edges, sort_route=sort_route, overlap=overlap,
        pad_multiple=pad_multiple, e_pad=e_pad, s_pad=s_pad,
        world_size=world_size,
    )
    homogeneous, E, W = pro.homogeneous, pro.E, world_size
    src_counts, dst_counts = pro.src_counts, pro.dst_counts
    sort_route, overlap = pro.sort_route, pro.overlap

    prep = _numpy_plan_prep(
        pro.src, pro.dst, pro.src_partition, pro.dst_partition,
        pro.src_offsets, pro.dst_offsets,
        src_counts, dst_counts, W, edge_owner, sort_edges,
        n_src_pad, n_dst_pad, e_pad, s_pad, pad_multiple,
    )
    statics = _shard_statics(
        prep, homogeneous=homogeneous, edge_owner=edge_owner,
        sort_edges=sort_edges, sort_route=sort_route, overlap=overlap,
    )
    writer = ps.PlanShardWriter(
        out_dir,
        fingerprint=fingerprint,
        world_size=W,
        statics=statics,
        build_kwargs={
            "edge_owner": edge_owner, "pad_multiple": pad_multiple,
            "sort_edges": sort_edges, "sort_route": bool(sort_route),
            "overlap": bool(overlap), "num_edges": E,
        },
        memory_budget_bytes=memory_budget_bytes,
        resume=resume,
        rebuild_ranks=rebuild_ranks,
    )
    # fail BEFORE assembling anything when even one shard cannot fit
    writer.check_budget(shard_nbytes_estimate(statics))
    built = 0
    for r in range(W):
        if writer.done(r):
            continue
        chaos.fire("plan.build_shard", index=r)
        payload, hints = _assemble_shard_payload(
            prep, r, sort_edges=sort_edges, sort_route=sort_route,
            overlap=overlap,
            overlap_pads=(statics.get("e_int_pad"), statics.get("e_bnd_pad")),
        )
        writer.write(r, payload, hints=hints)
        built += 1
    # plan-level Pallas hints are maxima over the per-shard values the
    # manifest recorded — identical whether the shards were built in one
    # pass or across resumed processes
    entries = writer.manifest["shards"]
    hint_names = ("scatter_mc", "gather_mv", "halo_sort_mc",
                  "interior_mc", "boundary_mc")
    hints_max = {
        name: max(int(entries[str(r)].get("hints", {}).get(name, 0))
                  for r in range(W))
        for name in hint_names
    }
    # the layout sidecar is O(E) (edge_rank/edge_slot): at papers100M
    # scale it pickles to tens of GB, and atomic_pickle_dump transiently
    # doubles that on disk — callers that never consume it (the p100m
    # plan stage, per-host shard loading) opt out with write_layout=False
    layout_payload = None
    if write_layout:
        layout_payload = {
            "edge_rank": prep.edge_rank,
            "edge_slot": prep.edge_slot,
            "halo_counts": prep.halo_counts,
            "src_counts": src_counts,
            "dst_counts": dst_counts,
        }
    manifest = writer.finalize(layout_payload, statics_update=hints_max)
    _logger.info(
        "sharded EdgePlan built in %s: W=%d E=%d e_pad=%d s_pad=%d "
        "(%d shard(s) assembled this run, %d resumed)",
        out_dir, W, E, prep.e_pad, prep.s_pad, built, W - built,
    )
    return manifest


def build_edge_plan_sharded(
    edge_index: np.ndarray,
    src_partition: np.ndarray,
    dst_partition: Optional[np.ndarray] = None,
    *,
    out_dir: str,
    ranks: Optional[list] = None,
    load_layout: Optional[bool] = None,
    **build_kwargs: Any,
) -> tuple:
    """:func:`build_plan_shards` + :func:`load_sharded_plan`: the
    streaming-mode :func:`build_edge_plan` for callers that want the
    assembled ``(plan, layout)`` back (accepts every
    :func:`build_plan_shards` keyword).

    ``ranks=None`` assembles all ranks — bit-identical to the monolithic
    build (pinned by ``tests/test_plan_shards.py``).  A subset returns a
    plan whose leading axis is ``len(ranks)`` while every static —
    including ``world_size`` — still describes the full W-rank world, the
    each-host-loads-its-shard shape ``comm.multihost`` consumes.
    ``load_layout=None`` loads the O(E) layout sidecar only for a
    full-world load — a rank subset is the per-host path, which must not
    read (or SHA-verify) an artifact as big as the edge list.
    """
    build_plan_shards(
        edge_index, src_partition, dst_partition, out_dir=out_dir,
        **build_kwargs,
    )
    if load_layout is None:
        load_layout = ranks is None and build_kwargs.get("write_layout", True)
    # verify=False: every shard was either written moments ago by this
    # process or checksum-verified when the writer adopted it for resume —
    # re-hashing a ~40+ GB artifact straight after writing it would double
    # the build's IO. Cold loads (cached_edge_plan's hit path) verify.
    return load_sharded_plan(
        out_dir, ranks=ranks, load_layout=load_layout, verify=False
    )


def assemble_plan(manifest: dict, payloads: dict, ranks: list) -> EdgePlan:
    """Stack per-rank shard payloads (``ranks`` order) into an
    :class:`EdgePlan` under the manifest's statics. ``ranks == range(W)``
    reproduces the monolithic build bit-for-bit; a subset yields the
    partial stack a multi-controller host feeds its own devices."""
    st = manifest["statics"]

    def stack(key):
        return np.stack([payloads[r][key] for r in ranks])

    def counts(key):
        return np.asarray([payloads[r][key] for r in ranks], np.int32)

    sort_route = st.get("sort_route", False)
    pair_rows = tuple(
        tuple(int(v) for v in row) for row in st.get("halo_pair_rows", [])
    )
    overlap_spec = None
    if st.get("overlap"):
        def ostack(key):
            return np.stack([payloads[r]["overlap"][key] for r in ranks])

        overlap_spec = OverlapSpec(
            int_src=ostack("int_src"), int_dst=ostack("int_dst"),
            int_mask=ostack("int_mask"), int_epos=ostack("int_epos"),
            bnd_src=ostack("bnd_src"), bnd_dst=ostack("bnd_dst"),
            bnd_mask=ostack("bnd_mask"), bnd_epos=ostack("bnd_epos"),
            num_interior=np.asarray(
                [payloads[r]["overlap"]["num_interior"] for r in ranks],
                np.int32),
            num_boundary=np.asarray(
                [payloads[r]["overlap"]["num_boundary"] for r in ranks],
                np.int32),
            e_int_pad=int(st["e_int_pad"]), e_bnd_pad=int(st["e_bnd_pad"]),
            interior_mc=int(st.get("interior_mc", 1)),
            boundary_mc=int(st.get("boundary_mc", 1)),
        )
    return EdgePlan(
        src_index=stack("src_index"),
        dst_index=stack("dst_index"),
        edge_mask=stack("edge_mask"),
        num_local_src=counts("num_local_src"),
        num_local_dst=counts("num_local_dst"),
        num_edges=counts("num_edges"),
        halo=HaloSpec(
            send_idx=stack("send_idx"), send_mask=stack("send_mask"),
            s_pad=int(st["s_pad"]),
        ),
        world_size=int(st["world_size"]),
        n_src_pad=int(st["n_src_pad"]),
        n_dst_pad=int(st["n_dst_pad"]),
        e_pad=int(st["e_pad"]),
        halo_side=st["halo_side"],
        homogeneous=bool(st["homogeneous"]),
        owner_sorted=bool(st["owner_sorted"]),
        scatter_mc=int(st.get("scatter_mc", 1)),
        scatter_block_e=int(st["scatter_block_e"]),
        scatter_block_n=int(st["scatter_block_n"]),
        halo_deltas=tuple(int(d) for d in st["halo_deltas"]),
        halo_sort_perm=stack("halo_sort_perm") if sort_route else None,
        halo_sorted_ids=stack("halo_sorted_ids") if sort_route else None,
        halo_sort_mc=int(st.get("halo_sort_mc", 1)),
        gather_mv=int(st.get("gather_mv", 0)),
        overlap=overlap_spec,
        halo_pair_rows=pair_rows,
        halo_schedule=compile_plan_schedule(
            pair_rows, s_pad=int(st["s_pad"]),
            world_size=int(st["world_size"]),
            halo_deltas=tuple(int(d) for d in st["halo_deltas"]),
        ),
        # stamped manifests carry their build-time resolution; pre-codec
        # manifests (no key) re-resolve through the same ONE attach rule
        wire_format=st.get("wire_format") or plan_wire_format(
            int(st["world_size"]),
            tuple(int(d) for d in st["halo_deltas"]),
        ),
    )


def load_sharded_plan(
    plan_dir: str,
    *,
    ranks: Optional[list] = None,
    verify: bool = True,
    load_layout: bool = True,
) -> tuple:
    """Load ``(plan, layout)`` from a v8 sharded-plan directory, reading
    ONLY the requested ranks' shards (checksum-verified on read; the
    ``plan.load`` chaos point fires per shard).  Raises
    :class:`~dgraph_tpu.plan_shards.PlanManifestError` /
    :class:`~dgraph_tpu.plan_shards.PlanShardError` — callers that can
    rebuild (``train.checkpoint.cached_edge_plan``) repair the named
    shard; callers that cannot should surface the structured error.
    ``load_layout=False`` returns ``layout=None`` (the layout sidecar is
    O(E) — per-host shard loading has no use for it)."""
    from dgraph_tpu import plan_shards as ps

    manifest = ps.read_manifest(plan_dir)
    if not manifest.get("complete"):
        raise ps.PlanManifestError(
            ps.manifest_path(plan_dir),
            "build incomplete (resume it with build_edge_plan_sharded)",
        )
    W = manifest["world_size"]
    rank_list = list(range(W)) if ranks is None else [int(r) for r in ranks]
    payloads = {
        r: ps.read_shard(plan_dir, r, manifest["shards"][str(r)], verify=verify)
        for r in rank_list
    }
    plan = assemble_plan(manifest, payloads, rank_list)
    layout = None
    if load_layout:
        lp = ps.read_layout(plan_dir, manifest, verify=verify)
        layout = EdgePlanLayout(
            edge_rank=lp["edge_rank"],
            edge_slot=lp["edge_slot"],
            halo_counts=lp["halo_counts"],
            src_counts=lp["src_counts"],
            dst_counts=lp["dst_counts"],
        )
    return plan, layout


# ---------------------------------------------------------------------------
# Data layout helpers
# ---------------------------------------------------------------------------


def shard_vertex_data(
    x: np.ndarray, counts: np.ndarray, n_pad: int
) -> np.ndarray:
    """[V, ...] global (contiguous-block numbered) -> [W, n_pad, ...] padded."""
    W = len(counts)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    out = np.zeros((W, n_pad) + x.shape[1:], dtype=x.dtype)
    for r in range(W):
        out[r, : counts[r]] = x[offsets[r] : offsets[r + 1]]
    return out


def unshard_vertex_data(x: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """[W, n_pad, ...] -> [V, ...] dropping padding."""
    return np.concatenate([x[r, : counts[r]] for r in range(len(counts))], axis=0)


def reshard_vertex_data(
    x: np.ndarray,
    old_counts: np.ndarray,
    new_index: np.ndarray,
    new_counts: np.ndarray,
    new_n_pad: int,
) -> np.ndarray:
    """Redistribute ``[W, n_pad, ...]`` vertex-sharded data to a different
    world: ``[W', n_pad', ...]``.

    ``new_index`` maps new global vertex id -> old global vertex id (a
    :class:`~dgraph_tpu.partition.Renumbering` ``inv`` — the composition
    across generations when shrinking repeatedly), so rows follow their
    vertex through an arbitrary renumbering.  This is the checkpoint-
    reshard primitive of elastic rank-loss recovery
    (:mod:`dgraph_tpu.train.shrink`): unshard by the old counts, reorder,
    reshard by the new — the padded rows never leak between worlds.
    """
    global_x = unshard_vertex_data(np.asarray(x), old_counts)
    return shard_vertex_data(
        global_x[np.asarray(new_index)], new_counts, int(new_n_pad)
    )


def shard_edge_data(
    vals: np.ndarray, layout: EdgePlanLayout, e_pad: int
) -> np.ndarray:
    """[E, ...] per-edge data (original edge order) -> [W, e_pad, ...] padded."""
    W = layout.src_counts.shape[0]
    out = np.zeros((W, e_pad) + vals.shape[1:], dtype=vals.dtype)
    out[layout.edge_rank, layout.edge_slot] = vals
    return out
