"""Graph partitioning and renumbering (host-side, pure numpy).

Reference parity: DGraph partitions vertices round-robin
(``DGraph/data/graph.py:270``) or with METIS
(``experiments/GraphCast/data_utils/preprocess.py:14-31``,
``experiments/OGB/preprocess.py:15-27``), then renumbers vertices into
contiguous per-rank blocks and sorts edges by owner rank
(``DGraph/data/preprocess.py:6-40,84-92``).

TPU-first deltas:
- METIS is replaced by a locality-preserving spectral/RCM ordering + block
  split (no external METIS dependency; scipy's reverse Cuthill-McKee gives
  the bandwidth-minimizing order that makes block splits low-cut). A greedy
  BFS partitioner is provided as an alternative.
- Everything here runs on host at plan-build time, outside jit; the outputs
  feed :func:`dgraph_tpu.plan.build_edge_plan` which emits static-shape
  padded plans.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def round_robin_partition(num_nodes: int, world_size: int) -> np.ndarray:
    """Rank of vertex v = v % world_size.

    Parity: ``DGraph/data/graph.py:270-288`` (get_round_robin_node_rank_map).
    """
    return (np.arange(num_nodes) % world_size).astype(np.int32)


def block_partition(num_nodes: int, world_size: int) -> np.ndarray:
    """Contiguous blocks of ceil(n/w) vertices per rank (last rank may be short).

    Mirrors the reference's ``largest_split``-style uneven split
    (``DGraph/utils.py:17-26``).
    """
    per = -(-num_nodes // world_size)
    return np.minimum(np.arange(num_nodes) // per, world_size - 1).astype(np.int32)


def random_partition(num_nodes: int, world_size: int, seed: int = 0) -> np.ndarray:
    """Balanced random assignment (shuffled round-robin)."""
    rng = np.random.default_rng(seed)
    part = np.arange(num_nodes) % world_size
    rng.shuffle(part)
    return part.astype(np.int32)


def rcm_partition(edge_index: np.ndarray, num_nodes: int, world_size: int) -> np.ndarray:
    """Locality partition: reverse Cuthill-McKee ordering + balanced block split.

    METIS substitute (reference uses METIS via ``experiments/OGB/preprocess.py:15-27``):
    RCM minimizes adjacency bandwidth, so splitting the reordered vertex line
    into equal blocks yields low edge cut for mesh-like and scale-free graphs
    without an external METIS dependency.
    """
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import reverse_cuthill_mckee

    src, dst = np.asarray(edge_index[0]), np.asarray(edge_index[1])
    data = np.ones(len(src), dtype=np.int8)
    adj = coo_matrix((data, (src, dst)), shape=(num_nodes, num_nodes)).tocsr()
    adj = adj + adj.T
    order = np.asarray(reverse_cuthill_mckee(adj, symmetric_mode=True))
    part = np.empty(num_nodes, dtype=np.int32)
    per = -(-num_nodes // world_size)
    part[order] = np.minimum(np.arange(num_nodes) // per, world_size - 1)
    return part


def greedy_bfs_partition(
    edge_index: np.ndarray, num_nodes: int, world_size: int, seed: int = 0
) -> np.ndarray:
    """Greedy BFS region-growing partition with a hard balance cap.

    Grows each partition from an unassigned seed vertex by BFS until it holds
    ceil(n/w) vertices, then moves to the next partition. Cheap, deterministic,
    and cut-quality between round-robin and METIS. Dispatches to the native
    C++ implementation (csrc/dgraph_host.cpp) when built — the python loop
    below is the fallback-and-oracle.
    """
    from dgraph_tpu import native

    if native.available():
        return native.greedy_bfs_partition(edge_index, num_nodes, world_size, seed)
    from scipy.sparse import coo_matrix

    src, dst = np.asarray(edge_index[0]), np.asarray(edge_index[1])
    data = np.ones(len(src), dtype=np.int8)
    adj = coo_matrix((data, (src, dst)), shape=(num_nodes, num_nodes)).tocsr()
    adj = (adj + adj.T).tocsr()

    cap = -(-num_nodes // world_size)
    part = np.full(num_nodes, -1, dtype=np.int32)
    rng = np.random.default_rng(seed)
    unassigned_ptr = 0
    order = np.arange(num_nodes)
    rng.shuffle(order)

    for r in range(world_size):
        count = 0
        frontier: list[int] = []
        while count < cap:
            if not frontier:
                # find a fresh seed
                while unassigned_ptr < num_nodes and part[order[unassigned_ptr]] >= 0:
                    unassigned_ptr += 1
                if unassigned_ptr >= num_nodes:
                    break
                frontier = [int(order[unassigned_ptr])]
            v = frontier.pop()
            if part[v] >= 0:
                continue
            part[v] = r
            count += 1
            nbrs = adj.indices[adj.indptr[v] : adj.indptr[v + 1]]
            frontier.extend(int(n) for n in nbrs if part[n] < 0)
    part[part < 0] = world_size - 1
    return part


def multilevel_partition(
    edge_index: np.ndarray, num_nodes: int, world_size: int, seed: int = 0
) -> np.ndarray:
    """Multilevel k-way partition — the METIS-shaped algorithm the reference
    uses via pymetis for its quality partitions (``experiments/OGB/
    preprocess.py:15-27``, ``GraphCast/data_utils/preprocess.py:14-31``):
    heavy-edge-matching coarsening, weighted greedy growth on the coarsest
    graph, FM-lite boundary refinement on the way back up.

    Native C++ only (csrc/dgraph_host.cpp) — a Python multilevel stack would
    defeat its purpose at scale; when the library is unavailable this falls
    back to :func:`greedy_bfs_partition` (the next-best cut quality here)
    with a warning.
    """
    from dgraph_tpu import native

    if native.available():
        return native.multilevel_partition(edge_index, num_nodes, world_size, seed)
    import warnings

    warnings.warn(
        "native library unavailable; multilevel partition falling back to "
        "greedy_bfs (worse cut quality)", stacklevel=2,
    )
    return greedy_bfs_partition(edge_index, num_nodes, world_size, seed)


@dataclasses.dataclass(frozen=True)
class Renumbering:
    """Vertex renumbering into contiguous per-rank blocks.

    Parity: ``DGraph/data/preprocess.py:6-40`` (node_renumbering). Contiguity
    is what lets the halo ordering convention (sorted global id == grouped by
    owner rank) hold — the same invariant the reference relies on when it
    concatenates per-rank recv segments into the halo buffer
    (``DGraph/distributed/commInfo.py:35-62`` + recv_offset ordering).

    Attributes:
      perm: old_id -> new_id (apply to edge lists as ``perm[edges]``).
      inv: new_id -> old_id (apply to feature matrices as ``x[inv]``).
      partition: [V] rank per NEW vertex id (non-decreasing).
      counts: [W] vertices owned per rank.
      offsets: [W+1] block start offsets in the new numbering.
    """

    perm: np.ndarray
    inv: np.ndarray
    partition: np.ndarray
    counts: np.ndarray
    offsets: np.ndarray


def renumber_contiguous(partition: np.ndarray, world_size: int) -> Renumbering:
    """Stable-sort vertices by rank so each rank owns a contiguous id block."""
    partition = np.asarray(partition)
    inv = np.argsort(partition, kind="stable")
    perm = np.empty_like(inv)
    perm[inv] = np.arange(len(inv))
    counts = np.bincount(partition, minlength=world_size).astype(np.int64)
    offsets = np.zeros(world_size + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    new_part = partition[inv].astype(np.int32)
    return Renumbering(perm=perm, inv=inv, partition=new_part, counts=counts, offsets=offsets)


def partition_graph(
    edge_index: np.ndarray,
    num_nodes: int,
    world_size: int,
    method: str = "rcm",
    seed: int = 0,
) -> tuple[np.ndarray, Renumbering]:
    """Partition + renumber in one call.

    Returns (renumbered_edge_index [2, E], renumbering). Edge endpoints are
    remapped into the new contiguous numbering; edge order is preserved.
    """
    if method == "round_robin":
        part = round_robin_partition(num_nodes, world_size)
    elif method == "block":
        part = block_partition(num_nodes, world_size)
    elif method == "random":
        part = random_partition(num_nodes, world_size, seed)
    elif method == "rcm":
        part = rcm_partition(edge_index, num_nodes, world_size)
    elif method == "greedy_bfs":
        part = greedy_bfs_partition(edge_index, num_nodes, world_size, seed)
    elif method in ("multilevel", "metis"):
        part = multilevel_partition(edge_index, num_nodes, world_size, seed)
    else:
        raise ValueError(f"unknown partition method: {method!r}")
    ren = renumber_contiguous(part, world_size)
    new_edges = ren.perm[np.asarray(edge_index)]
    return new_edges, ren


def edge_cut(edge_index: np.ndarray, partition: np.ndarray) -> float:
    """Fraction of edges crossing partitions (quality metric)."""
    src, dst = edge_index[0], edge_index[1]
    return float(np.mean(partition[src] != partition[dst]))
