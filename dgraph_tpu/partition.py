"""Graph partitioning and renumbering (host-side, pure numpy).

Reference parity: DGraph partitions vertices round-robin
(``DGraph/data/graph.py:270``) or with METIS
(``experiments/GraphCast/data_utils/preprocess.py:14-31``,
``experiments/OGB/preprocess.py:15-27``), then renumbers vertices into
contiguous per-rank blocks and sorts edges by owner rank
(``DGraph/data/preprocess.py:6-40,84-92``).

TPU-first deltas:
- METIS is replaced by a locality-preserving spectral/RCM ordering + block
  split (no external METIS dependency; scipy's reverse Cuthill-McKee gives
  the bandwidth-minimizing order that makes block splits low-cut). A greedy
  BFS partitioner is provided as an alternative.
- Everything here runs on host at plan-build time, outside jit; the outputs
  feed :func:`dgraph_tpu.plan.build_edge_plan` which emits static-shape
  padded plans.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


def round_robin_partition(num_nodes: int, world_size: int) -> np.ndarray:
    """Rank of vertex v = v % world_size.

    Parity: ``DGraph/data/graph.py:270-288`` (get_round_robin_node_rank_map).
    """
    return (np.arange(num_nodes) % world_size).astype(np.int32)


def block_partition(num_nodes: int, world_size: int) -> np.ndarray:
    """Contiguous blocks of ceil(n/w) vertices per rank (last rank may be short).

    Mirrors the reference's ``largest_split``-style uneven split
    (``DGraph/utils.py:17-26``).
    """
    per = -(-num_nodes // world_size)
    return np.minimum(np.arange(num_nodes) // per, world_size - 1).astype(np.int32)


def random_partition(num_nodes: int, world_size: int, seed: int = 0) -> np.ndarray:
    """Balanced random assignment (shuffled round-robin)."""
    rng = np.random.default_rng(seed)
    part = np.arange(num_nodes) % world_size
    rng.shuffle(part)
    return part.astype(np.int32)


def rcm_partition(edge_index: np.ndarray, num_nodes: int, world_size: int) -> np.ndarray:
    """Locality partition: reverse Cuthill-McKee ordering + balanced block split.

    METIS substitute (reference uses METIS via ``experiments/OGB/preprocess.py:15-27``):
    RCM minimizes adjacency bandwidth, so splitting the reordered vertex line
    into equal blocks yields low edge cut for mesh-like and scale-free graphs
    without an external METIS dependency.
    """
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import reverse_cuthill_mckee

    src, dst = np.asarray(edge_index[0]), np.asarray(edge_index[1])
    data = np.ones(len(src), dtype=np.int8)
    adj = coo_matrix((data, (src, dst)), shape=(num_nodes, num_nodes)).tocsr()
    adj = adj + adj.T
    order = np.asarray(reverse_cuthill_mckee(adj, symmetric_mode=True))
    part = np.empty(num_nodes, dtype=np.int32)
    per = -(-num_nodes // world_size)
    part[order] = np.minimum(np.arange(num_nodes) // per, world_size - 1)
    return part


def greedy_bfs_partition(
    edge_index: np.ndarray, num_nodes: int, world_size: int, seed: int = 0
) -> np.ndarray:
    """Greedy BFS region-growing partition with a hard balance cap.

    Grows each partition from an unassigned seed vertex by BFS until it holds
    ceil(n/w) vertices, then moves to the next partition. Cheap, deterministic,
    and cut-quality between round-robin and METIS. Dispatches to the native
    C++ implementation (csrc/dgraph_host.cpp) when built — the python loop
    below is the fallback-and-oracle.
    """
    from dgraph_tpu import native

    if native.available():
        return native.greedy_bfs_partition(edge_index, num_nodes, world_size, seed)
    from scipy.sparse import coo_matrix

    src, dst = np.asarray(edge_index[0]), np.asarray(edge_index[1])
    data = np.ones(len(src), dtype=np.int8)
    adj = coo_matrix((data, (src, dst)), shape=(num_nodes, num_nodes)).tocsr()
    adj = (adj + adj.T).tocsr()

    cap = -(-num_nodes // world_size)
    part = np.full(num_nodes, -1, dtype=np.int32)
    rng = np.random.default_rng(seed)
    unassigned_ptr = 0
    order = np.arange(num_nodes)
    rng.shuffle(order)

    for r in range(world_size):
        count = 0
        frontier: list[int] = []
        while count < cap:
            if not frontier:
                # find a fresh seed
                while unassigned_ptr < num_nodes and part[order[unassigned_ptr]] >= 0:
                    unassigned_ptr += 1
                if unassigned_ptr >= num_nodes:
                    break
                frontier = [int(order[unassigned_ptr])]
            v = frontier.pop()
            if part[v] >= 0:
                continue
            part[v] = r
            count += 1
            nbrs = adj.indices[adj.indptr[v] : adj.indptr[v + 1]]
            frontier.extend(int(n) for n in nbrs if part[n] < 0)
    part[part < 0] = world_size - 1
    return part


def multilevel_partition(
    edge_index: np.ndarray, num_nodes: int, world_size: int, seed: int = 0
) -> np.ndarray:
    """Multilevel k-way partition — the METIS-shaped algorithm the reference
    uses via pymetis for its quality partitions (``experiments/OGB/
    preprocess.py:15-27``, ``GraphCast/data_utils/preprocess.py:14-31``):
    heavy-edge-matching coarsening, weighted greedy growth on the coarsest
    graph, FM-lite boundary refinement on the way back up.

    Native C++ only (csrc/dgraph_host.cpp) — a Python multilevel stack would
    defeat its purpose at scale; when the library is unavailable this falls
    back to :func:`greedy_bfs_partition` (the next-best cut quality here)
    with a warning.
    """
    from dgraph_tpu import native

    if native.available():
        return native.multilevel_partition(edge_index, num_nodes, world_size, seed)
    import warnings

    warnings.warn(
        "native library unavailable; multilevel partition falling back to "
        "greedy_bfs (worse cut quality)", stacklevel=2,
    )
    return greedy_bfs_partition(edge_index, num_nodes, world_size, seed)


def multilevel_big_partition(
    edge_index: np.ndarray,
    num_nodes: int,
    world_size: int,
    seed: int = 0,
    max_cluster_weight: int = 12,
    refine_passes: int = 3,
    chunk: int = 1 << 26,
) -> np.ndarray:
    """Memory-bounded METIS-shaped partition for graphs the in-RAM
    multilevel stack cannot hold (VERDICT r4 #6: 22M nodes peaked 104 GB
    RSS; full papers100M would need >250 GB and 17-33 h).

    Pipeline (host peak = one int32 CSR + O(V) arrays + the coarse graph):

    1. capped greedy cluster coarsening (native ``cluster_coarsen_c``,
       ~4 bytes x 2E CSR) — one aggressive level instead of ~log V
       matching levels;
    2. chunked numpy contraction to unique weighted coarse pairs (the
       edge list may be a disk memmap; per-chunk dedup happens before
       the merged dedup, but the merge itself still sorts ALL surviving
       pairs — on hub-heavy graphs coarse pairs stay near E (measured
       ~0.93E even at 16x vertex reduction), so the merge transient is
       O(E) ints, not bounded; :func:`multilevel_sampled_partition` is
       the default full-papers100M path for exactly this reason);
    3. the full in-RAM multilevel+FM+volume-polish stack on the coarse
       graph (native ``multilevel_partition_w_c`` — balance objective is
       summed fine-vertex weight);
    4. projection + greedy boundary refinement on the fine graph (native
       ``refine_unweighted_csr_c``, same int32-CSR memory form).

    Falls back to :func:`greedy_bfs_partition` with a warning when the
    native library is unavailable (same policy as multilevel).
    """
    from dgraph_tpu import native

    if not native.available():
        import warnings

        warnings.warn(
            "native library unavailable; multilevel_big falling back to "
            "greedy_bfs (worse cut quality)", stacklevel=2,
        )
        return greedy_bfs_partition(edge_index, num_nodes, world_size, seed)

    src, dst = edge_index[0], edge_index[1]
    cmap, nc = native.cluster_coarsen(
        edge_index, num_nodes, max_cluster_weight, seed
    )

    # chunked contraction: map endpoints through cmap, drop intra-cluster
    # edges, dedup-accumulate (lo, hi) pair multiplicities
    enc_parts, cnt_parts = [], []
    E = src.shape[0]
    for lo_e in range(0, E, chunk):
        hi_e = min(lo_e + chunk, E)
        cu = cmap[np.asarray(src[lo_e:hi_e])]
        cv = cmap[np.asarray(dst[lo_e:hi_e])]
        lo = np.minimum(cu, cv)
        hi = np.maximum(cu, cv)
        keep = lo != hi
        enc = lo[keep] * nc + hi[keep]
        u, c = np.unique(enc, return_counts=True)
        enc_parts.append(u)
        cnt_parts.append(c.astype(np.int64))
    enc = np.concatenate(enc_parts) if enc_parts else np.zeros(0, np.int64)
    cnt = np.concatenate(cnt_parts) if cnt_parts else np.zeros(0, np.int64)
    del enc_parts, cnt_parts
    # no kind="stable": reduceat sums equal keys regardless of their
    # relative order, and introsort skips mergesort's working buffer
    order = np.argsort(enc)
    enc, cnt = enc[order], cnt[order]
    del order
    starts = np.flatnonzero(
        np.concatenate([[True], enc[1:] != enc[:-1]])
    ) if len(enc) else np.zeros(0, np.int64)
    uniq = enc[starts]
    w = np.add.reduceat(cnt, starts) if len(starts) else cnt
    del enc, cnt
    vw = np.bincount(cmap, minlength=nc).astype(np.int64)

    cpart = native.multilevel_partition_weighted(
        uniq // nc, uniq % nc, w, vw, nc, world_size, seed
    )
    part = cpart[cmap].astype(np.int32)
    return native.refine_unweighted_csr(
        edge_index, num_nodes, world_size, part, passes=refine_passes
    )


def multilevel_sampled_partition(
    edge_index: np.ndarray,
    num_nodes: int,
    world_size: int,
    seed: int = 0,
    sample_frac: float = 0.5,
    refine_passes: int = 3,
    chunk: int = 1 << 26,
    edge_balance: float = 0.0,
) -> np.ndarray:
    """Full multilevel+FM stack on a uniform edge sample, then greedy
    boundary refinement on the full graph (native
    ``refine_unweighted_csr_c``).

    Uniform sampling keeps the EXPECTED cut of every candidate partition
    proportional to its true cut, so the multilevel optimizer sees an
    unbiased objective at ``sample_frac`` of the memory/time — the lever
    that brings full papers100M (111M nodes / 1.6B edges) inside this
    host's RAM (VERDICT r4 #6), where the unsampled stack needs >250 GB
    and 17-33 h. With the supernode-weight bound + rebalance in the
    native core, measured power-law W=8 cuts MATCH the full stack at half
    the edges: 120k -> sampled 0.7500 vs full 0.7505; 500k -> 0.7499 vs
    0.7470 (both balance <= 1.03). The full-scale run logs its record to
    logs/p100m_fullscale_r5.jsonl (produced by the r5 background run).

    The sample is drawn chunk-wise so ``edge_index`` may be a disk memmap.
    """
    from dgraph_tpu import native

    if not native.available():
        import warnings

        warnings.warn(
            "native library unavailable; multilevel_sampled falling back "
            "to greedy_bfs (worse cut quality)", stacklevel=2,
        )
        return greedy_bfs_partition(edge_index, num_nodes, world_size, seed)

    rng = np.random.default_rng(seed)
    E = edge_index.shape[1]
    parts = []
    deg_in = (
        np.zeros(num_nodes, np.int64) if edge_balance > 0 else None
    )
    for lo in range(0, E, chunk):
        hi = min(lo + chunk, E)
        blk = np.asarray(edge_index[:, lo:hi])
        if deg_in is not None:
            # plans own edges at the dst vertex, so per-rank edge volume
            # is summed IN-degree of owned vertices — that's the weight
            # that co-balances e_pad
            deg_in += np.bincount(blk[1], minlength=num_nodes)
        keep = rng.random(hi - lo) < sample_frac
        parts.append(blk[:, keep])
    sub = np.ascontiguousarray(np.concatenate(parts, axis=1))
    del parts
    if deg_in is not None:
        # vw = 16 + round(16*alpha*deg/mean_deg): Σvw ≈ 16V(1+alpha); the
        # x16 scale keeps integer rounding from quantizing small alphas.
        # A vertex-balanced partition leaves owner-edge volume ~1.28x
        # imbalanced at papers100M scale (logs/p100m_fullscale_r5.jsonl
        # e_pad) because hub in-degrees concentrate; the blend trades a
        # little vertex padding (n_pad) for edge balance (e_pad).
        mean_deg = max(E / num_nodes, 1e-9)
        vw = 16 + np.rint(16.0 * edge_balance * deg_in / mean_deg).astype(
            np.int64
        )
        del deg_in
        part = native.multilevel_partition_vertex_weighted(
            sub, vw, num_nodes, world_size, seed
        )
        del sub
        # refine under the SAME weights: a unit-count refine rebalances
        # vertex counts to 1.03 and undoes the edge balance (measured at
        # 2M: e_imb 1.14 pre-refine -> 1.25 post-unit-refine)
        return native.refine_weighted_csr(
            edge_index, vw, num_nodes, world_size, part,
            passes=refine_passes,
        )
    part = multilevel_partition(sub, num_nodes, world_size, seed)
    del sub
    return native.refine_unweighted_csr(
        edge_index, num_nodes, world_size, part, passes=refine_passes
    )


@dataclasses.dataclass(frozen=True)
class Renumbering:
    """Vertex renumbering into contiguous per-rank blocks.

    Parity: ``DGraph/data/preprocess.py:6-40`` (node_renumbering). Contiguity
    is what lets the halo ordering convention (sorted global id == grouped by
    owner rank) hold — the same invariant the reference relies on when it
    concatenates per-rank recv segments into the halo buffer
    (``DGraph/distributed/commInfo.py:35-62`` + recv_offset ordering).

    Attributes:
      perm: old_id -> new_id (apply to edge lists as ``perm[edges]``).
      inv: new_id -> old_id (apply to feature matrices as ``x[inv]``).
      partition: [V] rank per NEW vertex id (non-decreasing).
      counts: [W] vertices owned per rank.
      offsets: [W+1] block start offsets in the new numbering.
    """

    perm: np.ndarray
    inv: np.ndarray
    partition: np.ndarray
    counts: np.ndarray
    offsets: np.ndarray


def renumber_contiguous(partition: np.ndarray, world_size: int) -> Renumbering:
    """Stable-sort vertices by rank so each rank owns a contiguous id block."""
    partition = np.asarray(partition)
    inv = np.argsort(partition, kind="stable")
    perm = np.empty_like(inv)
    perm[inv] = np.arange(len(inv))
    counts = np.bincount(partition, minlength=world_size).astype(np.int64)
    offsets = np.zeros(world_size + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    new_part = partition[inv].astype(np.int32)
    return Renumbering(perm=perm, inv=inv, partition=new_part, counts=counts, offsets=offsets)


def partition_graph(
    edge_index: np.ndarray,
    num_nodes: int,
    world_size: int,
    method: str = "rcm",
    seed: int = 0,
    *,
    sample_frac: Optional[float] = None,
    edge_balance: Optional[float] = None,
) -> tuple[np.ndarray, Renumbering]:
    """Partition + renumber in one call.

    Returns (renumbered_edge_index [2, E], renumbering). Edge endpoints are
    remapped into the new contiguous numbering; edge order is preserved.

    ``sample_frac`` / ``edge_balance`` tune ``method="multilevel_sampled"``
    (the full-scale papers100M settings are 0.35 / 1.0 — BASELINE.md /
    scripts/p100m_r5_stages.py, now reachable through this standard API
    instead of only the staged script, ADVICE r5). Passing either with any
    other method raises: the knob would be silently ignored, and a "tuned"
    run that never saw its tuning is the worst kind of benchmark.
    """
    if method != "multilevel_sampled" and (
        sample_frac is not None or edge_balance is not None
    ):
        raise ValueError(
            f"sample_frac/edge_balance only apply to method="
            f"'multilevel_sampled', got method={method!r}"
        )
    if method == "round_robin":
        part = round_robin_partition(num_nodes, world_size)
    elif method == "block":
        part = block_partition(num_nodes, world_size)
    elif method == "random":
        part = random_partition(num_nodes, world_size, seed)
    elif method == "rcm":
        part = rcm_partition(edge_index, num_nodes, world_size)
    elif method == "greedy_bfs":
        part = greedy_bfs_partition(edge_index, num_nodes, world_size, seed)
    elif method in ("multilevel", "metis"):
        part = multilevel_partition(edge_index, num_nodes, world_size, seed)
    elif method == "multilevel_big":
        part = multilevel_big_partition(edge_index, num_nodes, world_size, seed)
    elif method == "multilevel_sampled":
        kw = {}
        if sample_frac is not None:
            kw["sample_frac"] = sample_frac
        if edge_balance is not None:
            kw["edge_balance"] = edge_balance
        part = multilevel_sampled_partition(
            edge_index, num_nodes, world_size, seed, **kw
        )
    else:
        raise ValueError(f"unknown partition method: {method!r}")
    ren = renumber_contiguous(part, world_size)
    new_edges = ren.perm[np.asarray(edge_index)]
    return new_edges, ren


def fold_partition(
    partition: np.ndarray, world_size: int, lost_ranks
) -> tuple[np.ndarray, dict]:
    """Shrink-to-fit a partition: deterministically reassign the LOST
    ranks' vertices to the survivors and compact surviving rank ids to
    ``0..W'-1``.

    This is the redistribution step of elastic rank-loss recovery
    (:mod:`dgraph_tpu.train.shrink`): instead of re-partitioning from
    scratch (which would move *every* vertex and invalidate locality the
    tuner already priced), only the dead ranks' blocks move.  Allocation
    is a waterfill — each survivor receives enough orphaned vertices to
    equalize final loads (ties broken toward lower survivor ids), and the
    orphans are handed out in vertex order as contiguous chunks per
    survivor, preserving intra-block locality.  The whole fold is a pure
    function of ``(partition, lost_ranks)``, so a crashed recovery that
    reruns — or a fault-free run shrunk from the same inputs — lands the
    identical partition (the bit-identical degraded-resume contract).

    Returns ``(new_partition, survivor_map)`` where ``new_partition`` is
    over the SAME vertex numbering as the input (run
    :func:`renumber_contiguous` before building a plan) and
    ``survivor_map`` maps old surviving rank id -> new compact id.
    """
    part = np.asarray(partition)
    lost = sorted(set(int(r) for r in lost_ranks))
    if not lost:
        raise ValueError("fold_partition: lost_ranks is empty")
    for r in lost:
        if not 0 <= r < world_size:
            raise ValueError(
                f"fold_partition: lost rank {r} not in [0, {world_size})"
            )
    survivors = [r for r in range(world_size) if r not in lost]
    if not survivors:
        raise ValueError("fold_partition: no surviving ranks")
    survivor_map = {old: new for new, old in enumerate(survivors)}
    S = len(survivors)
    counts = np.bincount(part, minlength=world_size).astype(np.int64)
    loads = counts[survivors].copy()
    orphans = np.flatnonzero(np.isin(part, lost))
    L = orphans.size
    # waterfill: smallest final max-load, deterministic. Find the lowest
    # integer level T with sum(max(0, T - load)) >= L, allocate up to T,
    # then trim the surplus from the HIGHEST-id survivors (stable rule).
    lo, hi = int(loads.min()), int(loads.max()) + L
    while lo < hi:
        mid = (lo + hi) // 2
        if int(np.clip(mid - loads, 0, None).sum()) >= L:
            hi = mid
        else:
            lo = mid + 1
    alloc = np.clip(lo - loads, 0, None).astype(np.int64)
    surplus = int(alloc.sum()) - L
    for i in range(S - 1, -1, -1):
        if surplus <= 0:
            break
        take = min(surplus, int(alloc[i]))
        alloc[i] -= take
        surplus -= take
    new_part = np.empty_like(part, dtype=np.int32)
    # survivors keep their vertices under compacted ids
    remap = np.full(world_size, -1, dtype=np.int32)
    for old, new in survivor_map.items():
        remap[old] = new
    keep = ~np.isin(part, lost)
    new_part[keep] = remap[part[keep]]
    # orphans: contiguous chunks per survivor, in vertex order
    new_part[orphans] = np.repeat(
        np.arange(S, dtype=np.int32), alloc
    )
    return new_part, survivor_map


def unfold_partition(
    partition: np.ndarray, world_size: int, k: int
) -> tuple[np.ndarray, dict]:
    """Grow-to-fit a partition: deterministically donate tail chunks of
    the existing ranks' blocks to ``k`` NEW ranks (ids ``world_size ..
    world_size+k-1``) — the waterfill inverse of :func:`fold_partition`.

    This is the redistribution step of elastic rank-arrival recovery
    (:mod:`dgraph_tpu.train.grow`): instead of re-partitioning from
    scratch (which would move *every* vertex and invalidate locality the
    tuner already priced), existing ranks' kept vertices never move —
    each over-level rank donates only the TAIL of its block (its
    highest-id vertices, so the keepers stay a contiguous prefix after
    :func:`renumber_contiguous`).  The level is a waterfill mirror of
    the fold's: the lowest integer ``T`` such that capping every
    existing rank at ``T`` frees enough vertices to fill ``k`` newcomers
    to at most ``T`` each; newcomer allocations are trimmed from the
    HIGHEST-id newcomers first (the same stable tie rule the fold trims
    survivors with), and donated vertices are handed out in vertex
    order as contiguous chunks per newcomer.  The whole unfold is a
    pure function of ``(partition, k)``, so a crashed recovery that
    reruns lands the identical partition — and on a renumbered
    partition whose donated chunks sit at the high end of vertex order,
    ``fold_partition(unfold_partition(p, W, k)[0], W+k, [W..W+k-1])``
    restores ``p`` exactly (pinned by ``tests/test_grow.py``).

    Returns ``(new_partition, donor_map)`` where ``new_partition`` is
    over the SAME vertex numbering as the input (run
    :func:`renumber_contiguous` before building a plan) and
    ``donor_map`` maps donating old rank id -> number of vertices it
    donated.
    """
    part = np.asarray(partition)
    k = int(k)
    if k < 1:
        raise ValueError(f"unfold_partition: k must be >= 1, got {k}")
    counts = np.bincount(part, minlength=world_size).astype(np.int64)
    if len(counts) > world_size:
        raise ValueError(
            f"unfold_partition: partition names rank "
            f"{len(counts) - 1} >= world_size {world_size}"
        )
    # waterfill level: lowest integer T with
    # sum(min(counts, T)) + k*T >= total, i.e. capping every existing
    # rank at T frees enough orphans to fill k newcomers to <= T each —
    # the smallest achievable final max-load, deterministic
    lo, hi = 0, int(counts.max(initial=0))
    while lo < hi:
        mid = (lo + hi) // 2
        if int(np.clip(counts - mid, 0, None).sum()) <= k * mid:
            hi = mid
        else:
            lo = mid + 1
    level = lo
    donate = np.clip(counts - level, 0, None).astype(np.int64)
    donated_total = int(donate.sum())
    alloc = np.full(k, level, dtype=np.int64)
    surplus = k * level - donated_total
    for i in range(k - 1, -1, -1):
        if surplus <= 0:
            break
        take = min(surplus, int(alloc[i]))
        alloc[i] -= take
        surplus -= take
    new_part = part.astype(np.int32).copy()
    donated_ids = [
        # the donor's TAIL: its highest-id vertices, so the kept block
        # stays a contiguous prefix under the existing numbering
        np.flatnonzero(part == r)[-int(donate[r]):]
        for r in np.flatnonzero(donate)
    ]
    if donated_ids:
        donated_sorted = np.sort(np.concatenate(donated_ids))
        new_part[donated_sorted] = world_size + np.repeat(
            np.arange(k, dtype=np.int32), alloc
        )
    donor_map = {int(r): int(donate[r]) for r in np.flatnonzero(donate)}
    return new_part, donor_map


def edge_cut(edge_index: np.ndarray, partition: np.ndarray) -> float:
    """Fraction of edges crossing partitions (quality metric)."""
    src, dst = edge_index[0], edge_index[1]
    return float(np.mean(partition[src] != partition[dst]))
