"""Communicator facade — the user-facing API, parity with
``DGraph/Communicator.py`` (SURVEY.md §1 L4).

The reference validates a backend name in {nccl, mpi, nvshmem} and forwards
every call to a backend engine (``Communicator.py:24-141``). On TPU there is
one runtime (XLA), so the "backends" collapse to two *modes*:

- ``"tpu"`` (:class:`TpuComm`): SPMD over a mesh axis; methods must be
  called inside ``shard_map`` (or a jitted function with the mesh bound).
  Collectives lower to XLA ``all_to_all``/``psum`` over ICI/DCN — the
  NCCL/NVSHMEM/MPI wire mechanics (SURVEY.md §2.4) are all subsumed.
- ``"single"`` (:class:`SingleComm`): world size 1, no collectives — the
  reference's ``SingleProcessDummyCommunicator`` pattern
  (``GraphCast/dist_utils.py:8-39``), used so model code is testable
  without a mesh. Model code is byte-identical under either comm — the
  reference's key "fake backend" design point, kept on purpose.

Unlike the reference there is no process-group initialization to perform
(no ``init_process_group`` collective; ``jax.distributed.initialize`` is
only needed for true multi-host runs and is orthogonal to this object), so
``Communicator.init_process_group`` simply constructs the right comm object.
Methods that exist purely for API parity (``barrier``, ``destroy``,
``alloc_buffer``) are cheap no-ops or jnp allocations, documented as such.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.comm import collectives
from dgraph_tpu.comm.mesh import GRAPH_AXIS, REPLICA_AXIS
from dgraph_tpu.plan import EdgePlan, HaloSpec

# Every collective issued through the facade carries a named region so
# Perfetto traces (utils.timing.trace_to) attribute wire time to the API
# call that caused it (collectives.py annotates the primitive layer the
# same way).
from dgraph_tpu.utils.timing import named_scope as _scoped


@dataclasses.dataclass(frozen=True)
class _BaseComm:
    """Static (hashable, non-pytree) comm descriptor; safe as a flax module
    attribute or jit static arg."""

    graph_axis: Optional[str]
    replica_axis: Optional[str]

    # -- world/rank introspection (inside shard_map for tpu mode) --
    def get_rank(self):
        if self.graph_axis is None:
            return 0
        return lax.axis_index(self.graph_axis)

    def get_world_size(self) -> int:
        raise NotImplementedError

    # -- the differentiable primitives (L5) --
    def halo_exchange(self, x, halo: HaloSpec, deltas=None, impl=None,
                      wire_format=None):
        """Exchange boundary features. ``deltas``/``impl``/``wire_format``
        (from the plan / :func:`collectives.resolve_plan_impl` /
        :func:`collectives.resolve_plan_wire_format`) select the lowering
        and payload codec — resolve once per call site and thread them, so
        one jitted step can never mix lowerings (plan-less callers default
        to the padded all_to_all with the fp32 identity wire)."""
        return collectives.halo_exchange(
            x, halo, self.graph_axis, deltas=deltas, impl=impl,
            wire_format=wire_format,
        )

    def halo_exchange_overlap(self, x, plan: EdgePlan):
        """The overlap lowering's exchange: double-buffered ppermute rounds
        whose [W*S, F] result the boundary takes index directly."""
        return collectives.halo_exchange_overlap(
            x, plan.halo, self.graph_axis, tuple(plan.halo_deltas),
            collectives.resolve_plan_wire_format(plan, self.graph_axis),
        )

    def overlap_active(self, plan: EdgePlan) -> bool:
        """True when this plan lowers its halo exchange as the
        interior/boundary overlap schedule (models' routing predicate)."""
        return collectives.overlap_active(plan, self.graph_axis)

    def split_active(self, plan: EdgePlan) -> bool:
        """True when this plan routes through the interior/boundary split
        under EITHER split lowering — 'overlap' (ppermute rounds) or
        'pallas_p2p' (device-initiated one-sided puts). The models'
        routing predicate; :meth:`halo_exchange_split` picks the
        transport."""
        return collectives.split_active(plan, self.graph_axis)

    def halo_exchange_split(self, x, plan: EdgePlan):
        """The split lowerings' exchange: overlap ppermute rounds or
        pallas_p2p one-sided puts (one resolution decides), producing the
        [W*S, F] buffer the boundary takes index directly."""
        return collectives.halo_exchange_split(x, plan, self.graph_axis)

    def interior_take(self, x, plan: EdgePlan, side: str = "src"):
        """Interior-subset per-edge rows from the local table (no
        dependence on the in-flight exchange)."""
        return collectives.interior_take(x, plan, side)

    def boundary_take(self, x_or_halo, plan: EdgePlan, side: str = "src"):
        """Boundary-subset per-edge rows (halo side reads the exchange
        output buffer; owner side reads the local table)."""
        return collectives.boundary_take(x_or_halo, plan, side)

    def interior_scatter_sum(self, edata_int, plan: EdgePlan, side: str = "dst"):
        return collectives.interior_scatter_sum(edata_int, plan, side)

    def boundary_scatter_sum(self, edata_bnd, plan: EdgePlan, side: str = "dst"):
        return collectives.boundary_scatter_sum(edata_bnd, plan, side)

    def gather_scatter_overlap(self, x_local, halo_buf, plan: EdgePlan,
                               edge_weight=None):
        """Overlap-scheduled neighbor sum into the owner side (interior
        from the local table while the boundary rounds fly, then merge)."""
        return collectives.gather_scatter_overlap(
            x_local, halo_buf, plan, edge_weight
        )

    def scatter_bias_relu_overlap(self, stream_local, halo_buf, bias,
                                  plan: EdgePlan, side: str = "dst",
                                  edge_weight=None):
        """Overlap-scheduled fused Σ w·relu(stream + bias) aggregation."""
        return collectives.scatter_bias_relu_overlap(
            stream_local, halo_buf, bias, plan, side, self.graph_axis,
            edge_weight,
        )

    def gather(self, x, plan: EdgePlan, side: str = "src"):
        return collectives.gather(x, plan, side, self.graph_axis)

    def halo_extend(self, x, plan: EdgePlan, side: str = "src"):
        """gather's communication half: ONE full-width halo exchange ->
        the extended vertex table. Pair with local_take to feature-chunk
        the local work without re-issuing the collective per chunk."""
        return collectives.halo_extend(x, plan, side, self.graph_axis)

    def local_take(self, x_full, plan: EdgePlan, side: str = "src"):
        """gather's local half (no collectives): per-edge rows from the
        halo-extended table."""
        return collectives.local_take(x_full, plan, side)

    def gather_concat(self, x_src, x_dst, plan: EdgePlan):
        return collectives.gather_concat(x_src, x_dst, plan, self.graph_axis)

    def scatter(self, edata, plan: EdgePlan, side: str = "dst"):
        """Scatter-add per-edge values to vertices (``op=sum`` only, like the
        reference's maintained path, ``NCCLBackendEngine.py:183-215``)."""
        return collectives.scatter_sum(edata, plan, side, self.graph_axis)

    scatter_sum = scatter

    def scatter_bias_relu(self, edata, bias, plan: EdgePlan, side: str = "dst",
                          edge_weight=None):
        """Fused Σ w·relu(edata + bias[owner]) aggregation (the reference's
        fused scatter kernel family; Pallas on TPU, composed ops elsewhere)."""
        return collectives.scatter_bias_relu(
            edata, bias, plan, side, self.graph_axis, edge_weight
        )

    @_scoped("dgraph.comm.put")
    def put(self, send: jax.Array) -> jax.Array:
        """Deliver per-peer blocks by offsets — the ``BackendEngine.put``
        contract (``Engine.py:67-86``): two-sided backends alltoallv the
        blocks; one-sided backends write them at precomputed remote
        offsets. On TPU both collapse to ONE ``lax.all_to_all`` whose
        received blocks land in sender-rank order — exactly the
        ``CommPattern.put_forward_remote_offset`` positions (the plan's
        halo-slot numbering), so no receive-placement pass exists.

        Args:
          send: [W, S, F] — block ``send[p]`` goes to peer p (pad to the
            common S; mask padding upstream).
        Returns: [W*S, F]; rows [p*S, (p+1)*S) hold peer p's block.
        """
        W, S, F = send.shape
        if self.graph_axis is None:
            if W != 1:
                raise ValueError("put with world_size 1 expects send.shape[0] == 1")
            return send.reshape(S, F)
        recv = lax.all_to_all(send, self.graph_axis, split_axis=0, concat_axis=0)
        return recv.reshape(W * S, F)

    @_scoped("dgraph.comm.seq_attention")
    def seq_attention(self, q, k, v, *, causal: bool = False, kv_mask=None,
                      impl: str = "ring"):
        """Exact attention over the axis-sharded token/vertex dimension.

        ``tpu`` mode runs ring attention (K/V blocks stream around the
        graph axis via ppermute — :mod:`dgraph_tpu.parallel.sequence`) or,
        with ``impl='ulysses'``, the all-to-all head-sharded variant;
        ``single`` mode is the dense oracle. All three are exact, so model
        code is byte-identical under any choice. Wherever a device ends up
        holding a full-sequence view (single mode, or the Ulysses dense
        stage), the Mosaic flash kernel takes over when enabled + the
        shapes qualify (``config.use_flash_attention``).

        Args:
          q/k/v: [T_loc, H, D] per-shard (full [T, H, D] in single mode).
          kv_mask: [T_loc] 1.0 = real position (padding excluded from keys).
          impl: 'ring' (default; O(T/W) memory, ICI neighbor hops) or
            'ulysses' (2 all_to_alls, needs heads % axis == 0).
        """
        from dgraph_tpu.parallel.sequence import (
            _flash_applicable,
            _flash_dense,
            dense_attention,
            ring_attention,
            ulysses_attention,
        )

        if impl not in ("ring", "ulysses"):
            raise ValueError(f"unknown seq_attention impl: {impl!r}")
        if self.graph_axis is None:
            # flash here ONLY on an explicit pinned True (post-self-check):
            # single mode is the dense ORACLE parity harnesses compare
            # against — an unverified kernel must not replace it on auto
            if _flash_applicable(q, require_pinned=True):
                return _flash_dense(q, k, v, causal=causal, scale=None,
                                    kv_mask=kv_mask)
            return dense_attention(q, k, v, causal=causal, kv_mask=kv_mask)
        if impl == "ulysses":
            return ulysses_attention(
                q, k, v, self.graph_axis, causal=causal, kv_mask=kv_mask
            )
        return ring_attention(
            q, k, v, self.graph_axis, causal=causal, kv_mask=kv_mask
        )

    # -- reductions over mesh axes --
    @_scoped("dgraph.comm.all_reduce_sum")
    def all_reduce_sum(self, x):
        if self.graph_axis is None:
            return x
        return lax.psum(x, self.graph_axis)

    @_scoped("dgraph.comm.all_reduce_mean")
    def all_reduce_mean(self, x):
        if self.graph_axis is None:
            return x
        return lax.pmean(x, self.graph_axis)

    @_scoped("dgraph.comm.replica_mean")
    def replica_mean(self, x):
        if self.replica_axis is None:
            return x
        return lax.pmean(x, self.replica_axis)

    @_scoped("dgraph.comm.grad_sync")
    def grad_sync(self, grads):
        """Gradient synchronization — the DDP all-reduce equivalent
        (``experiments/OGB/main.py:111-112``): SUM over the graph axis (each
        shard holds a different slice of the one sample, so shard grads are
        partial sums of the same global loss) and MEAN over the replica axis
        (each replica holds a different sample). Matches the reference's
        loss scaling ``* ranks_per_sample / world_size``
        (``train_graphcast.py:29-34``)."""
        if self.graph_axis is not None:
            grads = jax.tree.map(lambda g: lax.psum(g, self.graph_axis), grads)
        if self.replica_axis is not None:
            grads = jax.tree.map(lambda g: lax.pmean(g, self.replica_axis), grads)
        return grads

    # -- parity no-ops --
    def barrier(self):
        """No-op: XLA's dataflow scheduling orders collectives; the
        reference's liberal ``dist.barrier()`` has no TPU analogue."""

    def destroy(self):
        """No-op (reference parity; and note ``Communicator.destroy`` in the
        reference never called the engine's destroy either — SURVEY §2.6)."""

    def alloc_buffer(self, shape, dtype=jnp.float32):
        """Parity with ``Communicator.alloc_buffer`` (``Communicator.py:99``):
        on TPU buffers are values, not symmetric-heap allocations."""
        return jnp.zeros(shape, dtype)


@dataclasses.dataclass(frozen=True)
class TpuComm(_BaseComm):
    """SPMD communicator bound to mesh axis names. Use inside shard_map."""

    world_size: int = 1

    def get_world_size(self) -> int:
        return self.world_size


@dataclasses.dataclass(frozen=True)
class SingleComm(_BaseComm):
    """World-size-1 communicator (no mesh, no collectives)."""

    def get_world_size(self) -> int:
        return 1


class Communicator:
    """Constructor facade, parity with ``DGraph/Communicator.py:24-66``."""

    SUPPORTED_BACKENDS = ("tpu", "single")

    @staticmethod
    def init_process_group(
        backend: str = "tpu",
        *,
        world_size: Optional[int] = None,
        graph_axis: str = GRAPH_AXIS,
        replica_axis: Optional[str] = None,
    ) -> _BaseComm:
        if backend == "tpu":
            if world_size is None:
                raise ValueError("backend='tpu' requires world_size (graph-axis size)")
            return TpuComm(
                graph_axis=graph_axis, replica_axis=replica_axis, world_size=world_size
            )
        if backend == "single":
            return SingleComm(graph_axis=None, replica_axis=replica_axis)
        raise ValueError(
            f"Backend {backend!r} not supported; expected one of "
            f"{Communicator.SUPPORTED_BACKENDS} (the reference's nccl/mpi/nvshmem "
            "backends are all subsumed by 'tpu' — SURVEY.md §2.4)"
        )
