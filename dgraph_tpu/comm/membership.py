"""Elastic world membership: lease/heartbeat liveness, deadline barriers,
retrying rendezvous, and structured rank-loss events.

DGraph's full-graph training has no fault story: one lost rank in the
NCCL/MPI/NVSHMEM halo exchange kills the whole run (PAPER.md L1/L2), and
``comm/multihost.py`` inherits that — PR 8 made each host load only its
plan shard, but nothing *detects* a dead host.  This module is the
detection half of treating rank loss as a planned redistribution to a
smaller world instead of a fatal crash ("Memory-efficient array
redistribution through portable collective communication", PAPERS.md);
the recovery half — shrink-to-fit re-planning and checkpoint resharding —
lives in :mod:`dgraph_tpu.train.shrink`, and the restart policy in
:func:`dgraph_tpu.train.supervise.supervise_group`.

Design rules:

- **Jax-free, lint-enforced, pure stdlib.** Liveness is exactly the thing
  that must keep working while jax is wedged: heartbeats, polls, barriers
  and rendezvous never touch an accelerator API (``analysis.lint``'s
  ``jax-free-module`` rule covers this file), and the module imports only
  stdlib plus the equally jax-free :mod:`dgraph_tpu.chaos` /
  :mod:`dgraph_tpu.obs.spans` / :mod:`dgraph_tpu.obs.health`.
- **Shared-directory transport.** A member is alive while its lease file
  advances; the membership directory lives wherever the run's artifacts
  do (local disk for single-host multi-process launches and tests, NFS /
  FUSE-mounted object storage for real pods — the same deployment story
  as the plan cache).  Writes are atomic (tmp + ``os.replace``), so a
  reader never sees a torn lease.
- **Logical-clock liveness, local deadlines.** Peers are judged by their
  *sequence number* advancing within ``lease_s`` on the observer's own
  monotonic clock — never by comparing wall clocks across hosts.  The
  clock and sleep are injectable, so every deadline/backoff schedule is
  testable without real sleeps.
- **Deterministic under chaos.** The ``comm.heartbeat`` point fires
  before each lease write (index = seq; a ``delay`` clause is the
  injected straggler) and ``comm.rendezvous`` before each join attempt
  (index = attempt; a ``raise`` clause exercises the retry/backoff
  path).

Events are structured (``.record()`` JSONL dicts, the ChaosFault/
serve-errors discipline) and written through :mod:`dgraph_tpu.obs.spans`
(one zero-duration span per event, joinable by trace id against the
supervisor lineage) and, when a :class:`~dgraph_tpu.obs.health.RunHealth`
is attached, ``RunHealth.record_event`` — so a degraded run's artifact
alone tells the detection story.

Interplay with the step watchdog: a *wedged* rank (hung dispatch, process
alive) should exit 17 via :class:`~dgraph_tpu.train.elastic.StepWatchdog`
and be collectively restarted at the same world size; only a rank whose
*process* died stops heartbeating and becomes a :class:`RankLost`.  Keep
``step_deadline_s`` (watchdog) **below** ``lease_s`` so a wedge is always
classified as a wedge before peers give up on the rank.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time
from typing import Callable, Optional

import dgraph_tpu.obs.spans as spans  # stdlib-only module (lint-enforced)
from dgraph_tpu import chaos
from dgraph_tpu.utils.env import RANK_ENV_VAR

# a survivor that detected rank loss exits with this code after saving its
# checkpoint; supervise_group treats it as "shrink the world and resume"
# (the membership analog of train.elastic.WEDGED_EXIT_CODE == 17)
RANK_LOST_EXIT_CODE = 19

# a member that observed a join request exits with this code after saving
# its checkpoint; supervise_group treats it as "grow the world and resume"
# (the arrival mirror of RANK_LOST_EXIT_CODE)
RANK_JOIN_EXIT_CODE = 23


def rank_from_env(default: Optional[int] = None) -> int:
    """The member ordinal ``supervise_group`` exported to this process
    (``$DGRAPH_RANK`` — :data:`dgraph_tpu.utils.env.RANK_ENV_VAR`).  The
    canonical way an elastic worker learns which plan shard / checkpoint
    block / membership slot is its own.  Raises when unset and no
    ``default`` is given: a worker silently assuming rank 0 would fight
    the real rank 0 over its lease file."""
    raw = os.environ.get(RANK_ENV_VAR, "").strip()
    if raw:
        return int(raw)
    if default is None:
        raise RuntimeError(
            f"{RANK_ENV_VAR} is not set: this process was not launched by "
            f"supervise_group (pass rank= explicitly, or export it)"
        )
    return int(default)

_MEMBER_PREFIX = "member_"
_LEFT_PREFIX = "left_"
_JOIN_PREFIX = "join_"
_GRANT_PREFIX = "grant_"
_BARRIER_DIR = "barriers"


# ---------------------------------------------------------------------------
# events + errors (structured, JSONL-able)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RankLost:
    """A peer's lease expired (or its process tombstoned abnormally):
    its heartbeat sequence did not advance within ``lease_s`` on the
    observer's clock."""

    kind = "rank_lost"
    rank: int
    silent_for_s: float
    last_seq: int
    generation: int

    def record(self) -> dict:
        return {
            "kind": self.kind,
            "rank": self.rank,
            "silent_for_s": round(self.silent_for_s, 3),
            "last_seq": self.last_seq,
            "generation": self.generation,
        }


@dataclasses.dataclass(frozen=True)
class MembershipChanged:
    """The observer's alive-set changed (join, graceful leave, or loss)."""

    kind = "membership_changed"
    generation: int
    alive: tuple
    lost: tuple
    left: tuple
    world_size: int

    def record(self) -> dict:
        return {
            "kind": self.kind,
            "generation": self.generation,
            "alive": list(self.alive),
            "lost": list(self.lost),
            "left": list(self.left),
            "world_size": self.world_size,
        }


@dataclasses.dataclass(frozen=True)
class Straggler:
    """A peer is late (silent past ``straggler_after_s``) but its lease
    has not expired — report, don't evict. One event per episode; a
    heartbeat that resumes re-arms the detector."""

    kind = "straggler"
    rank: int
    silent_for_s: float
    generation: int

    def record(self) -> dict:
        return {
            "kind": self.kind,
            "rank": self.rank,
            "silent_for_s": round(self.silent_for_s, 3),
            "generation": self.generation,
        }


@dataclasses.dataclass(frozen=True)
class JoinRequest:
    """A prospective member announced itself into this generation (a
    ``join_<token>`` lease appeared).  Emitted ONCE per token; the
    observer should land a durable checkpoint and exit
    :data:`RANK_JOIN_EXIT_CODE` so the group supervisor runs the
    grow-to-fit transition (:mod:`dgraph_tpu.train.grow`)."""

    kind = "join_request"
    token: str
    generation: int

    def record(self) -> dict:
        return {
            "kind": self.kind,
            "token": self.token,
            "generation": self.generation,
        }


class RankLostError(RuntimeError):
    """Raised by callers (e.g. ``run_elastic(membership=...)``) once loss
    is detected and the local checkpoint is durable — the process should
    exit :data:`RANK_LOST_EXIT_CODE` so the group supervisor shrinks."""

    def __init__(self, lost_ranks: tuple, events: tuple = ()):
        super().__init__(
            f"rank(s) {sorted(lost_ranks)} lost (lease expired); exit "
            f"{RANK_LOST_EXIT_CODE} for shrink-to-fit restart"
        )
        self.lost_ranks = tuple(sorted(lost_ranks))
        self.events = tuple(events)

    def record(self) -> dict:
        return {
            "kind": "rank_lost_exit",
            "lost_ranks": list(self.lost_ranks),
            "exit_code": RANK_LOST_EXIT_CODE,
            "events": [e.record() for e in self.events],
        }


class RankJoinError(RuntimeError):
    """Raised by callers (e.g. ``run_elastic(membership=...)``) once a
    join request is observed and the local checkpoint is durable — the
    process should exit :data:`RANK_JOIN_EXIT_CODE` so the group
    supervisor grows the world (the arrival mirror of
    :class:`RankLostError`)."""

    def __init__(self, tokens: tuple, events: tuple = ()):
        super().__init__(
            f"join request(s) {sorted(tokens)} observed; exit "
            f"{RANK_JOIN_EXIT_CODE} for grow-to-fit restart"
        )
        self.tokens = tuple(sorted(tokens))
        self.events = tuple(events)

    def record(self) -> dict:
        return {
            "kind": "rank_join_exit",
            "tokens": list(self.tokens),
            "exit_code": RANK_JOIN_EXIT_CODE,
            "events": [e.record() for e in self.events],
        }


class DeadlineExceeded(RuntimeError):
    """A barrier or rendezvous deadline expired; carries who was missing
    (and who straggled in late) so the operator log names the culprit."""

    def __init__(self, what: str, deadline_s: float, missing: tuple,
                 stragglers: tuple = ()):
        super().__init__(
            f"{what} deadline ({deadline_s:g}s) exceeded; missing ranks "
            f"{sorted(missing)}"
            + (f", stragglers {sorted(stragglers)}" if stragglers else "")
        )
        self.what = what
        self.deadline_s = deadline_s
        self.missing = tuple(sorted(missing))
        self.stragglers = tuple(sorted(stragglers))

    def record(self) -> dict:
        return {
            "kind": "deadline_exceeded",
            "what": self.what,
            "deadline_s": self.deadline_s,
            "missing": list(self.missing),
            "stragglers": list(self.stragglers),
        }


# ---------------------------------------------------------------------------
# the membership core
# ---------------------------------------------------------------------------


def _atomic_write_json(path: str, obj: dict) -> None:
    # no fsync on purpose: a lease file is liveness, not durability — a
    # heartbeat lost to a host crash is exactly a missed heartbeat
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        # torn/vanished files read as "no heartbeat yet"; atomic writes
        # make this transient
        return None


@dataclasses.dataclass
class _PeerView:
    """Observer-local liveness bookkeeping for one peer."""

    seq: int = -1
    last_change: float = 0.0  # observer monotonic time of last seq advance
    seen: bool = False
    lost: bool = False
    left: bool = False
    straggling: bool = False


class Membership:
    """One member's view of a fixed-id, shrinkable world.

    Usage (one instance per rank process)::

        mem = Membership(run_dir, rank=r, world_size=W, lease_s=5.0)
        mem.rendezvous(deadline_s=60.0)       # wait for the full world
        mem.start_heartbeats()                # lease tracks the PROCESS,
        for step in ...:                      # not the step cadence
            for ev in mem.poll():             # observe peers
                ...                           # RankLost -> checkpoint, exit 19

    ``generation`` names the world incarnation: after a shrink the
    supervisor relaunches survivors with a fresh membership directory
    (``shrink.membership_dir``), so stale generation-g leases can never
    pollute generation g+1.

    ``clock``/``sleep`` are injectable (tests drive every deadline with a
    fake clock); both default to the monotonic wall.  ``health`` is an
    optional :class:`~dgraph_tpu.obs.health.RunHealth` that receives every
    event via ``record_event``.
    """

    def __init__(
        self,
        directory: str,
        *,
        rank: int,
        world_size: int,
        lease_s: float = 5.0,
        heartbeat_interval_s: Optional[float] = None,
        straggler_after_s: Optional[float] = None,
        generation: int = 0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        jitter_seed: int = 0,
        health=None,
    ):
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} not in [0, {world_size})")
        if lease_s <= 0:
            raise ValueError(f"lease_s must be > 0, got {lease_s}")
        self.dir = directory
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.lease_s = float(lease_s)
        self.heartbeat_interval_s = (
            float(heartbeat_interval_s)
            if heartbeat_interval_s is not None else self.lease_s / 4.0
        )
        self.straggler_after_s = (
            float(straggler_after_s)
            if straggler_after_s is not None else self.lease_s / 2.0
        )
        if not (0 < self.straggler_after_s <= self.lease_s):
            raise ValueError(
                f"straggler_after_s ({self.straggler_after_s}) must be in "
                f"(0, lease_s={self.lease_s}]"
            )
        self.generation = int(generation)
        self._clock = clock
        self._sleep = sleep
        # rank-keyed jitter: members retrying a rendezvous must not
        # thundering-herd the shared directory in lockstep
        self._rng = random.Random((jitter_seed << 16) ^ (self.rank + 1))
        self._health = health
        self._seq = 0
        self._hb_lock = threading.Lock()
        self._hb_stop: Optional[threading.Event] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._view: dict = {}  # rank -> _PeerView
        self._join_view: dict = {}  # join token -> _PeerView
        self.events: list = []  # every event record, in emit order
        os.makedirs(self.dir, exist_ok=True)

    # -- lease writes -------------------------------------------------------

    def _member_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"{_MEMBER_PREFIX}{rank}.json")

    def _left_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"{_LEFT_PREFIX}{rank}")

    def heartbeat(self) -> int:
        """Advance and publish this member's lease; returns the new seq.
        The ``comm.heartbeat`` chaos point fires first (index = seq) — a
        ``delay`` clause injects the straggle *before* the write, exactly
        where a slow NFS round-trip would land.  Thread-safe (the
        background :meth:`start_heartbeats` thread and the step loop may
        both call it)."""
        with self._hb_lock:
            self._seq += 1
            seq = self._seq
            chaos.fire("comm.heartbeat", index=seq)
            _atomic_write_json(
                self._member_path(self.rank),
                {
                    "rank": self.rank,
                    "seq": seq,
                    "pid": os.getpid(),
                    "generation": self.generation,
                    "wall": time.time(),  # diagnostic only, never compared
                },
            )
        return seq

    def start_heartbeats(self, interval_s: Optional[float] = None) -> None:
        """Background lease maintenance: a daemon thread heartbeats every
        ``heartbeat_interval_s`` (default lease/4) so a slow host step —
        a long orbax write, a loaded machine, a GC pause — can never read
        as silence to peers.  Liveness must track the PROCESS, not the
        step cadence: only a dead process (or a wedge that the watchdog
        turns into exit 17 first) stops the thread.  ``poll()`` stays
        caller-driven.  An injected :class:`~dgraph_tpu.chaos.ChaosFault`
        inside the thread is swallowed — a raise clause on
        ``comm.heartbeat`` means exactly "this heartbeat was lost".
        Idempotent; pair with :meth:`stop_heartbeats`."""
        if self._hb_thread is not None:
            return
        interval = (
            float(interval_s) if interval_s is not None
            else self.heartbeat_interval_s
        )
        self._hb_stop = threading.Event()

        def _run():
            while not self._hb_stop.wait(interval):
                try:
                    self.heartbeat()
                except chaos.ChaosFault:
                    pass  # an injected lost heartbeat IS the fault
                except OSError:
                    pass  # transient store hiccup: the lease just ages

        self._hb_thread = threading.Thread(
            target=_run, name=f"membership-hb-{self.rank}", daemon=True
        )
        self._hb_thread.start()

    def stop_heartbeats(self) -> None:
        if self._hb_thread is None:
            return
        self._hb_stop.set()
        self._hb_thread.join(timeout=5.0)
        self._hb_thread = None
        self._hb_stop = None

    def leave(self) -> None:
        """Graceful departure: publish a tombstone so peers see a clean
        ``left`` (a MembershipChanged without the lease wait) instead of a
        loss."""
        # _seq is owned by the heartbeat lock (the background daemon
        # advances it concurrently); snapshot under it rather than read
        # a torn value mid-increment (host-lock-discipline)
        with self._hb_lock:
            seq = self._seq
        with open(self._left_path(self.rank), "w") as fh:
            fh.write(str(seq))

    # -- observation --------------------------------------------------------

    def _read_members(self) -> dict:
        out = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if name.startswith(_MEMBER_PREFIX) and name.endswith(".json"):
                rec = _read_json(os.path.join(self.dir, name))
                if rec is not None and rec.get("generation", 0) == self.generation:
                    out[int(rec["rank"])] = rec
        return out

    def alive(self) -> tuple:
        """Sorted ranks currently considered alive (self included)."""
        live = {self.rank}
        for r, v in self._view.items():
            if v.seen and not v.lost and not v.left:
                live.add(r)
        return tuple(sorted(live))

    def lost(self) -> tuple:
        """Sorted ranks whose lease has expired."""
        return tuple(sorted(r for r, v in self._view.items() if v.lost))

    def pending_joins(self) -> tuple:
        """Sorted join tokens announced into this generation and still
        fresh (announcement lease not expired on this observer's
        clock)."""
        return tuple(sorted(
            t for t, v in self._join_view.items()
            if v.seen and not v.lost
        ))

    def poll(self) -> list:
        """Read peers' leases and update the liveness view; returns the
        NEW events this poll produced (:class:`RankLost`,
        :class:`Straggler`, :class:`MembershipChanged`), each already
        written through spans/health."""
        now = self._clock()
        members = self._read_members()
        events: list = []
        changed_lost: list = []
        changed_left: list = []
        joined = False
        for r in range(self.world_size):
            if r == self.rank:
                continue
            v = self._view.setdefault(r, _PeerView())
            if v.lost or v.left:
                continue  # terminal in this generation
            if os.path.exists(self._left_path(r)):
                v.left = True
                changed_left.append(r)
                continue
            rec = members.get(r)
            if rec is None:
                # never heartbeated yet: pre-join, not lost (rendezvous
                # owns the join deadline)
                continue
            seq = int(rec.get("seq", 0))
            if not v.seen or seq != v.seq:
                if not v.seen:
                    joined = True
                v.seq = seq
                v.last_change = now
                v.seen = True
                if v.straggling:
                    v.straggling = False  # episode over; re-arm detector
                continue
            age = now - v.last_change
            if age > self.lease_s:
                v.lost = True
                ev = RankLost(
                    rank=r, silent_for_s=age, last_seq=v.seq,
                    generation=self.generation,
                )
                events.append(ev)
                changed_lost.append(r)
            elif age > self.straggler_after_s and not v.straggling:
                v.straggling = True
                events.append(Straggler(
                    rank=r, silent_for_s=age, generation=self.generation,
                ))
        # join announcements (grow-to-fit arrivals). Newcomers are judged
        # from FIRST-OBSERVED seq on this observer's clock: an observer
        # whose polling history predates the newcomer's first write must
        # never count that pre-arrival silence against it (the announce
        # file's wall time is diagnostic only, and the _PeerView default
        # last_change=0.0 would age an hours-old observer's first sight
        # of a fresh joiner straight past the lease). A token silent past
        # lease_s AFTER first observation expires quietly — a withdrawn
        # join request is a non-event, not a RankLost.
        for token, rec in sorted(_read_join_files(
            self.dir, self.generation
        ).items()):
            v = self._join_view.setdefault(token, _PeerView())
            seq = int(rec.get("seq", 0))
            if v.lost:
                # unlike a member's lease, join expiry is NOT terminal: a
                # stalled joiner (GC pause, swapped host) that resumes
                # announcing is a fresh rendezvous attempt, re-reported —
                # only the SAME stale seq stays withdrawn
                if seq == v.seq:
                    continue
                self._join_view[token] = v = _PeerView()
            if not v.seen or seq != v.seq:
                if not v.seen:
                    events.append(JoinRequest(
                        token=token, generation=self.generation,
                    ))
                v.seq = seq
                v.last_change = now
                v.seen = True
                continue
            if now - v.last_change > self.lease_s:
                v.lost = True
        if joined or changed_lost or changed_left:
            events.append(MembershipChanged(
                generation=self.generation,
                alive=self.alive(),
                lost=self.lost(),
                left=tuple(sorted(
                    r for r, v in self._view.items() if v.left
                )),
                world_size=self.world_size,
            ))
        for ev in events:
            self._emit(ev)
        return events

    def _emit(self, event) -> None:
        rec = event.record()
        self.events.append(rec)
        # zero-duration span per event: joinable by trace id against the
        # supervisor lineage (a no-op attribute read when tracing is off)
        spans.span(
            f"membership.{event.kind}", observer=self.rank, **rec
        ).end()
        if self._health is not None:
            self._health.record_event(rec)

    # -- collective waits ---------------------------------------------------

    def rendezvous(
        self,
        deadline_s: float,
        *,
        expected: Optional[int] = None,
        backoff_s: float = 0.05,
        backoff_factor: float = 2.0,
        backoff_max_s: float = 1.0,
    ) -> tuple:
        """Join the world and wait until ``expected`` (default: the full
        ``world_size``) distinct members have published a lease; returns
        the sorted roster.

        Retrying: each attempt heartbeats, fires the ``comm.rendezvous``
        chaos point (index = attempt; an injected :class:`~dgraph_tpu.
        chaos.ChaosFault` counts as a failed attempt and is retried), and
        re-reads the directory; between attempts the wait grows
        ``backoff_s * backoff_factor**k`` capped at ``backoff_max_s``,
        plus a rank-seeded jitter in ``[0, backoff_s)`` so members don't
        re-scan in lockstep. Past ``deadline_s``: :class:`DeadlineExceeded`
        naming the missing ranks.
        """
        expected = self.world_size if expected is None else int(expected)
        t0 = self._clock()
        attempt = 0
        present: tuple = ()
        with spans.span(
            "membership.rendezvous", rank=self.rank, expected=expected,
            generation=self.generation,
        ) as rspan:
            while True:
                try:
                    chaos.fire("comm.rendezvous", index=attempt)
                    self.heartbeat()
                    members = self._read_members()
                    present = tuple(sorted(set(members) | {self.rank}))
                    if len(present) >= expected:
                        rspan.annotate(attempts=attempt + 1,
                                       roster=list(present))
                        self._emit(MembershipChanged(
                            generation=self.generation,
                            alive=present,
                            lost=(),
                            left=(),
                            world_size=self.world_size,
                        ))
                        return present
                except chaos.ChaosFault:
                    pass  # injected transient: retry with backoff
                delay = min(
                    backoff_s * backoff_factor ** attempt, backoff_max_s
                ) + self._rng.uniform(0.0, backoff_s)
                if self._clock() - t0 + delay >= deadline_s:
                    missing = tuple(
                        r for r in range(self.world_size)
                        if r not in present
                    )
                    err = DeadlineExceeded(
                        "rendezvous", deadline_s, missing
                    )
                    rspan.end(error=str(err), attempts=attempt + 1)
                    if self._health is not None:
                        self._health.record_event(err.record())
                    raise err
                self._sleep(delay)
                attempt += 1

    def _barrier_dir(self, name: str) -> str:
        return os.path.join(self.dir, _BARRIER_DIR, name.replace(os.sep, "_"))

    def arrive(self, name: str) -> None:
        """Publish this member's arrival at barrier ``name`` without
        waiting (:meth:`barrier` = ``arrive`` + wait; split them when the
        arrival should land before other work, e.g. before a long
        checkpoint write that peers need not wait out)."""
        bdir = self._barrier_dir(name)
        os.makedirs(bdir, exist_ok=True)
        # same snapshot discipline as leave(): the heartbeat daemon owns
        # _seq under _hb_lock
        with self._hb_lock:
            seq = self._seq
        with open(os.path.join(bdir, f"rank_{self.rank}"), "w") as fh:
            fh.write(str(seq))

    def barrier(
        self,
        name: str,
        deadline_s: float,
        *,
        poll_interval_s: float = 0.05,
    ) -> dict:
        """Deadline barrier over the currently-alive ranks: publish own
        arrival, wait until every alive rank arrived, fail fast otherwise.

        Returns ``{"name", "arrived", "stragglers", "wall_s"}`` where
        ``stragglers`` are ranks that arrived later than
        ``straggler_after_s`` after this member (reported, not failed).
        Raises :class:`DeadlineExceeded` when the deadline passes with
        ranks missing, and :class:`RankLostError` immediately if a peer's
        lease expires while we wait — a dead rank's barrier can never
        complete, and burning the whole deadline to learn that wastes
        exactly the detection latency membership exists to bound.
        """
        bdir = self._barrier_dir(name)
        self.arrive(name)
        t0 = self._clock()
        stragglers: set = set()
        arrived: set = set()
        # lease writes + O(W) liveness polls are rate-limited to the
        # heartbeat interval (arrival checks below stay at
        # poll_interval_s — one listdir): a 50 ms full-poll cadence would
        # hammer the shared store hardest exactly while waiting it out
        hb_next = t0
        with spans.span(
            "membership.barrier", rank=self.rank, barrier=name,
            generation=self.generation,
        ) as bspan:
            while True:
                losses = []
                if self._clock() >= hb_next:
                    hb_next = self._clock() + self.heartbeat_interval_s
                    self.heartbeat()
                    losses = [
                        e for e in self.poll() if isinstance(e, RankLost)
                    ]
                if losses:
                    err = RankLostError(
                        tuple(e.rank for e in losses), tuple(losses)
                    )
                    bspan.end(error=str(err))
                    raise err
                want = set(self.alive())
                try:
                    arrived = {
                        int(f.split("_", 1)[1])
                        for f in os.listdir(bdir)
                        if f.startswith("rank_")
                    }
                except OSError:
                    arrived = set()
                now = self._clock()
                if now - t0 > self.straggler_after_s:
                    late = (want - arrived) - stragglers
                    for r in sorted(late):
                        stragglers.add(r)
                        self._emit(Straggler(
                            rank=r, silent_for_s=now - t0,
                            generation=self.generation,
                        ))
                if want <= arrived:
                    wall = now - t0
                    bspan.annotate(
                        arrived=sorted(arrived),
                        stragglers=sorted(stragglers),
                    )
                    return {
                        "name": name,
                        "arrived": sorted(arrived),
                        "stragglers": sorted(stragglers),
                        "wall_s": round(wall, 3),
                    }
                if now - t0 + poll_interval_s >= deadline_s:
                    err = DeadlineExceeded(
                        f"barrier {name!r}", deadline_s,
                        tuple(want - arrived), tuple(stragglers),
                    )
                    bspan.end(error=str(err))
                    if self._health is not None:
                        self._health.record_event(err.record())
                    raise err
                self._sleep(poll_interval_s)


def _read_join_files(directory: str, generation: Optional[int]) -> dict:
    """token -> join record for every readable ``join_<token>.json`` in
    ``directory`` (filtered to ``generation`` unless None)."""
    out = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if name.startswith(_JOIN_PREFIX) and name.endswith(".json"):
            rec = _read_json(os.path.join(directory, name))
            if rec is None or "token" not in rec:
                continue
            if generation is not None and rec.get("generation", 0) != generation:
                continue
            out[str(rec["token"])] = rec
    return out


def read_joins(directory: str, generation: Optional[int] = None) -> dict:
    """token -> join record for the pending join announcements in a
    membership directory (the grow path's discovery probe — see
    :func:`dgraph_tpu.train.grow.grow_world`).  Read-only; filtered to
    ``generation`` when given."""
    return _read_join_files(directory, generation)


def grant_join(
    directory: str, token: str, *, rank: int, generation: int,
    world_size: int,
) -> dict:
    """Answer a join announcement: durably publish the rank assignment a
    :class:`Joiner` polling ``directory`` is waiting on.  Written by the
    group supervisor AFTER the grow transition's ``world.json`` flip
    (the grant names a generation, so it must never precede the pointer
    that defines it)."""
    rec = {
        "token": str(token),
        "rank": int(rank),
        "generation": int(generation),
        "world_size": int(world_size),
        "wall": time.time(),  # diagnostic only, never compared
    }
    os.makedirs(directory, exist_ok=True)
    _atomic_write_json(
        os.path.join(directory, f"{_GRANT_PREFIX}{token}.json"), rec
    )
    return rec


class Joiner:
    """A prospective member's half of the grow-to-fit rendezvous: it
    announces itself into a LIVE generation's membership directory and
    waits for the supervisor's grant naming its rank in the grown world.

    Usage (one instance per joining process)::

        j = Joiner(membership_dir, token="node-b7", generation=g)
        grant = j.join(deadline_s=120.0)   # announce + wait for grant
        # grant == {"token", "rank", "generation", "world_size", ...}

    The announcement is a lease like a member's (seq-advancing, written
    atomically): live members observe it at their next poll
    (:class:`JoinRequest`), checkpoint, and exit
    :data:`RANK_JOIN_EXIT_CODE`; the supervisor re-plans to W+k
    (:mod:`dgraph_tpu.train.grow`) and answers with
    :func:`grant_join`.  A joiner that stops announcing before a grant
    ages out of observers' pending sets quietly — withdrawal is free.
    The ``comm.join`` chaos point fires before each announcement write
    (index = seq).
    """

    def __init__(
        self,
        directory: str,
        token: str,
        *,
        generation: int = 0,
        lease_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        jitter_seed: int = 0,
        health=None,
    ):
        if not str(token):
            raise ValueError("Joiner: token must be non-empty")
        if any(sep in str(token) for sep in (os.sep, "/", "\0")):
            raise ValueError(f"Joiner: token {token!r} is not a filename")
        self.dir = directory
        self.token = str(token)
        self.generation = int(generation)
        self.lease_s = float(lease_s)
        self._clock = clock
        self._sleep = sleep
        self._rng = random.Random((jitter_seed << 16) ^ (hash(token) & 0xFFFF))
        self._health = health
        self._seq = 0
        os.makedirs(self.dir, exist_ok=True)

    def _join_path(self) -> str:
        return os.path.join(self.dir, f"{_JOIN_PREFIX}{self.token}.json")

    def _grant_path(self) -> str:
        return os.path.join(self.dir, f"{_GRANT_PREFIX}{self.token}.json")

    def announce(self) -> int:
        """Advance and publish the join lease; returns the new seq.  The
        ``comm.join`` chaos point fires first (index = seq) — a ``raise``
        clause is a lost announcement, a ``sigterm`` a joiner preempted
        mid-rendezvous."""
        self._seq += 1
        seq = self._seq
        chaos.fire("comm.join", index=seq)
        _atomic_write_json(
            self._join_path(),
            {
                "token": self.token,
                "seq": seq,
                "pid": os.getpid(),
                "generation": self.generation,
                "wall": time.time(),  # diagnostic only, never compared
            },
        )
        return seq

    def grant(self) -> Optional[dict]:
        """The supervisor's answer, or None while it is still pending.
        A grant for a different token (impossible under the path scheme)
        or a torn file reads as pending."""
        rec = _read_json(self._grant_path())
        if rec is not None and rec.get("token") == self.token:
            return rec
        return None

    def join(
        self,
        deadline_s: float,
        *,
        backoff_s: float = 0.05,
        backoff_factor: float = 2.0,
        backoff_max_s: float = 1.0,
    ) -> dict:
        """Announce into the live generation and wait for the grant;
        returns the grant record (``rank``/``generation``/``world_size``).

        Retrying like :meth:`Membership.rendezvous`: each attempt
        re-announces (keeping the join lease fresh so observers never age
        it out mid-wait; an injected :class:`~dgraph_tpu.chaos.
        ChaosFault` counts as a failed attempt and is retried) and
        re-reads the grant; between attempts the wait grows
        ``backoff_s * backoff_factor**k`` capped at ``backoff_max_s``
        plus a token-seeded jitter.  Past ``deadline_s``:
        :class:`DeadlineExceeded`.
        """
        t0 = self._clock()
        attempt = 0
        with spans.span(
            "membership.join", token=self.token,
            generation=self.generation,
        ) as jspan:
            while True:
                try:
                    self.announce()
                    got = self.grant()
                    if got is not None:
                        jspan.annotate(
                            attempts=attempt + 1, rank=got.get("rank"),
                            world_size=got.get("world_size"),
                        )
                        if self._health is not None:
                            self._health.record_event({
                                "kind": "join_granted", **got,
                            })
                        return got
                except chaos.ChaosFault:
                    pass  # injected transient: retry with backoff
                delay = min(
                    backoff_s * backoff_factor ** attempt, backoff_max_s
                ) + self._rng.uniform(0.0, backoff_s)
                if self._clock() - t0 + delay >= deadline_s:
                    err = DeadlineExceeded(
                        f"join {self.token!r}", deadline_s, missing=(),
                    )
                    jspan.end(error=str(err), attempts=attempt + 1)
                    if self._health is not None:
                        self._health.record_event(err.record())
                    raise err
                self._sleep(delay)
                attempt += 1


def read_roster(directory: str) -> dict:
    """Read-only snapshot of a membership directory: every member's last
    published lease, ACROSS generations (the operator's "who was here"
    probe — a post-shrink dir's members all carry generation > 0, and a
    diagnostic that filtered them out would go blank exactly when the
    world is degraded).  Join announcements render too, keyed
    ``"join:<token>"`` with a ``granted`` flag (and the granted rank when
    the supervisor answered) — a grow transition's rendezvous must be as
    legible after the fact as a member's lease.  Never creates or
    mutates anything; raises FileNotFoundError for a missing directory
    (a typo'd path must not be silently created as an empty world)."""
    out = {}
    for name in os.listdir(directory):  # propagates FileNotFoundError
        if name.startswith(_MEMBER_PREFIX) and name.endswith(".json"):
            rec = _read_json(os.path.join(directory, name))
            if rec is not None:
                rec = dict(rec)
                rec["left"] = os.path.exists(
                    os.path.join(directory, f"{_LEFT_PREFIX}{rec['rank']}")
                )
                out[int(rec["rank"])] = rec
    for token, rec in _read_join_files(directory, None).items():
        rec = dict(rec)
        grant = _read_json(
            os.path.join(directory, f"{_GRANT_PREFIX}{token}.json")
        )
        rec["granted"] = grant is not None
        if grant is not None:
            rec["granted_rank"] = grant.get("rank")
            rec["granted_generation"] = grant.get("generation")
        out[f"join:{token}"] = rec
    return out


# ---------------------------------------------------------------------------
# CLI: `python -m dgraph_tpu.comm.membership --selftest true`
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Config:
    """Elastic world membership CLI (``--selftest`` is the compile-free
    tier-1 smoke; the default shows a membership directory's roster)."""

    selftest: bool = False
    dir: str = ""  # roster mode: membership directory to inspect
    indent: int = 0


class _FakeClock:
    """Deterministic monotonic clock; ``sleep`` advances it (no real
    sleeps anywhere in the selftest)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


def _selftest() -> dict:  # noqa: C901 — one linear scenario script
    import tempfile

    failures: list = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    clock = _FakeClock()

    def make(tmp, r, W, **kw):
        return Membership(
            tmp, rank=r, world_size=W, lease_s=2.0,
            clock=clock, sleep=clock.sleep, **kw,
        )

    with tempfile.TemporaryDirectory() as tmp:
        # --- rendezvous: all three members join ---
        ms = [make(tmp, r, 3) for r in range(3)]
        for m in ms[:2]:
            m.heartbeat()
        roster = ms[2].rendezvous(deadline_s=10.0)
        check(roster == (0, 1, 2), f"rendezvous roster {roster}")
        for m in ms:
            for _ in range(2):
                m.heartbeat()
        evs = ms[0].poll()
        check(ms[0].alive() == (0, 1, 2), f"alive {ms[0].alive()}")
        check(
            any(e.kind == "membership_changed" for e in evs),
            "join produced no membership_changed",
        )

        # --- straggler: rank 2 goes quiet past straggler_after_s ---
        clock.sleep(1.2)  # > lease/2 (=1.0), < lease (=2.0)
        for m in ms[:2]:
            m.heartbeat()
        evs = ms[0].poll()
        stragglers = [e for e in evs if e.kind == "straggler"]
        check(
            [e.rank for e in stragglers] == [2],
            f"straggler events {stragglers}",
        )
        check(ms[0].alive() == (0, 1, 2), "straggler wrongly evicted")
        check(not [e for e in ms[0].poll() if e.kind == "straggler"],
              "straggler re-reported within one episode")

        # --- loss: the lease expires ---
        clock.sleep(1.0)  # total silence 2.2 > lease
        evs = ms[0].poll()
        losses = [e for e in evs if e.kind == "rank_lost"]
        check(
            len(losses) == 1 and losses[0].rank == 2
            and losses[0].silent_for_s > 2.0,
            f"loss events {losses}",
        )
        check(ms[0].alive() == (0, 1), f"alive after loss {ms[0].alive()}")
        check(ms[0].lost() == (2,), f"lost set {ms[0].lost()}")
        changed = [e for e in evs if e.kind == "membership_changed"]
        check(
            changed and changed[-1].lost == (2,),
            f"membership_changed after loss {changed}",
        )
        check(not ms[0].poll(), "loss re-reported on the next poll")
        for rec in ms[0].events:
            json.dumps(rec)  # every event JSONL-able

        # --- graceful leave: tombstone, no lease wait ---
        ms[1].heartbeat()
        ms[1].leave()
        evs = ms[0].poll()
        check(
            any(e.kind == "membership_changed" and 1 in e.left for e in evs),
            f"leave not observed: {evs}",
        )
        check(ms[0].alive() == (0,), f"alive after leave {ms[0].alive()}")

        # --- read_roster: read-only, cross-generation, left-flagged ---
        roster = read_roster(tmp)
        check(sorted(roster) == [0, 1, 2], f"roster ranks {sorted(roster)}")
        check(roster[1]["left"] and not roster[0]["left"],
              f"roster left flags {roster}")
        try:
            read_roster(tmp + "/no-such-dir")
            failures.append("read_roster created/accepted a missing dir")
        except FileNotFoundError:
            pass

    with tempfile.TemporaryDirectory() as tmp:
        # --- barrier: both arrive; stragglers reported, not failed ---
        clock2 = _FakeClock()
        a = Membership(tmp, rank=0, world_size=2, lease_s=50.0,
                       clock=clock2, sleep=clock2.sleep)
        b = Membership(tmp, rank=1, world_size=2, lease_s=50.0,
                       clock=clock2, sleep=clock2.sleep)
        a.heartbeat(), b.heartbeat()
        a.poll(), b.poll()
        a.arrive("epoch0")  # split arrival: a lands, then b's wait is instant
        res_b = b.barrier("epoch0", deadline_s=60.0)
        res_a = a.barrier("epoch0", deadline_s=60.0)
        check(res_a["arrived"] == [0, 1], f"barrier arrivals {res_a}")
        check(res_b["arrived"] == [0, 1], f"barrier arrivals {res_b}")

        # --- barrier deadline: the absent rank is named ---
        try:
            a.barrier("epoch1", deadline_s=1.0)
            failures.append("barrier with an absent rank did not time out")
        except DeadlineExceeded as e:
            check(e.missing == (1,), f"barrier missing {e.missing}")
            json.dumps(e.record())

    with tempfile.TemporaryDirectory() as tmp:
        # --- rendezvous deadline + retry-under-chaos ---
        clock3 = _FakeClock()
        solo = Membership(tmp, rank=0, world_size=2, lease_s=2.0,
                          clock=clock3, sleep=clock3.sleep)
        try:
            solo.rendezvous(deadline_s=3.0)
            failures.append("solo rendezvous for world 2 did not time out")
        except DeadlineExceeded as e:
            check(e.missing == (1,), f"rendezvous missing {e.missing}")
        try:
            chaos.arm("comm.rendezvous=raise@0:count=2")
            other = Membership(tmp, rank=1, world_size=2, lease_s=2.0,
                               clock=clock3, sleep=clock3.sleep)
            other.heartbeat()
            roster = solo.rendezvous(deadline_s=30.0)
            check(roster == (0, 1),
                  f"rendezvous under chaos roster {roster}")
            check(chaos.call_count("comm.rendezvous") >= 3,
                  "chaos raise clauses did not force retries")
        finally:
            chaos.reset()

        # --- events flow into an attached RunHealth ---
        from dgraph_tpu.obs.health import RunHealth

        h = RunHealth.begin("membership.selftest")
        clock4 = _FakeClock()
        w = Membership(tmp + "/h", rank=0, world_size=2, lease_s=1.0,
                       clock=clock4, sleep=clock4.sleep, health=h)
        peer = Membership(tmp + "/h", rank=1, world_size=2, lease_s=1.0,
                          clock=clock4, sleep=clock4.sleep)
        peer.heartbeat()
        w.poll()
        clock4.sleep(1.5)
        w.poll()
        kinds = [e["kind"] for e in h.events]
        check("rank_lost" in kinds and "membership_changed" in kinds,
              f"health events {kinds}")
        json.dumps(h.finish())

    with tempfile.TemporaryDirectory() as tmp:
        # --- join rendezvous: announce -> observe -> grant -> joined ---
        clock5 = _FakeClock()
        obs = Membership(tmp, rank=0, world_size=2, lease_s=2.0,
                         clock=clock5, sleep=clock5.sleep)
        peer = Membership(tmp, rank=1, world_size=2, lease_s=2.0,
                          clock=clock5, sleep=clock5.sleep)
        peer.heartbeat()
        obs.heartbeat(), obs.poll()
        # an hours-old observer must judge the newcomer from FIRST-
        # OBSERVED seq, not from its own epoch (the joiner-ageing rule)
        clock5.sleep(1000.0)
        obs.heartbeat(), peer.heartbeat()
        obs.poll()
        j = Joiner(tmp, "node-b7", generation=0, lease_s=2.0,
                   clock=clock5, sleep=clock5.sleep)
        j.announce()
        evs = obs.poll()
        reqs = [e for e in evs if e.kind == "join_request"]
        check([e.token for e in reqs] == ["node-b7"],
              f"join_request events {evs}")
        check(obs.pending_joins() == ("node-b7",),
              f"pending joins {obs.pending_joins()}")
        check(not [e for e in obs.poll() if e.kind == "join_request"],
              "join_request re-reported for an already-seen token")
        check(obs.pending_joins() == ("node-b7",),
              "fresh join aged out before its lease (first-observed-seq "
              "rule violated)")
        # the grant completes the joiner's side of the rendezvous
        grant_join(tmp, "node-b7", rank=2, generation=1, world_size=3)
        got = j.join(deadline_s=5.0)
        check(got["rank"] == 2 and got["world_size"] == 3,
              f"grant record {got}")
        json.dumps(got)
        # silence past the lease (after first observation) expires the
        # announcement quietly — withdrawal is a non-event, never a loss.
        # Two silent windows: the first poll still refreshes on the seq
        # the join() call itself advanced.
        clock5.sleep(2.5)
        obs.heartbeat(), peer.heartbeat()
        obs.poll()
        clock5.sleep(2.5)
        obs.heartbeat(), peer.heartbeat()
        evs = obs.poll()
        check(obs.pending_joins() == (),
              f"withdrawn join still pending {obs.pending_joins()}")
        check(not [e for e in evs if e.kind == "rank_lost"],
              "an expired join announcement was reported as rank loss")
        # roster renders the join with its grant
        roster = read_roster(tmp)
        check(roster["join:node-b7"]["granted"]
              and roster["join:node-b7"]["granted_rank"] == 2,
              f"roster join entry {roster.get('join:node-b7')}")
        check(sorted(k for k in roster if isinstance(k, int)) == [0, 1],
              f"roster member ranks {sorted(roster, key=str)}")
        # a join deadline names itself
        lonely = Joiner(tmp, "never-granted", generation=0, lease_s=2.0,
                        clock=clock5, sleep=clock5.sleep)
        try:
            lonely.join(deadline_s=1.0)
            failures.append("ungranted join did not time out")
        except DeadlineExceeded as e:
            json.dumps(e.record())

    check(RANK_LOST_EXIT_CODE == 19, "RANK_LOST_EXIT_CODE drifted")
    check(RANK_JOIN_EXIT_CODE == 23, "RANK_JOIN_EXIT_CODE drifted")
    return {"kind": "membership_selftest", "failures": failures}


def main(cfg: Config) -> dict:
    from dgraph_tpu.obs.health import RunHealth

    health = RunHealth.begin("membership.cli")
    if cfg.selftest:
        try:
            out = _selftest()
        except BaseException as e:  # every exit path carries RunHealth
            rec = {
                "kind": "membership_selftest",
                "failures": [f"crashed: {type(e).__name__}: {e}"],
                "run_health": health.finish(
                    f"membership selftest crashed: {type(e).__name__}: {e}",
                    wedge="stage_failure",
                ),
            }
            print(json.dumps(rec, indent=cfg.indent or None))
            raise
        failures = out["failures"]
        out["run_health"] = health.finish(
            "; ".join(failures) if failures else None,
            wedge="stage_failure" if failures else None,
        )
        print(json.dumps(out, indent=cfg.indent or None))
        if failures:
            raise SystemExit(
                "membership selftest FAILED: " + "; ".join(failures)
            )
        return out
    if not cfg.dir:
        raise SystemExit(
            "nothing to do: pass --selftest true, or --dir <membership "
            "dir> for a roster snapshot"
        )
    # roster mode: a read-only snapshot of someone else's membership dir
    out = {
        "kind": "membership_roster",
        "dir": cfg.dir,
        "members": read_roster(cfg.dir),
        "run_health": health.finish(),
    }
    print(json.dumps(out, indent=cfg.indent or None, default=str))
    return out


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
