"""Differentiable distributed graph primitives (per-shard, inside shard_map).

TPU-native re-design of the reference's L5 "differentiable comm primitives"
(``DGraph/distributed/haloExchange.py``, ``nccl/_torch_func_impl.py``,
SURVEY.md §1 L5):

- ``HaloExchangeImpl`` (alltoallv by put-offsets, ``haloExchange.py:37-88``)
  ↦ :func:`halo_exchange`: a feature gather + one ``lax.all_to_all`` whose
  received blocks land directly in halo-slot order (no recv scatter needed).
- ``CommPlan_GatherFunction`` (local copy → all_to_all → boundary scatter,
  ``_torch_func_impl.py:27-191``) ↦ :func:`gather`.
- ``CommPlan_ScatterFunction`` (``_torch_func_impl.py:194-352``) ↦
  :func:`scatter_sum`.

No custom_vjp is required: every op here is linear in the data (take,
all_to_all, segment-sum, concat), and JAX's AD transposes them to exactly
the reference's hand-written backward pairs (gather-bwd = scatter-sum with
reversed splits, scatter-bwd = gather; ``_torch_func_impl.py:112-191,282-352``
and ``haloExchange.py:66-88``). The gradient tests in
``tests/test_collectives_grad.py`` pin this against the analytic transpose.

All functions take the PER-SHARD plan (leading [world_size] axis already
split off by shard_map; see :func:`dgraph_tpu.comm.mesh.squeeze_plan`) and an
``axis_name`` (None = single-device, world_size must be 1 — the reference's
SingleProcessDummyCommunicator pattern, ``GraphCast/dist_utils.py:8-39``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.plan import EdgePlan, HaloSpec, pick_halo_impl
from dgraph_tpu.ops import local as local_ops


# Every collective shows up as a named region in jax.profiler/Perfetto
# traces (canonical alias lives in utils.timing).
from dgraph_tpu.utils.timing import named_scope as _scoped  # noqa: E402


def _use_ppermute(axis_name, deltas) -> bool:
    from dgraph_tpu import config as _cfg

    if axis_name is None or deltas is None:
        return False
    # same precedence as plan.resolve_halo_impl (env pin > adopted tuning
    # record > heuristic) — checked inline because the heuristic tier needs
    # the axis size, which only exists inside the traced context here
    impl = _cfg.halo_impl
    if impl not in ("ppermute", "all_to_all"):
        impl = _cfg.tuned_halo_impl
    if impl == "ppermute":
        return True
    if impl == "all_to_all":
        return False
    # auto: shared cost model with the plan builder's logged pick
    W = jax.lax.psum(1, axis_name)
    return pick_halo_impl(int(W), deltas) == "ppermute"


@_scoped("dgraph.halo_exchange")
def halo_exchange(
    x: jax.Array,
    halo: HaloSpec,
    axis_name: Optional[str],
    deltas: Optional[tuple] = None,
) -> jax.Array:
    """Exchange boundary vertex features; returns the halo buffer.

    Two lowerings, same result layout:
    - all_to_all (default): one padded collective; received block from peer
      p lands at rows ``[p*S, (p+1)*S)`` — exactly the plan's halo-slot
      numbering, no receive-placement pass.
    - ppermute neighbor rounds (when ``deltas`` — the static set of rank
      offsets with traffic — is sparse): one CollectivePermute per delta,
      skipping empty peer pairs entirely (SURVEY §7 "ppermute rounds only
      to actual neighbors"; the NVSHMEM one-sided put analogue).

    Args:
      x: [n_pad, F] local (padded) vertex features of this shard.
      halo: per-shard spec; send_idx [W, S], send_mask [W, S].
      axis_name: mesh axis to exchange over, or None (single device).
      deltas: static tuple of active (peer-rank) mod W offsets
        (``EdgePlan.halo_deltas``); None disables the ppermute path.
    """
    F = x.shape[-1]
    W, S = halo.send_idx.shape[0], halo.s_pad
    if axis_name is not None and deltas is not None and len(deltas) == 0:
        # no live cross-rank traffic anywhere in the mesh (send_mask is
        # all-zero): the exchange is identically zero, so skip the padded
        # collective entirely — this is what makes pick_halo_impl's
        # 'none' verdict (and obs.footprint's 0-byte accounting) truthful
        return jnp.zeros((W * S, F), x.dtype)
    if axis_name is None:
        # mask in x's dtype: the plan stores send_mask as f32, and a raw
        # multiply silently upcasts a bf16 stream — which then upcasts the
        # halo_extend concat and EVERY downstream [E, F] tensor of the
        # layer (caught in the r4 TPU export: the whole edge pipeline ran
        # f32 and the scatter kernel picked its "highest" precision path)
        send = x[halo.send_idx] * halo.send_mask[..., None].astype(x.dtype)
        return send.reshape(-1, F)  # world size 1: mask is all-zero
    if _use_ppermute(axis_name, deltas):
        me = lax.axis_index(axis_name)
        out = jnp.zeros((W * S, F), x.dtype)
        for d in deltas:
            peer_row = (me + d) % W
            idx = jnp.take(halo.send_idx, peer_row, axis=0)
            msk = jnp.take(halo.send_mask, peer_row, axis=0)
            send = x[idx] * msk[..., None].astype(x.dtype)  # [S, F]
            perm = [(i, (i + d) % W) for i in range(W)]
            recv = lax.ppermute(send, axis_name, perm)
            src_rank = (me - d) % W
            out = lax.dynamic_update_slice(out, recv, (src_rank * S, 0))
        return out
    send = x[halo.send_idx] * halo.send_mask[..., None].astype(x.dtype)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0)
    return recv.reshape(-1, F)


@_scoped("dgraph.halo_scatter_sum")
def halo_scatter_sum(
    h: jax.Array,
    halo: HaloSpec,
    n_pad: int,
    axis_name: Optional[str],
    deltas: Optional[tuple] = None,
) -> jax.Array:
    """Linear transpose of :func:`halo_exchange`: deliver halo-slot values
    back to their owner ranks and sum into local vertices.

    This is the reference's halo-exchange backward (reversed put offsets,
    ``haloExchange.py:66-88``) and the boundary leg of
    ``CommPlan_ScatterFunction.forward`` (``_torch_func_impl.py:194-280``).

    Args:
      h: [W*S, F] halo-buffer values on this shard.
    Returns: [n_pad, F] per-local-vertex sums.
    """
    W, S = halo.send_idx.shape[0], halo.s_pad
    F = h.shape[-1]
    if axis_name is not None and deltas is not None and len(deltas) == 0:
        # transpose of the empty exchange: no halo slot maps anywhere
        return jnp.zeros((n_pad, F), h.dtype)
    if axis_name is not None and _use_ppermute(axis_name, deltas):
        me = lax.axis_index(axis_name)
        out = jnp.zeros((n_pad, F), h.dtype)
        for d in deltas:
            # my halo rows from rank (me-d) go back to their owner (me-d);
            # I receive my own vertices' partials from rank (me+d)
            src_rank = (me - d) % W
            block = lax.dynamic_slice(h.reshape(W * S, F), (src_rank * S, 0), (S, F))
            perm = [(i, (i - d) % W) for i in range(W)]
            recv = lax.ppermute(block, axis_name, perm)  # from rank (me+d)
            peer_row = (me + d) % W
            idx = jnp.take(halo.send_idx, peer_row, axis=0)
            msk = jnp.take(halo.send_mask, peer_row, axis=0)
            out = out + local_ops.segment_sum(
                recv * msk[..., None].astype(h.dtype), idx, n_pad)
        return out
    h = h.reshape(W, S, F)
    if axis_name is None:
        back = h
    else:
        back = lax.all_to_all(h, axis_name, split_axis=0, concat_axis=0)
    back = back * halo.send_mask[..., None].astype(back.dtype)
    flat_idx = halo.send_idx.reshape(-1)
    return local_ops.segment_sum(back.reshape(flat_idx.shape[0], -1), flat_idx, n_pad)


def _side_index(plan: EdgePlan, side: str) -> jax.Array:
    return plan.src_index if side == "src" else plan.dst_index


def _side_npad(plan: EdgePlan, side: str) -> int:
    return plan.n_src_pad if side == "src" else plan.n_dst_pad


def map_feature_chunks(fn, width: int, chunk: Optional[int] = None):
    """Scaffold of the feature-chunked edge pipeline (models/gcn.py
    rationale): apply ``fn(slice)`` over <=chunk-wide feature slices and
    concat the results on the last axis. ``chunk`` defaults to
    ``config.gather_col_block``. Callers are responsible for the gates
    (feature-separable per-edge math, collective-free per-chunk ops —
    pair with :func:`halo_extend` + :func:`local_take`)."""
    from dgraph_tpu import config as _cfg

    cb = chunk or _cfg.gather_col_block or width
    outs = [fn(slice(j, min(j + cb, width))) for j in range(0, width, cb)]
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


@_scoped("dgraph.halo_extend")
def halo_extend(
    x: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str]
) -> jax.Array:
    """The COMMUNICATION half of :func:`gather`: one full-width halo
    exchange producing the extended vertex table ``local_take`` indexes
    into ([n_pad + W*S, F] on the halo side; ``x`` unchanged elsewhere).

    Split out so feature-chunked edge pipelines (models/gcn.py) can pay
    the cross-rank exchange ONCE per layer at full width and chunk only
    the local take — chunking through plain ``gather`` would re-issue the
    all_to_all per 128-wide slice.
    """
    if side != plan.halo_side:
        return x
    haloed = halo_exchange(x, plan.halo, axis_name, deltas=plan.halo_deltas)
    return jnp.concatenate([x, haloed], axis=0)


@_scoped("dgraph.local_take")
def local_take(full: jax.Array, plan: EdgePlan, side: str) -> jax.Array:
    """The LOCAL half of :func:`gather`: per-edge rows taken from the
    (already halo-extended) vertex table. No collectives; masked edges are
    zero."""
    from dgraph_tpu import config as _cfg

    idx = _side_index(plan, side)
    if side == plan.halo_side:
        # halo-side ids are NOT monotone (local rows then halo slots); the
        # plan's sorting permutation still gives the VJP a sorted
        # segment-sum path (gather-by-perm first) when present
        if plan.halo_sort_perm is not None:
            taken = local_ops.take_rows_sort_route(
                full, idx, plan.halo_sort_perm, plan.halo_sorted_ids,
                pallas_hints=(
                    plan.scatter_block_e, plan.scatter_block_n, plan.halo_sort_mc
                ),
            )
            return taken * plan.edge_mask[:, None].astype(full.dtype)
        sorted_ids = False
    else:
        # owner-side ids are plan-sorted; route the VJP (a scatter-sum
        # transpose, _torch_func_impl.py:112-191) through the sorted path
        sorted_ids = plan.ids_sorted(side)
    hints = (
        (plan.scatter_block_e, plan.scatter_block_n, plan.scatter_mc)
        if (sorted_ids and _cfg.pallas_scatter_enabled())
        else None
    )
    taken = local_ops.take_rows(
        full, idx, indices_are_sorted=sorted_ids, pallas_hints=hints,
        gather_mv=plan.gather_mv,
    )
    return taken * plan.edge_mask[:, None].astype(full.dtype)


@_scoped("dgraph.gather")
def gather(
    x: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str]
) -> jax.Array:
    """Per-edge features gathered from one endpoint side.

    Parity: ``Communicator.gather`` / ``CommPlan_GatherFunction``
    (``_torch_func_impl.py:27-110``): local vertex→edge copy + boundary
    all_to_all + received-row placement. Here the non-halo side is a pure
    local take; the halo side prepends one halo exchange
    (= :func:`halo_extend` then :func:`local_take`).

    Args:
      x: [n_pad, F] per-shard vertex features for that side's vertex set.
    Returns: [e_pad, F] per-edge features (masked edges are zero).
    """
    return local_take(halo_extend(x, plan, side, axis_name), plan, side)


@_scoped("dgraph.scatter_sum")
def scatter_sum(
    edata: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str]
) -> jax.Array:
    """Sum per-edge values into that side's vertices (cross-rank aware).

    Parity: ``Communicator.scatter`` / ``CommPlan_ScatterFunction``
    (``_torch_func_impl.py:194-280``). TPU has no remote atomics (the NVSHMEM
    backend's CAS scatter-add, ``nvshmem_comm_kernels.cuh:17-54``), so the
    remote leg is: local segment-sum into halo slots (pre-aggregation per
    unique remote vertex — the reference's dedup does the same,
    ``_NCCLCommPlan.py:221-226``) → reverse all_to_all → local segment-sum.

    Args:
      edata: [e_pad, F] per-edge values.
    Returns: [n_pad, F] per-vertex sums for the requested side.
    """
    # mask in the activation dtype — a f32 mask would silently upcast bf16
    # edge tensors (and disable the bf16 kernel fast path below)
    edata = edata * plan.edge_mask[:, None].astype(edata.dtype)
    idx = _side_index(plan, side)
    n_pad = _side_npad(plan, side)
    if side != plan.halo_side:
        # owner-side aggregation: plan-sorted monotone segment ids ride the
        # shared Pallas-or-jnp dispatch (kill switch + precision policy in
        # ONE place: ops.local.sorted_segment_sum_any)
        if plan.ids_sorted(side):
            return local_ops.sorted_segment_sum_any(
                edata, idx, n_pad, plan.scatter_block_e, plan.scatter_block_n,
                plan.scatter_mc, gather_mv=plan.gather_mv,
            )
        return local_ops.segment_sum(edata, idx, n_pad, indices_are_sorted=False)
    W = plan.world_size
    n_full = n_pad + W * plan.halo.s_pad
    if plan.halo_sort_perm is not None:
        # unsorted halo-side ids, but the plan's sorting permutation turns
        # the forward into gather-by-perm + sorted segment-sum (Pallas MXU)
        full = local_ops.segment_sum_sort_route(
            edata, idx, plan.halo_sort_perm, plan.halo_sorted_ids, n_full,
            pallas_hints=(
                plan.scatter_block_e, plan.scatter_block_n, plan.halo_sort_mc
            ),
        )
    else:
        full = local_ops.segment_sum(edata, idx, n_full)
    local_part = full[:n_pad]
    remote_part = full[n_pad:]
    return local_part + halo_scatter_sum(
        remote_part, plan.halo, n_pad, axis_name, deltas=plan.halo_deltas
    )


@_scoped("dgraph.scatter_bias_relu")
def scatter_bias_relu(
    edata: jax.Array,  # [e_pad, F] per-edge stream (e.g. gathered src proj)
    bias: jax.Array,  # [n_pad, F] owner-side vertex operand
    plan: EdgePlan,
    side: str,
    axis_name: Optional[str],
    edge_weight: Optional[jax.Array] = None,  # [e_pad]
) -> jax.Array:
    """Fused owner-side aggregation: out[v] = Σ_e w_e · relu(edata_e + bias_v).

    Parity: the reference's fused scatter kernels
    (``Fused_ReLU_Scatter_Kernel`` / ``Fused_Sum_Norm_Scatter_Kernel``,
    ``local_data_kernels.cuh:34-116``). On TPU the fusion must live INSIDE
    the Pallas kernel (``pallas_call`` is an XLA fusion barrier, so the
    composed path materializes the [E, F] message tensor in HBM); off-TPU
    (or non-owner side) it falls back to the exact composed ops.
    """
    idx = _side_index(plan, side)
    n_pad = _side_npad(plan, side)
    # one compute dtype on both paths: the kernel runs bias at edata's
    # precision, so the fallback must too (cross-backend equivalence)
    bias = bias.astype(edata.dtype)
    if plan.ids_sorted(side):
        # owner side: shared Pallas-or-jnp dispatch (kill switch + precision
        # policy in ONE place — ops.local)
        return local_ops.sorted_segment_sum_bias_relu_any(
            edata, idx, bias, n_pad,
            plan.scatter_block_e, plan.scatter_block_n, plan.scatter_mc,
            edge_weight=edge_weight, gather_mv=plan.gather_mv,
        )
    m = jax.nn.relu(edata + gather(bias, plan, side, axis_name))
    if edge_weight is not None:
        m = m * edge_weight[:, None].astype(m.dtype)
    return scatter_sum(m, plan, side, axis_name)


@_scoped("dgraph.gather_concat")
def gather_concat(
    x_src: jax.Array,
    x_dst: jax.Array,
    plan: EdgePlan,
    axis_name: Optional[str],
) -> jax.Array:
    """[e_pad, F_src+F_dst] concat of src- and dst-side per-edge features.

    The reference's GCN/GAT layers start with exactly this double gather
    (``experiments/OGB/GCN.py:28-67``, ``RGAT.py:174-206``).
    """
    hs = gather(x_src, plan, "src", axis_name)
    hd = gather(x_dst, plan, "dst", axis_name)
    return jnp.concatenate([hs, hd], axis=-1)


def psum_mean(x, axis_name: Optional[str]):
    """Mean over a mesh axis (None = identity). For DP gradient sync —
    replaces the reference's DDP all-reduce (``experiments/OGB/main.py:111``)."""
    if axis_name is None:
        return x
    return lax.pmean(x, axis_name)
