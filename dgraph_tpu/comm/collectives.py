"""Differentiable distributed graph primitives (per-shard, inside shard_map).

TPU-native re-design of the reference's L5 "differentiable comm primitives"
(``DGraph/distributed/haloExchange.py``, ``nccl/_torch_func_impl.py``,
SURVEY.md §1 L5):

- ``HaloExchangeImpl`` (alltoallv by put-offsets, ``haloExchange.py:37-88``)
  ↦ :func:`halo_exchange`: a feature gather + one ``lax.all_to_all`` whose
  received blocks land directly in halo-slot order (no recv scatter needed).
- ``CommPlan_GatherFunction`` (local copy → all_to_all → boundary scatter,
  ``_torch_func_impl.py:27-191``) ↦ :func:`gather`.
- ``CommPlan_ScatterFunction`` (``_torch_func_impl.py:194-352``) ↦
  :func:`scatter_sum`.

No custom_vjp is required: every op here is linear in the data (take,
all_to_all, segment-sum, concat), and JAX's AD transposes them to exactly
the reference's hand-written backward pairs (gather-bwd = scatter-sum with
reversed splits, scatter-bwd = gather; ``_torch_func_impl.py:112-191,282-352``
and ``haloExchange.py:66-88``). The gradient tests in
``tests/test_collectives_grad.py`` pin this against the analytic transpose.

All functions take the PER-SHARD plan (leading [world_size] axis already
split off by shard_map; see :func:`dgraph_tpu.comm.mesh.squeeze_plan`) and an
``axis_name`` (None = single-device, world_size must be 1 — the reference's
SingleProcessDummyCommunicator pattern, ``GraphCast/dist_utils.py:8-39``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.plan import EdgePlan, HaloSpec, resolve_halo_impl
from dgraph_tpu.ops import local as local_ops


# Every collective shows up as a named region in jax.profiler/Perfetto
# traces (canonical alias lives in utils.timing).
from dgraph_tpu.utils.timing import named_scope as _scoped  # noqa: E402


def resolve_plan_impl(plan: EdgePlan, axis_name) -> str:
    """The halo lowering THIS call site will use — resolved exactly ONCE
    (env pin > adopted tuning record > heuristic; plan.resolve_halo_impl)
    and then threaded as a static ``impl`` argument into every leg of the
    op. The old scheme re-read the config at every trace of every leg, so
    a mid-run flag flip could hand the forward exchange and its transpose
    DIFFERENT lowerings inside one jitted step; resolving once per call
    site makes that impossible."""
    if axis_name is None:
        return "none"
    impl, _ = resolve_halo_impl(
        plan.world_size, plan.halo_deltas,
        overlap_available=getattr(plan, "overlap", None) is not None,
        sched_available=getattr(plan, "halo_schedule", None) is not None,
        pair_rows=getattr(plan, "halo_pair_rows", ()),
    )
    return impl


def resolve_plan_wire_format(plan: EdgePlan, axis_name) -> str:
    """The wire format THIS call site will encode halo payloads with —
    resolved exactly ONCE (env pin > adopted tuning record > the plan's
    build-time attachment > fp32 identity;
    :func:`dgraph_tpu.wire.spec.resolve_wire_format`) and threaded as a
    static ``wire_format`` argument into every leg of the op, for the
    same reason :func:`resolve_plan_impl` resolves once: a mid-run flag
    flip must never hand the forward exchange and its transpose
    DIFFERENT codecs inside one jitted step."""
    if axis_name is None:
        return "fp32"
    from dgraph_tpu.wire.spec import resolve_wire_format

    name, _source = resolve_wire_format(
        plan.world_size, tuple(plan.halo_deltas),
        plan_format=getattr(plan, "wire_format", "fp32"),
    )
    return name


def _wire_fns(wire_format, dtype):
    """Raw (encode, decode) for this format at this activation dtype —
    ``(None, None)`` keeps the caller's pre-codec code path byte-for-byte
    unchanged (the fp32 identity guarantee). Only called from inside the
    custom-VJP round executors, whose bodies are opaque to AD; plain-AD
    paths go through the wire-trip wrappers instead (an fp8 payload is a
    uint8 operand, and AD through an integer intermediate silently drops
    the gradient)."""
    if wire_format in (None, "fp32"):
        return None, None
    from dgraph_tpu.wire.codec import make_wire_transform

    return make_wire_transform(wire_format, str(jnp.dtype(dtype)))


def _resolve_halo_arg(impl, deltas, W) -> str:
    """Resolution for call sites that only hold a HaloSpec (no plan):
    ``impl=None`` resolves here; ``deltas=None`` means the caller carries
    no round info, which only the padded all_to_all can lower."""
    if impl is not None:
        return impl
    if deltas is None:
        return "all_to_all"
    impl, _ = resolve_halo_impl(W, tuple(deltas))
    return impl


def overlap_active(plan: EdgePlan, axis_name) -> bool:
    """True when THIS plan on THIS axis lowers its halo exchange as the
    interior/boundary overlap schedule (spec present + resolution says
    so) — the models' routing predicate."""
    return (
        axis_name is not None
        and getattr(plan, "overlap", None) is not None
        and resolve_plan_impl(plan, axis_name) == "overlap"
    )


SPLIT_IMPLS = ("overlap", "pallas_p2p")


def split_active(plan: EdgePlan, axis_name) -> bool:
    """True when this plan routes through the interior/boundary split —
    either split lowering: the double-buffered ppermute rounds
    (``overlap``) or the device-initiated one-sided puts (``pallas_p2p``).
    Everything downstream of the exchange (interior/boundary takes and
    owner-side scatter sums) is collective-free and shared by both, so
    models branch on THIS predicate and let
    :func:`halo_exchange_split` pick the transport."""
    return (
        axis_name is not None
        and getattr(plan, "overlap", None) is not None
        and resolve_plan_impl(plan, axis_name) in SPLIT_IMPLS
    )


def halo_exchange_split(x, plan: EdgePlan, axis_name) -> jax.Array:
    """The split lowerings' exchange leg: one resolution, then either the
    overlap ppermute rounds or the pallas_p2p one-sided puts — both
    produce the same ``[W*S, F]`` halo buffer the boundary takes index
    directly (and bit-identical values)."""
    impl = resolve_plan_impl(plan, axis_name)
    wf = resolve_plan_wire_format(plan, axis_name)
    if impl == "pallas_p2p":
        return halo_exchange_p2p(
            x, plan.halo, axis_name, tuple(plan.halo_deltas), wf
        )
    return halo_exchange_overlap(
        x, plan.halo, axis_name, tuple(plan.halo_deltas), wf
    )


def shard_map_checks(
    plan: Optional[EdgePlan] = None,
    axis_name=None,
    *,
    impl: Optional[str] = None,
    relax: Optional[str] = None,
) -> dict:
    """THE one source of ``jax.shard_map`` check kwargs — every call site
    in the tree routes through here (enforced by the
    ``no-unchecked-shard-map`` lint rule), so which programs run with the
    replication checker relaxed is a single greppable decision, not a
    sprinkle of raw ``check_vma=False``.

    Three spellings:

    - ``shard_map_checks(plan, axis_name)`` — resolve the halo lowering
      once (same place the lowering itself resolves) and relax ONLY for
      ``pallas_p2p`` programs: their ``pallas_call`` has no replication
      rule under jax 0.4.x's rep checker (``compat.RELAXED_CHECKS`` — a
      no-op on jax >= 0.6). Every other lowering keeps the checker on.
    - ``shard_map_checks(impl="pallas_p2p")`` — plan-less call sites that
      already KNOW their lowering (kernel selftests, audit scaffolding).
    - ``shard_map_checks(relax="<why>")`` — the documented escape for
      bodies the 0.4.x checker false-positives on regardless of lowering
      (replicated-by-construction init outputs, ring attention's causal
      ``lax.cond`` under AD). The reason string is mandatory and exists
      to be read in the caller — an un-explained relaxation is exactly
      what the lint rule forbids.
    """
    from dgraph_tpu import compat as _compat

    if relax is not None:
        return dict(_compat.RELAXED_CHECKS)
    if impl is None:
        if plan is None or axis_name is None:
            return {}
        impl = resolve_plan_impl(plan, axis_name)
    if impl == "pallas_p2p":
        return dict(_compat.RELAXED_CHECKS)
    return {}


def _overlap_rounds_fwd(x, send_idx, send_mask, axis_name, deltas, W, S,
                        wire_format="fp32"):
    """Double-buffered ppermute rounds: every round's send block is
    gathered up front and every CollectivePermute is issued before any
    received block is placed, so XLA's latency-hiding scheduler is free to
    run independent compute (the interior aggregation the callers
    interleave) while the wire is busy. Result layout and values are
    bit-identical to the padded all_to_all lowering (under the same
    ``wire_format``: each round's masked block is encoded per-row exactly
    as the a2a operand would be)."""
    F = x.shape[-1]
    me = lax.axis_index(axis_name)
    enc, dec = _wire_fns(wire_format, x.dtype)
    sends = []
    for d in deltas:
        peer_row = (me + d) % W
        idx = jnp.take(send_idx, peer_row, axis=0)
        msk = jnp.take(send_mask, peer_row, axis=0)
        blk = x[idx] * msk[..., None].astype(x.dtype)  # [S, F]
        sends.append(enc(blk) if enc is not None else blk)
    recvs = [
        lax.ppermute(s, axis_name, [(i, (i + d) % W) for i in range(W)])
        for s, d in zip(sends, deltas)
    ]
    out = jnp.zeros((W * S, F), x.dtype)
    for d, recv in zip(deltas, recvs):
        src_rank = (me - d) % W
        if dec is not None:
            recv = dec(recv)
        out = lax.dynamic_update_slice(out, recv, (src_rank * S, 0))
    return out


def _overlap_rounds_rev(h, send_idx, send_mask, n_pad, axis_name, deltas, W, S,
                        wire_format="fp32"):
    """Reverse of :func:`_overlap_rounds_fwd`: all reverse ppermutes are
    issued up front; the returned blocks are then placed into one [W, S]
    buffer and reduced with the SAME masked flat segment-sum the
    all_to_all path uses — so values are bit-identical to it, while the
    rounds themselves stay individually overlappable. The returning
    cotangent blocks ride the wire encoded with the same format as the
    forward payloads (decode happens BEFORE the mask-and-reduce, so the
    accumulation runs at the activation dtype)."""
    F = h.shape[-1]
    me = lax.axis_index(axis_name)
    enc, dec = _wire_fns(wire_format, h.dtype)
    h = h.reshape(W * S, F)
    blocks = []
    for d in deltas:
        src_rank = (me - d) % W
        blk = lax.dynamic_slice(h, (src_rank * S, 0), (S, F))
        blocks.append(enc(blk) if enc is not None else blk)
    recvs = [
        lax.ppermute(b, axis_name, [(i, (i - d) % W) for i in range(W)])
        for b, d in zip(blocks, deltas)
    ]
    back = jnp.zeros((W, S, F), h.dtype)
    for d, recv in zip(deltas, recvs):
        peer_row = (me + d) % W
        if dec is not None:
            recv = dec(recv)
        back = lax.dynamic_update_slice(back, recv[None], (peer_row, 0, 0))
    back = back * send_mask[..., None].astype(back.dtype)
    flat_idx = send_idx.reshape(-1)
    return local_ops.segment_sum(back.reshape(W * S, -1), flat_idx, n_pad)


@functools.lru_cache(maxsize=None)
def _make_overlap_pair(axis_name, deltas, W, S, n_pad, wire_format="fp32",
                       dtype_name="float32"):
    """The overlap exchange/unexchange custom-VJP pair. Mirrors the
    existing gather/scatter adjoint structure: the exchange's backward IS
    the reverse rounds (halo values delivered back to their owners) and
    the reverse's backward IS the forward rounds — pinned explicitly so
    the transpose keeps the double-buffered round schedule (JAX's default
    transpose would serialize placement chains) and keeps the masked
    segment-sum on the fast wrapper paths. The cache key carries the
    (static) wire format + activation dtype, so two configurations never
    share an executor — and because these bodies are opaque to AD, the
    codec's integer payloads (fp8) are safe inside them."""

    @jax.custom_vjp
    def exchange(x, send_idx, send_mask):
        return _overlap_rounds_fwd(x, send_idx, send_mask, axis_name, deltas,
                                   W, S, wire_format)

    def ex_fwd(x, send_idx, send_mask):
        return exchange(x, send_idx, send_mask), (send_idx, send_mask)

    def ex_bwd(res, g):
        send_idx, send_mask = res
        dx = _overlap_rounds_rev(
            g, send_idx, send_mask, n_pad, axis_name, deltas, W, S,
            wire_format)
        return dx, None, None

    exchange.defvjp(ex_fwd, ex_bwd)

    @jax.custom_vjp
    def unexchange(h, send_idx, send_mask):
        return _overlap_rounds_rev(
            h, send_idx, send_mask, n_pad, axis_name, deltas, W, S,
            wire_format)

    def un_fwd(h, send_idx, send_mask):
        return unexchange(h, send_idx, send_mask), (send_idx, send_mask)

    def un_bwd(res, g):
        send_idx, send_mask = res
        dh = _overlap_rounds_fwd(g, send_idx, send_mask, axis_name, deltas,
                                 W, S, wire_format)
        return dh, None, None

    unexchange.defvjp(un_fwd, un_bwd)
    return exchange, unexchange


def _p2p_rounds_fwd(x, send_idx, send_mask, axis_name, deltas, W, S,
                    wire_format="fp32"):
    """One-sided put schedule: gather each live delta's send tile exactly
    like the a2a path gathers its blocks, then hand the stack to the
    Pallas transport — the masking multiply fuses into the kernel (exact
    elementwise op, staged in VMEM, overlapped with the previous tile's
    in-flight put) and every tile DMAs straight into the destination
    shard's halo buffer. Result layout and values are bit-identical to
    the padded all_to_all lowering. Non-fp32 wire formats apply the mask
    BEFORE encoding (per-row fp8 scales depend only on the masked row, so
    the wire bytes match the a2a operand exactly) and ship the encoded
    tiles with ``mask=None`` — the kernel is dtype-generic and treats
    pre-masked tiles as pure data movement."""
    from dgraph_tpu.ops import pallas_p2p as _p2p

    me = lax.axis_index(axis_name)
    d = jnp.asarray(deltas, jnp.int32)
    peer_rows = (me + d) % W
    blocks = x[send_idx[peer_rows]]  # [n, S, F]
    msk = send_mask[peer_rows]  # [n, S]
    enc, dec = _wire_fns(wire_format, x.dtype)
    if enc is None:
        return _p2p.p2p_transport(blocks, axis_name, deltas, W, S, mask=msk)
    wire = enc(blocks * msk[..., None].astype(x.dtype))
    out = _p2p.p2p_transport(wire, axis_name, deltas, W, S)
    return dec(out.reshape(W, S, -1)).reshape(W * S, -1)


def _p2p_rounds_rev(h, send_idx, send_mask, n_pad, axis_name, deltas, W, S,
                    wire_format="fp32"):
    """Reverse of :func:`_p2p_rounds_fwd`: each delta's halo-slot block
    flies back to its owner as a one-sided put (``sign=-1`` mirrors the
    forward targets), lands in the same per-source-rank layout the
    all_to_all reverse produces, and reduces with the SAME masked flat
    segment-sum — bit-identical values, one-sided transport. Cotangent
    blocks are encoded UNMASKED (mask applies after decode, exactly as
    the other reverse lowerings order it) so the per-row wire bytes match
    the a2a reverse operand."""
    from dgraph_tpu.ops import pallas_p2p as _p2p

    F = h.shape[-1]
    me = lax.axis_index(axis_name)
    d = jnp.asarray(deltas, jnp.int32)
    src_rows = (me - d) % W
    blocks = h.reshape(W, S, F)[src_rows]  # [n, S, F]
    enc, dec = _wire_fns(wire_format, h.dtype)
    if enc is None:
        back = _p2p.p2p_transport(blocks, axis_name, deltas, W, S, sign=-1)
        back = back.reshape(W, S, F)
    else:
        wire = _p2p.p2p_transport(enc(blocks), axis_name, deltas, W, S,
                                  sign=-1)
        back = dec(wire.reshape(W, S, -1))
    back = back * send_mask[..., None].astype(h.dtype)
    flat_idx = send_idx.reshape(-1)
    return local_ops.segment_sum(back.reshape(W * S, -1), flat_idx, n_pad)


@functools.lru_cache(maxsize=None)
def _make_p2p_pair(axis_name, deltas, W, S, n_pad, wire_format="fp32",
                   dtype_name="float32"):
    """The pallas_p2p exchange/unexchange custom-VJP pair — the exact
    mirror of :func:`_make_overlap_pair` with the ppermute rounds swapped
    for the one-sided transport: the exchange's backward IS the reverse
    puts (halo cotangents delivered back to their owners) and the
    reverse's backward IS the forward puts. Pinned explicitly so AD never
    differentiates through the pallas_call (the kernel is pure data
    movement; its transpose is the mirrored transport). Cache key carries
    the static wire format + activation dtype like the overlap pair."""

    @jax.custom_vjp
    def exchange(x, send_idx, send_mask):
        return _p2p_rounds_fwd(x, send_idx, send_mask, axis_name, deltas,
                               W, S, wire_format)

    def ex_fwd(x, send_idx, send_mask):
        return exchange(x, send_idx, send_mask), (send_idx, send_mask)

    def ex_bwd(res, g):
        send_idx, send_mask = res
        dx = _p2p_rounds_rev(
            g, send_idx, send_mask, n_pad, axis_name, deltas, W, S,
            wire_format)
        return dx, None, None

    exchange.defvjp(ex_fwd, ex_bwd)

    @jax.custom_vjp
    def unexchange(h, send_idx, send_mask):
        return _p2p_rounds_rev(
            h, send_idx, send_mask, n_pad, axis_name, deltas, W, S,
            wire_format)

    def un_fwd(h, send_idx, send_mask):
        return unexchange(h, send_idx, send_mask), (send_idx, send_mask)

    def un_bwd(res, g):
        send_idx, send_mask = res
        dh = _p2p_rounds_fwd(g, send_idx, send_mask, axis_name, deltas,
                             W, S, wire_format)
        return dh, None, None

    unexchange.defvjp(un_fwd, un_bwd)
    return exchange, unexchange


@_scoped("dgraph.halo_exchange_p2p")
def halo_exchange_p2p(
    x: jax.Array,
    halo: HaloSpec,
    axis_name: Optional[str],
    deltas: tuple,
    wire_format: str = "fp32",
) -> jax.Array:
    """:func:`halo_exchange` lowered as device-initiated one-sided puts
    (``pltpu.make_async_remote_copy`` issued from inside the Pallas
    kernel — the TPU analogue of DGraph's NVSHMEM backend, PAPER.md
    L1/L2): per-tile DMAs with semaphores in scratch, the send-mask
    multiply fused in-kernel and double-buffered against the in-flight
    put, no exchange buffer staged through HBM. Values are bit-identical
    to the all_to_all lowering; the custom VJP is the mirrored reverse
    transport."""
    W, S = halo.send_idx.shape[0], halo.s_pad
    if axis_name is None or not deltas:
        return halo_exchange(x, halo, axis_name, deltas=deltas, impl="none")
    ex, _ = _make_p2p_pair(axis_name, tuple(deltas), W, S, x.shape[0],
                           wire_format, str(jnp.dtype(x.dtype)))
    return ex(x, halo.send_idx, halo.send_mask)


@_scoped("dgraph.halo_scatter_sum_p2p")
def halo_scatter_sum_p2p(
    h: jax.Array,
    halo: HaloSpec,
    n_pad: int,
    axis_name: Optional[str],
    deltas: tuple,
    wire_format: str = "fp32",
) -> jax.Array:
    """:func:`halo_scatter_sum` lowered as reverse one-sided puts (the
    pallas_p2p pair's transpose): every halo-slot partial flies back to
    its owner as a per-tile DMA, then reduces with the same masked flat
    segment-sum the all_to_all reverse path runs — bit-identical
    values."""
    W, S = halo.send_idx.shape[0], halo.s_pad
    if axis_name is None or not deltas:
        return halo_scatter_sum(h, halo, n_pad, axis_name, deltas=deltas,
                                impl="none")
    _, unex = _make_p2p_pair(axis_name, tuple(deltas), W, S, n_pad,
                             wire_format, str(jnp.dtype(h.dtype)))
    return unex(h, halo.send_idx, halo.send_mask)


@_scoped("dgraph.halo_exchange_overlap")
def halo_exchange_overlap(
    x: jax.Array,
    halo: HaloSpec,
    axis_name: Optional[str],
    deltas: tuple,
    wire_format: str = "fp32",
) -> jax.Array:
    """:func:`halo_exchange` lowered as double-buffered ppermute rounds
    built for compute–communication overlap: all sends are gathered and
    all rounds issued before any receive is consumed, so interior work
    scheduled between this call and the first use of its result hides the
    wire time (the redistribution-as-overlappable-rounds strategy of
    arxiv 2112.01075). Values are bit-identical to the all_to_all
    lowering; the custom VJP is the mirrored reverse-round schedule."""
    W, S = halo.send_idx.shape[0], halo.s_pad
    if axis_name is None or not deltas:
        return halo_exchange(x, halo, axis_name, deltas=deltas, impl="none")
    ex, _ = _make_overlap_pair(axis_name, tuple(deltas), W, S, x.shape[0],
                               wire_format, str(jnp.dtype(x.dtype)))
    return ex(x, halo.send_idx, halo.send_mask)


@_scoped("dgraph.halo_scatter_sum_overlap")
def halo_scatter_sum_overlap(
    h: jax.Array,
    halo: HaloSpec,
    n_pad: int,
    axis_name: Optional[str],
    deltas: tuple,
    wire_format: str = "fp32",
) -> jax.Array:
    """:func:`halo_scatter_sum` lowered as double-buffered reverse
    ppermute rounds (the overlap pair's transpose): issue every reverse
    round first, reduce after — the caller's interior aggregation runs
    while the rounds are in flight. Bit-identical to the all_to_all
    reverse path (same masked flat segment-sum over the same buffer)."""
    W, S = halo.send_idx.shape[0], halo.s_pad
    if axis_name is None or not deltas:
        return halo_scatter_sum(h, halo, n_pad, axis_name, deltas=deltas,
                                impl="none")
    _, unex = _make_overlap_pair(axis_name, tuple(deltas), W, S, n_pad,
                                 wire_format, str(jnp.dtype(h.dtype)))
    return unex(h, halo.send_idx, halo.send_mask)


def _sched_rounds_fwd(x, send_idx, send_mask, axis_name, schedule, W, S,
                      wire_format="fp32"):
    """Replay a compiled :class:`~dgraph_tpu.sched.ir.HaloSchedule`:
    per round, every rank gathers + masks the send block for its (static)
    round peer and slices its transfer's row window; all ppermutes are
    issued before any received block is placed (the overlap executor's
    double-buffered shape, so XLA's scheduler can hide the wire behind
    interleaved compute). Placement offsets come from the schedule's
    per-rank static tables indexed by the traced ``lax.axis_index`` —
    every rank traces the IDENTICAL program (the SPMD auditor's
    invariant). Ranks a round's ppermute names as no-one's receiver get
    zeros (lax.ppermute semantics), which land in a scratch tail row
    band ``[W*S, W*S+Cmax)`` and are dropped, so the clamping semantics
    of dynamic_update_slice never corrupt live slots. Result layout and
    values are bit-identical to the padded all_to_all lowering: each
    round writes rows of the masked (src -> dst) send block at the same
    ``src*S + row`` halo-slot positions the all_to_all produces, padded
    round rows carry the same masked values both lowerings carry, and
    the verifier guarantees the transfers tile each live block exactly
    once."""
    F = x.shape[-1]
    me = lax.axis_index(axis_name)
    enc, dec = _wire_fns(wire_format, x.dtype)
    rows = schedule.round_rows()
    c_max = max(rows)
    sends = []
    for k in range(schedule.num_rounds):
        ra = schedule.rank_arrays(k)
        dst = jnp.asarray(ra["send_dst"], jnp.int32)[me]
        start = jnp.asarray(ra["send_start"], jnp.int32)[me]
        idx = jnp.take(send_idx, dst, axis=0)
        msk = jnp.take(send_mask, dst, axis=0)
        blk = x[idx] * msk[..., None].astype(x.dtype)  # [S, F]
        blk = lax.dynamic_slice(blk, (start, 0), (rows[k], F))
        # encode AFTER the row slice: per-row codecs commute with row
        # slicing, so the wire bytes match the a2a operand's rows exactly
        sends.append(enc(blk) if enc is not None else blk)
    recvs = [
        lax.ppermute(s, axis_name, schedule.rounds[k].pairs)
        for k, s in enumerate(sends)
    ]
    out = jnp.zeros((W * S + c_max, F), x.dtype)
    for k, recv in enumerate(recvs):
        ra = schedule.rank_arrays(k)
        off = jnp.asarray(ra["place_off"], jnp.int32)[me]
        if dec is not None:
            # non-receivers get all-zero wire rows from ppermute, which
            # every codec decodes to exactly 0.0 — the scratch band stays
            # as clean as in the fp32 path
            recv = dec(recv)
        out = lax.dynamic_update_slice(out, recv, (off, 0))
    return out[: W * S]


def _sched_rounds_rev(h, send_idx, send_mask, n_pad, axis_name, schedule,
                      W, S, wire_format="fp32"):
    """Reverse replay: per round, each fwd RECEIVER slices the cotangent
    window its transfer landed in and ppermutes it along the reversed
    pairs back to the fwd sender, which parks it in its ``[W+1, S, F]``
    reduce buffer (plane = the peer it had sent to; idle ranks park the
    zeros ppermute hands them in the scratch plane W). The buffer then
    reduces with the SAME masked flat segment-sum the all_to_all reverse
    runs — the mask zeroes padded round rows, so values are bit-identical
    to it. All reverse ppermutes are issued before any placement, keeping
    each round individually overlappable (the exact mirror of
    :func:`_overlap_rounds_rev`)."""
    F = h.shape[-1]
    me = lax.axis_index(axis_name)
    enc, dec = _wire_fns(wire_format, h.dtype)
    h = h.reshape(W * S, F)
    rows = schedule.round_rows()
    blocks = []
    for k in range(schedule.num_rounds):
        ra = schedule.rank_arrays(k)
        off = jnp.asarray(ra["slice_off"], jnp.int32)[me]
        blk = lax.dynamic_slice(h, (off, 0), (rows[k], F))
        blocks.append(enc(blk) if enc is not None else blk)
    recvs = [
        lax.ppermute(
            b, axis_name,
            [(d, s) for (s, d) in schedule.rounds[k].pairs],
        )
        for k, b in enumerate(blocks)
    ]
    back = jnp.zeros((W + 1, S, F), h.dtype)
    for k, recv in enumerate(recvs):
        ra = schedule.rank_arrays(k)
        plane = jnp.asarray(ra["back_plane"], jnp.int32)[me]
        start = jnp.asarray(ra["send_start"], jnp.int32)[me]
        if dec is not None:
            recv = dec(recv)
        back = lax.dynamic_update_slice(back, recv[None], (plane, start, 0))
    back = back[:W] * send_mask[..., None].astype(back.dtype)
    flat_idx = send_idx.reshape(-1)
    return local_ops.segment_sum(back.reshape(W * S, -1), flat_idx, n_pad)


@functools.lru_cache(maxsize=None)
def _make_sched_pair(axis_name, schedule, W, S, n_pad, wire_format="fp32",
                     dtype_name="float32"):
    """The compiled-schedule exchange/unexchange custom-VJP pair — the
    exact mirror of :func:`_make_overlap_pair` with the per-delta rings
    swapped for the compiled rounds: the exchange's backward IS the
    reverse replay and the reverse's backward IS the forward replay,
    pinned explicitly so the transpose keeps the round schedule (and its
    op count, which the trace/HLO auditors pin per-round) instead of
    whatever JAX's default transpose would serialize. Cache key includes
    the (frozen, hashable) schedule itself plus the static wire format +
    activation dtype, so two configurations never share an executor."""

    @jax.custom_vjp
    def exchange(x, send_idx, send_mask):
        return _sched_rounds_fwd(
            x, send_idx, send_mask, axis_name, schedule, W, S, wire_format)

    def ex_fwd(x, send_idx, send_mask):
        return exchange(x, send_idx, send_mask), (send_idx, send_mask)

    def ex_bwd(res, g):
        send_idx, send_mask = res
        dx = _sched_rounds_rev(
            g, send_idx, send_mask, n_pad, axis_name, schedule, W, S,
            wire_format)
        return dx, None, None

    exchange.defvjp(ex_fwd, ex_bwd)

    @jax.custom_vjp
    def unexchange(h, send_idx, send_mask):
        return _sched_rounds_rev(
            h, send_idx, send_mask, n_pad, axis_name, schedule, W, S,
            wire_format)

    def un_fwd(h, send_idx, send_mask):
        return unexchange(h, send_idx, send_mask), (send_idx, send_mask)

    def un_bwd(res, g):
        send_idx, send_mask = res
        dh = _sched_rounds_fwd(
            g, send_idx, send_mask, axis_name, schedule, W, S, wire_format)
        return dh, None, None

    unexchange.defvjp(un_fwd, un_bwd)
    return exchange, unexchange


@_scoped("dgraph.halo_exchange_sched")
def halo_exchange_sched(
    x: jax.Array,
    halo: HaloSpec,
    axis_name: Optional[str],
    schedule,
    wire_format: str = "fp32",
) -> jax.Array:
    """:func:`halo_exchange` lowered as a compiled multi-round schedule
    (:mod:`dgraph_tpu.sched`): small pairs merged into shared ppermute
    rounds, hub pairs recursive-doubling split across rounds, rounds
    ordered heavy-first — all decided at plan build and replayed here as
    data. Values are bit-identical to the all_to_all lowering; the
    custom VJP is the mirrored reverse replay."""
    W, S = halo.send_idx.shape[0], halo.s_pad
    if axis_name is None or schedule is None or not schedule.rounds:
        return halo_exchange(x, halo, axis_name, deltas=(), impl="none")
    ex, _ = _make_sched_pair(axis_name, schedule, W, S, x.shape[0],
                             wire_format, str(jnp.dtype(x.dtype)))
    return ex(x, halo.send_idx, halo.send_mask)


@_scoped("dgraph.halo_scatter_sum_sched")
def halo_scatter_sum_sched(
    h: jax.Array,
    halo: HaloSpec,
    n_pad: int,
    axis_name: Optional[str],
    schedule,
    wire_format: str = "fp32",
) -> jax.Array:
    """:func:`halo_scatter_sum` lowered as the compiled schedule's
    reverse replay (the sched pair's transpose) — same masked flat
    segment-sum over the same buffer as the all_to_all reverse,
    bit-identical values."""
    W, S = halo.send_idx.shape[0], halo.s_pad
    if axis_name is None or schedule is None or not schedule.rounds:
        return halo_scatter_sum(h, halo, n_pad, axis_name, deltas=(),
                                impl="none")
    _, unex = _make_sched_pair(axis_name, schedule, W, S, n_pad,
                               wire_format, str(jnp.dtype(h.dtype)))
    return unex(h, halo.send_idx, halo.send_mask)


@_scoped("dgraph.halo_exchange")
def halo_exchange(
    x: jax.Array,
    halo: HaloSpec,
    axis_name: Optional[str],
    deltas: Optional[tuple] = None,
    impl: Optional[str] = None,
    schedule=None,
    wire_format: Optional[str] = None,
) -> jax.Array:
    """Exchange boundary vertex features; returns the halo buffer.

    Several lowerings, same result layout and values:
    - all_to_all (default): one padded collective; received block from peer
      p lands at rows ``[p*S, (p+1)*S)`` — exactly the plan's halo-slot
      numbering, no receive-placement pass.
    - ppermute neighbor rounds (when ``deltas`` — the static set of rank
      offsets with traffic — is sparse): one CollectivePermute per delta,
      skipping empty peer pairs entirely (SURVEY §7 "ppermute rounds only
      to actual neighbors"; the NVSHMEM one-sided put analogue).
    - overlap: the double-buffered round schedule
      (:func:`halo_exchange_overlap`).
    - sched: a compiled multi-round schedule replayed as data
      (:func:`halo_exchange_sched`; requires ``schedule`` — the plan's
      attached :class:`~dgraph_tpu.sched.ir.HaloSchedule`).

    Args:
      x: [n_pad, F] local (padded) vertex features of this shard.
      halo: per-shard spec; send_idx [W, S], send_mask [W, S].
      axis_name: mesh axis to exchange over, or None (single device).
      deltas: static tuple of active (peer-rank) mod W offsets
        (``EdgePlan.halo_deltas``); None disables the round-based paths.
      impl: the lowering, already resolved by the CALLER (one resolution
        per call site — see :func:`resolve_plan_impl`); None resolves
        here for direct/legacy callers.
      schedule: the plan's compiled HaloSchedule (``plan.halo_schedule``)
        — consulted only under ``impl='sched'``, where its absence is a
        loud error: the resolver only returns 'sched' when the plan
        carries a schedule, so a miss here means a caller bypassed it.
      wire_format: the payload codec (dgraph_tpu.wire), already resolved
        by the CALLER like ``impl`` (one resolution per call site — see
        :func:`resolve_plan_wire_format`). None = 'fp32' identity, which
        leaves every lowering's program literally unchanged.
    """
    F = x.shape[-1]
    W, S = halo.send_idx.shape[0], halo.s_pad
    wf = wire_format or "fp32"
    if axis_name is not None and deltas is not None and len(deltas) == 0:
        # no live cross-rank traffic anywhere in the mesh (send_mask is
        # all-zero): the exchange is identically zero, so skip the padded
        # collective entirely — this is what makes the resolver's
        # 'none' verdict (and obs.footprint's 0-byte accounting) truthful
        return jnp.zeros((W * S, F), x.dtype)
    if axis_name is None:
        # mask in x's dtype: the plan stores send_mask as f32, and a raw
        # multiply silently upcasts a bf16 stream — which then upcasts the
        # halo_extend concat and EVERY downstream [E, F] tensor of the
        # layer (caught in the r4 TPU export: the whole edge pipeline ran
        # f32 and the scatter kernel picked its "highest" precision path)
        send = x[halo.send_idx] * halo.send_mask[..., None].astype(x.dtype)
        return send.reshape(-1, F)  # world size 1: mask is all-zero
    impl = _resolve_halo_arg(impl, deltas, W)
    if impl == "pallas_p2p":
        return halo_exchange_p2p(x, halo, axis_name, tuple(deltas), wf)
    if impl == "overlap":
        return halo_exchange_overlap(x, halo, axis_name, tuple(deltas), wf)
    if impl == "sched":
        if schedule is None:
            raise ValueError(
                "halo_exchange(impl='sched') needs the plan's compiled "
                "halo schedule; resolve through resolve_plan_impl and "
                "pass schedule=plan.halo_schedule"
            )
        return halo_exchange_sched(x, halo, axis_name, schedule, wf)
    if impl == "ppermute":
        from dgraph_tpu.wire.codec import make_ppermute_codec

        me = lax.axis_index(axis_name)
        out = jnp.zeros((W * S, F), x.dtype)
        for d in deltas:
            peer_row = (me + d) % W
            idx = jnp.take(halo.send_idx, peer_row, axis=0)
            msk = jnp.take(halo.send_mask, peer_row, axis=0)
            send = x[idx] * msk[..., None].astype(x.dtype)  # [S, F]
            perm = tuple((i, (i + d) % W) for i in range(W))
            # trip = decode(ppermute(encode(.))) wrapped in a custom VJP
            # (the fp8 payload is uint8 — plain AD would drop the
            # cotangent); None = identity format, plain ppermute
            trip = make_ppermute_codec(axis_name, perm, wf,
                                       str(jnp.dtype(x.dtype)))
            if trip is None:
                recv = lax.ppermute(send, axis_name, list(perm))
            else:
                recv = trip(send)
            src_rank = (me - d) % W
            out = lax.dynamic_update_slice(out, recv, (src_rank * S, 0))
        return out
    from dgraph_tpu.wire.codec import make_a2a_codec

    send = x[halo.send_idx] * halo.send_mask[..., None].astype(x.dtype)
    trip = make_a2a_codec(axis_name, wf, str(jnp.dtype(x.dtype)))
    if trip is None:
        recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0)
    else:
        recv = trip(send)
    return recv.reshape(-1, F)


@_scoped("dgraph.halo_scatter_sum")
def halo_scatter_sum(
    h: jax.Array,
    halo: HaloSpec,
    n_pad: int,
    axis_name: Optional[str],
    deltas: Optional[tuple] = None,
    impl: Optional[str] = None,
    schedule=None,
    wire_format: Optional[str] = None,
) -> jax.Array:
    """Linear transpose of :func:`halo_exchange`: deliver halo-slot values
    back to their owner ranks and sum into local vertices.

    This is the reference's halo-exchange backward (reversed put offsets,
    ``haloExchange.py:66-88``) and the boundary leg of
    ``CommPlan_ScatterFunction.forward`` (``_torch_func_impl.py:194-280``).

    Args:
      h: [W*S, F] halo-buffer values on this shard.
      impl: the lowering, resolved once by the caller (see
        :func:`resolve_plan_impl`); None resolves here.
      wire_format: payload codec, resolved by the caller like ``impl``
        (:func:`resolve_plan_wire_format`); None = fp32 identity.
    Returns: [n_pad, F] per-local-vertex sums.
    """
    W, S = halo.send_idx.shape[0], halo.s_pad
    F = h.shape[-1]
    wf = wire_format or "fp32"
    if axis_name is not None and deltas is not None and len(deltas) == 0:
        # transpose of the empty exchange: no halo slot maps anywhere
        return jnp.zeros((n_pad, F), h.dtype)
    if axis_name is not None:
        impl = _resolve_halo_arg(impl, deltas, W)
        if impl == "pallas_p2p":
            return halo_scatter_sum_p2p(h, halo, n_pad, axis_name,
                                        tuple(deltas), wf)
        if impl == "overlap":
            return halo_scatter_sum_overlap(h, halo, n_pad, axis_name,
                                            tuple(deltas), wf)
        if impl == "sched":
            if schedule is None:
                raise ValueError(
                    "halo_scatter_sum(impl='sched') needs the plan's "
                    "compiled halo schedule; resolve through "
                    "resolve_plan_impl and pass schedule=plan.halo_schedule"
                )
            return halo_scatter_sum_sched(h, halo, n_pad, axis_name,
                                          schedule, wf)
        if impl == "ppermute":
            from dgraph_tpu.wire.codec import make_ppermute_codec

            me = lax.axis_index(axis_name)
            out = jnp.zeros((n_pad, F), h.dtype)
            for d in deltas:
                # my halo rows from rank (me-d) go back to their owner
                # (me-d); I receive my own vertices' partials from (me+d)
                src_rank = (me - d) % W
                block = lax.dynamic_slice(
                    h.reshape(W * S, F), (src_rank * S, 0), (S, F))
                perm = tuple((i, (i - d) % W) for i in range(W))
                trip = make_ppermute_codec(axis_name, perm, wf,
                                           str(jnp.dtype(h.dtype)))
                if trip is None:
                    recv = lax.ppermute(block, axis_name, list(perm))
                else:
                    recv = trip(block)  # from (me+d)
                peer_row = (me + d) % W
                idx = jnp.take(halo.send_idx, peer_row, axis=0)
                msk = jnp.take(halo.send_mask, peer_row, axis=0)
                out = out + local_ops.segment_sum(
                    recv * msk[..., None].astype(h.dtype), idx, n_pad)
            return out
    h = h.reshape(W, S, F)
    if axis_name is None:
        back = h
    else:
        from dgraph_tpu.wire.codec import make_a2a_codec

        trip = make_a2a_codec(axis_name, wf, str(jnp.dtype(h.dtype)))
        if trip is None:
            back = lax.all_to_all(h, axis_name, split_axis=0, concat_axis=0)
        else:
            # cotangent rows ride the wire encoded UNMASKED (the mask
            # applies after decode, below) — same ordering as every
            # round-based reverse lowering, so wire bytes stay identical
            back = trip(h)
    back = back * halo.send_mask[..., None].astype(back.dtype)
    flat_idx = halo.send_idx.reshape(-1)
    return local_ops.segment_sum(back.reshape(flat_idx.shape[0], -1), flat_idx, n_pad)


def _side_index(plan: EdgePlan, side: str) -> jax.Array:
    return plan.src_index if side == "src" else plan.dst_index


def _side_npad(plan: EdgePlan, side: str) -> int:
    return plan.n_src_pad if side == "src" else plan.n_dst_pad


def map_feature_chunks(fn, width: int, chunk: Optional[int] = None):
    """Scaffold of the feature-chunked edge pipeline (models/gcn.py
    rationale): apply ``fn(slice)`` over <=chunk-wide feature slices and
    concat the results on the last axis. ``chunk`` defaults to
    ``config.gather_col_block``. Callers are responsible for the gates
    (feature-separable per-edge math, collective-free per-chunk ops —
    pair with :func:`halo_extend` + :func:`local_take`)."""
    from dgraph_tpu import config as _cfg

    cb = chunk or _cfg.gather_col_block or width
    outs = [fn(slice(j, min(j + cb, width))) for j in range(0, width, cb)]
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


@_scoped("dgraph.halo_extend")
def halo_extend(
    x: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str],
    impl: Optional[str] = None,
) -> jax.Array:
    """The COMMUNICATION half of :func:`gather`: one full-width halo
    exchange producing the extended vertex table ``local_take`` indexes
    into ([n_pad + W*S, F] on the halo side; ``x`` unchanged elsewhere).

    Split out so feature-chunked edge pipelines (models/gcn.py) can pay
    the cross-rank exchange ONCE per layer at full width and chunk only
    the local take — chunking through plain ``gather`` would re-issue the
    all_to_all per 128-wide slice.
    """
    if side != plan.halo_side:
        return x
    if impl is None and axis_name is not None:
        impl = resolve_plan_impl(plan, axis_name)
    haloed = halo_exchange(x, plan.halo, axis_name, deltas=plan.halo_deltas,
                           impl=impl,
                           schedule=getattr(plan, "halo_schedule", None),
                           wire_format=resolve_plan_wire_format(
                               plan, axis_name))
    return jnp.concatenate([x, haloed], axis=0)


@_scoped("dgraph.local_take")
def local_take(full: jax.Array, plan: EdgePlan, side: str) -> jax.Array:
    """The LOCAL half of :func:`gather`: per-edge rows taken from the
    (already halo-extended) vertex table. No collectives; masked edges are
    zero."""
    from dgraph_tpu import config as _cfg

    idx = _side_index(plan, side)
    if side == plan.halo_side:
        # halo-side ids are NOT monotone (local rows then halo slots); the
        # plan's sorting permutation still gives the VJP a sorted
        # segment-sum path (gather-by-perm first) when present
        if plan.halo_sort_perm is not None:
            taken = local_ops.take_rows_sort_route(
                full, idx, plan.halo_sort_perm, plan.halo_sorted_ids,
                pallas_hints=(
                    plan.scatter_block_e, plan.scatter_block_n, plan.halo_sort_mc
                ),
            )
            return taken * plan.edge_mask[:, None].astype(full.dtype)
        sorted_ids = False
    else:
        # owner-side ids are plan-sorted; route the VJP (a scatter-sum
        # transpose, _torch_func_impl.py:112-191) through the sorted path
        sorted_ids = plan.ids_sorted(side)
    hints = (
        (plan.scatter_block_e, plan.scatter_block_n, plan.scatter_mc)
        if (sorted_ids and _cfg.pallas_scatter_enabled())
        else None
    )
    taken = local_ops.take_rows(
        full, idx, indices_are_sorted=sorted_ids, pallas_hints=hints,
        gather_mv=plan.gather_mv,
    )
    return taken * plan.edge_mask[:, None].astype(full.dtype)


@_scoped("dgraph.gather")
def gather(
    x: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str]
) -> jax.Array:
    """Per-edge features gathered from one endpoint side.

    Parity: ``Communicator.gather`` / ``CommPlan_GatherFunction``
    (``_torch_func_impl.py:27-110``): local vertex→edge copy + boundary
    all_to_all + received-row placement. Here the non-halo side is a pure
    local take; the halo side prepends one halo exchange
    (= :func:`halo_extend` then :func:`local_take`).

    Args:
      x: [n_pad, F] per-shard vertex features for that side's vertex set.
    Returns: [e_pad, F] per-edge features (masked edges are zero).
    """
    return local_take(halo_extend(x, plan, side, axis_name), plan, side)


@_scoped("dgraph.scatter_sum")
def scatter_sum(
    edata: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str]
) -> jax.Array:
    """Sum per-edge values into that side's vertices (cross-rank aware).

    Parity: ``Communicator.scatter`` / ``CommPlan_ScatterFunction``
    (``_torch_func_impl.py:194-280``). TPU has no remote atomics (the NVSHMEM
    backend's CAS scatter-add, ``nvshmem_comm_kernels.cuh:17-54``), so the
    remote leg is: local segment-sum into halo slots (pre-aggregation per
    unique remote vertex — the reference's dedup does the same,
    ``_NCCLCommPlan.py:221-226``) → reverse all_to_all → local segment-sum.

    Args:
      edata: [e_pad, F] per-edge values.
    Returns: [n_pad, F] per-vertex sums for the requested side.
    """
    # mask in the activation dtype — a f32 mask would silently upcast bf16
    # edge tensors (and disable the bf16 kernel fast path below)
    edata = edata * plan.edge_mask[:, None].astype(edata.dtype)
    idx = _side_index(plan, side)
    n_pad = _side_npad(plan, side)
    if side != plan.halo_side:
        # owner-side aggregation: plan-sorted monotone segment ids ride the
        # shared Pallas-or-jnp dispatch (kill switch + precision policy in
        # ONE place: ops.local.sorted_segment_sum_any)
        if plan.ids_sorted(side):
            return local_ops.sorted_segment_sum_any(
                edata, idx, n_pad, plan.scatter_block_e, plan.scatter_block_n,
                plan.scatter_mc, gather_mv=plan.gather_mv,
            )
        return local_ops.segment_sum(edata, idx, n_pad, indices_are_sorted=False)
    # halo-side scatter: resolve the lowering ONCE for both legs (the slot
    # reduction's shape and the reverse collective must agree)
    impl = resolve_plan_impl(plan, axis_name) if axis_name is not None else None
    if impl == "overlap":
        return _scatter_sum_overlap(edata, plan, side, axis_name)
    if impl == "pallas_p2p":
        return _scatter_sum_p2p(edata, plan, side, axis_name)
    W = plan.world_size
    n_full = n_pad + W * plan.halo.s_pad
    if plan.halo_sort_perm is not None:
        # unsorted halo-side ids, but the plan's sorting permutation turns
        # the forward into gather-by-perm + sorted segment-sum (Pallas MXU)
        full = local_ops.segment_sum_sort_route(
            edata, idx, plan.halo_sort_perm, plan.halo_sorted_ids, n_full,
            pallas_hints=(
                plan.scatter_block_e, plan.scatter_block_n, plan.halo_sort_mc
            ),
        )
    else:
        full = local_ops.segment_sum(edata, idx, n_full)
    local_part = full[:n_pad]
    remote_part = full[n_pad:]
    return local_part + halo_scatter_sum(
        remote_part, plan.halo, n_pad, axis_name, deltas=plan.halo_deltas,
        impl=impl, schedule=getattr(plan, "halo_schedule", None),
        wire_format=resolve_plan_wire_format(plan, axis_name),
    )


# ---------------------------------------------------------------------------
# Interior/boundary split ops (the compute–communication-overlap hot path)
# ---------------------------------------------------------------------------


def _overlap_spec(plan: EdgePlan):
    ov = getattr(plan, "overlap", None)
    if ov is None:
        raise ValueError(
            "plan carries no interior/boundary split; build it with "
            "build_edge_plan(overlap=True) (or adopt a tuning record whose "
            "halo_impl is 'overlap' before building)"
        )
    return ov


def _interior_chunks(n_deltas: int) -> int:
    """How many edge-axis chunks the interior aggregation splits into so
    individual pieces interleave with the boundary rounds. Default 1 (one
    sorted segment-sum — XLA can already overlap a single independent op
    with the in-flight rounds, and chunk partial-sums regroup float adds,
    breaking bit-parity with the serial path); raise
    ``config.overlap_interior_chunks`` / DGRAPH_TPU_OVERLAP_CHUNKS for
    finer-grained hiding once on-chip traces justify it."""
    from dgraph_tpu import config as _cfg

    c = getattr(_cfg, "overlap_interior_chunks", 1)
    return max(1, min(int(c) if c else 1, max(n_deltas, 1)))


@_scoped("dgraph.interior_take")
def interior_take(x: jax.Array, plan: EdgePlan, side: str) -> jax.Array:
    """Per-edge rows of the INTERIOR subset, taken from the local vertex
    table only — by construction no interior edge references a halo slot,
    so this op is collective-free and independent of the in-flight
    boundary exchange. Padded subset slots produce zero rows."""
    ov = _overlap_spec(plan)
    idx = ov.side("interior", side)
    sorted_ids = side != plan.halo_side and plan.ids_sorted(side)
    return local_ops.take_rows(x, idx, indices_are_sorted=sorted_ids)


@_scoped("dgraph.boundary_take")
def boundary_take(x_or_halo: jax.Array, plan: EdgePlan, side: str) -> jax.Array:
    """Per-edge rows of the BOUNDARY subset. On the halo side, ``x_or_halo``
    is the [W*S, F] halo buffer returned by
    :func:`halo_exchange_overlap` (boundary halo-side indices are rebased
    into it — no ``[local ; halo]`` concat is ever materialized); on the
    owner side it is the local vertex table."""
    ov = _overlap_spec(plan)
    idx = ov.side("boundary", side)
    sorted_ids = side != plan.halo_side and plan.ids_sorted(side)
    return local_ops.take_rows(x_or_halo, idx, indices_are_sorted=sorted_ids)


def _subset_owner_sum(edata, plan, ov, side, which, chunks=1):
    """Owner-side segment-sum of one subset's per-edge rows (monotone ids
    — subsets preserve the plan's owner-sorted order), optionally split
    into edge-axis chunks whose partial sums interleave with the boundary
    rounds in the schedule."""
    ids = ov.side(which, side)
    n_pad = _side_npad(plan, side)
    mc = ov.interior_mc if which == "interior" else ov.boundary_mc
    if not plan.ids_sorted(side):
        return local_ops.segment_sum(edata, ids, n_pad, indices_are_sorted=False)
    E = edata.shape[0]
    if chunks <= 1 or E < 2 * chunks:
        return local_ops.sorted_segment_sum_any(
            edata, ids, n_pad, plan.scatter_block_e, plan.scatter_block_n, mc
        )
    step = -(-E // chunks)
    out = None
    for j in range(0, E, step):
        part = local_ops.sorted_segment_sum_any(
            edata[j : j + step], ids[j : j + step], n_pad,
            plan.scatter_block_e, plan.scatter_block_n, mc,
        )
        out = part if out is None else out + part
    return out


@_scoped("dgraph.interior_scatter_sum")
def interior_scatter_sum(
    edata_int: jax.Array, plan: EdgePlan, side: str, chunks: Optional[int] = None
) -> jax.Array:
    """Sum INTERIOR per-edge rows into ``side``'s vertices. On the owner
    side this is the sorted fast path, chunked so the pieces interleave
    with the in-flight boundary rounds; on the halo side ids are local
    rows (interior edges never touch halo slots)."""
    ov = _overlap_spec(plan)
    if side == plan.halo_side:
        return local_ops.segment_sum(
            edata_int, ov.side("interior", side), _side_npad(plan, side),
            indices_are_sorted=False,
        )
    if chunks is None:
        chunks = _interior_chunks(len(plan.halo_deltas))
    return _subset_owner_sum(edata_int, plan, ov, side, "interior", chunks)


@_scoped("dgraph.boundary_scatter_sum")
def boundary_scatter_sum(
    edata_bnd: jax.Array, plan: EdgePlan, side: str
) -> jax.Array:
    """Sum BOUNDARY per-edge rows into ``side``'s OWNER vertices (the
    merge step after the exchange lands). Halo-side boundary ids are halo
    slots, not local vertices — scatter those through
    :func:`scatter_sum_overlap`, which runs the reverse rounds."""
    ov = _overlap_spec(plan)
    if side == plan.halo_side:
        raise ValueError(
            "boundary_scatter_sum targets the owner side; halo-side "
            "boundary scatters need the reverse exchange — use "
            "scatter_sum_overlap (or scatter_sum, which dispatches there)"
        )
    return _subset_owner_sum(edata_bnd, plan, ov, side, "boundary", chunks=1)


def overlap_edge_weight(
    edge_weight: Optional[jax.Array], plan: EdgePlan
) -> tuple:
    """Split a [e_pad] per-edge weight vector into its (interior,
    boundary) subsets (padded slots -> 0). Returns (None, None) when
    there is no weight."""
    if edge_weight is None:
        return None, None
    ov = _overlap_spec(plan)
    w_int = jnp.take(edge_weight, ov.int_epos, mode="fill", fill_value=0)
    w_bnd = jnp.take(edge_weight, ov.bnd_epos, mode="fill", fill_value=0)
    return w_int, w_bnd


@_scoped("dgraph.gather_scatter_overlap")
def gather_scatter_overlap(
    x_local: jax.Array,
    halo_buf: jax.Array,
    plan: EdgePlan,
    edge_weight: Optional[jax.Array] = None,
) -> jax.Array:
    """Fused neighbor aggregation ``out[v] = Σ_e w_e · x[halo-side endpoint
    of e]`` into the OWNER side, overlap-scheduled: interior edges read the
    local table ``x_local`` (independent of the exchange), boundary edges
    read the in-flight ``halo_buf`` from :func:`halo_exchange_overlap`, and
    the two partials merge at the end — the SAGE/GCN identity-message hot
    path with the collective hidden behind the interior work."""
    ov = _overlap_spec(plan)
    owner = "dst" if plan.halo_side == "src" else "src"
    w_int, w_bnd = overlap_edge_weight(edge_weight, plan)
    m_int = interior_take(x_local, plan, plan.halo_side)
    if w_int is not None:
        m_int = m_int * w_int[:, None].astype(m_int.dtype)
    agg_int = interior_scatter_sum(m_int, plan, owner)
    m_bnd = boundary_take(halo_buf, plan, plan.halo_side)
    if w_bnd is not None:
        m_bnd = m_bnd * w_bnd[:, None].astype(m_bnd.dtype)
    return agg_int + boundary_scatter_sum(m_bnd, plan, owner)


def _scatter_sum_split(edata, plan, side, axis_name, remote_fn):
    """The ONE split halo-side scatter schedule both split lowerings
    share (the PR 8 single-core discipline — two copies of this schedule
    could silently desynchronize the lowerings' values): the boundary
    subset is pre-reduced into halo slots and handed to ``remote_fn``'s
    reverse transport FIRST; the interior subset (local-vertex targets)
    aggregates while it flies; local and returned remote partials merge
    last. ``remote_fn`` — :func:`halo_scatter_sum_overlap` (reverse
    ppermute rounds) or :func:`halo_scatter_sum_p2p` (reverse one-sided
    puts) — is the ONLY difference between the lowerings, mirroring how
    :func:`halo_exchange_split` dispatches the exchange leg. The VJP
    composes the building blocks' pinned transposes — takes transpose to
    segment-sums and the reverse transport to the forward transport —
    mirroring the gather/scatter adjoint pair. ``edata`` must already be
    edge-masked (the public :func:`scatter_sum` wrapper does this)."""
    ov = _overlap_spec(plan)
    n_pad = _side_npad(plan, side)
    W, S = plan.world_size, plan.halo.s_pad
    # boundary leg first: rows -> slot partials -> reverse transport
    bnd_rows = local_ops.take_rows(edata, ov.bnd_epos)
    slot_sums = local_ops.segment_sum(
        bnd_rows, ov.side("boundary", side), W * S, indices_are_sorted=False
    )
    remote = remote_fn(
        slot_sums, plan.halo, n_pad, axis_name, tuple(plan.halo_deltas),
        resolve_plan_wire_format(plan, axis_name),
    )
    # interior leg while the transport is in flight
    int_rows = local_ops.take_rows(edata, ov.int_epos)
    interior = local_ops.segment_sum(
        int_rows, ov.side("interior", side), n_pad, indices_are_sorted=False
    )
    return interior + remote


@_scoped("dgraph.scatter_sum_overlap")
def _scatter_sum_overlap(
    edata: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str]
) -> jax.Array:
    """Halo-side :func:`scatter_sum` under the overlap schedule — the
    shared split schedule (:func:`_scatter_sum_split`) with the reverse
    ppermute rounds as the remote leg."""
    return _scatter_sum_split(
        edata, plan, side, axis_name, halo_scatter_sum_overlap
    )


def scatter_sum_overlap(
    edata: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str]
) -> jax.Array:
    """Public spelling of the overlap halo-side scatter (masks ``edata``
    like :func:`scatter_sum` does, then runs the overlap schedule)."""
    edata = edata * plan.edge_mask[:, None].astype(edata.dtype)
    if side != plan.halo_side:
        raise ValueError(
            "scatter_sum_overlap is the HALO-side scatter; owner-side "
            "aggregation has no collective to overlap — use scatter_sum "
            "(or interior/boundary_scatter_sum for split streams)"
        )
    return _scatter_sum_overlap(edata, plan, side, axis_name)


@_scoped("dgraph.scatter_sum_p2p")
def _scatter_sum_p2p(
    edata: jax.Array, plan: EdgePlan, side: str, axis_name: Optional[str]
) -> jax.Array:
    """Halo-side :func:`scatter_sum` under the pallas_p2p lowering — the
    shared split schedule (:func:`_scatter_sum_split`) with reverse
    one-sided puts as the remote leg: the per-delta slot-partial tiles
    DMA back to their owners while the interior subset aggregates.
    Reduction operands and order are identical to the serial path, so
    values stay bit-identical."""
    return _scatter_sum_split(
        edata, plan, side, axis_name, halo_scatter_sum_p2p
    )


@_scoped("dgraph.scatter_bias_relu_overlap")
def scatter_bias_relu_overlap(
    stream_local: jax.Array,  # [n_halo_pad, F] halo-side stream (local table)
    halo_buf: jax.Array,  # [W*S, F] in-flight exchange output
    bias: jax.Array,  # [n_owner_pad, F] owner-side vertex operand
    plan: EdgePlan,
    side: str,  # owner side to aggregate into
    axis_name: Optional[str],
    edge_weight: Optional[jax.Array] = None,  # [e_pad]
) -> jax.Array:
    """Overlap-scheduled :func:`scatter_bias_relu`: the fused
    Σ w·relu(stream + bias) aggregation runs once over the interior subset
    (reading only local rows — free to execute while the boundary rounds
    fly) and once over the boundary subset (reading the landed halo
    buffer), merging at the end. Exact same math as the unsplit op: relu
    is per-edge and the aggregation is a sum over a partitioned edge set."""
    ov = _overlap_spec(plan)
    n_pad = _side_npad(plan, side)
    bias = bias.astype(stream_local.dtype)
    w_int, w_bnd = overlap_edge_weight(edge_weight, plan)
    int_rows = interior_take(stream_local, plan, plan.halo_side)
    a = local_ops.sorted_segment_sum_bias_relu_any(
        int_rows, ov.side("interior", side), bias, n_pad,
        plan.scatter_block_e, plan.scatter_block_n, ov.interior_mc,
        edge_weight=w_int,
    )
    bnd_rows = boundary_take(halo_buf, plan, plan.halo_side)
    b = local_ops.sorted_segment_sum_bias_relu_any(
        bnd_rows, ov.side("boundary", side), bias, n_pad,
        plan.scatter_block_e, plan.scatter_block_n, ov.boundary_mc,
        edge_weight=w_bnd,
    )
    return a + b


@_scoped("dgraph.scatter_bias_relu")
def scatter_bias_relu(
    edata: jax.Array,  # [e_pad, F] per-edge stream (e.g. gathered src proj)
    bias: jax.Array,  # [n_pad, F] owner-side vertex operand
    plan: EdgePlan,
    side: str,
    axis_name: Optional[str],
    edge_weight: Optional[jax.Array] = None,  # [e_pad]
) -> jax.Array:
    """Fused owner-side aggregation: out[v] = Σ_e w_e · relu(edata_e + bias_v).

    Parity: the reference's fused scatter kernels
    (``Fused_ReLU_Scatter_Kernel`` / ``Fused_Sum_Norm_Scatter_Kernel``,
    ``local_data_kernels.cuh:34-116``). On TPU the fusion must live INSIDE
    the Pallas kernel (``pallas_call`` is an XLA fusion barrier, so the
    composed path materializes the [E, F] message tensor in HBM); off-TPU
    (or non-owner side) it falls back to the exact composed ops.
    """
    idx = _side_index(plan, side)
    n_pad = _side_npad(plan, side)
    # one compute dtype on both paths: the kernel runs bias at edata's
    # precision, so the fallback must too (cross-backend equivalence)
    bias = bias.astype(edata.dtype)
    if plan.ids_sorted(side):
        # owner side: shared Pallas-or-jnp dispatch (kill switch + precision
        # policy in ONE place — ops.local)
        return local_ops.sorted_segment_sum_bias_relu_any(
            edata, idx, bias, n_pad,
            plan.scatter_block_e, plan.scatter_block_n, plan.scatter_mc,
            edge_weight=edge_weight, gather_mv=plan.gather_mv,
        )
    m = jax.nn.relu(edata + gather(bias, plan, side, axis_name))
    if edge_weight is not None:
        m = m * edge_weight[:, None].astype(m.dtype)
    return scatter_sum(m, plan, side, axis_name)


@_scoped("dgraph.gather_concat")
def gather_concat(
    x_src: jax.Array,
    x_dst: jax.Array,
    plan: EdgePlan,
    axis_name: Optional[str],
) -> jax.Array:
    """[e_pad, F_src+F_dst] concat of src- and dst-side per-edge features.

    The reference's GCN/GAT layers start with exactly this double gather
    (``experiments/OGB/GCN.py:28-67``, ``RGAT.py:174-206``).
    """
    hs = gather(x_src, plan, "src", axis_name)
    hd = gather(x_dst, plan, "dst", axis_name)
    return jnp.concatenate([hs, hd], axis=-1)


@_scoped("dgraph.psum_mean")
def psum_mean(x, axis_name: Optional[str]):
    """Mean over a mesh axis (None = identity). For DP gradient sync —
    replaces the reference's DDP all-reduce (``experiments/OGB/main.py:111``)."""
    if axis_name is None:
        return x
    return lax.pmean(x, axis_name)
