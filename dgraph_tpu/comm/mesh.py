"""Mesh construction and sharding helpers.

The reference composes partition groups × replicas by integer arithmetic on
ranks (``ranks_per_graph``; ``NCCLBackendEngine.py:56-64``,
``GraphCast/dist_utils.py:50-113``). On TPU this is a 2-D
``jax.sharding.Mesh`` with axes ``('replica', 'graph')``: graph-partition
collectives ride the inner (ICI-contiguous) ``graph`` axis; data-parallel
gradient sync rides ``replica`` (ICI or DCN for multi-slice — XLA routes
hybrid meshes automatically).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P


GRAPH_AXIS = "graph"
REPLICA_AXIS = "replica"


def make_graph_mesh(
    ranks_per_graph: Optional[int] = None,
    num_replicas: int = 1,
    devices=None,
) -> Mesh:
    """Build a ``('replica', 'graph')`` mesh.

    ``ranks_per_graph`` defaults to (num_devices / num_replicas) — the
    reference's ``ranks_per_graph`` knob (``NCCLBackendEngine.py:56-64``).
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if ranks_per_graph is None:
        ranks_per_graph = n // num_replicas
    if ranks_per_graph * num_replicas != n:
        raise ValueError(
            f"ranks_per_graph ({ranks_per_graph}) x num_replicas ({num_replicas})"
            f" != device count ({n})"
        )
    return jax.make_mesh(
        (num_replicas, ranks_per_graph), (REPLICA_AXIS, GRAPH_AXIS), devices=devices
    )


def plan_in_specs(plan) -> object:
    """A pytree of ``P('graph')`` matching ``plan``'s structure, for shard_map
    in_specs: every plan leaf has a leading [world_size] axis."""
    return jax.tree.map(lambda _: P(GRAPH_AXIS), plan)


def squeeze_plan(plan):
    """Drop the leading per-shard axis of size 1 that shard_map leaves on
    every plan leaf (use inside the shard_map body)."""
    return jax.tree.map(lambda leaf: leaf[0], plan)


def replicated_specs(tree) -> object:
    """P() (fully replicated) specs for a pytree (e.g. model params)."""
    return jax.tree.map(lambda _: P(), tree)
