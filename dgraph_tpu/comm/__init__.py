from dgraph_tpu.comm.communicator import Communicator, TpuComm, SingleComm
from dgraph_tpu.comm.mesh import make_graph_mesh, plan_in_specs, squeeze_plan
from dgraph_tpu.comm import collectives

__all__ = [
    "Communicator",
    "TpuComm",
    "SingleComm",
    "make_graph_mesh",
    "plan_in_specs",
    "squeeze_plan",
    "collectives",
]
