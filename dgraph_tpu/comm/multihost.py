"""Multi-host (multi-controller) launch + DCN/ICI mesh construction.

Replaces the reference's launcher matrix — torchrun for NCCL, mpirun/srun
for MPI/NVSHMEM with THREAD_MULTIPLE requirements and per-backend rank
bookkeeping (``MPIBackendEngine.py:268-341``, SURVEY §3.1) — with the JAX
multi-controller model: every host runs the same program,
``jax.distributed.initialize`` wires the cluster, and a single global mesh
spans all devices.

Axis placement for pods/multi-slice: the ``graph`` axis (per-layer halo
all_to_all — latency-critical) goes on the INNER, ICI-contiguous dimension;
``replica`` (one grad all-reduce per step — bandwidth-tolerant) on the
OUTER dimension, which XLA routes over DCN for multi-slice topologies.
``jax.experimental.mesh_utils.create_hybrid_device_mesh`` handles the
slice-aware ordering.
"""

from __future__ import annotations

from typing import Optional

import jax

from dgraph_tpu.comm.mesh import GRAPH_AXIS, REPLICA_AXIS


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` passthrough (auto-detects on TPU pods;
    explicit args for manual launches). Idempotent."""
    try:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    except RuntimeError as e:  # already initialized
        if "already" not in str(e).lower():
            raise


def make_pod_mesh(ranks_per_graph: Optional[int] = None, num_replicas: int = 1):
    """Global mesh over ALL processes' devices, DCN-aware when multi-slice.

    Single-slice (or CPU): plain ``make_graph_mesh``. Multi-slice: replicas
    map to slices (DCN) and graph shards stay within a slice (ICI).
    """
    devices = jax.devices()
    n = len(devices)
    if ranks_per_graph is None:
        ranks_per_graph = n // num_replicas
    if ranks_per_graph * num_replicas != n:
        raise ValueError(
            f"ranks_per_graph ({ranks_per_graph}) x num_replicas ({num_replicas}) != {n}"
        )
    num_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if num_slices > 1 and num_replicas % num_slices == 0:
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh

        dm = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(num_replicas // num_slices, ranks_per_graph),
            dcn_mesh_shape=(num_slices, 1),
            devices=devices,
        )
        return Mesh(dm, (REPLICA_AXIS, GRAPH_AXIS))
    from dgraph_tpu.comm.mesh import make_graph_mesh

    return make_graph_mesh(ranks_per_graph, num_replicas, devices)


def process_local_shards(world_size: int) -> list:
    """Which graph shards this process should materialize host-side — for
    per-host data loading of very large graphs (each controller feeds only
    its addressable devices, the reference's per-rank dataset slicing,
    ``data/ogbn_datasets.py:135-148``)."""
    local = jax.local_devices()
    all_dev = jax.devices()
    index_of = {d.id: i for i, d in enumerate(all_dev)}
    n = len(all_dev)
    return sorted({index_of[d.id] * world_size // n for d in local})


def process_local_plan_shards(
    plan_dir: str,
    *,
    ranks: Optional[list] = None,
    verify: bool = True,
) -> tuple:
    """Each-host-loads-its-shard: ``(plan, ranks)`` holding ONLY this
    process's ranks' plan shards from a v8 sharded artifact
    (:mod:`dgraph_tpu.plan_shards`, built by
    ``plan.build_plan_shards`` / cached by
    ``train.checkpoint.cached_edge_plan``).

    This is what makes multi-controller papers100M-scale runs real
    rather than dryrun-only (ROADMAP item 3): the monolithic ~40+ GB
    EdgePlan never exists on any host — each controller reads, verifies
    (per-shard SHA-256), and stacks just the ``len(ranks)`` shards its
    addressable devices consume.  The returned plan's leading axis is
    ``len(ranks)`` while its statics (``world_size``, pads,
    ``halo_deltas``) still describe the full W-rank world, so
    ``shard_map`` programs see identical static shapes on every host.
    The O(E) layout sidecar is skipped entirely.

    Raises :class:`~dgraph_tpu.plan_shards.PlanManifestError` /
    :class:`~dgraph_tpu.plan_shards.PlanShardError` on integrity failure
    — multi-host loaders must NOT silently rebuild (hosts would race);
    rebuild on the lead host (``cached_edge_plan``) and re-land the
    artifact instead.
    """
    from dgraph_tpu import plan_shards as ps
    from dgraph_tpu.plan import load_sharded_plan

    manifest = ps.read_manifest(plan_dir)
    if ranks is None:
        ranks = process_local_shards(int(manifest["world_size"]))
    plan, _ = load_sharded_plan(
        plan_dir, ranks=ranks, verify=verify, load_layout=False
    )
    return plan, list(ranks)
