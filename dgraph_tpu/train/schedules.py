"""LR schedules.

Reference parity: GraphCast's 3-phase schedule — linear warmup, cosine decay
to a floor, then constant (``experiments/GraphCast/train_graphcast.py:82-103``).
"""

from __future__ import annotations

import optax


def graphcast_three_phase(
    peak_lr: float = 1e-3,
    warmup_steps: int = 1000,
    decay_steps: int = 100_000,
    floor_lr: float = 3e-7,
) -> optax.Schedule:
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, peak_lr, warmup_steps),
            optax.cosine_decay_schedule(peak_lr, decay_steps, alpha=floor_lr / peak_lr),
            optax.constant_schedule(floor_lr),
        ],
        boundaries=[warmup_steps, warmup_steps + decay_steps],
    )
