"""Checkpoint / resume + plan caching.

The reference checkpoints only model state_dicts with no optimizer/step state
and no resume path (``train_graphcast.py:150-151``, SURVEY §5); its important
persisted artifacts are preprocessing caches (partitioned graphs, per-rank
comm plans — ``distributed_graph_dataset.py:399-422``,
``ogbn_datasets.py:96-123``). This module provides both, better:

- full train-state checkpointing (params + opt_state + step) via orbax,
  with resume;
- a plan cache keyed by (graph content hash, world_size, edge_owner,
  pad_multiple) — the reference keys synthetic caches by config hash the
  same way (``synthetic_dataset.py:180-196``).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
from typing import Any, Optional

import numpy as np

_logger = logging.getLogger("dgraph_tpu.checkpoint")


def atomic_pickle_dump(path: str, obj: Any) -> None:
    """Pickle to a temp file, flush + fsync, then os.replace into place:
    concurrent readers (multi-process launches polling a cache path) never
    see a truncated artifact, and a HOST crash cannot leave a
    durable-looking but empty/truncated file behind the rename — without
    the fsync, os.replace can commit the name before the kernel commits
    the data, and the post-crash filesystem shows a valid path holding
    zero bytes."""
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# --- train state checkpointing (orbax) ---


def save_checkpoint(ckpt_dir: str, state: dict, step: int) -> None:
    """Save a pytree (e.g. {'params':…, 'opt_state':…, 'step':…}).

    Consults the ``ckpt.save`` chaos point (:mod:`dgraph_tpu.chaos`) at
    entry — a ``raise`` clause simulates the save-side IO fault whose
    recovery path is the restore-side fall-back-to-older-step."""
    from dgraph_tpu import chaos

    chaos.fire("ckpt.save")
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(ckpt_dir, f"step_{step:08d}"))
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)


def all_steps(ckpt_dir: str) -> list:
    """Ascending list of checkpoint step numbers present in ``ckpt_dir``.
    Quarantined entries (``step_XXXXXXXX.corrupt``, see
    :func:`restore_checkpoint`) are skipped — a step known bad is not a
    resume candidate."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(
        int(d.split("_")[1])
        for d in os.listdir(ckpt_dir)
        if d.startswith("step_") and d.split("_")[1].isdigit()
    )


def quarantined_steps(ckpt_dir: str) -> list:
    """Ascending step numbers of quarantined (``.corrupt``-renamed)
    checkpoint dirs — the operator's "what did the loader give up on"
    probe. Rename a dir back to ``step_XXXXXXXX`` to retry it."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for d in os.listdir(ckpt_dir):
        if d.startswith("step_") and d.endswith(".corrupt"):
            num = d[len("step_"):-len(".corrupt")]
            if num.isdigit():
                out.append(int(num))
    return sorted(out)


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = all_steps(ckpt_dir)
    return steps[-1] if steps else None


def restore_checkpoint(
    ckpt_dir: str, template: Optional[dict] = None, step: Optional[int] = None
) -> Optional[dict]:
    """Restore the given (or latest) step into template's structure; None if
    no checkpoint exists. ``template=None`` restores the raw saved tree.

    With ``step=None`` (the serving / resume path), a corrupt/truncated
    checkpoint (killed mid-save, torn copy) does not abort the restore:
    the loader logs it, falls back to the next-older step, and — once an
    older step restores successfully, proving the reader/template works —
    **quarantines** the failed dirs (renamed to ``step_XXXXXXXX.corrupt``,
    so the bad pickle is never silently re-read, and re-logged, on every
    subsequent load; ``all_steps`` skips quarantined entries and
    :func:`quarantined_steps` lists them). When every on-disk step fails
    the last error propagates (returning None there would silently
    restart from scratch) and NOTHING is quarantined — an all-steps
    failure is likely systematic (template mismatch, broken orbax env),
    and renaming every good checkpoint away would destroy the evidence.
    An explicitly requested ``step`` is strict: missing raises
    FileNotFoundError, unreadable raises the underlying error without
    quarantining — silently serving an older checkpoint than the one
    NAMED would mislabel every downstream metric.

    The ``ckpt.read`` chaos point fires at entry (a deterministic stand-in
    for the torn-copy/unreadable-volume faults the fallback loop exists
    for).
    """
    from dgraph_tpu import chaos

    chaos.fire("ckpt.read")
    import orbax.checkpoint as ocp

    steps = all_steps(ckpt_dir)
    if step is not None:
        if step not in steps:
            raise FileNotFoundError(
                f"checkpoint step {step} not found under {ckpt_dir!r} "
                f"(present: {steps})"
            )
        steps = [step]
    if not steps:
        return None
    last_err = None
    failed = []  # (step, path, error) pending quarantine
    for s in reversed(steps):
        path = os.path.abspath(os.path.join(ckpt_dir, f"step_{s:08d}"))
        try:
            with ocp.PyTreeCheckpointer() as ckptr:
                got = ckptr.restore(path, item=template)
        except Exception as e:  # noqa: BLE001 — any read/parse failure
            if step is not None:
                raise
            last_err = e
            failed.append((s, path, e))
            _logger.warning(
                "checkpoint step_%08d unreadable (%s: %s); falling back to "
                "next-older step", s, type(e).__name__, e,
            )
            continue
        # quarantine ONLY once an older step restored (that success proves
        # the reader works — an all-steps failure is systematic and would
        # otherwise rename every GOOD step away), and only when the
        # failed step is unreadable even RAW (template=None): a raw
        # restore that succeeds means the failure was a template/schema
        # mismatch — e.g. a code rollback across a state-schema change —
        # and the newest training progress must stay a resume candidate.
        # The rename is what makes "log once" true: the entry leaves
        # all_steps(), so no later load re-reads (or re-warns about) a
        # step already known bad. Reversible by renaming back;
        # best-effort (a read-only volume keeps fall-back-every-time).
        for fs, fpath, fe in failed:
            if template is not None:
                try:
                    with ocp.PyTreeCheckpointer() as ckptr:
                        ckptr.restore(fpath)
                    _logger.warning(
                        "checkpoint step_%08d restores raw but not into "
                        "the given template (%s: %s); NOT quarantining — "
                        "likely a state-schema mismatch, not corruption",
                        fs, type(fe).__name__, fe,
                    )
                    continue
                except Exception:  # noqa: BLE001 — genuinely unreadable
                    pass
            qpath = fpath + ".corrupt"
            try:
                os.replace(fpath, qpath)
                _logger.warning(
                    "checkpoint step_%08d quarantined to %s (%s: %s)",
                    fs, os.path.basename(qpath), type(fe).__name__, fe,
                )
            except OSError as qe:
                _logger.warning(
                    "checkpoint step_%08d quarantine failed: %s", fs, qe,
                )
        return got
    # every step failed: likely systematic (bad template, broken orbax
    # env) — quarantining here would destroy evidence wholesale
    raise last_err


def checkpoint_keys(ckpt_dir: str, step: Optional[int] = None):
    """Top-level pytree keys of the given (or latest) checkpoint, or None
    if no checkpoint exists. Lets callers pick a restore TEMPLATE from
    what the checkpoint actually contains (e.g. an 'ema' track) instead
    of try/except-ing template mismatches — which would also swallow
    genuine corruption/IO errors (ADVICE r3 #5)."""
    import orbax.checkpoint as ocp

    step = step if step is not None else latest_step(ckpt_dir)
    if step is None:
        return None
    path = os.path.abspath(os.path.join(ckpt_dir, f"step_{step:08d}"))
    with ocp.PyTreeCheckpointer() as ckptr:
        md = ckptr.metadata(path)
    tree = getattr(getattr(md, "item_metadata", md), "tree", None)
    if isinstance(tree, dict):
        return set(tree.keys())
    return None


# --- plan cache ---


# Bump whenever EdgePlan's fields/defaults change shape or meaning: stale
# cache pickles must REBUILD, not silently inherit new class defaults for
# fields they were never built with (e.g. scatter_block_e).
PLAN_FORMAT_VERSION = 10  # v10: wire_format static (dgraph_tpu.wire) —
# the adopted halo-payload codec rides EdgePlan statics + the sharded
# manifest, so cached plans predating the codec layer must rebuild and
# stamp their build-time resolution;
# v9: halo_pair_rows traffic matrix + compiled
# halo_schedule statics (dgraph_tpu.sched) — cached plans predating the
# schedule compiler must rebuild so the matrix lands in the manifest;
# v8: sharded plan artifacts — per-rank
# shard_XXXX.pkl files under plan_<key>/ with a checksummed manifest.json
# (dgraph_tpu.plan_shards), streamed by plan.build_edge_plan_sharded,
# loaded/repaired shard-by-shard here; the monolithic plan_<key>.pkl is
# gone (a ~40+ GB all-or-nothing artifact at papers100M scale);
# v7: overlap (interior/boundary OverlapSpec for
# the compute–communication-overlap halo lowering);
# v6: e_pad aligned to lcm(pad_multiple,
# SCATTER_BLOCK_E) so pallas operands need no per-call re-pad copy;
# v5: gather_mv (sorted-row-gather vblock hint);
# v4: halo-side sorted route (halo_sort_perm / halo_sorted_ids /
# halo_sort_mc); v3: scatter_block_e default 512 -> 1024


def _hash_array(h, arr: np.ndarray) -> None:
    # memoryview feeds hashlib without a copy; .tobytes() would materialize
    # the whole array again (26 GB for a papers100M edge list)
    arr = np.ascontiguousarray(arr)
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(memoryview(arr).cast("B"))


def _graph_fingerprint(edge_index: np.ndarray, partition: np.ndarray, **kw) -> str:
    h = hashlib.sha256()
    h.update(f"plan-format-v{PLAN_FORMAT_VERSION};".encode())
    _hash_array(h, edge_index)
    _hash_array(h, partition)
    h.update(repr(sorted(kw.items())).encode())
    return h.hexdigest()[:24]


def cached_edge_plan(
    cache_dir: str,
    edge_index: np.ndarray,
    src_partition: np.ndarray,
    dst_partition: Optional[np.ndarray] = None,
    *,
    ranks: Optional[list] = None,
    load_layout: Optional[bool] = None,
    memory_budget_bytes: Optional[int] = None,
    verify: bool = True,
    key_extra: Optional[dict] = None,
    **build_kwargs: Any,
):
    """build_edge_plan with an on-disk **sharded** cache (format v8).

    ``key_extra`` folds extra scalar knobs into the cache key WITHOUT
    forwarding them to the plan builder — upstream decisions (the
    partition method and its ``sample_frac``/``edge_balance`` blend)
    that shaped the inputs but are not build kwargs.  The partition
    content is hashed regardless; keying the knobs too keeps two blends
    that collide on content from sharing one artifact name and makes
    the cache directory self-describing.

    The cached artifact is a directory ``plan_<key>/`` of per-rank shard
    pickles plus a checksummed manifest (:mod:`dgraph_tpu.plan_shards`),
    streamed by :func:`~dgraph_tpu.plan.build_edge_plan_sharded`.  Loads
    verify every shard's checksum; a corrupt / truncated / missing shard
    (or a shard deleted out from under a valid manifest) rebuilds **just
    the bad shards** — logged with which shard triggered it, mirroring
    :func:`restore_checkpoint`'s fall-back-past-corrupt-steps contract —
    and only an unreadable manifest degrades to a full rebuild.  A
    build killed mid-stream resumes from the manifest on the next call.

    ``ranks`` loads only those shards (each-host-loads-its-shard; the
    returned plan's leading axis is ``len(ranks)``, statics still
    describe the full world) and defaults ``load_layout`` to False — the
    layout sidecar is O(E), and a host loading two shards must not read
    (or SHA-verify) an artifact as big as the edge list.
    ``memory_budget_bytes`` bounds the streaming build's per-shard RSS
    (:class:`~dgraph_tpu.plan_shards.PlanBuildMemoryExceeded`).

    ``verify=False`` skips SHA-256 verification on warm hits — at
    papers100M scale hashing the full artifact adds real wall time to
    every load.  Torn/truncated shards still surface as unpickle
    failures and take the same single-shard repair path; only silent
    bit-flips in an intact-length pickle go undetected.

    A falsy ``cache_dir`` ("" / None) builds without caching — the CLIs'
    ``--plan_cache ""`` convention resolves here, not at every call site.

    Parity: `_save_comm_plans`/`_load_comm_plans`
    (``distributed_graph_dataset.py:399-422``).
    """
    from dgraph_tpu.plan import build_edge_plan

    if not cache_dir:
        if ranks is not None:
            raise ValueError(
                "cached_edge_plan(ranks=...) needs a cache_dir: per-rank "
                "loading is a property of the sharded on-disk artifact"
            )
        # layout sidecar knobs describe the on-disk artifact; without a
        # cache there is none (build_edge_plan would reject the kwarg)
        build_kwargs.pop("write_layout", None)
        return build_edge_plan(
            edge_index, src_partition, dst_partition, **build_kwargs
        )
    os.makedirs(cache_dir, exist_ok=True)
    # The RESOLVED Pallas tile sizes must be part of the key: they're
    # baked into the built plan, and build_edge_plan defaults them from
    # the env-overridable module constants — a warm cache would otherwise
    # silently ignore DGRAPH_TPU_SCATTER_BLOCK_E/N (ADVICE r2 #2).
    # Likewise the RESOLVED overlap intent: overlap=None defaults from the
    # env pin / adopted tuning record (plan.resolve_overlap_intent — the
    # same rule the builder applies), and a warm spec-less artifact must
    # not satisfy a build that now wants the interior/boundary split.
    from dgraph_tpu import plan as _plan
    from dgraph_tpu import plan_shards as ps
    from dgraph_tpu.plan import build_edge_plan_sharded, load_sharded_plan

    # the v8 cache always streams through the numpy per-rank core: the
    # native core fills the whole [W, E_pad] stack at once — the
    # allocation the sharded artifact exists to avoid. The cores produce
    # identical plans, so an explicit use_native only changes the build's
    # time/RSS profile; honor old callers by ignoring it with a warning
    # rather than crashing deep inside build_plan_shards.
    if build_kwargs.pop("use_native", None):
        _logger.warning(
            "plan cache %s: use_native is ignored for sharded (v8) cache "
            "builds — the streaming numpy core bounds peak memory by one "
            "shard", cache_dir,
        )

    overlap_resolved = build_kwargs.get("overlap")
    if overlap_resolved is None:
        overlap_resolved = _plan.resolve_overlap_intent()
    key = _graph_fingerprint(
        edge_index,
        src_partition if dst_partition is None else np.concatenate([src_partition, dst_partition]),
        scatter_block_e=_plan.SCATTER_BLOCK_E,
        scatter_block_n=_plan.SCATTER_BLOCK_N,
        overlap=bool(overlap_resolved),
        **{
            f"x_{k}": v for k, v in sorted((key_extra or {}).items())
            if v is not None and (np.isscalar(v) or isinstance(v, str))
        },
        # write_layout is an artifact-shape knob, not a plan knob: the
        # shards are bit-identical either way, and the loader self-heals
        # a missing sidecar — keying on it would store a duplicate
        # multi-GB artifact per spelling
        **{k: v for k, v in build_kwargs.items()
           if k not in ("overlap", "write_layout")
           and (np.isscalar(v) or isinstance(v, str))},
    )
    plan_dir = os.path.join(cache_dir, f"plan_{key}")

    ll = (
        load_layout if load_layout is not None
        # no sidecar to load for a rank-subset (per-host) load, nor when
        # the caller opted out of writing it in the first place
        else ranks is None and build_kwargs.get("write_layout", True)
    )

    def _build(rebuild_ranks=()):
        return build_edge_plan_sharded(
            edge_index, src_partition, dst_partition,
            out_dir=plan_dir, fingerprint=key, ranks=ranks, load_layout=ll,
            memory_budget_bytes=memory_budget_bytes,
            rebuild_ranks=rebuild_ranks,
            **{**build_kwargs, "overlap": bool(overlap_resolved)},
        )

    try:
        return load_sharded_plan(
            plan_dir, ranks=ranks, load_layout=ll, verify=verify
        )
    except ps.PlanShardError as e:
        # one bad shard is a shard-level repair, never a full rebuild:
        # the builder resumes past every durable, checksum-intact shard
        # and reassembles only what's broken (plus the named shard, for
        # the unlikely checksum-intact-but-unpicklable case)
        _logger.warning(
            "plan cache %s: shard %s unreadable (%s); rebuilding that "
            "shard", plan_dir, e.rank, e.reason,
        )
        return _build(rebuild_ranks=(e.rank,) if e.rank >= 0 else ())
    except ps.PlanManifestError as e:
        if os.path.exists(ps.manifest_path(plan_dir)):
            # incomplete (killed mid-build -> resume) or corrupt (full
            # rebuild; the writer discards unverifiable progress itself)
            _logger.warning(
                "plan cache %s: %s; %s", plan_dir, e.reason,
                "resuming the interrupted build"
                if "incomplete" in e.reason else "rebuilding",
            )
        return _build()
