from dgraph_tpu.train.loop import (
    TrainState,
    make_train_step,
    make_eval_step,
    init_params,
)

__all__ = ["TrainState", "make_train_step", "make_eval_step", "init_params"]
