"""Shrink-to-fit elastic world recovery: re-plan, reshard, adopt.

The recovery half of treating rank loss as a *planned redistribution to a
smaller world* ("Memory-efficient array redistribution through portable
collective communication", PAPERS.md) rather than a fatal crash.
Detection lives in :mod:`dgraph_tpu.comm.membership`; the restart policy
in :func:`dgraph_tpu.train.supervise.supervise_group`; this module owns
the world STATE and its recovery transitions:

- **One run directory, generational artifacts.** ``world.json`` is the
  single adoption pointer: ``{generation, world_size, resume_step, ...}``.
  Every generation ``g`` owns its own plan directory (``plan_g<g>``, a PR 8
  sharded v8 artifact), per-rank checkpoint directories
  (``ckpt_g<g>/rank_<r>``), membership directory (``membership_g<g>`` —
  fresh per generation so stale leases can never pollute the shrunk
  world), and graph snapshot (``graph_g<g>.npz``: renumbered edges,
  partition, counts, and ``orig_ids`` mapping generation-local vertex ids
  back to the original numbering, composed across shrinks).

- **Shrink = fold + rebuild + reshard + atomic adopt.**
  :func:`shrink_world` folds the lost ranks' vertices onto survivors
  (:func:`~dgraph_tpu.partition.fold_partition` — deterministic
  waterfill), renumbers, and rebuilds the plan for the surviving world
  size **in the background** through the streaming
  :func:`~dgraph_tpu.plan.build_plan_shards` (memory-budgeted, durable
  after every shard, RESUMABLE — a recovery killed mid-build picks up
  from its manifest) while the foreground gathers the newest checkpoint
  step durable on EVERY old rank (the last consistent cut — the dead
  rank's state only survives in its checkpoint) and reshards it with
  :func:`~dgraph_tpu.plan.reshard_vertex_data`.  Only after the new plan,
  checkpoints, and graph snapshot are all durable does ``world.json``
  flip — one atomic rename (:func:`~dgraph_tpu.plan_shards.
  atomic_write_json`), so a crash at ANY point leaves either the old
  world or the new world adopted, never a torn mix.

- **Bit-identical degraded resume.** Every step of the transition is a
  pure function of ``(old artifacts, lost_ranks)``: the fold is
  deterministic, the plan build is the same streaming core a fault-free
  W−1 build uses, and the reshard moves rows by vertex identity.  A
  resumed degraded run is therefore bit-identical to a fault-free run at
  the smaller world started from the same resharded checkpoint — the
  contract PR 5 pinned for restart/resume, extended to world shrinks
  (pinned end-to-end by ``tests/test_shrink.py``).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import numpy as np

import dgraph_tpu.obs.spans as spans

_logger = logging.getLogger("dgraph_tpu.shrink")

WORLD_POINTER = "world.json"


class ShrinkError(RuntimeError):
    """A world transition could not complete (no consistent checkpoint
    cut, missing generation artifacts, ...)."""

    def __init__(self, reason: str):
        super().__init__(f"shrink-to-fit recovery failed: {reason}")
        self.reason = reason

    def record(self) -> dict:
        return {"kind": "shrink_error", "reason": self.reason}


# ---------------------------------------------------------------------------
# generational layout helpers (ONE place derives every path)
# ---------------------------------------------------------------------------


def world_path(run_dir: str) -> str:
    return os.path.join(run_dir, WORLD_POINTER)


def plan_dir(run_dir: str, generation: int) -> str:
    return os.path.join(run_dir, f"plan_g{generation}")


def ckpt_dir(run_dir: str, generation: int) -> str:
    return os.path.join(run_dir, f"ckpt_g{generation}")


def rank_ckpt_dir(run_dir: str, generation: int, rank: int) -> str:
    return os.path.join(ckpt_dir(run_dir, generation), f"rank_{rank}")


def membership_dir(run_dir: str, generation: int, attempt: int = 0) -> str:
    """Membership directory for one (generation, supervisor-attempt)
    incarnation.  Fresh per ATTEMPT, not just per generation: a
    same-world collective restart (wedge) would otherwise relaunch into
    the killed attempt's stale leases — rendezvous would count them as
    present and the first poll would age them into a spurious RankLost
    against a peer that is merely slow to re-import."""
    return os.path.join(run_dir, f"membership_g{generation}_a{attempt}")


def graph_path(run_dir: str, generation: int) -> str:
    return os.path.join(run_dir, f"graph_g{generation}.npz")


def read_world(run_dir: str) -> dict:
    """The current adoption pointer; raises :class:`ShrinkError` when the
    run directory holds none (or a torn/invalid one — the atomic write
    makes that a real corruption, not a benign race)."""
    import json

    path = world_path(run_dir)
    try:
        with open(path) as fh:
            rec = json.load(fh)
    except OSError as e:
        raise ShrinkError(f"no world pointer at {path} ({e})")
    except ValueError as e:
        raise ShrinkError(f"world pointer {path} unreadable: {e}")
    if rec.get("kind") != "elastic_world":
        raise ShrinkError(f"{path} is not an elastic_world record")
    return rec


def write_world(run_dir: str, rec: dict) -> None:
    """ATOMIC adoption: the rename is the commit point of a world
    transition."""
    from dgraph_tpu.plan_shards import atomic_write_json

    atomic_write_json(world_path(run_dir), rec)


# ---------------------------------------------------------------------------
# world lifecycle
# ---------------------------------------------------------------------------


def init_world(
    run_dir: str,
    edge_index: np.ndarray,
    num_nodes: int,
    world_size: int,
    *,
    partition_method: str = "block",
    seed: int = 0,
    pad_multiple: int = 8,
    overlap: bool = False,
    lease_s: float = 5.0,
    heartbeat_interval_s: Optional[float] = None,
    memory_budget_bytes: Optional[int] = None,
) -> dict:
    """Create generation 0 of an elastic run: partition + renumber the
    graph, build the sharded plan artifact, snapshot the graph, and adopt
    ``world.json``.  Idempotent on rerun (the plan build resumes; the
    pointer write is last)."""
    from dgraph_tpu.partition import partition_graph
    from dgraph_tpu.plan import build_plan_shards
    from dgraph_tpu.plan_shards import atomic_savez

    os.makedirs(run_dir, exist_ok=True)
    new_edges, ren = partition_graph(
        edge_index, num_nodes, world_size, method=partition_method,
        seed=seed,
    )
    # fsync+rename, never a bare np.savez: a crash mid-write must not
    # leave a torn graph_g0.npz under the name every later generation
    # folds from (host-durable-write)
    atomic_savez(
        graph_path(run_dir, 0),
        edge_index=new_edges,
        partition=ren.partition,
        counts=ren.counts,
        orig_ids=ren.inv,  # generation-0 vertex id -> original id
    )
    build_plan_shards(
        new_edges, ren.partition, out_dir=plan_dir(run_dir, 0),
        world_size=world_size, pad_multiple=pad_multiple,
        overlap=overlap or None,
        write_layout=False, memory_budget_bytes=memory_budget_bytes,
    )
    rec = {
        "kind": "elastic_world",
        "generation": 0,
        "world_size": int(world_size),
        "resume_step": 0,
        "lease_s": float(lease_s),
        "heartbeat_interval_s": heartbeat_interval_s,
        "pad_multiple": int(pad_multiple),
        # plan-build knobs every later generation must REPLAY: a shrink
        # that rebuilt without the interior/boundary split would silently
        # outlaw the overlap/pallas_p2p lowerings in the degraded world
        "plan_overlap": bool(overlap),
        "lost_history": [],
    }
    write_world(run_dir, rec)
    return rec


def build_generation_plan(
    run_dir: str,
    generation: int,
    edges: np.ndarray,
    partition: np.ndarray,
    world: dict,
    world_size: int,
) -> dict:
    """Rebuild the sharded plan artifact for one generation through the
    streaming per-rank builder (durable after every shard, RESUMABLE from
    its own manifest), replaying the world record's plan knobs — a
    transition that rebuilt without the interior/boundary split would
    silently outlaw the overlap/pallas_p2p lowerings in the new world.
    Shared by the shrink AND grow transitions (:mod:`dgraph_tpu.train.
    grow` is lint-enforced jax-free, so the jax-pulling
    :mod:`dgraph_tpu.plan` import stays quarantined here)."""
    from dgraph_tpu.plan import build_plan_shards

    return build_plan_shards(
        edges, partition,
        out_dir=plan_dir(run_dir, generation),
        world_size=world_size,
        pad_multiple=int(world.get("pad_multiple", 8)),
        overlap=world.get("plan_overlap", False) or None,
        write_layout=False,
    )


def _walk_leaves(tree, path=()):
    """(path, leaf) pairs over dict/list/tuple trees — hand-rolled like
    chaos.poison_pytree; checkpointed host state is plain containers."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _walk_leaves(tree[k], path + (k,))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _walk_leaves(v, path + (i,))
    else:
        yield path, tree


def _map_tree(tree, fn, path=()):
    """Rebuild a dict/list/tuple tree with ``fn(path, leaf)`` at every
    leaf.  Functional on purpose: tuples (incl. optimizer-state
    NamedTuples) are immutable, so in-place leaf assignment cannot
    reshard them."""
    if isinstance(tree, dict):
        return {k: _map_tree(v, fn, path + (k,)) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        items = [_map_tree(v, fn, path + (i,)) for i, v in enumerate(tree)]
        if isinstance(tree, tuple):
            # NamedTuples (optax states) take positional fields; plain
            # tuples take an iterable
            return (
                type(tree)(*items) if hasattr(tree, "_fields")
                else tuple(items)
            )
        return items
    return fn(path, tree)


def _get_leaf(tree, path):
    node = tree
    for p in path:
        node = node[p]
    return node


def _reshard_states(
    states: list,
    old_counts: np.ndarray,
    n_pad_old: int,
    new_index: np.ndarray,
    new_counts: np.ndarray,
    n_pad_new: int,
    new_world: int,
) -> list:
    """Per-OLD-rank state trees -> per-NEW-rank state trees.  A leaf whose
    leading dim equals the old per-rank pad is vertex-sharded and moves
    through :func:`~dgraph_tpu.plan.reshard_vertex_data`; anything else is
    replicated (model params, scalars) and rank 0's copy is adopted."""
    from dgraph_tpu.plan import reshard_vertex_data

    resharded = {}
    for path, leaf in _walk_leaves(states[0]):
        arr = np.asarray(leaf)
        if arr.ndim >= 1 and arr.shape[0] == n_pad_old:
            stacked = np.stack([
                np.asarray(_get_leaf(states[r], path))
                for r in range(len(states))
            ])
            resharded[path] = reshard_vertex_data(
                stacked, old_counts, new_index, new_counts, n_pad_new
            )
    return [
        _map_tree(
            states[0],
            lambda path, leaf: (
                resharded[path][r] if path in resharded else leaf
            ),
        )
        for r in range(new_world)
    ]


def shrink_world(run_dir: str, lost_ranks) -> dict:
    """Transition the run to ``W - len(lost_ranks)`` ranks; returns the
    adopted world record (plus ``resume_step``).

    Crash-safe and rerunnable: artifacts are written under the NEW
    generation's names (the old world stays intact and adopted until the
    final pointer flip), the plan build resumes from its own manifest,
    and checkpoint/graph writes are atomic.  The plan rebuild runs in a
    background thread, overlapped with the checkpoint gather/reshard.
    """
    from dgraph_tpu import plan_shards as ps
    from dgraph_tpu.partition import fold_partition, renumber_contiguous
    from dgraph_tpu.train.checkpoint import (
        all_steps,
        restore_checkpoint,
        save_checkpoint,
    )

    world = read_world(run_dir)
    gen, W = int(world["generation"]), int(world["world_size"])
    lost = sorted(set(int(r) for r in lost_ranks))
    new_gen, new_world = gen + 1, W - len(lost)
    if new_world < 1:
        raise ShrinkError(
            f"cannot shrink world {W} by {len(lost)} lost rank(s)"
        )
    with spans.span(
        "shrink.recover", run_dir=run_dir, generation=new_gen,
        old_world=W, new_world=new_world, lost=lost,
    ) as rspan:
        graph = np.load(graph_path(run_dir, gen))
        part_fold, _survivor_map = fold_partition(
            graph["partition"], W, lost
        )
        ren = renumber_contiguous(part_fold, new_world)
        new_edges = ren.perm[np.asarray(graph["edge_index"])]
        orig_ids = np.asarray(graph["orig_ids"])[ren.inv]

        # background: rebuild the plan for the surviving world through the
        # streaming per-rank builder (durable + resumable, plan.* chaos
        # points live) while the foreground reshards the checkpoint
        build_out: dict = {}

        def _build():
            with spans.span("shrink.replan", parent=rspan,
                            world_size=new_world):
                try:
                    build_out["manifest"] = build_generation_plan(
                        run_dir, new_gen, new_edges, ren.partition,
                        world, new_world,
                    )
                except BaseException as e:  # re-raised on join
                    build_out["error"] = e

        builder = threading.Thread(target=_build, name="shrink-replan")
        builder.start()

        # foreground: the newest checkpoint step durable on EVERY old rank
        # — the dead ranks' state only survives in their checkpoints, and
        # a step some rank never finished saving is not a consistent cut
        step_sets = [
            set(all_steps(rank_ckpt_dir(run_dir, gen, r))) for r in range(W)
        ]
        common = set.intersection(*step_sets) if step_sets else set()
        if not common:
            builder.join()
            raise ShrinkError(
                f"no checkpoint step durable on all {W} rank(s) of "
                f"generation {gen} (per-rank steps: "
                f"{[sorted(s) for s in step_sets]})"
            )
        resume_step = max(common)
        with spans.span("shrink.gather", parent=rspan, step=resume_step):
            per_rank = [
                restore_checkpoint(
                    rank_ckpt_dir(run_dir, gen, r), step=resume_step
                )
                for r in range(W)
            ]
        builder.join()
        if "error" in build_out:
            raise build_out["error"]
        manifest = build_out["manifest"]
        statics = manifest["statics"]
        if not statics.get("homogeneous", True):
            raise NotImplementedError(
                "shrink_world currently reshards homogeneous vertex state"
            )
        n_pad_new = int(statics["n_dst_pad"])
        old_statics = ps.read_manifest(plan_dir(run_dir, gen))["statics"]
        n_pad_old = int(old_statics["n_dst_pad"])

        with spans.span("shrink.reshard", parent=rspan, step=resume_step):
            new_states = _reshard_states(
                [p["state"] for p in per_rank],
                np.asarray(graph["counts"]),
                n_pad_old,
                ren.inv,
                ren.counts,
                n_pad_new,
                new_world,
            )
            for r in range(new_world):
                save_checkpoint(
                    rank_ckpt_dir(run_dir, new_gen, r),
                    {"state": new_states[r], "step": resume_step},
                    resume_step,
                )
        # atomic like the checkpoints above it: the graph snapshot is a
        # payload the pointer flip below adopts, and a torn snapshot
        # under a valid name would poison every later fold
        ps.atomic_savez(
            graph_path(run_dir, new_gen),
            edge_index=new_edges,
            partition=ren.partition,
            counts=ren.counts,
            orig_ids=orig_ids,
        )
        rec = {
            **world,
            "generation": new_gen,
            "world_size": new_world,
            "resume_step": int(resume_step),
            "lost_history": list(world.get("lost_history", []))
            + [{"generation": gen, "lost": lost,
                "resume_step": int(resume_step)}],
        }
        # THE adoption: one atomic rename flips every reader (workers
        # derive plan/ckpt/membership paths from the generation) to the
        # degraded world
        write_world(run_dir, rec)
        rspan.annotate(resume_step=int(resume_step))
        _logger.info(
            "shrink-to-fit adopted: generation %d, world %d -> %d, lost "
            "%s, resume step %d", new_gen, W, new_world, lost, resume_step,
        )
    return rec
