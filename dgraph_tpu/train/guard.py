"""Host-side accounting for the non-finite step guard.

The device half lives in ``train.loop.make_train_step(nonfinite_guard=
True)``: the jitted step checks that the global gradient norm is finite and
selects — with ``jnp.where``, inside the one already-compiled program, so a
poisoned step and a clean step replay the same executable — between the
applied update and the carried-forward state.  That makes a transient
non-finite step (a bad batch row, a bfloat16 overflow spike) cost one
skipped update instead of a destroyed run.

This module is the host half: :class:`NonFiniteMonitor` counts skips as
they stream out of the step's metrics and raises :class:`NonFiniteAbort`
(a structured, JSONL-able abort) after N *consecutive* skips — a gradient
stream that never recovers is not transient, and silently skipping forever
would burn the whole step budget training nothing.  ``run_elastic``
catches the abort and rolls back to the last checkpoint.
"""

from __future__ import annotations

from typing import Optional


class NonFiniteAbort(RuntimeError):
    """Raised after ``max_consecutive`` non-finite steps in a row; carries
    the structured record the training driver logs before rolling back."""

    def __init__(self, message: str, *, step: Optional[int] = None,
                 consecutive: int = 0, total_skipped: int = 0):
        super().__init__(message)
        self.step = step
        self.consecutive = consecutive
        self.total_skipped = total_skipped

    def record(self) -> dict:
        """One JSONL-able dict (the serve-errors ``record()`` discipline)."""
        return {
            "kind": "nonfinite_abort",
            "step": self.step,
            "consecutive": self.consecutive,
            "total_skipped": self.total_skipped,
            "detail": str(self),
        }


class NonFiniteMonitor:
    """Consecutive-skip counter over the guard's per-step skip flag.

    Usage (the driver's step closure)::

        monitor = NonFiniteMonitor(max_consecutive=3)
        def train_step(state):
            params, opt_state, m = step(params, opt_state, batch, plan)
            monitor.observe(m["nonfinite_skipped"], step=state.step)
            ...

    ``observe`` coerces the device scalar to a bool on host (one scalar
    transfer per step, only when the guard is enabled), returns it, and
    raises :class:`NonFiniteAbort` once ``max_consecutive`` skips land in
    a row.  A finite step resets the streak; ``total_skipped`` keeps the
    lifetime count for the run's summary record.
    """

    def __init__(self, max_consecutive: int = 3):
        if max_consecutive < 1:
            raise ValueError(
                f"max_consecutive must be >= 1, got {max_consecutive}"
            )
        self.max_consecutive = int(max_consecutive)
        self.consecutive = 0
        self.total_skipped = 0
        self.last_skipped_step: Optional[int] = None

    def observe(self, skipped, *, step: Optional[int] = None) -> bool:
        s = bool(float(skipped))
        if not s:
            self.consecutive = 0
            return False
        self.consecutive += 1
        self.total_skipped += 1
        self.last_skipped_step = step
        if self.consecutive >= self.max_consecutive:
            raise NonFiniteAbort(
                f"{self.consecutive} consecutive non-finite gradient steps "
                f"(last at step {step}); aborting rather than skipping "
                "forever",
                step=step,
                consecutive=self.consecutive,
                total_skipped=self.total_skipped,
            )
        return True

    def summary(self) -> dict:
        """JSONL-able end-of-run summary of what the guard absorbed."""
        return {
            "kind": "nonfinite_guard",
            "total_skipped": self.total_skipped,
            "consecutive": self.consecutive,
            "max_consecutive": self.max_consecutive,
            "last_skipped_step": self.last_skipped_step,
        }
