"""SPMD training loop for full-graph node-level tasks.

The reference keeps its training loops in experiment scripts
(``experiments/OGB/main.py:50-227``) with DDP for gradient sync; here the
loop is a library: one jitted train step that runs the whole
model + loss + backward + gradient psum under ``shard_map`` over the
``('replica','graph')`` mesh, with optax for updates. Loss is normalized by
the *global* target count, matching the reference
(``distributed_layers.py:210-214``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from dgraph_tpu import compat as _compat
from dgraph_tpu.comm.mesh import GRAPH_AXIS, REPLICA_AXIS, plan_in_specs, squeeze_plan
from dgraph_tpu.obs.metrics import StepMetrics
from dgraph_tpu.plan import EdgePlan


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0


def init_params(model, mesh, plan: EdgePlan, batch: dict, seed: int = 0,
                batch_args: Callable = None):
    """Initialize params under shard_map (the model's collectives need the
    mesh axis bound even at trace time). Same key on every shard ->
    deterministic identical params, declared replicated via out_specs P()."""
    from dgraph_tpu.comm.collectives import shard_map_checks

    batch_args = batch_args or _batch_args

    def body(batch_, plan_):
        plan_s = squeeze_plan(plan_)
        b = jax.tree.map(lambda leaf: leaf[0], batch_)
        return model.init(jax.random.key(seed), *batch_args(b, plan_s))

    batch_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), batch)
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(batch_specs, plan_in_specs(plan)),
        out_specs=P(),
        # params ARE replicated (same key, shape-only init) but the 0.4.x
        # rep checker cannot prove it through model.init's per-shard data
        **shard_map_checks(relax="init outputs replicated by construction"),
    )
    with jax.set_mesh(mesh):
        return jax.jit(fn)(batch, plan)


def masked_cross_entropy(logits, labels, mask, axis_name):
    """Sum of per-vertex CE over the mask / global mask count."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ll = jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    local = -(ll * mask).sum()
    count = mask.sum()
    if axis_name is not None:
        count = lax.psum(count, axis_name)
    return local / jnp.maximum(count, 1.0)


def masked_bce_multilabel(logits, labels, mask, axis_name):
    """Mean sigmoid BCE for [n, C] multi-label float targets (ogbn-proteins'
    112-way labels — the case the reference handles with a per-dataset
    num_classes table, ``ogbn_datasets.py:25-37``)."""
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    local = (per.sum(axis=-1) * mask).sum()
    count = mask.sum() * logits.shape[-1]
    if axis_name is not None:
        count = lax.psum(count, axis_name)
    return local / jnp.maximum(count, 1.0)


def _batch_args(b: dict, plan):
    """Default model-arg builder: (x, plan, [edge_weight]) — the GCN-family
    signature. Models with other signatures (e.g. GraphTransformer's
    (x, plan, vmask)) pass a custom ``batch_args`` to the step builders /
    ``fit``."""
    args = [b["x"], plan]
    if "edge_weight" in b:
        args.append(b["edge_weight"])
    return args


def vmask_batch_args(b: dict, plan):
    """(x, plan, vmask) — the GraphTransformer signature (global-attention
    models need the vertex padding mask, not edge weights)."""
    return [b["x"], plan, b["vmask"]]


def model_apply(model, params, b: dict, plan, batch_args: Callable = None):
    """THE per-shard forward call: train, eval, and serve all route the
    model through this one helper (``model.apply(params, *batch_args(b,
    plan))``), so the forward semantics — which batch keys feed which model
    arguments — cannot drift between the three paths. ``b`` and ``plan``
    are per-shard (already squeezed); ``batch_args`` defaults to the
    GCN-family ``(x, plan, [edge_weight])`` builder."""
    batch_args = batch_args or _batch_args
    return model.apply(params, *batch_args(b, plan))


def make_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh,
    plan_template: EdgePlan,
    *,
    loss_fn: Callable = masked_cross_entropy,
    donate: bool = True,
    per_replica_batch: bool = False,
    batch_args: Callable = None,
    step_metrics: bool = False,
    nonfinite_guard: bool = False,
):
    """Build a jitted SPMD train step: (params, opt_state, batch, plan) ->
    (params, opt_state, metrics).

    ``step_metrics=True`` returns a :class:`~dgraph_tpu.obs.metrics.
    StepMetrics` aux-pytree (loss, accuracy, grad_norm, mask_count) instead
    of the bare dict; the flag is a BUILD-time constant, so the default
    step's traced program is byte-identical to the flag not existing —
    zero overhead and zero extra recompiles when disabled (pinned by
    tests/test_obs.py).

    ``nonfinite_guard=True`` adds an all-finite check on the global grad
    norm and selects — via ``jnp.where`` inside the SAME traced program,
    so a poisoned step and a clean step replay one executable with zero
    recompiles (pinned by tests/test_obs.py) — between the applied update
    and the carried-forward ``(params, opt_state)``.  The skip indicator
    comes back in the metrics as ``nonfinite_skipped`` (0.0/1.0); feed it
    to :class:`~dgraph_tpu.train.guard.NonFiniteMonitor` to abort after N
    consecutive skips.  Like ``step_metrics`` this is a build-time
    constant: disabled, the traced program is byte-identical to the flag
    not existing.

    ``batch`` is a dict pytree with leading-[W] leaves (from
    ``DistributedGraph.batch`` + labels); params/opt_state are replicated.

    ``per_replica_batch=True``: batch leaves carry a leading [R, W, ...]
    pair of axes and each replica group trains on its OWN sample (see
    :class:`~dgraph_tpu.train.sampler.ReplicaSampler` — the reference's
    ``CommAwareDistributedSampler`` semantics, ``dist_utils.py:50-113``).
    With False (default), all replicas see the same batch and data
    parallelism degenerates to scaled-loss replication.
    """

    # replica-axis size (data parallelism): grads auto-psum over EVERY axis
    # params are replicated on, so scale the loss by 1/num_replicas to turn
    # the replica-sum into the DDP mean (graph-axis contributions are partial
    # sums of one sample and must stay a sum).
    num_replicas = dict(mesh.shape).get(REPLICA_AXIS, 1)
    batch_args = batch_args or _batch_args
    batch_spec = (
        P(REPLICA_AXIS, GRAPH_AXIS) if per_replica_batch else P(GRAPH_AXIS)
    )

    def _squeeze_batch(batch):
        # drop the size-1 per-shard leading axes shard_map leaves on each
        # leaf: [1, n, ...] (shared batch) or [1, 1, n, ...] (per-replica)
        n_lead = 2 if per_replica_batch else 1
        out = batch
        for _ in range(n_lead):
            out = jax.tree.map(lambda leaf: leaf[0], out)
        return out

    def shard_body(params, batch, plan):
        plan = squeeze_plan(plan)
        b = _squeeze_batch(batch)

        def lf(p):
            logits = model_apply(model, p, b, plan, batch_args)
            loss = loss_fn(logits, b["y"], b["mask"], GRAPH_AXIS)
            if b["y"].ndim == logits.ndim:
                # multi-label float targets: per-label binary accuracy
                hits = ((logits > 0) == (b["y"] > 0.5)).mean(axis=-1)
                correct = (hits * b["mask"]).sum()
            else:
                correct = ((jnp.argmax(logits, -1) == b["y"]) * b["mask"]).sum()
            return loss / num_replicas, (loss, correct)

        (_, (loss, correct)), grads = jax.value_and_grad(lf, has_aux=True)(params)
        # NO explicit grad psum on jax >= 0.6: params enter replicated
        # (in_specs P()), and shard_map's vma tracking makes
        # grad-of-replicated-input insert the cross-shard psum
        # automatically (the transpose of the replicated broadcast) — an
        # extra lax.psum there would double-count by W. On jax 0.4.x no
        # such rewrite exists, so compat inserts the psum explicitly over
        # exactly the axes the batch is sharded on. Pinned either way by
        # tests/test_models.py::test_distributed_gradients_match_single_
        # device.
        # BOTH axes unconditionally: params are replicated over replica
        # too, and with the loss pre-scaled by 1/num_replicas the replica
        # psum is exactly the DDP mean (with per_replica_batch=False the
        # replica grads are identical, so sum/R reproduces them; a
        # graph-only psum would leave grads scaled 1/R when R > 1)
        grads = _compat.sync_inbody_grads(grads, (REPLICA_AXIS, GRAPH_AXIS))
        loss = lax.psum(loss, GRAPH_AXIS)
        mask_count = lax.psum(b["mask"].sum(), GRAPH_AXIS)
        acc = lax.psum(correct, GRAPH_AXIS) / jnp.maximum(mask_count, 1.0)
        if per_replica_batch:
            # distinct samples: report the replica-mean metrics (out_specs
            # P() requires values statically replicated over the replica
            # axis — also when its size is 1)
            loss = lax.pmean(loss, REPLICA_AXIS)
            acc = lax.pmean(acc, REPLICA_AXIS)
            mask_count = lax.pmean(mask_count, REPLICA_AXIS)
        out = {"loss": loss, "accuracy": acc}
        if step_metrics:
            out["mask_count"] = mask_count
        return grads, out

    def step(params, opt_state, batch, plan):
        from dgraph_tpu.comm.collectives import shard_map_checks

        batch_specs = jax.tree.map(lambda _: batch_spec, batch)
        grads, metrics = jax.shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), batch_specs, plan_in_specs(plan)),
            out_specs=(P(), P()),
            # pallas_p2p programs relax the 0.4.x rep checker (pallas_call
            # has no replication rule there); all other lowerings keep it
            **shard_map_checks(plan, GRAPH_AXIS),
        )(params, batch, plan)
        if nonfinite_guard:
            # one scalar decides the whole step: a single non-finite value
            # anywhere in the grads makes the global norm non-finite, and
            # applying such an update would poison params forever. The
            # select is data-dependent inside the one traced program —
            # skipped and applied steps share the executable.
            gnorm = optax.global_norm(grads)
            ok = jnp.isfinite(gnorm)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            params = jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new_params, params
            )
            opt_state = jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new_opt_state, opt_state
            )
            skipped = 1.0 - ok.astype(jnp.float32)
        else:
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        if step_metrics:
            metrics = StepMetrics(
                loss=metrics["loss"],
                accuracy=metrics["accuracy"],
                grad_norm=gnorm if nonfinite_guard else optax.global_norm(grads),
                mask_count=metrics["mask_count"],
                nonfinite_skipped=skipped if nonfinite_guard else None,
            )
        elif nonfinite_guard:
            metrics = dict(metrics, nonfinite_skipped=skipped)
        return params, opt_state, metrics

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def make_eval_step(model, mesh, loss_fn: Callable = masked_cross_entropy,
                   batch_args: Callable = None):
    """Jitted SPMD eval: (params, batch, plan) -> metrics dict."""
    batch_args = batch_args or _batch_args

    def shard_body(params, batch, plan):
        plan = squeeze_plan(plan)
        b = jax.tree.map(lambda leaf: leaf[0], batch)
        logits = model_apply(model, params, b, plan, batch_args)
        loss = loss_fn(logits, b["y"], b["mask"], GRAPH_AXIS)
        if b["y"].ndim == logits.ndim:
            hits = ((logits > 0) == (b["y"] > 0.5)).mean(axis=-1)
            correct = (hits * b["mask"]).sum()
        else:
            correct = ((jnp.argmax(logits, -1) == b["y"]) * b["mask"]).sum()
        acc = lax.psum(correct, GRAPH_AXIS) / jnp.maximum(
            lax.psum(b["mask"].sum(), GRAPH_AXIS), 1.0
        )
        return {"loss": lax.psum(loss, GRAPH_AXIS), "accuracy": acc}

    def step(params, batch, plan):
        from dgraph_tpu.comm.collectives import shard_map_checks

        batch_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), batch)
        return jax.shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(P(), batch_specs, plan_in_specs(plan)),
            out_specs=P(),
            **shard_map_checks(plan, GRAPH_AXIS),
        )(params, batch, plan)

    return jax.jit(step)


def fit(
    model,
    graph,
    mesh,
    *,
    optimizer: Optional[optax.GradientTransformation] = None,
    num_epochs: int = 50,
    seed: int = 0,
    log_every: int = 0,
    loss_fn: Callable = masked_cross_entropy,
    batch_args: Callable = None,
    nonfinite_guard: bool = False,
):
    """Convenience full-graph training driver (the ``_run_experiment`` loop,
    ``experiments/OGB/main.py:50-227``, as a function). Returns
    (params, history).

    This loop owns the per-epoch batch, so it is also the in-repo consumer
    of the ``grads`` chaos point (:mod:`dgraph_tpu.chaos`): a
    ``grads=poison@K`` clause NaN-poisons epoch K's features host-side,
    which makes that step's gradients non-finite — pair it with
    ``nonfinite_guard=True`` to watch the guard absorb it."""
    import numpy as np

    from dgraph_tpu import chaos
    from dgraph_tpu.obs import spans

    optimizer = optimizer or optax.adam(1e-2)
    # vmask rides along for models whose batch_args want it (harmless
    # otherwise — the default builder ignores unknown keys)
    batch_tr = dict(graph.batch("train"), y=graph.labels, vmask=graph.vertex_mask)
    batch_va = dict(graph.batch("val"), y=graph.labels, vmask=graph.vertex_mask)
    batch_tr = jax.tree.map(jnp.asarray, batch_tr)
    batch_va = jax.tree.map(jnp.asarray, batch_va)
    plan = jax.tree.map(jnp.asarray, graph.plan)

    params = init_params(model, mesh, plan, batch_tr, seed, batch_args=batch_args)
    opt_state = optimizer.init(params)
    train_step = make_train_step(
        model, optimizer, mesh, plan, loss_fn=loss_fn, batch_args=batch_args,
        nonfinite_guard=nonfinite_guard,
    )
    eval_step = make_eval_step(model, mesh, loss_fn=loss_fn, batch_args=batch_args)

    history = []
    with jax.set_mesh(mesh):
        for epoch in range(num_epochs):
            bt = batch_tr
            if chaos.fire("grads", index=epoch):
                # host-side poison of this epoch's features only — same
                # shapes, same executable, one step's grads go non-finite
                bt = dict(batch_tr, x=jnp.asarray(chaos.poison_array(batch_tr["x"])))
            # host-boundary span (never inside the jitted step): one attr
            # read when tracing is off
            with spans.span("train.epoch", epoch=epoch):
                params, opt_state, m = train_step(params, opt_state, bt, plan)
            rec = {"epoch": epoch, "loss": float(m["loss"]), "acc": float(m["accuracy"])}
            if log_every and epoch % log_every == 0:
                ev = eval_step(params, batch_va, plan)
                rec["val_loss"] = float(ev["loss"])
                rec["val_acc"] = float(ev["accuracy"])
                print(rec)
            history.append(rec)
    return params, history
