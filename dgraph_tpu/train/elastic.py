"""Preemption-aware training: failure detection + graceful save/resume.

Beyond-reference subsystem (SURVEY.md §5 lists failure detection/elastic as
ABSENT in the reference; checkpoint/resume was its whole recovery story).
TPU pods are preemptible — maintenance events and pool re-leases land as
SIGTERM with a grace window — so the trainer needs three things the
reference never had:

1. **Preemption detection**: a signal handler that flips a flag the train
   loop polls between steps (``PreemptionGuard``). Polling between steps
   (never inside jit) keeps the XLA program free of host callbacks.
2. **Graceful exit**: on the first poll after the signal, save a full
   train-state checkpoint (orbax, ``train/checkpoint.py``) and stop
   cleanly, so the next launch resumes from the exact step.
3. **Step watchdog**: a wedged device (observed: tunnel lease loss hangs
   ANY dispatch indefinitely) never returns control to Python, so
   detection must be preemptive — a monitor thread that hard-exits the
   process with a distinct code if a step exceeds a deadline, letting the
   launcher restart and resume rather than hang forever.

Single-controller AND multi-controller safe: the handler runs per process;
checkpoint writes go through the lead process only (callers pass
``is_lead``), matching the lead-first convention in ``data/ogbn.py``.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
import time
from typing import Callable, Optional

import dgraph_tpu.obs.spans as spans  # stdlib-only module (lint-enforced)

WEDGED_EXIT_CODE = 17  # distinct exit for "device wedged, restart+resume me"


class PreemptionGuard:
    """Flag-based preemption detection for the between-steps poll.

    Usage::

        guard = PreemptionGuard()              # installs SIGTERM/SIGINT
        for step in range(start, num_steps):
            state = train_step(state, batch)
            if guard.should_stop():            # poll AFTER each step
                save_checkpoint(ckpt_dir, state, step)
                break

    ``signals=()`` makes it inert (tests drive :meth:`request_stop`).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._stop = threading.Event()
        self._prev = {}
        for s in signals:
            self._prev[s] = signal.signal(s, self._handler)

    def _handler(self, signum, frame):
        self._stop.set()
        # chain to any previous CUSTOM handler so outer supervisors still
        # see it — but NOT Python's default SIGINT handler, which raises
        # KeyboardInterrupt mid-step and would bypass exactly the graceful
        # poll-and-checkpoint this class exists for
        prev = self._prev.get(signum)
        if (
            callable(prev)
            and prev not in (signal.SIG_IGN, signal.SIG_DFL)
            and prev is not signal.default_int_handler
        ):
            prev(signum, frame)

    def request_stop(self) -> None:
        """Programmatic preemption (tests; cooperative shutdown)."""
        self._stop.set()

    def should_stop(self) -> bool:
        return self._stop.is_set()

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)


class StepWatchdog:
    """Hard deadline per training step for wedge detection.

    A wedged device hangs inside the dispatch, so no in-loop check can
    fire; this monitor thread exits the whole process (``os._exit``) with
    :data:`WEDGED_EXIT_CODE` if :meth:`beat` isn't called within
    ``deadline_s``. The launcher treats that exit as "restart and resume
    from the last checkpoint" — the elastic story for single-controller
    runs. Call :meth:`stop` before teardown.

    ``on_expire`` (tests / custom supervisors) replaces the hard exit.

    The FIRST step includes XLA trace+compile and can legitimately take many
    times the steady-state step time; until the first :meth:`beat`, the
    deadline is ``first_deadline_s`` (default 10x) so a slow compile does
    not trigger a spurious wedged-exit restart loop.
    """

    def __init__(self, deadline_s: float, on_expire: Optional[Callable] = None,
                 first_deadline_s: Optional[float] = None):
        self.deadline_s = deadline_s
        self.first_deadline_s = (
            first_deadline_s if first_deadline_s is not None else 10 * deadline_s
        )
        self._last = time.monotonic()
        self._beaten = False
        self._suspended = False
        self._on_expire = on_expire
        self._done = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def beat(self) -> None:
        """Mark the step boundary (call once per completed step)."""
        # _last FIRST: the monitor must never pair the steady deadline with
        # a first-step-age _last (race window if _beaten flipped first)
        self._last = time.monotonic()
        self._beaten = True

    @contextlib.contextmanager
    def suspended(self):
        """Context manager: pause expiry (e.g. around checkpoint saves —
        a long orbax write is not a wedged device) and restart the clock
        on exit."""
        self._suspended = True
        try:
            yield
        finally:
            self._last = time.monotonic()
            self._suspended = False

    def _run(self) -> None:
        while not self._done.wait(min(self.deadline_s / 4, 5.0)):
            if self._suspended:
                continue
            limit = self.deadline_s if self._beaten else self.first_deadline_s
            if time.monotonic() - self._last > limit:
                if self._on_expire is not None:
                    self._on_expire()
                    self._last = time.monotonic()  # custom handler: keep watching
                    continue
                print(
                    f"[elastic] step exceeded {self.deadline_s}s deadline — "
                    f"device wedged? exiting {WEDGED_EXIT_CODE} for restart+resume",
                    flush=True,
                )
                os._exit(WEDGED_EXIT_CODE)

    def stop(self) -> None:
        self._done.set()
        self._thread.join(timeout=5.0)


def run_elastic(
    train_step: Callable,  # state -> state (one step, device-synced inside)
    state,
    *,
    start_step: int,
    num_steps: int,
    ckpt_dir: Optional[str],
    checkpoint_every: int = 0,  # 0 = only on preemption/finish
    step_deadline_s: float = 0.0,  # 0 = no watchdog
    first_deadline_s: Optional[float] = None,  # None = watchdog default (10x)
    is_lead: bool = True,
    guard: Optional[PreemptionGuard] = None,
    rollback_on_abort: bool = True,
    membership=None,
):
    """Drive ``train_step`` with preemption polling, periodic checkpoints,
    and an optional per-step wedge watchdog. Returns (state, last_step,
    preempted: bool).

    The reference's trainers loop bare (``experiments/OGB/main.py:129-221``);
    this wrapper is what makes long runs restartable on preemptible TPU
    capacity. Resume by restoring the latest checkpoint and passing its
    step as ``start_step`` (see ``train/checkpoint.py::latest_step``) — or
    run the whole thing under ``python -m dgraph_tpu.train.supervise``,
    which restarts on :data:`WEDGED_EXIT_CODE` and crashes for you.

    ``first_deadline_s`` widens the FIRST step's watchdog allowance (trace +
    XLA compile legitimately dwarf the steady-state step time); None keeps
    :class:`StepWatchdog`'s 10x default. Callers whose first step compiles
    a large program should pass their compile budget here rather than
    inflating ``step_deadline_s`` for the whole run.

    Each step consults the ``step`` chaos point (:mod:`dgraph_tpu.chaos`)
    with the global step as the index, so injected wedges/preemptions/
    crashes land deterministically even across restart+resume.

    If ``train_step`` raises :class:`~dgraph_tpu.train.guard.
    NonFiniteAbort` (the non-finite step guard's consecutive-skip abort)
    and ``rollback_on_abort`` holds, the newest readable checkpoint is
    restored and ``(restored_state, its_step, True)`` returned — the
    caller decides whether to re-enter with a lower LR, different data
    order, or give up. With no checkpoint to roll back to the abort
    propagates.

    ``is_lead`` gates saves for SINGLE-controller runs (replicated or
    single-process state). In a multi-controller launch with state sharded
    across processes, pass ``is_lead=True`` on EVERY process: orbax must be
    entered by all hosts to serialize non-fully-addressable arrays (it
    coordinates lead-writes internally); gating to one process would
    deadlock or fail the save.

    ``membership`` (a :class:`~dgraph_tpu.comm.membership.Membership`)
    makes the loop a live member of an elastic world: background
    heartbeats are started (``start_heartbeats``, idempotent — the lease
    tracks the PROCESS, so a slow step or watchdog-suspended checkpoint
    write never reads as silence), loss polls run at step boundaries
    rate-limited to the heartbeat interval, and a detected peer loss
    saves a checkpoint
    (the survivor's contribution to the next consistent cut) and raises
    :class:`~dgraph_tpu.comm.membership.RankLostError` — the caller
    should exit :data:`~dgraph_tpu.comm.membership.RANK_LOST_EXIT_CODE`
    so ``supervise_group`` runs the shrink-to-fit recovery
    (:mod:`dgraph_tpu.train.shrink`).  Keep ``step_deadline_s`` below the
    membership ``lease_s``: a *wedged* rank must exit 17 (collective
    restart, same world) before its peers declare it lost.
    """
    from dgraph_tpu import chaos
    from dgraph_tpu.comm.membership import RankJoinError, RankLostError
    from dgraph_tpu.train.checkpoint import save_checkpoint
    from dgraph_tpu.train.guard import NonFiniteAbort

    if start_step >= num_steps:  # nothing to do (e.g. resuming a finished run)
        return state, start_step, False
    own_guard = guard is None
    guard = guard or PreemptionGuard()
    dog = (
        StepWatchdog(step_deadline_s, first_deadline_s=first_deadline_s)
        if step_deadline_s > 0 else None
    )
    preempted = False
    step = start_step
    last_saved = None
    # membership liveness is PROCESS-scoped, not step-scoped: the
    # background heartbeat thread (idempotent start) keeps the lease
    # alive through long steps and watchdog-suspended checkpoint writes —
    # a slow orbax save must never read as silence to peers. Loss POLLS
    # stay at step boundaries, rate-limited to the heartbeat interval
    # (a lease write + O(W) poll per step would hammer the shared
    # membership dir at short step times, and detection latency is
    # bounded by the lease anyway; 0.0 = check the first boundary).
    if membership is not None:
        membership.start_heartbeats()
    mem_next = 0.0

    def _save(st, n):
        # a long orbax write is not a wedged device — pause the watchdog
        nonlocal last_saved
        with (dog.suspended() if dog is not None else contextlib.nullcontext()):
            with spans.span("train.checkpoint", parent=run_span, step=n):
                save_checkpoint(ckpt_dir, {"state": st, "step": n}, n)
        last_saved = n

    # one span per attempt-run, one per step (both no-ops when tracing is
    # off — a single attribute read each). Under train.supervise the
    # inherited trace env roots these under the supervisor's attempt span,
    # which is what makes restart chains one joinable timeline.
    run_span = spans.span(
        "train.run", start_step=start_step, num_steps=num_steps,
        attempt=os.environ.get("DGRAPH_CHAOS_ATTEMPT"),
    )
    try:
        for step in range(start_step, num_steps):
            # fault injection lands HERE, at the host step boundary: a
            # 'wedge' holds the loop exactly like a hung dispatch (only the
            # watchdog can catch it), 'sigterm' exercises the preemption
            # poll below, 'raise' the supervisor's crash-restart path
            step_span = spans.span("train.step", parent=run_span, step=step)
            try:
                chaos.fire("step", index=step)
                state = train_step(state)
                step_span.end()
            except NonFiniteAbort as e:
                step_span.end(error="nonfinite_abort")
                restored = (
                    _rollback(ckpt_dir, state, dog)
                    if rollback_on_abort and ckpt_dir else None
                )
                if restored is None:
                    raise
                import json as _json

                print(
                    _json.dumps(
                        {**e.record(), "rolled_back_to": restored[1]}
                    ),
                    flush=True,
                )
                return restored[0], restored[1], True
            except BaseException as e:
                # a crashing step must still land its span record — this
                # is exactly the step the flight recorder needs to show
                # (the supervisor only sees "attempt crashed")
                step_span.end(error=f"{type(e).__name__}: {e}")
                raise
            if dog is not None:
                dog.beat()
            if membership is not None and time.monotonic() >= mem_next:
                mem_next = (
                    time.monotonic() + membership.heartbeat_interval_s
                )
                evs = membership.poll()
                lost_events = [e for e in evs if e.kind == "rank_lost"]
                join_events = [e for e in evs if e.kind == "join_request"]
                if lost_events:
                    # a survivor's job: land a durable checkpoint (its
                    # block of the next consistent cut) and exit for the
                    # group supervisor's shrink path
                    if ckpt_dir and is_lead:
                        _save(state, step + 1)
                    err = RankLostError(
                        tuple(e.rank for e in lost_events),
                        tuple(lost_events),
                    )
                    run_span.annotate(rank_lost=[e.rank for e in lost_events])
                    raise err
                if join_events:
                    # the arrival mirror: land a durable checkpoint (this
                    # rank's block of the cut the grow transition will
                    # reshard from) and exit for the group supervisor's
                    # grow path. Loss wins when both land in one poll —
                    # the world must shrink to a consistent cut before it
                    # can entertain newcomers.
                    if ckpt_dir and is_lead:
                        _save(state, step + 1)
                    err = RankJoinError(
                        tuple(e.token for e in join_events),
                        tuple(join_events),
                    )
                    run_span.annotate(
                        rank_join=[e.token for e in join_events]
                    )
                    raise err
            done_now = guard.should_stop()
            periodic = (
                checkpoint_every > 0 and (step + 1) % checkpoint_every == 0
            )
            if ckpt_dir and is_lead and (done_now or periodic):
                _save(state, step + 1)
            if done_now:
                preempted = True
                break
        else:
            if ckpt_dir and is_lead and last_saved != num_steps:
                _save(state, num_steps)
    finally:
        run_span.end(last_step=step, preempted=preempted)
        if dog is not None:
            dog.stop()
        if own_guard:
            guard.uninstall()
    return state, step + 1, preempted


def _rollback(ckpt_dir: str, state, dog: Optional[StepWatchdog]):
    """Restore the newest readable checkpoint for the non-finite abort
    path; None when the directory holds none. ``state`` is only the
    restore TEMPLATE (structure/shapes — its buffers may already be
    donated), never a value source."""
    from dgraph_tpu.train.checkpoint import latest_step, restore_checkpoint

    if latest_step(ckpt_dir) is None:
        return None
    with (dog.suspended() if dog is not None else contextlib.nullcontext()):
        got = restore_checkpoint(ckpt_dir, {"state": state, "step": 0})
    return got["state"], int(got["step"])
