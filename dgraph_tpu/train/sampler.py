"""Replica-axis data sampling — the reference's
``CommAwareDistributedSampler`` (``experiments/GraphCast/dist_utils.py:50-113``)
re-designed for a 2-D device mesh.

The reference assigns every rank in a partition group the SAME sample and
different groups DIFFERENT samples by integer rank arithmetic
(``sample_idx = indices[batch * num_groups + partition_id]``). On TPU the
grouping is the mesh itself: the ``graph`` axis holds one sample's vertex
shards, the ``replica`` axis holds independent samples. This sampler
produces, for global step ``t``, the R sample indices for the replica axis
and stacks their sharded batches into leading-[R, W, ...] arrays to be fed
with ``in_specs P(REPLICA_AXIS, GRAPH_AXIS)``.
"""

from __future__ import annotations

import numpy as np


class ReplicaSampler:
    """Deterministic epoch-shuffled sampler over ``num_samples`` items for
    ``num_replicas`` replica groups.

    Matches the reference semantics: an epoch is a seeded permutation of
    the dataset; step ``t`` within an epoch hands replica ``r`` the item
    ``perm[t * R + r]``; a short final step wraps (drop_last=False
    behavior via modulo)."""

    def __init__(self, num_samples: int, num_replicas: int, seed: int = 0):
        if num_samples < 1:
            raise ValueError("num_samples must be >= 1")
        self.num_samples = num_samples
        self.num_replicas = num_replicas
        self.seed = seed

    @property
    def steps_per_epoch(self) -> int:
        return max(1, -(-self.num_samples // self.num_replicas))

    def indices(self, global_step: int) -> list[int]:
        """Sample index for each replica at this global step."""
        epoch, t = divmod(int(global_step), self.steps_per_epoch)
        rng = np.random.default_rng((self.seed, epoch))
        perm = rng.permutation(self.num_samples)
        base = t * self.num_replicas
        return [int(perm[(base + r) % self.num_samples]) for r in range(self.num_replicas)]

    def stacked(self, global_step: int, get_sharded):
        """Fetch + stack: ``get_sharded(i) -> pytree of [W, ...] leaves``
        becomes a pytree of [R, W, ...] leaves (one sample per replica)."""
        import jax

        parts = [get_sharded(i) for i in self.indices(global_step)]
        return jax.tree.map(lambda *leaves: np.stack(leaves, axis=0), *parts)
