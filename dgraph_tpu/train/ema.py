"""Exponential moving average of parameters (Polyak averaging).

The GraphCast training recipe evaluates with EMA weights; the reference
repo omits this (its GraphCast trainer keeps only raw params). One pytree
map per step, jit-safe, device-resident.

Usage::

    ema = ema_init(params)
    for ...:
        params, ... = train_step(...)
        ema = ema_update(ema, params, decay=0.999)
    eval_logits = model.apply(ema, ...)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ema_init(params):
    """EMA state = a copy of the initial parameters.

    A REAL copy: aliasing the live buffers (``lambda p: p``) breaks under
    buffer donation — make_train_step's default ``donate=True`` deletes
    the originals on the first step and the first ema_update would read
    dead arrays (ADVICE r2 #1)."""
    return jax.tree.map(jnp.copy, params)


def ema_update(ema, params, decay: float = 0.999):
    """ema <- decay * ema + (1 - decay) * params (elementwise, any pytree)."""
    return jax.tree.map(lambda e, p: decay * e + (1.0 - decay) * p, ema, params)
